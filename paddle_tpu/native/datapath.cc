// Native host data-path: batch-packing scanners.
//
// TPU-native counterpart of the reference's C++ field scanners
// (/root/reference/paddle/gserver/dataproviders/PyDataProvider2.cpp:611-865
// DenseScanner/IndexScanner/SparseNonValueScanner/SparseValueScanner/
// SequenceScanner): user sample generators stay in Python, but the
// per-sample packing into padded device-feed buffers runs here, GIL-free,
// so the prefetch thread overlaps real work with the training step.
//
// Called through ctypes (C ABI only). All buffers are caller-allocated
// numpy arrays; offsets/lengths describe ragged sample layouts flattened
// by the Python side.

#include <atomic>
#include <cstdint>
#include <cstring>

extern "C" {

// Pack ragged index sequences into a zero-padded [B, T] int32 batch.
// flat: concatenation of all sequences; lengths[b] gives each length.
void pt_pack_index_seq(const int32_t* flat, const int32_t* lengths, int64_t B,
                       int64_t T, int32_t* out) {
  std::memset(out, 0, sizeof(int32_t) * B * T);
  const int32_t* src = flat;
  for (int64_t b = 0; b < B; ++b) {
    const int64_t n = lengths[b];
    std::memcpy(out + b * T, src, sizeof(int32_t) * n);
    src += n;
  }
}

// Pack ragged nested index sequences into [B, S, T].
// sub_lengths is row-major [B, S] (0 beyond each sample's subsequence
// count); flat concatenates every subsequence in order.
void pt_pack_index_subseq(const int32_t* flat, const int32_t* sub_lengths,
                          int64_t B, int64_t S, int64_t T, int32_t* out) {
  std::memset(out, 0, sizeof(int32_t) * B * S * T);
  const int32_t* src = flat;
  for (int64_t b = 0; b < B; ++b) {
    for (int64_t s = 0; s < S; ++s) {
      const int64_t n = sub_lengths[b * S + s];
      std::memcpy(out + (b * S + s) * T, src, sizeof(int32_t) * n);
      src += n;
    }
  }
}

// Scatter sparse rows into a zeroed dense [B, D] float batch.
// indices: concatenated per-row active column ids; counts[b] = #ids in row
// b; values: per-id values or nullptr (binary rows get 1.0).
void pt_pack_sparse_rows(const int64_t* indices, const float* values,
                         const int32_t* counts, int64_t B, int64_t D,
                         float* out) {
  std::memset(out, 0, sizeof(float) * B * D);
  const int64_t* idx = indices;
  const float* val = values;
  for (int64_t b = 0; b < B; ++b) {
    float* row = out + b * D;
    const int64_t n = counts[b];
    if (values) {
      for (int64_t i = 0; i < n; ++i) row[idx[i]] = val[i];
      val += n;
    } else {
      for (int64_t i = 0; i < n; ++i) row[idx[i]] = 1.0f;
    }
    idx += n;
  }
}

// Pack ragged dense-vector sequences into zero-padded [B, T, D].
// flat: concatenation of all [len_b, D] sample blocks.
void pt_pack_dense_seq(const float* flat, const int32_t* lengths, int64_t B,
                       int64_t T, int64_t D, float* out) {
  std::memset(out, 0, sizeof(float) * B * T * D);
  const float* src = flat;
  for (int64_t b = 0; b < B; ++b) {
    const int64_t n = lengths[b];
    std::memcpy(out + b * T * D, src, sizeof(float) * n * D);
    src += n * D;
  }
}

// Scatter sparse *sequence* rows into zeroed [B, T, D]: step_counts gives
// the number of active ids per (b, t) flattened in sequence order
// (total_steps entries, grouped by lengths[b] steps per sample).
void pt_pack_sparse_seq(const int64_t* indices, const float* values,
                        const int32_t* step_counts, const int32_t* lengths,
                        int64_t B, int64_t T, int64_t D, float* out) {
  std::memset(out, 0, sizeof(float) * B * T * D);
  const int64_t* idx = indices;
  const float* val = values;
  const int32_t* sc = step_counts;
  for (int64_t b = 0; b < B; ++b) {
    const int64_t steps = lengths[b];
    for (int64_t t = 0; t < steps; ++t) {
      float* row = out + (b * T + t) * D;
      const int64_t n = *sc++;
      if (values) {
        for (int64_t i = 0; i < n; ++i) row[idx[i]] = val[i];
        val += n;
      } else {
        for (int64_t i = 0; i < n; ++i) row[idx[i]] = 1.0f;
      }
      idx += n;
    }
  }
}

// ABI version tag so a stale cached .so is rebuilt on upgrade.
int32_t pt_datapath_abi_version() { return 1; }

}  // extern "C"
