"""Native (C++) host runtime — build-on-first-import, ctypes-loaded.

The TPU compute path is XLA; this package holds the host-side native code
the reference keeps in C++ — currently the data-path scanners
(datapath.cc). The library is compiled once per source hash into
``~/.cache/paddle_tpu`` (or $PADDLE_TPU_CACHE) and loaded via ctypes; any
failure (no g++, sandboxed tmp, exotic platform) degrades to the pure
NumPy fallbacks in the callers, so the framework never hard-depends on a
toolchain at run time. Set PADDLE_TPU_NO_NATIVE=1 to force the fallback.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import sys
import threading
from paddle_tpu.utils import concurrency as cc
from typing import Optional

_SRC = os.path.join(os.path.dirname(os.path.abspath(__file__)), "datapath.cc")
_ABI_VERSION = 1

_lock = cc.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _cache_dir() -> str:
    d = os.environ.get("PADDLE_TPU_CACHE")
    if not d:
        d = os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu")
    os.makedirs(d, exist_ok=True)
    return d


def build_command(src: str, out: str) -> list:
    """The one datapath compile line — shared with setup.py's wheel
    prebuild so a bundled library can never be compiled with different
    flags than a first-import cache build."""
    return [
        os.environ.get("CXX", "g++"),
        "-O3",
        "-shared",
        "-fPIC",
        "-std=c++17",
        "-o",
        out,
        src,
    ]


def _build(src: str, out: str) -> None:
    subprocess.run(build_command(src, out), check=True, capture_output=True,
                   timeout=120)


def _declare(lib: ctypes.CDLL) -> ctypes.CDLL:
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    f32p = ctypes.POINTER(ctypes.c_float)
    i64 = ctypes.c_int64

    lib.pt_pack_index_seq.argtypes = [i32p, i32p, i64, i64, i32p]
    lib.pt_pack_index_subseq.argtypes = [i32p, i32p, i64, i64, i64, i32p]
    lib.pt_pack_sparse_rows.argtypes = [i64p, f32p, i32p, i64, i64, f32p]
    lib.pt_pack_dense_seq.argtypes = [f32p, i32p, i64, i64, i64, f32p]
    lib.pt_pack_sparse_seq.argtypes = [i64p, f32p, i32p, i32p, i64, i64, i64, f32p]
    lib.pt_datapath_abi_version.restype = ctypes.c_int32
    for fn in (
        lib.pt_pack_index_seq,
        lib.pt_pack_index_subseq,
        lib.pt_pack_sparse_rows,
        lib.pt_pack_dense_seq,
        lib.pt_pack_sparse_seq,
    ):
        fn.restype = None
    return lib


def get_lib() -> Optional[ctypes.CDLL]:
    """The datapath library, building it if needed; None if unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("PADDLE_TPU_NO_NATIVE"):
            return None
        # a wheel-bundled prebuild (setup.py BuildPyWithDatapath) skips
        # the toolchain requirement entirely — accepted only when BOTH
        # the ABI version and the build-time source-hash stamp match the
        # present datapath.cc, so a stale-but-ABI-compatible binary can
        # never silently shadow an edited source (the same guarantee the
        # hash-keyed cache path gives)
        bundled = os.path.join(os.path.dirname(_SRC), "_datapath.so")
        if os.path.exists(bundled):
            try:
                with open(_SRC, "rb") as f:
                    src_digest = hashlib.sha256(f.read()).hexdigest()
                with open(bundled.replace(".so", ".hash")) as f:
                    stamp = f.read().strip()
                if stamp == src_digest:
                    lib = ctypes.CDLL(bundled)
                    if lib.pt_datapath_abi_version() == _ABI_VERSION:
                        _lib = _declare(lib)
                        return _lib
            except Exception:  # noqa: BLE001 — stale/foreign-arch bundle
                pass
        try:
            with open(_SRC, "rb") as f:
                src_bytes = f.read()
            tag = hashlib.sha256(src_bytes).hexdigest()[:16]
            so = os.path.join(_cache_dir(), f"datapath_{tag}.so")
            if not os.path.exists(so):
                tmp = f"{so}.tmp.{os.getpid()}"
                _build(_SRC, tmp)
                os.replace(tmp, so)  # atomic vs concurrent builders
            lib = _declare(ctypes.CDLL(so))
            if lib.pt_datapath_abi_version() != _ABI_VERSION:
                return None
            _lib = lib
        except Exception as e:  # noqa: BLE001 — any failure means fallback
            sys.stderr.write(
                f"paddle_tpu: native datapath unavailable ({e!r}); "
                "using NumPy fallback\n"
            )
            _lib = None
        return _lib


def ptr(arr, ctype):
    """ctypes pointer into a numpy array (must be C-contiguous)."""
    return arr.ctypes.data_as(ctypes.POINTER(ctype))
