"""Data source declaration (reference: trainer_config_helpers/
data_sources.py define_py_data_sources2): binds train/test file lists to a
python @provider module.
"""

from __future__ import annotations

import pickle
from typing import Any, Dict, Optional

from paddle_tpu.config.builder import current_context
from paddle_tpu.proto import DataConfig

__all__ = ["define_py_data_sources2"]


def _encode_args(args: Any) -> str:
    if args is None:
        return ""
    import json

    return json.dumps(args)


def define_py_data_sources2(
    train_list: Optional[str],
    test_list: Optional[str],
    module,
    obj,
    args: Optional[Dict] = None,
) -> None:
    ctx = current_context()
    train_module = module[0] if isinstance(module, (list, tuple)) else module
    test_module = module[1] if isinstance(module, (list, tuple)) else module
    train_obj = obj[0] if isinstance(obj, (list, tuple)) else obj
    test_obj = obj[1] if isinstance(obj, (list, tuple)) else obj
    if train_list is not None:
        ctx.trainer_config.data_config = DataConfig(
            type="py2",
            files=train_list,
            load_data_module=train_module,
            load_data_object=train_obj,
            load_data_args=_encode_args(args),
        )
    if test_list is not None:
        ctx.trainer_config.test_data_config = DataConfig(
            type="py2",
            files=test_list,
            load_data_module=test_module,
            load_data_object=test_obj,
            load_data_args=_encode_args(args),
            for_test=True,
        )
