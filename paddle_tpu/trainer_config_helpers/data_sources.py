"""Data source declaration (reference: trainer_config_helpers/
data_sources.py define_py_data_sources2): binds train/test file lists to a
python @provider module.
"""

from __future__ import annotations

import pickle
from typing import Any, Dict, Optional

from paddle_tpu.config.builder import current_context
from paddle_tpu.proto import DataConfig

__all__ = ["define_py_data_sources2", "define_bin_data_sources", "define_multi_py_data_sources2"]


def _encode_args(args: Any) -> str:
    if args is None:
        return ""
    import json

    return json.dumps(args)


def define_py_data_sources2(
    train_list: Optional[str],
    test_list: Optional[str],
    module,
    obj,
    args: Optional[Dict] = None,
) -> None:
    ctx = current_context()
    train_module = module[0] if isinstance(module, (list, tuple)) else module
    test_module = module[1] if isinstance(module, (list, tuple)) else module
    train_obj = obj[0] if isinstance(obj, (list, tuple)) else obj
    test_obj = obj[1] if isinstance(obj, (list, tuple)) else obj
    if train_list is not None:
        ctx.trainer_config.data_config = DataConfig(
            type="py2",
            files=train_list,
            load_data_module=train_module,
            load_data_object=train_obj,
            load_data_args=_encode_args(args),
        )
    if test_list is not None:
        ctx.trainer_config.test_data_config = DataConfig(
            type="py2",
            files=test_list,
            load_data_module=test_module,
            load_data_object=test_obj,
            load_data_args=_encode_args(args),
            for_test=True,
        )


def define_bin_data_sources(train_list, test_list=None):
    """Binary-shard data sources (the ProtoData role,
    paddle_tpu.data.binary): file lists name .npz shards written by
    write_shard; slot types come from the shard metadata."""
    ctx = current_context()
    if train_list is not None:
        ctx.trainer_config.data_config = DataConfig(type="bin", files=train_list)
    if test_list is not None:
        ctx.trainer_config.test_data_config = DataConfig(type="bin", files=test_list)


def define_multi_py_data_sources2(
    train_lists, module, obj, args_list=None, ratios=None, test_list=None,
    test_module=None, test_obj=None,
):
    """Ratio-mixed multi-provider training data (the MultiDataProvider
    role): each entry of ``train_lists`` gets its own @provider
    (module/obj may be a single name shared by all, or parallel lists) and
    contributes data_ratio samples per mixing round."""
    n = len(train_lists)
    modules = module if isinstance(module, (list, tuple)) else [module] * n
    objs = obj if isinstance(obj, (list, tuple)) else [obj] * n
    if args_list is None or isinstance(args_list, dict):
        args_list = [args_list] * n
    ratios = [1] * n if ratios is None else list(ratios)
    for nm, val in (("module", modules), ("obj", objs),
                    ("args_list", args_list), ("ratios", ratios)):
        assert len(val) == n, (
            f"define_multi_py_data_sources2: {nm} has {len(val)} entries "
            f"for {n} train_lists"
        )
    subs = []
    for files, m, o, a, r in zip(train_lists, modules, objs, args_list, ratios):
        subs.append(DataConfig(
            type="py2", files=files, load_data_module=m, load_data_object=o,
            load_data_args=_encode_args(a), data_ratio=int(r),
        ))
    ctx = current_context()
    ctx.trainer_config.data_config = DataConfig(type="multi", sub_data_configs=subs)
    if test_list is not None:
        ctx.trainer_config.test_data_config = DataConfig(
            type="py2", files=test_list,
            load_data_module=test_module or modules[0],
            load_data_object=test_obj or objs[0],
        )
