"""Pooling type objects (API of the reference's poolings.py)."""

__all__ = ["BasePoolingType", "MaxPooling", "AvgPooling", "SumPooling", "SquareRootNPooling"]


class BasePoolingType:
    name = ""


class MaxPooling(BasePoolingType):
    name = "max"


class AvgPooling(BasePoolingType):
    name = "average"


class SumPooling(BasePoolingType):
    name = "sum"


class SquareRootNPooling(BasePoolingType):
    name = "squarerootn"
