"""Activation objects for the DSL.

API-compatible with /root/reference/python/paddle/trainer_config_helpers/
activations.py — each maps to a registered activation name in
paddle_tpu.ops.activations.
"""

__all__ = [
    "BaseActivation",
    "TanhActivation",
    "SigmoidActivation",
    "SoftmaxActivation",
    "SequenceSoftmaxActivation",
    "IdentityActivation",
    "LinearActivation",
    "ReluActivation",
    "BReluActivation",
    "SoftReluActivation",
    "STanhActivation",
    "AbsActivation",
    "SquareActivation",
    "ExpActivation",
]


class BaseActivation:
    name = ""

    def __init__(self):
        pass

    def __repr__(self):
        return f"{type(self).__name__}()"


def _make(cls_name: str, act_name: str):
    cls = type(cls_name, (BaseActivation,), {"name": act_name})
    return cls


TanhActivation = _make("TanhActivation", "tanh")
SigmoidActivation = _make("SigmoidActivation", "sigmoid")
SoftmaxActivation = _make("SoftmaxActivation", "softmax")
SequenceSoftmaxActivation = _make("SequenceSoftmaxActivation", "sequence_softmax")
IdentityActivation = _make("IdentityActivation", "")
LinearActivation = IdentityActivation
ReluActivation = _make("ReluActivation", "relu")
BReluActivation = _make("BReluActivation", "brelu")
SoftReluActivation = _make("SoftReluActivation", "softrelu")
STanhActivation = _make("STanhActivation", "stanh")
AbsActivation = _make("AbsActivation", "abs")
SquareActivation = _make("SquareActivation", "square")
ExpActivation = _make("ExpActivation", "exponential")
