"""User-facing config DSL — `from paddle_tpu.trainer_config_helpers import *`.

API-compatible with the reference package
(/root/reference/python/paddle/trainer_config_helpers/__init__.py).
"""

from paddle_tpu.trainer_config_helpers.activations import *  # noqa: F401,F403
from paddle_tpu.trainer_config_helpers.attrs import *  # noqa: F401,F403
from paddle_tpu.trainer_config_helpers.poolings import *  # noqa: F401,F403
from paddle_tpu.trainer_config_helpers.layers import *  # noqa: F401,F403
from paddle_tpu.trainer_config_helpers.networks import *  # noqa: F401,F403
from paddle_tpu.trainer_config_helpers.optimizers import *  # noqa: F401,F403
from paddle_tpu.trainer_config_helpers.evaluators import *  # noqa: F401,F403
from paddle_tpu.trainer_config_helpers.data_sources import *  # noqa: F401,F403
from paddle_tpu.config.config_parser import get_config_arg  # noqa: F401
from os.path import join as join_path  # noqa: F401  (reference utils.py export)
