"""Composed networks (reference: trainer_config_helpers/networks.py).

simple_lstm:436, lstmemory_unit:505, lstmemory_group:606, gru_unit:689,
gru_group:741, simple_gru:806, bidirectional_lstm:872, simple_attention:943,
sequence_conv_pool:41, img_conv_group:279, small_vgg:359,
vgg_16_network:384, outputs:1055 — same math, rebuilt on the paddle_tpu DSL.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

from paddle_tpu.config.builder import current_context
from paddle_tpu.trainer_config_helpers.activations import (
    BaseActivation,
    IdentityActivation,
    LinearActivation,
    ReluActivation,
    SequenceSoftmaxActivation,
    SigmoidActivation,
    TanhActivation,
)
from paddle_tpu.trainer_config_helpers.attrs import ExtraLayerAttribute, ParameterAttribute
from paddle_tpu.trainer_config_helpers.layers import (
    LayerOutput,
    batch_norm_layer,
    concat_layer,
    context_projection,
    dropout_layer,
    embedding_layer,
    expand_layer,
    fc_layer,
    full_matrix_projection,
    get_output_layer,
    grumemory,
    gru_step_layer,
    identity_projection,
    img_conv_layer,
    img_pool_layer,
    last_seq,
    lstm_step_layer,
    lstmemory,
    memory,
    mixed_layer,
    pooling_layer,
    recurrent_group,
    scaling_layer,
)
from paddle_tpu.trainer_config_helpers.poolings import MaxPooling, SumPooling

__all__ = [
    "sequence_conv_pool",
    "simple_lstm",
    "lstmemory_unit",
    "lstmemory_group",
    "gru_unit",
    "gru_group",
    "simple_gru",
    "bidirectional_lstm",
    "simple_attention",
    "simple_img_conv_pool",
    "img_conv_bn_pool",
    "img_conv_group",
    "small_vgg",
    "vgg_16_network",
    "outputs",
]


def sequence_conv_pool(
    input: LayerOutput,
    context_len: int,
    hidden_size: int,
    name: Optional[str] = None,
    context_start: Optional[int] = None,
    pool_type=None,
    context_proj_layer_name: Optional[str] = None,
    context_proj_param_attr=False,
    fc_layer_name: Optional[str] = None,
    fc_param_attr=None,
    fc_bias_attr=None,
    fc_act=None,
    pool_bias_attr=False,
    fc_attr=None,
    context_attr=None,
    pool_attr=None,
) -> LayerOutput:
    """Text CNN: context projection (n-gram window) → fc → seq pooling."""
    name = name or current_context().unique_name("sequence_conv_pool")
    context_proj_layer_name = context_proj_layer_name or f"{name}_conv_proj"
    m = mixed_layer(
        name=context_proj_layer_name,
        size=input.size * context_len,
        input=[
            context_projection(
                input,
                context_len=context_len,
                context_start=context_start,
                padding_attr=context_proj_param_attr,
            )
        ],
        act=LinearActivation(),
        layer_attr=context_attr,
    )
    fc_layer_name = fc_layer_name or f"{name}_fc"
    fc = fc_layer(
        name=fc_layer_name,
        input=m,
        size=hidden_size,
        act=fc_act or TanhActivation(),
        param_attr=fc_param_attr,
        bias_attr=fc_bias_attr if fc_bias_attr is not None else True,
        layer_attr=fc_attr,
    )
    return pooling_layer(
        name=f"{name}_pool",
        input=fc,
        pooling_type=pool_type or MaxPooling(),
        bias_attr=pool_bias_attr,
        layer_attr=pool_attr,
    )


def simple_lstm(
    input: LayerOutput,
    size: int,
    name: Optional[str] = None,
    reverse: bool = False,
    mat_param_attr=None,
    bias_param_attr=None,
    inner_param_attr=None,
    act=None,
    gate_act=None,
    state_act=None,
    mixed_layer_attr=None,
    lstm_cell_attr=None,
) -> LayerOutput:
    """x → [W x] (mixed) → lstmemory (ref: networks.py:436)."""
    name = name or current_context().unique_name("lstm")
    m = mixed_layer(
        name=f"lstm_transform_{name}",
        size=size * 4,
        input=[full_matrix_projection(input, param_attr=mat_param_attr)],
        act=IdentityActivation(),
        bias_attr=False,
        layer_attr=mixed_layer_attr,
    )
    return lstmemory(
        name=name,
        input=m,
        reverse=reverse,
        bias_attr=bias_param_attr if bias_param_attr is not None else True,
        param_attr=inner_param_attr,
        act=act,
        gate_act=gate_act,
        state_act=state_act,
        layer_attr=lstm_cell_attr,
    )


def lstmemory_unit(
    input: LayerOutput,
    name: Optional[str] = None,
    size: Optional[int] = None,
    param_attr=None,
    act=None,
    gate_act=None,
    state_act=None,
    mixed_bias_attr=None,
    lstm_bias_attr=None,
    mixed_layer_attr=None,
    lstm_layer_attr=None,
    get_output_layer_attr=None,
) -> LayerOutput:
    """One LSTM step for use inside recurrent_group (ref: networks.py:505):
    out/state memories + [identity(x) + W_h h_prev] mixed + lstm_step."""
    name = name or current_context().unique_name("lstm_unit")
    if size is None:
        assert input.size % 4 == 0
        size = input.size // 4
    out_mem = memory(name=name, size=size)
    state_mem = memory(name=f"{name}_state", size=size)
    m = mixed_layer(
        name=f"{name}_input_recurrent",
        size=size * 4,
        input=[
            identity_projection(input),
            full_matrix_projection(out_mem, param_attr=param_attr),
        ],
        bias_attr=mixed_bias_attr if mixed_bias_attr is not None else False,
        act=IdentityActivation(),
        layer_attr=mixed_layer_attr,
    )
    lstm_out = lstm_step_layer(
        name=name,
        input=m,
        state=state_mem,
        size=size,
        bias_attr=lstm_bias_attr if lstm_bias_attr is not None else True,
        act=act,
        gate_act=gate_act,
        state_act=state_act,
        layer_attr=lstm_layer_attr,
    )
    get_output_layer(
        name=f"{name}_state", input=lstm_out, arg_name="state", layer_attr=get_output_layer_attr
    )
    return lstm_out


def lstmemory_group(
    input: LayerOutput,
    size: Optional[int] = None,
    name: Optional[str] = None,
    reverse: bool = False,
    param_attr=None,
    act=None,
    gate_act=None,
    state_act=None,
    mixed_bias_attr=None,
    lstm_bias_attr=None,
    mixed_layer_attr=None,
    lstm_layer_attr=None,
    get_output_layer_attr=None,
) -> LayerOutput:
    name = name or current_context().unique_name("lstm_group")

    def _step(ipt):
        return lstmemory_unit(
            input=ipt,
            name=name,
            size=size,
            param_attr=param_attr,
            act=act,
            gate_act=gate_act,
            state_act=state_act,
            mixed_bias_attr=mixed_bias_attr,
            lstm_bias_attr=lstm_bias_attr,
            mixed_layer_attr=mixed_layer_attr,
            lstm_layer_attr=lstm_layer_attr,
            get_output_layer_attr=get_output_layer_attr,
        )

    return recurrent_group(
        name=f"{name}_recurrent_group", step=_step, reverse=reverse, input=input
    )


def gru_unit(
    input: LayerOutput,
    size: Optional[int] = None,
    name: Optional[str] = None,
    gru_bias_attr=None,
    act=None,
    gate_act=None,
    gru_layer_attr=None,
) -> LayerOutput:
    name = name or current_context().unique_name("gru_unit")
    assert input.size % 3 == 0
    if size is None:
        size = input.size // 3
    out_mem = memory(name=name, size=size)
    return gru_step_layer(
        name=name,
        input=input,
        output_mem=out_mem,
        size=size,
        bias_attr=gru_bias_attr if gru_bias_attr is not None else True,
        act=act,
        gate_act=gate_act,
        layer_attr=gru_layer_attr,
    )


def gru_group(
    input: LayerOutput,
    size: Optional[int] = None,
    name: Optional[str] = None,
    reverse: bool = False,
    gru_bias_attr=None,
    act=None,
    gate_act=None,
    gru_layer_attr=None,
    force_group: bool = False,
) -> LayerOutput:
    name = name or current_context().unique_name("gru_group")
    # The fixed step here is exactly one gru_unit, and the reference
    # documents gru_group as "exactly the same calculation as the
    # grumemory layer" (reference networks.py:741-755) — so at top level
    # lower straight to the fused gated_recurrent layer: identical layer
    # name, parameter names and shapes (checkpoint-compatible), one
    # lax.scan instead of a per-step layer group, and the fused Pallas
    # kernel applies under settings(pallas_rnn=True). Inside another
    # recurrent_group the group form is kept (nested sub-scan contract).
    # Consequence (doc/divergences.md): the '<name>_recurrent_group'
    # submodel and its step-level memory no longer exist at top level —
    # configs that reference them (get_output/memory against the step
    # form) pass force_group=True to keep the group form.
    if not current_context().submodel_stack and not force_group:
        assert size is None or input.size == 3 * size, (
            f"gru_group size {size} does not match input size {input.size}"
        )
        return grumemory(
            input=input,
            name=name,
            reverse=reverse,
            act=act,
            gate_act=gate_act,
            bias_attr=gru_bias_attr if gru_bias_attr is not None else True,
            layer_attr=gru_layer_attr,
        )

    def _step(ipt):
        return gru_unit(
            input=ipt,
            name=name,
            size=size,
            gru_bias_attr=gru_bias_attr,
            act=act,
            gate_act=gate_act,
            gru_layer_attr=gru_layer_attr,
        )

    return recurrent_group(
        name=f"{name}_recurrent_group", step=_step, reverse=reverse, input=input
    )


def simple_gru(
    input: LayerOutput,
    size: int,
    name: Optional[str] = None,
    reverse: bool = False,
    mixed_param_attr=None,
    mixed_bias_param_attr=None,
    mixed_layer_attr=None,
    gru_bias_attr=None,
    act=None,
    gate_act=None,
    gru_layer_attr=None,
) -> LayerOutput:
    name = name or current_context().unique_name("simple_gru")
    m = mixed_layer(
        name=f"{name}_transform",
        size=size * 3,
        input=[full_matrix_projection(input, param_attr=mixed_param_attr)],
        bias_attr=mixed_bias_param_attr if mixed_bias_param_attr is not None else False,
        layer_attr=mixed_layer_attr,
    )
    return gru_group(
        name=name,
        size=size,
        input=m,
        reverse=reverse,
        gru_bias_attr=gru_bias_attr,
        act=act,
        gate_act=gate_act,
        gru_layer_attr=gru_layer_attr,
    )


def bidirectional_lstm(
    input: LayerOutput,
    size: int,
    name: Optional[str] = None,
    return_seq: bool = False,
    fwd_mat_param_attr=None,
    fwd_bias_param_attr=None,
    fwd_inner_param_attr=None,
    bwd_mat_param_attr=None,
    bwd_bias_param_attr=None,
    bwd_inner_param_attr=None,
    last_seq_attr=None,
    first_seq_attr=None,
    concat_attr=None,
    concat_act=None,
) -> LayerOutput:
    """Forward + backward LSTM, concatenated (ref: networks.py:872)."""
    name = name or current_context().unique_name("bidirectional_lstm")
    fw = simple_lstm(
        name=f"{name}_fw",
        input=input,
        size=size,
        mat_param_attr=fwd_mat_param_attr,
        bias_param_attr=fwd_bias_param_attr,
        inner_param_attr=fwd_inner_param_attr,
    )
    bw = simple_lstm(
        name=f"{name}_bw",
        input=input,
        size=size,
        reverse=True,
        mat_param_attr=bwd_mat_param_attr,
        bias_param_attr=bwd_bias_param_attr,
        inner_param_attr=bwd_inner_param_attr,
    )
    if return_seq:
        return concat_layer(input=[fw, bw], name=name, act=concat_act, layer_attr=concat_attr)
    fw_end = last_seq(input=fw, name=f"{name}_fw_last", layer_attr=last_seq_attr)
    from paddle_tpu.trainer_config_helpers.layers import first_seq

    bw_end = first_seq(input=bw, name=f"{name}_bw_first", layer_attr=first_seq_attr)
    return concat_layer(input=[fw_end, bw_end], name=name, act=concat_act, layer_attr=concat_attr)


def simple_attention(
    encoded_sequence: LayerOutput,
    encoded_proj: LayerOutput,
    decoder_state: LayerOutput,
    transform_param_attr=None,
    softmax_param_attr=None,
    weight_act=None,
    name: Optional[str] = None,
) -> LayerOutput:
    """Bahdanau additive attention (ref: networks.py:943):
    scores = v·act(W s_{t-1} + U h_j); context = Σ softmax(scores)_j h_j."""
    name = name or current_context().unique_name("attention")
    assert encoded_proj.size == decoder_state.size
    proj_size = encoded_proj.size
    m = mixed_layer(
        size=proj_size,
        name=f"{name}_transform",
        input=[full_matrix_projection(decoder_state, param_attr=transform_param_attr)],
    )
    expanded = expand_layer(input=m, expand_as=encoded_sequence, name=f"{name}_expand")
    combined = mixed_layer(
        size=proj_size,
        name=f"{name}_combine",
        act=weight_act or TanhActivation(),
        input=[identity_projection(expanded), identity_projection(encoded_proj)],
    )
    attention_weight = fc_layer(
        input=combined,
        size=1,
        act=SequenceSoftmaxActivation(),
        param_attr=softmax_param_attr,
        name=f"{name}_softmax",
        bias_attr=False,
    )
    scaled = scaling_layer(weight=attention_weight, input=encoded_sequence, name=f"{name}_scaling")
    return pooling_layer(input=scaled, pooling_type=SumPooling(), name=f"{name}_pooling")


# ------------------------------------------------------------ vision nets


def simple_img_conv_pool(
    input: LayerOutput,
    filter_size: int,
    num_filters: int,
    pool_size: int,
    name: Optional[str] = None,
    pool_type=None,
    act=None,
    groups: int = 1,
    conv_stride: int = 1,
    conv_padding: int = 0,
    bias_attr=None,
    num_channel: Optional[int] = None,
    param_attr=None,
    shared_bias: bool = True,
    conv_layer_attr=None,
    pool_stride: int = 1,
    pool_start: int = 0,
    pool_padding: int = 0,
    pool_layer_attr=None,
) -> LayerOutput:
    name = name or current_context().unique_name("conv_pool")
    conv = img_conv_layer(
        name=f"{name}_conv",
        input=input,
        filter_size=filter_size,
        num_filters=num_filters,
        num_channels=num_channel,
        act=act,
        groups=groups,
        stride=conv_stride,
        padding=conv_padding,
        bias_attr=bias_attr if bias_attr is not None else True,
        param_attr=param_attr,
        shared_biases=shared_bias,
        layer_attr=conv_layer_attr,
    )
    return img_pool_layer(
        name=f"{name}_pool",
        input=conv,
        pool_size=pool_size,
        pool_type=pool_type or MaxPooling(),
        stride=pool_stride,
        start=pool_start,
        padding=pool_padding,
        layer_attr=pool_layer_attr,
    )


def img_conv_bn_pool(
    input: LayerOutput,
    filter_size: int,
    num_filters: int,
    pool_size: int,
    name: Optional[str] = None,
    pool_type=None,
    act=None,
    groups: int = 1,
    conv_stride: int = 1,
    conv_padding: int = 0,
    conv_bias_attr=None,
    num_channel: Optional[int] = None,
    conv_param_attr=None,
    shared_bias: bool = True,
    conv_layer_attr=None,
    bn_param_attr=None,
    bn_bias_attr=None,
    bn_layer_attr=None,
    pool_stride: int = 1,
    pool_start: int = 0,
    pool_padding: int = 0,
    pool_layer_attr=None,
) -> LayerOutput:
    name = name or current_context().unique_name("conv_bn_pool")
    conv = img_conv_layer(
        name=f"{name}_conv",
        input=input,
        filter_size=filter_size,
        num_filters=num_filters,
        num_channels=num_channel,
        act=LinearActivation(),
        groups=groups,
        stride=conv_stride,
        padding=conv_padding,
        bias_attr=conv_bias_attr if conv_bias_attr is not None else True,
        param_attr=conv_param_attr,
        shared_biases=shared_bias,
        layer_attr=conv_layer_attr,
    )
    bn = batch_norm_layer(
        name=f"{name}_bn",
        input=conv,
        act=act or ReluActivation(),
        bias_attr=bn_bias_attr if bn_bias_attr is not None else True,
        param_attr=bn_param_attr,
        layer_attr=bn_layer_attr,
    )
    return img_pool_layer(
        name=f"{name}_pool",
        input=bn,
        pool_size=pool_size,
        pool_type=pool_type or MaxPooling(),
        stride=pool_stride,
        start=pool_start,
        padding=pool_padding,
        layer_attr=pool_layer_attr,
    )


def img_conv_group(
    input: LayerOutput,
    conv_num_filter: Sequence[int],
    pool_size: int,
    num_channels: Optional[int] = None,
    conv_padding: Union[int, Sequence[int]] = 1,
    conv_filter_size: Union[int, Sequence[int]] = 3,
    conv_act: Optional[BaseActivation] = None,
    conv_with_batchnorm: Union[bool, Sequence[bool]] = False,
    conv_batchnorm_drop_rate: Union[float, Sequence[float]] = 0,
    pool_stride: int = 1,
    pool_type=None,
) -> LayerOutput:
    """Stack of convs (optionally with BN+dropout) followed by one pool
    (ref: networks.py:279 — the VGG building block)."""
    n = len(conv_num_filter)
    expand = lambda v: list(v) if isinstance(v, (list, tuple)) else [v] * n
    paddings = expand(conv_padding)
    fsizes = expand(conv_filter_size)
    with_bn = expand(conv_with_batchnorm)
    drop_rates = expand(conv_batchnorm_drop_rate)
    tmp = input
    channels = num_channels
    for i in range(n):
        tmp = img_conv_layer(
            input=tmp,
            padding=paddings[i],
            filter_size=fsizes[i],
            num_filters=conv_num_filter[i],
            num_channels=channels,
            act=LinearActivation() if with_bn[i] else (conv_act or ReluActivation()),
        )
        channels = None
        if with_bn[i]:
            dr = drop_rates[i]
            tmp = batch_norm_layer(
                input=tmp,
                act=conv_act or ReluActivation(),
                layer_attr=ExtraLayerAttribute(drop_rate=dr) if dr else None,
            )
    return img_pool_layer(
        input=tmp, pool_size=pool_size, stride=pool_stride, pool_type=pool_type or MaxPooling()
    )


def small_vgg(input_image: LayerOutput, num_channels: int, num_classes: int) -> LayerOutput:
    """VGG-style CIFAR net (ref: networks.py:359)."""

    def _vgg_block(ipt, num_filter, times, dropouts, channels=None):
        return img_conv_group(
            input=ipt,
            num_channels=channels,
            pool_size=2,
            pool_stride=2,
            conv_num_filter=[num_filter] * times,
            conv_filter_size=3,
            conv_act=ReluActivation(),
            conv_with_batchnorm=True,
            conv_batchnorm_drop_rate=dropouts,
            pool_type=MaxPooling(),
        )

    tmp = _vgg_block(input_image, 64, 2, [0.3, 0], channels=num_channels)
    tmp = _vgg_block(tmp, 128, 2, [0.4, 0])
    tmp = _vgg_block(tmp, 256, 3, [0.4, 0.4, 0])
    tmp = _vgg_block(tmp, 512, 3, [0.4, 0.4, 0])
    tmp = img_pool_layer(input=tmp, stride=2, pool_size=2, pool_type=MaxPooling())
    tmp = dropout_layer(input=tmp, dropout_rate=0.5)
    tmp = fc_layer(
        input=tmp,
        size=512,
        act=LinearActivation(),
        bias_attr=False,
    )
    tmp = batch_norm_layer(
        input=tmp, act=ReluActivation(), layer_attr=ExtraLayerAttribute(drop_rate=0.5)
    )
    tmp = fc_layer(input=tmp, size=512, act=LinearActivation())
    from paddle_tpu.trainer_config_helpers.activations import SoftmaxActivation

    return fc_layer(input=tmp, size=num_classes, act=SoftmaxActivation())


def vgg_16_network(input_image: LayerOutput, num_channels: int, num_classes: int = 1000) -> LayerOutput:
    """VGG-16 (ref: networks.py:384)."""
    tmp = img_conv_group(
        input=input_image,
        num_channels=num_channels,
        conv_padding=1,
        conv_num_filter=[64, 64],
        conv_filter_size=3,
        conv_act=ReluActivation(),
        pool_size=2,
        pool_stride=2,
        pool_type=MaxPooling(),
    )
    for filters, times in [(128, 2), (256, 3), (512, 3), (512, 3)]:
        tmp = img_conv_group(
            input=tmp,
            conv_padding=1,
            conv_num_filter=[filters] * times,
            conv_filter_size=3,
            conv_act=ReluActivation(),
            pool_size=2,
            pool_stride=2,
            pool_type=MaxPooling(),
        )
    tmp = fc_layer(
        input=tmp, size=4096, act=ReluActivation(),
        layer_attr=ExtraLayerAttribute(drop_rate=0.5),
    )
    tmp = fc_layer(
        input=tmp, size=4096, act=ReluActivation(),
        layer_attr=ExtraLayerAttribute(drop_rate=0.5),
    )
    from paddle_tpu.trainer_config_helpers.activations import SoftmaxActivation

    return fc_layer(input=tmp, size=num_classes, act=SoftmaxActivation())


def outputs(layers, *args) -> None:
    """Declare the network outputs (ref: networks.py:1055)."""
    ctx = current_context()
    if isinstance(layers, LayerOutput):
        layers = [layers]
    layers = list(layers) + [a for a in args]
    for l in layers:
        ctx.mark_output(l.name)
