"""trainer_config_helpers.layers — the user-facing layer DSL.

API-compatible rebuild of /root/reference/python/paddle/
trainer_config_helpers/layers.py (fc_layer:658, data_layer:599,
lstmemory:788, recurrent_group:2141, beam_search:2363, ...). Functions
return ``LayerOutput`` handles and append LayerConfig/ParameterConfig
records to the active ConfigContext. No numerics here — the runtime
compiles the resulting ModelConfig (paddle_tpu.graph).
"""

from __future__ import annotations

import math
from typing import Any, Callable, List, Optional, Sequence, Union

from paddle_tpu.config.builder import current_context
from paddle_tpu.proto import (
    ConvConfig,
    EvaluatorConfig,
    GeneratorConfig,
    ImageConfig,
    LayerConfig,
    LayerInputConfig,
    LinkConfig,
    MemoryConfig,
    NormConfig,
    OperatorConfig,
    ParameterConfig,
    PoolConfig,
    ProjectionConfig,
    BlockExpandConfig,
)
from paddle_tpu.trainer_config_helpers.activations import (
    BaseActivation,
    IdentityActivation,
    ReluActivation,
    SigmoidActivation,
    TanhActivation,
)
from paddle_tpu.trainer_config_helpers.attrs import ExtraLayerAttribute, ParameterAttribute
from paddle_tpu.trainer_config_helpers.poolings import AvgPooling, BasePoolingType, MaxPooling

__all__ = [
    "LayerOutput",
    "StaticInput",
    "SubsequenceInput",
    "GeneratedInput",
    "AggregateLevel",
    "ExpandLevel",
    "full_matrix_projection",
    "trans_full_matrix_projection",
    "table_projection",
    "identity_projection",
    "dotmul_projection",
    "context_projection",
    "conv_operator",
    "dotmul_operator",
    "mixed_layer",
    "data_layer",
    "embedding_layer",
    "sparse_embedding",
    "fc_layer",
    "pooling_layer",
    "lstmemory",
    "grumemory",
    "recurrent_layer",
    "last_seq",
    "first_seq",
    "expand_layer",
    "interpolation_layer",
    "power_layer",
    "scaling_layer",
    "trans_layer",
    "cos_sim",
    "hsigmoid",
    "img_conv_layer",
    "img_pool_layer",
    "img_cmrnorm_layer",
    "batch_norm_layer",
    "sum_to_one_norm_layer",
    "addto_layer",
    "concat_layer",
    "memory",
    "lstm_step_layer",
    "gru_step_layer",
    "get_output_layer",
    "recurrent_group",
    "maxid_layer",
    "eos_layer",
    "beam_search",
    "regression_cost",
    "classification_cost",
    "auc_validation",
    "pnpair_validation",
    "conv_shift_layer",
    "tensor_layer",
    "selective_fc_layer",
    "sampling_id_layer",
    "slope_intercept_layer",
    "convex_comb_layer",
    "block_expand_layer",
    "ctc_layer",
    "crf_layer",
    "crf_decoding_layer",
    "rank_cost",
    "lambda_cost",
    "cross_entropy",
    "cross_entropy_with_selfnorm",
    "huber_cost",
    "multi_binary_label_cross_entropy",
    "nce_layer",
    "dropout_layer",
    "out_prod_layer",
    "multiplex_layer",
    "multi_head_attention_layer",
    "mdlstm_layer",
    "sub_network",
]


class AggregateLevel:
    EACH_TIMESTEP = "non-seq"
    EACH_SEQUENCE = "seq"


class ExpandLevel:
    FROM_TIMESTEP = "non-seq"
    FROM_SEQUENCE = "seq"


class LayerOutput:
    """Handle to a configured layer (reference: layers.py LayerOutput)."""

    def __init__(
        self,
        name: str,
        layer_type: str,
        parents: Optional[List["LayerOutput"]] = None,
        size: Optional[int] = None,
        activation: Optional[BaseActivation] = None,
        reverse: Optional[bool] = None,
        outputs: Optional[List[str]] = None,
    ):
        self.name = name
        self.layer_type = layer_type
        self.parents = parents or []
        self.size = size
        self.activation = activation
        self.reverse = reverse
        self.outputs = outputs

    def __repr__(self):
        return f"LayerOutput({self.name!r}, type={self.layer_type!r}, size={self.size})"


class StaticInput:
    """Whole-value input to a recurrent_group (same value every step)."""

    def __init__(self, input: LayerOutput, is_seq: bool = False, size: Optional[int] = None):
        self.input = input
        self.is_seq = is_seq
        self.size = size or input.size


class SubsequenceInput:
    """Nested-sequence in-link: the group steps over subsequences."""

    def __init__(self, input: LayerOutput):
        self.input = input


class GeneratedInput:
    """Generation-time input: embedding of the previously generated token."""

    def __init__(
        self,
        size: int,
        embedding_name: str,
        embedding_size: int,
        eos_id: Optional[int] = None,
    ):
        self.size = size
        self.embedding_name = embedding_name
        self.embedding_size = embedding_size
        self.eos_id = eos_id


# --------------------------------------------------------------- helpers


def _ctx():
    return current_context()


def _act_name(act: Optional[BaseActivation]) -> str:
    if act is None:
        return ""
    return act.name


def _apply_layer_attr(cfg: LayerConfig, layer_attr: Optional[ExtraLayerAttribute]) -> None:
    if layer_attr is not None:
        layer_attr.apply_to(cfg)


def _create_parameter(
    name: str,
    size: int,
    dims: Sequence[int],
    attr: Optional[Union[ParameterAttribute, bool]] = None,
    is_bias: bool = False,
    sparse: bool = False,
) -> str:
    """Create (or share) a ParameterConfig; returns its name.

    Default init mirrors the reference (config_parser.py:2780-2840):
    weights N(0, 0.01) unless initial_smart/attr overrides; biases zero.
    """
    ctx = _ctx()
    d = ctx.defaults
    pc = ParameterConfig(name=name, size=int(size), dims=[int(x) for x in dims])
    pc.momentum = d.get("momentum", 0.0)
    pc.decay_rate = d.get("decay_rate", 0.0)
    pc.decay_rate_l1 = d.get("decay_rate_l1", 0.0)
    pc.gradient_clipping_threshold = d.get("gradient_clipping_threshold", 0.0)
    if is_bias:
        pc.initial_mean = 0.0
        pc.initial_std = 0.0
    else:
        pc.initial_mean = d.get("initial_mean", 0.0)
        pc.initial_std = d.get("initial_std", 0.01)
        pc.initial_strategy = d.get("initial_strategy", 0)
        # reference semantics: a weight with no explicit init attr gets
        # "smart" init, std = 1/sqrt(fan_in) (attrs.py:67 ParamAttr() →
        # {'initial_smart': True}); the 0.01 default only applies when the
        # user set default_initial_std()/settings overrides.
        pc.initial_smart = d.get(
            "initial_smart",
            not isinstance(attr, ParameterAttribute) and "initial_std" not in d,
        )
    if isinstance(attr, ParameterAttribute):
        if attr.name:
            # shared parameter: reuse existing config if present
            pc.name = attr.name
            if attr.name in ctx.param_map:
                existing = ctx.param_map[attr.name]
                if existing.size != pc.size:
                    raise ValueError(
                        f"shared parameter {attr.name!r} size mismatch: "
                        f"{existing.size} vs {pc.size}"
                    )
                existing.is_shared = True
                return attr.name
        attr.apply_to(pc)
    if sparse:
        pc.is_sparse = True
    if pc.initial_smart:
        pc.initial_mean = 0.0
        fan = pc.dims[0] if pc.dims else pc.size
        pc.initial_std = 1.0 / math.sqrt(fan)
    ctx.add_parameter(pc)
    return pc.name


def _bias_name(
    layer_name: str,
    size: int,
    bias_attr: Union[bool, ParameterAttribute, None],
) -> str:
    """Resolve the bias_attr convention: False/None→no bias unless
    ParamAttr; True→default bias. Returns '' for no bias."""
    if bias_attr is False or bias_attr is None:
        return ""
    attr = bias_attr if isinstance(bias_attr, ParameterAttribute) else None
    name = (attr.name if attr and attr.name else f"_{layer_name}.wbias")
    ctx = _ctx()
    if name in ctx.param_map:
        return name
    return _create_parameter(name, size, [1, size], attr, is_bias=True)


def _add_layer(cfg: LayerConfig, layer_attr=None) -> LayerConfig:
    _apply_layer_attr(cfg, layer_attr)
    return _ctx().add_layer(cfg)


def _input(
    layer: LayerOutput,
    param_name: str = "",
    **kw,
) -> LayerInputConfig:
    return LayerInputConfig(input_layer_name=layer.name, input_parameter_name=param_name, **kw)


def _name(name: Optional[str], prefix: str) -> str:
    if name is not None:
        return name
    return _ctx().unique_name(prefix)


def _to_list(x) -> list:
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


# ------------------------------------------------------------ projections


class _Projection:
    """Deferred projection: materialized when attached to a mixed layer."""

    def __init__(self, type_: str, input: LayerOutput, size: int, param_attr=None, **extra):
        self.type = type_
        self.input = input
        self.size = size
        self.param_attr = param_attr
        self.extra = extra

    def materialize(self, mixed_name: str, mixed_size: int, idx: int) -> LayerInputConfig:
        out_size = self.size or mixed_size
        in_size = self.input.size
        proj = ProjectionConfig(
            type=self.type, name=f"{mixed_name}.proj{idx}", input_size=in_size, output_size=out_size
        )
        pname = ""
        if self.type == "fc":
            pname = _create_parameter(
                f"_{mixed_name}.w{idx}", in_size * out_size, [in_size, out_size], self.param_attr
            )
        elif self.type == "trans_fc":
            pname = _create_parameter(
                f"_{mixed_name}.w{idx}", in_size * out_size, [out_size, in_size], self.param_attr
            )
        elif self.type == "table":
            pname = _create_parameter(
                f"_{mixed_name}.w{idx}",
                in_size * out_size,
                [in_size, out_size],
                self.param_attr,
                sparse=bool(self.extra.get("sparse", False)),
            )
        elif self.type == "dot_mul":
            pname = _create_parameter(
                f"_{mixed_name}.w{idx}", out_size, [1, out_size], self.param_attr
            )
        elif self.type == "context":
            proj.context_start = self.extra["context_start"]
            proj.context_length = self.extra["context_length"]
            proj.trainable_padding = self.extra.get("trainable_padding", False)
            if proj.trainable_padding:
                total_pad = max(0, -proj.context_start) + max(
                    0, proj.context_start + proj.context_length - 1
                )
                pname = _create_parameter(
                    f"_{mixed_name}.w{idx}", total_pad * in_size, [total_pad, in_size], self.param_attr
                )
            proj.output_size = in_size * proj.context_length
        elif self.type == "identity_offset":
            proj.offset = self.extra.get("offset", 0)
        return LayerInputConfig(
            input_layer_name=self.input.name, input_parameter_name=pname, proj_conf=proj
        )

    def output_size(self, mixed_size: int) -> int:
        if self.type == "context":
            return self.input.size * self.extra["context_length"]
        return self.size or mixed_size


def full_matrix_projection(input: LayerOutput, size: int = 0, param_attr=None) -> _Projection:
    return _Projection("fc", input, size, param_attr)


def trans_full_matrix_projection(input: LayerOutput, size: int = 0, param_attr=None) -> _Projection:
    return _Projection("trans_fc", input, size, param_attr)


def table_projection(input: LayerOutput, size: int = 0, param_attr=None) -> _Projection:
    return _Projection("table", input, size, param_attr)


def identity_projection(input: LayerOutput, offset: Optional[int] = None) -> _Projection:
    if offset is None:
        return _Projection("identity", input, input.size)
    return _Projection("identity_offset", input, 0, offset=offset)


def dotmul_projection(input: LayerOutput, param_attr=None, scale: float = 1.0) -> _Projection:
    return _Projection("dot_mul", input, input.size, param_attr)


def context_projection(
    input: LayerOutput,
    context_len: int,
    context_start: Optional[int] = None,
    padding_attr: Union[bool, ParameterAttribute] = False,
) -> _Projection:
    start = context_start if context_start is not None else -(context_len // 2)
    trainable = isinstance(padding_attr, ParameterAttribute) or padding_attr is True
    return _Projection(
        "context",
        input,
        0,
        padding_attr if isinstance(padding_attr, ParameterAttribute) else None,
        context_start=start,
        context_length=context_len,
        trainable_padding=trainable,
    )


class _Operator:
    def __init__(self, type_: str, inputs: List[LayerOutput], conf: OperatorConfig):
        self.type = type_
        self.inputs = inputs
        self.conf = conf


def dotmul_operator(a: LayerOutput, b: LayerOutput, scale: float = 1.0) -> _Operator:
    conf = OperatorConfig(
        type="dot_mul", output_size=a.size, input_sizes=[a.size, b.size], dotmul_scale=scale
    )
    return _Operator("dot_mul", [a, b], conf)


def conv_operator(
    input: Sequence[LayerOutput],
    filter_size: int,
    num_filters: int,
    num_channel: Optional[int] = None,
    stride: int = 1,
    padding: int = 0,
    filter_size_y: Optional[int] = None,
    stride_y: Optional[int] = None,
    padding_y: Optional[int] = None,
) -> _Operator:
    img, filt = input[0], input[1]
    num_channel = num_channel or 1
    img_size = int(math.sqrt(img.size // num_channel))
    out_x = _conv_out(img_size, filter_size, padding, stride, caffe_mode=True)
    cc = ConvConfig(
        filter_size=filter_size,
        channels=num_channel,
        stride=stride,
        padding=padding,
        groups=1,
        filter_channels=num_channel,
        output_x=out_x,
        img_size=img_size,
        filter_size_y=filter_size_y or filter_size,
        stride_y=stride_y or stride,
        padding_y=padding_y or padding,
    )
    conf = OperatorConfig(
        type="conv",
        output_size=out_x * out_x * num_filters,
        input_sizes=[img.size, filt.size],
        conv_conf=cc,
        num_filters=num_filters,
    )
    return _Operator("conv", [img, filt], conf)


# ----------------------------------------------------------- mixed layer


class _MixedLayer(LayerOutput):
    """mixed_layer handle supporting `with ... as m: m += proj` style."""

    def __init__(self, name, size, act, bias_attr, layer_attr):
        super().__init__(name, "mixed", [], size, act)
        self._pending: List[Union[_Projection, _Operator]] = []
        self._bias_attr = bias_attr
        self._layer_attr = layer_attr
        self._finalized = False

    def __iadd__(self, other):
        assert not self._finalized, "mixed_layer already finalized"
        self._pending.append(other)
        return self

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            self._finalize()

    def _finalize(self):
        if self._finalized:
            return
        self._finalized = True
        cfg = LayerConfig(name=self.name, type="mixed", active_type=_act_name(self.activation))
        size = self.size or 0
        # infer size from first projection/operator if not given
        for item in self._pending:
            if size:
                break
            if isinstance(item, _Projection):
                size = item.output_size(0)
            else:
                size = item.conf.output_size
        self.size = size
        cfg.size = size
        idx = 0
        op_layer_index = {}
        for item in self._pending:
            if isinstance(item, _Projection):
                cfg.inputs.append(item.materialize(self.name, size, idx))
                self.parents.append(item.input)
                op_layer_index[id(item.input)] = len(cfg.inputs) - 1
                idx += 1
            else:
                indices = []
                for l in item.inputs:
                    cfg.inputs.append(LayerInputConfig(input_layer_name=l.name))
                    self.parents.append(l)
                    indices.append(len(cfg.inputs) - 1)
                item.conf.input_indices = indices
                item.conf.output_size = item.conf.output_size or size
                cfg.operator_confs.append(item.conf)
        cfg.bias_parameter_name = _bias_name(self.name, size, self._bias_attr)
        _add_layer(cfg, self._layer_attr)


def mixed_layer(
    size: int = 0,
    input: Optional[Sequence[Union[_Projection, _Operator]]] = None,
    name: Optional[str] = None,
    act: Optional[BaseActivation] = None,
    bias_attr: Union[bool, ParameterAttribute] = False,
    layer_attr: Optional[ExtraLayerAttribute] = None,
) -> LayerOutput:
    name = _name(name, "mixed")
    m = _MixedLayer(name, size, act or IdentityActivation(), bias_attr, layer_attr)
    if input is not None:
        for item in _to_list(input):
            m += item
        m._finalize()
    return m


# ------------------------------------------------------------ basic layers


def data_layer(name: str, size: int, layer_attr=None) -> LayerOutput:
    cfg = LayerConfig(name=name, type="data", size=size)
    _add_layer(cfg, layer_attr)
    _ctx().mark_input(name)
    return LayerOutput(name, "data", size=size)


def fc_layer(
    input: Union[LayerOutput, Sequence[LayerOutput]],
    size: int,
    act: Optional[BaseActivation] = None,
    name: Optional[str] = None,
    param_attr: Optional[Union[ParameterAttribute, Sequence[ParameterAttribute]]] = None,
    bias_attr: Union[bool, ParameterAttribute] = True,
    layer_attr: Optional[ExtraLayerAttribute] = None,
) -> LayerOutput:
    name = _name(name, "fc")
    inputs = _to_list(input)
    attrs = param_attr if isinstance(param_attr, (list, tuple)) else [param_attr] * len(inputs)
    cfg = LayerConfig(name=name, type="fc", size=size, active_type=_act_name(act or TanhActivation()))
    for i, (inp, attr) in enumerate(zip(inputs, attrs)):
        pname = _create_parameter(
            f"_{name}.w{i}", inp.size * size, [inp.size, size], attr
        )
        cfg.inputs.append(_input(inp, pname))
    cfg.bias_parameter_name = _bias_name(name, size, bias_attr)
    _add_layer(cfg, layer_attr)
    return LayerOutput(name, "fc", inputs, size, act)


def embedding_layer(
    input: LayerOutput,
    size: int,
    name: Optional[str] = None,
    param_attr: Optional[ParameterAttribute] = None,
    layer_attr=None,
) -> LayerOutput:
    return mixed_layer(
        size=size,
        input=[table_projection(input, size, param_attr)],
        name=_name(name, "embedding"),
        act=IdentityActivation(),
        bias_attr=False,
        layer_attr=layer_attr,
    )


def sparse_embedding(
    input: LayerOutput,
    size: int,
    name: Optional[str] = None,
    param_attr: Optional[ParameterAttribute] = None,
    layer_attr=None,
) -> LayerOutput:
    """An :func:`embedding_layer` whose table trains on the row-sparse
    path (doc/sparse.md): ``sparse_update=True`` is forced onto the
    table's :class:`ParameterAttribute`, so gradients stay per-row
    (``RowSparseGrad``), optimizer slots update only touched rows, the
    durable checkpoint stamps ``row_range`` into the table's shard
    records, and multi-host relaunches reshard the rows. The config
    helper the CTR demo (demo/ctr/) builds its id features with."""
    if param_attr is None:
        param_attr = ParameterAttribute(sparse_update=True)
    else:
        param_attr.sparse_update = True
    return embedding_layer(
        input, size, name=_name(name, "sparse_embedding"),
        param_attr=param_attr, layer_attr=layer_attr,
    )


def pooling_layer(
    input: LayerOutput,
    pooling_type: Optional[BasePoolingType] = None,
    name: Optional[str] = None,
    bias_attr: Union[bool, ParameterAttribute] = False,
    agg_level: str = AggregateLevel.EACH_TIMESTEP,
    layer_attr=None,
) -> LayerOutput:
    pooling_type = pooling_type or MaxPooling()
    type_map = {"max": "max", "average": "average", "sum": "average", "squarerootn": "average"}
    ltype = type_map[pooling_type.name]
    name = _name(name, "pool")
    cfg = LayerConfig(name=name, type=ltype, size=input.size, trans_type=agg_level)
    if ltype == "average":
        cfg.average_strategy = pooling_type.name if pooling_type.name != "average" else "average"
    cfg.inputs.append(_input(input))
    cfg.bias_parameter_name = _bias_name(name, input.size, bias_attr)
    _add_layer(cfg, layer_attr)
    return LayerOutput(name, ltype, [input], input.size)


def lstmemory(
    input: LayerOutput,
    name: Optional[str] = None,
    reverse: bool = False,
    act: Optional[BaseActivation] = None,
    gate_act: Optional[BaseActivation] = None,
    state_act: Optional[BaseActivation] = None,
    bias_attr: Union[bool, ParameterAttribute] = True,
    param_attr: Optional[ParameterAttribute] = None,
    layer_attr=None,
) -> LayerOutput:
    assert input.size % 4 == 0, "lstmemory input size must be 4*size"
    size = input.size // 4
    name = _name(name, "lstmemory")
    cfg = LayerConfig(
        name=name,
        type="lstmemory",
        size=size,
        active_type=_act_name(act or TanhActivation()),
        active_gate_type=_act_name(gate_act or SigmoidActivation()),
        active_state_type=_act_name(state_act or TanhActivation()),
        reversed=reverse,
    )
    pname = _create_parameter(f"_{name}.w0", size * size * 4, [size, 4 * size], param_attr)
    cfg.inputs.append(_input(input, pname))
    if bias_attr is not False and bias_attr is not None:
        attr = bias_attr if isinstance(bias_attr, ParameterAttribute) else None
        bname = (attr.name if attr and attr.name else f"_{name}.wbias")
        if bname not in _ctx().param_map:
            bname = _create_parameter(bname, 7 * size, [1, 7 * size], attr, is_bias=True)
        cfg.bias_parameter_name = bname
    _add_layer(cfg, layer_attr)
    return LayerOutput(name, "lstmemory", [input], size, act, reverse)


def grumemory(
    input: LayerOutput,
    name: Optional[str] = None,
    reverse: bool = False,
    act: Optional[BaseActivation] = None,
    gate_act: Optional[BaseActivation] = None,
    bias_attr: Union[bool, ParameterAttribute] = True,
    param_attr: Optional[ParameterAttribute] = None,
    layer_attr=None,
) -> LayerOutput:
    assert input.size % 3 == 0, "grumemory input size must be 3*size"
    size = input.size // 3
    name = _name(name, "grumemory")
    cfg = LayerConfig(
        name=name,
        type="gated_recurrent",
        size=size,
        active_type=_act_name(act or TanhActivation()),
        active_gate_type=_act_name(gate_act or SigmoidActivation()),
        reversed=reverse,
    )
    pname = _create_parameter(f"_{name}.w0", size * size * 3, [size, 3 * size], param_attr)
    cfg.inputs.append(_input(input, pname))
    if bias_attr is not False and bias_attr is not None:
        attr = bias_attr if isinstance(bias_attr, ParameterAttribute) else None
        bname = (attr.name if attr and attr.name else f"_{name}.wbias")
        if bname not in _ctx().param_map:
            bname = _create_parameter(bname, 3 * size, [1, 3 * size], attr, is_bias=True)
        cfg.bias_parameter_name = bname
    _add_layer(cfg, layer_attr)
    return LayerOutput(name, "gated_recurrent", [input], size, act, reverse)


def recurrent_layer(
    input: LayerOutput,
    act: Optional[BaseActivation] = None,
    bias_attr: Union[bool, ParameterAttribute] = True,
    param_attr: Optional[ParameterAttribute] = None,
    name: Optional[str] = None,
    reverse: bool = False,
    layer_attr=None,
) -> LayerOutput:
    size = input.size
    name = _name(name, "recurrent")
    cfg = LayerConfig(
        name=name, type="recurrent", size=size, active_type=_act_name(act or TanhActivation()),
        reversed=reverse,
    )
    pname = _create_parameter(f"_{name}.w0", size * size, [size, size], param_attr)
    cfg.inputs.append(_input(input, pname))
    cfg.bias_parameter_name = _bias_name(name, size, bias_attr)
    _add_layer(cfg, layer_attr)
    return LayerOutput(name, "recurrent", [input], size, act, reverse)


def last_seq(
    input: LayerOutput,
    name: Optional[str] = None,
    agg_level: str = AggregateLevel.EACH_TIMESTEP,
    layer_attr=None,
) -> LayerOutput:
    name = _name(name, "seqlastins")
    cfg = LayerConfig(name=name, type="seqlastins", size=input.size, trans_type=agg_level)
    cfg.inputs.append(_input(input))
    _add_layer(cfg, layer_attr)
    return LayerOutput(name, "seqlastins", [input], input.size)


def first_seq(
    input: LayerOutput,
    name: Optional[str] = None,
    agg_level: str = AggregateLevel.EACH_TIMESTEP,
    layer_attr=None,
) -> LayerOutput:
    name = _name(name, "seqfirstins")
    cfg = LayerConfig(
        name=name, type="seqlastins", size=input.size, trans_type=agg_level, select_first=True
    )
    cfg.inputs.append(_input(input))
    _add_layer(cfg, layer_attr)
    return LayerOutput(name, "seqfirstins", [input], input.size)


def expand_layer(
    input: LayerOutput,
    expand_as: LayerOutput,
    name: Optional[str] = None,
    bias_attr: Union[bool, ParameterAttribute] = False,
    expand_level: str = ExpandLevel.FROM_TIMESTEP,
    layer_attr=None,
) -> LayerOutput:
    name = _name(name, "expand")
    cfg = LayerConfig(name=name, type="expand", size=input.size, trans_type=expand_level)
    cfg.inputs.append(_input(input))
    cfg.inputs.append(_input(expand_as))
    cfg.bias_parameter_name = _bias_name(name, input.size, bias_attr)
    _add_layer(cfg, layer_attr)
    return LayerOutput(name, "expand", [input, expand_as], input.size)


def interpolation_layer(input: Sequence[LayerOutput], weight: LayerOutput, name=None, layer_attr=None):
    a, b = input[0], input[1]
    name = _name(name, "interpolation")
    cfg = LayerConfig(name=name, type="interpolation", size=a.size)
    cfg.inputs.append(_input(weight))
    cfg.inputs.append(_input(a))
    cfg.inputs.append(_input(b))
    _add_layer(cfg, layer_attr)
    return LayerOutput(name, "interpolation", [weight, a, b], a.size)


def power_layer(input: LayerOutput, weight: LayerOutput, name=None, layer_attr=None):
    name = _name(name, "power")
    cfg = LayerConfig(name=name, type="power", size=input.size)
    cfg.inputs.append(_input(weight))
    cfg.inputs.append(_input(input))
    _add_layer(cfg, layer_attr)
    return LayerOutput(name, "power", [weight, input], input.size)


def scaling_layer(input: LayerOutput, weight: LayerOutput, name=None, layer_attr=None):
    name = _name(name, "scaling")
    cfg = LayerConfig(name=name, type="scaling", size=input.size)
    cfg.inputs.append(_input(weight))
    cfg.inputs.append(_input(input))
    _add_layer(cfg, layer_attr)
    return LayerOutput(name, "scaling", [weight, input], input.size)


def trans_layer(input: LayerOutput, name=None, layer_attr=None):
    name = _name(name, "trans")
    cfg = LayerConfig(name=name, type="trans", size=input.size)
    cfg.inputs.append(_input(input))
    _add_layer(cfg, layer_attr)
    return LayerOutput(name, "trans", [input], input.size)


def cos_sim(a: LayerOutput, b: LayerOutput, scale: float = 5.0, size: int = 1, name=None, layer_attr=None):
    name = _name(name, "cos")
    if size == 1:
        cfg = LayerConfig(name=name, type="cos", size=1, cos_scale=scale)
    else:
        cfg = LayerConfig(name=name, type="cos_vm", size=size, cos_scale=scale)
    cfg.inputs.append(_input(a))
    cfg.inputs.append(_input(b))
    _add_layer(cfg, layer_attr)
    return LayerOutput(name, cfg.type, [a, b], size)


def hsigmoid(
    input: Union[LayerOutput, Sequence[LayerOutput]],
    label: LayerOutput,
    num_classes: int,
    name: Optional[str] = None,
    bias_attr: Union[bool, ParameterAttribute] = True,
    param_attr: Optional[Union[ParameterAttribute, Sequence]] = None,
    layer_attr=None,
) -> LayerOutput:
    name = _name(name, "hsigmoid")
    inputs = _to_list(input)
    attrs = param_attr if isinstance(param_attr, (list, tuple)) else [param_attr] * len(inputs)
    cfg = LayerConfig(name=name, type="hsigmoid", size=1, num_classes=num_classes)
    for i, (inp, attr) in enumerate(zip(inputs, attrs)):
        pname = _create_parameter(
            f"_{name}.w{i}", (num_classes - 1) * inp.size, [num_classes - 1, inp.size], attr
        )
        cfg.inputs.append(_input(inp, pname))
    cfg.inputs.append(_input(label))
    cfg.bias_parameter_name = _bias_name(name, num_classes - 1, bias_attr)
    _add_layer(cfg, layer_attr)
    out = LayerOutput(name, "hsigmoid", inputs + [label], 1)
    _ctx().mark_output(name)
    return out


def _conv_out(img: int, f: int, p: int, s: int, caffe_mode: bool = True) -> int:
    if caffe_mode:
        return (img - f + 2 * p) // s + 1
    return (img - f + 2 * p + s - 1) // s + 1


def img_conv_layer(
    input: LayerOutput,
    filter_size: int,
    num_filters: int,
    name: Optional[str] = None,
    num_channels: Optional[int] = None,
    act: Optional[BaseActivation] = None,
    groups: int = 1,
    stride: int = 1,
    padding: int = 0,
    bias_attr: Union[bool, ParameterAttribute] = True,
    param_attr: Optional[ParameterAttribute] = None,
    shared_biases: bool = True,
    layer_attr=None,
    filter_size_y: Optional[int] = None,
    stride_y: Optional[int] = None,
    padding_y: Optional[int] = None,
) -> LayerOutput:
    name = _name(name, "conv")
    if num_channels is None:
        num_channels = input.num_filters if hasattr(input, "num_filters") and input.num_filters else 1
        if getattr(input, "num_filters", None) is None and input.size is not None:
            # infer: input is a square image with unknown channels = 1
            pass
    img_size = int(round(math.sqrt(input.size / num_channels)))
    assert img_size * img_size * num_channels == input.size, (
        f"img_conv_layer {name}: input size {input.size} does not factor into "
        f"{num_channels} x {img_size}^2"
    )
    out_x = _conv_out(img_size, filter_size, padding, stride)
    filter_channels = num_channels // groups
    cc = ConvConfig(
        filter_size=filter_size,
        channels=num_channels,
        stride=stride,
        padding=padding,
        groups=groups,
        filter_channels=filter_channels,
        output_x=out_x,
        img_size=img_size,
        filter_size_y=filter_size_y or filter_size,
        stride_y=stride_y or stride,
        padding_y=padding_y if padding_y is not None else padding,
    )
    cfg = LayerConfig(
        name=name,
        type="exconv",
        size=out_x * out_x * num_filters,
        active_type=_act_name(act or ReluActivation()),
        num_filters=num_filters,
        shared_biases=shared_biases,
    )
    fy = filter_size_y or filter_size
    wsize = num_filters * filter_channels * filter_size * fy
    pname = _create_parameter(
        f"_{name}.w0", wsize, [num_filters, filter_channels * filter_size * fy], param_attr
    )
    cfg.inputs.append(LayerInputConfig(input_layer_name=input.name, input_parameter_name=pname, conv_conf=cc))
    bias_size = num_filters if shared_biases else cfg.size
    cfg.bias_parameter_name = _bias_name(name, bias_size, bias_attr)
    _add_layer(cfg, layer_attr)
    out = LayerOutput(name, "exconv", [input], cfg.size, act)
    out.num_filters = num_filters
    out.img_size = out_x
    return out


def img_pool_layer(
    input: LayerOutput,
    pool_size: int,
    name: Optional[str] = None,
    num_channels: Optional[int] = None,
    pool_type: Optional[BasePoolingType] = None,
    stride: int = 1,
    start: int = 0,
    padding: int = 0,
    layer_attr=None,
    pool_size_y: Optional[int] = None,
    stride_y: Optional[int] = None,
    padding_y: Optional[int] = None,
) -> LayerOutput:
    name = _name(name, "pool")
    if num_channels is None:
        num_channels = getattr(input, "num_filters", None) or 1
    img_size = getattr(input, "img_size", None) or int(round(math.sqrt(input.size / num_channels)))
    pool_type = pool_type or MaxPooling()
    type_name = ("max" if pool_type.name == "max" else "avg") + "-projection"
    out_x = _conv_out(img_size, pool_size, padding, stride, caffe_mode=False)
    pc = PoolConfig(
        pool_type=type_name,
        channels=num_channels,
        size_x=pool_size,
        start=start,
        stride=stride,
        output_x=out_x,
        img_size=img_size,
        padding=padding,
        size_y=pool_size_y or pool_size,
        stride_y=stride_y or stride,
        padding_y=padding_y if padding_y is not None else padding,
        output_y=out_x,
        img_size_y=img_size,
    )
    cfg = LayerConfig(name=name, type="pool", size=out_x * out_x * num_channels)
    cfg.inputs.append(LayerInputConfig(input_layer_name=input.name, pool_conf=pc))
    _add_layer(cfg, layer_attr)
    out = LayerOutput(name, "pool", [input], cfg.size)
    out.num_filters = num_channels
    out.img_size = out_x
    return out


def img_cmrnorm_layer(
    input: LayerOutput,
    size: int,
    scale: float = 0.0128,
    power: float = 0.75,
    name: Optional[str] = None,
    num_channels: Optional[int] = None,
    layer_attr=None,
) -> LayerOutput:
    name = _name(name, "norm")
    if num_channels is None:
        num_channels = getattr(input, "num_filters", None) or 1
    img_size = getattr(input, "img_size", None) or int(round(math.sqrt(input.size / num_channels)))
    nc = NormConfig(
        norm_type="cmrnorm-projection",
        channels=num_channels,
        size=size,
        # the stored value is scale/size (reference config_parser.py
        # divides before writing the proto; the kernel uses it directly)
        scale=scale / size,
        pow=power,
        output_x=img_size,
        img_size=img_size,
    )
    cfg = LayerConfig(name=name, type="norm", size=input.size)
    cfg.inputs.append(LayerInputConfig(input_layer_name=input.name, norm_conf=nc))
    _add_layer(cfg, layer_attr)
    out = LayerOutput(name, "norm", [input], input.size)
    out.num_filters = num_channels
    out.img_size = img_size
    return out


def batch_norm_layer(
    input: LayerOutput,
    act: Optional[BaseActivation] = None,
    name: Optional[str] = None,
    num_channels: Optional[int] = None,
    bias_attr: Union[bool, ParameterAttribute] = True,
    param_attr: Optional[ParameterAttribute] = None,
    layer_attr=None,
    batch_norm_type: Optional[str] = None,
    moving_average_fraction: float = 0.9,
    use_global_stats: Optional[bool] = None,
) -> LayerOutput:
    name = _name(name, "batch_norm")
    if num_channels is None:
        num_channels = getattr(input, "num_filters", None) or input.size
    img_size = getattr(input, "img_size", None) or (
        int(round(math.sqrt(input.size / num_channels))) if input.size != num_channels else 0
    )
    ic = ImageConfig(channels=num_channels, img_size=img_size or 0)
    cfg = LayerConfig(
        name=name,
        type="batch_norm",
        size=input.size,
        active_type=_act_name(act or ReluActivation()),
        moving_average_fraction=moving_average_fraction,
        use_global_stats=bool(use_global_stats) if use_global_stats is not None else False,
    )
    gamma = _create_parameter(
        f"_{name}.w0",
        num_channels,
        [1, num_channels],
        param_attr or ParameterAttribute(initial_mean=1.0, initial_std=0.0),
    )
    cfg.inputs.append(LayerInputConfig(input_layer_name=input.name, input_parameter_name=gamma, image_conf=ic))
    # moving mean / variance: static state parameters
    mean_p = _create_parameter(
        f"_{name}.w1", num_channels, [1, num_channels],
        ParameterAttribute(initial_mean=0.0, initial_std=0.0, is_static=True),
    )
    var_p = _create_parameter(
        f"_{name}.w2", num_channels, [1, num_channels],
        ParameterAttribute(initial_mean=1.0, initial_std=0.0, is_static=True),
    )
    cfg.inputs.append(LayerInputConfig(input_parameter_name=mean_p))
    cfg.inputs.append(LayerInputConfig(input_parameter_name=var_p))
    cfg.bias_parameter_name = _bias_name(name, num_channels, bias_attr)
    _add_layer(cfg, layer_attr)
    out = LayerOutput(name, "batch_norm", [input], input.size, act)
    out.num_filters = num_channels if img_size else None
    out.img_size = img_size or None
    return out


def sum_to_one_norm_layer(input: LayerOutput, name=None, layer_attr=None):
    name = _name(name, "sum_to_one_norm")
    cfg = LayerConfig(name=name, type="sum_to_one_norm", size=input.size)
    cfg.inputs.append(_input(input))
    _add_layer(cfg, layer_attr)
    return LayerOutput(name, "sum_to_one_norm", [input], input.size)


def addto_layer(
    input: Union[LayerOutput, Sequence[LayerOutput]],
    act: Optional[BaseActivation] = None,
    name: Optional[str] = None,
    bias_attr: Union[bool, ParameterAttribute] = False,
    layer_attr=None,
) -> LayerOutput:
    name = _name(name, "addto")
    inputs = _to_list(input)
    cfg = LayerConfig(
        name=name, type="addto", size=inputs[0].size, active_type=_act_name(act or IdentityActivation())
    )
    for inp in inputs:
        cfg.inputs.append(_input(inp))
    cfg.bias_parameter_name = _bias_name(name, inputs[0].size, bias_attr)
    _add_layer(cfg, layer_attr)
    out = LayerOutput(name, "addto", inputs, inputs[0].size, act)
    out.num_filters = getattr(inputs[0], "num_filters", None)
    out.img_size = getattr(inputs[0], "img_size", None)
    return out


def concat_layer(
    input: Sequence[LayerOutput],
    act: Optional[BaseActivation] = None,
    name: Optional[str] = None,
    layer_attr=None,
) -> LayerOutput:
    name = _name(name, "concat")
    inputs = _to_list(input)
    if any(isinstance(i, _Projection) for i in inputs):
        # projections in the list -> concat2 (reference ConcatenateLayer2:
        # project each input, concatenate the projection outputs)
        assert all(isinstance(i, _Projection) for i in inputs), (
            "concat_layer: mix of projections and layers is not supported — "
            "wrap plain layers in identity_projection()"
        )
        def _c2_size(p):
            # per-projection output width; identity falls back to the
            # input width, identity_offset to the remaining slice, and
            # context to in_size * context_length (output_size helper)
            if p.type == "identity_offset":
                off = p.extra.get("offset", 0)
                assert 0 <= off < p.input.size, (
                    f"identity_projection offset {off} out of range for "
                    f"input of size {p.input.size}"
                )
                return p.size or (p.input.size - off)
            return p.output_size(p.input.size)

        sizes = [_c2_size(p) for p in inputs]
        size = sum(sizes)
        cfg = LayerConfig(
            name=name, type="concat2", size=size,
            active_type=_act_name(act or IdentityActivation()),
        )
        for idx, (p, out_size) in enumerate(zip(inputs, sizes)):
            cfg.inputs.append(p.materialize(name, out_size, idx))
        _add_layer(cfg, layer_attr)
        return LayerOutput(name, "concat2", [p.input for p in inputs], size, act)
    size = sum(i.size for i in inputs)
    cfg = LayerConfig(
        name=name, type="concat", size=size, active_type=_act_name(act or IdentityActivation())
    )
    for inp in inputs:
        cfg.inputs.append(_input(inp))
    _add_layer(cfg, layer_attr)
    return LayerOutput(name, "concat", inputs, size, act)


def dropout_layer(input: LayerOutput, dropout_rate: float, name=None) -> LayerOutput:
    return addto_layer(
        input=input,
        name=_name(name, "dropout"),
        act=IdentityActivation(),
        bias_attr=False,
        layer_attr=ExtraLayerAttribute(drop_rate=dropout_rate),
    )


# --------------------------------------------------- recurrent group DSL


def memory(
    name: str,
    size: int,
    is_seq: bool = False,
    boot_layer: Optional[LayerOutput] = None,
    boot_bias: Union[bool, ParameterAttribute, None] = None,
    boot_bias_active_type: Optional[BaseActivation] = None,
    boot_with_const_id: Optional[int] = None,
) -> LayerOutput:
    """Declare a recurrence edge: reads layer ``name``'s output from the
    previous timestep (reference: layers.py memory:1853)."""
    ctx = _ctx()
    assert ctx.in_recurrent_group, "memory() must be called inside a recurrent_group step"
    sub = ctx.current_submodel()
    agent_name = f"{name}@{sub.name}@memory"
    agent_cfg = LayerConfig(name=agent_name, type="agent", size=size)
    ctx.add_layer(agent_cfg)
    mem = MemoryConfig(layer_name=name, link_name=agent_name)
    if boot_layer is not None:
        mem.boot_layer_name = boot_layer.name
    if isinstance(boot_bias, ParameterAttribute) or boot_bias is True:
        attr = boot_bias if isinstance(boot_bias, ParameterAttribute) else None
        mem.boot_bias_parameter_name = _create_parameter(
            f"_{agent_name}.wbias", size, [1, size], attr, is_bias=True
        )
        mem.boot_bias_active_type = _act_name(boot_bias_active_type)
    if boot_with_const_id is not None:
        mem.boot_with_const_id = boot_with_const_id
    mem.is_sequence = is_seq
    sub.memories.append(mem)
    return LayerOutput(agent_name, "agent", [], size)



def _subseq_inlink_proxy(ctx, sub, outer, group_name):
    """Emit the nested in-link triple (sequence_scatter_agent layer,
    has_subseq LinkConfig, step proxy) shared by recurrent_group and
    beam_search."""
    agent_name = f"{outer.name}@{group_name}"
    ctx.add_layer(
        LayerConfig(name=agent_name, type="sequence_scatter_agent", size=outer.size)
    )
    sub.in_links.append(
        LinkConfig(layer_name=outer.name, link_name=agent_name, has_subseq=True)
    )
    return LayerOutput(agent_name, "sequence_scatter_agent", [outer], outer.size)


def recurrent_group(
    step: Callable,
    input,
    reverse: bool = False,
    name: Optional[str] = None,
) -> Union[LayerOutput, List[LayerOutput]]:
    """Build a recurrent sub-model from a per-timestep ``step`` function
    (reference: layers.py recurrent_group:2141). Sequence inputs are
    scattered per timestep; StaticInput passes whole; memory() edges carry
    state between steps."""
    ctx = _ctx()
    name = _name(name, "recurrent_group")
    inputs = _to_list(input)
    sub = ctx.begin_submodel(name)
    sub.reversed = reverse
    proxies: List[LayerOutput] = []
    for item in inputs:
        if isinstance(item, GeneratedInput):
            raise ValueError(
                "GeneratedInput is only valid with beam_search(); use "
                "beam_search(step=..., input=[...]) for generation groups"
            )
        if isinstance(item, SubsequenceInput):
            proxies.append(_subseq_inlink_proxy(ctx, sub, item.input, name))
        elif isinstance(item, StaticInput):
            outer = item.input
            agent_name = f"{outer.name}@{name}"
            ltype = "sequence_agent" if item.is_seq else "agent"
            ctx.add_layer(LayerConfig(name=agent_name, type=ltype, size=item.size))
            sub.static_links.append(LinkConfig(layer_name=outer.name, link_name=agent_name, has_subseq=item.is_seq))
            proxies.append(LayerOutput(agent_name, ltype, [outer], item.size))
        else:
            outer = item
            agent_name = f"{outer.name}@{name}"
            ctx.add_layer(LayerConfig(name=agent_name, type="scatter_agent", size=outer.size))
            sub.in_links.append(LinkConfig(layer_name=outer.name, link_name=agent_name))
            proxies.append(LayerOutput(agent_name, "scatter_agent", [outer], outer.size))
    outs = step(*proxies)
    out_list = _to_list(outs)
    for o in out_list:
        sub.out_links.append(LinkConfig(layer_name=o.name, link_name=o.name))
    ctx.end_submodel()
    # the parent-scope group layer that triggers sub-model execution
    group_cfg = LayerConfig(name=name, type="recurrent_layer_group", size=out_list[0].size)
    for item in inputs:
        outer = item.input if isinstance(item, (StaticInput, SubsequenceInput)) else item
        group_cfg.inputs.append(LayerInputConfig(input_layer_name=outer.name))
    for m in sub.memories:
        if m.boot_layer_name:
            group_cfg.inputs.append(LayerInputConfig(input_layer_name=m.boot_layer_name))
    ctx.add_layer(group_cfg)
    return outs


def lstm_step_layer(
    input: LayerOutput,
    state: LayerOutput,
    size: int,
    act: Optional[BaseActivation] = None,
    name: Optional[str] = None,
    gate_act: Optional[BaseActivation] = None,
    state_act: Optional[BaseActivation] = None,
    bias_attr: Union[bool, ParameterAttribute] = True,
    layer_attr=None,
) -> LayerOutput:
    name = _name(name, "lstm_step")
    cfg = LayerConfig(
        name=name,
        type="lstm_step",
        size=size,
        active_type=_act_name(act or TanhActivation()),
        active_gate_type=_act_name(gate_act or SigmoidActivation()),
        active_state_type=_act_name(state_act or TanhActivation()),
    )
    cfg.inputs.append(_input(input))
    cfg.inputs.append(_input(state))
    if bias_attr is not False and bias_attr is not None:
        attr = bias_attr if isinstance(bias_attr, ParameterAttribute) else None
        bname = attr.name if attr and attr.name else f"_{name}.wbias"
        if bname not in _ctx().param_map:
            bname = _create_parameter(bname, 7 * size, [1, 7 * size], attr, is_bias=True)
        cfg.bias_parameter_name = bname
    _add_layer(cfg, layer_attr)
    out = LayerOutput(name, "lstm_step", [input, state], size, act, outputs=["default", "state"])
    return out


def gru_step_layer(
    input: LayerOutput,
    output_mem: LayerOutput,
    size: Optional[int] = None,
    act: Optional[BaseActivation] = None,
    name: Optional[str] = None,
    gate_act: Optional[BaseActivation] = None,
    bias_attr: Union[bool, ParameterAttribute] = True,
    param_attr: Optional[ParameterAttribute] = None,
    layer_attr=None,
) -> LayerOutput:
    size = size or input.size // 3
    name = _name(name, "gru_step")
    cfg = LayerConfig(
        name=name,
        type="gru_step",
        size=size,
        active_type=_act_name(act or TanhActivation()),
        active_gate_type=_act_name(gate_act or SigmoidActivation()),
    )
    pname = _create_parameter(f"_{name}.w0", size * size * 3, [size, 3 * size], param_attr)
    cfg.inputs.append(_input(input, pname))
    cfg.inputs.append(_input(output_mem))
    cfg.bias_parameter_name = _bias_name(name, 3 * size, bias_attr)
    _add_layer(cfg, layer_attr)
    return LayerOutput(name, "gru_step", [input, output_mem], size, act)


def get_output_layer(input: LayerOutput, arg_name: str, name=None, layer_attr=None) -> LayerOutput:
    name = _name(name, "get_output")
    cfg = LayerConfig(name=name, type="get_output", size=input.size)
    cfg.inputs.append(
        LayerInputConfig(input_layer_name=input.name, input_layer_argument=arg_name)
    )
    _add_layer(cfg, layer_attr)
    return LayerOutput(name, "get_output", [input], input.size)


def maxid_layer(input: LayerOutput, name=None, layer_attr=None) -> LayerOutput:
    name = _name(name, "maxid")
    cfg = LayerConfig(name=name, type="maxid", size=1)
    cfg.inputs.append(_input(input))
    _add_layer(cfg, layer_attr)
    return LayerOutput(name, "maxid", [input], 1)


def eos_layer(input: LayerOutput, eos_id: int, name=None, layer_attr=None) -> LayerOutput:
    name = _name(name, "eos")
    cfg = LayerConfig(name=name, type="eos_id", size=1, eos_id=eos_id)
    cfg.inputs.append(_input(input))
    _add_layer(cfg, layer_attr)
    return LayerOutput(name, "eos_id", [input], 1)


def beam_search(
    step: Callable,
    input,
    bos_id: int,
    eos_id: int,
    beam_size: int,
    max_length: int = 500,
    name: Optional[str] = None,
    num_results_per_sample: Optional[int] = None,
    id_input=None,
    dict_file: Optional[str] = None,
    result_file: Optional[str] = None,
) -> LayerOutput:
    """Configure beam-search generation over a recurrent step function
    (reference: layers.py beam_search:2363). The GeneratedInput in
    ``input`` names the embedding used to feed back generated tokens."""
    ctx = _ctx()
    name = _name(name, "beam_search")
    num_results_per_sample = num_results_per_sample or beam_size
    inputs = _to_list(input)
    gen: Optional[GeneratedInput] = None
    real_inputs = []
    gen_pos = 0
    for i, item in enumerate(inputs):
        if isinstance(item, GeneratedInput):
            assert gen is None, "only one GeneratedInput allowed"
            gen = item
            gen_pos = i
        else:
            real_inputs.append(item)
    assert gen is not None, "beam_search needs a GeneratedInput"

    sub = ctx.begin_submodel(name)
    proxies = []
    for item in real_inputs:
        outer = item.input if isinstance(item, (StaticInput, SubsequenceInput)) else item
        agent_name = f"{outer.name}@{name}"
        if isinstance(item, StaticInput):
            ltype = "sequence_agent" if item.is_seq else "agent"
            ctx.add_layer(LayerConfig(name=agent_name, type=ltype, size=item.size))
            sub.static_links.append(
                LinkConfig(layer_name=outer.name, link_name=agent_name, has_subseq=item.is_seq)
            )
            proxies.append(LayerOutput(agent_name, ltype, [outer], item.size))
        elif isinstance(item, SubsequenceInput):
            # nested in-link: each generated step consumes one whole
            # subsequence (the step sees it as a flat sequence)
            proxies.append(_subseq_inlink_proxy(ctx, sub, outer, name))
        else:
            ctx.add_layer(LayerConfig(name=agent_name, type="scatter_agent", size=outer.size))
            sub.in_links.append(LinkConfig(layer_name=outer.name, link_name=agent_name))
            proxies.append(LayerOutput(agent_name, "scatter_agent", [outer], outer.size))
    # the predecessor-token embedding: a table projection over the ids
    # generated at the previous step, fed through the shared embedding.
    predict_id_name = f"__generated_id@{name}"
    ctx.add_layer(LayerConfig(name=predict_id_name, type="agent", size=1))
    emb = mixed_layer(
        size=gen.embedding_size,
        input=[
            table_projection(
                LayerOutput(predict_id_name, "agent", [], gen.size),
                gen.embedding_size,
                ParameterAttribute(name=gen.embedding_name),
            )
        ],
        name=f"__generated_emb@{name}",
        bias_attr=False,
    )
    proxies.insert(gen_pos, emb)
    outs = step(*proxies)
    out = outs if isinstance(outs, LayerOutput) else outs[0]
    sub.out_links.append(LinkConfig(layer_name=out.name, link_name=out.name))
    sub.generator = GeneratorConfig(
        max_num_frames=max_length,
        eos_layer_name="",
        num_results_per_sample=num_results_per_sample,
        beam_size=beam_size,
        result_file=result_file or "",
        dict_file=dict_file or "",
        id_input_layer=id_input.name if id_input is not None else "",
    )
    # record bos/eos on the scoring layer config for the executor
    score_cfg = ctx.get_layer(out.name)
    score_cfg.bos_id = bos_id
    score_cfg.eos_id = eos_id
    ctx.end_submodel()
    group_cfg = LayerConfig(
        name=name, type="recurrent_layer_group", size=out.size, bos_id=bos_id, eos_id=eos_id,
        beam_size=beam_size,
    )
    for item in real_inputs:
        outer = item.input if isinstance(item, (StaticInput, SubsequenceInput)) else item
        group_cfg.inputs.append(LayerInputConfig(input_layer_name=outer.name))
    for m in sub.memories:
        if m.boot_layer_name:
            group_cfg.inputs.append(LayerInputConfig(input_layer_name=m.boot_layer_name))
    ctx.add_layer(group_cfg)
    result = LayerOutput(name, "recurrent_layer_group", real_inputs, out.size)
    _ctx().mark_output(name)
    return result


# ------------------------------------------------------------------ costs


def _cost_layer(
    cost_type: str,
    name: str,
    inputs: List[LayerOutput],
    coeff: float = 1.0,
    **cfg_kw,
) -> LayerOutput:
    cfg = LayerConfig(name=name, type=cost_type, size=1, coeff=coeff, **cfg_kw)
    for inp in inputs:
        cfg.inputs.append(_input(inp))
    _add_layer(cfg)
    out = LayerOutput(name, cost_type, inputs, 1)
    _ctx().mark_output(name)
    return out


def regression_cost(input: LayerOutput, label: LayerOutput, cost: str = "square_error", name=None):
    return _cost_layer(cost, _name(name, "cost"), [input, label])


def classification_cost(
    input: LayerOutput,
    label: LayerOutput,
    name: Optional[str] = None,
    cost: str = "multi-class-cross-entropy",
    evaluator=None,
    coeff: float = 1.0,
) -> LayerOutput:
    name = _name(name, "cost")
    out = _cost_layer(cost, name, [input, label], coeff=coeff)
    # default classification-error evaluator (reference behavior)
    from paddle_tpu.trainer_config_helpers.evaluators import classification_error_evaluator

    if evaluator is None:
        evaluator = classification_error_evaluator
    evaluator(input=input, label=label, name=f"{name}.classification_error")
    return out


def auc_validation(input, label, weight=None, name=None, coeff=1.0):
    """AUC validation layer (ref: AucValidation,
    paddle/gserver/layers/ValidationLayer.h:52, registered cost type
    'auc-validation', config_parser.py:1703): a zero-gradient cost-family
    node; its AUC accumulates in the evaluator runtime and reports at
    every log period and pass end."""
    name = _name(name, "auc_validation")
    inputs = [input, label] + ([weight] if weight is not None else [])
    out = _cost_layer("auc-validation", name, inputs, coeff=coeff)
    from paddle_tpu.trainer_config_helpers.evaluators import evaluator_base

    evaluator_base("last-column-auc", [input, label], weight=weight,
                   name=f"{name}.auc")
    return out


def pnpair_validation(input, label, info, weight=None, name=None, coeff=1.0):
    """Positive-negative pair validation layer (ref: PnpairValidation,
    paddle/gserver/layers/ValidationLayer.h:84, cost type
    'pnpair-validation', config_parser.py:1704): info carries the query id
    grouping; pair ordering accuracy reports via the evaluator runtime."""
    name = _name(name, "pnpair_validation")
    inputs = [input, label, info] + ([weight] if weight is not None else [])
    out = _cost_layer("pnpair-validation", name, inputs, coeff=coeff)
    from paddle_tpu.trainer_config_helpers.evaluators import evaluator_base

    evaluator_base("pnpair", [input, label, info], weight=weight,
                   name=f"{name}.pnpair")
    return out


def cross_entropy(input, label, name=None, coeff=1.0):
    return _cost_layer("multi-class-cross-entropy", _name(name, "cost"), [input, label], coeff)


def cross_entropy_with_selfnorm(input, label, name=None, coeff=1.0, softmax_selfnorm_alpha=0.1):
    return _cost_layer(
        "multi_class_cross_entropy_with_selfnorm",
        _name(name, "cost"),
        [input, label],
        coeff,
        softmax_selfnorm_alpha=softmax_selfnorm_alpha,
    )


def huber_cost(input, label, name=None, coeff=1.0):
    return _cost_layer("huber", _name(name, "cost"), [input, label], coeff)


def multi_binary_label_cross_entropy(input, label, name=None, coeff=1.0):
    return _cost_layer("multi_binary_label_cross_entropy", _name(name, "cost"), [input, label], coeff)


def rank_cost(left, right, lable=None, label=None, weight=None, name=None, coeff=1.0):
    # (the reference misspells the arg as `lable`; accept both)
    lab = label if label is not None else lable
    ins = [left, right, lab] + ([weight] if weight is not None else [])
    return _cost_layer("rank-cost", _name(name, "cost"), ins, coeff)


def lambda_cost(input, score, NDCG_num=5, max_sort_size=-1, coeff=1.0, name=None):
    return _cost_layer(
        "lambda_cost",
        _name(name, "cost"),
        [input, score],
        coeff,
        NDCG_num=NDCG_num,
        max_sort_size=max_sort_size,
    )


def ctc_layer(input, label, size, name=None, norm_by_times=False):
    name = _name(name, "ctc")
    cfg = LayerConfig(name=name, type="ctc", size=size, norm_by_times=norm_by_times)
    cfg.inputs.append(_input(input))
    cfg.inputs.append(_input(label))
    _add_layer(cfg)
    out = LayerOutput(name, "ctc", [input, label], size)
    _ctx().mark_output(name)
    return out


def crf_layer(input, label, size=None, weight=None, param_attr=None, name=None):
    size = size or input.size
    name = _name(name, "crf")
    cfg = LayerConfig(name=name, type="crf", size=size)
    pname = _create_parameter(f"_{name}.w0", (size + 2) * size, [size + 2, size], param_attr)
    cfg.inputs.append(_input(input, pname))
    cfg.inputs.append(_input(label))
    if weight is not None:
        cfg.inputs.append(_input(weight))
    _add_layer(cfg)
    out = LayerOutput(name, "crf", [input, label], size)
    _ctx().mark_output(name)
    return out


def crf_decoding_layer(input, size=None, label=None, param_attr=None, name=None):
    size = size or input.size
    name = _name(name, "crf_decoding")
    cfg = LayerConfig(name=name, type="crf_decoding", size=size)
    pname = _create_parameter(f"_{name}.w0", (size + 2) * size, [size + 2, size], param_attr)
    cfg.inputs.append(_input(input, pname))
    if label is not None:
        cfg.inputs.append(_input(label))
    _add_layer(cfg)
    return LayerOutput(name, "crf_decoding", [input], size)


def nce_layer(
    input,
    label,
    num_classes,
    weight=None,
    num_neg_samples=10,
    neg_distribution=None,
    name=None,
    bias_attr=True,
    param_attr=None,
):
    name = _name(name, "nce")
    inputs = _to_list(input)
    attrs = param_attr if isinstance(param_attr, (list, tuple)) else [param_attr] * len(inputs)
    cfg = LayerConfig(
        name=name, type="nce", size=1, num_classes=num_classes, num_neg_samples=num_neg_samples
    )
    if neg_distribution is not None:
        cfg.neg_sampling_dist = list(neg_distribution)
    for i, (inp, attr) in enumerate(zip(inputs, attrs)):
        pname = _create_parameter(
            f"_{name}.w{i}", num_classes * inp.size, [num_classes, inp.size], attr
        )
        cfg.inputs.append(_input(inp, pname))
    cfg.inputs.append(_input(label))
    if weight is not None:
        cfg.inputs.append(_input(weight))
    cfg.bias_parameter_name = _bias_name(name, num_classes, bias_attr)
    _add_layer(cfg)
    out = LayerOutput(name, "nce", inputs + [label], 1)
    _ctx().mark_output(name)
    return out


# ----------------------------------------------------------- other layers


def conv_shift_layer(input: Sequence[LayerOutput], name=None):
    a, b = input[0], input[1]
    name = _name(name, "conv_shift")
    cfg = LayerConfig(name=name, type="conv_shift", size=a.size)
    cfg.inputs.append(_input(a))
    cfg.inputs.append(_input(b))
    _add_layer(cfg)
    return LayerOutput(name, "conv_shift", [a, b], a.size)


def tensor_layer(
    input: Sequence[LayerOutput],
    size: int,
    act=None,
    name=None,
    param_attr=None,
    bias_attr=True,
    layer_attr=None,
) -> LayerOutput:
    a, b = input[0], input[1]
    name = _name(name, "tensor")
    cfg = LayerConfig(name=name, type="tensor", size=size, active_type=_act_name(act or TanhActivation()))
    pname = _create_parameter(
        f"_{name}.w0", a.size * size * b.size, [a.size, size * b.size], param_attr
    )
    cfg.inputs.append(_input(a, pname))
    cfg.inputs.append(_input(b))
    cfg.bias_parameter_name = _bias_name(name, size, bias_attr)
    _add_layer(cfg, layer_attr)
    return LayerOutput(name, "tensor", [a, b], size, act)


def selective_fc_layer(
    input,
    size,
    select=None,
    act=None,
    name=None,
    pass_generation=False,
    has_selected_colums=True,
    mul_ratio=0.02,
    param_attr=None,
    bias_attr=True,
    layer_attr=None,
) -> LayerOutput:
    name = _name(name, "selective_fc")
    inputs = _to_list(input)
    attrs = param_attr if isinstance(param_attr, (list, tuple)) else [param_attr] * len(inputs)
    cfg = LayerConfig(
        name=name,
        type="selective_fc",
        size=size,
        active_type=_act_name(act or TanhActivation()),
        selective_fc_pass_generation=pass_generation,
        has_selected_colums=has_selected_colums,
        selective_fc_full_mul_ratio=mul_ratio,
    )
    for i, (inp, attr) in enumerate(zip(inputs, attrs)):
        pname = _create_parameter(f"_{name}.w{i}", inp.size * size, [inp.size, size], attr)
        cfg.inputs.append(_input(inp, pname))
    if select is not None:
        cfg.inputs.append(_input(select))
    cfg.bias_parameter_name = _bias_name(name, size, bias_attr)
    _add_layer(cfg, layer_attr)
    return LayerOutput(name, "selective_fc", inputs, size, act)


def sampling_id_layer(input: LayerOutput, name=None) -> LayerOutput:
    name = _name(name, "sampling_id")
    cfg = LayerConfig(name=name, type="sampling_id", size=1)
    cfg.inputs.append(_input(input))
    _add_layer(cfg)
    return LayerOutput(name, "sampling_id", [input], 1)


def slope_intercept_layer(input: LayerOutput, name=None, slope=1.0, intercept=0.0) -> LayerOutput:
    name = _name(name, "slope_intercept")
    cfg = LayerConfig(name=name, type="slope_intercept", size=input.size, slope=slope, intercept=intercept)
    cfg.inputs.append(_input(input))
    _add_layer(cfg)
    return LayerOutput(name, "slope_intercept", [input], input.size)


def convex_comb_layer(input: Sequence[LayerOutput], size: int, name=None) -> LayerOutput:
    w, v = input[0], input[1]
    name = _name(name, "convex_comb")
    cfg = LayerConfig(name=name, type="convex_comb", size=size)
    cfg.inputs.append(_input(w))
    cfg.inputs.append(_input(v))
    _add_layer(cfg)
    return LayerOutput(name, "convex_comb", [w, v], size)


def block_expand_layer(
    input: LayerOutput,
    channel: int = 0,
    block_x: int = 0,
    block_y: int = 0,
    stride_x: int = 0,
    stride_y: int = 0,
    padding_x: int = 0,
    padding_y: int = 0,
    name=None,
) -> LayerOutput:
    name = _name(name, "blockexpand")
    img_x = getattr(input, "img_size", None) or int(round(math.sqrt(input.size / channel)))
    out_x = (img_x + 2 * padding_x - block_x + stride_x - 1) // stride_x + 1
    out_y = (img_x + 2 * padding_y - block_y + stride_y - 1) // stride_y + 1
    bc = BlockExpandConfig(
        channels=channel,
        stride_x=stride_x,
        stride_y=stride_y,
        padding_x=padding_x,
        padding_y=padding_y,
        block_x=block_x,
        block_y=block_y,
        output_x=out_x,
        output_y=out_y,
        img_size_x=img_x,
        img_size_y=img_x,
    )
    size = channel * block_x * block_y
    cfg = LayerConfig(name=name, type="blockexpand", size=size)
    cfg.inputs.append(LayerInputConfig(input_layer_name=input.name, block_expand_conf=bc))
    _add_layer(cfg)
    return LayerOutput(name, "blockexpand", [input], size)


def out_prod_layer(a: LayerOutput, b: LayerOutput, name=None) -> LayerOutput:
    name = _name(name, "out_prod")
    cfg = LayerConfig(name=name, type="out_prod", size=a.size * b.size)
    cfg.inputs.append(_input(a))
    cfg.inputs.append(_input(b))
    _add_layer(cfg)
    return LayerOutput(name, "out_prod", [a, b], a.size * b.size)


def multiplex_layer(input: Sequence[LayerOutput], name=None) -> LayerOutput:
    name = _name(name, "multiplex")
    inputs = _to_list(input)
    cfg = LayerConfig(name=name, type="multiplex", size=inputs[1].size)
    for inp in inputs:
        cfg.inputs.append(_input(inp))
    _add_layer(cfg)
    return LayerOutput(name, "multiplex", inputs, inputs[1].size)


def multi_head_attention_layer(
    input: LayerOutput,
    num_heads: int,
    size: Optional[int] = None,
    name: Optional[str] = None,
    causal: bool = False,
    seq_parallel: str = "",
    act: Optional[BaseActivation] = None,
    param_attr: Optional[ParameterAttribute] = None,
    bias_attr: Union[bool, ParameterAttribute] = False,
    layer_attr=None,
) -> LayerOutput:
    """Transformer-style multi-head self-attention over a sequence (TPU
    extension; the reference's only attention is simple_attention inside
    recurrent groups). ``seq_parallel``: "" | "ring" | "alltoall" — shard
    the context over the mesh "seq" axis (paddle_tpu.parallel.
    sequence_parallel)."""
    assert seq_parallel in ("", "ring", "alltoall"), (
        f"seq_parallel must be '', 'ring' or 'alltoall', got {seq_parallel!r}"
    )
    name = _name(name, "mha")
    size = size or input.size
    cfg = LayerConfig(
        name=name,
        type="multi_head_attention",
        size=size,
        active_type=_act_name(act or IdentityActivation()),
    )
    cfg.num_heads = num_heads
    cfg.causal_attention = causal
    cfg.seq_parallel_mode = seq_parallel
    wqkv = _create_parameter(
        f"_{name}.wqkv", input.size * 3 * size, [input.size, 3 * size], param_attr
    )
    _create_parameter(f"_{name}.wo", size * size, [size, size], param_attr)
    cfg.inputs.append(_input(input, wqkv))
    cfg.bias_parameter_name = _bias_name(name, size, bias_attr)
    _add_layer(cfg, layer_attr)
    return LayerOutput(name, "multi_head_attention", [input], size, act)


def mdlstm_layer(
    input: LayerOutput,
    size: Optional[int] = None,
    directions: Sequence[bool] = (True, True),
    name: Optional[str] = None,
    act: Optional[BaseActivation] = None,
    gate_act: Optional[BaseActivation] = None,
    state_act: Optional[BaseActivation] = None,
    param_attr: Optional[ParameterAttribute] = None,
    bias_attr: Union[bool, ParameterAttribute] = True,
    layer_attr=None,
) -> LayerOutput:
    """Multi-dimensional LSTM over a 2-D grid (ref: config_parser.py:2608
    MDLstmLayer / MDLstmLayer.cpp). ``input`` holds the precomputed
    x-projections, size (3+len(directions))*size, over a nested
    [B, H, W, ...] grid; directions[d]=False scans dim d backwards."""
    D = len(directions)
    name = _name(name, "mdlstm")
    size = size or input.size // (3 + D)
    assert input.size == (3 + D) * size, (
        f"mdlstm input size {input.size} must be (3+{D})*size (= {(3 + D) * size})"
    )
    cfg = LayerConfig(
        name=name,
        type="mdlstmemory",
        size=size,
        active_type=_act_name(act or TanhActivation()),
        active_gate_type=_act_name(gate_act or SigmoidActivation()),
        active_state_type=_act_name(state_act or SigmoidActivation()),
    )
    cfg.directions = [bool(d) for d in directions]
    pname = _create_parameter(
        f"_{name}.w0", size * size * (3 + D), [size, (3 + D) * size], param_attr
    )
    cfg.inputs.append(_input(input, pname))
    cfg.bias_parameter_name = _bias_name(name, (5 + 2 * D) * size, bias_attr)
    _add_layer(cfg, layer_attr)
    return LayerOutput(name, "mdlstmemory", [input], size, act)


class sub_network:
    """Plain (non-recurrent) sub-network — multi-task / multi_nn configs.

    TPU analog of the reference's MultiNetwork machine
    (/root/reference/paddle/gserver/gradientmachines/MultiNetwork.h:25,
    selected by ModelConfig.type == 'multi_nn'): each ``with
    sub_network("task"):`` block is an independent sub-graph with its own
    data layers and cost; all of them train jointly in ONE fused step
    (their costs sum into the total loss), replacing the reference's
    split-by-dataId argument multiplexing.
    """

    def __init__(self, name: str):
        self.name = name

    def __enter__(self):
        ctx = _ctx()
        ctx.model.type = "multi_nn"
        self.sub = ctx.begin_submodel(self.name, recurrent=False)
        return self.sub

    def __exit__(self, exc_type, exc, tb):
        _ctx().end_submodel()
        return False
