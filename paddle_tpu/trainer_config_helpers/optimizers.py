"""settings() and optimizer/regularization DSL objects.

API-compatible with /root/reference/python/paddle/trainer_config_helpers/
optimizers.py:73-338. Each optimizer maps to a learning_method name
implemented in paddle_tpu.optimizer; regularization/model-average/clipping
fold into OptimizationConfig and per-parameter defaults.
"""

from __future__ import annotations

from typing import Optional

from paddle_tpu.config.builder import current_context

__all__ = [
    "Optimizer",
    "BaseSGDOptimizer",
    "MomentumOptimizer",
    "AdamOptimizer",
    "AdamaxOptimizer",
    "AdaGradOptimizer",
    "RMSPropOptimizer",
    "DecayedAdaGradOptimizer",
    "AdaDeltaOptimizer",
    "LBFGSOptimizer",
    "OWLQNOptimizer",
    "BaseRegularization",
    "L1Regularization",
    "L2Regularization",
    "ModelAverage",
    "GradientClippingThreshold",
    "settings",
]


class Optimizer:
    def to_settings(self, s: dict, defaults: dict) -> None:
        raise NotImplementedError


class BaseSGDOptimizer(Optimizer):
    pass


class MomentumOptimizer(BaseSGDOptimizer):
    def __init__(self, momentum: float = 0.0, sparse: bool = False):
        self.momentum = momentum
        self.sparse = sparse

    def to_settings(self, s, defaults):
        s["learning_method"] = "sparse_momentum" if self.sparse else "momentum"
        defaults["momentum"] = self.momentum


class AdamOptimizer(BaseSGDOptimizer):
    def __init__(self, beta1: float = 0.9, beta2: float = 0.999, epsilon: float = 1e-8):
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def to_settings(self, s, defaults):
        s["learning_method"] = "adam"
        s["adam_beta1"] = self.beta1
        s["adam_beta2"] = self.beta2
        s["adam_epsilon"] = self.epsilon


class AdamaxOptimizer(BaseSGDOptimizer):
    def __init__(self, beta1: float = 0.9, beta2: float = 0.999):
        self.beta1, self.beta2 = beta1, beta2

    def to_settings(self, s, defaults):
        s["learning_method"] = "adamax"
        s["adam_beta1"] = self.beta1
        s["adam_beta2"] = self.beta2


class AdaGradOptimizer(BaseSGDOptimizer):
    def to_settings(self, s, defaults):
        s["learning_method"] = "adagrad"


class RMSPropOptimizer(BaseSGDOptimizer):
    def __init__(self, rho: float = 0.95, epsilon: float = 1e-6):
        self.rho, self.epsilon = rho, epsilon

    def to_settings(self, s, defaults):
        s["learning_method"] = "rmsprop"
        s["ada_rou"] = self.rho
        s["ada_epsilon"] = self.epsilon


class DecayedAdaGradOptimizer(BaseSGDOptimizer):
    def __init__(self, rho: float = 0.95, epsilon: float = 1e-6):
        self.rho, self.epsilon = rho, epsilon

    def to_settings(self, s, defaults):
        s["learning_method"] = "decayed_adagrad"
        s["ada_rou"] = self.rho
        s["ada_epsilon"] = self.epsilon


class AdaDeltaOptimizer(BaseSGDOptimizer):
    def __init__(self, rho: float = 0.95, epsilon: float = 1e-6):
        self.rho, self.epsilon = rho, epsilon

    def to_settings(self, s, defaults):
        s["learning_method"] = "adadelta"
        s["ada_rou"] = self.rho
        s["ada_epsilon"] = self.epsilon


class LBFGSOptimizer(Optimizer):
    """Whole-data L-BFGS: one line-searched quasi-Newton update per pass
    (the reference's batch-algorithm mode, Trainer::trainOnePassBatch —
    selected there by any non-SGD learning method, algorithm='owlqn').
    Hyperparameter names follow reference config_parser.py settings
    (c1/backoff/owlqn_steps/max_backoff)."""

    learning_method = "lbfgs"

    def __init__(self, history: int = 10, c1: float = 1e-4, backoff: float = 0.5,
                 max_backoff: int = 5):
        self.history, self.c1 = history, c1
        self.backoff, self.max_backoff = backoff, max_backoff

    def to_settings(self, s, defaults):
        s["algorithm"] = "owlqn"
        s["learning_method"] = self.learning_method
        s["owlqn_steps"] = self.history
        s["c1"] = self.c1
        s["backoff"] = self.backoff
        s["max_backoff"] = self.max_backoff


class OWLQNOptimizer(LBFGSOptimizer):
    """L-BFGS with L1 regularization (orthant-wise limited-memory
    quasi-Newton). Pair with L1Regularization(rate) — under
    algorithm='owlqn' the rate becomes OptimizationConfig.l1weight
    (reference optimizers.py:288 maps regularization the same way)."""

    learning_method = "owlqn"


class BaseRegularization(Optimizer):
    def to_settings(self, s, defaults):
        pass


class L2Regularization(BaseRegularization):
    def __init__(self, rate: float):
        self.rate = rate

    def to_settings(self, s, defaults):
        if s.get("algorithm") == "owlqn":
            # batch methods fold l2 into the objective (reference
            # optimizers.py:288-291 maps the rate to l2weight)
            s["l2weight"] = self.rate
            return
        # sgd path: becomes the per-parameter default decay_rate
        # (reference: default_decay_rate(rate))
        defaults["decay_rate"] = self.rate


class L1Regularization(BaseRegularization):
    def __init__(self, rate: float):
        self.rate = rate

    def to_settings(self, s, defaults):
        if s.get("algorithm") == "owlqn":
            s["l1weight"] = self.rate
            return
        defaults["decay_rate_l1"] = self.rate


class ModelAverage(Optimizer):
    def __init__(self, average_window, max_average_window=None, do_average_in_cpu=False):
        self.average_window = average_window
        self.max_average_window = max_average_window
        self.do_average_in_cpu = do_average_in_cpu

    def to_settings(self, s, defaults):
        s["average_window"] = self.average_window
        if self.max_average_window is not None:
            s["max_average_window"] = self.max_average_window
        s["do_average_in_cpu"] = self.do_average_in_cpu


class GradientClippingThreshold(Optimizer):
    def __init__(self, threshold: float):
        self.threshold = threshold

    def to_settings(self, s, defaults):
        s["gradient_clipping_threshold"] = self.threshold
        defaults["gradient_clipping_threshold"] = self.threshold


def settings(
    batch_size,
    learning_rate: float = 1e-3,
    learning_method: Optional[Optimizer] = None,
    regularization: Optional[BaseRegularization] = None,
    is_async: bool = False,
    async_lagged_grad_discard_ratio: Optional[float] = None,
    model_average: Optional[ModelAverage] = None,
    gradient_clipping_threshold: Optional[float] = None,
    learning_rate_decay_a: float = 0.0,
    learning_rate_decay_b: float = 0.0,
    learning_rate_schedule: Optional[str] = None,
    learning_rate_args: str = "",
    # TPU extensions
    dtype: Optional[str] = None,
    mesh_shape: Optional[str] = None,
    remat: Optional[str] = None,
    scan_unroll: Optional[int] = None,
    num_batches_per_send_parameter: Optional[int] = None,
    batches_per_launch: Optional[int] = None,
    pallas_rnn: Optional[bool] = None,
    pallas_flat: Optional[bool] = None,
    conv_s2d: Optional[bool] = None,
    conv_stats_mode: Optional[str] = None,
    pallas_decoder: Optional[bool] = None,
):
    ctx = current_context()
    s, defaults = ctx.settings, ctx.defaults
    s["batch_size"] = batch_size
    s["learning_rate"] = learning_rate
    if learning_method is None:
        learning_method = MomentumOptimizer()
    assert isinstance(learning_method, Optimizer)
    s["algorithm"] = "async_sgd" if is_async else "sgd"
    if async_lagged_grad_discard_ratio is not None:
        # async mode's staleness gate (here: replica drift gate at the
        # merge — paddle_tpu/parallel/local_sgd.py)
        s["async_lagged_grad_discard_ratio"] = async_lagged_grad_discard_ratio
    learning_method.to_settings(s, defaults)
    if regularization is not None:
        regs = regularization if isinstance(regularization, (list, tuple)) else [regularization]
        for r in regs:
            r.to_settings(s, defaults)
    if model_average is not None:
        model_average.to_settings(s, defaults)
    if gradient_clipping_threshold is not None:
        GradientClippingThreshold(gradient_clipping_threshold).to_settings(s, defaults)
    s["learning_rate_decay_a"] = learning_rate_decay_a
    s["learning_rate_decay_b"] = learning_rate_decay_b
    if learning_rate_schedule is not None:
        s["learning_rate_schedule"] = learning_rate_schedule
    if learning_rate_args:
        s["learning_rate_args"] = learning_rate_args
    if dtype is not None:
        s["dtype"] = dtype
    if remat is not None:
        s["remat"] = remat
    if scan_unroll is not None:
        s["scan_unroll"] = scan_unroll
    if batches_per_launch is not None:
        s["batches_per_launch"] = batches_per_launch
    if pallas_rnn is not None:
        s["pallas_rnn"] = pallas_rnn
    if pallas_flat is not None:
        # transpose-free pallas_rnn interface (batch-major [B, T*width]
        # reads instead of a materialized time-major swap)
        s["pallas_flat"] = pallas_flat
    if conv_s2d is not None:
        s["conv_s2d"] = conv_s2d
    if conv_stats_mode is not None:
        # fused 1x1-conv + BN statistics: "gram" | "pallas" | ""
        s["conv_stats_mode"] = conv_stats_mode
    if pallas_decoder is not None:
        s["pallas_decoder"] = pallas_decoder
    if num_batches_per_send_parameter is not None:
        # gradient accumulation: N batches per optimizer update
        s["num_batches_per_send_parameter"] = num_batches_per_send_parameter
    if mesh_shape is not None:
        s["mesh_shape"] = mesh_shape


# ------------------------------------------------- global init defaults
# (reference config_parser.py:55-60: default_initial_std / default_initial_mean
#  / default_initial_strategy / default_initial_smart / default_decay_rate /
#  default_momentum set g_default_* consumed by every later Parameter())


def _set_default(key, val):
    from paddle_tpu.config.builder import current_context

    current_context().defaults[key] = val


def default_initial_std(val: float) -> None:
    _set_default("initial_std", val)


def default_initial_mean(val: float) -> None:
    _set_default("initial_mean", val)


def default_initial_strategy(val: int) -> None:
    _set_default("initial_strategy", val)


def default_initial_smart(val: bool) -> None:
    _set_default("initial_smart", val)


def default_decay_rate(val: float) -> None:
    _set_default("decay_rate", val)


def default_momentum(val: float) -> None:
    _set_default("momentum", val)


def default_gradient_clipping_threshold(val: float) -> None:
    _set_default("gradient_clipping_threshold", val)


__all__ += [
    "default_initial_std",
    "default_initial_mean",
    "default_initial_strategy",
    "default_initial_smart",
    "default_decay_rate",
    "default_momentum",
    "default_gradient_clipping_threshold",
]
