"""Evaluator DSL (reference: trainer_config_helpers/evaluators.py).

Each call appends an EvaluatorConfig to the model; the runtime implements
them in paddle_tpu.trainer.evaluators.
"""

from __future__ import annotations

from typing import Optional, Sequence

from paddle_tpu.config.builder import current_context
from paddle_tpu.proto import EvaluatorConfig

__all__ = [
    "evaluator_base",
    "classification_error_evaluator",
    "auc_evaluator",
    "rank_auc_evaluator",
    "seq_classification_error_evaluator",
    "pnpair_evaluator",
    "precision_recall_evaluator",
    "ctc_error_evaluator",
    "chunk_evaluator",
    "sum_evaluator",
    "column_sum_evaluator",
    "value_printer_evaluator",
    "gradient_printer_evaluator",
    "maxid_printer_evaluator",
    "maxframe_printer_evaluator",
    "seqtext_printer_evaluator",
    "classification_error_printer_evaluator",
]


def evaluator_base(
    type: str,
    input,
    label=None,
    weight=None,
    name: Optional[str] = None,
    chunk_scheme: Optional[str] = None,
    num_chunk_types: Optional[int] = None,
    classification_threshold: Optional[float] = None,
    positive_label: Optional[int] = None,
    dict_file: Optional[str] = None,
    result_file: Optional[str] = None,
    num_results: Optional[int] = None,
    delimited: Optional[bool] = None,
):
    ctx = current_context()
    inputs = input if isinstance(input, (list, tuple)) else [input]
    cfg = EvaluatorConfig(name=name or ctx.unique_name(f"eval_{type}"), type=type)
    for i in inputs:
        cfg.input_layers.append(i.name)
    if label is not None:
        cfg.input_layers.append(label.name)
    if weight is not None:
        cfg.input_layers.append(weight.name)
    if chunk_scheme is not None:
        cfg.chunk_scheme = chunk_scheme
        cfg.num_chunk_types = num_chunk_types or 0
    if classification_threshold is not None:
        cfg.classification_threshold = classification_threshold
    if positive_label is not None:
        cfg.positive_label = positive_label
    if dict_file is not None:
        cfg.dict_file = dict_file
    if result_file is not None:
        cfg.result_file = result_file
    if num_results is not None:
        cfg.num_results = num_results
    if delimited is not None:
        cfg.delimited = delimited
    ctx.model.evaluators.append(cfg)
    if ctx.submodel_stack:
        ctx.submodel_stack[-1].evaluator_names.append(cfg.name)
    return cfg


def classification_error_evaluator(input, label, name=None, weight=None, threshold=None):
    return evaluator_base(
        "classification_error", input, label, weight, name, classification_threshold=threshold
    )


def auc_evaluator(input, label, name=None, weight=None):
    return evaluator_base("last-column-auc", input, label, weight, name)


def rank_auc_evaluator(input, click, pv=None, name=None):
    """AUC over rank-model scores (ref: RankAucEvaluator, Evaluator.h:202)."""
    return evaluator_base("rank-auc", input, click, pv, name)


def seq_classification_error_evaluator(input, label, name=None):
    """Per-sequence classification error (ref: Evaluator.cpp:111)."""
    return evaluator_base("seq_classification_error", input, label, None, name)


def pnpair_evaluator(input, info, name=None, weight=None):
    return evaluator_base("pnpair", input, info, weight, name)


def precision_recall_evaluator(input, label, positive_label=None, weight=None, name=None):
    return evaluator_base(
        "precision_recall", input, label, weight, name, positive_label=positive_label
    )


def ctc_error_evaluator(input, label, name=None):
    return evaluator_base("ctc_edit_distance", input, label, None, name)


def chunk_evaluator(input, label, chunk_scheme, num_chunk_types, name=None):
    return evaluator_base(
        "chunk", input, label, None, name, chunk_scheme=chunk_scheme, num_chunk_types=num_chunk_types
    )


def sum_evaluator(input, name=None, weight=None):
    return evaluator_base("sum", input, None, weight, name)


def column_sum_evaluator(input, name=None, weight=None):
    return evaluator_base("last-column-sum", input, None, weight, name)


def value_printer_evaluator(input, name=None):
    return evaluator_base("value_printer", input, None, None, name)


def gradient_printer_evaluator(input, name=None):
    return evaluator_base("gradient_printer", input, None, None, name)


def maxid_printer_evaluator(input, num_results=None, name=None):
    return evaluator_base("max_id_printer", input, None, None, name, num_results=num_results)


def maxframe_printer_evaluator(input, num_results=None, name=None):
    return evaluator_base("max_frame_printer", input, None, None, name, num_results=num_results)


def seqtext_printer_evaluator(input, result_file, id_input=None, dict_file=None, delimited=None, name=None):
    inputs = [input] if id_input is None else [id_input, input]
    return evaluator_base(
        "seq_text_printer", inputs, None, None, name,
        dict_file=dict_file, result_file=result_file, delimited=delimited,
    )


def classification_error_printer_evaluator(input, label, threshold=0.5, name=None):
    return evaluator_base(
        "classification_error_printer", input, label, None, name,
        classification_threshold=threshold,
    )
