"""Parameter / layer attribute objects.

API-compatible with the reference's trainer_config_helpers.attrs
(/root/reference/python/paddle/trainer_config_helpers/attrs.py): users pass
``ParamAttr(...)`` / ``ExtraAttr(...)`` into layer functions to control
init, per-parameter learning rate/regularization, dropout, etc.
"""

from __future__ import annotations

from typing import Optional

__all__ = [
    "HookAttr",
    "HookAttribute",
    "ParamAttr",
    "ExtraAttr",
    "ParameterAttribute",
    "ExtraLayerAttribute",
]


class HookAttribute:
    """Parameter updater hook declaration (StaticPruningHook,
    /root/reference/paddle/parameter/ParameterUpdaterHook.cpp:37):
    ``HookAttr(type="pruning", mask_filename="layer.mask")`` keeps the
    weights disabled by the bitmask file at zero through training."""

    def __init__(self, type: str = "pruning", mask_filename: str = ""):
        assert type in ("pruning", "static_pruning"), type
        assert mask_filename, "pruning hook needs a mask_filename"
        self.type = type
        self.mask_filename = mask_filename


class ParameterAttribute:
    def __init__(
        self,
        name: Optional[str] = None,
        is_static: bool = False,
        initial_std: Optional[float] = None,
        initial_mean: Optional[float] = None,
        initial_max: Optional[float] = None,
        initial_min: Optional[float] = None,
        l1_rate: Optional[float] = None,
        l2_rate: Optional[float] = None,
        learning_rate: Optional[float] = None,
        momentum: Optional[float] = None,
        sparse_update: bool = False,
        # TPU extension: logical mesh-axis sharding for this parameter,
        # e.g. sharding=("model", None)
        sharding=None,
        update_hooks=None,
    ):
        self.name = name
        self.is_static = is_static
        self.initial_std = initial_std
        self.initial_mean = initial_mean
        self.initial_max = initial_max
        self.initial_min = initial_min
        self.l1_rate = l1_rate
        self.l2_rate = l2_rate
        self.learning_rate = learning_rate
        self.momentum = momentum
        self.sparse_update = sparse_update
        self.sharding = sharding
        if update_hooks is not None and not isinstance(update_hooks, (list, tuple)):
            update_hooks = [update_hooks]
        self.update_hooks = update_hooks

    def apply_to(self, pc) -> None:
        """Fill a ParameterConfig with the attribute's overrides."""
        if self.is_static:
            pc.is_static = True
        if self.initial_max is not None or self.initial_min is not None:
            lo = self.initial_min if self.initial_min is not None else 0.0
            hi = self.initial_max if self.initial_max is not None else 1.0
            pc.initial_strategy = 1
            pc.initial_mean = (lo + hi) / 2.0
            pc.initial_std = (hi - lo) / 2.0
            pc.initial_smart = False
        elif self.initial_mean is not None or self.initial_std is not None:
            if self.initial_mean is not None:
                pc.initial_mean = self.initial_mean
            if self.initial_std is not None:
                pc.initial_std = self.initial_std
            pc.initial_smart = False
        elif not self.is_static:
            # ParamAttr() with no init fields means "smart" init —
            # std = 1/sqrt(fan_in) (reference attrs.py:67).
            pc.initial_smart = True
        if self.l1_rate is not None:
            pc.decay_rate_l1 = self.l1_rate
        if self.l2_rate is not None:
            pc.decay_rate = self.l2_rate
        if self.learning_rate is not None:
            pc.learning_rate = self.learning_rate
        if self.momentum is not None:
            pc.momentum = self.momentum
        if self.sparse_update:
            pc.sparse_update = True
        if self.sharding is not None:
            pc.sharding = list(self.sharding)
        if self.update_hooks:
            from paddle_tpu.proto import ParameterUpdaterHookConfig

            pc.update_hooks = [
                ParameterUpdaterHookConfig(
                    type=h.type, purning_mask_filename=h.mask_filename
                )
                for h in self.update_hooks
            ]


class ExtraLayerAttribute:
    def __init__(
        self,
        error_clipping_threshold: Optional[float] = None,
        drop_rate: Optional[float] = None,
    ):
        self.error_clipping_threshold = error_clipping_threshold
        self.drop_rate = drop_rate

    def apply_to(self, layer_cfg) -> None:
        if self.error_clipping_threshold is not None:
            layer_cfg.error_clipping_threshold = self.error_clipping_threshold
        if self.drop_rate is not None:
            layer_cfg.drop_rate = self.drop_rate


ParamAttr = ParameterAttribute
HookAttr = HookAttribute
ExtraAttr = ExtraLayerAttribute
