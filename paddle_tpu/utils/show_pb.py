"""Inspect paddle_tpu binary artifacts from the command line.

Role analog of the reference's python/paddle/utils/show_pb.py (which
dumped varint-framed DataFormat protobuf records); our binary surfaces
are npz-based, so this tool recognizes and pretty-prints all three:

- binary data shards (paddle_tpu.data.binary write_shard format):
  slot types, sample count, and the first few samples;
- checkpoint pass dirs / params.npz trees: parameter names, shapes,
  dtypes, and value stats;
- merged models (trainer/checkpoint.py merge_model): the embedded config
  JSON plus the parameter table.

Usage:
  python -m paddle_tpu.utils.show_pb FILE_OR_PASS_DIR [--samples N]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

_SEQ = {0: "", 1: " seq", 2: " subseq"}
_TYPE = {0: "dense", 1: "sparse_binary", 2: "sparse_value", 3: "index"}


def _show_shard(path: str, n_samples: int) -> None:
    import itertools

    from paddle_tpu.data.binary import read_shard, shard_input_types

    types = shard_input_types(path)
    print(f"binary shard {path}")
    for i, t in enumerate(types):
        print(f"  slot {i}: {_TYPE.get(t.type, t.type)}{_SEQ.get(t.seq_type, '')} dim={t.dim}")
    with np.load(path) as z:
        n = json.loads(bytes(z["__meta__"]).decode())["n"]
    print(f"  samples: {n}")
    # decode only what gets printed — shards can be huge
    for s in itertools.islice(read_shard(path), n_samples):
        print("   ", " | ".join(str(v)[:70] for v in s))


def _show_params(arrays, title: str) -> None:
    print(title)
    total = 0
    for k in sorted(arrays):
        v = arrays[k]
        total += v.size
        stats = (
            f" mean={float(np.mean(v)):+.4g} absmax={float(np.max(np.abs(v))):.4g}"
            if v.size else " (empty)"
        )
        print(f"  {k:<45} {str(v.shape):<18} {str(v.dtype):<10}{stats}")
    print(f"  total parameters: {total:,}")


def show(path: str, n_samples: int = 4) -> int:
    if os.path.isdir(path):
        from paddle_tpu.trainer import checkpoint as ckpt

        arrays = ckpt._load_tree_numpy(path, "params")
        if arrays is None:
            print(f"{path}: directory without a params tree", file=sys.stderr)
            return 1
        meta_path = os.path.join(path, "meta.json")
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                print("meta:", json.dumps(json.load(f)))
        _show_params(arrays, f"checkpoint {path}")
        return 0
    with np.load(path, allow_pickle=False) as z:
        files = set(z.files)
        if "__meta__" in files:
            _show_shard(path, n_samples)
            return 0
        arrays = {k: z[k] for k in z.files if k != "__config_json__"}
        if "__config_json__" in files:
            cfg = bytes(z["__config_json__"]).decode()
            print("merged model config:", cfg[:400] + ("..." if len(cfg) > 400 else ""))
            _show_params(arrays, f"merged model {path}")
        else:
            _show_params(arrays, f"params tree {path}")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("path", help="shard npz, params npz, merged model, or pass dir")
    ap.add_argument("--samples", type=int, default=4, help="samples to print for shards")
    args = ap.parse_args(argv)
    return show(args.path, args.samples)


if __name__ == "__main__":
    sys.exit(main())
