"""Multi-host launcher over ssh
(ref: paddle/scripts/cluster_train/paddle.py, the fabric/ssh cluster
driver reading conf.py HOSTS).

Reads a conf module defining HOSTS (list of "user@host" strings) and
launches the same `paddle train` command on every host with the jax
distributed-runtime flags filled in (process 0's host becomes the
coordinator). Assumes a shared or rsynced workdir, as the reference did.

Failure handling (doc/resilience.md): children are POLLED, not serially
waited — when any host's process dies, the remaining hosts are torn down
immediately (SIGTERM, then SIGKILL after --grace seconds) instead of
hanging forever inside collectives waiting for the dead rank, and the
failing rank is named in the exit message. With --max_restarts=N the
whole job is relaunched up to N times with `--init_model_path=auto`
appended, so a relaunch resumes from the newest manifest-verified
checkpoint. SIGTERM to the launcher is forwarded to every host (pod
preemption: each trainer checkpoints via --save_on_preempt).

Usage:
    python -m paddle_tpu.utils.cluster_launch --conf=conf.py \
        --workdir=/path/on/hosts [--max_restarts=N] \
        -- --config=train.conf --mesh_shape=data=16 ...
"""

from __future__ import annotations

import argparse
import importlib.util
import os
import shlex
import signal
import subprocess
import sys
import time
from typing import List, Optional, Tuple


def load_hosts(conf_path: str) -> List[str]:
    spec = importlib.util.spec_from_file_location("cluster_conf", conf_path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    hosts = getattr(mod, "HOSTS", None)
    assert hosts, f"{conf_path} must define HOSTS = ['user@host', ...]"
    return list(hosts)


def _launch(args, hosts: List[str], train_args: List[str],
            attempt: int) -> List[subprocess.Popen]:
    coordinator = f"{hosts[0].split('@')[-1]}:{args.port}"
    extra = []
    if attempt > 0:
        # relaunch after a failure: resume every host from the newest
        # verified checkpoint instead of its original init
        from paddle_tpu.utils.flags import strip_flag

        train_args = strip_flag(train_args, "init_model_path")
        extra = ["--init_model_path=auto"]
    procs = []
    for rank, host in enumerate(hosts):
        cmd = [
            args.paddle, "train", *train_args, *extra,
            f"--coordinator_address={coordinator}",
            f"--num_processes={len(hosts)}",
            f"--process_id={rank}",
        ]
        remote = f"cd {shlex.quote(args.workdir)} && {' '.join(shlex.quote(c) for c in cmd)}"
        ssh = ["ssh", "-o", "BatchMode=yes", host, remote]
        print(f"[{rank}] {host}: {remote}")
        if not args.dry_run:
            # each ssh gets its own process group so teardown can signal
            # the whole group — a bare terminate() of the ssh process
            # would orphan anything it spawned, leaving it holding the
            # job's pipes/ports
            procs.append(subprocess.Popen(ssh, start_new_session=True))
    return procs


def _signal_group(proc: subprocess.Popen, sig: int) -> None:
    try:
        os.killpg(proc.pid, sig)  # pid == pgid (start_new_session)
    except (ProcessLookupError, PermissionError, OSError):
        try:
            proc.send_signal(sig)
        except OSError:
            pass


def _wait_first_failure(procs: List[subprocess.Popen],
                        poll_s: float) -> Optional[Tuple[int, int]]:
    """Poll all children; None when every one exited 0, else
    (rank, exit code) of the FIRST failure observed — the launcher must
    never sit in a serial wait() on rank 0 while rank 3 is already dead
    and the survivors hang in collectives."""
    pending = dict(enumerate(procs))
    while pending:
        for rank, proc in list(pending.items()):
            rc = proc.poll()
            if rc is None:
                continue
            del pending[rank]
            if rc != 0:
                return rank, rc
        if pending:
            time.sleep(poll_s)
    return None


def _teardown(procs: List[subprocess.Popen], grace_s: float) -> None:
    """SIGTERM every still-running host (their trainers checkpoint via
    --save_on_preempt), escalate to SIGKILL after the grace window."""
    live = [p for p in procs if p.poll() is None]
    for p in live:
        _signal_group(p, signal.SIGTERM)
    deadline = time.monotonic() + grace_s
    for p in live:
        try:
            p.wait(timeout=max(deadline - time.monotonic(), 0.1))
        except subprocess.TimeoutExpired:
            _signal_group(p, signal.SIGKILL)
            p.wait()


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if "--" in argv:
        split = argv.index("--")
        own, train_args = argv[:split], argv[split + 1:]
    else:
        own, train_args = argv, []
    p = argparse.ArgumentParser()
    p.add_argument("--conf", required=True, help="python file defining HOSTS")
    p.add_argument("--workdir", required=True, help="job dir present on every host")
    p.add_argument("--port", type=int, default=8476, help="coordinator port")
    p.add_argument("--paddle", default="paddle", help="paddle executable on hosts")
    p.add_argument("--dry_run", action="store_true")
    p.add_argument("--max_restarts", type=int, default=0,
                   help="relaunch the whole job (with --init_model_path=auto) "
                        "up to N times after a host failure; 0 = fail fast")
    p.add_argument("--restart_delay", type=float, default=5.0,
                   help="seconds between teardown and relaunch")
    p.add_argument("--poll_interval", type=float, default=0.5,
                   help="child liveness poll period, seconds")
    p.add_argument("--grace", type=float, default=10.0,
                   help="seconds between SIGTERM and SIGKILL at teardown")
    args = p.parse_args(own)

    hosts = load_hosts(args.conf)
    current: List[subprocess.Popen] = []
    terminating = False

    def on_sigterm(signum, frame):
        # preemption of the launcher itself: forward to every host and
        # stop relaunching — each trainer checkpoints on its own SIGTERM
        nonlocal terminating
        terminating = True
        for proc in current:
            if proc.poll() is None:
                _signal_group(proc, signal.SIGTERM)

    prev_handler = signal.getsignal(signal.SIGTERM)
    try:
        signal.signal(signal.SIGTERM, on_sigterm)
    except ValueError:  # non-main thread (tests): degrade to no handler
        prev_handler = None

    attempt = 0
    try:
        while True:
            current[:] = _launch(args, hosts, train_args, attempt)
            if args.dry_run:
                return 0
            failure = _wait_first_failure(current, args.poll_interval)
            if failure is None:
                return 0
            rank, rc = failure
            _teardown(current, args.grace)
            if terminating:
                print("cluster_launch: SIGTERM — job torn down, not "
                      "relaunching", file=sys.stderr)
                return rc or 143
            print(
                f"cluster_launch: host rank {rank} ({hosts[rank]}) exited "
                f"rc={rc}; tore down the remaining {len(hosts) - 1} host(s) "
                "to avoid hung collectives",
                file=sys.stderr,
            )
            if attempt >= args.max_restarts:
                if args.max_restarts:
                    print(
                        f"cluster_launch: restart budget "
                        f"({args.max_restarts}) exhausted — giving up",
                        file=sys.stderr,
                    )
                return rc or 1
            attempt += 1
            print(
                f"cluster_launch: relaunching whole job with "
                f"--init_model_path=auto (restart {attempt}/"
                f"{args.max_restarts}) in {args.restart_delay:g}s",
                file=sys.stderr,
            )
            time.sleep(args.restart_delay)
            if terminating:
                # SIGTERM landed while no hosts were running (teardown
                # already done, restart_delay sleep): honor it here
                # instead of relaunching a job the scheduler is ending
                print("cluster_launch: SIGTERM during restart delay — "
                      "not relaunching", file=sys.stderr)
                return rc or 143
    finally:
        if prev_handler is not None:
            signal.signal(signal.SIGTERM, prev_handler)


if __name__ == "__main__":
    sys.exit(main())
