"""Multi-host launcher over ssh
(ref: paddle/scripts/cluster_train/paddle.py, the fabric/ssh cluster
driver reading conf.py HOSTS).

Reads a conf module defining HOSTS (list of "user@host" strings) and
launches the same `paddle train` command on every host with the jax
distributed-runtime flags filled in (process 0's host becomes the
coordinator). Assumes a shared or rsynced workdir, as the reference did.

Usage:
    python -m paddle_tpu.utils.cluster_launch --conf=conf.py \
        --workdir=/path/on/hosts -- --config=train.conf --mesh_shape=data=16 ...
"""

from __future__ import annotations

import argparse
import importlib.util
import shlex
import subprocess
import sys
from typing import List


def load_hosts(conf_path: str) -> List[str]:
    spec = importlib.util.spec_from_file_location("cluster_conf", conf_path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    hosts = getattr(mod, "HOSTS", None)
    assert hosts, f"{conf_path} must define HOSTS = ['user@host', ...]"
    return list(hosts)


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if "--" in argv:
        split = argv.index("--")
        own, train_args = argv[:split], argv[split + 1:]
    else:
        own, train_args = argv, []
    p = argparse.ArgumentParser()
    p.add_argument("--conf", required=True, help="python file defining HOSTS")
    p.add_argument("--workdir", required=True, help="job dir present on every host")
    p.add_argument("--port", type=int, default=8476, help="coordinator port")
    p.add_argument("--paddle", default="paddle", help="paddle executable on hosts")
    p.add_argument("--dry_run", action="store_true")
    args = p.parse_args(own)

    hosts = load_hosts(args.conf)
    coordinator = f"{hosts[0].split('@')[-1]}:{args.port}"
    procs = []
    for rank, host in enumerate(hosts):
        cmd = [
            args.paddle, "train", *train_args,
            f"--coordinator_address={coordinator}",
            f"--num_processes={len(hosts)}",
            f"--process_id={rank}",
        ]
        remote = f"cd {shlex.quote(args.workdir)} && {' '.join(shlex.quote(c) for c in cmd)}"
        ssh = ["ssh", "-o", "BatchMode=yes", host, remote]
        print(f"[{rank}] {host}: {remote}")
        if not args.dry_run:
            procs.append(subprocess.Popen(ssh))
    rc = 0
    for rank, proc in enumerate(procs):
        rc |= proc.wait()
    return rc


if __name__ == "__main__":
    sys.exit(main())
