"""Multi-host launcher over ssh
(ref: paddle/scripts/cluster_train/paddle.py, the fabric/ssh cluster
driver reading conf.py HOSTS).

Reads a conf module defining HOSTS (list of "user@host" strings) and
launches the same `paddle train` command on every host with the jax
distributed-runtime flags filled in (process 0's host becomes the
coordinator). Assumes a shared or rsynced workdir, as the reference did.

Failure handling (doc/resilience.md): children are POLLED, not serially
waited — when any host's process dies, the remaining hosts are torn down
immediately (SIGTERM, then SIGKILL after --grace seconds) instead of
hanging forever inside collectives waiting for the dead rank, and the
failing rank is named in the exit message (signal deaths rendered by
name: rc=-15 prints as SIGTERM). When the train flags enable heartbeats
(--heartbeat_interval, resilience/heartbeat.py) the launcher ALSO polls
heartbeat staleness, so a wedged-but-alive rank — the failure process
liveness cannot see — is named and torn down too. With --max_restarts=N
the whole job is relaunched up to N times with `--init_model_path=auto`
appended, so a relaunch resumes from the newest manifest-verified
checkpoint; a host that exits EXIT_PREEMPTED (18, clean preemption
save) triggers a relaunch that consumes NO restart budget, and with
--elastic_min_hosts=M a host that keeps failing is dropped from the
next relaunch as long as M hosts remain (the per-pass rng fold_in keeps
feeder resharding deterministic for the survivors). SIGTERM to the
launcher is forwarded to every host (pod preemption: each trainer
checkpoints via --save_on_preempt).

Elasticity is RESHARDING, not just shrinking (doc/resilience.md
"Elastic sharded checkpointing"): every relaunch round recomputes the
mesh from the surviving host set — the forwarded --mesh_shape's data
axis is rescaled by mesh.rescale_mesh_spec (model/pipe axes keep their
extents), so an N-host checkpoint restores onto the M-host mesh through
the ordinary sharded-restore path (parallel/spmd.py sharding rules) and
the GLOBAL batch is preserved: the config batch_size is the global
batch, each process takes a 1/num_processes row block, so the per-host
batch rescales automatically and sync-SGD semantics never change. A
host dropped by --elastic_min_hosts is probed (`ssh host true`, bounded
by --rejoin_probe_timeout) at each later relaunch round and REJOINS the
mesh when reachable again — recovery is not permanent capacity loss.
Before each relaunch round the heartbeat dir is swept: ranks renumber
with the host set, and a stale host-N.json written by the previous
mesh's rank N must not masquerade as (or spuriously condemn) the new
rank N.

Usage:
    python -m paddle_tpu.utils.cluster_launch --conf=conf.py \
        --workdir=/path/on/hosts [--max_restarts=N] \
        [--elastic_min_hosts=M] \
        -- --config=train.conf --mesh_shape=data=16 ...
"""

from __future__ import annotations

import argparse
import importlib.util
import os
import shlex
import signal
import subprocess
import sys
import time
from typing import List, Optional, Tuple

from paddle_tpu.resilience import EXIT_HANG, EXIT_PREEMPTED

# a host is dropped (when --elastic_min_hosts allows) after this many
# job failures were attributed to it
ELASTIC_STRIKES = 2

# preemption relaunches are budget-free, but bounded: a broken node
# agent SIGTERMing every fresh round would otherwise loop forever
PREEMPT_RELAUNCH_LIMIT = 100


def load_hosts(conf_path: str) -> List[str]:
    spec = importlib.util.spec_from_file_location("cluster_conf", conf_path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    hosts = getattr(mod, "HOSTS", None)
    assert hosts, f"{conf_path} must define HOSTS = ['user@host', ...]"
    return list(hosts)


def describe_rc(rc: int) -> str:
    """Exit status as a human reads it: negative returncodes (subprocess
    convention for signal deaths) carry the signal NAME, and the
    disciplined codes carry their classification."""
    if rc < 0:
        try:
            name = signal.Signals(-rc).name
        except ValueError:
            name = f"signal {-rc}"
        return f"rc={rc} ({name})"
    if rc == EXIT_PREEMPTED:
        return f"rc={rc} (preempted — checkpointed and exited cleanly)"
    if rc == EXIT_HANG:
        return f"rc={rc} (hang detected by hangwatch — see hang_report.json)"
    return f"rc={rc}"


def _exit_code(rc: int) -> int:
    """Launcher process exit status for a child rc: signal deaths map to
    the shell's 128+signum convention instead of a wrapped negative."""
    return 128 - rc if rc < 0 else rc


def _reshard_error(train_args: List[str], orig_n: int, cur_n: int) -> Optional[str]:
    """Why the forwarded --mesh_shape cannot be rescaled from ``orig_n``
    to ``cur_n`` hosts, or None when it can. Checked BEFORE committing to
    a host-set change (elastic drop / rejoin): changing the host count
    without a reshardable mesh would launch a job whose mesh no longer
    matches its devices."""
    from paddle_tpu.parallel.mesh import rescale_mesh_spec
    from paddle_tpu.utils.flags import flag_value

    try:
        rescale_mesh_spec(flag_value(train_args, "mesh_shape", ""), orig_n, cur_n)
    except ValueError as e:
        return str(e)
    # row-sharded sparse tables add a second refusal: the new host set
    # must hold the declared table within --sparse_row_budget rows per
    # host (declared via --sparse_total_rows so this supervisor stays
    # jax/config-free; doc/sparse.md "Refusal rule")
    try:
        budget = int(flag_value(train_args, "sparse_row_budget", "0") or 0)
        rows = int(flag_value(train_args, "sparse_total_rows", "0") or 0)
    except ValueError:
        budget = rows = 0
    if budget > 0 and rows > 0:
        from paddle_tpu.sparse.rowshard import row_budget_error

        return row_budget_error({"": rows}, cur_n, budget)
    return None


def _rescaled_train_args(train_args: List[str], orig_n: int,
                         cur_n: int) -> List[str]:
    """The train args for a round on ``cur_n`` hosts: --mesh_shape's data
    axis rescaled from the ORIGINAL launch spec (reshard-on-relaunch —
    the N-host checkpoint restores onto the M-host mesh through the
    normal sharded-restore path, and the global batch is preserved
    because each process takes a 1/num_processes row block of the
    config's batch_size). Identity when the host count is unchanged."""
    if cur_n == orig_n:
        return train_args
    from paddle_tpu.parallel.mesh import rescale_mesh_spec
    from paddle_tpu.utils.flags import flag_value, strip_flag

    spec = rescale_mesh_spec(
        flag_value(train_args, "mesh_shape", ""), orig_n, cur_n
    )
    if not spec:
        # auto-sized mesh (no --mesh_shape): the trainer derives it from
        # jax.devices(), which already follows the surviving host set
        return train_args
    return strip_flag(train_args, "mesh_shape") + [f"--mesh_shape={spec}"]


def _probe_host(host: str, timeout_s: float) -> bool:
    """Is a dropped host reachable again? One bounded `ssh host true` —
    the same transport the launch itself uses, so "probe ok" means "the
    next round's ssh will connect", nothing stronger."""
    if timeout_s <= 0:
        return False
    try:
        return subprocess.run(
            ["ssh", "-o", "BatchMode=yes", host, "true"],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            timeout=timeout_s,
        ).returncode == 0
    except (subprocess.TimeoutExpired, OSError):
        return False


def _clear_heartbeats(dir_: Optional[str]) -> int:
    """Delete every host-N.json beat before a relaunch round. Ranks are
    positional: when the host set shrinks, grows, or renumbers, a beat
    file written by the PREVIOUS mesh's rank N is stale evidence about
    the NEW rank N — left in place it can trigger a spurious staleness
    teardown (or hide a genuinely silent host behind a fresh-looking
    file, and defeat the monitor's no-beats unshared-mount guard).
    Returns how many files were removed; missing dir is fine."""
    if not dir_ or not os.path.isdir(dir_):
        return 0
    removed = 0
    for name in os.listdir(dir_):
        if name.startswith("host-") and name.endswith(".json"):
            try:
                os.remove(os.path.join(dir_, name))
                removed += 1
            except OSError:
                pass
    return removed


def _launch(args, hosts: List[str], train_args: List[str],
            resume: bool, orig_n: Optional[int] = None) -> List[subprocess.Popen]:
    coordinator = f"{hosts[0].split('@')[-1]}:{args.port}"
    extra = []
    if orig_n is not None:
        # reshard-on-relaunch: recompute the mesh for THIS round's host
        # count (no-op while the full original set is launching)
        train_args = _rescaled_train_args(train_args, orig_n, len(hosts))
    if resume:
        # relaunch after a failure: resume every host from the newest
        # verified checkpoint instead of its original init
        from paddle_tpu.utils.flags import strip_flag

        train_args = strip_flag(train_args, "init_model_path")
        extra = ["--init_model_path=auto"]
    procs = []
    for rank, host in enumerate(hosts):
        cmd = [
            args.paddle, "train", *train_args, *extra,
            f"--coordinator_address={coordinator}",
            f"--num_processes={len(hosts)}",
            f"--process_id={rank}",
        ]
        remote = f"cd {shlex.quote(args.workdir)} && {' '.join(shlex.quote(c) for c in cmd)}"
        ssh = ["ssh", "-o", "BatchMode=yes", host, remote]
        print(f"[{rank}] {host}: {remote}")
        if not args.dry_run:
            # each ssh gets its own process group so teardown can signal
            # the whole group — a bare terminate() of the ssh process
            # would orphan anything it spawned, leaving it holding the
            # job's pipes/ports
            procs.append(subprocess.Popen(ssh, start_new_session=True))
    return procs


def _signal_group(proc: subprocess.Popen, sig: int) -> None:
    try:
        os.killpg(proc.pid, sig)  # pid == pgid (start_new_session)
    except (ProcessLookupError, PermissionError, OSError):
        try:
            proc.send_signal(sig)
        except OSError:
            pass


class _HeartbeatMonitor:
    """Staleness view over the shared heartbeat dir for ONE launch round.

    ``since`` (construction time) is the observation epoch: beats from a
    previous round cannot trigger, and a host that never writes one is
    aged from launch — both fall out of heartbeat.stale_hosts' ``since``
    clamp. Only still-running ranks are reported (an exited rank's
    silence is process death's job to notice)."""

    def __init__(self, dir_: str, num_hosts: int, stale_after_s: float,
                 warmup_s: float = 0.0):
        self.dir = dir_
        self.num_hosts = num_hosts
        self.stale_after_s = stale_after_s
        self.warmup_s = warmup_s
        self.since = time.time()
        # staleness granularity is tens of seconds; re-listing and
        # parsing every beat file on a shared mount at the liveness
        # poll rate (0.5s) would be pure NFS-metadata churn
        self._scan_every = max(stale_after_s / 4.0, 0.5)
        self._last_scan = -float("inf")
        self.disabled = False

    def stale(self, alive_ranks) -> List[Tuple[int, float]]:
        # startup warmup: ssh + interpreter + jax init + checkpoint
        # restore all happen before the trainer's first beat (and again
        # on every relaunch round) — enforcing staleness that early
        # would tear down a healthy job that is merely starting. A
        # genuinely wedged startup still gets caught, just warmup_s
        # later.
        now = time.monotonic()
        if self.disabled or time.time() - self.since < self.warmup_s:
            return []
        if now - self._last_scan < self._scan_every:
            return []
        self._last_scan = now
        from paddle_tpu.resilience.heartbeat import read_beats, stale_hosts

        beats = read_beats(self.dir)
        if not beats:
            # not one beat from ANY host: too early to judge while the
            # staleness window is still open; past it, all ranks
            # wedging simultaneously is far less likely than a dir the
            # launcher cannot actually see (wrong mount, unshared
            # path). Tearing down a healthy job on that evidence would
            # serially eject every host — disable loudly instead.
            if time.time() - self.since > self.warmup_s + self.stale_after_s:
                self.disabled = True
                print(
                    f"cluster_launch: no heartbeat from any host under "
                    f"{self.dir!r} after the startup grace — the dir is "
                    "probably not visible to the launcher (unshared "
                    "mount?); heartbeat monitoring disabled, process "
                    "liveness still active",
                    file=sys.stderr,
                )
            return []
        return [
            (rank, age)
            for rank, age in stale_hosts(
                self.dir, self.num_hosts, self.stale_after_s,
                since=self.since, beats=beats,
            )
            if rank in alive_ranks
        ]


def _wait_first_failure(
    procs: List[subprocess.Popen],
    poll_s: float,
    hb: Optional[_HeartbeatMonitor] = None,
) -> Optional[Tuple[int, int, str]]:
    """Poll all children; None when every one exited 0, else
    (rank, exit code, human detail) of the FIRST failure observed — the
    launcher must never sit in a serial wait() on rank 0 while rank 3 is
    already dead and the survivors hang in collectives. With a heartbeat
    monitor, a still-running rank whose beat went stale is a failure too
    (reported as EXIT_HANG): wedged-but-alive is exactly the state
    process liveness cannot see."""
    pending = dict(enumerate(procs))
    while pending:
        for rank, proc in list(pending.items()):
            rc = proc.poll()
            if rc is None:
                continue
            del pending[rank]
            if rc != 0:
                return rank, rc, f"exited {describe_rc(rc)}"
        if hb is not None and pending:
            stale = hb.stale(pending.keys())
            if stale:
                rank, age = stale[0]
                return rank, EXIT_HANG, (
                    f"is wedged: heartbeat stale for {age:.1f}s "
                    f"(> {hb.stale_after_s:g}s) while the process is "
                    "still alive"
                )
        if pending:
            time.sleep(poll_s)
    return None


def _teardown(procs: List[subprocess.Popen], grace_s: float) -> None:
    """SIGTERM every still-running host (their trainers checkpoint via
    --save_on_preempt), escalate to SIGKILL after the grace window. All
    hosts share ONE deadline: each wait gets only the time remaining,
    and once the deadline has passed the rest skip straight to SIGKILL —
    never a serial ≥0.1s wait per already-expired host."""
    live = [p for p in procs if p.poll() is None]
    for p in live:
        _signal_group(p, signal.SIGTERM)
    deadline = time.monotonic() + grace_s
    for p in live:
        remaining = deadline - time.monotonic()
        if remaining > 0:
            try:
                p.wait(timeout=remaining)
                continue
            except subprocess.TimeoutExpired:
                pass
        _signal_group(p, signal.SIGKILL)
        p.wait()


def _heartbeat_config(train_args: List[str]):
    """(dir, stale_after_s) the launcher should monitor, or None.

    Read from the TRAIN flags (one source of truth — the same flags the
    hosts will heartbeat with): monitoring turns on when
    --heartbeat_interval > 0 and a heartbeat dir is resolvable. The dir
    must be visible to the launcher too (an absolute path on the shared
    filesystem), exactly like the shared workdir assumption."""
    from paddle_tpu.resilience.heartbeat import (
        DEFAULT_STALE_MULTIPLE,
        resolve_dir,
    )
    from paddle_tpu.utils.flags import flag_value

    interval = float(flag_value(train_args, "heartbeat_interval", "0") or 0)
    if interval <= 0:
        return None
    dir_ = resolve_dir(
        flag_value(train_args, "heartbeat_dir", ""),
        flag_value(train_args, "save_dir", ""),
    )
    if not dir_:
        print(
            "cluster_launch: --heartbeat_interval set but no "
            "--heartbeat_dir/--save_dir to watch — heartbeat monitoring "
            "disabled",
            file=sys.stderr,
        )
        return None
    if not os.path.isabs(dir_):
        # the trainers resolve this path under the remote workdir; the
        # launcher resolving it under its OWN cwd would watch an empty
        # local directory and tear down healthy jobs as "wedged".
        # Monitoring needs one path valid on both sides — an absolute
        # path on the shared mount.
        print(
            f"cluster_launch: heartbeat dir {dir_!r} is relative (the "
            "hosts resolve it under --workdir, this launcher cannot) — "
            "heartbeat monitoring disabled; pass an absolute "
            "--heartbeat_dir on the shared filesystem to enable it",
            file=sys.stderr,
        )
        return None
    stale = float(
        flag_value(train_args, "heartbeat_stale_after", "0") or 0
    ) or interval * DEFAULT_STALE_MULTIPLE
    return dir_, stale


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if "--" in argv:
        split = argv.index("--")
        own, train_args = argv[:split], argv[split + 1:]
    else:
        own, train_args = argv, []
    p = argparse.ArgumentParser()
    p.add_argument("--conf", required=True, help="python file defining HOSTS")
    p.add_argument("--workdir", required=True, help="job dir present on every host")
    p.add_argument("--port", type=int, default=8476, help="coordinator port")
    p.add_argument("--paddle", default="paddle", help="paddle executable on hosts")
    p.add_argument("--dry_run", action="store_true")
    p.add_argument("--max_restarts", type=int, default=0,
                   help="relaunch the whole job (with --init_model_path=auto) "
                        "up to N times after a host failure; 0 = fail fast "
                        "(a clean preemption exit never consumes budget)")
    p.add_argument("--restart_delay", type=float, default=5.0,
                   help="seconds between teardown and relaunch")
    p.add_argument("--poll_interval", type=float, default=0.5,
                   help="child liveness poll period, seconds")
    p.add_argument("--grace", type=float, default=10.0,
                   help="seconds between SIGTERM and SIGKILL at teardown")
    p.add_argument("--heartbeat_startup_grace", type=float, default=120.0,
                   help="seconds after each (re)launch before heartbeat "
                        "staleness is enforced — covers ssh/jax startup "
                        "and checkpoint restore, which happen before a "
                        "host's first beat")
    p.add_argument("--elastic_min_hosts", type=int, default=0,
                   help="when > 0, a host that has caused "
                        f"{ELASTIC_STRIKES} job failures is dropped "
                        "(budget-free) from the next relaunch as long as "
                        "this many hosts remain; 0 disables elastic "
                        "shrink. Needs --max_restarts >= "
                        f"{ELASTIC_STRIKES - 1}: the strikes before the "
                        "drop are ordinary budgeted relaunches. The mesh "
                        "is resharded for the surviving host count "
                        "(--mesh_shape data axis rescaled; global batch "
                        "preserved)")
    p.add_argument("--rejoin_probe_timeout", type=float, default=5.0,
                   help="seconds allowed for the `ssh host true` "
                        "reachability probe of each dropped host at every "
                        "relaunch round; a host that answers rejoins the "
                        "mesh (on probation: one more failure re-drops "
                        "it). 0 disables rejoin — drops become permanent")
    args = p.parse_args(own)

    hosts = load_hosts(args.conf)
    hb_conf = _heartbeat_config(train_args)
    current: List[subprocess.Popen] = []
    terminating = False

    def on_sigterm(signum, frame):
        # preemption of the launcher itself: forward to every host and
        # stop relaunching — each trainer checkpoints on its own SIGTERM
        nonlocal terminating
        terminating = True
        for proc in current:
            if proc.poll() is None:
                _signal_group(proc, signal.SIGTERM)

    prev_handler = signal.getsignal(signal.SIGTERM)
    try:
        signal.signal(signal.SIGTERM, on_sigterm)
    except ValueError:  # non-main thread (tests): degrade to no handler
        prev_handler = None

    restarts = 0          # budgeted relaunches (counted vs --max_restarts)
    preempt_relaunches = 0  # budget-free rounds, bounded separately
    resumed = False       # any relaunch at all → --init_model_path=auto
    strikes = {h: 0 for h in hosts}  # per-host failure attribution
    orig_hosts = list(hosts)  # rank order + mesh anchor: --mesh_shape
    orig_n = len(hosts)       # describes THIS many hosts, rescale from it
    round_no = 0
    # (original index, host, round it was dropped in): the round number
    # gates the rejoin probe to LATER rounds — probing in the drop round
    # itself would immediately reinstate a crash-looping host whose sshd
    # is healthy, turning the budget-free drop into an unbounded
    # drop/rejoin relaunch loop. Delayed one round, every rejoin is
    # preceded by a budget-consuming (or completing) round, so the
    # cycle stays bounded by --max_restarts.
    dropped: List[Tuple[int, str, int]] = []
    try:
        while True:
            round_no += 1
            if resumed:
                # new mesh epoch: sweep beats written by the previous
                # round's (possibly renumbered) ranks, and offer every
                # dropped host its way back in
                if hb_conf is not None:
                    swept = _clear_heartbeats(hb_conf[0])
                    if swept:
                        print(
                            f"cluster_launch: cleared {swept} heartbeat "
                            "file(s) from the previous round (ranks "
                            "renumber with the host set)",
                            file=sys.stderr,
                        )
                if dropped and args.rejoin_probe_timeout > 0:
                    still_out: List[Tuple[int, str, int]] = []
                    for oidx, host, drop_round in dropped:
                        if (
                            round_no > drop_round + 1
                            and _reshard_error(train_args, orig_n, len(hosts) + 1)
                            is None
                            and _probe_host(host, args.rejoin_probe_timeout)
                        ):
                            # original relative order ⇒ deterministic
                            # ranks: insert before every current host
                            # that originally came after it
                            pos = sum(
                                1 for h in hosts
                                if orig_hosts.index(h) < oidx
                            )
                            hosts.insert(pos, host)
                            # probation: one more failure re-drops it
                            # immediately instead of charging two fresh
                            # strikes to a flapping host
                            strikes[host] = ELASTIC_STRIKES - 1
                            print(
                                f"cluster_launch: host {host} is reachable "
                                f"again — rejoining the mesh at rank {pos} "
                                f"({len(hosts)} host(s); mesh reshards "
                                "this round)",
                                file=sys.stderr,
                            )
                        else:
                            still_out.append((oidx, host, drop_round))
                    dropped[:] = still_out
            current[:] = _launch(args, hosts, train_args, resume=resumed,
                                 orig_n=orig_n)
            if args.dry_run:
                return 0
            hb = (
                _HeartbeatMonitor(hb_conf[0], len(hosts), hb_conf[1],
                                  warmup_s=args.heartbeat_startup_grace)
                if hb_conf else None
            )
            failure = _wait_first_failure(current, args.poll_interval, hb)
            if failure is None:
                return 0
            rank, rc, detail = failure
            _teardown(current, args.grace)
            if terminating:
                print("cluster_launch: SIGTERM — job torn down, not "
                      "relaunching", file=sys.stderr)
                return _exit_code(rc) or 143
            print(
                f"cluster_launch: host rank {rank} ({hosts[rank]}) {detail}; "
                f"tore down the remaining {len(hosts) - 1} host(s) "
                "to avoid hung collectives",
                file=sys.stderr,
            )
            if rc == EXIT_PREEMPTED:
                # the rank checkpointed and left on the scheduler's
                # order — relaunch with auto-resume WITHOUT consuming
                # the restart budget (and without a strike: preemption
                # says nothing about the host's health). Bounded: a
                # preemption STORM (every round killed) must terminate.
                preempt_relaunches += 1
                if preempt_relaunches > PREEMPT_RELAUNCH_LIMIT:
                    print(
                        f"cluster_launch: {preempt_relaunches} "
                        "consecutive preemption rounds with no completed "
                        "run — giving up (something is killing every "
                        "launch, not scheduling it)",
                        file=sys.stderr,
                    )
                    return _exit_code(rc)
                resumed = True
                print(
                    "cluster_launch: preemption — relaunching whole job "
                    "with --init_model_path=auto (no restart budget "
                    f"consumed) in {args.restart_delay:g}s",
                    file=sys.stderr,
                )
            else:
                strikes[hosts[rank]] = strikes.get(hosts[rank], 0) + 1
                drop_ok = (
                    args.elastic_min_hosts > 0
                    and strikes[hosts[rank]] >= ELASTIC_STRIKES
                    and len(hosts) - 1 >= args.elastic_min_hosts
                )
                if drop_ok:
                    err = _reshard_error(train_args, orig_n, len(hosts) - 1)
                    if err is not None:
                        # a drop the mesh cannot follow would launch a
                        # job whose --mesh_shape no longer matches its
                        # devices — keep the host and spend budget on an
                        # ordinary full-set relaunch instead
                        drop_ok = False
                        print(
                            f"cluster_launch: cannot drop host "
                            f"{hosts[rank]} — the mesh does not reshard "
                            f"to {len(hosts) - 1} host(s) ({err}); "
                            "keeping it and relaunching on budget",
                            file=sys.stderr,
                        )
                if drop_ok:
                    # dropping the offender IS the fix, not another try
                    # at the same job — this relaunch consumes no budget
                    # (otherwise the drop round could announce
                    # "continuing" and then immediately exhaust the
                    # budget it just consumed)
                    bad = hosts.pop(rank)
                    dropped.append((orig_hosts.index(bad), bad, round_no))
                    resumed = True
                    print(
                        f"cluster_launch: dropping host {bad} after "
                        f"{ELASTIC_STRIKES} failures — relaunching with "
                        f"{len(hosts)} host(s), no restart budget "
                        "consumed (--elastic_min_hosts allows it); the "
                        "mesh reshards to the survivors (global batch "
                        "preserved) and the host may rejoin when it "
                        "answers the reachability probe",
                        file=sys.stderr,
                    )
                elif restarts >= args.max_restarts:
                    if args.max_restarts:
                        print(
                            f"cluster_launch: restart budget "
                            f"({args.max_restarts}) exhausted — giving up",
                            file=sys.stderr,
                        )
                    return _exit_code(rc) or 1
                else:
                    restarts += 1
                    resumed = True
                    print(
                        f"cluster_launch: relaunching whole job with "
                        f"--init_model_path=auto (restart {restarts}/"
                        f"{args.max_restarts}) in {args.restart_delay:g}s",
                        file=sys.stderr,
                    )
            time.sleep(args.restart_delay)
            if terminating:
                # SIGTERM landed while no hosts were running (teardown
                # already done, restart_delay sleep): honor it here
                # instead of relaunching a job the scheduler is ending
                print("cluster_launch: SIGTERM during restart delay — "
                      "not relaunching", file=sys.stderr)
                return _exit_code(rc) or 143
    finally:
        if prev_handler is not None:
            signal.signal(signal.SIGTERM, prev_handler)


if __name__ == "__main__":
    sys.exit(main())
