"""Emit a graphviz dot diagram of a model config
(ref: python/paddle/utils/make_model_diagram.py).

Usage:
    python -m paddle_tpu.utils.make_model_diagram config.py [config_args] > model.dot
"""

from __future__ import annotations

import sys


def make_diagram(model_config) -> str:
    lines = ["digraph model {", "  rankdir=BT;", '  node [shape=box, fontsize=10];']
    for layer in model_config.layers:
        label = f"{layer.name}\\n{layer.type}"
        if layer.size:
            label += f" [{layer.size}]"
        shape = "ellipse" if layer.type == "data" else "box"
        lines.append(f'  "{layer.name}" [label="{label}", shape={shape}];')
        for inp in layer.inputs:
            lines.append(f'  "{inp.input_layer_name}" -> "{layer.name}";')
    for name in model_config.output_layer_names:
        lines.append(f'  "{name}" [style=bold];')
    lines.append("}")
    return "\n".join(lines)


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv:
        print(__doc__, file=sys.stderr)
        return 2
    from paddle_tpu.config import parse_config

    config = parse_config(argv[0], argv[1] if len(argv) > 1 else "")
    print(make_diagram(config.model_config))
    return 0


if __name__ == "__main__":
    import signal

    if hasattr(signal, "SIGPIPE"):
        signal.signal(signal.SIGPIPE, signal.SIG_DFL)
    sys.exit(main())
