"""Per-host step-time skew diagnostics — the BarrierStat role.

The reference's BarrierStat (/root/reference/paddle/utils/BarrierStat.h:
36-60) records per-trainer wait times at pserver barriers and reports
which hosts straggle. The SPMD analog: every step is an implicit barrier
(collectives synchronize the mesh), so the observable is each host's
wall-clock step time; skew between hosts is exactly the time fast hosts
spend waiting inside collectives for stragglers.
"""

from __future__ import annotations

import itertools
from typing import List, Optional

import numpy as np

from paddle_tpu.utils.logging import logger

# host_barrier ids must be unique per rendezvous; all processes make the
# same sequence of host_barrier calls (they are collective by contract),
# so a shared monotonic counter keeps ids aligned across the pod
_BARRIER_SEQ = itertools.count()


def distributed_client():
    """The jax distributed-runtime KV/barrier client of this process, or
    None (single process, or jax.distributed never initialized). The
    client provides HOST-level coordination — key_value_set/get and
    wait_at_barrier — that works even on backends that cannot run
    cross-process device computations (the CPU backend in CI), which is
    exactly why the checkpoint protocol rendezvous rides it instead of
    a device collective."""
    try:
        from jax._src import distributed

        return distributed.global_state.client
    except Exception:  # private API moved / jax too old: degrade
        return None


def host_barrier(tag: str, timeout_s: float = 600.0) -> None:
    """Cross-process rendezvous with NO device collective.

    The sharded checkpoint protocol only needs ordering between host-side
    filesystem effects (shards written before the merge, merge durable
    before anyone loads); a device collective (sync_global_devices) would
    drag the accelerator runtime into a pure host protocol — and fails
    outright on backends without cross-process computations. Uses the
    distributed runtime's host barrier; single-process is a no-op; falls
    back to sync_global_devices if the client API is unavailable.

    Raises RuntimeError when the rendezvous times out (a peer died
    mid-protocol) — callers translate to their own error type."""
    import jax

    if jax.process_count() == 1:
        return
    client = distributed_client()
    if client is None:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(tag)
        return
    barrier_id = f"{tag}#{next(_BARRIER_SEQ)}"
    try:
        client.wait_at_barrier(barrier_id, int(timeout_s * 1000))
    except Exception as e:
        raise RuntimeError(
            f"host barrier {tag!r} failed after {timeout_s:g}s — a peer "
            f"process likely died mid-protocol: {e}"
        ) from e


def step_time_skew_summary(
    step_times_s: List[float], pass_id: Optional[int] = None
) -> Optional[str]:
    """All-gather this host's mean/p99 step time and summarize cross-host
    skew. Returns the log line (also logged here), or None when not
    running multi-process. Also emits the gathered table as a structured
    ``barrier_skew`` metrics record (doc/observability.md), so the
    supervisor's crash report and `paddle metrics` read attribution from
    telemetry instead of grepping this log line."""
    import jax

    if jax.process_count() == 1:
        return None
    # every process MUST reach the allgather: a host whose pass trained
    # zero launches (all-remainder pass, fast-forward after rollback)
    # joins with NaN sentinels instead of returning early — the old
    # early return desynced the collective and hung the pod, and a
    # zero-filled row would have skewed the min/argmax attribution
    if step_times_s:
        local = np.asarray(
            [np.mean(step_times_s), np.percentile(step_times_s, 99)], np.float32
        )
    else:
        local = np.asarray([np.nan, np.nan], np.float32)
    from jax.experimental import multihost_utils

    all_stats = np.asarray(multihost_utils.process_allgather(local))  # [P, 2]
    line = summarize_host_stats(all_stats)
    if line is not None:
        logger.info(line)
        from paddle_tpu.observability import metrics as obs

        means = all_stats[:, 0].astype(float)
        valid = np.isfinite(means)
        obs.emit(
            "barrier_skew",
            pass_id=pass_id,
            mean_s=[float(m) if np.isfinite(m) else None for m in means],
            p99_s=[
                float(p) if np.isfinite(p) else None for p in all_stats[:, 1]
            ],
            skew_s=float(np.nanmax(means) - np.nanmin(means)),
            slowest_host=int(np.nanargmax(means)),
            idle_hosts=[int(i) for i in np.flatnonzero(~valid)],
            line=line,
        )
    return line


def summarize_host_stats(all_stats: np.ndarray) -> Optional[str]:
    """Format the gathered [P, 2] (mean, p99) table into the BarrierStat
    line. NaN rows (hosts that recorded no steps) are excluded from the
    skew/slowest attribution but called out, so a dead-idle host can
    neither fake being the fastest nor hide. None when no host has data.

    Split out from the collective so the sentinel handling is unit
    testable without a multi-process run; the supervisor's crash report
    greps the resulting line for slowest-host attribution."""
    all_stats = np.asarray(all_stats, np.float64)
    means = all_stats[:, 0]
    valid = np.isfinite(means)
    if not valid.any():
        return None
    slowest = int(np.nanargmax(means))
    skew = float(np.nanmax(means) - np.nanmin(means))
    fmt = ["%.1fms" % (m * 1e3) if np.isfinite(m) else "n/a" for m in means]
    line = (
        f"BarrierStat: step mean/host={fmt} "
        f"skew={skew * 1e3:.1f}ms slowest=host{slowest} "
        f"p99[slowest]={all_stats[slowest, 1] * 1e3:.1f}ms"
    )
    idle = [str(i) for i in np.flatnonzero(~valid)]
    if idle:
        line += f" (no steps recorded on host(s) {','.join(idle)})"
    return line
