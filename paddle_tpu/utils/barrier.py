"""Per-host step-time skew diagnostics — the BarrierStat role.

The reference's BarrierStat (/root/reference/paddle/utils/BarrierStat.h:
36-60) records per-trainer wait times at pserver barriers and reports
which hosts straggle. The SPMD analog: every step is an implicit barrier
(collectives synchronize the mesh), so the observable is each host's
wall-clock step time; skew between hosts is exactly the time fast hosts
spend waiting inside collectives for stragglers.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from paddle_tpu.utils.logging import logger


def step_time_skew_summary(step_times_s: List[float]) -> Optional[str]:
    """All-gather this host's mean/p99 step time and summarize cross-host
    skew. Returns the log line (also logged here), or None when not
    running multi-process."""
    import jax

    if not step_times_s:
        return None
    local = np.asarray(
        [np.mean(step_times_s), np.percentile(step_times_s, 99)], np.float32
    )
    if jax.process_count() == 1:
        return None
    from jax.experimental import multihost_utils

    all_stats = np.asarray(multihost_utils.process_allgather(local))  # [P, 2]
    means = all_stats[:, 0]
    slowest = int(np.argmax(means))
    skew = float(means.max() - means.min())
    line = (
        f"BarrierStat: step mean/host={['%.1fms' % (m * 1e3) for m in means]} "
        f"skew={skew * 1e3:.1f}ms slowest=host{slowest} "
        f"p99[slowest]={all_stats[slowest, 1] * 1e3:.1f}ms"
    )
    logger.info(line)
    return line
