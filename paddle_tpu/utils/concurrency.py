"""The concurrency seam — every thread, lock, and clock the framework
uses, acquired through ONE module.

Production behavior is identical to the stdlib: ``cc.Thread`` is
``threading.Thread``, ``cc.monotonic`` is ``time.monotonic``, and so
on — this module adds one attribute lookup per construction, nothing
per operation. What the seam buys is *substitutability*: the dynamic
race analyzer (``paddle race``, ``paddle_tpu/analysis/dynamic/``)
installs a virtualized provider whose primitives report every
acquire/release/wait/notify to a deterministic scheduler, so the REAL
daemon-thread code (async checkpoint writers, hangwatch, heartbeat,
the feeder pool) can be run under explored interleavings and replayed
from a seed.

Rules for framework code:

- construct primitives via this module (``cc.Lock()``, ``cc.Thread``,
  ``cc.Event()``, ``cc.Queue()``, ``cc.Timer``), and read time via
  ``cc.monotonic()`` / ``cc.sleep()`` where a blocked thread or timer
  is involved;
- primitives constructed before ``install()`` (module-import-time
  globals) stay real — the analyzer serializes execution, so a real,
  uncontended lock inside virtualized code is benign;
- never cache ``cc.Thread`` etc. into a local/module alias at import
  time (that would freeze the provider choice); call through the
  module.

jax-free and stdlib-only: the resilience and analysis layers import
this while the accelerator runtime may be the thing being debugged.
"""

from __future__ import annotations

import queue as _queue
import threading as _threading
import time as _time

__all__ = [
    "Thread", "Timer", "Lock", "RLock", "Condition", "Event", "Queue",
    "monotonic", "perf_counter", "sleep", "current_thread", "main_thread",
    "get_ident", "enumerate_threads", "install", "uninstall", "provider",
    "Empty", "Full",
]

# re-exported so `except cc.Empty` works against both real and virtual
# queues (the virtual Queue raises the REAL queue module's exceptions)
Empty = _queue.Empty
Full = _queue.Full


class _RealProvider:
    """The stdlib, behind the seam's call signatures."""

    Thread = _threading.Thread
    Timer = _threading.Timer
    Lock = staticmethod(_threading.Lock)
    RLock = staticmethod(_threading.RLock)
    Condition = _threading.Condition
    Event = _threading.Event
    Queue = _queue.Queue
    monotonic = staticmethod(_time.monotonic)
    perf_counter = staticmethod(_time.perf_counter)
    sleep = staticmethod(_time.sleep)
    current_thread = staticmethod(_threading.current_thread)
    main_thread = staticmethod(_threading.main_thread)
    get_ident = staticmethod(_threading.get_ident)
    enumerate_threads = staticmethod(_threading.enumerate)


_REAL = _RealProvider()
_provider = _REAL


def install(p) -> None:
    """Swap the provider (the race analyzer's virtualized primitives).
    Affects only primitives constructed AFTER this call; process-global,
    so callers own the install/uninstall bracket (the analyzer brackets
    every schedule)."""
    global _provider
    _provider = p


def uninstall() -> None:
    global _provider
    _provider = _REAL


def provider():
    return _provider


# ------------------------------------------------------------ constructors
#
# Plain functions (not aliases): the provider is resolved at CALL time,
# so an installed shim governs primitives made anywhere downstream.


def Thread(*args, **kwargs):
    return _provider.Thread(*args, **kwargs)


def Timer(*args, **kwargs):
    return _provider.Timer(*args, **kwargs)


def Lock():
    return _provider.Lock()


def RLock():
    return _provider.RLock()


def Condition(lock=None):
    return _provider.Condition(lock)


def Event():
    return _provider.Event()


def Queue(maxsize: int = 0):
    return _provider.Queue(maxsize)


# ------------------------------------------------------------------ clocks


def monotonic() -> float:
    return _provider.monotonic()


def perf_counter() -> float:
    return _provider.perf_counter()


def sleep(seconds: float) -> None:
    _provider.sleep(seconds)


# --------------------------------------------------------------- thread ids


def current_thread():
    return _provider.current_thread()


def main_thread():
    return _provider.main_thread()


def get_ident():
    return _provider.get_ident()


def enumerate_threads():
    return _provider.enumerate_threads()
