"""Global process flags.

Analog of the reference's gflags layer (/root/reference/paddle/utils/
Flags.cpp:19-68 and CommandLineParser.h). One flat namespace consumed by the
CLI and the trainer; programs may also set them directly
(``FLAGS.use_tpu = True``). GPU-era flags that have no TPU meaning
(nics/rdma/ports_num...) are intentionally absent; their roles are served by
the mesh spec (see paddle_tpu.parallel).
"""

from __future__ import annotations

import argparse
from dataclasses import dataclass, field, fields
from typing import List, Optional


@dataclass
class _Flags:
    # device / mesh
    use_tpu: bool = True                 # reference: -use_gpu
    trainer_count: int = 0               # >1 = data=N mesh; 0/1 = single program
                                         # (reference: -trainer_count; use --mesh_shape
                                         # for multi-axis parallelism)
    mesh_shape: str = ""                 # e.g. "data=8" or "data=4,model=2"
    # jobs
    job: str = "train"                   # train | test | checkgrad
    config: str = ""                     # user config script
    config_args: str = ""                # k=v,k2=v2 passed to the config
    # training control
    num_passes: int = 100
    start_pass: int = 0
    test_period: int = 0                 # batches; 0 = test at pass end
    log_period: int = 100
    dot_period: int = 1
    saving_period: int = 1               # passes between checkpoints
    saving_period_by_batches: int = 0
    # preemption-aware checkpoint: SIGTERM during train() saves at the
    # next launch boundary and exits cleanly (TPU pods preempt with a
    # SIGTERM notice; resume via --init_model_path + --start_pass)
    save_on_preempt: bool = True
    save_dir: str = ""
    # a pass dir, or "auto": restore the newest checkpoint under
    # save_dir that passes manifest verification (fresh start when none)
    init_model_path: str = ""
    load_missing_parameter_strategy: str = "fail"   # fail | rand | zero
    show_parameter_stats_period: int = 0
    test_pass: int = -1
    test_wait: bool = False
    predict_output_dir: str = ""
    gen_result: str = ""                 # gen job output file (overrides config)
    # profiling (the reference's WITH_TIMER/BarrierStat analogs ride the
    # jax profiler: xplane traces with the stat_timer scope annotations)
    profile_dir: str = ""                # write a profiler trace here
    profile_start_batch: int = 5
    profile_num_batches: int = 10
    # observability (doc/observability.md): per-host structured telemetry.
    # metrics_path: run dir for the append-only metrics.jsonl stream
    # (empty = use --save_dir when set, else telemetry off);
    # trace_events_path: export stat_timer scopes as Chrome trace-event
    # JSON here (host-side spans; composes with --profile_dir's device
    # xplanes via the shared scope names)
    metrics_path: str = ""
    trace_events_path: str = ""
    # persistent XLA compilation cache (doc/observability.md "Compile
    # telemetry"): compiled launch groups are cached here across
    # processes, so elastic relaunches and repeat runs skip the XLA
    # backend compile of unchanged steps — compile records then show
    # cache_hit=true and the restart record a lower
    # time_to_first_step_s ("" disables; point every host of a pod at a
    # shared dir)
    compile_cache_dir: str = ""
    # resilience (doc/resilience.md)
    # fault injection: site=action[:arg][@trigger];... (see
    # paddle_tpu/resilience/faultinject.py; PADDLE_TPU_FAULTS env also works)
    fault_spec: str = ""
    fault_seed: int = 0
    # data-pipeline watchdog: no provider progress (not even one SAMPLE
    # pulled) for this many seconds raises DataStallError instead of
    # hanging (0 disables). Generous default: 30 min of true dead air is
    # indistinguishable from a hang
    data_stall_timeout: float = 1800.0
    # host-overlap knobs (doc/performance.md "Zero-stall host"):
    # async_checkpoint moves checkpoint serialize/fsync/rename off the
    # step loop onto a background writer — save() only pays the
    # device→host snapshot; ckpt_inflight_limit bounds queued background
    # saves (drop-oldest-pending beyond it). data_packer_threads packs
    # batches on an N-thread pool (the native C packers release the
    # GIL); prefetch_depth is the order-preserving packed-batch queue
    # depth between the packers and the step loop.
    async_checkpoint: bool = False
    ckpt_inflight_limit: int = 1
    # multi-process async saves: how long drain()'s pass-end commit
    # agreement (host KV rendezvous) waits for the slowest peer's
    # background shard write before declaring the pod torn
    ckpt_agree_timeout: float = 600.0
    data_packer_threads: int = 2
    prefetch_depth: int = 4
    # skip-and-log up to N malformed samples per provider, then fail
    # (0 = fail on the first one, the old behavior)
    max_bad_samples: int = 0
    # shared transient-I/O retry policy (checkpoint I/O, provider reads):
    # exponential backoff from io_retry_base_delay, capped attempts and
    # total elapsed seconds
    io_retry_attempts: int = 4
    io_retry_base_delay: float = 0.25
    io_retry_deadline: float = 120.0
    # divergence policy: what a non-finite (NaN/Inf) training loss does.
    # abort = raise NonFiniteLossError immediately (the reference's FP
    # trap); skip = discard the poisoned update and continue; rollback =
    # restore the newest verified checkpoint, scale the learning rate by
    # rollback_lr_scale, and fast-forward past the poison region. skip
    # and rollback disable step-buffer donation (~2x parameter memory)
    # and are bounded by max_nonfinite_steps total events per run.
    nonfinite_policy: str = "abort"      # abort | skip | rollback
    max_nonfinite_steps: int = 3
    rollback_lr_scale: float = 0.5
    # per-layer model-health telemetry (observability/numerics.py):
    # every N batches, read back the in-step health aux (grad norm /
    # param norm / update ratio / nonfinite count per layer — computed
    # inside the jitted step, so enabling it never recompiles) and emit
    # a kind=numerics record. 0 disables (no aux, no readback).
    numerics_log_period: int = 0
    # row-sharded sparse-parameter training (paddle_tpu/sparse/,
    # doc/sparse.md): sparse_row_budget caps how many embedding-table
    # rows one host may hold (0 = unlimited) — the trainer refuses to
    # start, and cluster_launch refuses a relaunch round, when the
    # host set cannot hold every sparse_update table within the
    # budget; sparse_total_rows declares the largest table's row count
    # to the (jax-free) cluster_launch supervisor so it can apply the
    # same refusal without importing the model config
    sparse_row_budget: int = 0
    sparse_total_rows: int = 0
    # hang defense (resilience/hangwatch.py): no step-loop progress for
    # this many seconds dumps all thread stacks + telemetry tail into
    # hang_report.json and exits EXIT_HANG=19 (0 disables). Set it
    # comfortably above the worst-case launch + in-pass save/test time.
    step_hang_timeout: float = 0.0
    # cluster liveness (resilience/heartbeat.py): each host renews a
    # heartbeat file under heartbeat_dir (default <save_dir>/heartbeats)
    # every heartbeat_interval seconds (0 disables); an observer
    # (cluster_launch) declares a host wedged after heartbeat_stale_after
    # seconds of silence (0 = 3x the interval)
    heartbeat_interval: float = 0.0
    heartbeat_stale_after: float = 0.0
    heartbeat_dir: str = ""
    # run supervision (`paddle supervise`, resilience/supervisor.py):
    # restart a dead `paddle train` child with exponential backoff and
    # --init_model_path=auto, at most restart_budget times; repeated
    # death at the same restored checkpoint for crash_loop_threshold
    # consecutive attempts is classified as poison (stop + JSON crash
    # report under supervise_dir, default <save_dir>/supervise)
    restart_budget: int = 5
    restart_base_delay: float = 1.0
    crash_loop_threshold: int = 3
    supervise_dir: str = ""
    # print the child command + restart policy without launching
    # (`paddle supervise --dry_run`)
    dry_run: bool = False
    # serving (`paddle serve`, paddle_tpu/serving/, doc/serving.md):
    # the continuous-batching engine holds serve_slots concurrent
    # decode sequences in donated device buffers; serve_queue_cap
    # rejects submits past the bound (0 = unbounded queue);
    # serve_request_timeout is each request's wall-clock deadline from
    # submission — expiry frees the queue entry or the decode slot at
    # the next iteration boundary (outcome=timeout);
    # serve_prompt_tokens is the fixed prompt padding width (ONE
    # prefill signature — longer prompts truncate); serve_decode_block
    # is the decode-block LADDER — decode micro-steps per launch, a
    # single int or a comma list like "1,2,4,8" the engine's adaptive
    # policy picks from per iteration (amortizes dispatch; admission/
    # eviction happen at block boundaries; one compiled signature
    # covers the whole ladder); serve_pipeline overlaps host scheduling
    # with the in-flight decode launch (dispatch/collect split — off =
    # the serial PR-12 loop, the A/B baseline); serve_fused_step swaps
    # the per-step graph walk for the extracted attention-GRU step math
    # (ops/pallas_attention_gru.attention_gru_step; template-matched,
    # token-parity-pinned, refuses non-matching models loudly)
    serve_slots: int = 8
    serve_queue_cap: int = 0
    serve_request_timeout: float = 60.0
    serve_prompt_tokens: int = 32
    serve_decode_block: str = "1"
    serve_pipeline: bool = True
    serve_fused_step: bool = False
    # speculative decode + slot-state precision (doc/serving.md
    # "Speculative decode" / "Reduced-precision slot state"):
    # serve_spec_tokens is the draft-length LADDER — max draft tokens
    # per verify launch, a single int or comma list like "2,4" the
    # engine's acceptance-EMA policy picks from ("0" disables; drafts
    # come from a host-side n-gram table fed by committed tokens, ONE
    # fused serve_verify signature covers the whole ladder, greedy
    # output is bit-identical to plain decode); serve_slot_dtype
    # stores GRU carries + captured statics in f32 or bf16 (compute
    # stays f32 — bf16 roughly halves per-slot HBM so --serve_slots
    # can double at fixed footprint, token parity within tolerance)
    serve_spec_tokens: str = "0"
    serve_slot_dtype: str = "f32"
    # serving resilience (doc/resilience.md "Serving resilience"):
    # serve_hang_timeout — no collect-boundary progress for this many
    # seconds dumps serve_hang_report.json (thread stacks + in-flight
    # cohort), answers in-flight requests outcome=error, exits 19
    # (0 disables); serve_shed_policy — off | deadline (shed queued
    # requests whose deadline the measured prefill+decode estimate
    # can't cover, at admission) | brownout (deadline + sustained
    # queue-pressure EMA caps output budgets and sheds new arrivals
    # with a retry-after hint); serve_breaker_threshold — N consecutive
    # launch faults open a reject-fast circuit breaker for
    # serve_breaker_cooldown seconds (0 disables);
    # serve_journal_path — durable JSONL request journal: accepted
    # requests are fsynced before submission and re-offered on restart
    # (at-least-once, dedupe by id); status_path — atomic health JSON
    # renewed every second (queue depth, occupancy, last-collect age,
    # shed/error totals, draining) for load-balancer probes and
    # `paddle serve-status`
    serve_hang_timeout: float = 0.0
    serve_shed_policy: str = "off"
    serve_breaker_threshold: int = 0
    serve_breaker_cooldown: float = 30.0
    serve_journal_path: str = ""
    status_path: str = ""
    # serving fleet (`paddle serve-fleet`, serving/fleet.py, doc/
    # serving.md "Serving fleet"): fleet_replicas `paddle serve`
    # children behind one stdin-JSONL router balancing on each
    # replica's health JSON; fleet_status_dir holds the per-replica
    # status/journal/metrics files (default <save_dir>/fleet_status) —
    # also what `paddle serve-status <dir>` aggregates;
    # serve_reload_watch — a checkpoint save_dir each replica watches:
    # when a NEWER durable (manifest-verified) checkpoint lands there,
    # weights hot-swap at the next iteration boundary without dropping
    # in-flight or queued requests ("" disables)
    fleet_replicas: int = 2
    fleet_status_dir: str = ""
    serve_reload_watch: str = ""
    # cross-host fleet (serving/transport.py, doc/serving.md "Cross-host
    # fleet"): listen — `paddle serve --listen HOST:PORT` accepts
    # length-prefixed JSON frames over TCP instead of stdin JSONL (same
    # journal/dedupe/drain contract; port 0 = ephemeral, the bound
    # address is printed on stderr); replica_addr — `paddle serve-fleet
    # --replica_addr HOST:PORT` (repeatable, or one comma list) routes
    # to remote listeners through SocketReplica instead of spawning
    # pipe children (reconnect/backoff via the --io_retry_* policy);
    # hedge_after — a request outstanding on one replica longer than
    # max(hedge_after, adaptive p99 of observed answer latency) seconds
    # is re-sent to the next-healthiest replica, first answer wins
    # (0 disables hedging; works for pipe and socket fleets alike)
    listen: str = ""
    replica_addr: str = ""
    hedge_after: float = 0.0
    # `paddle supervise` child job: train (default) or serve — a serve
    # child keeps its args on restart (no --init_model_path=auto
    # injection; the request journal is its resume state) and its
    # crash-loop probe reads journal progress instead of checkpoints
    supervise_job: str = "train"
    # rng
    seed: int = 1
    # distributed (multi-host jax)
    coordinator_address: str = ""
    num_processes: int = 1
    process_id: int = 0
    # misc
    use_double: bool = False                 # reference: WITH_DOUBLE build

    def parse(self, argv: Optional[List[str]] = None) -> List[str]:
        """Parse known flags from argv (``--flag=value`` style); returns leftovers."""
        p = argparse.ArgumentParser(add_help=False)
        for f in fields(self):
            if f.type == "bool" or isinstance(getattr(self, f.name), bool):
                p.add_argument(f"--{f.name}", type=_parse_bool, default=getattr(self, f.name))
            else:
                p.add_argument(f"--{f.name}", type=type(getattr(self, f.name)), default=getattr(self, f.name))
        ns, rest = p.parse_known_args(argv)
        for f in fields(self):
            setattr(self, f.name, getattr(ns, f.name))
        return rest


def _parse_bool(v: str) -> bool:
    return str(v).lower() in ("1", "true", "yes", "on")


def flag_value(argv: List[str], name: str, default: str = "") -> str:
    """Last occurrence of ``--name=value`` / ``--name value`` in an argv
    list, without a full parse. Used by wrappers (cluster_launch) that
    forward train flags verbatim but need to READ a few of them — e.g.
    the heartbeat settings — so there is exactly one source of truth:
    the flags the trainers themselves will run with."""
    out = default
    for i, a in enumerate(argv):
        if a == f"--{name}":
            if i + 1 < len(argv):
                out = argv[i + 1]
        elif a.startswith(f"--{name}="):
            out = a[len(name) + 3:]
    return out


def flag_values(argv: List[str], name: str) -> List[str]:
    """Every occurrence of ``--name=value`` / ``--name value`` in an argv
    list, in order, with comma lists split. The repeatable-flag
    companion to :func:`flag_value` — e.g. ``paddle serve-fleet
    --replica_addr h1:9000 --replica_addr h2:9000`` (or the equivalent
    ``--replica_addr h1:9000,h2:9000``) yields both addresses."""
    out: List[str] = []
    for i, a in enumerate(argv):
        v = None
        if a == f"--{name}":
            if i + 1 < len(argv):
                v = argv[i + 1]
        elif a.startswith(f"--{name}="):
            v = a[len(name) + 3:]
        if v is not None:
            out.extend(p for p in (s.strip() for s in v.split(",")) if p)
    return out


def strip_flag(argv: List[str], name: str) -> List[str]:
    """Remove every occurrence of ``--name=value`` / ``--name value``
    from an argv list. Shared by the restart paths (supervisor, cluster
    launcher) that replace a user's flag with their own — e.g. swapping
    ``--init_model_path`` for ``auto`` on relaunch."""
    out: List[str] = []
    skip_next = False
    for a in argv:
        if skip_next:
            skip_next = False
            continue
        if a == f"--{name}":
            skip_next = True  # the space-separated value form
            continue
        if a.startswith(f"--{name}="):
            continue
        out.append(a)
    return out


FLAGS = _Flags()
