"""Layer-stack error context.

Analog of the reference's ``CustomStackTrace``
(/root/reference/paddle/utils/CustomStackTrace.h:55): while compiling or
executing a layer graph we push the layer name so failures report *which
layer* broke, not just a jax traceback.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Iterator, List

_tls = threading.local()


def current_layer_stack() -> List[str]:
    return list(getattr(_tls, "stack", []))


@contextlib.contextmanager
def layer_scope(name: str) -> Iterator[None]:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append(name)
    try:
        yield
    except Exception as e:
        if not getattr(e, "_pt_layer_stack_noted", False):
            e._pt_layer_stack_noted = True
            e.args = (
                (f"{e.args[0] if e.args else ''} [layer stack: {' -> '.join(stack)}]",)
                + tuple(e.args[1:])
            )
        raise
    finally:
        stack.pop()
