"""Backend hardening for driver-facing entry points.

The environment may pre-register an accelerator PJRT plugin (e.g. an
'axon' TPU tunnel) via sitecustomize at interpreter start. When that
backend is unavailable, any jax call that initializes backends either
raises UNAVAILABLE or hangs — which is how the round-1 driver gates
failed. This module gives every entry point (tests, bench, dryrun) one
defensive routine: force the CPU platform with N virtual devices and
drop non-CPU backend factories *before* any backend initializes, even
if jax was already imported (sitecustomize imports it too early for
env vars alone to work).

Role analog in the reference: the CPU-only stub build
(/root/reference/paddle/cuda/include/stub/) that lets everything run
without accelerators.

This module deliberately does NOT retry a hung accelerator claim
through ``paddle_tpu.utils.retry.RetryPolicy``: a claimant must be
abandoned, never re-driven (see run_abandoning) — retrying the claim
is exactly what wedges the tunnel. RetryPolicy is for transient
*completing* failures (shared-FS I/O, flaky providers).
"""

from __future__ import annotations

import os


def ensure_cpu_mesh(n_devices: int = 8) -> None:
    """Force jax onto the CPU platform with >= n_devices virtual devices.

    Safe to call multiple times; safe whether or not jax backends have
    already initialized (re-initializes them if the current platform or
    device count is wrong). Keeps the 'tpu' factory registered so
    pallas/checkify lowering rules stay importable — it never
    initializes under JAX_PLATFORMS=cpu.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags = (flags + f" --xla_force_host_platform_device_count={n_devices}").strip()
        os.environ["XLA_FLAGS"] = flags

    import jax
    import jax._src.xla_bridge as _xb

    # jax may have been imported by sitecustomize before our env vars
    # were set — override the already-read config directly.
    jax.config.update("jax_platforms", "cpu")

    for _name in list(_xb._backend_factories):
        if _name not in ("cpu", "tpu"):
            del _xb._backend_factories[_name]

    try:
        devices = jax.devices()
    except Exception:
        devices = []
    if len(devices) < n_devices or any(d.platform != "cpu" for d in devices):
        # Backends initialized before the guard (wrong platform or too few
        # virtual devices) — drop them and re-initialize under the forced
        # config. Best-effort: _clear_backends is internal but stable.
        os.environ["XLA_FLAGS"] = _with_device_count(flags, n_devices)
        try:
            _xb._clear_backends()
        except Exception:
            pass
        devices = jax.devices()
    if len(devices) < n_devices:
        raise RuntimeError(
            f"backend guard could not provision {n_devices} CPU devices; "
            f"got {devices}"
        )


def _with_device_count(flags: str, n: int) -> str:
    parts = [p for p in flags.split() if "xla_force_host_platform_device_count" not in p]
    parts.append(f"--xla_force_host_platform_device_count={n}")
    return " ".join(parts)


def run_abandoning(cmd, timeout_s, env=None, signal_if=None):
    """Like run_graceful but NEVER signals a timed-out child: a hung
    accelerator claimant that gets SIGTERM/SIGKILLed mid-claim wedges the
    tunnel for every later claim (~25-minute rejections), which is worse
    than letting it finish its own rejection as an orphan. On timeout the
    child is abandoned — a daemon thread keeps draining its pipes so it
    can't block, and it exits on its own once the claim resolves.

    ``signal_if(stdout_so_far, stderr_so_far) -> bool`` carves out the
    one case where signaling IS safe: a timed-out child that provably
    never touched the accelerator (e.g. it printed its forced-CPU
    backend decision) is merely slow, not hung in a claim — terminating
    it frees the cores for the retry instead of running both
    concurrently.

    Returns (returncode|None, stdout, stderr); returncode None = timeout,
    with whatever output had arrived by then (reader threads drain the
    pipes incrementally, so partial results — e.g. a bench headline
    emitted before a later leg hung — are still salvaged)."""
    import subprocess
    import threading
    from paddle_tpu.utils import concurrency as cc

    proc = subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env
    )
    bufs = {"out": [], "err": []}

    def _reader(stream, key):
        for line in stream:
            bufs[key].append(line)

    threads = [
        cc.Thread(target=_reader, args=(proc.stdout, "out"), daemon=True),
        cc.Thread(target=_reader, args=(proc.stderr, "err"), daemon=True),
    ]
    for t in threads:
        t.start()
    rc: "int | None"
    try:
        rc = proc.wait(timeout=timeout_s)
        for t in threads:  # streams hit EOF at exit; finish the drain
            t.join(timeout=5)
    except subprocess.TimeoutExpired:
        rc = None  # abandoned: threads keep draining, child exits on its own
        for t in threads:  # brief join so already-written output lands
            t.join(timeout=0.5)
        if signal_if and signal_if("".join(bufs["out"]), "".join(bufs["err"])):
            proc.terminate()  # provably claim-free child: safe to stop
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()  # e.g. stuck in an uninterruptible native call
                try:
                    proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    pass
            for t in threads:
                t.join(timeout=5)
    return rc, "".join(bufs["out"]), "".join(bufs["err"])


def probe_backend(timeout_s: float = 180.0) -> str:
    """Report which jax backend a fresh process can actually initialize.

    Runs the probe in a subprocess so a hanging accelerator plugin (the
    round-1 failure mode: axon tunnel up but chip unreachable) cannot
    wedge the caller. A probe that exceeds timeout_s is ABANDONED, never
    killed — see run_abandoning. Returns the backend platform name
    ('tpu', 'cpu', ...) on success, or 'cpu' on failure/timeout.
    """
    import sys

    code = "import jax; print(jax.default_backend())"
    rc, out, _ = run_abandoning([sys.executable, "-c", code], timeout_s)
    if rc != 0:
        return "cpu"
    backend = out.strip().splitlines()[-1] if out.strip() else ""
    return backend or "cpu"
