"""Convert torch model weights into a loadable checkpoint.

Role analog of the reference's python/paddle/utils/torch2paddle.py (which
read torchfile .t7 archives and wrote per-parameter binary files); this
version reads what today's torch ecosystem actually produces — a .pt/.pth
file holding a state_dict (name -> tensor) or a plain list of tensors —
and writes a pass-00000 checkpoint that --init_model_path loads.

Mapping follows the reference's contract: a layers file lists the target
layer names IN ORDER; tensors pair up as (weight, bias) per layer.
Layout conversion per tensor rank:
  2-D  torch Linear [out, in]      -> transposed to our fc w0 [in, out]
  4-D  torch Conv2d [O, I, kh, kw] -> flattened to [O, I*kh*kw] (the
       reference conv parameter layout our conv layers reshape from,
       layers/vision.py)
  1-D  bias -> wbias unchanged

Usage:
  python -m paddle_tpu.utils.torch2paddle -i model.pth -l layers.txt -o out_dir
Then: bin/paddle train --init_model_path=out_dir/pass-00000 ...
"""

from __future__ import annotations

import argparse
import sys

import numpy as np


def convert_tensor(name: str, t) -> np.ndarray:
    a = np.asarray(t, dtype=np.float32)
    if a.ndim == 2:
        return a.T.copy()  # torch Linear [out,in] -> ours [in,out]
    if a.ndim == 4:
        return a.reshape(a.shape[0], -1).copy()  # OIHW -> [O, I*kh*kw]
    if a.ndim == 1:
        return a
    raise ValueError(f"{name}: unsupported tensor rank {a.ndim} (shape {a.shape})")


def convert(tensors, layer_names) -> dict:
    """tensors: ordered list of arrays, (weight, bias) per layer name.
    Returns the params dict ({_<layer>.w0, _<layer>.wbias})."""
    if len(tensors) != 2 * len(layer_names):
        raise ValueError(
            f"{len(tensors)} tensors for {len(layer_names)} layers — expected "
            "one (weight, bias) pair per layer"
        )
    params = {}
    for i, layer in enumerate(layer_names):
        w, b = tensors[2 * i], tensors[2 * i + 1]
        params[f"_{layer}.w0"] = convert_tensor(f"{layer}.weight", w)
        params[f"_{layer}.wbias"] = convert_tensor(f"{layer}.bias", b)
    return params


def load_tensors(path: str):
    """Ordered tensor list from a .pt/.pth state_dict or tensor list.
    Unwraps the common {'state_dict': ...} checkpoint wrapper and skips
    non-tensor / scalar entries (epoch counters, num_batches_tracked)
    with a note instead of crashing on them."""
    import torch

    obj = torch.load(path, map_location="cpu", weights_only=True)
    if isinstance(obj, dict):
        for wrapper_key in ("state_dict", "model_state_dict", "model"):
            if isinstance(obj.get(wrapper_key), dict):
                obj = obj[wrapper_key]
                break
        out = []
        for k, v in obj.items():
            if not hasattr(v, "numpy") or getattr(v, "ndim", 0) == 0:
                print(f"skipping non-parameter entry {k!r}", file=sys.stderr)
                continue
            out.append(v.numpy())
        return out
    return [np.asarray(v) for v in obj]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-i", "--input", required=True, help=".pt/.pth torch weights")
    ap.add_argument("-l", "--layers", required=True,
                    help="file listing target layer names, one per line, in order")
    ap.add_argument("-o", "--output", required=True, help="checkpoint save_dir")
    args = ap.parse_args(argv)

    with open(args.layers) as f:
        layer_names = [ln.strip() for ln in f if ln.strip()]
    params = convert(load_tensors(args.input), layer_names)

    from paddle_tpu.utils.backend_guard import ensure_cpu_mesh

    ensure_cpu_mesh(1)
    from paddle_tpu.trainer.checkpoint import save_checkpoint

    path = save_checkpoint(args.output, 0, params, extra_meta={"source": "torch2paddle"})
    print(f"wrote {len(params)} parameters to {path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
