"""Name → factory registries.

TPU-native analog of the reference's ``ClassRegistrar``
(/root/reference/paddle/utils/ClassRegistrar.h): layer types, activations,
evaluators, data providers and optimizers all register themselves by name so
config-driven construction can look them up.
"""

from __future__ import annotations

from typing import Callable, Dict, Generic, Iterable, TypeVar

T = TypeVar("T")


class Registry(Generic[T]):
    """A simple name→object registry with decorator-style registration."""

    def __init__(self, kind: str):
        self.kind = kind
        self._entries: Dict[str, T] = {}

    def register(self, *names: str) -> Callable[[T], T]:
        def deco(obj: T) -> T:
            for name in names:
                if name in self._entries:
                    raise KeyError(f"duplicate {self.kind} registration: {name!r}")
                self._entries[name] = obj
            return obj

        return deco

    def register_obj(self, name: str, obj: T) -> None:
        if name in self._entries:
            raise KeyError(f"duplicate {self.kind} registration: {name!r}")
        self._entries[name] = obj

    def get(self, name: str) -> T:
        try:
            return self._entries[name]
        except KeyError:
            known = ", ".join(sorted(self._entries))
            raise KeyError(
                f"unknown {self.kind} {name!r}; registered: [{known}]"
            ) from None

    def __contains__(self, name: str) -> bool:
        return name in self._entries

    def names(self) -> Iterable[str]:
        return sorted(self._entries)
