from paddle_tpu.utils.registry import Registry
from paddle_tpu.utils.logging import logger
from paddle_tpu.utils.stats import stat_timer, global_stats
from paddle_tpu.utils.flags import FLAGS

__all__ = ["Registry", "logger", "stat_timer", "global_stats", "FLAGS"]
