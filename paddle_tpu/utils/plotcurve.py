"""Plot cost curves from training logs
(ref: python/paddle/utils/plotcurve.py — reads trainer log lines and
plots AvgCost and any named evaluator over passes).

Usage:
    python -m paddle_tpu.utils.plotcurve [-o out.png] [key ...] < train.log
Keys default to AvgCost; any `name=value` token in "Pass N done" lines
can be named (e.g. classification_error). Without matplotlib, prints an
ASCII curve instead.
"""

from __future__ import annotations

import argparse
import re
import sys
from typing import Dict, List

PASS_RE = re.compile(r"Pass (\d+) done: (.*)")
KV_RE = re.compile(r"([A-Za-z_][\w.]*)=([-+0-9.eE]+)")


def parse_log(lines) -> Dict[str, List[float]]:
    """pass-indexed series for every name=value on 'Pass N done' lines."""
    series: Dict[str, List[float]] = {}
    for line in lines:
        m = PASS_RE.search(line)
        if not m:
            continue
        for key, val in KV_RE.findall(m.group(2)):
            try:
                series.setdefault(key, []).append(float(val))
            except ValueError:
                pass
    return series


def ascii_plot(ys: List[float], width: int = 60, height: int = 12) -> str:
    if not ys:
        return "(no data)"
    lo, hi = min(ys), max(ys)
    span = (hi - lo) or 1.0
    rows = [[" "] * width for _ in range(height)]
    for i, y in enumerate(ys):
        x = int(i * (width - 1) / max(len(ys) - 1, 1))
        r = int((hi - y) * (height - 1) / span)
        rows[r][x] = "*"
    out = [f"{hi:10.4g} ┐"]
    out += ["           │" + "".join(r) for r in rows]
    out += [f"{lo:10.4g} ┘" + f"  (passes 0..{len(ys)-1})"]
    return "\n".join(out)


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("keys", nargs="*", default=[])
    p.add_argument("-i", "--input", default="-", help="log file (default stdin)")
    p.add_argument("-o", "--output", default="", help="png path (matplotlib)")
    args = p.parse_args(argv)

    lines = sys.stdin if args.input == "-" else open(args.input)
    series = parse_log(lines)
    keys = args.keys or (["AvgCost"] if "AvgCost" in series else sorted(series)[:1])
    missing = [k for k in keys if k not in series]
    if missing:
        print(f"keys not found in log: {missing}; have {sorted(series)}", file=sys.stderr)
        return 1
    if args.output:
        try:
            import matplotlib

            matplotlib.use("Agg")
            import matplotlib.pyplot as plt
        except ImportError:
            print("matplotlib unavailable; use ASCII mode (no -o)", file=sys.stderr)
            return 1
        for k in keys:
            plt.plot(series[k], label=k)
        plt.xlabel("pass")
        plt.legend()
        plt.savefig(args.output)
        print(f"wrote {args.output}")
    else:
        for k in keys:
            print(f"== {k} ==")
            print(ascii_plot(series[k]))
    return 0


if __name__ == "__main__":
    import signal

    if hasattr(signal, "SIGPIPE"):
        signal.signal(signal.SIGPIPE, signal.SIG_DFL)
    sys.exit(main())
