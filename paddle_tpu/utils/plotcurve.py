"""Plot cost curves from run telemetry or training logs
(ref: python/paddle/utils/plotcurve.py — reads trainer log lines and
plots AvgCost and any named evaluator over passes).

Usage:
    python -m paddle_tpu.utils.plotcurve [-o out.png] [key ...] < train.log
    python -m paddle_tpu.utils.plotcurve -i <run_dir> AvgCost

When the input is a run dir (or a metrics*.jsonl file), the structured
``pass_end`` records are the source — no regex scraping (see
doc/observability.md). The legacy "Pass N done" log-scraping path stays
as the fallback for plain log files and stdin, so curves from
pre-telemetry runs keep plotting. Keys default to AvgCost; any numeric
field of the pass_end record (or `name=value` log token) can be named
(e.g. classification_error, step_time_p99_s). Without matplotlib,
prints an ASCII curve instead.
"""

from __future__ import annotations

import argparse
import os
import re
import sys
from typing import Dict, List

PASS_RE = re.compile(r"Pass (\d+) done: (.*)")
KV_RE = re.compile(r"([A-Za-z_][\w.]*)=([-+0-9.eE]+)")


def parse_log(lines) -> Dict[str, List[float]]:
    """pass-indexed series for every name=value on 'Pass N done' lines."""
    series: Dict[str, List[float]] = {}
    for line in lines:
        m = PASS_RE.search(line)
        if not m:
            continue
        for key, val in KV_RE.findall(m.group(2)):
            try:
                series.setdefault(key, []).append(float(val))
            except ValueError:
                pass
    return series


def parse_metrics(run_dir: str) -> Dict[str, List[float]]:
    """pass-indexed series from metrics.jsonl ``pass_end`` records (host
    0's stream when several exist — costs are identical across hosts)."""
    from paddle_tpu.observability import metrics as obs

    by_pass: Dict[int, Dict[str, float]] = {}
    for path in obs.metrics_files(run_dir):
        for rec in obs.read_records(path):
            if rec.get("kind") != "pass_end" or rec.get("host", 0) != 0:
                continue
            fields = {
                k: float(v) for k, v in rec.items()
                if isinstance(v, (int, float)) and not isinstance(v, bool)
                and k not in ("v", "host", "pass", "t")
            }
            by_pass[int(rec.get("pass", len(by_pass)))] = fields
    # every series spans the SAME pass axis: a field absent from some
    # pass (mfu when FLOP accounting failed, an evaluator that didn't
    # run) holds a NaN gap there instead of silently shifting later
    # points left onto the wrong pass
    passes = sorted(by_pass)
    keys = {k for fields in by_pass.values() for k in fields}
    return {
        k: [by_pass[p].get(k, float("nan")) for p in passes] for k in keys
    }


def _is_metrics_input(path: str) -> bool:
    from paddle_tpu.observability import metrics as obs

    if os.path.isdir(path):
        return bool(obs.metrics_files(path))
    # must actually exist: a typo'd .jsonl path falls through to the log
    # path, whose open() raises the honest FileNotFoundError
    return path.endswith(".jsonl") and os.path.isfile(path)


def ascii_plot(ys: List[float], width: int = 60, height: int = 12) -> str:
    finite = [y for y in ys if y == y]  # NaN gaps (see parse_metrics)
    if not finite:
        return "(no data)"
    lo, hi = min(finite), max(finite)
    span = (hi - lo) or 1.0
    rows = [[" "] * width for _ in range(height)]
    for i, y in enumerate(ys):
        if y != y:
            continue  # gap: leave the column empty
        x = int(i * (width - 1) / max(len(ys) - 1, 1))
        r = int((hi - y) * (height - 1) / span)
        rows[r][x] = "*"
    out = [f"{hi:10.4g} ┐"]
    out += ["           │" + "".join(r) for r in rows]
    out += [f"{lo:10.4g} ┘" + f"  (passes 0..{len(ys)-1})"]
    return "\n".join(out)


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("keys", nargs="*", default=[])
    p.add_argument("-i", "--input", default="-", help="log file (default stdin)")
    p.add_argument("-o", "--output", default="", help="png path (matplotlib)")
    args = p.parse_args(argv)

    if args.input != "-" and _is_metrics_input(args.input):
        # structured telemetry preferred; the regex path below stays for
        # plain logs (old runs scrape exactly as before)
        series = parse_metrics(args.input)
    elif args.input != "-" and os.path.isdir(args.input):
        print(f"{args.input} is a directory with no metrics*.jsonl "
              "(pass a log file, or rerun training with --metrics_path)",
              file=sys.stderr)
        return 1
    else:
        lines = sys.stdin if args.input == "-" else open(args.input)
        series = parse_log(lines)
    keys = args.keys or (["AvgCost"] if "AvgCost" in series else sorted(series)[:1])
    missing = [k for k in keys if k not in series]
    if missing:
        print(f"keys not found in log: {missing}; have {sorted(series)}", file=sys.stderr)
        return 1
    if args.output:
        try:
            import matplotlib

            matplotlib.use("Agg")
            import matplotlib.pyplot as plt
        except ImportError:
            print("matplotlib unavailable; use ASCII mode (no -o)", file=sys.stderr)
            return 1
        for k in keys:
            plt.plot(series[k], label=k)
        plt.xlabel("pass")
        plt.legend()
        plt.savefig(args.output)
        print(f"wrote {args.output}")
    else:
        for k in keys:
            print(f"== {k} ==")
            print(ascii_plot(series[k]))
    return 0


if __name__ == "__main__":
    import signal

    if hasattr(signal, "SIGPIPE"):
        signal.signal(signal.SIGPIPE, signal.SIG_DFL)
    sys.exit(main())
