"""Scoped timers aggregated into a global stat set.

TPU-native analog of the reference's ``REGISTER_TIMER`` / ``StatSet``
(/root/reference/paddle/utils/Stat.h:70,127,244): named scopes accumulate
wall-time and call counts, dumped periodically by the trainer. On TPU the
device work is async, so timers around jitted calls measure dispatch unless
you pass ``block=True`` (which block_until_ready's the result); the trainer
uses blocking timers only at log boundaries. Scopes also emit
``jax.profiler.TraceAnnotation`` so they show up in xplane traces.
"""

from __future__ import annotations

import contextlib
import threading
from paddle_tpu.utils import concurrency as cc
import time
from dataclasses import dataclass, field
from typing import Dict, Iterator


@dataclass
class Stat:
    name: str
    total_s: float = 0.0
    count: int = 0
    max_s: float = 0.0
    _lock: object = field(default_factory=cc.Lock, repr=False)

    def add(self, dt: float) -> None:
        with self._lock:
            self.total_s += dt
            self.count += 1
            if dt > self.max_s:
                self.max_s = dt

    @property
    def avg_s(self) -> float:
        return self.total_s / self.count if self.count else 0.0


class StatSet:
    def __init__(self, name: str = "global"):
        self.name = name
        self._stats: Dict[str, Stat] = {}
        self._lock = cc.Lock()

    def get(self, name: str) -> Stat:
        with self._lock:
            st = self._stats.get(name)
            if st is None:
                st = self._stats[name] = Stat(name)
            return st

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()

    def summary(self) -> str:
        with self._lock:
            stats = sorted(self._stats.values(), key=lambda s: -s.total_s)
        if not stats:
            return f"=== StatSet {self.name}: empty ==="
        lines = [f"=== StatSet {self.name} ==="]
        for s in stats:
            lines.append(
                f"  {s.name:<40s} total={s.total_s * 1e3:10.2f}ms "
                f"avg={s.avg_s * 1e3:8.3f}ms max={s.max_s * 1e3:8.3f}ms n={s.count}"
            )
        return "\n".join(lines)


global_stats = StatSet()


@contextlib.contextmanager
def stat_timer(name: str, block_on=None) -> Iterator[None]:
    """Time a scope into ``global_stats``, the jax profiler trace, and —
    when ``--trace_events_path`` configured a collector — the span layer
    (observability/spans.py), where the same named scopes export as
    nested Chrome trace events.

    ``block_on``: optional pytree whose leaves are block_until_ready'd before
    stopping the clock, so device time is included.
    """
    # lazy: importing this module must not pull in jax — the supervisor
    # CLI (`paddle supervise`) imports the utils package and has to stay
    # usable when the accelerator runtime is exactly what keeps crashing
    import jax

    from paddle_tpu.observability import spans

    t0 = time.perf_counter()
    with jax.profiler.TraceAnnotation(name):
        yield
    if block_on is not None:
        jax.block_until_ready(block_on)
    dt = time.perf_counter() - t0
    global_stats.get(name).add(dt)
    spans.record_perf(name, t0, dt)
