"""Image preprocessing & augmentation helpers.

Role analog of the reference's python/paddle/utils/image_util.py:30-101
(resize/flip/crop/mean-subtract/oversample/ImageTransformer) — re-designed
rather than translated:

- every random op takes an explicit ``rng`` (numpy Generator/RandomState);
  nothing reads global numpy random state, so a provider seeded per file
  is bit-reproducible (the reference uses np.random.* globals);
- pure-numpy host-side transforms (this is input-pipeline work that
  overlaps device compute via the feeder's async prefetch; the batched
  on-device rotate/scale perturbation lives in
  paddle_tpu/ops/perturbation.py, the hl_perturbation_util.cu analog);
- PIL-dependent helpers (jpeg decode, file loading, resize) degrade with a
  clear ImportError message instead of importing PIL at module scope.

Layout convention matches the reference: color images are CHW ndarrays
(K x H x W), grayscale are HW.
"""

from __future__ import annotations

import io
from typing import Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "resize_image",
    "flip",
    "crop_img",
    "decode_jpeg",
    "preprocess_img",
    "load_meta",
    "load_image",
    "oversample",
    "ImageTransformer",
]


def _pil_image():
    try:
        from PIL import Image
    except ImportError as e:  # pragma: no cover - PIL is in the base image
        raise ImportError(
            "PIL is required for jpeg/file image helpers "
            "(resize_image/decode_jpeg/load_image)"
        ) from e
    return Image


def resize_image(img, target_size: int):
    """Resize a PIL image so its SHORTER edge equals target_size
    (aspect-preserving, antialiased)."""
    Image = _pil_image()
    percent = target_size / float(min(img.size[0], img.size[1]))
    resized = (int(round(img.size[0] * percent)), int(round(img.size[1] * percent)))
    return img.resize(resized, Image.LANCZOS)


def flip(im: np.ndarray) -> np.ndarray:
    """Mirror an image along the horizontal (width) axis.

    Accepts CHW (K x H x W) or HW ndarrays — width is always the last
    axis under the module's layout convention.
    """
    return im[..., ::-1]


def _rng(rng):
    # None falls back to the module-level global stream (reference
    # behavior); providers should pass a per-file-seeded RandomState
    return np.random if rng is None else rng


def _randint(rng, low, high):
    """[low, high) draw working across the RandomState (randint) and
    Generator (integers) APIs."""
    fn = getattr(rng, "integers", None) or rng.randint
    return int(fn(low, high))


def crop_img(
    im: np.ndarray,
    inner_size: int,
    color: bool = True,
    test: bool = True,
    rng=None,
) -> np.ndarray:
    """Crop to inner_size x inner_size: center crop in test mode, random
    crop + 50% horizontal flip in train mode (test=False).

    Images smaller than inner_size are zero-padded to it first (centered),
    matching the reference's padding semantics. ``rng`` makes train-mode
    randomness explicit and reproducible.
    """
    im = np.asarray(im, dtype=np.float32)
    r = _rng(rng)
    spatial = im.shape[1:] if color else im.shape
    height, width = max(inner_size, spatial[0]), max(inner_size, spatial[1])
    if (height, width) != tuple(spatial):
        pad_shape = (im.shape[0], height, width) if color else (height, width)
        padded = np.zeros(pad_shape, dtype=np.float32)
        y0 = (height - spatial[0]) // 2
        x0 = (width - spatial[1]) // 2
        padded[..., y0 : y0 + spatial[0], x0 : x0 + spatial[1]] = im
        im = padded
    if test:
        start_y = (height - inner_size) // 2
        start_x = (width - inner_size) // 2
    else:
        start_y = _randint(r, 0, height - inner_size + 1)
        start_x = _randint(r, 0, width - inner_size + 1)
    pic = im[..., start_y : start_y + inner_size, start_x : start_x + inner_size]
    if not test and _randint(r, 0, 2) == 0:
        pic = flip(pic)
    return pic


def decode_jpeg(jpeg_string: bytes) -> np.ndarray:
    """Decode an encoded image byte string to a CHW (color) or HW
    (grayscale) ndarray."""
    Image = _pil_image()
    arr = np.array(Image.open(io.BytesIO(jpeg_string)))
    if arr.ndim == 3:
        arr = np.transpose(arr, (2, 0, 1))
    return arr


def preprocess_img(
    im: np.ndarray,
    img_mean: np.ndarray,
    crop_size: int,
    is_train: bool,
    color: bool = True,
    rng=None,
) -> np.ndarray:
    """Standard train/eval image pipeline: crop (random+flip when training,
    center otherwise), subtract the dataset mean, flatten to a feature
    vector. The reference's preprocess_img with explicit rng."""
    pic = crop_img(np.asarray(im, np.float32), crop_size, color, test=not is_train, rng=rng)
    pic = pic - np.asarray(img_mean, np.float32)
    return pic.ravel()


def load_meta(meta_path: str, mean_img_size: int, crop_size: int, color: bool = True) -> np.ndarray:
    """Load the dataset mean image from a meta file and center-crop it to
    crop_size so it aligns with cropped samples.

    Accepts either an .npz/npy-style file with a 'data_mean' entry (our
    converters write np.savez) or a pickled dict with 'data_mean' (the
    reference's cPickle batches.meta format).
    """
    try:
        mean = np.load(meta_path, allow_pickle=True)["data_mean"]
    except Exception:
        import pickle

        with open(meta_path, "rb") as f:
            mean = pickle.load(f, encoding="latin1")["data_mean"]
    mean = np.asarray(mean, np.float32)
    border = (mean_img_size - crop_size) // 2
    if color:
        assert mean.size == 3 * mean_img_size * mean_img_size, mean.shape
        mean = mean.reshape(3, mean_img_size, mean_img_size)
    else:
        assert mean.size == mean_img_size * mean_img_size, mean.shape
        mean = mean.reshape(mean_img_size, mean_img_size)
    return mean[..., border : border + crop_size, border : border + crop_size]


def load_image(img_path: str, is_color: bool = True):
    """Open an image file as a PIL image (converted to RGB or L)."""
    Image = _pil_image()
    img = Image.open(img_path)
    img.load()
    return img.convert("RGB" if is_color else "L")


def oversample(imgs: Sequence[np.ndarray], crop_dims: Tuple[int, int]) -> np.ndarray:
    """10-crop test-time augmentation: 4 corners + center, each mirrored.

    imgs: iterable of HWC ndarrays (the reference's oversample contract).
    Returns (10*N, crop_h, crop_w, K) float32.
    """
    im_shape = np.array(imgs[0].shape)
    crop_dims = np.array(crop_dims)
    center = im_shape[:2] / 2.0
    h_inds = (0, im_shape[0] - crop_dims[0])
    w_inds = (0, im_shape[1] - crop_dims[1])
    crops_ix = np.empty((5, 4), dtype=int)
    curr = 0
    for i in h_inds:
        for j in w_inds:
            crops_ix[curr] = (i, j, i + crop_dims[0], j + crop_dims[1])
            curr += 1
    crops_ix[4] = np.concatenate([center - crop_dims / 2.0, center + crop_dims / 2.0]).astype(int)
    out = np.empty((10 * len(imgs), crop_dims[0], crop_dims[1], im_shape[-1]), np.float32)
    ix = 0
    for im in imgs:
        for y0, x0, y1, x1 in crops_ix:
            out[ix] = im[y0:y1, x0:x1, :]
            ix += 1
        for k in range(5):
            out[ix] = out[ix - 5][:, ::-1, :]
            ix += 1
    return out


class ImageTransformer:
    """Composable inference-time transform: axis transpose, channel swap,
    mean subtraction (reference ImageTransformer contract)."""

    def __init__(self, transpose=None, channel_swap=None, mean=None, is_color: bool = True):
        self.is_color = is_color
        self.transpose = None
        self.channel_swap = None
        self.mean = None
        if transpose is not None:
            self.set_transpose(transpose)
        if channel_swap is not None:
            self.set_channel_swap(channel_swap)
        if mean is not None:
            self.set_mean(mean)

    def set_transpose(self, order):
        if self.is_color:
            assert len(order) == 3
        self.transpose = tuple(order)

    def set_channel_swap(self, order):
        if self.is_color:
            assert len(order) == 3
        self.channel_swap = tuple(order)

    def set_mean(self, mean):
        mean = np.asarray(mean, np.float32)
        if mean.ndim == 1:  # one value per channel
            mean = mean[:, np.newaxis, np.newaxis]
        elif self.is_color:
            assert mean.ndim == 3
        self.mean = mean

    def transformer(self, data: np.ndarray) -> np.ndarray:
        data = np.asarray(data, np.float32)
        if self.transpose is not None:
            data = data.transpose(self.transpose)
        if self.channel_swap is not None:
            data = data[list(self.channel_swap), :, :]
        if self.mean is not None:
            data = data - self.mean
        return data
