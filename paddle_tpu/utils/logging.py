"""Framework logger.

Role of the reference's mini-glog (/root/reference/paddle/utils/Logging.h):
leveled logging plus CHECK-style assertion helpers that attach the current
layer stack (see paddle_tpu.utils.error) to failures.
"""

from __future__ import annotations

import logging
import os
import sys

logger = logging.getLogger("paddle_tpu")

if not logger.handlers:
    _handler = logging.StreamHandler(sys.stderr)
    _handler.setFormatter(
        logging.Formatter("[%(asctime)s %(levelname).1s paddle_tpu] %(message)s", "%H:%M:%S")
    )
    logger.addHandler(_handler)
    logger.setLevel(os.environ.get("PADDLE_TPU_LOG_LEVEL", "INFO").upper())
    logger.propagate = False


def check(cond: bool, msg: str = "") -> None:
    """CHECK(cond) — raise with the layer stack attached on failure."""
    if not cond:
        from paddle_tpu.utils.error import current_layer_stack

        stack = current_layer_stack()
        suffix = f" [layer stack: {' -> '.join(stack)}]" if stack else ""
        raise AssertionError(f"check failed: {msg}{suffix}")
