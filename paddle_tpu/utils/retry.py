"""Shared retry policy: exponential backoff + jitter + deadline.

One policy object serves every transient-failure site — checkpoint I/O
against a shared filesystem and data-provider iteration both retry
through here — so backoff behavior is configured once (``--io_retry_*``
flags) instead of re-invented ad hoc per call site. The L-BFGS
line-search ``backoff`` in ``optimizer/batch_methods.py`` is a numerical
step-shrink factor, not an I/O retry, and deliberately does not use
this.

Two usage shapes::

    policy.call(write_file)              # function-shaped work

    state = policy.begin("read samples") # loop/generator-shaped work
    while True:
        try:
            ...; break
        except policy.retry_on as e:
            state.retry(e)               # sleeps, or re-raises e when
                                         # attempts/deadline exhausted
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Tuple, Type

from paddle_tpu.utils.logging import logger


@dataclass
class RetryPolicy:
    """Exponential backoff: delay = base_delay * multiplier**(attempt-1),
    capped at max_delay, each sleep jittered by ±jitter·delay. A retry is
    abandoned (the error re-raised) after max_attempts total attempts or
    once deadline seconds have elapsed since the first attempt."""

    max_attempts: int = 4
    base_delay: float = 0.25
    max_delay: float = 30.0
    multiplier: float = 2.0
    jitter: float = 0.25
    deadline: float = 0.0  # seconds since first attempt; 0 = none
    retry_on: Tuple[Type[BaseException], ...] = (OSError,)
    name: str = ""
    # injectable for tests (fake clock / no real sleeping)
    sleep: Callable[[float], None] = field(default=time.sleep, repr=False)
    seed: Optional[int] = None  # None = nondeterministic jitter

    def delay_for(self, attempt: int, rng: random.Random) -> float:
        """Sleep before attempt ``attempt+1`` (attempt counts from 1)."""
        d = min(self.base_delay * self.multiplier ** (attempt - 1), self.max_delay)
        if self.jitter > 0:
            d *= 1.0 + self.jitter * (2.0 * rng.random() - 1.0)
        return max(d, 0.0)

    def begin(self, name: str = "") -> "_RetryState":
        return _RetryState(self, name or self.name)

    def call(self, fn: Callable[..., Any], *args, name: str = "", **kwargs) -> Any:
        state = self.begin(name or self.name or getattr(fn, "__name__", "call"))
        while True:
            try:
                return fn(*args, **kwargs)
            except self.retry_on as e:
                state.retry(e)

    @classmethod
    def from_flags(cls, flags, **overrides) -> "RetryPolicy":
        """The process-wide I/O policy (``--io_retry_*``)."""
        kw = dict(
            max_attempts=max(1, int(getattr(flags, "io_retry_attempts", 4))),
            base_delay=float(getattr(flags, "io_retry_base_delay", 0.25)),
            deadline=float(getattr(flags, "io_retry_deadline", 120.0)),
        )
        kw.update(overrides)
        return cls(**kw)


class _RetryState:
    """Attempt bookkeeping for loop-shaped work (see module docstring)."""

    def __init__(self, policy: RetryPolicy, name: str):
        self.policy = policy
        self.name = name or "retry"
        self.attempt = 0  # completed (failed) attempts
        self.started = time.monotonic()
        self._rng = random.Random(policy.seed)

    def retry(self, exc: BaseException) -> None:
        """Record a failed attempt. Sleeps and returns when another
        attempt is allowed; re-raises ``exc`` when exhausted."""
        # telemetry: every failed attempt and every give-up is counted
        # (registry snapshot rides the pass_end record; `paddle metrics`
        # surfaces the per-pass delta)
        from paddle_tpu.observability import metrics as obs

        obs.registry().counter("retry.attempts").inc()
        self.attempt += 1
        p = self.policy
        elapsed = time.monotonic() - self.started
        if self.attempt >= p.max_attempts:
            logger.warning(
                "%s: attempt %d/%d failed (%s) — giving up",
                self.name, self.attempt, p.max_attempts, exc,
            )
            obs.registry().counter("retry.exhausted").inc()
            raise exc
        if p.deadline and elapsed >= p.deadline:
            logger.warning(
                "%s: retry deadline (%.1fs) exhausted after attempt %d (%s) "
                "— giving up", self.name, p.deadline, self.attempt, exc,
            )
            obs.registry().counter("retry.exhausted").inc()
            raise exc
        d = p.delay_for(self.attempt, self._rng)
        if p.deadline:
            d = min(d, max(p.deadline - elapsed, 0.0))
        obs.registry().counter("retry.backoff_s").inc(d)
        logger.warning(
            "%s: attempt %d/%d failed (%s) — retrying in %.2gs",
            self.name, self.attempt, p.max_attempts, exc, d,
        )
        if d > 0:
            p.sleep(d)
