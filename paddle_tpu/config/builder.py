"""Config build context — the state behind the DSL.

Role of the reference's config_parser globals (g_config, g_layer_map,
g_parameter_map, g_current_submodel; /root/reference/python/paddle/trainer/
config_parser.py:167-430): DSL calls append LayerConfig/ParameterConfig
records here; ``parse_config`` opens a context, executes the user script,
and closes it into a TrainerConfig.
"""

from __future__ import annotations

import contextlib
from typing import Any, Dict, List, Optional

from paddle_tpu.proto import (
    DataConfig,
    LayerConfig,
    ModelConfig,
    OptimizationConfig,
    ParameterConfig,
    SubModelConfig,
    TrainerConfig,
)

_current: Optional["ConfigContext"] = None


def current_context() -> "ConfigContext":
    global _current
    if _current is None:
        _current = ConfigContext()
    return _current


@contextlib.contextmanager
def fresh_context():
    global _current
    prev = _current
    _current = ConfigContext()
    try:
        yield _current
    finally:
        _current = prev


class ConfigContext:
    def __init__(self) -> None:
        self.trainer_config = TrainerConfig()
        self.model: ModelConfig = self.trainer_config.model_config
        self.layer_map: Dict[str, LayerConfig] = {}
        self.param_map: Dict[str, ParameterConfig] = {}
        # settings() state — mirrors the reference's `settings` dict
        self.settings: Dict[str, Any] = {}
        # per-parameter defaults set by settings()/default_* calls
        # (reference: default_decay_rate / default_momentum / ...)
        self.defaults: Dict[str, Any] = {}
        # sub-model stack: None = root scope
        self.submodel_stack: List[SubModelConfig] = []
        self.root_submodel: Optional[SubModelConfig] = None
        self.config_args: Dict[str, str] = {}
        # memory links declared in the current recurrent group
        self._counters: Dict[str, int] = {}

    # ------------------------------------------------------------ layers

    def unique_name(self, prefix: str) -> str:
        # per-prefix invoke counter (reference wrap_name_default semantics,
        # default_decorators.py:74): names stay stable between configs that
        # differ elsewhere — critical for train vs. generation configs
        # sharing one checkpoint.
        n = self._counters.get(prefix, 0)
        self._counters[prefix] = n + 1
        return f"__{prefix}_{n}__"

    def has_layer(self, name: str) -> bool:
        return name in self.layer_map

    def get_layer(self, name: str) -> LayerConfig:
        try:
            return self.layer_map[name]
        except KeyError:
            raise KeyError(f"unknown layer {name!r}") from None

    def add_layer(self, cfg: LayerConfig) -> LayerConfig:
        if cfg.name in self.layer_map:
            raise ValueError(f"duplicate layer name {cfg.name!r}")
        self.layer_map[cfg.name] = cfg
        self.model.layers.append(cfg)
        if self.submodel_stack:
            self.submodel_stack[-1].layer_names.append(cfg.name)
        elif self.root_submodel is not None:
            self.root_submodel.layer_names.append(cfg.name)
        return cfg

    # -------------------------------------------------------- parameters

    def add_parameter(self, cfg: ParameterConfig) -> ParameterConfig:
        if cfg.name in self.param_map:
            return self.param_map[cfg.name]  # shared parameter reuse
        cfg.para_id = len(self.model.parameters)
        self.param_map[cfg.name] = cfg
        self.model.parameters.append(cfg)
        return cfg

    # -------------------------------------------------------- sub-models

    def ensure_root_submodel(self) -> SubModelConfig:
        """Once any recurrent group exists, the root layer set must be
        tracked explicitly (reference: SubModelBegin/End with 'root')."""
        if self.root_submodel is None:
            root = SubModelConfig(name="root")
            root.layer_names = [l.name for l in self.model.layers]
            self.model.sub_models.insert(0, root)
            self.root_submodel = root
        return self.root_submodel

    def begin_submodel(self, name: str, recurrent: bool = True) -> SubModelConfig:
        self.ensure_root_submodel()
        sub = SubModelConfig(name=name, is_recurrent_layer_group=recurrent)
        self.model.sub_models.append(sub)
        self.submodel_stack.append(sub)
        return sub

    def end_submodel(self) -> SubModelConfig:
        return self.submodel_stack.pop()

    @property
    def in_recurrent_group(self) -> bool:
        return bool(self.submodel_stack)

    def current_submodel(self) -> Optional[SubModelConfig]:
        return self.submodel_stack[-1] if self.submodel_stack else None

    # ------------------------------------------------------------ inputs

    def mark_input(self, name: str) -> None:
        if self.submodel_stack:
            sub = self.submodel_stack[-1]
            if name not in sub.input_layer_names:
                sub.input_layer_names.append(name)
            if sub.is_recurrent_layer_group:
                return
            # plain (multi_nn) sub-network inputs are fed from the data
            # provider like root inputs — fall through
        if name not in self.model.input_layer_names:
            self.model.input_layer_names.append(name)

    def mark_output(self, name: str) -> None:
        if self.submodel_stack:
            sub = self.submodel_stack[-1]
            if name not in sub.output_layer_names:
                sub.output_layer_names.append(name)
            if sub.is_recurrent_layer_group:
                return
            if name not in self.model.output_layer_names:
                self.model.output_layer_names.append(name)
        else:
            if name not in self.model.output_layer_names:
                self.model.output_layer_names.append(name)
            if self.root_submodel is not None and name not in self.root_submodel.output_layer_names:
                self.root_submodel.output_layer_names.append(name)

    # ---------------------------------------------------------- finalize

    def finalize(self) -> TrainerConfig:
        opt = self.trainer_config.opt_config
        s = self.settings
        if s:
            _apply_settings(opt, s)
        if self.root_submodel is not None:
            self.root_submodel.input_layer_names = list(self.model.input_layer_names)
            # model-level outputs include plain (multi_nn) sub-network
            # outputs; the root network serves them all
            self.root_submodel.output_layer_names = list(
                dict.fromkeys(
                    list(self.root_submodel.output_layer_names)
                    + list(self.model.output_layer_names)
                )
            )
        return self.trainer_config


def _apply_settings(opt: OptimizationConfig, s: Dict[str, Any]) -> None:
    direct = [
        "batch_size",
        "algorithm",
        "learning_rate",
        "learning_rate_decay_a",
        "learning_rate_decay_b",
        "learning_rate_schedule",
        "learning_rate_args",
        "average_window",
        "max_average_window",
        "do_average_in_cpu",
        "delta_add_rate",
        "ada_epsilon",
        "ada_rou",
        "shrink_parameter_value",
        "adam_beta1",
        "adam_beta2",
        "adam_epsilon",
        "num_batches_per_send_parameter",
        "num_batches_per_get_parameter",
        "async_lagged_grad_discard_ratio",
        "gradient_clipping_threshold",
        "dtype",
        "mesh_shape",
        "remat",
        "scan_unroll",
        "batches_per_launch",
        "pallas_rnn",
        "pallas_flat",
        "conv_s2d",
        "conv_stats_mode",
        "pallas_decoder",
        "c1",
        "backoff",
        "owlqn_steps",
        "max_backoff",
    ]
    for k in direct:
        if k in s and s[k] is not None:
            setattr(opt, k, s[k])
    if s.get("learning_method") is not None:
        opt.learning_method = s["learning_method"]
    if s.get("l1weight") is not None:
        opt.l1weight = s["l1weight"]
    if s.get("l2weight") is not None:
        opt.l2weight = s["l2weight"]
