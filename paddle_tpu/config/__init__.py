from paddle_tpu.config.builder import ConfigContext, current_context
from paddle_tpu.config.config_parser import (
    parse_config,
    parse_config_and_serialize,
    parse_config_at,
)

__all__ = ["ConfigContext", "current_context", "parse_config", "parse_config_and_serialize", "parse_config_at"]
