"""parse_config — execute a user config script into a TrainerConfig.

Role of the reference's config_parser.parse_config
(/root/reference/python/paddle/trainer/config_parser.py:3056): runs the
user's config .py in a namespace pre-seeded with the DSL, collects the
layer/parameter/optimization records from the build context, and returns
the finished TrainerConfig. ``--config_args k=v,k2=v2`` values are exposed
through ``get_config_arg``.
"""

from __future__ import annotations

import os
import sys
from typing import Callable, Dict, Optional, Union

from paddle_tpu.config.builder import current_context, fresh_context
from paddle_tpu.proto import TrainerConfig


def get_config_arg(name: str, type_: type = str, default=None):
    """Read a --config_args value (reference: config_parser get_config_arg)."""
    ctx = current_context()
    if name not in ctx.config_args:
        return default
    v = ctx.config_args[name]
    if type_ is bool:
        return str(v).lower() in ("1", "true", "yes", "on")
    return type_(v)


def _parse_config_args(config_arg_str: str) -> Dict[str, str]:
    args: Dict[str, str] = {}
    if config_arg_str:
        for pair in config_arg_str.split(","):
            if not pair.strip():
                continue
            k, _, v = pair.partition("=")
            args[k.strip()] = v.strip()
    return args


def _ensure_compat_path() -> None:
    """Make `import paddle.trainer_config_helpers` resolve to our shim."""
    shim_dir = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(__file__))), "compat")
    if os.path.isdir(shim_dir) and shim_dir not in sys.path:
        sys.path.insert(0, shim_dir)


def evict_shadowed_modules(directory: str) -> None:
    """Drop cached top-level modules that are shadowed by same-named .py files
    in ``directory``, so user configs always import their *local* helper
    modules (two demos both ship a ``dataprovider.py``; the reference runs
    each config in a fresh embedded interpreter so never hits this)."""
    try:
        entries = os.listdir(directory)
    except OSError:
        return
    for fname in entries:
        if not fname.endswith(".py"):
            continue
        stem = fname[:-3]
        mod = sys.modules.get(stem)
        if mod is None:
            continue
        modfile = getattr(mod, "__file__", None)
        local = os.path.join(os.path.realpath(directory), fname)
        if modfile is None or os.path.realpath(modfile) != local:
            for k in list(sys.modules):
                if k == stem or k.startswith(stem + "."):
                    del sys.modules[k]


def parse_config(
    config: Union[str, Callable[[], None]],
    config_arg_str: str = "",
) -> TrainerConfig:
    """Execute ``config`` (a script path or a callable) and return the built
    TrainerConfig."""
    _ensure_compat_path()
    with fresh_context() as ctx:
        ctx.config_args = _parse_config_args(config_arg_str)
        if callable(config):
            config()
        else:
            import paddle_tpu.trainer_config_helpers as tch

            namespace = {"__file__": config, "__name__": "__paddle_tpu_config__"}
            for k in dir(tch):
                if not k.startswith("_"):
                    namespace[k] = getattr(tch, k)
            namespace["get_config_arg"] = get_config_arg
            config_dir = os.path.dirname(os.path.abspath(config))
            evict_shadowed_modules(config_dir)
            added = False
            if config_dir not in sys.path:
                sys.path.insert(0, config_dir)
                added = True
            try:
                with open(config) as f:
                    code = compile(f.read(), config, "exec")
                exec(code, namespace)
            finally:
                if added:
                    sys.path.remove(config_dir)
            ctx.trainer_config.config_files.append(config)
        return ctx.finalize()


def parse_config_at(config_path: str, config_arg_str: str = "") -> TrainerConfig:
    """parse_config with cwd temporarily set to the config's directory, so
    configs using relative file lists / local imports work from anywhere."""
    config_path = os.path.abspath(config_path)
    cwd = os.getcwd()
    os.chdir(os.path.dirname(config_path))
    try:
        return parse_config(os.path.basename(config_path), config_arg_str)
    finally:
        os.chdir(cwd)


def parse_config_and_serialize(config, config_arg_str: str = "") -> str:
    """JSON form (the reference returned serialized protobuf bytes)."""
    return parse_config(config, config_arg_str).to_json()
