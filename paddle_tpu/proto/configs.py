"""Config messages — the model/trainer schema.

Field names and defaults mirror the reference protobuf contract
(/root/reference/proto/ModelConfig.proto.m4, TrainerConfig.proto.m4,
ParameterConfig.proto.m4, DataConfig.proto.m4) so configs written against
the reference DSL parse to the same logical structure. Fields that only
made sense for the 2016 CPU/GPU runtime (device pinning, selective-fc
thread counts, owlqn line-search knobs) are kept where demos/config_parser
touch them and ignored by the TPU runtime, which documents its divergences
in doc/divergences.md.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from paddle_tpu.proto.message import Message

MAX_I64 = 0x7FFFFFFFFFFFFFFF


# ---------------------------------------------------------------- parameters


@dataclass
class ParameterUpdaterHookConfig(Message):
    # ref: ParameterConfig.proto.m4 ParameterUpdaterHookConfig (static pruning mask)
    type: str = ""
    purning_mask_filename: str = ""  # sic — reference field name preserved


@dataclass
class ParameterConfig(Message):
    # ref: ParameterConfig.proto.m4:21-51
    name: str = ""
    size: int = 0
    learning_rate: float = 1.0
    momentum: float = 0.0
    initial_mean: float = 0.0
    initial_std: float = 0.01
    decay_rate: float = 0.0
    decay_rate_l1: float = 0.0
    dims: List[int] = field(default_factory=list)
    device: int = -1
    initial_strategy: int = 0  # 0 = normal(mean,std), 1 = uniform
    initial_smart: bool = False
    num_batches_regularization: int = 1
    is_sparse: bool = False
    format: str = "csr"
    sparse_remote_update: bool = False
    gradient_clipping_threshold: float = 0.0
    is_static: bool = False
    para_id: int = 0
    update_hooks: List[ParameterUpdaterHookConfig] = field(default_factory=list)
    need_compact: bool = False
    sparse_update: bool = False
    is_shared: bool = False
    parameter_block_size: int = 0
    # TPU extension: logical sharding spec, e.g. ("model", None) to shard dim 0
    # over the "model" mesh axis. Empty = replicated.
    sharding: List[Optional[str]] = field(default_factory=list)


# ------------------------------------------------------------------- layers


@dataclass
class ActivationConfig(Message):
    type: str = ""


@dataclass
class ConvConfig(Message):
    # ref: ModelConfig.proto.m4 ConvConfig
    filter_size: int = 0
    channels: int = 0
    stride: int = 1
    padding: int = 0
    groups: int = 1
    filter_channels: int = 0
    output_x: int = 0
    img_size: int = 0
    caffe_mode: bool = True
    filter_size_y: int = 0
    padding_y: int = -1   # -1 = unset → fall back to padding
    stride_y: int = 0     # 0 = unset → fall back to stride


@dataclass
class PoolConfig(Message):
    pool_type: str = ""
    channels: int = 0
    size_x: int = 0
    start: int = 0
    stride: int = 1
    output_x: int = 0
    img_size: int = 0
    padding: int = 0
    size_y: int = 0
    stride_y: int = 0
    output_y: int = 0
    img_size_y: int = 0
    padding_y: int = 0


@dataclass
class NormConfig(Message):
    norm_type: str = ""
    channels: int = 0
    size: int = 0
    scale: float = 0.0
    pow: float = 0.0
    output_x: int = 0
    img_size: int = 0
    blocked: bool = False


@dataclass
class BlockExpandConfig(Message):
    channels: int = 0
    stride_x: int = 0
    stride_y: int = 0
    padding_x: int = 0
    padding_y: int = 0
    block_x: int = 0
    block_y: int = 0
    output_x: int = 0
    output_y: int = 0
    img_size_x: int = 0
    img_size_y: int = 0


@dataclass
class ImageConfig(Message):
    channels: int = 0
    img_size: int = 0


@dataclass
class ProjectionConfig(Message):
    type: str = ""
    name: str = ""
    input_size: int = 0
    output_size: int = 0
    context_start: int = 0
    context_length: int = 0
    trainable_padding: bool = False
    conv_conf: Optional[ConvConfig] = None
    num_filters: int = 0
    offset: int = 0


@dataclass
class OperatorConfig(Message):
    type: str = ""
    input_indices: List[int] = field(default_factory=list)
    input_sizes: List[int] = field(default_factory=list)
    output_size: int = 0
    dotmul_scale: float = 1.0
    conv_conf: Optional[ConvConfig] = None
    num_filters: int = 0


@dataclass
class LayerInputConfig(Message):
    input_layer_name: str = ""
    input_parameter_name: str = ""
    conv_conf: Optional[ConvConfig] = None
    pool_conf: Optional[PoolConfig] = None
    norm_conf: Optional[NormConfig] = None
    proj_conf: Optional[ProjectionConfig] = None
    block_expand_conf: Optional[BlockExpandConfig] = None
    image_conf: Optional[ImageConfig] = None
    input_layer_argument: str = ""


@dataclass
class LayerConfig(Message):
    # ref: ModelConfig.proto.m4 LayerConfig:229 (~90 fields; the ones demos
    # and config_parser actually set)
    name: str = ""
    type: str = ""
    size: int = 0
    active_type: str = ""
    inputs: List[LayerInputConfig] = field(default_factory=list)
    bias_parameter_name: str = ""
    num_filters: int = 0
    shared_biases: bool = False
    partial_sum: int = 1
    drop_rate: float = 0.0
    num_classes: int = 0
    device: int = -1
    reversed: bool = False
    active_gate_type: str = ""
    active_state_type: str = ""
    num_neg_samples: int = 10
    neg_sampling_dist: List[float] = field(default_factory=list)
    output_max_index: bool = False
    softmax_selfnorm_alpha: float = 0.1
    directions: List[bool] = field(default_factory=list)
    norm_by_times: bool = False
    coeff: float = 1.0
    average_strategy: str = "average"
    error_clipping_threshold: float = 0.0
    operator_confs: List[OperatorConfig] = field(default_factory=list)
    NDCG_num: int = 0
    max_sort_size: int = -1
    slope: float = 1.0
    intercept: float = 0.0
    cos_scale: float = 1.0
    data_norm_strategy: str = ""
    bos_id: int = 0
    eos_id: int = 0
    beam_size: int = 0
    select_first: bool = False
    trans_type: str = "non-seq"
    selective_fc_pass_generation: bool = False
    has_selected_colums: bool = True
    selective_fc_full_mul_ratio: float = 0.02
    use_global_stats: bool = False
    moving_average_fraction: float = 0.9
    # TPU extensions (no 2016 counterpart): multi-head attention + context
    # parallelism knobs (paddle_tpu/layers/attention.py)
    num_heads: int = 0
    causal_attention: bool = False
    seq_parallel_mode: str = ""   # "" | ring | alltoall


@dataclass
class EvaluatorConfig(Message):
    name: str = ""
    type: str = ""
    input_layers: List[str] = field(default_factory=list)
    chunk_scheme: str = ""
    num_chunk_types: int = 0
    classification_threshold: float = 0.5
    positive_label: int = -1
    dict_file: str = ""
    result_file: str = ""
    num_results: int = 1
    delimited: bool = True


@dataclass
class LinkConfig(Message):
    layer_name: str = ""
    link_name: str = ""
    has_subseq: bool = False


@dataclass
class MemoryConfig(Message):
    layer_name: str = ""
    link_name: str = ""
    boot_layer_name: str = ""
    boot_bias_parameter_name: str = ""
    boot_bias_active_type: str = ""
    boot_with_const_id: int = -1
    is_sequence: bool = False


@dataclass
class GeneratorConfig(Message):
    max_num_frames: int = 0
    eos_layer_name: str = ""
    num_results_per_sample: int = 1
    beam_size: int = 1
    log_prob: bool = True
    # TPU extension: where the gen job writes results and the id→word dict
    # (the reference demos thread these through shell flags instead).
    result_file: str = ""
    dict_file: str = ""
    # data slot whose ids tag each sample in the result file (beam_search
    # id_input; empty = sequential indices)
    id_input_layer: str = ""


@dataclass
class SubModelConfig(Message):
    name: str = ""
    layer_names: List[str] = field(default_factory=list)
    input_layer_names: List[str] = field(default_factory=list)
    output_layer_names: List[str] = field(default_factory=list)
    evaluator_names: List[str] = field(default_factory=list)
    is_recurrent_layer_group: bool = False
    reversed: bool = False
    memories: List[MemoryConfig] = field(default_factory=list)
    in_links: List[LinkConfig] = field(default_factory=list)
    out_links: List[LinkConfig] = field(default_factory=list)
    generator: Optional[GeneratorConfig] = None
    # TPU extension: whole-value (non-scattered) inputs to the group —
    # the reference encodes these as ScatterAgent "real layers" at runtime;
    # making them explicit keeps the config self-describing.
    static_links: List[LinkConfig] = field(default_factory=list)


@dataclass
class ModelConfig(Message):
    # ref: ModelConfig.proto.m4 ModelConfig:457
    type: str = "nn"
    layers: List[LayerConfig] = field(default_factory=list)
    parameters: List[ParameterConfig] = field(default_factory=list)
    input_layer_names: List[str] = field(default_factory=list)
    output_layer_names: List[str] = field(default_factory=list)
    evaluators: List[EvaluatorConfig] = field(default_factory=list)
    sub_models: List[SubModelConfig] = field(default_factory=list)


# --------------------------------------------------------------------- data


@dataclass
class DataConfig(Message):
    # ref: DataConfig.proto.m4
    type: str = ""
    files: str = ""
    buffer_capacity: int = 0
    train_sample_num: int = -1
    async_load_data: bool = False
    for_test: bool = False
    constant_slots: List[float] = field(default_factory=list)
    load_data_module: str = ""
    load_data_object: str = ""
    load_data_args: str = ""
    data_ratio: int = 1
    is_main_data: bool = True
    usage_ratio: float = 1.0
    # ref: DataConfig.proto.m4 sub_data_configs (MultiDataProvider)
    sub_data_configs: List["DataConfig"] = field(default_factory=list)


# ----------------------------------------------------------------- trainer


@dataclass
class OptimizationConfig(Message):
    # ref: TrainerConfig.proto.m4 OptimizationConfig:20-129
    batch_size: int = 1
    algorithm: str = "sgd"
    num_batches_per_send_parameter: int = 1
    num_batches_per_get_parameter: int = 1
    learning_rate: float = 1.0
    learning_rate_decay_a: float = 0.0
    learning_rate_decay_b: float = 0.0
    learning_rate_schedule: str = "constant"
    learning_rate_args: str = ""
    l1weight: float = 0.1
    l2weight: float = 0.0
    l2weight_zero_iter: int = 0
    # whole-data batch algorithms (algorithm=owlqn; config_parser.py
    # settings c1/backoff/owlqn_steps/max_backoff)
    c1: float = 0.0001
    backoff: float = 0.5
    owlqn_steps: int = 10
    max_backoff: int = 5
    average_window: float = 0.0
    max_average_window: int = MAX_I64
    do_average_in_cpu: bool = False
    learning_method: str = "momentum"
    ada_epsilon: float = 1e-6
    ada_rou: float = 0.95
    delta_add_rate: float = 1.0
    shrink_parameter_value: float = 0.0
    adam_beta1: float = 0.9
    adam_beta2: float = 0.999
    adam_epsilon: float = 1e-8
    async_lagged_grad_discard_ratio: float = 1.5
    use_sparse_remote_updater: bool = False
    # TPU extensions
    gradient_clipping_threshold: float = 0.0
    dtype: str = "float32"       # compute dtype for activations: float32|bfloat16
    mesh_shape: str = ""         # e.g. "data=8" / "data=4,model=2"
    # rematerialization: "none" stores all activations for backward;
    # "full" wraps the loss in jax.checkpoint so backward recomputes the
    # forward — trades ~33% more FLOPs for O(1) activation memory, the
    # HBM lever for big models/long sequences (SURVEY.md: jax.checkpoint)
    remat: str = "none"          # none|full
    # lax.scan unroll factor for recurrent layers / recurrent groups:
    # unrolling k steps per scan iteration lets XLA pipeline the per-step
    # MXU matmuls and amortize loop overhead, at k× program size. 1 = off.
    scan_unroll: int = 1
    # run lstmemory/gated_recurrent layers through the fused Pallas
    # sequence kernels
    # (ops/pallas_lstm.py): whole time scan in one kernel launch, carry +
    # recurrent weight resident in VMEM. Off by default until measured
    # faster on the target chip; layers fall back to lax.scan for
    # unsupported shapes/activations either way.
    pallas_rnn: bool = False
    # transpose-free interface for the fused Pallas sequence kernels:
    # the kernel reads the projection output's batch-major value through
    # a free [B, T*width] reshape instead of a materialized time-major
    # swap (layers/recurrent.py _pallas_rnn_path). A/B knob beside
    # pallas_rnn; the PADDLE_TPU_PALLAS_FLAT=1 env var still forces it
    # on for configs that can't be edited. Flip the default only on a
    # measured win.
    pallas_flat: bool = False
    # space-to-depth rewrite of few-channel 7x7/s2 stem convs (ResNet
    # conv1) into an MXU-friendly 4x4/s1 conv over a 2x2-block view —
    # exact arithmetic, summation order aside (layers/vision.py
    # _stem_s2d_conv). Off by default until measured on the target chip.
    conv_s2d: bool = False
    # fused 1x1-conv + batch-norm statistics, to eliminate the BN stats
    # pass's full re-read of the conv output from HBM (30.7% of the
    # measured ResNet-50 bf16 step). Two modes:
    #  - "gram": compute sum/sumsq of y = x@w + b from the INPUT side
    #    (colsum(x)@w and w^T(x^Tx)w, exact algebra) — pure XLA, keeps
    #    every conv layout/fusion, applied when N >= 2K so the two x
    #    reads beat the saved y read (layers/vision.py).
    #  - "pallas": the ops/pallas_conv1x1_bn kernel accumulates stats in
    #    the matmul epilogue. Measured END-TO-END LOSER on v5e
    #    (2026-08-01: 1272 vs 2220 imgs/s): XLA lays conv outputs
    #    batch-near-minor and the kernel's row-major [M,K] interface
    #    forces ~33% of the step into relayout copies. Kept for A/B.
    #  - "": off (default until a measured win).
    conv_stats_mode: str = ""
    # run a matching attention-GRU decoder recurrent group (the seqToseq
    # template) as ONE fused Pallas launch per train step, encoder
    # states VMEM-resident per batch block (ops/pallas_attention_gru,
    # graph/fused_decoder.py). Off by default until measured faster on
    # the target chip; non-matching groups take the lax.scan either way.
    pallas_decoder: bool = False
    # fuse k consecutive same-shape batches into ONE device launch
    # (lax.scan over stacked batches): amortizes per-dispatch host latency
    # when single steps are short — each batch still gets its own optimizer
    # update, so numerics match k=1. 1 = off. See doc/performance.md.
    batches_per_launch: int = 1


@dataclass
class TrainerConfig(Message):
    # ref: TrainerConfig.proto.m4 TrainerConfig:132
    model_config: ModelConfig = field(default_factory=ModelConfig)
    data_config: Optional[DataConfig] = None
    opt_config: OptimizationConfig = field(default_factory=OptimizationConfig)
    test_data_config: Optional[DataConfig] = None
    config_files: List[str] = field(default_factory=list)
    save_dir: str = "./output/model"
    init_model_path: str = ""
    start_pass: int = 0
