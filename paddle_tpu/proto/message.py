"""Lightweight message base for config schemas.

The reference encodes its Python⇄C++ contract as protobuf (m4-preprocessed
.proto under /root/reference/proto/). In this TPU-native rebuild both sides
of the contract are Python, so configs are plain dataclasses with the same
field names and defaults, serializable to/from JSON for checkpointing and
`dump_config` tooling. ``real`` is float (float32 numerics; see
/root/reference/proto/CMakeLists.txt:15-16 for the reference's WITH_DOUBLE
switch, which we drop — TPUs want f32/bf16).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Type, TypeVar, get_args, get_origin, get_type_hints

T = TypeVar("T", bound="Message")


@dataclasses.dataclass
class Message:
    """Base class: dataclass config message with dict/JSON round-trip."""

    def to_dict(self, keep_defaults: bool = False) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        defaults = _defaults_of(type(self))
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if not keep_defaults and _eq_default(v, defaults.get(f.name, _MISSING)):
                continue
            out[f.name] = _encode(v, keep_defaults)
        return out

    @classmethod
    def from_dict(cls: Type[T], d: Dict[str, Any]) -> T:
        hints = get_type_hints(cls)
        kwargs: Dict[str, Any] = {}
        known = {f.name for f in dataclasses.fields(cls)}
        for k, v in d.items():
            if k not in known:
                raise KeyError(f"{cls.__name__}: unknown field {k!r}")
            kwargs[k] = _decode(v, hints[k])
        return cls(**kwargs)

    def to_json(self, indent: int | None = None) -> str:
        return json.dumps(self.to_dict(), indent=indent, sort_keys=True)

    @classmethod
    def from_json(cls: Type[T], s: str) -> T:
        return cls.from_dict(json.loads(s))

    def clone(self: T) -> T:
        return type(self).from_dict(self.to_dict(keep_defaults=True))


class _Missing:
    pass


_MISSING = _Missing()
_DEFAULTS_CACHE: Dict[type, Dict[str, Any]] = {}


def _defaults_of(cls: type) -> Dict[str, Any]:
    cached = _DEFAULTS_CACHE.get(cls)
    if cached is None:
        cached = {}
        for f in dataclasses.fields(cls):
            if f.default is not dataclasses.MISSING:
                cached[f.name] = f.default
            elif f.default_factory is not dataclasses.MISSING:  # type: ignore[misc]
                cached[f.name] = f.default_factory()  # type: ignore[misc]
        _DEFAULTS_CACHE[cls] = cached
    return cached


def _eq_default(v: Any, default: Any) -> bool:
    if default is _MISSING:
        return False
    if isinstance(v, Message) or isinstance(default, Message):
        return isinstance(v, Message) and isinstance(default, Message) and v.to_dict() == default.to_dict()
    return v == default


def _encode(v: Any, keep_defaults: bool) -> Any:
    if isinstance(v, Message):
        return v.to_dict(keep_defaults)
    if isinstance(v, list):
        return [_encode(x, keep_defaults) for x in v]
    return v


def _decode(v: Any, hint: Any) -> Any:
    origin = get_origin(hint)
    if origin in (list, List):
        (elem,) = get_args(hint)
        return [_decode(x, elem) for x in v]
    if isinstance(hint, type) and issubclass(hint, Message):
        if v is None:
            return None
        return hint.from_dict(v)
    # Optional[Message]
    args = get_args(hint)
    for a in args:
        if isinstance(a, type) and issubclass(a, Message) and isinstance(v, dict):
            return a.from_dict(v)
    return v
