"""Fused 1x1-conv (matmul) + batch-norm-statistics Pallas TPU kernel.

ResNet-style conv+BN chains pay a full extra HBM read per layer: the
conv writes its output y, then the BN statistics pass re-reads all of y
to reduce per-channel sum/sum-of-squares (30.7% of the measured
ResNet-50 bf16 step — benchmarks/RESULTS.md round-5 trace, the
`convert_reduce_fusion` category). XLA:TPU cannot fuse a reduction into
a convolution's epilogue from lax-level code, but a 1x1 stride-1 conv
IS a matmul over [B*H*W, Cin] x [Cin, Cout] — so this kernel computes
the matmul tile-by-tile and accumulates the per-channel statistics of
each output tile while it is still in VMEM, before it is ever written.
The separate statistics pass (and its HBM read) disappears.

In ResNet-50 bottlenecks the two 1x1 convs produce the reduce (C) and
expand (4C) feature maps — ~80% of the BN-statistics volume — so
covering only 1x1/s1 convs captures most of the win without writing a
general conv kernel (the 3x3 keeps XLA's conv).

Statistics semantics match layers/vision.py batch_norm_layer exactly:
sum and sumsq accumulate in f32 over the *rounded* activation-dtype
output rows (the same values the XLA path's one-pass
``jnp.mean(xr, dtype=f32)`` sees), so downstream mean/var agree with
the unfused path to reduction-order rounding.

Backward is plain XLA (no pallas): with y = x@w + b, s = sum_m(y),
q = sum_m(y^2), the cotangent into the matmul is
    g = dy + ds[None, :] + 2*y*dq[None, :]
and dx = g @ w.T, dw = x.T @ g, db = sum_m(g) — the same two matmuls
the unfused conv backward costs.

ref role: this replaces the reference's ConvProjection +
BatchNormalizationLayer::calMeanAndStd forward pair
(paddle/gserver/layers/BatchNormalizationLayer.cpp) for 1x1 convs;
the reference fuses nothing here (cuDNN conv then column reductions).
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # unavailable when jax has no TPU platform registered (CPU test env)
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # noqa: BLE001
    pltpu = None

Array = jax.Array

# Per-invocation VMEM budget, shared convention with pallas_lstm.py.
_VMEM_BUDGET_BYTES = (
    int(os.environ.get("PADDLE_TPU_PALLAS_VMEM_BUDGET", 0)) or 14 * 1024 * 1024
)

# Row-block candidates: prefer big blocks (fewer weight re-streams),
# multiples of 128 first (native sublane*lane tiling), 8 minimum.
_BM_CANDIDATES = (1024, 896, 768, 640, 512, 384, 256, 128, 64, 32, 16, 8)


def _pick_bm(M: int) -> int | None:
    for bm in _BM_CANDIDATES:
        if M % bm == 0:
            return bm
    return None


def _pick_bn(N: int) -> int | None:
    # OUTPUT blocks need a full 128 lane dim: N=64 is a measured Mosaic
    # compile rejection on hardware (2026-08-01), unlike sub-128 INPUT
    # k blocks which compile fine (the K=64 expand shape passes). The
    # excluded convs are resnet's stage-2 1x1 reduces — the smallest
    # stats tensors, so the loss is minor.
    for bn in (512, 256, 128):
        if N % bn == 0:
            return bn
    return None


def _pick_bk(K: int) -> int | None:
    if K <= 512:
        return K if (K % 128 == 0 or (K < 128 and K % 8 == 0)) else None
    for bk in (512, 256, 128):
        if K % bk == 0:
            return bk
    return None


def _vmem_bytes(bm: int, bn: int, bk: int, N: int, itemsize: int) -> int:
    x_blk = 2 * bm * bk * itemsize            # double-buffered
    w_blk = 2 * bk * bn * itemsize
    o_blk = 2 * bm * bn * itemsize
    acc = bm * bn * 4
    stats = 2 * 2 * N * 4 + 2 * N * itemsize  # s/q outputs + bias block
    return x_blk + w_blk + o_blk + acc + stats


def blocks_for(M: int, K: int, N: int, itemsize: int):
    """(bm, bn, bk) if the kernel supports this shape, else None."""
    if pltpu is None:
        return None
    bm, bn, bk = _pick_bm(M), _pick_bn(N), _pick_bk(K)
    if bm is None or bn is None or bk is None:
        return None
    if _vmem_bytes(bm, bn, bk, N, itemsize) >= _VMEM_BUDGET_BYTES:
        return None
    return bm, bn, bk


def supported(M: int, K: int, N: int, itemsize: int = 2) -> bool:
    return blocks_for(M, K, N, itemsize) is not None


def _kernel(x_ref, w_ref, b_ref, o_ref, s_ref, q_ref, acc_scr, *, bn: int, nk: int):
    m = pl.program_id(0)
    n = pl.program_id(1)
    k = pl.program_id(2)

    @pl.when((m == 0) & (n == 0) & (k == 0))
    def _zero_stats():
        s_ref[...] = jnp.zeros_like(s_ref)
        q_ref[...] = jnp.zeros_like(q_ref)

    @pl.when(k == 0)
    def _zero_acc():
        acc_scr[...] = jnp.zeros_like(acc_scr)

    acc_scr[...] += jnp.dot(
        x_ref[...], w_ref[...], preferred_element_type=jnp.float32
    )

    @pl.when(k == nk - 1)
    def _epilogue():
        y32 = acc_scr[...] + b_ref[...].astype(jnp.float32)
        yb = y32.astype(o_ref.dtype)
        o_ref[...] = yb
        # statistics of the ROUNDED output (what the XLA path reduces),
        # accumulated f32 while the tile is VMEM-resident
        yf = yb.astype(jnp.float32)
        sl = pl.dslice(n * bn, bn)
        s_ref[0, sl] += jnp.sum(yf, axis=0)
        q_ref[0, sl] += jnp.sum(yf * yf, axis=0)


def _run(x: Array, w: Array, b: Array, interpret: bool):
    M, K = x.shape
    _, N = w.shape
    # blocks_for returned non-None (callers gate on supported()), which
    # implies pltpu imported — no pltpu-less branch exists below
    blocks = blocks_for(M, K, N, x.dtype.itemsize)
    assert blocks is not None, (M, K, N)
    bm, bn, bk = blocks
    nm, nn, nk = M // bm, N // bn, K // bk
    kernel = functools.partial(_kernel, bn=bn, nk=nk)
    from paddle_tpu.ops.pallas_compat import compiler_params as _cp

    compiler_params = _cp(dimension_semantics=("arbitrary",) * 3)
    y, s, q = pl.pallas_call(
        kernel,
        grid=(nm, nn, nk),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda m, n, k: (m, k)),
            pl.BlockSpec((bk, bn), lambda m, n, k: (k, n)),
            pl.BlockSpec((1, bn), lambda m, n, k: (0, n)),
        ],
        out_specs=[
            pl.BlockSpec((bm, bn), lambda m, n, k: (m, n)),
            pl.BlockSpec((1, N), lambda m, n, k: (0, 0)),
            pl.BlockSpec((1, N), lambda m, n, k: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((M, N), x.dtype),
            jax.ShapeDtypeStruct((1, N), jnp.float32),
            jax.ShapeDtypeStruct((1, N), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=compiler_params,
        interpret=interpret,
    )(x, w, b.reshape(1, N))
    return y, s[0], q[0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def conv1x1_stats(x: Array, w: Array, b: Array, interpret: bool = False):
    """y = x @ w + b with fused per-channel statistics.

    x: [M, K] rows (B*H*W pixels), w: [K, N], b: [N] (zeros when the
    conv has no bias). Returns (y [M,N] in x.dtype, sum [N] f32,
    sumsq [N] f32) where sum/sumsq reduce the rounded y over rows.
    """
    return _run(x, w, b, interpret)


def _fwd(x, w, b, interpret):
    y, s, q = _run(x, w, b, interpret)
    return (y, s, q), (x, w, b, y)


def _bwd(interpret, res, cts):
    x, w, b, y = res
    dy, ds, dq = cts
    f32 = jnp.float32
    g32 = (
        dy.astype(f32)
        + ds[None, :].astype(f32)
        + 2.0 * y.astype(f32) * dq[None, :].astype(f32)
    )
    g = g32.astype(y.dtype)
    dx = jax.lax.dot_general(
        g, w, (((1,), (1,)), ((), ())), preferred_element_type=f32
    ).astype(x.dtype)
    dw = jax.lax.dot_general(
        x, g, (((0,), (0,)), ((), ())), preferred_element_type=f32
    ).astype(w.dtype)
    db = jnp.sum(g32, axis=0).astype(b.dtype)
    return dx, dw, db


conv1x1_stats.defvjp(_fwd, _bwd)
