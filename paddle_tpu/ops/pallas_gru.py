"""Fused GRU sequence kernel (Pallas TPU) — the gated_recurrent analog of
ops/pallas_lstm.py: the whole time scan in one kernel launch, carry and
both recurrent weight blocks resident in VMEM.

Cell semantics are exactly `gru_cell_step` (reference
GatedRecurrentLayer.cpp / GruCompute contract, layers/recurrent.py:127):
weight [H, 3H] split [update, reset | candidate]; bias 3H = 2H gate +
H candidate (pre-added to the x-projection outside the kernel, so bias
gradients ride the dx3 sum); output = update * prev + (1-update) * cand.
Per step the kernel runs TWO MXU dots (gates: [B,H]x[H,2H]; candidate:
[B,H]x[H,H]) plus VPU gate math. Backward is a reverse-grid kernel
accumulating dW in VMEM, derivatives rebuilt from the saved
post-activation (u, r, c) values.

Correctness: interpret-mode parity in tests/test_pallas_gru.py.
Enabled together with the LSTM kernel via settings(pallas_rnn=True).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from paddle_tpu.ops.pallas_lstm import (_act, _dact, _load_step, _params,
                                        _store_step, pltpu, shape_ok)

Array = jax.Array


def supported(act_in: str, act_gate: str, B: int, H: int,
              itemsize: int = 4) -> bool:
    return shape_ok((act_in, act_gate), B, H, gates=3, itemsize=itemsize,
                    f32_state=False)


def _cell_fwd(x3_ref, w_ref, h_scr, act_in, act_gate, flat=False):
    H = w_ref.shape[0]
    h_prev = h_scr[:]                                   # [B, H] f32
    w = w_ref[:]
    wg, wc = w[:, : 2 * H], w[:, 2 * H :]
    x3 = _load_step(x3_ref, flat).astype(jnp.float32)   # [B, 3H]
    xg, xc = x3[:, : 2 * H], x3[:, 2 * H :]
    hp = h_prev.astype(w.dtype)
    g = _act(act_gate, xg + jax.lax.dot(hp, wg, preferred_element_type=jnp.float32))
    u, r = g[:, :H], g[:, H:]
    cand = xc + jax.lax.dot(
        (r * h_prev).astype(w.dtype), wc, preferred_element_type=jnp.float32
    )
    c = _act(act_in, cand)
    h_new = u * h_prev + (1.0 - u) * c
    return h_prev, h_new, u, r, c


def _fwd_kernel(x3_ref, m_ref, w_ref, y_ref, acts_ref, hprev_ref,
                h_scr, *, act_in, act_gate, flat=False):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        h_scr[:] = jnp.zeros_like(h_scr)

    h_prev, h_new, u, r, c = _cell_fwd(x3_ref, w_ref, h_scr, act_in, act_gate,
                                       flat)
    m = m_ref[0].astype(jnp.float32)                    # [B, 1]

    hprev_ref[0] = h_prev.astype(hprev_ref.dtype)       # residuals (pre-update)
    acts_ref[0] = jnp.concatenate([u, r, c], axis=1).astype(acts_ref.dtype)
    _store_step(y_ref, (m * h_new).astype(y_ref.dtype), flat)
    h_scr[:] = m * h_new + (1.0 - m) * h_prev


def _fwd_kernel_light(x3_ref, m_ref, w_ref, y_ref, h_scr, *, act_in,
                      act_gate, flat=False):
    """Inference/eval variant: ys only (pallas outputs are never DCE'd)."""
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        h_scr[:] = jnp.zeros_like(h_scr)

    h_prev, h_new, _u, _r, _c = _cell_fwd(x3_ref, w_ref, h_scr, act_in,
                                          act_gate, flat)
    m = m_ref[0].astype(jnp.float32)
    _store_step(y_ref, (m * h_new).astype(y_ref.dtype), flat)
    h_scr[:] = m * h_new + (1.0 - m) * h_prev


def _bwd_kernel(dy_ref, acts_ref, hprev_ref, m_ref, w_ref,
                dx3_ref, dw_ref, dh_scr, *, act_in, act_gate, flat=False):
    idx = pl.program_id(0)  # walks t = T-1 .. 0 via the index maps

    @pl.when(idx == 0)
    def _init():
        dh_scr[:] = jnp.zeros_like(dh_scr)
        dw_ref[:] = jnp.zeros_like(dw_ref)

    H = w_ref.shape[0]
    acts = acts_ref[0].astype(jnp.float32)
    u, r, c = acts[:, :H], acts[:, H : 2 * H], acts[:, 2 * H :]
    h_prev = hprev_ref[0].astype(jnp.float32)
    m = m_ref[0].astype(jnp.float32)
    DH = dh_scr[:]

    dy = _load_step(dy_ref, flat).astype(jnp.float32)
    dh = m * (DH + dy)                        # cell path; (1-m) passes through
    du = dh * (h_prev - c)
    dcand = dh * (1.0 - u) * _dact(act_in, c)
    w = w_ref[:]
    wg, wc = w[:, : 2 * H], w[:, 2 * H :]
    drh = jax.lax.dot_general(
        dcand.astype(w.dtype), wc, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                          # d(r*h_prev) [B, H]
    dr = drh * h_prev
    dgu = du * _dact(act_gate, u)
    dgr = dr * _dact(act_gate, r)
    dg = jnp.concatenate([dgu, dgr], axis=1)   # [B, 2H]
    _store_step(dx3_ref, jnp.concatenate([dg, dcand], axis=1).astype(dx3_ref.dtype), flat)

    dh_prev = (
        dh * u
        + drh * r
        + jax.lax.dot_general(
            dg.astype(w.dtype), wg, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
    )
    dh_scr[:] = dh_prev + (1.0 - m) * DH
    dwg = jax.lax.dot_general(
        h_prev, dg, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    dwc = jax.lax.dot_general(
        r * h_prev, dcand, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dw_ref[:] += jnp.concatenate([dwg, dwc], axis=1)     # [H, 3H]


def _run_fwd(x3, mask_tb1, w, acts, interpret, residuals=True, flat=False):
    """``flat``: x3 is [B, T*3H] (the x-projection's natural row-major
    reshape) and ys comes back [B, T*H] — same per-step [B, *] tiles at
    lane offset t*width, no boundary transposes (pallas_lstm._run_fwd)."""
    if flat:
        T, B = mask_tb1.shape[0], mask_tb1.shape[1]
        H3 = x3.shape[1] // T
    else:
        T, B, H3 = x3.shape
    H = H3 // 3
    step3 = pl.BlockSpec((1, B, H3), lambda t: (t, 0, 0))
    step1 = pl.BlockSpec((1, B, H), lambda t: (t, 0, 0))
    if flat:
        x_spec = pl.BlockSpec((B, H3), lambda t: (0, t))
        y_spec = pl.BlockSpec((B, H), lambda t: (0, t))
        ys_shape = jax.ShapeDtypeStruct((B, T * H), x3.dtype)
    else:
        x_spec, y_spec = step3, step1
        ys_shape = jax.ShapeDtypeStruct((T, B, H), x3.dtype)
    # mask rides time-major as [T, B, 1]: a (B, 1) block over [B, T] has
    # a lane dim that is neither 128-divisible nor the full array dim,
    # which Mosaic rejects (see pallas_lstm._run_fwd)
    mask_spec = pl.BlockSpec((1, B, 1), lambda t: (t, 0, 0))
    wspec = pl.BlockSpec(w.shape, lambda t: (0, 0))
    kern = functools.partial(
        _fwd_kernel if residuals else _fwd_kernel_light,
        act_in=acts[0], act_gate=acts[1], flat=flat,
    )
    out_specs = [y_spec]
    out_shape = [ys_shape]
    if residuals:
        out_specs += [step3, step1]
        out_shape += [
            jax.ShapeDtypeStruct((T, B, H3), x3.dtype),  # acts (u, r, c)
            jax.ShapeDtypeStruct((T, B, H), x3.dtype),   # h_prev
        ]
    return pl.pallas_call(
        kern,
        grid=(T,),
        in_specs=[x_spec, mask_spec, wspec],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((B, H), jnp.float32)] if pltpu is not None else [],
        interpret=interpret,
        compiler_params=_params(1),
    )(x3, mask_tb1, w)


def _run_bwd(dy, acts_seq, hprev, mask_tb1, w, acts, interpret, flat=False):
    T, B, H3 = acts_seq.shape
    H = H3 // 3
    rev3 = pl.BlockSpec((1, B, H3), lambda i: (T - 1 - i, 0, 0))
    rev1 = pl.BlockSpec((1, B, H), lambda i: (T - 1 - i, 0, 0))
    if flat:
        dy_spec = pl.BlockSpec((B, H), lambda i: (0, T - 1 - i))
        dx_spec = pl.BlockSpec((B, H3), lambda i: (0, T - 1 - i))
        dx_shape = jax.ShapeDtypeStruct((B, T * H3), dy.dtype)
    else:
        dy_spec, dx_spec = rev1, rev3
        dx_shape = jax.ShapeDtypeStruct((T, B, H3), dy.dtype)
    mask_spec = pl.BlockSpec((1, B, 1), lambda i: (T - 1 - i, 0, 0))
    wspec = pl.BlockSpec(w.shape, lambda i: (0, 0))
    kern = functools.partial(_bwd_kernel, act_in=acts[0], act_gate=acts[1],
                             flat=flat)
    dx3, dw = pl.pallas_call(
        kern,
        grid=(T,),
        in_specs=[dy_spec, rev3, rev1, mask_spec, wspec],
        out_specs=[dx_spec, wspec],
        out_shape=[
            dx_shape,
            jax.ShapeDtypeStruct(w.shape, jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((B, H), jnp.float32)] if pltpu is not None else [],
        interpret=interpret,
        compiler_params=_params(1),
    )(dy, acts_seq, hprev, mask_tb1, w)
    return dx3, dw.astype(w.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def fused_gru(x3, mask, w, acts, interpret, flat=False):
    """Masked GRU over the whole sequence in one kernel launch.

    Time-major (flat=False): x3 [T, B, 3H], ys [T, B, H]. Flat
    (flat=True): x3 [B, T*3H], ys [B, T*H] — no boundary transposes
    (see fused_lstm). mask [T, B] in both modes; x3 carries biases;
    w [H, 3H]; acts = (act_in, act_gate)."""
    from paddle_tpu.ops import kernel_flops

    T, B = mask.shape
    H3 = x3.shape[2] if not flat else x3.shape[1] // T
    kernel_flops.record(kernel_flops.gru_fwd_flops(T, B, H3 // 3))
    (ys,) = _run_fwd(x3, mask[:, :, None], w, acts, interpret,
                     residuals=False, flat=flat)
    return ys


def _fused_fwd(x3, mask, w, acts, interpret, flat=False):
    from paddle_tpu.ops import kernel_flops

    T, B = mask.shape
    H3 = x3.shape[2] if not flat else x3.shape[1] // T
    kernel_flops.record(kernel_flops.gru_fwd_flops(T, B, H3 // 3))
    ys, acts_seq, hprev = _run_fwd(x3, mask[:, :, None], w, acts, interpret,
                                   flat=flat)
    return ys, (acts_seq, hprev, mask, w)


def _fused_bwd(acts, interpret, flat, res, dy):
    from paddle_tpu.ops import kernel_flops

    acts_seq, hprev, mask, w = res
    T, B, H3 = acts_seq.shape
    kernel_flops.record(kernel_flops.gru_bwd_flops(T, B, H3 // 3))
    dx3, dw = _run_bwd(dy, acts_seq, hprev, mask[:, :, None], w, acts,
                       interpret, flat=flat)
    return dx3, jnp.zeros_like(mask), dw


fused_gru.defvjp(_fused_fwd, _fused_bwd)


def gru_layer_forward(cfg, x, mask, w, bias, interpret, x_bt=None):
    """The gated_recurrent layer body on the fused kernel: ys [T, B, H]
    (time-major) or [B, T, H] (x_bt flat interface).

    x: [T, B, 3H] pre-bias x-projection, bias: [3H] or None; handles
    cfg.reversed by flipping time outside the kernel (same carry-masking
    argument as the LSTM kernel). ``x_bt``: batch-major [B, T, 3H] for
    the transpose-free flat interface (see pallas_lstm)."""
    H = cfg.size
    flat = x_bt is not None
    T = mask.shape[0]
    if flat:
        x = x_bt
        if bias is not None:
            x = x + bias.astype(x.dtype)
        if cfg.reversed:
            x = jnp.flip(x, 1)
            mask = jnp.flip(mask, 0)
        x = x.reshape(x.shape[0], T * 3 * H)
    else:
        if bias is not None:
            x = x + bias.astype(x.dtype)
        if cfg.reversed:
            x = jnp.flip(x, 0)
            mask = jnp.flip(mask, 0)
    acts = (cfg.active_type or "tanh", cfg.active_gate_type or "sigmoid")
    ys = fused_gru(x, mask, w, acts, interpret, flat)
    if flat:
        ys = ys.reshape(ys.shape[0], T, H)
        if cfg.reversed:
            ys = jnp.flip(ys, 1)
        return ys                          # batch-major [B, T, H]
    if cfg.reversed:
        ys = jnp.flip(ys, 0)
    return ys                              # time-major [T, B, H]


def usable(cfg, x) -> bool:
    T, B, H3 = x.shape
    if x.dtype not in (jnp.float32, jnp.bfloat16) or H3 != 3 * cfg.size:
        return False
    return supported(
        cfg.active_type or "tanh", cfg.active_gate_type or "sigmoid", B, cfg.size,
        itemsize=jnp.dtype(x.dtype).itemsize,
    )
