"""Fused GRU sequence kernel (Pallas TPU) — the gated_recurrent analog of
ops/pallas_lstm.py: the whole time scan in one kernel launch, carry and
both recurrent weight blocks resident in VMEM.

Cell semantics are exactly `gru_cell_step` (reference
GatedRecurrentLayer.cpp / GruCompute contract, layers/recurrent.py:127):
weight [H, 3H] split [update, reset | candidate]; bias 3H = 2H gate +
H candidate (pre-added to the x-projection outside the kernel, so bias
gradients ride the dx3 sum); output = update * prev + (1-update) * cand.
Per step the kernel runs TWO MXU dots (gates: [B,H]x[H,2H]; candidate:
[B,H]x[H,H]) plus VPU gate math. Backward is a reverse-grid kernel
accumulating dW in VMEM, derivatives rebuilt from the saved
post-activation (u, r, c) values.

Correctness: interpret-mode parity in tests/test_pallas_gru.py.
Enabled together with the LSTM kernel via settings(pallas_rnn=True).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from paddle_tpu.ops.pallas_lstm import _act, _dact, _params, pltpu, shape_ok

Array = jax.Array


def supported(act_in: str, act_gate: str, B: int, H: int,
              itemsize: int = 4) -> bool:
    return shape_ok((act_in, act_gate), B, H, gates=3, itemsize=itemsize,
                    f32_state=False)


def _cell_fwd(x3_ref, w_ref, h_scr, act_in, act_gate):
    H = w_ref.shape[0]
    h_prev = h_scr[:]                                   # [B, H] f32
    w = w_ref[:]
    wg, wc = w[:, : 2 * H], w[:, 2 * H :]
    x3 = x3_ref[0].astype(jnp.float32)                  # [B, 3H]
    xg, xc = x3[:, : 2 * H], x3[:, 2 * H :]
    hp = h_prev.astype(w.dtype)
    g = _act(act_gate, xg + jax.lax.dot(hp, wg, preferred_element_type=jnp.float32))
    u, r = g[:, :H], g[:, H:]
    cand = xc + jax.lax.dot(
        (r * h_prev).astype(w.dtype), wc, preferred_element_type=jnp.float32
    )
    c = _act(act_in, cand)
    h_new = u * h_prev + (1.0 - u) * c
    return h_prev, h_new, u, r, c


def _fwd_kernel(x3_ref, m_ref, w_ref, y_ref, acts_ref, hprev_ref,
                h_scr, *, act_in, act_gate):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        h_scr[:] = jnp.zeros_like(h_scr)

    h_prev, h_new, u, r, c = _cell_fwd(x3_ref, w_ref, h_scr, act_in, act_gate)
    m = m_ref[0].astype(jnp.float32)                    # [B, 1]

    hprev_ref[0] = h_prev.astype(hprev_ref.dtype)       # residuals (pre-update)
    acts_ref[0] = jnp.concatenate([u, r, c], axis=1).astype(acts_ref.dtype)
    y_ref[0] = (m * h_new).astype(y_ref.dtype)
    h_scr[:] = m * h_new + (1.0 - m) * h_prev


def _fwd_kernel_light(x3_ref, m_ref, w_ref, y_ref, h_scr, *, act_in, act_gate):
    """Inference/eval variant: ys only (pallas outputs are never DCE'd)."""
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        h_scr[:] = jnp.zeros_like(h_scr)

    h_prev, h_new, _u, _r, _c = _cell_fwd(x3_ref, w_ref, h_scr, act_in, act_gate)
    m = m_ref[0].astype(jnp.float32)
    y_ref[0] = (m * h_new).astype(y_ref.dtype)
    h_scr[:] = m * h_new + (1.0 - m) * h_prev


def _bwd_kernel(dy_ref, acts_ref, hprev_ref, m_ref, w_ref,
                dx3_ref, dw_ref, dh_scr, *, act_in, act_gate):
    idx = pl.program_id(0)  # walks t = T-1 .. 0 via the index maps

    @pl.when(idx == 0)
    def _init():
        dh_scr[:] = jnp.zeros_like(dh_scr)
        dw_ref[:] = jnp.zeros_like(dw_ref)

    H = w_ref.shape[0]
    acts = acts_ref[0].astype(jnp.float32)
    u, r, c = acts[:, :H], acts[:, H : 2 * H], acts[:, 2 * H :]
    h_prev = hprev_ref[0].astype(jnp.float32)
    m = m_ref[0].astype(jnp.float32)
    DH = dh_scr[:]

    dy = dy_ref[0].astype(jnp.float32)
    dh = m * (DH + dy)                        # cell path; (1-m) passes through
    du = dh * (h_prev - c)
    dcand = dh * (1.0 - u) * _dact(act_in, c)
    w = w_ref[:]
    wg, wc = w[:, : 2 * H], w[:, 2 * H :]
    drh = jax.lax.dot_general(
        dcand.astype(w.dtype), wc, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                          # d(r*h_prev) [B, H]
    dr = drh * h_prev
    dgu = du * _dact(act_gate, u)
    dgr = dr * _dact(act_gate, r)
    dg = jnp.concatenate([dgu, dgr], axis=1)   # [B, 2H]
    dx3_ref[0] = jnp.concatenate([dg, dcand], axis=1).astype(dx3_ref.dtype)

    dh_prev = (
        dh * u
        + drh * r
        + jax.lax.dot_general(
            dg.astype(w.dtype), wg, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
    )
    dh_scr[:] = dh_prev + (1.0 - m) * DH
    dwg = jax.lax.dot_general(
        h_prev, dg, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    dwc = jax.lax.dot_general(
        r * h_prev, dcand, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dw_ref[:] += jnp.concatenate([dwg, dwc], axis=1)     # [H, 3H]


def _run_fwd(x3, mask_tb1, w, acts, interpret, residuals=True):
    T, B, H3 = x3.shape
    H = H3 // 3
    step3 = pl.BlockSpec((1, B, H3), lambda t: (t, 0, 0))
    step1 = pl.BlockSpec((1, B, H), lambda t: (t, 0, 0))
    # mask rides time-major as [T, B, 1]: a (B, 1) block over [B, T] has
    # a lane dim that is neither 128-divisible nor the full array dim,
    # which Mosaic rejects (see pallas_lstm._run_fwd)
    mask_spec = pl.BlockSpec((1, B, 1), lambda t: (t, 0, 0))
    wspec = pl.BlockSpec(w.shape, lambda t: (0, 0))
    kern = functools.partial(
        _fwd_kernel if residuals else _fwd_kernel_light,
        act_in=acts[0], act_gate=acts[1],
    )
    out_specs = [step1]
    out_shape = [jax.ShapeDtypeStruct((T, B, H), x3.dtype)]  # ys
    if residuals:
        out_specs += [step3, step1]
        out_shape += [
            jax.ShapeDtypeStruct((T, B, H3), x3.dtype),  # acts (u, r, c)
            jax.ShapeDtypeStruct((T, B, H), x3.dtype),   # h_prev
        ]
    return pl.pallas_call(
        kern,
        grid=(T,),
        in_specs=[step3, mask_spec, wspec],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((B, H), jnp.float32)] if pltpu is not None else [],
        interpret=interpret,
        compiler_params=_params(1),
    )(x3, mask_tb1, w)


def _run_bwd(dy, acts_seq, hprev, mask_tb1, w, acts, interpret):
    T, B, H3 = acts_seq.shape
    H = H3 // 3
    rev3 = pl.BlockSpec((1, B, H3), lambda i: (T - 1 - i, 0, 0))
    rev1 = pl.BlockSpec((1, B, H), lambda i: (T - 1 - i, 0, 0))
    mask_spec = pl.BlockSpec((1, B, 1), lambda i: (T - 1 - i, 0, 0))
    wspec = pl.BlockSpec(w.shape, lambda i: (0, 0))
    kern = functools.partial(_bwd_kernel, act_in=acts[0], act_gate=acts[1])
    dx3, dw = pl.pallas_call(
        kern,
        grid=(T,),
        in_specs=[rev1, rev3, rev1, mask_spec, wspec],
        out_specs=[rev3, wspec],
        out_shape=[
            jax.ShapeDtypeStruct((T, B, H3), dy.dtype),
            jax.ShapeDtypeStruct(w.shape, jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((B, H), jnp.float32)] if pltpu is not None else [],
        interpret=interpret,
        compiler_params=_params(1),
    )(dy, acts_seq, hprev, mask_tb1, w)
    return dx3, dw.astype(w.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def fused_gru(x3, mask, w, acts, interpret):
    """ys [T, B, H] = masked GRU over time-major x-projections.

    x3: [T, B, 3H] x-projection with biases already added; mask: [T, B];
    w: [H, 3H]; acts: (act_in, act_gate) static name pair."""
    from paddle_tpu.ops import kernel_flops

    T, B, H3 = x3.shape
    kernel_flops.record(kernel_flops.gru_fwd_flops(T, B, H3 // 3))
    (ys,) = _run_fwd(x3, mask[:, :, None], w, acts, interpret, residuals=False)
    return ys


def _fused_fwd(x3, mask, w, acts, interpret):
    from paddle_tpu.ops import kernel_flops

    T, B, H3 = x3.shape
    kernel_flops.record(kernel_flops.gru_fwd_flops(T, B, H3 // 3))
    ys, acts_seq, hprev = _run_fwd(x3, mask[:, :, None], w, acts, interpret)
    return ys, (acts_seq, hprev, mask, w)


def _fused_bwd(acts, interpret, res, dy):
    from paddle_tpu.ops import kernel_flops

    acts_seq, hprev, mask, w = res
    T, B, H3 = acts_seq.shape
    kernel_flops.record(kernel_flops.gru_bwd_flops(T, B, H3 // 3))
    dx3, dw = _run_bwd(dy, acts_seq, hprev, mask[:, :, None], w, acts, interpret)
    return dx3, jnp.zeros_like(mask), dw


fused_gru.defvjp(_fused_fwd, _fused_bwd)


def gru_layer_forward(cfg, x, mask, w, bias, interpret):
    """The gated_recurrent layer body on the fused kernel: ys [T, B, H].

    x: [T, B, 3H] pre-bias x-projection, bias: [3H] or None; handles
    cfg.reversed by flipping time outside the kernel (same carry-masking
    argument as the LSTM kernel)."""
    if bias is not None:
        x = x + bias.astype(x.dtype)
    if cfg.reversed:
        x = jnp.flip(x, 0)
        mask = jnp.flip(mask, 0)
    acts = (cfg.active_type or "tanh", cfg.active_gate_type or "sigmoid")
    ys = fused_gru(x, mask, w, acts, interpret)
    if cfg.reversed:
        ys = jnp.flip(ys, 0)
    return ys


def usable(cfg, x) -> bool:
    T, B, H3 = x.shape
    if x.dtype not in (jnp.float32, jnp.bfloat16) or H3 != 3 * cfg.size:
        return False
    return supported(
        cfg.active_type or "tanh", cfg.active_gate_type or "sigmoid", B, cfg.size,
        itemsize=jnp.dtype(x.dtype).itemsize,
    )
