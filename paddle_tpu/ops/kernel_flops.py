"""Analytic FLOP accounting for the fused Pallas recurrent kernels.

XLA's cost analysis (``compiled.cost_analysis()['flops']`` — the basis of
benchmarks/mfu.py) cannot see inside a ``pallas_call`` custom call, so a
train step that runs the fused LSTM/GRU kernels would report an MFU that
excludes the kernels' matmul FLOPs — the dominant term. The kernel
wrappers therefore ``record()`` their analytic FLOP count at TRACE time;
bench.py wraps its one AOT ``step.lower(...)`` in ``capture()`` and adds
the recorded counts to the cost-analysis number, restoring a
comparable-basis MFU between the pallas and XLA-scan paths.

FLOP conventions match HloCostAnalysis: a [M,K]x[K,N] dot is 2·M·K·N;
elementwise add/mul count 1 per output element; transcendentals
(tanh/sigmoid exp) are NOT counted as flops. Matmul terms below are exact
per the kernel bodies (ops/pallas_lstm.py, ops/pallas_gru.py); the
elementwise coefficients are close counts of the gate math (within a few
ops — at the flagship H=512 the matmul term is ~200x larger, so the
approximation is irrelevant to MFU). Verified against XLA's own count of
the fully-unrolled scan path in tests/test_kernel_flops.py.

Interpret-mode runs record too (the wrapper cannot know whether the
interpreter's ops also land in the HLO); interpret mode is a CPU
debugging path whose MFU is never quoted, so the double count is
accepted for simplicity.
"""

from __future__ import annotations

import contextlib
from typing import List, Optional

# ---------------------------------------------------------------- formulas


def lstm_fwd_flops(T: int, B: int, H: int) -> float:
    """Fused LSTM forward: per step one [B,H]x[H,4H] dot (8·B·H²) plus
    gate/peephole/carry-mask elementwise math (~21·B·H: x4+dot add 4BH,
    3 peephole mul+add 6BH, c_new 3BH, h_new+y 2BH, two masked carry
    merges 6BH)."""
    return float(T) * (8.0 * B * H * H + 21.0 * B * H)


def lstm_bwd_flops(T: int, B: int, H: int) -> float:
    """Fused LSTM backward: per step dgates@Wᵀ ([B,4H]x[4H,H]) and the
    dW accumulation ([H,B]x[B,4H]) — 16·B·H² — plus the dgate chain,
    peephole grads and masked carry merges (~40·B·H)."""
    return float(T) * (16.0 * B * H * H + 40.0 * B * H)


def gru_fwd_flops(T: int, B: int, H: int) -> float:
    """Fused GRU forward: per step gates [B,H]x[H,2H] (4·B·H²) and
    candidate [B,H]x[H,H] (2·B·H²), plus r·h, the update blend and the
    masked carry merge (~14·B·H)."""
    return float(T) * (6.0 * B * H * H + 14.0 * B * H)


def gru_bwd_flops(T: int, B: int, H: int) -> float:
    """Fused GRU backward: per step dcand@Wcᵀ (2·B·H²), dg@Wgᵀ (4·B·H²),
    dWg ([H,B]x[B,2H], 4·B·H²), dWc (2·B·H²) — 12·B·H² — plus the dgate
    chain and merges (~25·B·H)."""
    return float(T) * (12.0 * B * H * H + 25.0 * B * H)


# ----------------------------------------------------- jaxpr matmul counter
#
# XLA's HloCostAnalysis counts a while/scan BODY once regardless of trip
# count, so `compiled.cost_analysis()['flops']` understates any scanned
# computation by ~T — on the recurrent bench legs the recurrence is the
# dominant FLOP term, which made their round-4 MFU figures several-fold
# pessimistic (the hoisted x-projections were counted, the T-step
# recurrence effectively not). The honest basis for MFU is analytic MODEL
# matmul FLOPs (the MLPerf / scaling-book convention); this counter
# computes them exactly by walking the train step's jaxpr: dot_general and
# conv_general_dilated FLOPs, scan bodies multiplied by their static
# `length`, pallas_call bodies multiplied by their grid size, cond taking
# the max branch, while bodies counted once (trip count unknowable).
# Elementwise/transcendental ops are deliberately excluded — matmul FLOPs
# over peak-matmul throughput is the standard MFU definition.


def _prod(xs) -> float:
    r = 1.0
    for x in xs:
        r *= float(x)
    return r


def _dot_flops(eqn) -> float:
    (lc, rc), (lb, _rb) = eqn.params["dimension_numbers"]
    lhs = eqn.invars[0].aval.shape
    rhs = eqn.invars[1].aval.shape
    batch = _prod(lhs[d] for d in lb)
    k = _prod(lhs[d] for d in lc)
    m = _prod(lhs[d] for d in range(len(lhs)) if d not in set(lc) | set(lb))
    n = _prod(rhs[d] for d in range(len(rhs)) if d not in set(rc) | set(_rb))
    return 2.0 * batch * m * n * k


def _conv_flops(eqn) -> float:
    # 2 * out_elements * (kernel_spatial * C_in_per_group); prod(rhs
    # shape) = kspatial * C_in_per_group * C_out, so divide out C_out.
    # lhs_dilation marks a transposed conv (the dX of a strided forward
    # conv): only 1/prod(lhs_dilation) of its taps hit non-inserted-zero
    # inputs, so discount to count canonical model FLOPs, not zeros.
    out = eqn.outvars[0].aval.shape
    rhs = eqn.invars[1].aval.shape
    dn = eqn.params["dimension_numbers"]
    c_out = rhs[dn.rhs_spec[0]]
    lhs_dil = _prod(eqn.params.get("lhs_dilation") or (1,))
    return 2.0 * _prod(out) * _prod(rhs) / float(c_out) / lhs_dil


def jaxpr_flops(jaxpr, scale: float = 1.0) -> float:
    """Matmul/conv FLOPs of a (possibly closed) jaxpr, with exact scan /
    pallas grid trip counts."""
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    total = 0.0
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "dot_general":
            total += scale * _dot_flops(eqn)
        elif name == "conv_general_dilated":
            total += scale * _conv_flops(eqn)
        elif name == "scan":
            total += jaxpr_flops(
                eqn.params["jaxpr"], scale * float(eqn.params["length"])
            )
        elif name == "pallas_call":
            grid = tuple(getattr(eqn.params.get("grid_mapping"), "grid", ()) or ())
            total += jaxpr_flops(eqn.params["jaxpr"], scale * _prod(grid or (1,)))
        elif name == "while":
            # trip count is dynamic: count the body once (the generation
            # decoder is the only while user; bench legs are scans)
            total += jaxpr_flops(eqn.params["body_jaxpr"], scale)
        elif name == "cond":
            total += max(
                (jaxpr_flops(b, scale) for b in eqn.params["branches"]),
                default=0.0,
            )
        else:
            # pjit / remat / custom_vjp / closed_call / ...: recurse into
            # every jaxpr-valued param once
            for v in eqn.params.values():
                if hasattr(v, "jaxpr") or hasattr(v, "eqns"):
                    total += jaxpr_flops(v, scale)
    return total


def train_step_flops(fn, *args, **kwargs) -> float:
    """Model matmul FLOPs of one call of ``fn(*args)`` (jaxpr-traced; works
    on plain or jit-wrapped functions)."""
    import jax

    return jaxpr_flops(jax.make_jaxpr(fn, **kwargs)(*args))


# ------------------------------------------------------------ chip peaks

# substring (lowercased device_kind) -> peak bf16 TFLOP/s per jax device
# (Google's published TPU specs; v3 entry is per core = one jax device)
_PEAK_BF16_TFLOPS = [
    ("v6e", 918.0),
    ("v6 lite", 918.0),
    ("v5p", 459.0),
    ("v5e", 197.0),
    ("v5 lite", 197.0),
    ("v5litepod", 197.0),
    ("v4", 275.0),
    ("v3", 61.5),
    ("v2", 23.0),
]

# substring (lowercased device_kind) -> peak HBM bandwidth GB/s per jax
# device (same published specs; v3 entry is per core). The ratio
# peak_flops/peak_bytes is the roofline ridge point the cost-attribution
# layer classifies launch groups against (observability/costs.py).
_PEAK_HBM_GBPS = [
    ("v6e", 1640.0),
    ("v6 lite", 1640.0),
    ("v5p", 2765.0),
    ("v5e", 819.0),
    ("v5 lite", 819.0),
    ("v5litepod", 819.0),
    ("v4", 1228.0),
    ("v3", 450.0),
    ("v2", 350.0),
]


# substring (lowercased device_kind) -> HBM capacity GB per jax device
# (same published specs; v2/v3 entries are per core). The memory
# analyzer (observability/memory.py) computes peak-vs-capacity headroom
# against this when the allocator reported no bytes_limit — same
# omitted-never-guessed contract as the peak tables above.
_PEAK_HBM_GB = [
    ("v6e", 32.0),
    ("v6 lite", 32.0),
    ("v5p", 95.0),
    ("v5e", 16.0),
    ("v5 lite", 16.0),
    ("v5litepod", 16.0),
    ("v4", 32.0),
    ("v3", 16.0),
    ("v2", 8.0),
]


def _peak_of(table, device_kind: str):
    dk = device_kind.lower()
    for key, peak in table:
        if key in dk:
            return peak
    return None


def peak_tflops(device_kind: str):
    """Peak bf16 TFLOP/s for a jax device kind; None when unknown (MFU
    is omitted, never guessed)."""
    return _peak_of(_PEAK_BF16_TFLOPS, device_kind)


def peak_gbps(device_kind: str):
    """Peak HBM GB/s for a jax device kind; None when unknown (roofline
    buckets degrade to 'unknown', never guessed)."""
    return _peak_of(_PEAK_HBM_GBPS, device_kind)


def peak_hbm_gb(device_kind: str):
    """HBM capacity GB for a jax device kind; None when unknown (the
    memory analyzer omits the headroom line, never guessed)."""
    return _peak_of(_PEAK_HBM_GB, device_kind)


# ------------------------------------------------------------- trace capture

_LOG: Optional[List[float]] = None


def record(flops: float) -> None:
    """Called by the pallas kernel wrappers at TRACE time (their Python
    bodies run exactly once per jit trace). No-op outside capture()."""
    if _LOG is not None:
        _LOG.append(float(flops))


@contextlib.contextmanager
def capture():
    """Collect analytic FLOP records from every pallas kernel traced in
    the body. Yields the (mutable) list; re-entrant (inner capture wins,
    restoring the outer log on exit)."""
    global _LOG
    prev = _LOG
    _LOG = log = []
    try:
        yield log
    finally:
        _LOG = prev
