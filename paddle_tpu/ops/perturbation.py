"""Batched on-device image perturbation: rotate + scale + patch sampling.

Role analog of the reference's GPU augmentation kernels
(paddle/cuda/src/hl_perturbation_util.cu: kSamplingPatches +
hl_generate_disturb_params), re-designed for XLA instead of translated:
the whole batch is one jittable inverse-mapped nearest-neighbor gather
(static shapes, no per-image host loop), and randomness is an explicit
jax PRNG key split per call — reproducible under jit, unlike the
reference's srand(time(NULL)).

Geometry matches the reference kernel: for each output pixel the source
coordinate is found by translating to the sampled patch center, rotating
by -theta, unscaling, and rounding to the nearest source pixel;
out-of-bounds sources read pad_value.

Typical use: augment a host batch right before the train step
(`perturb` is jit-compatible and fuses with the rest of the step), with
rotate_angle the max |rotation| in degrees and scale_ratio the total
relative scale jitter (scale in 1 +/- scale_ratio/2).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["perturb"]


@functools.partial(
    jax.jit, static_argnames=("tgt_size", "sampling_rate", "is_train")
)
def perturb(
    images: jax.Array,
    key: jax.Array,
    tgt_size: int,
    rotate_angle: float = 0.0,
    scale_ratio: float = 0.0,
    sampling_rate: int = 1,
    pad_value: float = 0.0,
    is_train: bool = True,
) -> jax.Array:
    """Sample rotated/scaled patches from a batch of square images.

    images: (N, C, S, S) float array.
    Returns (N * sampling_rate, C, tgt_size, tgt_size); patch i*k of image
    i shares that image's rotation/scale draw (reference semantics: one
    disturbance per image, sampling_rate patch locations).

    Eval mode (is_train=False) is deterministic: no rotation, unit scale,
    center patch — the key is unused.
    """
    n, c, s, _ = images.shape
    num_patches = n * sampling_rate
    img_center = (s - 1) / 2.0
    tgt_center = (tgt_size - 1) / 2.0

    if is_train:
        k_theta, k_scale, k_center = jax.random.split(key, 3)
        theta = (rotate_angle * jnp.pi / 180.0) * (
            jax.random.uniform(k_theta, (n,)) - 0.5
        )
        scale = 1.0 + (jax.random.uniform(k_scale, (n,)) - 0.5) * scale_ratio
        # patch centers anywhere in the source image (reference samples
        # centers over [0, S-1]; out-of-bounds reads become pad_value)
        centers = jax.random.uniform(
            k_center, (num_patches, 2), minval=0.0, maxval=float(s - 1)
        )
        center_r, center_c = jnp.round(centers[:, 0]), jnp.round(centers[:, 1])
    else:
        theta = jnp.zeros((n,))
        scale = jnp.ones((n,))
        center_r = jnp.full((num_patches,), img_center)
        center_c = jnp.full((num_patches,), img_center)

    # per-patch transform params (patch p belongs to image p // sampling_rate)
    img_idx = jnp.arange(num_patches) // sampling_rate
    theta_p = theta[img_idx]
    scale_p = scale[img_idx]

    # output pixel grid, shared by every patch
    ys, xs = jnp.meshgrid(jnp.arange(tgt_size), jnp.arange(tgt_size), indexing="ij")
    # translate into source frame around the sampled center
    x_new = xs[None] - tgt_center + center_c[:, None, None] - img_center
    y_new = ys[None] - tgt_center + center_r[:, None, None] - img_center
    cos_t = jnp.cos(-theta_p)[:, None, None]
    sin_t = jnp.sin(-theta_p)[:, None, None]
    xx = cos_t * x_new - sin_t * y_new
    yy = sin_t * x_new + cos_t * y_new
    src_x = jnp.round(xx / scale_p[:, None, None] + img_center).astype(jnp.int32)
    src_y = jnp.round(yy / scale_p[:, None, None] + img_center).astype(jnp.int32)

    in_bounds = (src_x >= 0) & (src_x < s) & (src_y >= 0) & (src_y < s)
    sx = jnp.clip(src_x, 0, s - 1)
    sy = jnp.clip(src_y, 0, s - 1)

    # one gather for the whole batch: (P, tgt, tgt) indices into (P, C, S, S)
    src = images[img_idx]  # (P, C, S, S)
    patch = src[
        jnp.arange(num_patches)[:, None, None, None],
        jnp.arange(c)[None, :, None, None],
        sy[:, None, :, :],
        sx[:, None, :, :],
    ]
    return jnp.where(in_bounds[:, None, :, :], patch, pad_value)
