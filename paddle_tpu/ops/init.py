"""Parameter initialization strategies.

Mirrors the reference's init semantics (Parameter::randomize,
/root/reference/paddle/parameter/Parameter.cpp and
ParameterConfig.proto.m4: initial_strategy 0=normal(mean,std), 1=uniform,
initial_smart → std = 1/sqrt(fan_in)): biases init to zero unless
initial_mean/std say otherwise.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

from paddle_tpu.proto import ParameterConfig


def param_shape(cfg: ParameterConfig) -> Tuple[int, ...]:
    if cfg.dims:
        return tuple(int(d) for d in cfg.dims)
    return (int(cfg.size),)


def init_parameter(rng: jax.Array, cfg: ParameterConfig, dtype=jnp.float32) -> jax.Array:
    shape = param_shape(cfg)
    if cfg.initial_smart and len(shape) >= 2:
        # "smart" init: normal with std = 1/sqrt(fan_in); fan_in = dims[0]
        # (reference: config_parser sets initial_std via si/sqrt) — here we
        # honor it directly at init time.
        std = 1.0 / jnp.sqrt(jnp.asarray(float(shape[0])))
        return std * jax.random.normal(rng, shape, dtype)
    if cfg.initial_strategy == 1:
        # uniform in [mean - std, mean + std] — reference uniform strategy
        # uses initial_std as the half-width.
        lo = cfg.initial_mean - cfg.initial_std
        hi = cfg.initial_mean + cfg.initial_std
        return jax.random.uniform(rng, shape, dtype, minval=lo, maxval=hi)
    if cfg.initial_std == 0.0:
        return jnp.full(shape, cfg.initial_mean, dtype)
    return cfg.initial_mean + cfg.initial_std * jax.random.normal(rng, shape, dtype)
