"""Flash attention as a Pallas TPU kernel (O(T) memory local attention).

The XLA `full_attention` materializes the [B, H, T, T] score matrix; this
kernel streams K/V blocks through an online-softmax accumulator in VMEM so
activation memory stays O(T·D) — the per-chip building block that, combined
with ring attention (paddle_tpu.parallel.sequence_parallel), sets the max
context length. Forward saves only (out, logsumexp); backward recomputes
scores blockwise (flash-attention-2 style) in two kernels (dQ; dK/dV).

Layout: [B, H, T, D] inside the kernels (callers transpose from the
[B, T, H, D] sequence_parallel layout). T must divide the block sizes;
callers fall back to the XLA path otherwise (see
sequence_parallel.full_attention). Correctness is tested in interpret mode
on CPU against the XLA reference (tests/test_pallas_attention.py).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # unavailable when jax has no TPU platform registered (CPU test env)
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # noqa: BLE001
    pltpu = None

from paddle_tpu.ops.pallas_compat import compiler_params as _compiler_params

Array = jax.Array

_NEG = -1e30
BLOCK_Q = 128
BLOCK_K = 128


def _positions(start, n):
    return start + jax.lax.broadcasted_iota(jnp.int32, (n, 1), 0)[:, 0]


def _mask(q_pos, kv_pos, length, causal):
    m = kv_pos[None, :] < length
    if causal:
        m = m & (kv_pos[None, :] <= q_pos[:, None])
    return m


def _dot(a, b, dims):
    return jax.lax.dot_general(a, b, (dims, ((), ())), preferred_element_type=jnp.float32)


def _fwd_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, *, causal, block_k, scale):
    b = pl.program_id(0)
    iq = pl.program_id(2)
    bq, D = q_ref.shape[2], q_ref.shape[3]
    T = k_ref.shape[2]
    length = len_ref[b]
    q = q_ref[0, 0].astype(jnp.float32) * scale               # [bq, D]
    q_pos = _positions(iq * bq, bq)

    def body(ik, carry):
        o, m, l = carry
        kv_idx = (0, 0, pl.ds(ik * block_k, block_k), slice(None))
        k_blk = k_ref[kv_idx].astype(jnp.float32)
        v_blk = v_ref[kv_idx].astype(jnp.float32)
        kv_pos = _positions(ik * block_k, block_k)
        s = _dot(q, k_blk, ((1,), (1,)))                      # [bq, bk]
        msk = _mask(q_pos, kv_pos, length, causal)
        s = jnp.where(msk, s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        alpha = jnp.exp(m - m_new)
        p = jnp.where(msk, jnp.exp(s - m_new[:, None]), 0.0)
        l = l * alpha + jnp.sum(p, axis=1)
        o = o * alpha[:, None] + _dot(p, v_blk, ((1,), (0,)))
        return o, m_new, l

    n_k = (iq + 1) * bq // block_k if causal else T // block_k
    o0 = jnp.zeros((bq, D), jnp.float32)
    m0 = jnp.full((bq,), _NEG, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    o, m, l = jax.lax.fori_loop(0, n_k, body, (o0, m0, l0))
    l_safe = jnp.maximum(l, 1e-20)
    o_ref[0, 0] = (o / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[0, 0] = jnp.where(l > 0, m + jnp.log(l_safe), _NEG)


def _dq_kernel(len_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               *, causal, block_k, scale):
    b = pl.program_id(0)
    iq = pl.program_id(2)
    bq, D = q_ref.shape[2], q_ref.shape[3]
    T = k_ref.shape[2]
    length = len_ref[b]
    q = q_ref[0, 0].astype(jnp.float32)
    do = do_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0]
    delta = delta_ref[0, 0]
    q_pos = _positions(iq * bq, bq)

    def body(ik, dq):
        kv_idx = (0, 0, pl.ds(ik * block_k, block_k), slice(None))
        k_blk = k_ref[kv_idx].astype(jnp.float32)
        v_blk = v_ref[kv_idx].astype(jnp.float32)
        kv_pos = _positions(ik * block_k, block_k)
        s = _dot(q, k_blk, ((1,), (1,))) * scale
        msk = _mask(q_pos, kv_pos, length, causal)
        p = jnp.where(msk, jnp.exp(s - lse[:, None]), 0.0)
        dp = _dot(do, v_blk, ((1,), (1,)))
        ds = p * (dp - delta[:, None]) * scale
        return dq + _dot(ds, k_blk, ((1,), (0,)))

    n_k = (iq + 1) * bq // block_k if causal else T // block_k
    dq = jax.lax.fori_loop(0, n_k, body, jnp.zeros((bq, D), jnp.float32))
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _dkv_kernel(len_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, *, causal, block_q, scale):
    b = pl.program_id(0)
    ik = pl.program_id(2)
    bk, D = k_ref.shape[2], k_ref.shape[3]
    T = q_ref.shape[2]
    length = len_ref[b]
    k = k_ref[0, 0].astype(jnp.float32)
    v = v_ref[0, 0].astype(jnp.float32)
    kv_pos = _positions(ik * bk, bk)

    def body(jq, carry):
        dk, dv = carry
        q_idx = (0, 0, pl.ds(jq * block_q, block_q), slice(None))
        q_blk = q_ref[q_idx].astype(jnp.float32)
        do_blk = do_ref[q_idx].astype(jnp.float32)
        stat_idx = (0, 0, pl.ds(jq * block_q, block_q))
        lse_blk = lse_ref[stat_idx]
        delta_blk = delta_ref[stat_idx]
        q_pos = _positions(jq * block_q, block_q)
        s = _dot(q_blk, k, ((1,), (1,))) * scale              # [bq, bk]
        msk = _mask(q_pos, kv_pos, length, causal)
        p = jnp.where(msk, jnp.exp(s - lse_blk[:, None]), 0.0)
        dv = dv + _dot(p, do_blk, ((0,), (0,)))
        dp = _dot(do_blk, v, ((1,), (1,)))
        ds = p * (dp - delta_blk[:, None]) * scale
        dk = dk + _dot(ds, q_blk, ((0,), (0,)))
        return dk, dv

    start = ik * bk // block_q if causal else 0
    dk, dv = jax.lax.fori_loop(
        start, T // block_q, body,
        (jnp.zeros((bk, D), jnp.float32), jnp.zeros((bk, D), jnp.float32)),
    )
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def _len_spec(B):
    # full lengths vector visible to every program — scalar memory on TPU,
    # a plain whole-array block under the interpreter
    if pltpu is not None:
        return pl.BlockSpec(memory_space=pltpu.SMEM)
    return pl.BlockSpec((B,), lambda b, h, i: (0,))


def _run_fwd(q, k, v, lengths, causal, bq, bk, interpret):
    B, H, T, D = q.shape
    scale = 1.0 / math.sqrt(D)
    qspec = pl.BlockSpec((1, 1, bq, D), lambda b, h, i: (b, h, i, 0))
    kvspec = pl.BlockSpec((1, 1, T, D), lambda b, h, i: (b, h, 0, 0))
    lse_spec = pl.BlockSpec((1, 1, bq), lambda b, h, i: (b, h, i))
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, causal=causal, block_k=bk, scale=scale),
        grid=(B, H, T // bq),
        in_specs=[_len_spec(B), qspec, kvspec, kvspec],
        out_specs=[qspec, lse_spec],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, T, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, T), jnp.float32),
        ],
        interpret=interpret,
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
    )(lengths, q, k, v)
    # barrier: stop XLA's alternate-memory pass from pinning the whole
    # output in VMEM (scoped-vmem OOM on real chips)
    out, lse = jax.lax.optimization_barrier((out, lse))
    return out, lse


def _run_bwd(q, k, v, do, out, lse, lengths, causal, bq, bk, interpret):
    B, H, T, D = q.shape
    scale = 1.0 / math.sqrt(D)
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    qspec = pl.BlockSpec((1, 1, bq, D), lambda b, h, i: (b, h, i, 0))
    kv_full = pl.BlockSpec((1, 1, T, D), lambda b, h, i: (b, h, 0, 0))
    stat_q = pl.BlockSpec((1, 1, bq), lambda b, h, i: (b, h, i))
    stat_full = pl.BlockSpec((1, 1, T), lambda b, h, i: (b, h, 0))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, causal=causal, block_k=bk, scale=scale),
        grid=(B, H, T // bq),
        in_specs=[_len_spec(B), qspec, kv_full, kv_full, qspec, stat_q, stat_q],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((B, H, T, D), q.dtype),
        interpret=interpret,
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
    )(lengths, q, k, v, do, lse, delta)

    k_blk = pl.BlockSpec((1, 1, bk, D), lambda b, h, i: (b, h, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, causal=causal, block_q=bq, scale=scale),
        grid=(B, H, T // bk),
        in_specs=[_len_spec(B), kv_full, k_blk, k_blk, kv_full, stat_full, stat_full],
        out_specs=[k_blk, k_blk],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, T, D), k.dtype),
            jax.ShapeDtypeStruct((B, H, T, D), v.dtype),
        ],
        interpret=interpret,
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
    )(lengths, q, k, v, do, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _flash(q, k, v, lengths, causal, interpret):
    out, _ = _run_fwd(q, k, v, lengths, causal, BLOCK_Q, BLOCK_K, interpret)
    return out


def _flash_fwd(q, k, v, lengths, causal, interpret):
    out, lse = _run_fwd(q, k, v, lengths, causal, BLOCK_Q, BLOCK_K, interpret)
    return out, (q, k, v, out, lse, lengths)


def _flash_bwd(causal, interpret, res, g):
    q, k, v, out, lse, lengths = res
    dq, dk, dv = _run_bwd(q, k, v, g, out, lse, lengths, causal, BLOCK_Q, BLOCK_K, interpret)
    return dq, dk, dv, None


_flash.defvjp(_flash_fwd, _flash_bwd)


def supported(T: int, D: int) -> bool:
    """Shapes the kernel handles: T divisible by the block sizes."""
    return T % BLOCK_Q == 0 and T % BLOCK_K == 0 and D <= 256


def tpu_flash_attention(
    q: Array, k: Array, v: Array,
    lengths: Optional[Array] = None,
    causal: bool = False,
) -> Array:
    """Flash attention on a real TPU via jax's production Mosaic kernel
    (jax.experimental.pallas.ops.tpu.flash_attention), with padding masked
    through segment ids (valid positions = segment 1, padding = 0 → no
    cross-attention between them). Layout [B, T, H, D] like
    sequence_parallel. The hand-rolled kernels above remain the
    interpret-mode-tested specification of the same math; the library
    kernel carries the battle-tested Mosaic scheduling on hardware.
    """
    from jax.experimental.pallas.ops.tpu import flash_attention as fa

    B, T, H, D = q.shape
    qt, kt, vt = (jnp.transpose(x, (0, 2, 1, 3)) for x in (q, k, v))
    segment_ids = None
    if lengths is not None:
        valid = (jnp.arange(T)[None, :] < lengths[:, None]).astype(jnp.int32)
        segment_ids = fa.SegmentIds(q=valid, kv=valid)
    out = fa.flash_attention(
        qt, kt, vt,
        causal=causal,
        segment_ids=segment_ids,
        sm_scale=1.0 / math.sqrt(D),
    )
    return jnp.transpose(out, (0, 2, 1, 3))


def flash_attention(
    q: Array, k: Array, v: Array,
    lengths: Optional[Array] = None,
    causal: bool = False,
    interpret: bool = False,
) -> Array:
    """Flash attention over [B, T, H, D] (the sequence_parallel layout)."""
    B, T, H, D = q.shape
    assert supported(T, D), f"unsupported shape T={T}, D={D}"
    if lengths is None:
        lengths = jnp.full((B,), T, jnp.int32)
    qt, kt, vt = (jnp.transpose(x, (0, 2, 1, 3)) for x in (q, k, v))
    out = _flash(qt, kt, vt, jnp.asarray(lengths, jnp.int32), causal, interpret)
    return jnp.transpose(out, (0, 2, 1, 3))
