"""Shims over jax.experimental.pallas.tpu API drift.

The TPU compiler-params class was renamed across jax releases
(``TPUCompilerParams`` -> ``CompilerParams``); kernels call
:func:`compiler_params` instead of naming either class, so one wheel of
this package runs on both sides of the rename (and degrades to None —
"no params" — when pallas TPU support is absent entirely, e.g. CPU-only
installs running kernels in interpret mode).
"""

from __future__ import annotations

try:  # unavailable when jax has no TPU platform registered (CPU test env)
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # noqa: BLE001
    pltpu = None

_PARAMS_CLS = None
if pltpu is not None:
    _PARAMS_CLS = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams", None
    )


def compiler_params(**kwargs):
    """TPU compiler params under whichever name this jax exposes, or
    None when pallas TPU support (or the class) is unavailable."""
    if _PARAMS_CLS is None:
        return None
    return _PARAMS_CLS(**kwargs)
