"""Activation functions.

The reference registers 14 activation types by name
(/root/reference/paddle/gserver/activations/ActivationFunction.cpp:86-308)
with hand-written forward/backward; here each is a pure jax function (XLA
fuses it into the producing matmul; jax.grad supplies the backward).

``sequence_softmax`` normalizes over the *time* axis of a padded sequence
using the validity mask — the replacement for the reference's ragged
per-sequence softmax.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from paddle_tpu.ops.precision import hp
from paddle_tpu.utils.registry import Registry

Array = jax.Array

# activation(value, mask) -> value. mask is [B, T] (or None for non-seq).
activation_registry: Registry[Callable] = Registry("activation")

# strictly-elementwise activations (commute with any layout permutation,
# so e.g. the vision layers' NHWC fast path may apply them pre-flatten).
# _simple registrations are elementwise by construction; axis-dependent
# ones (softmax families) must never appear here.
ELEMENTWISE_ACTS = set()


def is_elementwise(name: str) -> bool:
    return name in ELEMENTWISE_ACTS


def _simple(name: str):
    def deco(fn):
        activation_registry.register_obj(name, lambda x, mask=None: fn(x))
        ELEMENTWISE_ACTS.add(name)
        return fn

    return deco


@_simple("")
@_simple("linear")
def identity(x: Array) -> Array:
    return x


@_simple("sigmoid")
def sigmoid(x: Array) -> Array:
    return jax.nn.sigmoid(x)


@_simple("tanh")
def tanh(x: Array) -> Array:
    return jnp.tanh(x)


@_simple("stanh")
def stanh(x: Array) -> Array:
    # scaled tanh: 1.7159 * tanh(2/3 x) (LeCun) — matches reference STanh.
    return 1.7159 * jnp.tanh((2.0 / 3.0) * x)


@_simple("relu")
def relu(x: Array) -> Array:
    return jax.nn.relu(x)


@_simple("brelu")
def brelu(x: Array) -> Array:
    # bounded relu: clip to [0, 24] (reference BRelu bound).
    return jnp.clip(x, 0.0, 24.0)


@_simple("softrelu")
def softrelu(x: Array) -> Array:
    # log(1 + e^x), with the reference's +-40 input clamp for stability.
    return jnp.log1p(jnp.exp(jnp.clip(x, -40.0, 40.0)))


@_simple("abs")
def abs_act(x: Array) -> Array:
    return jnp.abs(x)


@_simple("square")
def square(x: Array) -> Array:
    return x * x


@_simple("exponential")
def exponential(x: Array) -> Array:
    return jnp.exp(x)


def softmax(x: Array, mask: Optional[Array] = None) -> Array:
    # feature-axis softmax (last dim); the exp/sum runs in f32 even for
    # bf16 activations (mixed-precision islands), result returns narrow
    return jax.nn.softmax(hp(x), axis=-1).astype(x.dtype)


activation_registry.register_obj("softmax", softmax)


def sequence_softmax(x: Array, mask: Optional[Array] = None) -> Array:
    """Softmax across timesteps of each sequence.

    x: [B, T, 1] (or [B, T]) scores; mask: [B, T] validity. Padded steps get
    probability 0. Replaces the reference's per-sequence ragged softmax
    (SequenceSoftmaxActivation).
    """
    squeeze = x.ndim == 3
    s = x[..., 0] if squeeze else x
    s = hp(s)  # f32 island
    if mask is not None:
        s = jnp.where(mask > 0, s, -jnp.inf)
    out = jax.nn.softmax(s, axis=-1)
    if mask is not None:
        out = jnp.where(mask > 0, out, 0.0)
    out = out.astype(x.dtype)
    return out[..., None] if squeeze else out


activation_registry.register_obj("sequence_softmax", sequence_softmax)


def apply_activation(name: str, x: Array, mask: Optional[Array] = None) -> Array:
    return activation_registry.get(name)(x, mask)
