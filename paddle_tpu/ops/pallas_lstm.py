"""Fused LSTM sequence kernel (Pallas TPU): the whole time scan in ONE
kernel launch.

The XLA path (`layers/recurrent.py` ``_scan_time``) compiles the LSTM to a
`lax.while` whose per-step body is a small [B, H]x[H, 4H] matmul plus ~7
separate gate/mask/slice fusions — on the traced bench leg those per-step
fusions are ~36% of device time and the while-loop wrappers dominate the
rest. Here one Pallas kernel walks the sequential grid over T with the
recurrent weight and the (h, c) carry resident in VMEM: per step, one MXU
dot plus VPU gate math, no HBM round-trips for the carry and no per-step
kernel launches. Backward is a second sequential kernel (reverse grid)
that accumulates dW / peephole grads in VMEM across steps — the classic
fused-LSTM backward.

Cell semantics are exactly `lstm_cell_step` (reference LstmLayer.cpp /
LstmCompute.cu contract, see layers/recurrent.py:79): gate order
[candidate, input, forget, output]; bias = 4 gate biases + 3 peephole
vectors; carry masking keeps padded steps transparent. Activation
derivatives are computed from the SAVED post-activation values (tanh' =
1-y², sigmoid' = y(1-y)), so the forward saves (a, i, f, o) once and the
backward rebuilds everything else.

Correctness: interpret-mode parity against the XLA scan path in
tests/test_pallas_lstm.py (forward + grads, masked + reversed + peephole
cases). Enabled per-config via settings(pallas_rnn=True); the layer
falls back to the scan path for unsupported shapes/activations.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # unavailable when jax has no TPU platform registered (CPU test env)
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # noqa: BLE001
    pltpu = None

Array = jax.Array

_ACTS = ("tanh", "sigmoid", "relu", "linear")


def _act(name: str, v: Array) -> Array:
    if name == "tanh":
        return jnp.tanh(v)
    if name == "sigmoid":
        return jax.nn.sigmoid(v)
    if name == "relu":
        return jnp.maximum(v, 0.0)
    return v  # linear


def _dact(name: str, y: Array) -> Array:
    """Derivative from the SAVED post-activation value y = act(x)."""
    if name == "tanh":
        return 1.0 - y * y
    if name == "sigmoid":
        return y * (1.0 - y)
    if name == "relu":
        return (y > 0.0).astype(y.dtype)
    return jnp.ones_like(y)  # linear


# VMEM budget for one kernel invocation (per-core VMEM is ~16MB; leave
# headroom for the compiler's own buffers). The backward kernel is the
# binding case: it holds the recurrent weight, an f32 dW accumulator,
# carry scratch, and double-buffered per-step blocks simultaneously —
# configurations over budget fall back to the scan path instead of dying
# in a VMEM-exceeded compile error. (bf16 flagship shapes: LSTM
# B=256,H=512 ≈ 12.3MB; GRU encoder B=256,H=512 ≈ 8MB; an H=1024 LSTM
# ≈ 25MB is correctly rejected.) PADDLE_TPU_PALLAS_VMEM_BUDGET (bytes)
# overrides for A/B experiments near the boundary — the measured edge:
# the GRU at B=448 compiles, at B=512 Mosaic rejects (2026-08-01).
_VMEM_BUDGET_BYTES = (
    int(os.environ.get("PADDLE_TPU_PALLAS_VMEM_BUDGET", 0)) or 14 * 1024 * 1024
)


def _bwd_vmem_bytes(B: int, H: int, gates: int, itemsize: int,
                    f32_state: bool) -> int:
    w_and_dw = H * gates * H * (itemsize + 4)
    per_step_in = B * gates * H * itemsize + 2 * B * H * itemsize
    if f32_state:
        per_step_in += B * H * 4                   # saved c_prev rides in f32
    out_block = B * gates * H * itemsize
    scratch = (2 if f32_state else 1) * B * H * 4
    return w_and_dw + 2 * per_step_in + out_block + scratch


def shape_ok(acts, B: int, H: int, gates: int, itemsize: int,
             f32_state: bool) -> bool:
    """Shared kernel gate: TPU pallas available, whitelisted activations,
    MXU-friendly tiling, and the backward's VMEM residency fits."""
    return (
        pltpu is not None  # kernels need TPU scratch shapes even interpreted
        and all(a in _ACTS for a in acts)
        and H % 128 == 0 and B % 8 == 0
        and _bwd_vmem_bytes(B, H, gates, itemsize, f32_state) < _VMEM_BUDGET_BYTES
    )


def supported(act_in: str, act_gate: str, act_state: str, B: int, H: int,
              itemsize: int = 4) -> bool:
    return shape_ok((act_in, act_gate, act_state), B, H, gates=4,
                    itemsize=itemsize, f32_state=True)


def _split4(g: Array, H: int):
    return g[:, :H], g[:, H : 2 * H], g[:, 2 * H : 3 * H], g[:, 3 * H :]


def _load_step(ref, flat: bool):
    """Per-step [B, width] tile: 2-D block in flat mode, [0] of a
    (1, B, width) time-major block otherwise (shared by both kernels)."""
    return ref[...] if flat else ref[0]


def _store_step(ref, v, flat: bool):
    if flat:
        ref[...] = v
    else:
        ref[0] = v


def _cell_fwd(x4_ref, w_ref, peep_ref, h_scr, c_scr, act_in, act_gate,
              act_state, flat=False):
    """One forward cell step from the VMEM carry; returns everything the
    residual-saving kernel needs. ``flat`` = the x4 block is the 2-D
    [B, 4H] lane slice of a [B, T*4H] array (see _run_fwd)."""
    H = w_ref.shape[0]
    h_prev = h_scr[:]                                   # [B, H] f32
    c_prev = c_scr[:]
    w = w_ref[:]
    x4 = _load_step(x4_ref, flat).astype(jnp.float32)   # [B, 4H]
    gates = x4 + jax.lax.dot(
        h_prev.astype(w.dtype), w, preferred_element_type=jnp.float32
    )
    peep = peep_ref[:].astype(jnp.float32)              # [3, H]
    pi, pf, po = peep[0:1], peep[1:2], peep[2:3]        # [1, H] each
    ga, gi, gf, go = _split4(gates, H)
    i = _act(act_gate, gi + pi * c_prev)
    f = _act(act_gate, gf + pf * c_prev)
    a = _act(act_in, ga)
    c_new = f * c_prev + i * a
    o = _act(act_gate, go + po * c_new)
    h_new = o * _act(act_state, c_new)
    return h_prev, c_prev, h_new, c_new, a, i, f, o


def _fwd_kernel(x4_ref, m_ref, w_ref, peep_ref,
                y_ref, acts_ref, hprev_ref, cprev_ref,
                h_scr, c_scr, *, act_in, act_gate, act_state, flat=False):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        h_scr[:] = jnp.zeros_like(h_scr)
        c_scr[:] = jnp.zeros_like(c_scr)

    h_prev, c_prev, h_new, c_new, a, i, f, o = _cell_fwd(
        x4_ref, w_ref, peep_ref, h_scr, c_scr, act_in, act_gate, act_state,
        flat,
    )
    m = m_ref[0].astype(jnp.float32)                    # [B, 1]

    hprev_ref[0] = h_prev.astype(hprev_ref.dtype)       # residuals (pre-update)
    cprev_ref[0] = c_prev
    acts_ref[0] = jnp.concatenate([a, i, f, o], axis=1).astype(acts_ref.dtype)
    _store_step(y_ref, (m * h_new).astype(y_ref.dtype), flat)
    h_scr[:] = m * h_new + (1.0 - m) * h_prev
    c_scr[:] = m * c_new + (1.0 - m) * c_prev


def _fwd_kernel_light(x4_ref, m_ref, w_ref, peep_ref, y_ref,
                      h_scr, c_scr, *, act_in, act_gate, act_state,
                      flat=False):
    """Inference/eval variant: ys only, no residual writes (pallas outputs
    are never DCE'd, so the primal must not emit them at all)."""
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        h_scr[:] = jnp.zeros_like(h_scr)
        c_scr[:] = jnp.zeros_like(c_scr)

    h_prev, c_prev, h_new, c_new, _a, _i, _f, _o = _cell_fwd(
        x4_ref, w_ref, peep_ref, h_scr, c_scr, act_in, act_gate, act_state,
        flat,
    )
    m = m_ref[0].astype(jnp.float32)
    _store_step(y_ref, (m * h_new).astype(y_ref.dtype), flat)
    h_scr[:] = m * h_new + (1.0 - m) * h_prev
    c_scr[:] = m * c_new + (1.0 - m) * c_prev


def _bwd_kernel(dy_ref, acts_ref, hprev_ref, cprev_ref, m_ref, w_ref, peep_ref,
                dx4_ref, dw_ref, dpeep_ref,
                dh_scr, dc_scr, *, act_in, act_gate, act_state, flat=False):
    idx = pl.program_id(0)  # walks t = T-1 .. 0 via the index maps

    @pl.when(idx == 0)
    def _init():
        dh_scr[:] = jnp.zeros_like(dh_scr)
        dc_scr[:] = jnp.zeros_like(dc_scr)
        dw_ref[:] = jnp.zeros_like(dw_ref)
        dpeep_ref[:] = jnp.zeros_like(dpeep_ref)

    H = w_ref.shape[0]
    acts = acts_ref[0].astype(jnp.float32)
    a, i, f, o = _split4(acts, H)
    c_prev = cprev_ref[0]
    h_prev = hprev_ref[0]
    m = m_ref[0].astype(jnp.float32)
    peep = peep_ref[:].astype(jnp.float32)
    pi, pf, po = peep[0:1], peep[1:2], peep[2:3]
    DH = dh_scr[:]
    DC = dc_scr[:]

    c_new = f * c_prev + i * a
    s_c = _act(act_state, c_new)
    dy = _load_step(dy_ref, flat).astype(jnp.float32)
    dh_new = m * (DH + dy)                    # cell path; (1-m) passes through
    dgo = dh_new * s_c * _dact(act_gate, o)
    dc_new = dh_new * o * _dact(act_state, s_c) + m * DC + dgo * po
    dgi = dc_new * a * _dact(act_gate, i)
    dgf = dc_new * c_prev * _dact(act_gate, f)
    dga = dc_new * i * _dact(act_in, a)
    dgates = jnp.concatenate([dga, dgi, dgf, dgo], axis=1)   # [B, 4H]
    _store_step(dx4_ref, dgates.astype(dx4_ref.dtype), flat)

    w = w_ref[:]
    dh_prev = jax.lax.dot_general(
        dgates.astype(w.dtype), w, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                                        # [B, H]
    dh_scr[:] = dh_prev + (1.0 - m) * DH
    dc_scr[:] = dc_new * f + dgi * pi + dgf * pf + (1.0 - m) * DC
    dw_ref[:] += jax.lax.dot_general(
        h_prev.astype(jnp.float32), dgates, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                                        # [H, 4H]
    dpeep_ref[:] += jnp.concatenate(
        [
            jnp.sum(dgi * c_prev, axis=0, keepdims=True),
            jnp.sum(dgf * c_prev, axis=0, keepdims=True),
            jnp.sum(dgo * c_new, axis=0, keepdims=True),
        ],
        axis=0,
    )                                                        # [3, H]


def _params(n):
    from paddle_tpu.ops.pallas_compat import compiler_params

    return compiler_params(dimension_semantics=("arbitrary",) * n)


def _run_fwd(x4, mask_tb1, w, peep, acts, interpret, residuals=True,
             flat=False):
    """``flat``: x4 is [B, T*4H] (the x-projection's natural row-major
    reshape) and ys comes back [B, T*H]; the per-step blocks are the
    same [B, 4H]/[B, H] tiles, addressed at lane offset t*width, so the
    boundary transposes the time-major interface forced on the x4/ys
    cotangent path disappear (measured 16.9% of the pallas-leg step —
    benchmarks/RESULTS.md round-5 trace note). Residual streams stay
    time-major: they never cross the kernel boundary."""
    if flat:
        B = mask_tb1.shape[1]
        T = mask_tb1.shape[0]
        H4 = x4.shape[1] // T
    else:
        T, B, H4 = x4.shape
    H = H4 // 4
    step_spec4 = pl.BlockSpec((1, B, H4), lambda t: (t, 0, 0))
    step_spec = pl.BlockSpec((1, B, H), lambda t: (t, 0, 0))
    if flat:
        x_spec = pl.BlockSpec((B, H4), lambda t: (0, t))
        y_spec = pl.BlockSpec((B, H), lambda t: (0, t))
        ys_shape = jax.ShapeDtypeStruct((B, T * H), x4.dtype)
    else:
        x_spec, y_spec = step_spec4, step_spec
        ys_shape = jax.ShapeDtypeStruct((T, B, H), x4.dtype)
    # mask rides time-major as [T, B, 1] so the block's last two dims are
    # (B, 1) with the lane dim EQUAL to the overall array's — Mosaic
    # rejects a (B, 1) block over a [B, T] array (lane dim 1 is neither
    # 128-divisible nor the full T)
    mask_spec = pl.BlockSpec((1, B, 1), lambda t: (t, 0, 0))
    const2 = lambda shape: pl.BlockSpec(shape, lambda t: (0, 0))
    kern = functools.partial(
        _fwd_kernel if residuals else _fwd_kernel_light,
        act_in=acts[0], act_gate=acts[1], act_state=acts[2], flat=flat,
    )
    out_specs = [y_spec]
    out_shape = [ys_shape]
    if residuals:
        out_specs += [step_spec4, step_spec, step_spec]
        out_shape += [
            jax.ShapeDtypeStruct((T, B, H4), x4.dtype),      # acts (a,i,f,o)
            jax.ShapeDtypeStruct((T, B, H), x4.dtype),       # h_prev
            jax.ShapeDtypeStruct((T, B, H), jnp.float32),    # c_prev
        ]
    return pl.pallas_call(
        kern,
        grid=(T,),
        in_specs=[x_spec, mask_spec, const2(w.shape), const2(peep.shape)],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((B, H), jnp.float32),
            pltpu.VMEM((B, H), jnp.float32),
        ] if pltpu is not None else [],
        interpret=interpret,
        compiler_params=_params(1),
    )(x4, mask_tb1, w, peep)


def _run_bwd(dy, saved, mask_tb1, w, peep, acts, interpret, flat=False):
    acts_seq, hprev, cprev = saved
    T, B, H4 = acts_seq.shape
    H = H4 // 4
    rev4 = pl.BlockSpec((1, B, H4), lambda i: (T - 1 - i, 0, 0))
    rev = pl.BlockSpec((1, B, H), lambda i: (T - 1 - i, 0, 0))
    if flat:
        dy_spec = pl.BlockSpec((B, H), lambda i: (0, T - 1 - i))
        dx_spec = pl.BlockSpec((B, H4), lambda i: (0, T - 1 - i))
        dx_shape = jax.ShapeDtypeStruct((B, T * H4), dy.dtype)
    else:
        dy_spec, dx_spec = rev, rev4
        dx_shape = jax.ShapeDtypeStruct((T, B, H4), dy.dtype)
    mask_spec = pl.BlockSpec((1, B, 1), lambda i: (T - 1 - i, 0, 0))
    const2 = lambda shape: pl.BlockSpec(shape, lambda i: (0, 0))
    kern = functools.partial(
        _bwd_kernel, act_in=acts[0], act_gate=acts[1], act_state=acts[2],
        flat=flat,
    )
    dx4, dw, dpeep = pl.pallas_call(
        kern,
        grid=(T,),
        in_specs=[dy_spec, rev4, rev, rev, mask_spec, const2(w.shape), const2(peep.shape)],
        out_specs=[dx_spec, const2(w.shape), const2(peep.shape)],
        out_shape=[
            dx_shape,
            jax.ShapeDtypeStruct(w.shape, jnp.float32),
            jax.ShapeDtypeStruct(peep.shape, jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((B, H), jnp.float32),
            pltpu.VMEM((B, H), jnp.float32),
        ] if pltpu is not None else [],
        interpret=interpret,
        compiler_params=_params(1),
    )(dy, acts_seq, hprev, cprev, mask_tb1, w, peep)
    return dx4, dw.astype(w.dtype), dpeep.astype(peep.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def fused_lstm(x4, mask, w, peep, acts, interpret, flat=False):
    """Masked LSTM over the whole sequence in one kernel launch.

    Time-major interface (flat=False): x4 [T, B, 4H], ys [T, B, H].
    Flat interface (flat=True): x4 [B, T*4H] — the x-projection's
    row-major reshape, no transpose — and ys [B, T*H]; removes the
    boundary transposes on the x4/ys cotangent path (a measured 16.9%
    of the pallas-leg step). mask is [T, B] in BOTH modes (tiny).
    x4 carries the gate biases already added; w [H, 4H]; peep [3, H]
    (zeros when absent); acts = (act_in, act_gate, act_state).
    """
    from paddle_tpu.ops import kernel_flops

    T, B = mask.shape
    H4 = x4.shape[2] if not flat else x4.shape[1] // T
    kernel_flops.record(kernel_flops.lstm_fwd_flops(T, B, H4 // 4))
    (ys,) = _run_fwd(x4, mask[:, :, None], w, peep, acts, interpret,
                     residuals=False, flat=flat)
    return ys


def _fused_fwd(x4, mask, w, peep, acts, interpret, flat=False):
    from paddle_tpu.ops import kernel_flops

    T, B = mask.shape
    H4 = x4.shape[2] if not flat else x4.shape[1] // T
    kernel_flops.record(kernel_flops.lstm_fwd_flops(T, B, H4 // 4))
    ys, acts_seq, hprev, cprev = _run_fwd(
        x4, mask[:, :, None], w, peep, acts, interpret, flat=flat
    )
    return ys, (acts_seq, hprev, cprev, mask, w, peep)


def _fused_bwd(acts, interpret, flat, res, dy):
    from paddle_tpu.ops import kernel_flops

    acts_seq, hprev, cprev, mask, w, peep = res
    T, B, H4 = acts_seq.shape
    kernel_flops.record(kernel_flops.lstm_bwd_flops(T, B, H4 // 4))
    dx4, dw, dpeep = _run_bwd(
        dy, (acts_seq, hprev, cprev), mask[:, :, None], w, peep, acts,
        interpret, flat=flat,
    )
    return dx4, jnp.zeros_like(mask), dw, dpeep


fused_lstm.defvjp(_fused_fwd, _fused_bwd)


def lstm_layer_forward(cfg, x, mask, w, bias, interpret, x_bt=None):
    """The lstmemory layer body on the fused kernel: returns ys
    [T, B, H] (time-major interface) or [B, T, H] (x_bt flat interface).

    x: [T, B, 4H] (pre-bias x-projection), mask: [T, B], w: [H, 4H],
    bias: [7H] (4 gate biases + 3 peepholes) or None. Handles
    cfg.reversed by flipping time outside the kernel (padded steps then
    run first with mask 0, which leaves the carry at init — the same
    semantics as lax.scan(reverse=True) with carry masking).

    ``x_bt`` (PADDLE_TPU_PALLAS_FLAT=1): the batch-major [B, T, 4H]
    projection output — the kernel then runs on its free row-major
    [B, T*4H] reshape and returns ys without any boundary transpose
    (the time-major interface's x4/ys/dx4 relayouts were a measured
    16.9% of the pallas-leg step)."""
    H = cfg.size
    flat = x_bt is not None
    T = mask.shape[0]
    if flat:
        x = x_bt
        if bias is not None:
            x = x + bias[: 4 * H].astype(x.dtype)
        if cfg.reversed:
            x = jnp.flip(x, 1)
            mask = jnp.flip(mask, 0)
        x = x.reshape(x.shape[0], T * 4 * H)
    elif bias is not None:
        x = x + bias[: 4 * H].astype(x.dtype)
    if bias is not None:
        peep = jnp.stack(
            [bias[4 * H : 5 * H], bias[5 * H : 6 * H], bias[6 * H : 7 * H]]
        )
    else:
        peep = jnp.zeros((3, H), x.dtype)
    if not flat and cfg.reversed:
        x = jnp.flip(x, 0)
        mask = jnp.flip(mask, 0)
    acts = (
        cfg.active_type or "tanh",
        cfg.active_gate_type or "sigmoid",
        cfg.active_state_type or "sigmoid",
    )
    ys = fused_lstm(x, mask, w, peep, acts, interpret, flat)
    if flat:
        ys = ys.reshape(ys.shape[0], T, H)
        if cfg.reversed:
            ys = jnp.flip(ys, 1)
        return ys                          # batch-major [B, T, H]
    if cfg.reversed:
        ys = jnp.flip(ys, 0)
    return ys                              # time-major [T, B, H]


def usable(cfg, x) -> bool:
    """Shapes/activations the kernel handles (layer falls back otherwise)."""
    T, B, H4 = x.shape
    if x.dtype not in (jnp.float32, jnp.bfloat16) or H4 != 4 * cfg.size:
        return False
    return supported(
        cfg.active_type or "tanh",
        cfg.active_gate_type or "sigmoid",
        cfg.active_state_type or "sigmoid",
        B,
        cfg.size,
        itemsize=jnp.dtype(x.dtype).itemsize,
    )
