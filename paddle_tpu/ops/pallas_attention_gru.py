"""Fused attention-GRU decoder-step kernel (Pallas TPU).

The seqToseq NMT decoder's per-step machinery — Bahdanau attention
(transform/combine/softmax/scaling/pooling), the context projection and
the GRU cell — is ~57% of the measured NMT train step (the
2026-08-01 traces_nmt_flat summary: per-step scan/while bodies and
their small fusions), because every decoder step pays XLA while-loop
bookkeeping plus a handful of sub-MXU kernel launches. This kernel runs
the WHOLE decoder time loop in one launch, batch-blocked so the encoder
states stay VMEM-resident across all decoder steps of a batch block:

    grid = (B/bB, Td), b outer, t inner
    resident per b-block: enc_proj [Te,bB,D], enc_vec [Te,bB,E],
        W_att [D,D], v [D], W_ctx [E,3D], W_gru [D,3D], carry h [bB,D]

Per step (semantics exactly the step-graph layers they replace —
trainer_config_helpers.networks.simple_attention (ref networks.py:943),
layers/sequence.py sequence pooling, layers/recurrent.py gru_cell_step
(ref GruStepLayer.cpp)):

    m_t   = h @ W_att + b_att                     (attention transform,
                                                   combine bias folded)
    s_t   = sum_D(tanh(ep + m_t) * v)             [Te, bB] scores
    a_t   = masked softmax over Te (f32, pads 0)  (sequence_softmax)
    ctx_t = sum_Te(a_t * ev)                      [bB, E] (sum pooling)
    din_t = ctx_t @ W_ctx + xw_t                  (mixed projection; the
             word-side projection and every bias ride xw_t, which the
             recurrent group's prologue hoisting already computes as one
             time-parallel matmul)
    GRU(h, din_t) -> h_new; carry h = dmask ? h_new : h

The frontier output stream is the RAW h_new (matching the scan path,
which masks only the carry and the out-link; the hoisted epilogue masks
at the end). Backward is a reverse-grid kernel: dW_att/dv/db_att and
d_enc_proj accumulate in VMEM f32; dW_gru, dW_ctx and d_enc_vec are
reconstructed OUTSIDE from the streamed (h_prev, r, d_din),
(ctx, d_din) and (alpha, d_ctx) pairs as large time-parallel matmuls —
keeping the backward kernel inside the 14MB VMEM budget (the measured
ceiling discipline from ops/pallas_lstm.py) and the sequential critical
path free of weight-gradient dots. Forward and backward size their
batch blocks independently (fwd bb=64 / bwd bb=32 at flagship shapes).

Correctness: interpret-mode parity vs the unfused recurrent-group scan
in tests/test_fused_decoder.py. Enabled via
settings(pallas_decoder=True) — a separate knob from pallas_rnn so the
unmeasured kernel can never silently become a default.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from paddle_tpu.ops.pallas_lstm import _act, _dact, _params, pltpu

Array = jax.Array

_VMEM_BUDGET_BYTES = (
    int(os.environ.get("PADDLE_TPU_PALLAS_VMEM_BUDGET", 0)) or 14 * 1024 * 1024
)


def _pick_bb(B: int, vmem_fn=None) -> int | None:
    """Largest batch block that divides B AND keeps the calling kernel
    under the VMEM budget (``vmem_fn(bb) -> bytes``). Forward and
    backward pick INDEPENDENTLY — they communicate only through
    [Td,B,*]/[Te,B,*] HBM streams, and the forward is ~2x lighter (no
    dW/d_enc accumulators), so it gets larger, better-MXU-filling row
    blocks (bb=64 vs the backward's 32 at flagship shapes)."""
    for bb in (64, 32, 16, 8):
        if B % bb != 0:
            continue
        if vmem_fn is not None and vmem_fn(bb) >= _VMEM_BUDGET_BYTES:
            continue
        return bb
    if B < 8 and (vmem_fn is None or vmem_fn(B) < _VMEM_BUDGET_BYTES):
        return B
    return None


def _vmem_fwd(bb: int, Te: int, D: int, E: int, itemsize: int,
              residuals: bool = True) -> int:
    enc_in = Te * bb * (D + E + 1) * itemsize      # ep + ev + emask blocks
    w_in = (D * D + E * 3 * D + D * 3 * D + 2 * D) * itemsize
    step_widths = 3 * D + 1 + D                    # xw + dmask + ys
    if residuals:
        step_widths += D + 3 * D + Te + E          # h_prev, acts, alpha, ctx
    steps = 2 * bb * step_widths * itemsize
    scr = bb * D * 4
    # the attention step materializes `combined` (tanh(ep + m)) as a
    # live [Te,bB,D] f32 temporary every iteration — the largest single
    # buffer in the step and previously unaccounted, so marginal shapes
    # passed the estimate and OOM'd VMEM at compile time
    tmp = Te * bb * D * 4
    return enc_in + w_in + steps + scr + tmp


def _vmem_bwd(bb: int, Te: int, D: int, E: int, itemsize: int) -> int:
    """dW_gru/dW_ctx/d_enc_vec live OUTSIDE the kernel (rebuilt from the
    streamed pairs); in-kernel f32 accumulators are dW_att, db_att, dv
    and the d_enc_proj block."""
    enc_in = Te * bb * (D + E + 1) * itemsize
    w_in = (D * D + E * 3 * D + D * 3 * D + 2 * D) * itemsize
    dw_acc = (D * D + 2 * D) * 4                   # dW_att + db_att + dv f32
    dep_acc = Te * bb * D * 4                      # d_enc_proj f32
    steps = 2 * bb * (D + 1 + D + 3 * D + Te + 3 * D + E) * itemsize
    scr = bb * D * 4
    # the attention backward recomputes `combined` and holds `d_comb`
    # and `dtanh` beside it — three live [Te,bB,D] f32 temporaries per
    # step (see _bwd_step), previously unaccounted in the estimate
    tmp = 3 * Te * bb * D * 4
    return enc_in + w_in + dw_acc + dep_acc + steps + scr + tmp


def supported(B: int, Te: int, D: int, E: int, itemsize: int = 2) -> bool:
    if pltpu is None:
        return False
    if D % 128 != 0 or E % 128 != 0:
        return False
    bwd = lambda bb: _vmem_bwd(bb, Te, D, E, itemsize)
    return _pick_bb(B, bwd) is not None


# --------------------------------------------------------------- forward


def _attention(ep, em, v, m, Te):
    """Scores + masked softmax + d-less pieces shared by fwd/bwd.

    ep [Te,bB,D] f32-able, em [Te,bB,1], v [1,D], m [bB,D].
    Returns (combined [Te,bB,D] f32, alpha [Te,bB] f32)."""
    f32 = jnp.float32
    combined = jnp.tanh(ep.astype(f32) + m.astype(f32)[None, :, :])
    s = jnp.sum(combined * v.astype(f32)[None, :, :], axis=-1)      # [Te,bB]
    s = jnp.where(em[:, :, 0] > 0, s, -1e30)
    smax = jnp.max(s, axis=0, keepdims=True)
    e = jnp.exp(s - smax)
    alpha = e / jnp.sum(e, axis=0, keepdims=True)
    alpha = jnp.where(em[:, :, 0] > 0, alpha, 0.0)
    return combined, alpha


def _gru(h_prev, din, wg, wc, act_in, act_gate, D):
    f32 = jnp.float32
    xg, xc = din[:, : 2 * D], din[:, 2 * D :]
    hp = h_prev.astype(wg.dtype)
    g = _act(act_gate, xg + jax.lax.dot(hp, wg, preferred_element_type=f32))
    u, r = g[:, :D], g[:, D:]
    cand = xc + jax.lax.dot(
        (r * h_prev).astype(wc.dtype), wc, preferred_element_type=f32
    )
    c = _act(act_in, cand)
    return u * h_prev + (1.0 - u) * c, u, r, c


def attention_gru_step(h_prev, ep, ev, em, xw_t, wa, ba, v, wctx, wg,
                       acts=("tanh", "sigmoid")):
    """ONE decoder step of the fused attention-GRU math, as a plain jnp
    function — the per-step seam for iteration-level (continuous-
    batching) decode, where the time loop lives on the HOST scheduler
    instead of inside a kernel grid or a ``lax.while_loop``. The
    serving engine wires it in behind ``--serve_fused_step``
    (graph/decode_step.plan_fused_step template-matches the generation
    step graph and feeds this function the extracted weights); a
    TPU-fused ``serve_decode`` kernel plugs into the same seam.

    Exactly the `_fwd_kernel` step body (attention transform → masked
    softmax → sum-pooled context → mixed projection → GRU), so the
    serve-side step and the training kernel cannot diverge; pinned
    against `fused_attention_gru` in tests/test_engine.py.

    Shapes: ``h_prev [B, D]``, ``ep [Te, B, D]`` (encoder projection),
    ``ev [Te, B, E]`` (encoder values), ``em [Te, B, 1]`` (encoder
    mask), ``xw_t [B, 3D]`` (the step's hoisted word-side projection,
    biases folded), weights as in :func:`fused_attention_gru`. Returns
    ``h_new [B, D]`` in f32."""
    f32 = jnp.float32
    act_in, act_gate = acts
    D = h_prev.shape[-1]
    m = jax.lax.dot(
        h_prev.astype(wa.dtype), wa, preferred_element_type=f32
    ) + ba.astype(f32)                                   # [B, D]
    _, alpha = _attention(ep, em, v.reshape(1, D), m, ep.shape[0])
    ctx = jnp.sum(alpha[:, :, None] * ev.astype(f32), axis=0)     # [B, E]
    din = jax.lax.dot(
        ctx.astype(wctx.dtype), wctx, preferred_element_type=f32
    ) + xw_t.astype(f32)                                 # [B, 3D]
    h_new, _, _, _ = _gru(
        h_prev.astype(f32), din, wg[:, : 2 * D], wg[:, 2 * D:],
        act_in, act_gate, D,
    )
    return h_new


def _fwd_kernel(ep_ref, ev_ref, em_ref, xw_ref, dm_ref, h0_ref,
                wa_ref, ba_ref, v_ref, wctx_ref, wg_ref,
                y_ref, hprev_ref, acts_ref, alpha_ref, ctx_ref,
                h_scr, *, act_in, act_gate, Te, D, residuals):
    t = pl.program_id(1)
    f32 = jnp.float32

    @pl.when(t == 0)
    def _init():
        h_scr[...] = h0_ref[...].astype(f32)

    h_prev = h_scr[...]                                  # [bB, D] f32
    m = jax.lax.dot(
        h_prev.astype(wa_ref.dtype), wa_ref[...], preferred_element_type=f32
    ) + ba_ref[...].astype(f32)                          # [bB, D]
    combined, alpha = _attention(ep_ref[...], em_ref[...], v_ref[...], m, Te)
    ev = ev_ref[...].astype(f32)                         # [Te, bB, E]
    ctx = jnp.sum(alpha[:, :, None] * ev, axis=0)        # [bB, E]
    din = jax.lax.dot(
        ctx.astype(wctx_ref.dtype), wctx_ref[...], preferred_element_type=f32
    ) + xw_ref[0].astype(f32)                            # [bB, 3D]
    wg_all = wg_ref[...]
    h_new, u, r, c = _gru(
        h_prev, din, wg_all[:, : 2 * D], wg_all[:, 2 * D :], act_in, act_gate, D
    )
    dm = dm_ref[0].astype(f32)                           # [bB, 1]
    y_ref[0] = h_new.astype(y_ref.dtype)                 # RAW frontier stream
    if residuals:
        hprev_ref[0] = h_prev.astype(hprev_ref.dtype)
        acts_ref[0] = jnp.concatenate([u, r, c], axis=1).astype(acts_ref.dtype)
        alpha_ref[0] = alpha.T.astype(alpha_ref.dtype)   # [bB, Te]
        ctx_ref[0] = ctx.astype(ctx_ref.dtype)
    h_scr[...] = dm * h_new + (1.0 - dm) * h_prev


def _run_fwd(ep, ev, em, xw, dmask, h0, wa, ba, v, wctx, wg,
             acts, interpret, residuals=True):
    Te, B, D = ep.shape
    E = ev.shape[2]
    Td = xw.shape[0]
    # interpret mode (CPU parity tests) takes any shape: fall back to a
    # single whole-batch block when no hardware block fits
    bb = _pick_bb(
        B, lambda n: _vmem_fwd(n, Te, D, E, ep.dtype.itemsize, residuals)
    ) or (B if interpret else None)
    assert bb is not None, (B, Te, D, E)  # callers gate on supported()
    enc3 = lambda width: pl.BlockSpec((Te, bb, width), lambda b, t: (0, b, 0))
    step = lambda width: pl.BlockSpec((1, bb, width), lambda b, t: (t, b, 0))
    wspec = lambda shp: pl.BlockSpec(shp, lambda b, t: (0, 0))
    bspec = pl.BlockSpec((bb, D), lambda b, t: (b, 0))
    kern = functools.partial(
        _fwd_kernel, act_in=acts[0], act_gate=acts[1], Te=Te, D=D,
        residuals=residuals,
    )
    out_specs = [step(D), step(D), step(3 * D), step(Te), step(E)]
    out_shape = [
        jax.ShapeDtypeStruct((Td, B, D), ep.dtype),       # raw h_new stream
        jax.ShapeDtypeStruct((Td, B, D), ep.dtype),       # h_prev residuals
        jax.ShapeDtypeStruct((Td, B, 3 * D), ep.dtype),   # u, r, c
        jax.ShapeDtypeStruct((Td, B, Te), ep.dtype),      # alpha
        jax.ShapeDtypeStruct((Td, B, E), ep.dtype),       # ctx
    ]
    if not residuals:
        out_specs, out_shape = out_specs[:1], out_shape[:1]
        kern = functools.partial(
            _fwd_kernel_light, act_in=acts[0], act_gate=acts[1], Te=Te, D=D
        )
    outs = pl.pallas_call(
        kern,
        grid=(B // bb, Td),
        in_specs=[
            enc3(D), enc3(E), enc3(1), step(3 * D), step(1), bspec,
            wspec(wa.shape), wspec(ba.shape), wspec(v.shape),
            wspec(wctx.shape), wspec(wg.shape),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((bb, D), jnp.float32)]
        if pltpu is not None
        else [],
        interpret=interpret,
        compiler_params=_params(2),
    )(ep, ev, em, xw, dmask, h0, wa, ba, v, wctx, wg)
    return outs


def _fwd_kernel_light(ep_ref, ev_ref, em_ref, xw_ref, dm_ref, h0_ref,
                      wa_ref, ba_ref, v_ref, wctx_ref, wg_ref, y_ref,
                      h_scr, *, act_in, act_gate, Te, D):
    _fwd_kernel(ep_ref, ev_ref, em_ref, xw_ref, dm_ref, h0_ref,
                wa_ref, ba_ref, v_ref, wctx_ref, wg_ref,
                y_ref, None, None, None, None, h_scr,
                act_in=act_in, act_gate=act_gate, Te=Te, D=D,
                residuals=False)


# -------------------------------------------------------------- backward


def _bwd_kernel(dy_ref, ep_ref, ev_ref, em_ref, dm_ref,
                hprev_ref, acts_ref, alpha_ref,
                wa_ref, ba_ref, v_ref, wctx_ref, wg_ref,
                dxw_ref, dctx_ref, dh0_ref, dep_ref,
                dwa_ref, dba_ref, dv_ref,
                dh_scr, *, act_in, act_gate, Te, D):
    b = pl.program_id(0)
    idx = pl.program_id(1)            # walks t = Td-1 .. 0 via index maps
    nb = pl.num_programs(0)
    nt = pl.num_programs(1)
    f32 = jnp.float32

    @pl.when(idx == 0)
    def _init_block():
        dh_scr[...] = jnp.zeros_like(dh_scr)
        dep_ref[...] = jnp.zeros_like(dep_ref)

    @pl.when((b == 0) & (idx == 0))
    def _init_weights():
        dwa_ref[...] = jnp.zeros_like(dwa_ref)
        dba_ref[...] = jnp.zeros_like(dba_ref)
        dv_ref[...] = jnp.zeros_like(dv_ref)

    h_prev = hprev_ref[0].astype(f32)                    # [bB, D]
    acts = acts_ref[0].astype(f32)
    u, r, c = acts[:, :D], acts[:, D : 2 * D], acts[:, 2 * D :]
    alpha = alpha_ref[0].astype(f32).T                   # [Te, bB]
    dmv = dm_ref[0].astype(f32)                          # [bB, 1]
    DH = dh_scr[...]

    # frontier stream is RAW h_new; carry is masked
    dh_new = dy_ref[0].astype(f32) + dmv * DH
    du = dh_new * (h_prev - c)
    dcand = dh_new * (1.0 - u) * _dact(act_in, c)
    wg_all = wg_ref[...]
    wgg, wgc = wg_all[:, : 2 * D], wg_all[:, 2 * D :]
    drh = jax.lax.dot_general(
        dcand.astype(wgc.dtype), wgc, (((1,), (1,)), ((), ())),
        preferred_element_type=f32,
    )
    dr = drh * h_prev
    dgu = du * _dact(act_gate, u)
    dgr = dr * _dact(act_gate, r)
    dg = jnp.concatenate([dgu, dgr], axis=1)             # [bB, 2D]
    d_din = jnp.concatenate([dg, dcand], axis=1)         # [bB, 3D]
    dxw_ref[0] = d_din.astype(dxw_ref.dtype)
    # dW_gru is NOT accumulated here: it is rebuilt outside the kernel
    # from the streamed (h_prev, r, d_din) as two time-parallel matmuls
    # — saves 3MB of f32 VMEM (bb 16 -> 32 at flagship shapes) and two
    # MXU dots from the sequential critical path

    # context projection: d_ctx in-kernel (needed for the attention
    # chain); dW_ctx reconstructed OUTSIDE from the (ctx, d_din) streams
    d_ctx = jax.lax.dot_general(
        d_din.astype(wctx_ref.dtype), wctx_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=f32,
    )                                                    # [bB, E]
    dctx_ref[0] = d_ctx.astype(dctx_ref.dtype)

    # attention backward; combined is recomputed from the resident
    # enc_proj and the transform output (cheaper than streaming
    # [Td,Te,bB,D] activations through HBM)
    m = jax.lax.dot(
        h_prev.astype(wa_ref.dtype), wa_ref[...], preferred_element_type=f32
    ) + ba_ref[...].astype(f32)
    ev = ev_ref[...].astype(f32)
    combined = jnp.tanh(ep_ref[...].astype(f32) + m[None, :, :])
    dalpha = jnp.sum(ev * d_ctx[None, :, :], axis=-1)    # [Te, bB]
    # masked softmax backward (pads have alpha = 0, so they drop out)
    ds = alpha * (dalpha - jnp.sum(alpha * dalpha, axis=0, keepdims=True))
    v32 = v_ref[...].astype(f32)                         # [1, D]
    d_comb = ds[:, :, None] * v32[None, :, :]            # [Te, bB, D]
    dv_ref[...] += jnp.sum(combined * ds[:, :, None], axis=(0, 1))[None, :]
    dtanh = (1.0 - combined * combined) * d_comb
    dep_ref[...] += dtanh.astype(dep_ref.dtype)
    d_m = jnp.sum(dtanh, axis=0)                         # [bB, D]
    dba_ref[...] += jnp.sum(d_m, axis=0)[None, :]
    dwa_ref[...] += jax.lax.dot_general(
        h_prev, d_m, (((0,), (0,)), ((), ())), preferred_element_type=f32
    )

    dh_prev = (
        dh_new * u
        + drh * r
        + jax.lax.dot_general(
            dg.astype(wgg.dtype), wgg, (((1,), (1,)), ((), ())),
            preferred_element_type=f32,
        )
        + jax.lax.dot_general(
            d_m.astype(wa_ref.dtype), wa_ref[...], (((1,), (1,)), ((), ())),
            preferred_element_type=f32,
        )
    )
    dh_scr[...] = dh_prev + (1.0 - dmv) * DH

    @pl.when(idx == nt - 1)
    def _final():
        dh0_ref[...] = dh_scr[...].astype(dh0_ref.dtype)


def _run_bwd(dy, ep, ev, em, dmask, hprev, acts3, alphas,
             wa, ba, v, wctx, wg, acts, interpret):
    Te, B, D = ep.shape
    E = ev.shape[2]
    Td = dy.shape[0]
    bb = _pick_bb(
        B, lambda n: _vmem_bwd(n, Te, D, E, ep.dtype.itemsize)
    ) or (B if interpret else None)
    assert bb is not None, (B, Te, D, E)  # callers gate on supported()
    enc3 = lambda width: pl.BlockSpec((Te, bb, width), lambda b, i: (0, b, 0))
    rev = lambda width: pl.BlockSpec((1, bb, width), lambda b, i: (Td - 1 - i, b, 0))
    wspec = lambda shp: pl.BlockSpec(shp, lambda b, i: (0, 0))
    bspec = pl.BlockSpec((bb, D), lambda b, i: (b, 0))
    kern = functools.partial(
        _bwd_kernel, act_in=acts[0], act_gate=acts[1], Te=Te, D=D
    )
    f32 = jnp.float32
    dxw, dctxs, dh0, dep, dwa, dba, dv = pl.pallas_call(
        kern,
        grid=(B // bb, Td),
        in_specs=[
            rev(D),                       # dy
            enc3(D), enc3(E), enc3(1),    # ep, ev, emask
            rev(1),                       # dmask
            rev(D), rev(3 * D), rev(Te),  # hprev, acts, alpha
            wspec(wa.shape), wspec(ba.shape), wspec(v.shape),
            wspec(wctx.shape), wspec(wg.shape),
        ],
        out_specs=[
            rev(3 * D),                   # dxw (= d_din)
            rev(E),                       # d_ctx stream
            bspec,                        # dh0
            enc3(D),                      # d_enc_proj (per b-block)
            wspec(wa.shape), wspec(ba.shape), wspec(v.shape),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Td, B, 3 * D), dy.dtype),
            jax.ShapeDtypeStruct((Td, B, E), dy.dtype),
            jax.ShapeDtypeStruct((B, D), dy.dtype),
            jax.ShapeDtypeStruct((Te, B, D), f32),
            jax.ShapeDtypeStruct(wa.shape, f32),
            jax.ShapeDtypeStruct(ba.shape, f32),
            jax.ShapeDtypeStruct(v.shape, f32),
        ],
        scratch_shapes=[pltpu.VMEM((bb, D), jnp.float32)]
        if pltpu is not None
        else [],
        interpret=interpret,
        compiler_params=_params(2),
    )(dy, ep, ev, em, dmask, hprev, acts3, alphas, wa, ba, v, wctx, wg)
    return dxw, dctxs, dh0, dep, dwa, dba, dv


# ------------------------------------------------------------ public API


def _flops(Td, B, Te, D, E, bwd: bool) -> float:
    att = 2.0 * B * D * D + 4.0 * B * Te * D + 2.0 * B * Te * E
    proj = 2.0 * B * E * 3 * D
    gru = 2.0 * B * D * 2 * D + 2.0 * B * D * D
    per_step = att + proj + gru
    return Td * per_step * (3.0 if bwd else 1.0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(11, 12))
def fused_attention_gru(ep, ev, em, xw, dmask, h0, wa, ba, v, wctx, wg,
                        acts, interpret):
    """Raw per-step GRU outputs [Td, B, D] of the fused decoder loop.

    ep [Te,B,D] encoder projection; ev [Te,B,E] encoder states;
    em [Te,B,1] encoder validity; xw [Td,B,3D] hoisted word-side
    decoder inputs WITH all biases folded in; dmask [Td,B,1] target
    validity; h0 [B,D] boot state; wa [D,D] + ba [1,D] attention
    transform (+ folded combine bias); v [1,D] scoring vector;
    wctx [E,3D]; wg [D,3D] GRU weight. acts = (act_in, act_gate)."""
    from paddle_tpu.ops import kernel_flops

    Td, B = xw.shape[0], xw.shape[1]
    Te, D, E = ep.shape[0], ep.shape[2], ev.shape[2]
    kernel_flops.record(_flops(Td, B, Te, D, E, bwd=False))
    (ys,) = _run_fwd(ep, ev, em, xw, dmask, h0, wa, ba, v, wctx, wg,
                     acts, interpret, residuals=False)
    return ys


def _fused_fwd(ep, ev, em, xw, dmask, h0, wa, ba, v, wctx, wg,
               acts, interpret):
    from paddle_tpu.ops import kernel_flops

    Td, B = xw.shape[0], xw.shape[1]
    Te, D, E = ep.shape[0], ep.shape[2], ev.shape[2]
    kernel_flops.record(_flops(Td, B, Te, D, E, bwd=False))
    ys, hprev, acts3, alphas, ctxs = _run_fwd(
        ep, ev, em, xw, dmask, h0, wa, ba, v, wctx, wg, acts, interpret
    )
    return ys, (ep, ev, em, dmask, hprev, acts3, alphas, ctxs,
                wa, ba, v, wctx, wg)


def _fused_bwd(acts, interpret, res, dy):
    from paddle_tpu.ops import kernel_flops

    (ep, ev, em, dmask, hprev, acts3, alphas, ctxs, wa, ba, v, wctx, wg) = res
    Td, B = dy.shape[0], dy.shape[1]
    Te, D, E = ep.shape[0], ep.shape[2], ev.shape[2]
    kernel_flops.record(_flops(Td, B, Te, D, E, bwd=True))
    dxw, dctxs, dh0, dep, dwa, dba, dv = _run_bwd(
        dy, ep, ev, em, dmask, hprev, acts3, alphas,
        wa, ba, v, wctx, wg, acts, interpret,
    )
    f32 = jnp.float32
    # dW_ctx, dW_gru and d_enc_vec as large time-parallel contractions
    # OUTSIDE the kernel (VMEM budget — see module docstring)
    dwctx = jax.lax.dot_general(
        ctxs.reshape(-1, E), dxw.reshape(-1, 3 * D),
        (((0,), (0,)), ((), ())), preferred_element_type=f32,
    ).astype(wctx.dtype)
    hp2 = hprev.reshape(-1, D)
    dxw2 = dxw.reshape(-1, 3 * D)
    r2 = acts3.reshape(-1, 3 * D)[:, D : 2 * D]
    dwg = jnp.concatenate(
        [
            jax.lax.dot_general(hp2, dxw2[:, : 2 * D],
                                (((0,), (0,)), ((), ())),
                                preferred_element_type=f32),
            jax.lax.dot_general((r2.astype(f32) * hp2.astype(f32)).astype(hp2.dtype),
                                dxw2[:, 2 * D :],
                                (((0,), (0,)), ((), ())),
                                preferred_element_type=f32),
        ],
        axis=1,
    )
    # d_ev[te, b, :] = sum_td alpha[td, b, te] * d_ctx[td, b, :]
    dev = jnp.einsum(
        "tbe,tbd->ebd", alphas.astype(f32), dctxs.astype(f32),
        preferred_element_type=f32,
    ).astype(ev.dtype)
    return (
        dep.astype(ep.dtype),
        dev,
        jnp.zeros_like(em),
        dxw,
        jnp.zeros_like(dmask),
        dh0,
        dwa.astype(wa.dtype),
        dba.astype(ba.dtype),
        dv.astype(v.dtype),
        dwctx,
        dwg.astype(wg.dtype),
    )


fused_attention_gru.defvjp(_fused_fwd, _fused_bwd)
