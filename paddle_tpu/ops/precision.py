"""Mixed-precision helpers shared across layers/activations.

The f32-island rule: loss math, softmax internals, batch-norm statistics
and CRF/CTC recursions run in at least f32 even when activations are bf16
(LayerContext.compute_dtype). `hp` is the single upcast point so the
promotion policy lives in one place.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def hp(x: jax.Array) -> jax.Array:
    """Upcast half-precision values to f32; no-op for f32/f64 (x64)."""
    hi = jnp.promote_types(x.dtype, jnp.float32)
    return x.astype(hi) if hi != x.dtype else x
