"""Per-file CRC32/size manifests for checkpoint directories.

``MANIFEST.json`` makes a pass directory self-verifying: every data file
is recorded with its byte size and CRC32, so a torn write, a truncated
shard, or shared-filesystem bit rot is detected *before* a restore
deserializes garbage into live training state. Format:

    {"format": 1,
     "files": {"params.npz": {"size": 1234, "crc32": 305419896}, ...}}

Multi-host saves cannot have process 0 re-read every shard just to
checksum it, so each process writes a ``MANIFEST.partial.<pid>.json``
covering only the files it wrote (data it just produced, a local
read-back), and process 0 merges the partials — the same
partial-then-merge discipline the sharded index already uses.

The manifest never lists itself, and verification ignores files absent
from it (a later tool dropping e.g. ``merged_model.npz`` into a pass dir
must not invalidate the checkpoint).
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Dict, Iterable, List, Optional

MANIFEST_NAME = "MANIFEST.json"
_PARTIAL_FMT = "MANIFEST.partial.%05d.json"
_CHUNK = 1 << 20


def file_digest(path: str) -> Dict[str, int]:
    """{'size': bytes, 'crc32': unsigned crc} of one file, streamed."""
    crc = 0
    size = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(_CHUNK)
            if not chunk:
                break
            size += len(chunk)
            crc = zlib.crc32(chunk, crc)
    return {"size": size, "crc32": crc & 0xFFFFFFFF}


def _is_manifest_file(name: str) -> bool:
    return name == MANIFEST_NAME or name.startswith("MANIFEST.partial.")


def build_manifest(dirpath: str, files: Optional[Iterable[str]] = None) -> Dict:
    """Digest ``files`` (default: every regular file in ``dirpath``
    except manifests) into a manifest dict."""
    if files is None:
        files = [
            n
            for n in sorted(os.listdir(dirpath))
            if not _is_manifest_file(n)
            and os.path.isfile(os.path.join(dirpath, n))
        ]
    return {
        "format": 1,
        "files": {n: file_digest(os.path.join(dirpath, n)) for n in files},
    }


def _write_json_fsync(path: str, obj: Dict) -> None:
    with open(path, "w") as f:
        json.dump(obj, f, indent=2, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())


def write_manifest(dirpath: str, manifest: Optional[Dict] = None) -> Dict:
    """Write (building if needed) ``MANIFEST.json``; fsynced."""
    if manifest is None:
        manifest = build_manifest(dirpath)
    _write_json_fsync(os.path.join(dirpath, MANIFEST_NAME), manifest)
    return manifest


def write_partial_manifest(dirpath: str, pid: int, files: Iterable[str]) -> None:
    """One process's share of a multi-host manifest: digests of the
    files this process wrote (local read-back of its own data)."""
    _write_json_fsync(
        os.path.join(dirpath, _PARTIAL_FMT % pid), build_manifest(dirpath, files)
    )


def merge_partial_manifests(dirpath: str) -> Dict:
    """Process 0, after the shard barrier: union the partials, digest
    any remaining un-covered files (merged indexes, meta.json — all
    process-0-local writes), drop the partials, write MANIFEST.json."""
    merged: Dict[str, Dict[str, int]] = {}
    partials = [
        n for n in sorted(os.listdir(dirpath)) if n.startswith("MANIFEST.partial.")
    ]
    for n in partials:
        with open(os.path.join(dirpath, n)) as f:
            merged.update(json.load(f).get("files", {}))
    for n in sorted(os.listdir(dirpath)):
        full = os.path.join(dirpath, n)
        if n not in merged and not _is_manifest_file(n) and os.path.isfile(full):
            merged[n] = file_digest(full)
    manifest = {"format": 1, "files": merged}
    write_manifest(dirpath, manifest)
    # partials dropped only AFTER the merged manifest is durable, so a
    # retried merge (transient write error) still finds its inputs
    for n in partials:
        os.remove(os.path.join(dirpath, n))
    return manifest


def read_manifest(dirpath: str) -> Optional[Dict]:
    """The parsed manifest, or None when absent/unreadable (an
    unreadable manifest is reported by verify_dir, not here)."""
    path = os.path.join(dirpath, MANIFEST_NAME)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def verify_dir(dirpath: str) -> List[str]:
    """Problems found checking ``dirpath`` against its manifest; empty
    list = verified clean. A directory WITHOUT a manifest verifies clean
    (pre-resilience checkpoints must keep loading) — callers that want
    to surface that distinction use ``read_manifest`` directly."""
    path = os.path.join(dirpath, MANIFEST_NAME)
    if not os.path.exists(path):
        return []
    try:
        with open(path) as f:
            manifest = json.load(f)
    except FileNotFoundError:
        # vanished after the exists() check: concurrent delete, a
        # verification problem rather than a crash
        return [f"{MANIFEST_NAME}: vanished while verifying (concurrent delete?)"]
    except ValueError as e:
        # corrupt JSON is real corruption; transient OSErrors propagate
        # so the caller's retry policy gets a chance before a good
        # checkpoint is condemned
        return [f"{MANIFEST_NAME} unreadable: {e}"]
    problems: List[str] = []
    for name, want in sorted(manifest.get("files", {}).items()):
        full = os.path.join(dirpath, name)
        if not os.path.exists(full):
            problems.append(f"{name}: missing (manifest says {want['size']} bytes)")
            continue
        try:
            got = file_digest(full)
        except FileNotFoundError:
            # vanished between the exists() check and the read — another
            # process rotated/quarantined the dir out from under us; a
            # verification problem, not a crash (other OSErrors propagate
            # so the caller's retry policy can handle transients)
            problems.append(f"{name}: vanished while verifying (concurrent delete?)")
            continue
        if got["size"] != want["size"]:
            problems.append(
                f"{name}: size {got['size']} != manifest {want['size']} (truncated?)"
            )
        elif got["crc32"] != want["crc32"]:
            problems.append(
                f"{name}: crc32 {got['crc32']:#010x} != manifest "
                f"{want['crc32']:#010x} (corrupted)"
            )
    return problems
