"""Run supervision — crash-loop-aware auto-restart (`paddle supervise`).

PR 1 made a single trainer process survive bad disks and hung providers;
this module supplies the layer above it: *noticing a dead run and
bringing it back*. `paddle supervise <train flags>` runs `paddle train`
as a child process and

- restarts it on nonzero exit with exponential backoff
  (``utils/retry.py`` is the single backoff implementation), injecting
  ``--init_model_path=auto`` so every restart resumes from the newest
  manifest-verified checkpoint;
- bounds restarts by ``--restart_budget`` — a run that cannot stay up is
  an operator problem, not something to retry forever;
- detects crash loops: ``--crash_loop_threshold`` consecutive deaths
  with NO checkpoint progress between them (same restorable pass every
  launch) classifies the failure as deterministic poison — restarting
  would replay it — so the supervisor stops and writes a JSON crash
  report (exit code, restore history, child-log tail, the last N
  structured metrics records per host from the child's metrics.jsonl
  telemetry, and the last barrier-skew record for slowest-host
  attribution — log-line grepping only as the telemetry-less fallback);
- forwards SIGTERM to the child, so a preempted supervised run still
  checkpoints at the next launch boundary (``--save_on_preempt``) and is
  NOT restarted — the preemption is the scheduler's decision;
- exit-code discipline: a child that exits ``EXIT_PREEMPTED`` (18 — it
  was SIGTERMed directly, checkpointed, and left cleanly) is restarted
  for FREE (no budget, no crash-loop accounting); a child that exits
  ``EXIT_HANG`` (19 — hangwatch killed a wedged step loop) counts as a
  real failure and its ``hang_report.json`` (thread stacks, telemetry
  tail) is embedded in the crash report.

The supervisor deliberately never initializes jax: probing the save_dir
for checkpoint progress uses the manifest layer only, so a child killed
by the accelerator runtime itself can still be supervised. Child stdout/
stderr land in ``<supervise_dir>/attempt-NNN.log`` (default
``<save_dir>/supervise``).

Chaos drills: ``--fault_spec='trainer.crash=exit:9@N'`` (forwarded to
the child like every other train flag) kills the child at the Nth
trained launch — deterministic, so tests/test_supervision.py proves both
the recovery path and the crash-loop stop.
"""

from __future__ import annotations

import json
import os
import random
import shlex
import signal
import subprocess
import sys
import time
from typing import Callable, Dict, List, Optional

from paddle_tpu.resilience import (
    EXIT_CRASH_LOOP,
    EXIT_HANG,
    EXIT_OOM,
    EXIT_PREEMPTED,
)
from paddle_tpu.utils.logging import logger
from paddle_tpu.utils.retry import RetryPolicy

CRASH_REPORT = "crash_report.json"
LOG_TAIL_BYTES = 8192
METRICS_TAIL_RECORDS = 25  # last N metrics records per host in the report
# preemption restarts are budget-free, but not INFINITE: a child that is
# SIGTERMed moments after every launch (broken node agent, cgroup
# killer) would otherwise loop forever. 100 consecutive preemptions
# with zero completed runs is a storm, not scheduling.
FREE_RESTART_LIMIT = 100


def probe_restorable(save_dir: str) -> Optional[str]:
    """Newest pass dir under ``save_dir`` that passes manifest
    verification, or None. jax-free twin of
    ``checkpoint.find_restorable_checkpoint`` — the supervisor uses it
    only to detect PROGRESS between child deaths; the authoritative
    restore is the child's own ``--init_model_path=auto``."""
    if not save_dir or not os.path.isdir(save_dir):
        return None
    from paddle_tpu.resilience.manifest import verify_dir

    cands = []
    for name in os.listdir(save_dir):
        base = name[: -len(".old")] if name.endswith(".old") else name
        if not (base.startswith("pass-") and base[5:].isdigit()):
            continue
        cands.append((int(base[5:]), not name.endswith(".old"), name))
    # newest pass wins; for the same pass id a completed dir beats the
    # torn-commit ``.old`` leftover
    for _pid, _plain, name in sorted(cands, reverse=True):
        path = os.path.join(save_dir, name)
        if not os.path.exists(os.path.join(path, "meta.json")):
            continue  # still being written, or not a checkpoint at all
        if verify_dir(path) == []:
            return path
    return None


class Supervisor:
    """Launch/restart driver around one `paddle train` child.

    ``child_cmd`` overrides the spawned command (tests drive the restart
    machinery with tiny stub children); ``probe`` overrides the
    checkpoint-progress probe; ``sleep`` makes backoff testable."""

    def __init__(
        self,
        train_args: List[str],
        flags,
        child_cmd: Optional[List[str]] = None,
        probe: Optional[Callable[[], Optional[str]]] = None,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self.train_args = list(train_args)
        self.flags = flags
        self._child_cmd_override = child_cmd
        # which child this supervises: `paddle train` (default) or
        # `paddle serve` (--supervise_job=serve). The exit-code
        # discipline is identical (17/18/19/20, preemption free); the
        # deltas are restart args (a serve child keeps its own — the
        # request journal, not a checkpoint, is its resume state) and
        # the crash-loop progress probe (journal answered-count instead
        # of restorable passes).
        self.job = getattr(flags, "supervise_job", "train") or "train"
        self.save_dir = getattr(flags, "save_dir", "") or ""
        # where the child's telemetry lands (observability/metrics.py
        # resolves the same way: --metrics_path wins, save_dir doubles
        # as the run dir) — the crash report reads its tail from here
        self.metrics_dir = (
            getattr(flags, "metrics_path", "") or self.save_dir
        )
        self.dir = getattr(flags, "supervise_dir", "") or (
            os.path.join(self.save_dir, "supervise")
            if self.save_dir else "supervise"
        )
        self.budget = max(0, int(getattr(flags, "restart_budget", 5)))
        self.loop_threshold = max(
            1, int(getattr(flags, "crash_loop_threshold", 3))
        )
        self.backoff = RetryPolicy(
            max_attempts=self.budget + 1,
            base_delay=float(getattr(flags, "restart_base_delay", 1.0)),
            max_delay=60.0,
            name="supervise-restart",
            sleep=sleep,
        )
        if probe is not None:
            self._probe = probe
        elif self.job == "serve":
            journal = getattr(flags, "serve_journal_path", "") or ""
            self._probe = lambda: self._probe_serve(journal)
        else:
            self._probe = lambda: probe_restorable(self.save_dir)
        # wall-clock birth of this supervise invocation: the staleness
        # gate for hang_report.json (see _hang_report)
        self._t0_wall = time.time()
        self._rng = random.Random()
        self._proc: Optional[subprocess.Popen] = None
        self._terminating = False
        self.attempts: List[Dict] = []

    # ------------------------------------------------------------ child

    @staticmethod
    def _probe_serve(journal_path: str):
        """Serve-child progress = the request journal's answered count
        (jax-free, like the manifest probe): consecutive deaths with an
        identical fingerprint served nothing between them — the crash
        loop a restart would only replay."""
        from paddle_tpu.serving.resilience import journal_progress

        return journal_progress(journal_path)

    def child_cmd(self, restart: bool) -> List[str]:
        if self._child_cmd_override is not None:
            return list(self._child_cmd_override)
        from paddle_tpu.utils.flags import strip_flag

        # --dry_run is the supervisor's own; the trainer would ignore it,
        # but forwarding it makes the printed plan misleading to copy.
        # --supervise_job likewise: the child would warn on it
        args = [
            a for a in self.train_args
            if a != "--dry_run" and not a.startswith("--dry_run=")
        ]
        args = strip_flag(args, "supervise_job")
        if restart and self.job != "serve":
            # every restart resumes from the newest verified checkpoint;
            # the user's own --init_model_path only applies to the first
            # launch (an explicit pretrained init must not clobber the
            # progress the run made before dying). A serve child keeps
            # its args untouched — its resume state is the request
            # journal (--serve_journal_path), re-offered by the child
            # itself at startup.
            args = strip_flag(args, "init_model_path")
            args.append("--init_model_path=auto")
        return [sys.executable, "-m", "paddle_tpu.cli", self.job, *args]

    def describe(self) -> str:
        q = lambda cmd: " ".join(shlex.quote(c) for c in cmd)
        return "\n".join([
            "supervise plan:",
            f"  child:      {q(self.child_cmd(restart=False))}",
            f"  on restart: {q(self.child_cmd(restart=True))}",
            f"  restart_budget={self.budget} "
            f"crash_loop_threshold={self.loop_threshold}",
            f"  backoff: base={self.backoff.base_delay:g}s "
            f"x{self.backoff.multiplier:g} (cap {self.backoff.max_delay:g}s, "
            f"jitter +/-{self.backoff.jitter:g})",
            f"  logs: {os.path.join(self.dir, 'attempt-NNN.log')}",
            f"  crash report: {os.path.join(self.dir, CRASH_REPORT)}",
        ])

    # -------------------------------------------------------------- run

    def run(self) -> int:
        if getattr(self.flags, "dry_run", False):
            print(self.describe())
            return 0
        os.makedirs(self.dir, exist_ok=True)
        restarts = 0
        restarts_free = 0  # preemption restarts: never charged to budget
        same_state_deaths = 0
        prev_restored: object = self  # sentinel: no failed attempt yet
        prev_handler = self._install_sigterm()
        try:
            while True:
                restored = self._probe()
                rc, log_path = self._run_once(
                    restart=(restarts + restarts_free) > 0,
                    restored=restored,
                )
                if rc == 0:
                    logger.info(
                        "supervise: child finished cleanly after %d "
                        "restart(s)", restarts,
                    )
                    return 0
                if self._terminating:
                    logger.info(
                        "supervise: SIGTERM forwarded — child exited rc=%d, "
                        "not restarting (resume later with the same "
                        "command; --init_model_path=auto picks up the "
                        "preemption checkpoint)", rc,
                    )
                    return rc
                if rc == EXIT_PREEMPTED:
                    # the CHILD was preempted directly (its own SIGTERM,
                    # not one we forwarded): it checkpointed and exited
                    # cleanly. Preemption is the scheduler's decision,
                    # not the run's failure — restart for free: no
                    # restart budget consumed, no crash-loop accounting
                    # (a preempted attempt that made no checkpoint
                    # progress is NOT evidence of poison).
                    restarts_free += 1
                    if restarts_free > FREE_RESTART_LIMIT:
                        self._crash_report(
                            "preemption_storm", log_path,
                            f"{restarts_free} consecutive preemption "
                            "exits with no completed run — something is "
                            "killing every child, not scheduling them",
                        )
                        return EXIT_PREEMPTED
                    # escalating delay (capped at the policy max): a
                    # rapid preemption storm must not hot-loop launches
                    delay = self.backoff.delay_for(
                        min(restarts_free, 8), self._rng
                    )
                    logger.info(
                        "supervise: child preempted (rc=%d) — restarting "
                        "without consuming budget (free restart #%d) in "
                        "%.2gs", rc, restarts_free, delay,
                    )
                    if delay > 0:
                        self.backoff.sleep(delay)
                    if self._terminating:
                        logger.info(
                            "supervise: SIGTERM during preemption restart "
                            "— not relaunching"
                        )
                        return rc
                    continue
                # crash-loop detection: consecutive deaths launched from
                # the SAME restorable state made zero progress — a
                # deterministic failure a restart would only replay
                same_state_deaths = (
                    same_state_deaths + 1 if restored == prev_restored else 1
                )
                prev_restored = restored
                if same_state_deaths >= self.loop_threshold:
                    self._crash_report(
                        "crash_loop", log_path,
                        f"{same_state_deaths} consecutive deaths with no "
                        f"checkpoint progress (restored_from={restored!r})",
                    )
                    return EXIT_CRASH_LOOP
                if restarts >= self.budget:
                    self._crash_report(
                        "restart_budget_exhausted", log_path,
                        f"child still failing after {restarts} restart(s)",
                    )
                    return rc
                restarts += 1
                delay = self.backoff.delay_for(restarts, self._rng)
                logger.warning(
                    "supervise: child died rc=%d%s (restored_from=%s) — "
                    "restart %d/%d in %.2gs",
                    rc,
                    " (hang detected — see hang_report.json)"
                    if rc == EXIT_HANG else "",
                    restored, restarts, self.budget, delay,
                )
                if delay > 0:
                    self.backoff.sleep(delay)
                if self._terminating:
                    # SIGTERM landed between children (during the backoff
                    # sleep): there was no child to forward it to — honor
                    # it HERE instead of launching a fresh trainer the
                    # scheduler is about to hard-kill
                    logger.info(
                        "supervise: SIGTERM during restart backoff — "
                        "not relaunching"
                    )
                    return rc
        finally:
            self._restore_sigterm(prev_handler)

    def _run_once(self, restart: bool, restored: Optional[str]):
        log_path = os.path.join(
            self.dir, f"attempt-{len(self.attempts):03d}.log"
        )
        cmd = self.child_cmd(restart=restart)
        t0 = time.monotonic()
        with open(log_path, "ab") as lf:
            self._proc = subprocess.Popen(
                cmd, stdout=lf, stderr=subprocess.STDOUT
            )
            try:
                rc = self._proc.wait()
            finally:
                self._proc = None
        self.attempts.append({
            "cmd": cmd,
            "exit_code": rc,
            "restored_from": restored,
            "duration_s": round(time.monotonic() - t0, 3),
            "log": log_path,
        })
        return rc, log_path

    # ---------------------------------------------------------- signals

    def _install_sigterm(self):
        """Forward SIGTERM (preemption notice) to the child so its own
        --save_on_preempt handler checkpoints; a forwarded SIGTERM also
        stops the restart loop. No-op off the main thread (library/test
        embedding), same degradation as the trainer's guard."""
        from paddle_tpu.utils import concurrency as cc

        if cc.current_thread() is not cc.main_thread():
            return None

        def fwd(signum, frame):
            self._terminating = True
            proc = self._proc
            if proc is not None and proc.poll() is None:
                try:
                    proc.send_signal(signal.SIGTERM)
                except OSError:
                    pass

        prev = signal.getsignal(signal.SIGTERM)
        signal.signal(signal.SIGTERM, fwd)
        return (prev,)

    def _restore_sigterm(self, token) -> None:
        if token is None:
            return
        prev = token[0]
        signal.signal(
            signal.SIGTERM, prev if prev is not None else signal.SIG_DFL
        )

    # ----------------------------------------------------- crash report

    @staticmethod
    def _log_tail(log_path: str) -> str:
        try:
            with open(log_path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - LOG_TAIL_BYTES))
                return f.read().decode("utf-8", "replace")
        except OSError:
            return ""

    def _metrics_tail(self):
        """Last N structured telemetry records per host from the child's
        metrics.jsonl streams (observability/metrics.py) — the primary
        post-mortem evidence, replacing log-grepping. Returns ({host:
        [records]}, last barrier_skew record or None)."""
        if not self.metrics_dir:
            return {}, None
        from paddle_tpu.observability.metrics import tail_with_last_skew

        return tail_with_last_skew(self.metrics_dir, n=METRICS_TAIL_RECORDS)

    def _forensics_report(self, filename: str):
        """A child-written forensics JSON (hang_report.json /
        oom_report.json) from the run dir, freshness-gated to THIS
        supervise invocation: a report older than _t0_wall is a
        leftover from a previous incident in the same save_dir and
        embedding it would present another process's evidence as this
        run's. The child stamps written_at on the same host (same
        clock); the file mtime is only the parse-failure fallback —
        an NFS-server-assigned mtime can skew by seconds."""
        if not self.metrics_dir:
            return None
        from paddle_tpu.resilience.hangwatch import run_dir_of

        path = os.path.join(run_dir_of(self.metrics_dir), filename)
        try:
            with open(path) as f:
                report = json.load(f)
        except (OSError, ValueError):
            return None
        written = None
        try:
            written = time.mktime(time.strptime(
                str(report.get("written_at", ""))[:19], "%Y-%m-%dT%H:%M:%S"
            ))
        except ValueError:
            try:
                written = os.path.getmtime(path)
            except OSError:
                pass
        if written is not None and written < self._t0_wall - 1.0:
            logger.warning(
                "supervise: %s predates this supervise run — stale "
                "forensics from an earlier incident, not embedding", path,
            )
            return None
        return report

    def _hang_report(self):
        """The child's hang forensics, when any attempt died of a
        detected hang (EXIT_HANG): hangwatch writes hang_report.json
        into the same run dir the metrics tail comes from — a serve
        child's hangwatch writes serve_hang_report.json (thread stacks
        PLUS the in-flight cohort snapshot) instead. Parsed and
        embedded so one crash_report.json carries the whole story."""
        from paddle_tpu.resilience.hangwatch import HANG_REPORT

        report = self._forensics_report(HANG_REPORT)
        if report is None:
            from paddle_tpu.serving.resilience import SERVE_HANG_REPORT

            report = self._forensics_report(SERVE_HANG_REPORT)
        return report

    def _oom_report(self):
        """The child's OOM pre-mortem (oom_report.json — per-group
        static footprint, last live memory snapshot), when any attempt
        died of device-memory exhaustion (EXIT_OOM). Same run dir, same
        freshness gate as the hang forensics."""
        from paddle_tpu.observability.memory import OOM_REPORT

        return self._forensics_report(OOM_REPORT)

    def _crash_report(self, reason: str, log_path: str, detail: str) -> str:
        tail = self._log_tail(log_path)
        # slowest-host attribution for multi-host deaths: primary source
        # is the structured barrier_skew metrics record; a telemetry-less
        # child (no save_dir/--metrics_path) falls back to grepping the
        # BarrierStat log line the trainer still prints at pass end
        metrics_tail, skew_rec = self._metrics_tail()
        skew = skew_rec if skew_rec is not None else next(
            (l for l in reversed(tail.splitlines()) if "BarrierStat:" in l),
            None,
        )
        report = {
            "reason": reason,
            "detail": detail,
            "restart_budget": self.budget,
            "crash_loop_threshold": self.loop_threshold,
            "train_args": self.train_args,
            "attempts": self.attempts,
            "log_tail": tail,
            "metrics_tail": metrics_tail,
            "step_time_skew": skew,
            "written_at": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        }
        # a hung attempt left in-process forensics — attach them
        if any(a.get("exit_code") == EXIT_HANG for a in self.attempts):
            report["hang_report"] = self._hang_report()
        # same for an OOM'd attempt's pre-mortem (exit 20: the child
        # classified its own death and ranked the launch groups by
        # static footprint before dying)
        if any(a.get("exit_code") == EXIT_OOM for a in self.attempts):
            report["oom_report"] = self._oom_report()
        path = os.path.join(self.dir, CRASH_REPORT)
        with open(path, "w") as f:
            json.dump(report, f, indent=2)
        logger.error(
            "supervise: %s (%s) — giving up; crash report: %s\n"
            "--- last child output ---\n%s",
            reason, detail, path,
            "\n".join(tail.splitlines()[-15:]),
        )
        return path
