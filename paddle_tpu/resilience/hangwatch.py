"""In-process step-progress watchdog (``--step_hang_timeout``).

Hangs — not clean crashes — dominate lost pod-hours at scale (MegaScale,
Jiang et al. 2024): one wedged host leaves every other host blocked
inside a collective, and a fail-fast stack like ours (PR 1-3) only
reacts to processes that *exit*. A hung trainer previously burned its
whole external timeout with zero forensics.

:class:`HangWatch` closes the in-process half of that gap. The trainer
pings it at every launch/step boundary; a daemon monitor thread tracks
the age of the last ping. When the age exceeds ``--step_hang_timeout``
the monitor

1. dumps every Python thread's stack — structured (per-thread frame
   lists, for ``hang_report.json``) *and* via ``faulthandler`` to
   stderr (the raw form that survives even a wedged allocator),
2. attaches the telemetry tail (last metrics.jsonl records) and the
   last ``barrier_skew`` record, so a multi-host hang carries
   straggler attribution,
3. writes ``hang_report.json`` into the run dir, and
4. exits with the distinct code :data:`EXIT_HANG` (19), so supervisors
   and launchers see a *diagnosed* hang instead of a timeout mystery.

The monitor also publishes the live ``trainer.progress_age_s`` gauge
into the metrics registry and keeps a max-since-last-read the trainer
folds into each ``pass_end`` record (``progress_age_max_s``), which
`paddle metrics` surfaces per pass.

jax-free and stdlib-light: the supervisor imports this module for the
report filename and exit code, and it must stay importable when the
accelerator runtime is what wedged the child.

Chaos drills: the ``trainer.stall`` fault site
(``--fault_spec='trainer.stall=sleep:600@N'``) blocks the step loop at
the Nth launch — deterministic, so tests prove detection, forensics,
and the supervised restart end to end.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback
from typing import Any, Callable, Dict, Optional, Tuple

from paddle_tpu.resilience import EXIT_HANG  # re-export for callers
from paddle_tpu.utils import concurrency as cc
from paddle_tpu.utils.logging import logger

HANG_REPORT = "hang_report.json"

# hard deadline on the forensics themselves: every write in _trigger
# (report file, metrics flush) can block in uninterruptible I/O when the
# SHARED FS is what wedged — the exact failure class the watchdog
# exists for — so a backstop timer guarantees the exit regardless
FORENSICS_DEADLINE_S = 30.0

__all__ = ["EXIT_HANG", "HANG_REPORT", "HangWatch", "run_dir_of",
           "thread_stacks"]


def run_dir_of(metrics_path: str) -> str:
    """The run DIRECTORY for a ``--metrics_path`` value — which the
    metrics layer allows to be either a run dir or an explicit
    ``*.jsonl`` stream file. The hang report (and the supervisor
    looking for it) must agree on the directory either way; treating a
    ``.jsonl`` path as a directory would make ``os.makedirs`` fail and
    silently drop the forensics."""
    if metrics_path.endswith(".jsonl"):
        return os.path.dirname(metrics_path) or "."
    return metrics_path


def thread_stacks() -> Dict[str, Any]:
    """Every live Python thread's current stack, structured for JSON:
    ``{thread name: {"daemon": bool, "frames": ["file:line fn | src"]}}``.
    Never raises — forensics must not be able to mask the hang."""
    names = {t.ident: t for t in threading.enumerate()}
    out: Dict[str, Any] = {}
    try:
        frames = sys._current_frames()
    except Exception:  # pragma: no cover - CPython always provides it
        return out
    for ident, frame in frames.items():
        t = names.get(ident)
        label = f"{t.name} (tid={ident})" if t is not None else f"tid={ident}"
        rows = []
        for fs in traceback.extract_stack(frame):
            rows.append(f"{fs.filename}:{fs.lineno} {fs.name} | "
                        f"{(fs.line or '').strip()}")
        out[label] = {
            "daemon": bool(t.daemon) if t is not None else None,
            "frames": rows,
        }
    return out


class HangWatch:
    """Step-progress monitor. ``ping()`` from the driven thread at every
    launch boundary; the monitor thread fires once the ping age exceeds
    ``timeout_s``.

    Injectable seams (``clock``, ``exit_fn``, ``poll_s``) exist for
    fake-clock unit tests; production uses monotonic time and
    ``os._exit`` (a wedged main thread cannot run atexit handlers — the
    telemetry layer flushes explicitly before exit, exactly like an
    ``exit``-action fault).

    Subclass seams (the serving watch,
    ``paddle_tpu/serving/resilience.py``): ``REPORT_NAME``/``REASON``
    name the forensics file and its ``reason`` field;
    :meth:`_pre_exit` runs after the report + telemetry flush and
    before the exit — the hook where a server answers what it still
    can (the backstop timer does NOT wait for it, so a wedged hook can
    only delay the exit up to its own bounded waits, never past
    :data:`FORENSICS_DEADLINE_S`)."""

    REPORT_NAME = HANG_REPORT
    REASON = "step_hang"

    def __init__(
        self,
        timeout_s: float,
        report_dir: str = "",
        *,
        clock: Optional[Callable[[], float]] = None,
        exit_fn: Callable[[int], None] = os._exit,
        poll_s: Optional[float] = None,
    ):
        assert timeout_s > 0, timeout_s
        self.timeout_s = float(timeout_s)
        self.report_dir = report_dir or "."
        # resolved at construction through the concurrency seam: under
        # `paddle race` the watch runs on the explorer's virtual clock
        self.clock = clock if clock is not None else cc.monotonic
        self.exit_fn = exit_fn
        self.poll_s = float(poll_s) if poll_s else min(self.timeout_s / 4.0, 5.0)
        self._lock = cc.Lock()
        self._last = self.clock()
        self._where: Tuple[Optional[int], Optional[int]] = (None, None)
        self._max_age = 0.0
        self._stop = cc.Event()
        self._thread = None
        self._fired = False

    # ------------------------------------------------------------ driven side

    def ping(self, pass_id: Optional[int] = None,
             step: Optional[int] = None) -> None:
        """Record progress. Called at every launch/step boundary (and at
        coarser boundaries — pass end, save, test) by the step loop."""
        with self._lock:
            now = self.clock()
            # fold the age this ping just ended into the max BEFORE
            # resetting: a near-miss stall shorter than the monitor's
            # poll period would otherwise never reach
            # progress_age_max_s — the exact signal operators tune
            # --step_hang_timeout against
            age = now - self._last
            if age > self._max_age:
                self._max_age = age
            self._last = now
            self._where = (pass_id, step)

    def take_max_age(self) -> float:
        """Max observed progress age since the last call (seconds), then
        reset — the trainer folds this into each ``pass_end`` record."""
        with self._lock:
            v, self._max_age = self._max_age, 0.0
        return v

    # ----------------------------------------------------------- monitor side

    def start(self) -> "HangWatch":
        if self._thread is None:
            # fresh epoch, not a ping: construction-to-start time (model
            # init, checkpoint restore) is not step progress and must
            # not seed either the hang age or the per-pass max
            with self._lock:
                self._last = self.clock()
                self._max_age = 0.0
            self._stop.clear()
            self._thread = cc.Thread(
                target=self._run, name="hangwatch", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=max(self.poll_s * 2, 1.0))

    def __enter__(self) -> "HangWatch":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            self.check()

    def check(self) -> float:
        """One monitor tick: update the gauge/max, fire on a stall.
        Public so fake-clock tests drive it without the thread; returns
        the observed age."""
        with self._lock:
            age = self.clock() - self._last
            if age > self._max_age:
                self._max_age = age
            where = self._where
            # claim the firing under the lock: check() is driven by the
            # monitor thread in production AND directly by fake-clock
            # tests — an unlocked test-and-set could file two reports
            fire = age > self.timeout_s and not self._fired
            if fire:
                self._fired = True  # one report even if exit_fn returns (tests)
        from paddle_tpu.observability import metrics as obs

        obs.registry().gauge("trainer.progress_age_s").set(age)
        if fire:
            self._trigger(age, where)
        return age

    # ------------------------------------------------------------- the report

    def _trigger(self, age: float, where) -> None:
        pass_id, step = where
        logger.error(
            "hangwatch: no step progress for %.1fs (> timeout=%g) "
            "— last progress at pass=%s step=%s; dumping thread stacks and "
            "writing %s, then exiting %d",
            age, self.timeout_s, pass_id, step,
            os.path.join(self.report_dir, self.REPORT_NAME), EXIT_HANG,
        )
        try:
            import faulthandler

            # the stderr dump goes FIRST: it cannot touch the (possibly
            # wedged) shared fs, so the stacks survive even when nothing
            # below completes
            faulthandler.dump_traceback(file=sys.stderr, all_threads=True)
        except Exception:
            pass
        # every write below may block in uninterruptible I/O on the very
        # filesystem whose death caused the hang — OSError would never
        # fire. The backstop guarantees exit 19 within
        # FORENSICS_DEADLINE_S no matter what the forensics do.
        backstop = cc.Timer(
            FORENSICS_DEADLINE_S, self.exit_fn, args=(EXIT_HANG,)
        )
        backstop.daemon = True
        backstop.start()
        report = self.build_report(age, where)
        path = self.write_report(report)
        from paddle_tpu.observability import metrics as obs

        obs.registry().counter("hangs.detected").inc()
        obs.emit("hang", pass_id=pass_id, step=step, age_s=round(age, 3),
                 timeout_s=self.timeout_s, report=path)
        obs.flush()  # os._exit skips atexit — same discipline as exit faults
        try:
            # subclass hook: the serving watch resolves every in-flight
            # request with outcome=error here, so clients hear "the
            # server hung" instead of waiting out their own timeouts.
            # Best-effort — the hang must exit regardless.
            self._pre_exit()
        except Exception:
            pass
        backstop.cancel()  # forensics completed: exit on the normal path
        self.exit_fn(EXIT_HANG)

    def _pre_exit(self) -> None:
        """Hook between forensics and exit (see class docstring)."""

    def build_report(self, age: float, where) -> Dict[str, Any]:
        pass_id, step = where
        report: Dict[str, Any] = {
            "reason": self.REASON,
            "age_s": round(age, 3),
            "timeout_s": self.timeout_s,
            "last_progress": {"pass": pass_id, "step": step},
            "pid": os.getpid(),
            "written_at": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
            "threads": thread_stacks(),
        }
        # telemetry tail + last barrier_skew: the same post-mortem
        # evidence the supervisor's crash report carries (one shared
        # helper, so the skew-selection rule cannot drift), gathered
        # here because only THIS process knows it is about to die
        try:
            from paddle_tpu.observability.metrics import tail_with_last_skew

            tails, skew = tail_with_last_skew(self.report_dir, n=25)
            report["metrics_tail"] = tails
            report["barrier_skew"] = skew
        except Exception as e:  # forensics best-effort, never masks the hang
            report["metrics_tail_error"] = str(e)
        return report

    def write_report(self, report: Dict[str, Any]) -> str:
        path = os.path.join(self.report_dir, self.REPORT_NAME)
        try:
            os.makedirs(self.report_dir, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(report, f, indent=2, default=str)
            os.replace(tmp, path)  # readers never see a torn report
        except OSError as e:
            logger.error("hangwatch: could not write %s: %s", path, e)
        return path
