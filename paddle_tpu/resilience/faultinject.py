"""Deterministic, site-named fault injection.

Production failure modes — a crash between checkpoint write and rename,
a torn shard, a provider that throws EIO once, a prefetch worker that
hangs — are impossible to reproduce on demand without help. This module
plants named injection points at the few places those failures occur and
fires them from a declarative spec, so chaos tests (and operators
rehearsing recovery) get the exact same failure every run.

Spec grammar (``--fault_spec`` / ``PADDLE_TPU_FAULTS``)::

    spec    := entry (';' entry)*
    entry   := site '=' action [':' arg] ['@' trigger]
    action  := raise | oserror | exit | sleep
    trigger := N      fire on the Nth hit of the site only (1-based)
             | N+     fire on every hit >= N
             | pP     fire with probability P per hit (seeded, so the
                      decision sequence is a pure function of
                      (seed, site) — reruns fail identically)

Actions: ``raise`` raises FaultInjected (simulated crash the test can
observe in-process); ``oserror`` raises OSError(EIO) (a *retryable*
transient, exercises RetryPolicy); ``exit[:code]`` calls os._exit
(a real mid-write kill — no atexit, no finally blocks, default code 3);
``sleep[:secs]`` blocks the calling thread (stalls, default 3600).

Examples::

    checkpoint.rename=exit@1          # die between write and rename
    provider.yield=oserror@3          # 3rd sample read throws EIO once
    provider.stall=sleep:120@5        # prefetch worker hangs at item 5
    checkpoint.write=oserror@p0.2     # 20% of file writes flake

Instrumented sites: see ``SITE_DOCS`` below — `paddle faults` prints
the same table, so chaos specs are written from documentation instead
of read out of source.

Inactive cost is one global ``is None`` check per site hit.
"""

from __future__ import annotations

import os
import random
import re
import time
import zlib
from typing import Dict, List, Optional

ENV_SPEC = "PADDLE_TPU_FAULTS"
ENV_SEED = "PADDLE_TPU_FAULT_SEED"

# every instrumented site, with the one-line description `paddle faults`
# prints — chaos specs should be written from this table, not guessed
# from source. Keys double as the KNOWN_SITES membership set.
SITE_DOCS = {
    "checkpoint.write":
        "before each checkpoint file write (oserror = flaky disk; "
        "exit = die mid-write)",
    "checkpoint.rename":
        "between checkpoint write and the tmp->final commit rename "
        "(exit = torn commit)",
    "provider.yield":
        "before each sample leaves a data provider (oserror = "
        "retryable read flake)",
    "provider.stall":
        "inside the prefetch worker loop (sleep = hung data pipeline, "
        "trips the --data_stall_timeout watchdog)",
    "trainer.crash":
        "before each trained launch (exit = mid-run process death for "
        "`paddle supervise` drills)",
    "trainer.stall":
        "before each trained launch (sleep = wedged step loop, trips "
        "the --step_hang_timeout hangwatch -> hang_report.json + "
        "exit 19)",
    "trainer.nonfinite":
        "at the per-batch loss check (raise = that batch's loss "
        "becomes NaN, the deterministic divergence for "
        "--nonfinite_policy drills)",
    "trainer.oom":
        "before each trained launch (raise = a synthetic "
        "RESOURCE_EXHAUSTED at the launch boundary -> oom_report.json "
        "+ exit 20, the OOM pre-mortem drill)",
    "trainer.nonfinite_layer":
        "before each trained launch (raise:LAYER = poison the named "
        "layer's parameters with NaN, as a nonfinite gradient applied "
        "by the optimizer would — the next loss goes NaN and the "
        "per-layer blame re-run must name LAYER)",
    "serve.crash":
        "at each serve collect boundary (exit = mid-serve process "
        "death for `paddle supervise --supervise_job=serve` drills — "
        "the request journal re-offers the queue on restart)",
    "serve.stall":
        "at each serve collect boundary (sleep = wedged serve_decode "
        "launch, trips the --serve_hang_timeout hangwatch -> "
        "serve_hang_report.json + in-flight answered outcome=error + "
        "exit 19)",
    "serve.oom":
        "at each serve collect boundary (raise = synthetic "
        "RESOURCE_EXHAUSTED in the serve loop -> everything answered "
        "outcome=error, oom_report.json + exit 20, budget-consuming "
        "under supervision)",
    "serve.launch_fault":
        "at each serve collect boundary (raise = one decode launch "
        "faults: the in-flight cohort resolves outcome=error and "
        "consecutive faults trip the --serve_breaker_threshold "
        "circuit breaker)",
    "fleet.replica_crash":
        "at each serve-fleet router supervision poll (raise:K = "
        "hard-kill replica index K — the journal re-offer/failover "
        "drill: its unanswered requests replay onto survivors)",
    "fleet.status_stale":
        "at each serve-fleet health probe (raise = that replica's "
        "status reads as stale — the router must route around it, "
        "never crash, and only kill it past the persistence bound)",
    "fleet.reload_torn":
        "in the weight-reload watcher between the durability probe "
        "and the checkpoint load (raise = the checkpoint became "
        "durable mid-swap — abort the attempt, keep serving old "
        "weights, retry next poll)",
    "sparse.gather_fault":
        "before each launch that prefetches sparse-table rows "
        "(raise = the touched-row gather fails — the batch aborts "
        "loudly instead of training on stale rows)",
    "sparse.row_corrupt":
        "after a durable row-shard write, before the pass commits "
        "(raise = flip a byte inside this host's row-shard file — "
        "the CRC manifest verify must catch the poisoned row and "
        "quarantine/fall back, never load it)",
    "sparse.shard_lost":
        "at the row-shard write boundary, before this host's shard "
        "bytes or partial index land (raise = this host's row "
        "shards vanish — check-checkpoint must name the exact "
        "missing row interval, not zero-init it)",
    "net.drop":
        "before each socket frame write (raise = connection reset "
        "mid-stream — the transport must reconnect with backoff and "
        "the hello handshake must re-offer undelivered requests)",
    "net.stall":
        "inside each socket read loop iteration (sleep = wedged read: "
        "heartbeat pongs stop, the replica's health goes stale and "
        "the router routes around it, then kills past the bound)",
    "net.torn_frame":
        "before each socket frame write (raise = a strict prefix of "
        "the frame is sent, then the connection closes — the reader "
        "must discard the partial frame, never crash the router)",
    "net.dup":
        "after each socket frame write (raise = the frame is sent "
        "twice — duplicate delivery the id-dedupe on both ends must "
        "absorb, like a hedge loser)",
}

KNOWN_SITES = tuple(SITE_DOCS)


class FaultInjected(RuntimeError):
    """Raised by the ``raise`` action at an injection site. ``arg``
    carries the rule's ``:arg`` payload, so sites can parameterize the
    failure (e.g. ``trainer.nonfinite_layer=raise:output`` names which
    layer to poison)."""

    def __init__(self, site: str, hit: int, info: str = "",
                 arg: Optional[str] = None):
        detail = f" ({info})" if info else ""
        super().__init__(f"injected fault at {site!r} hit #{hit}{detail}")
        self.site = site
        self.hit = hit
        self.arg = arg


_ENTRY_RE = re.compile(
    r"^(?P<site>[\w.]+)=(?P<action>raise|oserror|exit|sleep)"
    r"(?::(?P<arg>[^@]+))?(?:@(?P<trigger>.+))?$"
)


class _Rule:
    def __init__(self, site: str, action: str, arg: Optional[str], trigger: Optional[str]):
        self.site = site
        self.action = action
        self.arg = arg
        # trigger: ("nth", n) | ("from", n) | ("prob", p) | ("always",)
        if trigger is None:
            self.trigger = ("always",)
        elif trigger.startswith("p"):
            p = float(trigger[1:])
            if not 0.0 <= p <= 1.0:
                raise ValueError(f"probability {p} out of [0, 1]")
            self.trigger = ("prob", p)
        elif trigger.endswith("+"):
            self.trigger = ("from", int(trigger[:-1]))
        else:
            self.trigger = ("nth", int(trigger))

    def should_fire(self, hit: int, rng: random.Random) -> bool:
        kind = self.trigger[0]
        if kind == "always":
            return True
        if kind == "nth":
            return hit == self.trigger[1]
        if kind == "from":
            return hit >= self.trigger[1]
        # "prob": one seeded draw per hit — deterministic in (seed, site)
        return rng.random() < self.trigger[1]

    def fire(self, site: str, hit: int, info: str) -> None:
        if self.action == "raise":
            raise FaultInjected(site, hit, info, arg=self.arg)
        if self.action == "oserror":
            import errno

            raise OSError(
                errno.EIO, f"injected transient I/O error at {site!r} hit #{hit}"
            )
        if self.action == "exit":
            code = int(self.arg) if self.arg else 3
            # os._exit: no atexit, no finally — the honest simulation of
            # a preemption landing mid-write
            os._exit(code)  # lint: disable=PTL006 -- FaultInjector.fire flushes the fault record before dispatching any action (evidence-before-action)
        if self.action == "sleep":
            time.sleep(float(self.arg) if self.arg else 3600.0)


class FaultInjector:
    """Parsed plan + per-site hit counters + seeded per-site rngs."""

    def __init__(self, spec: str, seed: int = 0):
        self.spec = spec
        self.seed = seed
        self.rules: Dict[str, List[_Rule]] = {}
        self._hits: Dict[str, int] = {}
        self._rngs: Dict[str, random.Random] = {}
        for raw in spec.replace(",", ";").split(";"):
            raw = raw.strip()
            if not raw:
                continue
            m = _ENTRY_RE.match(raw)
            if m is None:
                raise ValueError(
                    f"bad fault spec entry {raw!r} "
                    "(want site=action[:arg][@trigger])"
                )
            rule = _Rule(m["site"], m["action"], m["arg"], m["trigger"])
            if rule.site not in KNOWN_SITES:
                # a typo'd site would otherwise parse fine and never fire,
                # making a chaos drill "pass" without testing anything.
                # Warn, don't raise: tests and future call sites may plant
                # their own fault points.
                import logging

                logging.getLogger("paddle_tpu").warning(
                    "fault spec names unknown site %r (known: %s) — it will "
                    "only fire if something calls fault_point(%r)",
                    rule.site, ", ".join(KNOWN_SITES), rule.site,
                )
            self.rules.setdefault(rule.site, []).append(rule)

    def _rng(self, site: str) -> random.Random:
        rng = self._rngs.get(site)
        if rng is None:
            rng = self._rngs[site] = random.Random(
                (self.seed * 1000003) ^ zlib.crc32(site.encode())
            )
        return rng

    def fire(self, site: str, info: str = "") -> None:
        rules = self.rules.get(site)
        if not rules:
            return
        hit = self._hits.get(site, 0) + 1
        self._hits[site] = hit
        rng = self._rng(site)
        for rule in rules:
            if rule.should_fire(hit, rng):
                # count (and record) the firing BEFORE the action runs —
                # exit/raise must not lose the telemetry of their own
                # firing. Lazy import: this module stays stdlib-only
                # when injection is inactive.
                from paddle_tpu.observability import metrics as obs

                obs.registry().counter("faults.fired").inc()
                obs.emit("fault", site=site, hit=hit,
                         action=rule.action, info=info)
                obs.flush()  # an exit-action fault never reaches atexit
                rule.fire(site, hit, info)

    def hits(self, site: str) -> int:
        return self._hits.get(site, 0)


_injector: Optional[FaultInjector] = None
_env_checked = False


def configure(spec: str, seed: int = 0) -> Optional[FaultInjector]:
    """Install (or with an empty spec, clear) the process-global plan."""
    global _injector, _env_checked
    _env_checked = True  # explicit configuration wins over the env var
    _injector = FaultInjector(spec, seed) if spec else None
    return _injector


def _maybe_configure_from_env() -> None:
    global _env_checked
    _env_checked = True
    spec = os.environ.get(ENV_SPEC, "")
    if spec:
        configure(spec, int(os.environ.get(ENV_SEED, "0") or 0))


def fault_point(site: str, info: str = "") -> None:
    """The hook planted at instrumented sites. No-op unless a plan
    names this site."""
    if not _env_checked:
        _maybe_configure_from_env()
    if _injector is not None:
        _injector.fire(site, info)


def is_active() -> bool:
    if not _env_checked:
        _maybe_configure_from_env()
    return _injector is not None


def current() -> Optional[FaultInjector]:
    if not _env_checked:
        _maybe_configure_from_env()
    return _injector
