"""Fault-tolerance layer.

The reference PaddlePaddle v0 is fail-fast only — its recovery story is
"restart from the last pass directory and hope the files are intact"
(SURVEY §5). At pod scale preemptions, transient shared-filesystem
errors, and hung data providers are routine, so this package supplies
the missing half: *surviving* the failure, not just noticing it.

Pieces (see doc/resilience.md for the failure model):

- ``manifest``  — per-file CRC32/size manifests (``MANIFEST.json``) that
  make a checkpoint directory self-verifying; used by the atomic
  write-rename protocol in ``trainer/checkpoint.py`` and the offline
  ``paddle check-checkpoint`` subcommand.
- ``faultinject`` — deterministic, seeded, site-named fault injection
  (``checkpoint.write``, ``checkpoint.rename``, ``provider.yield``,
  ``provider.stall``, ``trainer.crash``, ``trainer.nonfinite``) so chaos
  tests exercise mid-write crashes, torn renames, flaky providers,
  stalls, mid-run process deaths, and diverging losses reproducibly.
- ``supervisor`` — `paddle supervise`: run `paddle train` as a child
  process, restart it with backoff and ``--init_model_path=auto`` on
  nonzero exit, detect crash loops (repeated death at the same restored
  checkpoint), and emit a JSON crash report when recovery is hopeless.
- ``hangwatch`` — in-process step-progress watchdog: the trainer pings
  it at every launch boundary; a stall longer than
  ``--step_hang_timeout`` dumps all thread stacks + the telemetry tail
  into ``hang_report.json`` and exits ``EXIT_HANG`` so supervisors see
  a *diagnosed* death instead of a silent external timeout.
- ``heartbeat`` — cluster-level liveness: each host renews a heartbeat
  file under the shared run dir; ``cluster_launch`` polls staleness so
  a wedged-but-alive rank is named and torn down instead of burning
  every other host inside a blocked collective.
- errors below — typed failures the trainer and tools can act on.

Exit-code discipline (supervisors and launchers dispatch on these —
all distinct from each other and from ordinary crash codes):

- ``EXIT_CRASH_LOOP`` (17) — the supervisor classified the failure as
  deterministic poison and stopped restarting.
- ``EXIT_PREEMPTED`` (18) — the trainer was SIGTERM-preempted, saved at
  a launch boundary, and exited cleanly; supervisors/launchers restart
  WITHOUT consuming restart budget (preemption is the scheduler's
  decision, not the run's failure).
- ``EXIT_HANG`` (19) — hangwatch detected a stalled step loop, wrote
  ``hang_report.json``, and killed the process; counts as a real
  failure (budget consumed), with forensics attached.
- ``EXIT_OOM`` (20) — a launch died of device-memory exhaustion
  (RESOURCE_EXHAUSTED); the trainer wrote ``oom_report.json``
  (per-launch-group static footprint ranked, last live memory
  snapshot, telemetry tail — observability/memory.py) before exiting.
  Budget-consuming like a hang: an OOM is deterministic poison (the
  same model at the same batch size OOMs again), so an OOM loop must
  never restart for free.

The shared backoff machinery lives in ``paddle_tpu.utils.retry``
(checkpoint I/O and data-provider iteration both use it). The
L-BFGS/OWL-QN line-search "backoff" in ``optimizer/batch_methods.py`` is
a *numerical* step-shrink factor, not an I/O retry, and intentionally
stays separate.
"""

from __future__ import annotations

# canonical process exit codes (see module docstring). EXIT_CRASH_LOOP
# predates this table and is re-exported by resilience.supervisor for
# existing importers; the values must stay distinct forever — wrappers
# dispatch on them.
EXIT_CRASH_LOOP = 17
EXIT_PREEMPTED = 18
EXIT_HANG = 19
EXIT_OOM = 20


class CheckpointError(RuntimeError):
    """A checkpoint operation failed. Base of the corruption case below;
    raised directly by the async checkpoint pipeline
    (``trainer/async_ckpt.py``) when a background write failed — the
    error surfaces on the NEXT save or drain so an async failure can
    never be silently lost."""


class CheckpointCorruptError(CheckpointError):
    """A checkpoint directory failed manifest/completeness verification
    and no fallback pass directory could be restored either."""

    def __init__(self, message: str, problems=None):
        super().__init__(message)
        self.problems = list(problems or [])


class DataStallError(RuntimeError):
    """The data-pipeline watchdog saw no provider progress within the
    configured stall timeout (``--data_stall_timeout``)."""


class BadSampleError(RuntimeError):
    """More malformed samples than ``--max_bad_samples`` allows."""


class NonFiniteLossError(FloatingPointError):
    """A training loss (or whole-data cost) came back NaN/Inf and the
    configured ``--nonfinite_policy`` could not (or may not) recover:
    ``abort`` raises immediately, ``skip``/``rollback`` raise once the
    ``--max_nonfinite_steps`` budget is exhausted or no restorable
    checkpoint exists to roll back to.

    Subclasses ``FloatingPointError`` so pre-existing fail-fast callers
    keep working; supervisors and tests should catch THIS type to
    classify divergence separately from an ordinary crash."""

    def __init__(self, message: str, value=None, pass_id=None, batch_id=None):
        super().__init__(message)
        self.value = value
        self.pass_id = pass_id
        self.batch_id = batch_id


__all__ = [
    "EXIT_CRASH_LOOP",
    "EXIT_PREEMPTED",
    "EXIT_HANG",
    "EXIT_OOM",
    "CheckpointError",
    "CheckpointCorruptError",
    "DataStallError",
    "BadSampleError",
    "NonFiniteLossError",
]
