"""Fault-tolerance layer.

The reference PaddlePaddle v0 is fail-fast only — its recovery story is
"restart from the last pass directory and hope the files are intact"
(SURVEY §5). At pod scale preemptions, transient shared-filesystem
errors, and hung data providers are routine, so this package supplies
the missing half: *surviving* the failure, not just noticing it.

Pieces (see doc/resilience.md for the failure model):

- ``manifest``  — per-file CRC32/size manifests (``MANIFEST.json``) that
  make a checkpoint directory self-verifying; used by the atomic
  write-rename protocol in ``trainer/checkpoint.py`` and the offline
  ``paddle check-checkpoint`` subcommand.
- ``faultinject`` — deterministic, seeded, site-named fault injection
  (``checkpoint.write``, ``checkpoint.rename``, ``provider.yield``,
  ``provider.stall``) so chaos tests exercise mid-write crashes, torn
  renames, flaky providers, and stalls reproducibly.
- errors below — typed failures the trainer and tools can act on.

The shared backoff machinery lives in ``paddle_tpu.utils.retry``
(checkpoint I/O and data-provider iteration both use it). The
L-BFGS/OWL-QN line-search "backoff" in ``optimizer/batch_methods.py`` is
a *numerical* step-shrink factor, not an I/O retry, and intentionally
stays separate.
"""

from __future__ import annotations


class CheckpointCorruptError(RuntimeError):
    """A checkpoint directory failed manifest/completeness verification
    and no fallback pass directory could be restored either."""

    def __init__(self, message: str, problems=None):
        super().__init__(message)
        self.problems = list(problems or [])


class DataStallError(RuntimeError):
    """The data-pipeline watchdog saw no provider progress within the
    configured stall timeout (``--data_stall_timeout``)."""


class BadSampleError(RuntimeError):
    """More malformed samples than ``--max_bad_samples`` allows."""


__all__ = [
    "CheckpointCorruptError",
    "DataStallError",
    "BadSampleError",
]
