"""Cluster-level heartbeat liveness (``--heartbeat_interval``).

`cluster_launch` can only see hosts whose processes *exit*. A rank
wedged inside a collective (or an ssh tunnel that died without killing
the remote) is alive by every process-level test while the rest of the
pod burns inside blocked collectives. The heartbeat layer adds the
missing signal: each host's trainer renews a small JSON file under a
shared directory (``--heartbeat_dir``, defaulting to
``<save_dir>/heartbeats``), and any observer — `cluster_launch` today —
compares file timestamps against ``--heartbeat_stale_after`` to *name*
the wedged rank and tear the job down deliberately.

Design constraints:

- **Atomic renewal** (write tmp + ``os.replace``): a reader never sees
  a torn heartbeat, and a crashed writer leaves the last complete beat
  as evidence of *when* it stopped.
- **Wall-clock timestamps in the payload**, not file mtimes: the files
  live on a shared filesystem whose server sets mtimes; payload time is
  written by the host being judged (pods run NTP; the staleness
  thresholds are tens of seconds, far above sync error).
- **Injectable clock** end to end, so staleness logic is unit-testable
  without sleeping.
- jax-free: the launcher imports this while the accelerator runtime may
  be the thing that is wedged.
"""

from __future__ import annotations

import json
import os
import socket
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from paddle_tpu.utils import concurrency as cc

HEARTBEAT_FMT = "host-%d.json"
# the monitor's default when --heartbeat_stale_after is unset: a beat
# must be missable a couple of times (GC pause, fs hiccup) before a
# host is declared wedged
DEFAULT_STALE_MULTIPLE = 3.0


def resolve_dir(heartbeat_dir: str, save_dir: str) -> str:
    """The one shared resolution rule: an explicit ``--heartbeat_dir``
    wins; otherwise the save_dir (the run's shared directory) hosts a
    ``heartbeats/`` child. Empty when neither is configured — writers
    and monitors both disable themselves then."""
    if heartbeat_dir:
        return heartbeat_dir
    if save_dir:
        return os.path.join(save_dir, "heartbeats")
    return ""


def heartbeat_path(dir_: str, host: int) -> str:
    return os.path.join(dir_, HEARTBEAT_FMT % int(host))


def write_beat(dir_: str, host: int, *, seq: int = 0,
               clock: Callable[[], float] = time.time,
               extra: Optional[Dict[str, Any]] = None) -> str:
    """Write one atomic heartbeat; returns the path."""
    os.makedirs(dir_, exist_ok=True)
    path = heartbeat_path(dir_, host)
    payload = {
        "host": int(host),
        "pid": os.getpid(),
        "hostname": socket.gethostname(),
        "t": clock(),
        "seq": int(seq),
    }
    if extra:
        payload.update(extra)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)
    return path


def read_beats(dir_: str) -> Dict[int, Dict[str, Any]]:
    """{host: payload} for every readable heartbeat under ``dir_``.
    Unparseable or foreign files are skipped — staleness logic treats a
    missing beat the same as a never-started host."""
    out: Dict[int, Dict[str, Any]] = {}
    if not dir_ or not os.path.isdir(dir_):
        return out
    for name in os.listdir(dir_):
        if not (name.startswith("host-") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(dir_, name)) as f:
                payload = json.load(f)
            host = int(payload["host"])
        except (OSError, ValueError, KeyError, TypeError):
            continue
        out[host] = payload
    return out


def stale_hosts(
    dir_: str,
    num_hosts: int,
    stale_after_s: float,
    *,
    now: Optional[float] = None,
    since: Optional[float] = None,
    beats: Optional[Dict[int, Dict[str, Any]]] = None,
) -> List[Tuple[int, float]]:
    """Ranks whose heartbeat age exceeds ``stale_after_s``, with the age.

    ``since`` is the observation epoch (typically the launch time): a
    host that never wrote a beat is aged from ``since`` — so a trainer
    wedged *before its first beat* is still caught — while before
    ``since + stale_after_s`` nothing can be flagged (startup grace).
    ``now`` defaults to wall time; tests pass a fake clock value.
    ``beats`` lets a caller that already paid for ``read_beats`` (the
    launcher reads once per scan for its emptiness check) skip a second
    listdir+parse round-trip against the shared mount.
    """
    now = time.time() if now is None else now
    if beats is None:
        beats = read_beats(dir_)
    out: List[Tuple[int, float]] = []
    for host in range(num_hosts):
        beat = beats.get(host)
        t = None
        if beat is not None and isinstance(beat.get("t"), (int, float)):
            t = float(beat["t"])
        if since is not None:
            t = since if t is None else max(t, since)
        if t is None:
            continue  # no beat and no epoch: nothing to judge against
        age = now - t
        if age > stale_after_s:
            out.append((host, age))
    return out


class HeartbeatWriter:
    """Daemon thread renewing this host's beat every ``interval_s``.

    The final beat on ``stop()`` carries ``"stopped": True`` so a
    monitor can distinguish "exited cleanly between beats" from "went
    silent" when doing post-mortems."""

    def __init__(self, dir_: str, host: int, interval_s: float, *,
                 clock: Callable[[], float] = time.time):
        assert interval_s > 0, interval_s
        self.dir = dir_
        self.host = int(host)
        self.interval_s = float(interval_s)
        self.clock = clock
        self._seq = 0
        # beat() runs on BOTH the daemon renewal thread and the caller
        # (start's synchronous first beat, stop's final one) — the seq
        # increment must not tear between them, and monitors rely on
        # seq to be strictly increasing per host
        self._seq_lock = cc.Lock()
        self._stop = cc.Event()
        self._thread = None

    def beat(self, **extra) -> None:
        from paddle_tpu.utils.logging import logger

        # the lock serializes the WHOLE beat, not just the increment:
        # stop()'s final beat can overlap a daemon-thread beat stuck in
        # slow-fs I/O past the join timeout, and both share the same
        # pid-keyed tmp file — an unserialized pair can tear the write
        # or publish seq N over seq N+1, breaking the strictly-
        # increasing contract monitors rely on. BOUNDED acquire: when
        # the holder is wedged in dead-fs I/O, the caller (stop() at
        # shutdown) must not inherit the wedge — skipping the beat and
        # letting the monitor see staleness is the honest outcome, same
        # rationale as the OSError swallow below
        if not self._seq_lock.acquire(timeout=max(self.interval_s, 1.0)):
            logger.warning(
                "heartbeat: beat skipped for host %d — a concurrent beat "
                "holds the lock (wedged shared-fs write?)", self.host,
            )
            return
        try:
            self._seq += 1  # lint: disable=PTL005 -- _seq_lock IS held: acquired with a timeout above (bounded acquire has no with-form), released in the finally
            try:
                write_beat(self.dir, self.host, seq=self._seq,
                           clock=self.clock,
                           extra={"interval_s": self.interval_s, **extra})
            except OSError as e:
                # liveness reporting must never kill the run it reports
                # on; the monitor sees a stale beat and names this host,
                # which is the honest outcome if the shared fs is gone
                logger.warning("heartbeat write failed for host %d: %s",
                               self.host, e)
        finally:
            self._seq_lock.release()

    def start(self) -> "HeartbeatWriter":
        if self._thread is None:
            self.beat()  # first beat synchronously: monitors see us asap
            self._stop.clear()
            self._thread = cc.Thread(
                target=self._run, name="heartbeat", daemon=True
            )
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.beat()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=max(self.interval_s, 1.0))
        self.beat(stopped=True)

    def __enter__(self) -> "HeartbeatWriter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
