"""paddle_tpu — a TPU-native deep-learning framework.

A from-scratch rebuild of the capabilities of PaddlePaddle v0 (the 2016
layer-graph framework) designed for TPU hardware: layers are pure functions
over jax arrays, the gradient machine is a jit-compiled train step, and
distribution is SPMD over a `jax.sharding.Mesh` (ICI collectives) instead of
a socket parameter-server.

Public surface (mirrors the roles of the reference's python/paddle +
paddle/api, see /root/reference SURVEY):

- ``paddle_tpu.trainer_config_helpers`` — the user-facing config DSL
  (``fc_layer``, ``lstmemory``, ``recurrent_group``, ``settings`` ...).
- ``paddle_tpu.config`` — ``parse_config`` turning a user config script into
  a ``TrainerConfig``.
- ``paddle_tpu.graph`` — ``GradientMachine``: compiles a ``ModelConfig``
  into jitted forward/backward functions.
- ``paddle_tpu.trainer`` — the training driver (pass/batch loops,
  checkpointing, evaluation).
- ``paddle_tpu.parallel`` — device mesh, SPMD train-step sharding,
  collectives, ring attention.
- ``paddle_tpu.data`` — the ``@provider`` data ingestion contract.
"""

from paddle_tpu.version import __version__

__all__ = ["__version__"]
