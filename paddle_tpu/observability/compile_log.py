"""Per-launch-group compile telemetry + the persistent compilation cache.

Every distinct launch group the trainer dispatches (fused step, single
step, test forward, generator — one per batch-shape signature) costs a
trace + an XLA compile the first time it runs, and costs it AGAIN on
every process restart: the elastic/preemption machinery made restarts
frequent, which made recompilation a first-order throughput tax nobody
could see (ROADMAP item 5). This module makes every compile a schema
record and makes the cache persistent:

- :class:`CompileRegistry` AOT-compiles each (group, signature) once
  via ``fn.lower(...).compile()`` — timing the trace and the compile
  separately — pulls XLA's cost analysis off the executable
  (``observability/costs.py``), and emits a ``kind=compile`` record
  (trace_s, compile_s, recompile count, cache hit/miss, FLOPs, bytes).
  Callables without ``.lower`` (the mesh-sharded step closures, plain
  python) degrade to timing the first dispatch as one combined number
  (``mode="inline"``) — the telemetry never loses a compile, it just
  reports it coarser.
- :func:`enable_compile_cache` wires jax's persistent compilation cache
  to ``--compile_cache_dir``: warm restarts skip the XLA backend
  compile, and the compile records prove it (``cache_hit=true``, lower
  ``time_to_first_step_s`` in the PR-6 ``restart`` record).
- The registry also accumulates per-group execution time
  (:meth:`CompileRegistry.note_exec`) and emits ``kind=roofline``
  records at pass end — the raw material of ``paddle roofline``.

Cache-hit detection is host-side and observational: a compile that
consults the persistent cache writes a new ``*-cache`` entry on a miss
and writes nothing on a hit, so counting entries around the compile
classifies it without reaching into jax internals. Single-process
precise; on a pod several hosts may race the same entry — the records
stay per-host honest ("this host's compile did not add an entry").
"""

from __future__ import annotations

import hashlib
import os
import time
from typing import Any, Callable, Dict, Optional, Tuple

from paddle_tpu.observability import metrics as obs
from paddle_tpu.utils.logging import logger

# the enabled persistent-cache dir ("" = off) — module state, one per
# process, matching jax's own process-global cache config
_cache_dir: str = ""


def enable_compile_cache(cache_dir: str) -> bool:
    """Point jax's persistent compilation cache at ``cache_dir``
    (created if missing). Also drops the min-compile-time/entry-size
    gates so even fast CPU-backend compiles populate the cache — without
    that, smoke-scale steps would never cache and a warm restart would
    measure nothing. Returns True when the cache is active; never
    raises (telemetry must not take down the run it observes)."""
    global _cache_dir
    if not cache_dir:
        return False
    try:
        import jax

        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        for name, val in (
            ("jax_persistent_cache_min_compile_time_secs", 0.0),
            ("jax_persistent_cache_min_entry_size_bytes", -1),
        ):
            try:
                jax.config.update(name, val)
            except Exception:
                pass  # older jax: its defaults apply
        _cache_dir = cache_dir
        logger.info("persistent compilation cache: %s", cache_dir)
        return True
    except Exception as e:
        logger.warning(
            "persistent compilation cache unavailable (%s): %s", cache_dir, e
        )
        return False


def cache_dir() -> str:
    return _cache_dir


def _cache_entries() -> Optional[int]:
    """Number of compiled-executable entries in the persistent cache
    (None = cache off/unreadable)."""
    if not _cache_dir:
        return None
    try:
        return sum(1 for f in os.listdir(_cache_dir) if f.endswith("-cache"))
    except OSError:
        return None


def cache_probe() -> Callable[[], Optional[bool]]:
    """Snapshot for hit detection: call BEFORE a compile, call the
    returned closure after — True = hit (no new cache entry written),
    False = miss, None = cache disabled/unreadable."""
    before = _cache_entries()

    def hit() -> Optional[bool]:
        after = _cache_entries()
        if before is None or after is None:
            return None
        return after == before

    return hit


def sig_hash(key: Any) -> str:
    """Short stable id of a launch-group signature key for records
    (the full key is a nested shape/dtype tuple — too long to log)."""
    return hashlib.md5(repr(key).encode()).hexdigest()[:10]


class _Entry:
    __slots__ = (
        "sig", "callable", "fallback_fn", "flops", "bytes_accessed",
        "flops_analytic", "exec_s", "calls", "batches", "compile_s_pending",
        "degraded", "mem",
    )

    def __init__(self, sig: str, callable_, fallback_fn):
        self.sig = sig
        self.callable = callable_
        self.fallback_fn = fallback_fn
        self.flops: Optional[float] = None
        self.bytes_accessed: Optional[float] = None
        self.flops_analytic: Optional[float] = None
        # static memory plan (mem_*_bytes, observability/memory.py) —
        # the OOM pre-mortem ranks launch groups from these
        self.mem: Optional[Dict[str, int]] = None
        self.exec_s = 0.0
        self.calls = 0
        self.batches = 0
        # trace+compile seconds paid INSIDE the first timed launch —
        # note_exec subtracts it once so roofline exec time measures
        # execution, not compilation
        self.compile_s_pending = 0.0
        self.degraded = False


class CompileRegistry:
    """Per-trainer compile/cost bookkeeping for launch groups.

    ``call(group, key, fn, *args)`` routes a launch through the cached
    AOT executable for its (group, signature); the first call per
    signature is the instrumented compile. ``note_exec`` accumulates the
    caller-measured wall time (the caller's timing includes the
    device sync the registry cannot see), and ``emit_roofline`` turns
    the accumulated totals into ``kind=roofline`` records.
    """

    def __init__(self, device_kind: Optional[str] = None):
        self._entries: Dict[Tuple[str, Any], _Entry] = {}
        self._warned_flops: set = set()
        self._warned_degraded: set = set()
        self._device_kind = device_kind
        # compiles per group over the registry's LIFETIME — survives
        # invalidate(), so a rollback re-jit records recompiles>0
        self._group_compiles: Dict[str, int] = {}
        # exec totals of invalidated entries, re-seeded into the
        # recompiled entry: roofline records are cumulative per
        # (group, sig) and the analyzers keep latest-wins, so losing
        # the pre-rollback totals would skew achieved FLOP/s upward
        self._carryover: Dict[Tuple[str, Any], Tuple[float, int, int]] = {}

    @property
    def device_kind(self) -> Optional[str]:
        return self._device_kind

    # ------------------------------------------------------------- call

    def call(self, group: str, key: Any, fn, *args,
             analytic_flops: Optional[float] = None,
             pass_id: Optional[int] = None, step: Optional[int] = None):
        ent = self._entries.get((group, key))
        if ent is not None:
            return self._run(group, ent, args)
        return self._first_call(group, key, fn, args, analytic_flops,
                                pass_id, step)

    def _run(self, group: str, ent: _Entry, args):
        if ent.callable is not ent.fallback_fn:
            try:
                return ent.callable(*args)
            except (TypeError, ValueError) as e:
                # an AOT executable is stricter than jit dispatch about
                # input avals/shardings; a rejection is raised BEFORE
                # dispatch (TypeError/ValueError), so re-running via the
                # jit path is safe even with donated buffers. Runtime
                # failures (OOM etc.) propagate — after dispatch the
                # donated args are gone and a retry would only mask the
                # real error with "Array has been deleted".
                if group not in self._warned_degraded:
                    self._warned_degraded.add(group)
                    logger.warning(
                        "AOT executable for launch group %r rejected its "
                        "inputs (%s: %s) — falling back to jit dispatch",
                        group, type(e).__name__, e,
                    )
                ent.callable = ent.fallback_fn
                ent.degraded = True
        return ent.fallback_fn(*args)

    def _first_call(self, group, key, fn, args, analytic_flops,
                    pass_id, step):
        rec: Dict[str, Any] = {
            "group": group,
            "sig": sig_hash(key),
            # compiles of this group BEFORE this one: >0 means the group
            # recompiled (new batch signature / rollback invalidation —
            # lifetime count, so invalidate() cannot reset it to 0)
            "recompiles": self._group_compiles.get(group, 0),
        }
        self._group_compiles[group] = self._group_compiles.get(group, 0) + 1
        hit_probe = cache_probe()
        out = None
        callable_ = fn
        cost = None
        mem = None
        lower = getattr(fn, "lower", None)
        if lower is not None:
            try:
                t0 = time.perf_counter()
                lowered = lower(*args)
                t1 = time.perf_counter()
                compiled = lowered.compile()
                t2 = time.perf_counter()
                rec["trace_s"] = round(t1 - t0, 6)
                rec["compile_s"] = round(t2 - t1, 6)
                from paddle_tpu.observability.costs import cost_analysis_of
                from paddle_tpu.observability.memory import memory_analysis_of

                cost = cost_analysis_of(compiled)
                # static HBM plan (argument/output/temp/generated
                # bytes): joined onto the SAME compile record, so every
                # launch group's planned footprint is on disk before
                # the first step runs — the raw material of
                # `paddle memory` and the OOM pre-mortem
                mem = memory_analysis_of(compiled)
                callable_ = compiled
            except Exception as e:
                logger.debug(
                    "AOT compile of launch group %r failed (%s) — timing "
                    "the first dispatch instead", group, e, exc_info=True,
                )
                lower = None
        if lower is None:
            # no .lower (mesh-sharded closures, plain python) or AOT
            # refused: the first dispatch pays trace+compile together —
            # still measured, just not separable
            t0 = time.perf_counter()
            out = fn(*args)
            rec["compile_s"] = round(time.perf_counter() - t0, 6)
            rec["mode"] = "inline"
        hit = hit_probe()
        if hit is not None:
            rec["cache_hit"] = hit
        if cost is not None:
            rec.update(cost)  # flops / bytes_accessed, whichever exist
        if mem is not None:
            rec.update(mem)  # mem_*_bytes static footprint, when known
        if analytic_flops:
            rec["flops_analytic"] = float(analytic_flops)
        self._cross_check(group, rec)
        r = obs.registry()
        r.counter("compile.count").inc()
        r.counter("compile.total_s").inc(
            rec.get("compile_s", 0.0) + rec.get("trace_s", 0.0)
        )
        if hit is True:
            r.counter("compile.cache_hits").inc()
        elif hit is False:
            r.counter("compile.cache_misses").inc()
        obs.emit("compile", pass_id=pass_id, step=step, **rec)
        ent = _Entry(rec["sig"], callable_, fn)
        ent.flops = rec.get("flops")
        ent.bytes_accessed = rec.get("bytes_accessed")
        ent.flops_analytic = rec.get("flops_analytic")
        ent.mem = mem
        ent.compile_s_pending = rec.get("compile_s", 0.0) + rec.get("trace_s", 0.0)
        carried = self._carryover.pop((group, key), None)
        if carried is not None:
            ent.exec_s, ent.calls, ent.batches = carried
        self._entries[(group, key)] = ent
        if out is None:
            out = self._run(group, ent, args)
        return out

    def _cross_check(self, group: str, rec: Dict[str, Any]) -> None:
        """Satellite: the analytic matmul count (the MFU basis) vs XLA's
        cost analysis, once per signature — >10% disagreement becomes a
        logged warning instead of folklore (kernel_flops.py documents
        that XLA counts scan/while bodies once regardless of trip count,
        so scanned models are understated there)."""
        af, xf = rec.get("flops_analytic"), rec.get("flops")
        if not af or not xf:
            return
        ratio = abs(af - xf) / max(abs(af), abs(xf))
        rec["flops_disagreement"] = round(ratio, 4)
        mark = (group, rec["sig"])
        if ratio > 0.10 and mark not in self._warned_flops:
            self._warned_flops.add(mark)
            logger.warning(
                "FLOPs accounting disagreement for launch group %r (sig "
                "%s): analytic %.4g vs XLA cost analysis %.4g (%.0f%% "
                "apart). XLA counts scan/while bodies once regardless of "
                "trip count (ops/kernel_flops.py), so scanned models are "
                "understated there; MFU and the roofline use the analytic "
                "count when present.",
                group, rec["sig"], af, xf, ratio * 100,
            )

    # ------------------------------------------------------ exec/roofline

    def note_exec(self, group: str, key: Any, seconds: float,
                  batches: int = 1) -> None:
        """Attribute one launch's measured wall time (caller-timed, sync
        included) to its group. The first launch's time has the compile
        cost deducted — roofline positions measure execution."""
        ent = self._entries.get((group, key))
        if ent is None:
            return
        s = float(seconds)
        if ent.compile_s_pending:
            s = max(s - ent.compile_s_pending, 0.0)
            ent.compile_s_pending = 0.0
        ent.exec_s += s
        ent.calls += 1
        ent.batches += int(batches)

    def drop_pending(self, group: str, key: Any) -> None:
        """Discard the pending compile-cost deduction of a group whose
        first launch was thrown away (non-finite skip): the launch that
        paid the compile never reaches note_exec, and the deduction
        must not zero a later clean launch's exec time instead."""
        ent = self._entries.get((group, key))
        if ent is not None:
            ent.compile_s_pending = 0.0

    def emit_roofline(self, pass_id: Optional[int] = None) -> None:
        """One ``kind=roofline`` record per launch group with execution
        data — cumulative totals (the analyzer keeps latest-wins per
        (host, group, sig), so restarts/re-runs never double-count)."""
        for (group, _key), ent in self._entries.items():
            if not ent.calls:
                continue
            rec: Dict[str, Any] = {
                "group": group,
                "sig": ent.sig,
                "launches": ent.calls,
                "batches": ent.batches,
                "exec_s": round(ent.exec_s, 6),
            }
            if ent.flops:
                rec["flops_per_launch"] = ent.flops
            if ent.flops_analytic:
                rec["flops_analytic_per_launch"] = ent.flops_analytic
            if ent.bytes_accessed:
                rec["bytes_per_launch"] = ent.bytes_accessed
            if self._device_kind:
                rec["device_kind"] = self._device_kind
            obs.emit("roofline", pass_id=pass_id, **rec)

    def static_memory_rows(self) -> list:
        """Per-launch-group static memory plan (mem_*_bytes), ranked by
        total footprint — the OOM pre-mortem's group ranking. Groups
        whose backend reported no memory analysis are absent (omitted,
        never guessed)."""
        rows = []
        for (group, _key), ent in self._entries.items():
            if not ent.mem:
                continue
            rows.append({
                "group": group, "sig": ent.sig, "launches": ent.calls,
                **ent.mem,
            })
        rows.sort(key=lambda r: -int(r.get("mem_total_bytes", 0)))
        return rows

    def invalidate(self, *groups: str) -> None:
        """Drop the cached executables of the named groups (rollback
        retunes the learning rate — the baked constants are stale). The
        groups' cumulative exec totals are carried over to the
        recompiled entries: the roofline records share the (group, sig)
        identity across the recompile, and the analyzers keep
        latest-wins, so a reset here would silently shed the
        pre-rollback execution time."""
        for k in [k for k in self._entries if k[0] in groups]:
            ent = self._entries.pop(k)
            if ent.calls:
                self._carryover[k] = (ent.exec_s, ent.calls, ent.batches)
