"""``paddle compare <run_a> <run_b>`` — diff two runs, with a verdict.

Bench trajectory has been eyeballed across ``BENCH_*.json`` files and
run dirs since round 1; this makes the comparison mechanical. Each side
may be:

- a **run dir** (or one ``metrics*.jsonl``): compared on the analyzer's
  steady-state numbers — last-pass step p50/p99, samples/s, MFU,
  data-wait share, total checkpoint-blocked seconds, compile totals
  (count / seconds / cache hits), and worst time-to-first-step;
- a **bench artifact**: a ``BENCH_*.json`` driver record (the last
  parseable result line inside its ``tail``), or a raw bench JSON line
  file — compared on the headline value plus every numeric leg;
- a **lint artifact** (``paddle lint --json`` output): compared on the
  total and per-rule NEW-finding counts from the ``lint_summary``
  record — all lower-is-better, zero-filled from the summary's rule
  list so a rule going 0 → N is judged (REGRESSION, exit 1) instead of
  falling into ``only_b``;
- a **race artifact** (``paddle race --json`` output): same shape as
  the lint diff — total and per-detector NEW-finding counts from the
  ``race_summary`` record, zero-filled from its detector list, all
  lower-is-better (a PR introducing a lock-order inversion regresses).

Every shared metric gets a relative delta and a per-metric verdict
against a noise threshold (``--threshold``, default 5%): metrics where
higher is better (throughput, MFU) regress when B is lower; latency-like
metrics (step quantiles, data-wait, compile seconds, ttfs) regress when
B is higher. The overall verdict is REGRESSION if any metric regressed,
IMPROVED if any improved (and none regressed), else NO CHANGE — and the
exit code is 1 on REGRESSION so scripts can gate on it.

jax-free, like the other analyzers.

Usage::

    paddle compare <run_a> <run_b> [--threshold 0.05] [--abs-floor 0.05]
                   [--json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

from paddle_tpu.observability import metrics as obs

# metric name -> True when higher is better (throughput-like); absent
# names are matched by _higher_is_better's suffix rules
_HIGHER_BETTER = {
    "samples_per_sec": True,
    "mfu": True,
    "step_p50_ms": False,
    "step_p99_ms": False,
    "data_wait_share": False,
    "ckpt_blocked_s": False,
    "compile_count": False,
    "compile_total_s": False,
    "time_to_first_step_s": False,
    "restore_s": False,
    "cache_hits": True,
    # memory plane: footprint growth is a regression (the headroom the
    # next batch-size bump needs); numerics: a layer newly producing
    # nonfinite gradients is a regression even when throughput held
    "hbm_peak_bytes": False,
    "static_mem_bytes": False,
    "nonfinite_layers": False,
    # sparse plane (doc/sparse.md): rows/s is throughput; gather share
    # growing means the step is spending more of itself fetching rows
    "sparse_rows_per_sec": True,
    "sparse_gather_share": False,
}


def _serve_key(offered_rps, qualifier, seen_pre: set,
               engine: Optional[str] = None,
               pipeline: Optional[str] = None,
               replicas: Any = None,
               transport: Optional[str] = None,
               spec: Optional[str] = None,
               slot_dtype: Optional[str] = None) -> str:
    """The ONE serve rung key format, shared by the run-dir and bench-
    artifact sides (a divergence would silently break their
    comparability): 6 significant digits of offered load — a slow
    backend's sub-1 req/s ladder must not collapse rungs into one key —
    with later duplicates engine-qualified first (a both-engines
    artifact repeats every rate once per engine; joining them as one
    key would diff an engine against itself), then PIPELINE-qualified
    (a one-artifact pipelined-vs-blocking sweep repeats every (engine,
    rate) once per mode), and finally rung-qualified (variance-gauging
    repeated rates) instead of silently overwritten.

    The rung join is therefore (engine, pipeline, replicas, offered
    load): two sweeps of the SAME configuration join on offered load
    alone; mismatched ladders land in only_a/only_b (visible, never a
    bogus verdict); and a pure A/B — one engine (or one pipeline mode)
    per artifact, pinned PADDLE_TPU_BENCH_SERVE_RATES — joins on
    offered load, which is exactly the static-vs-continuous (or
    pipelined-vs-blocking) comparison being asked for.

    Fleet rungs (``--replicas=N``, N > 1) carry an unconditional
    ``xN`` qualifier: a replicas ladder repeats every (engine, rate)
    once per fleet size IN ONE artifact, and the scaling curve
    (goodput vs replicas, router overhead share) is read by joining
    same-x rungs across artifacts — an x2 rung must never diff against
    an x4 one.

    Transport (``pipe`` vs ``tcp``, the socket-fleet sweep) qualifies
    only on collision, AFTER pipeline: a one-transport-per-artifact
    A/B (pipe baseline vs tcp candidate, pinned rates) joins on
    offered load alone — which is exactly the cross-transport
    router_share comparison being asked for — while a both-transports
    artifact repeats every (engine, pipeline, rate) once per wire and
    must not diff a transport against itself.

    Speculation config (``spec``, the draft-length ladder spelling or
    "off") and slot-state dtype qualify the same way, after pipeline:
    the intended spec-on-vs-spec-off (or bf16-vs-f32) A/B is one
    config per artifact with pinned rates — joining on offered load
    alone — while a both-configs sweep in ONE artifact repeats every
    (engine, pipeline, rate) once per config and must not diff a
    config against itself."""
    rate = format(float(offered_rps or 0.0), ".6g")
    x = f"x{int(replicas)}." if replicas and int(replicas) > 1 else ""
    pre = f"serve.{x}{rate}rps."
    if pre in seen_pre and engine:
        pre = f"serve.{engine}.{x}{rate}rps."
    if pre in seen_pre and engine and pipeline:
        pre = f"serve.{engine}.pipe-{pipeline}.{x}{rate}rps."
    if pre in seen_pre and engine and pipeline and spec:
        pre = f"serve.{engine}.pipe-{pipeline}.spec-{spec}.{x}{rate}rps."
    if pre in seen_pre and engine and pipeline and spec and slot_dtype:
        pre = (f"serve.{engine}.pipe-{pipeline}.spec-{spec}"
               f".dt-{slot_dtype}.{x}{rate}rps.")
    if pre in seen_pre and engine and pipeline and transport:
        pre = f"serve.{engine}.pipe-{pipeline}.net-{transport}.{x}{rate}rps."
    if pre in seen_pre:
        pre = f"{pre[:-1]}.r{qualifier}."
    seen_pre.add(pre)
    return pre


def _engine_scoped(pre: str, engine: Optional[str], key: str) -> str:
    """Key for SHARE-type rung metrics (queue_wait_share): a share of
    e2e is only comparable when the latency regime is shared, so these
    are engine-qualified unconditionally — same-engine A/Bs still join,
    while a cross-engine join (where the denominator shrank with the
    engine change) lands in only_a/only_b instead of minting a phantom
    verdict."""
    if not engine:
        return pre + key
    if pre.startswith(f"serve.{engine}."):
        return pre + key  # already engine-qualified (both-engines side)
    return f"serve.{engine}.{pre[len('serve.'):]}{key}"


def _higher_is_better(name: str) -> bool:
    if name in _HIGHER_BETTER:
        return _HIGHER_BETTER[name]
    n = name.lower()
    # per-rung overload-defense rates (shed = policy refusals, error =
    # failed launches): growth is a serving regression. Checked before
    # the generic suffix rules — neither matches "_s"/"latency", and
    # the throughput default would judge them backwards
    if n.endswith(("shed_rate", "error_rate")):
        return False
    # speculative-decode draft acceptance (doc/serving.md "Speculative
    # decode"): a higher share of draft tokens surviving verification
    # is more free tokens per launch — explicit because the generic
    # rules below would only cover it by the fall-through default
    if n.endswith("accept_rate"):
        return True
    # lint/race metrics are finding counts: fewer is always better (and
    # the bare rule/detector ids would otherwise fall through to the
    # throughput default below)
    if n.startswith(("lint", "race")):
        return False
    # tail-attribution shares (doc/observability.md "Distributed
    # tracing"): an overhead bucket growing its slice of the p99 cohort
    # is a regression — EXCEPT decode, whose share growing means the
    # tail spends its time on useful token work instead of waiting (a
    # decode-dominated p99 is the healthy end state)
    if ".p99_share." in n:
        return n.endswith(".decode")
    # serving metrics (doc/observability.md "Serving telemetry"):
    # goodput and the saturation knee are throughput-like; latency/TTFT/
    # queue-wait fall through to the lower-is-better suffixes below
    if any(s in n for s in ("per_sec", "per_chip", "samples", "tokens",
                            "imgs", "speedup", "mfu", "hits", "goodput",
                            "knee")):
        return True
    if any(s in n for s in ("_s", "_ms", "latency", "wait", "blocked",
                            "compile", "p50", "p99", "_bytes")):
        return False
    return True  # bench values are throughput by convention


# ------------------------------------------------------------- run sides


def _run_side(path: str) -> Dict[str, float]:
    """Comparable scalars of one run dir / metrics stream."""
    from paddle_tpu.observability.analyze import analyze, load_run

    streams = load_run(path)
    doc = analyze(streams)
    out: Dict[str, float] = {}
    # steady state: the LAST pass row carries the converged step shape
    if doc["passes"]:
        last = doc["passes"][-1]
        for src, dst, scale in (
            ("samples_per_sec", "samples_per_sec", 1.0),
            ("mfu", "mfu", 1.0),
            ("step_time_p50_s", "step_p50_ms", 1e3),
            ("step_time_p99_s", "step_p99_ms", 1e3),
            ("data_wait_share", "data_wait_share", 1.0),
        ):
            if src in last:
                out[dst] = float(last[src]) * scale
        # 0.0 is a real measurement (async saves block nothing) and must
        # stay comparable — omitting it would hide a 0 → nonzero
        # regression from the verdict
        out["ckpt_blocked_s"] = sum(
            float(r.get("ckpt_blocked_s", 0.0)) for r in doc["passes"]
        )
    t = doc.get("compile_totals") or {}
    if t.get("count"):
        out["compile_count"] = float(t["count"])
        out["compile_total_s"] = t["trace_s"] + t["compile_s"]
        out["cache_hits"] = float(t["cache_hits"])
    lat = doc.get("restart_latency") or {}
    if lat:
        out["time_to_first_step_s"] = float(lat["time_to_first_step_s_max"])
        out["restore_s"] = float(lat["restore_s_max"])
    # memory plane: worst last-snapshot HBM peak across hosts (lower is
    # better — footprint growth is the regression the OOM pre-mortem
    # exists for). Host RSS deliberately stays OUT of the verdict
    # surface: it moves a few percent between identical runs (allocator
    # noise), and a flaky REGRESSION teaches people to ignore the tool.
    # Numerics plane: distinct layers that produced a nonfinite
    # gradient, zero-filled whenever numerics ran so 0 -> N gets a
    # REGRESSION verdict instead of landing in only_b
    mem_last = (doc.get("memory") or {}).get("last") or {}
    peaks = [
        float(r["hbm_peak_bytes"]) for r in mem_last.values()
        if isinstance(r.get("hbm_peak_bytes"), (int, float))
    ]
    if peaks:
        out["hbm_peak_bytes"] = max(peaks)
    num = doc.get("numerics")
    if num is not None:
        out["nonfinite_layers"] = float(len(num.get("nonfinite_layers") or ()))
    # serve runs (doc/observability.md "Serving telemetry"): per-rung
    # latency/TTFT (lower is better) and goodput (higher), keyed by the
    # rung's OFFERED LOAD — not its index: two auto-calibrated sweeps
    # can land different rate ladders, and joining rung 3 of a 20 req/s
    # ladder against rung 3 of a 10 req/s ladder would judge a 2x-load
    # latency gap as a perf regression. Mismatched ladders instead fall
    # into only_a/only_b (visible, never a bogus verdict); pin
    # PADDLE_TPU_BENCH_SERVE_RATES for A/B runs. The knee rides as one
    # headline number either way. A run dir can carry both training and
    # serve telemetry — the key namespaces never collide.
    # per-replica fleet windows (carrying `replica`) are diagnostics,
    # not comparison units: N of them share one (engine, pipeline,
    # rate) per rung, and the MERGED replicas=N rollup is the record
    # the scaling curve joins on — keying the parts would mint
    # nondeterministic .rN qualifiers and bogus cross-replica diffs
    windows = [w for w in (doc.get("serve_windows") or [])
               if not w.get("replica")]
    seen_pre: set = set()
    # p99 tail-latency attribution (doc/observability.md "Distributed
    # tracing"): per-rate bucket shares reconstructed from the run's
    # span streams, ZERO-FILLED below so pre-tracing artifacts (no span
    # records) still share the keys — a 0 -> N queue-wait share then
    # gets a REGRESSION verdict instead of landing invisibly in only_b.
    # Joined on the same ".6g" offered-load format as the rung keys.
    from paddle_tpu.observability.tracing import (BUCKETS,
                                                  p99_shares_by_rate)

    # training-only dirs skip the trace pass (it would re-read every
    # stream just to find zero rungs)
    shares_by_rate = ({format(rate, ".6g"): s
                       for rate, s in p99_shares_by_rate(path).items()}
                      if windows else {})
    # deterministic key assignment: iterate (engine, rung)-sorted so a
    # both-engines stream always hands the SAME engine the unqualified
    # keys regardless of which sweep was recorded first — two such
    # artifacts then join engine-to-engine, never crosswise
    for w in sorted(windows,
                    key=lambda w: (str(w.get("engine") or ""),
                                   str(w.get("pipeline") or ""),
                                   str(w.get("spec") or ""),
                                   str(w.get("slot_dtype") or ""),
                                   int(w.get("replicas") or 0),
                                   str(w.get("transport") or ""),
                                   w.get("rung") if isinstance(
                                       w.get("rung"), int) else 0)):
        engine = w.get("engine") if isinstance(w.get("engine"), str) else None
        pipe = w.get("pipeline") if isinstance(w.get("pipeline"), str) else None
        tran = (w.get("transport")
                if isinstance(w.get("transport"), str) else None)
        pre = _serve_key(w.get("offered_rps"), w.get("rung", 0), seen_pre,
                         engine=engine, pipeline=pipe,
                         replicas=w.get("replicas"), transport=tran,
                         spec=(w.get("spec")
                               if isinstance(w.get("spec"), str) else None),
                         slot_dtype=(w.get("slot_dtype")
                                     if isinstance(w.get("slot_dtype"), str)
                                     else None))
        for snap_key, dst, scale in (
            ("latency", "p50_ms", 1e3), ("latency", "p99_ms", 1e3),
            ("ttft", "ttft_p50_ms", 1e3), ("ttft", "ttft_p99_ms", 1e3),
        ):
            q = "p99" if "p99" in dst else "p50"
            v = (w.get(snap_key) or {}).get(q)
            if isinstance(v, (int, float)):
                out[pre + dst] = float(v) * scale
        if isinstance(w.get("goodput_tok_s"), (int, float)):
            out[pre + "goodput_tok_s"] = float(w["goodput_tok_s"])
        if isinstance(w.get("queue_wait_share"), (int, float)):
            out[_engine_scoped(pre, engine, "queue_wait_share")] = float(
                w["queue_wait_share"])
        if isinstance(w.get("router_share"), (int, float)):
            # fleet rungs: the router's measured host-seconds share of
            # the window — the scaling curve's overhead axis
            out[_engine_scoped(pre, engine, "router_share")] = float(
                w["router_share"])
        # overload-defense rates, ZERO-FILLED when the window predates
        # them (pre-shed artifacts carry no `shed` field): both sides
        # then share the keys, and 0 -> N shed/error growth gets a
        # REGRESSION verdict instead of landing invisibly in only_b
        arrived = w.get("arrived")
        if isinstance(arrived, (int, float)) and arrived > 0:
            out[pre + "shed_rate"] = round(
                float(w.get("shed", 0) or 0) / float(arrived), 6)
            out[pre + "error_rate"] = round(
                float(w.get("errors", 0) or 0) / float(arrived), 6)
        # speculative-decode acceptance, ZERO-FILLED like shed_rate:
        # pre-speculation artifacts (no accept_rate field) still share
        # the key, and 0 -> N acceptance shows up as IMPROVED instead
        # of landing invisibly in only_b. Only the continuous engine
        # speculates — static windows stay 0 == 0 (SAME).
        if engine == "continuous":
            out[pre + "accept_rate"] = round(
                float(w.get("accept_rate", 0.0) or 0.0), 6)
        # engine-scoped like the other share metrics: a share of e2e is
        # only comparable within one latency regime
        shares = shares_by_rate.get(
            format(float(w.get("offered_rps") or 0.0), ".6g")) or {}
        for bucket in BUCKETS:
            out[_engine_scoped(pre, engine, f"p99_share.{bucket}")] = round(
                float(shares.get(bucket, 0.0)), 6)
    if windows:
        from paddle_tpu.observability.serving import saturation_knee

        knee = saturation_knee(windows)
        if knee is not None:
            out["serve_knee_rps"] = float(knee)
    return out


# ----------------------------------------------------------- bench sides


def _bench_lines(text: str) -> List[Dict[str, Any]]:
    """Bench result lines: the shared tolerant JSONL policy, narrowed
    to records carrying a ``metric`` key (driver tails mix result lines
    with free-form log output)."""
    return [rec for rec in obs.parse_record_lines(text) if "metric" in rec]


def _bench_side(path: str, raw: str) -> Dict[str, float]:
    """Comparable scalars of one bench artifact: the headline value plus
    every numeric leg/extras field (compile_s, cache-hit counts included
    — bench records carry them since the compile-telemetry PR)."""
    try:
        doc = json.loads(raw)
    except ValueError:
        doc = None
    if isinstance(doc, dict) and "tail" in doc and "metric" not in doc:
        # BENCH_*.json driver artifact: result lines live in the tail
        lines = _bench_lines(doc["tail"])
    elif isinstance(doc, dict) and "metric" in doc:
        lines = [doc]
    else:
        lines = _bench_lines(raw)
    good = [
        l for l in lines
        if l.get("metric") != "bench_failed"
        and isinstance(l.get("value"), (int, float))
    ]
    if not good:
        raise ValueError(f"no bench result line in {path!r}")
    line = good[-1]  # cumulative re-emits: the last line is most complete
    out: Dict[str, float] = {line["metric"]: float(line["value"])}
    if isinstance(line.get("mfu"), (int, float)):
        out["mfu"] = float(line["mfu"])
    # same quantity under the same name as the run-dir side: trace +
    # XLA compile together (a bench-vs-run comparison must not diff
    # two different definitions of "compile_total_s")
    if isinstance(line.get("compile_s"), (int, float)):
        out["compile_total_s"] = float(line["compile_s"]) + float(
            line.get("trace_s") or 0.0
        )
    # memory trajectory: bench legs stamp static_mem_bytes (the leg's
    # compiled plan — deterministic, comparable) AND peak_hbm_bytes
    # (allocator peak). Only the static plan joins the verdict surface:
    # the allocator peak is cumulative over the PROCESS, so a ladder
    # leg that stepped down past an OOM'd larger attempt inherits that
    # attempt's peak — diffing it against a straight-to-size baseline
    # would manufacture a phantom footprint regression.
    v = line.get("static_mem_bytes")
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        out["static_mem_bytes"] = float(v)
    # serve-leg artifacts (doc/observability.md "Serving telemetry"):
    # the archived BENCH_*.json carries per-rung latency/TTFT/goodput
    # and the knee — comparable WITHOUT the telemetry run dir, under
    # the same offered-load-keyed join as the run-dir side
    seen_pre: set = set()
    rungs = [(i, r) for i, r in enumerate(line.get("rungs") or [])
             if isinstance(r, dict)]
    # (engine, pipeline, replicas, transport, index)-sorted for the same
    # deterministic key assignment as the run-dir side (see _run_side)
    rungs.sort(key=lambda p: (str(p[1].get("engine") or ""),
                              str(p[1].get("pipeline") or ""),
                              str(p[1].get("spec") or ""),
                              str(p[1].get("slot_dtype") or ""),
                              int(p[1].get("replicas") or 0),
                              str(p[1].get("transport") or ""), p[0]))
    for i, r in rungs:
        engine = r.get("engine") if isinstance(r.get("engine"), str) else None
        pipe = r.get("pipeline") if isinstance(r.get("pipeline"), str) else None
        tran = (r.get("transport")
                if isinstance(r.get("transport"), str) else None)
        pre = _serve_key(r.get("offered_rps"), i, seen_pre, engine=engine,
                         pipeline=pipe, replicas=r.get("replicas"),
                         transport=tran,
                         spec=(r.get("spec")
                               if isinstance(r.get("spec"), str) else None),
                         slot_dtype=(r.get("slot_dtype")
                                     if isinstance(r.get("slot_dtype"), str)
                                     else None))
        for key in ("p50_ms", "p99_ms", "ttft_p50_ms", "ttft_p99_ms",
                    "goodput_tok_s"):
            v = r.get(key)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                out[pre + key] = float(v)
        v = r.get("queue_wait_share")
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out[_engine_scoped(pre, engine, "queue_wait_share")] = float(v)
        v = r.get("router_share")
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            # fleet rungs: measured router overhead share of the window
            out[_engine_scoped(pre, engine, "router_share")] = float(v)
        # zero-filled like the run-dir side: pre-shed bench artifacts
        # (no shed_rate field) still join, with 0 -> N judged
        for key in ("shed_rate", "error_rate"):
            v = r.get(key)
            out[pre + key] = (
                float(v)
                if isinstance(v, (int, float)) and not isinstance(v, bool)
                else 0.0
            )
        # draft acceptance, zero-filled on continuous rungs like the
        # run-dir side (0 -> N = IMPROVED, never only_b); per-slot
        # state bytes (memory_analysis stamp, the bf16 proof surface)
        # ride conditionally — zero-filling them would mint a phantom
        # "bytes went to 0" IMPROVED verdict against pre-stamp artifacts
        if engine == "continuous":
            v = r.get("accept_rate")
            out[pre + "accept_rate"] = (
                float(v)
                if isinstance(v, (int, float)) and not isinstance(v, bool)
                else 0.0
            )
        v = r.get("slot_bytes")
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out[pre + "slot_bytes"] = float(v)
    if isinstance(line.get("knee_rps"), (int, float)):
        out["serve_knee_rps"] = float(line["knee_rps"])
    # headline per-slot state bytes (bf16 slot-state A/B): lower is
    # better via the "_bytes" suffix rule
    v = line.get("slot_bytes")
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        out["slot_bytes"] = float(v)
    for leg, payload in (line.get("legs") or {}).items():
        if isinstance(payload, dict) and isinstance(
            payload.get("value"), (int, float)
        ):
            out[leg] = float(payload["value"])
            # peak_hbm_bytes deliberately NOT copied — see the
            # ladder-inheritance note above
            for key in ("mfu", "compile_s", "trace_s", "static_mem_bytes"):
                v = payload.get(key)
                # bool is an int subclass — exclude it explicitly
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    out[f"{leg}.{key}"] = float(v)
            hit = payload.get("compile_cache_hit")
            if isinstance(hit, bool):
                out[f"{leg}.cache_hits"] = 1.0 if hit else 0.0
    return out


# ------------------------------------------------------------ lint sides


def _lint_side(raw: str) -> Optional[Dict[str, float]]:
    """Comparable scalars of a ``paddle lint --json`` artifact, or None
    when the text carries no lint records (so bench/run detection can
    proceed). Counts are NEW (non-baselined) findings; per-rule keys
    are zero-filled from the summary's rule list so both sides share
    every rule key and 0 -> N drift gets a verdict (new-findings
    regression => exit 1) instead of landing in only_b."""
    recs = list(obs.parse_record_lines(raw))
    summaries = [r for r in recs if r.get("kind") == "lint_summary"]
    if summaries:
        s = summaries[-1]  # re-run appended to the same file: last wins
        counts = s.get("counts") or {}
        out = {"lint_findings": float(s.get("findings") or 0)}
        for rid in (s.get("rules") or sorted(counts)):
            out[f"lint.{rid}"] = float(counts.get(rid, 0))
        return out
    findings = [r for r in recs if r.get("kind") == "lint_finding"]
    if findings:
        # summary-less stream (filtered/truncated): count what's there
        out = {"lint_findings": 0.0}
        for r in findings:
            if r.get("baselined"):
                continue
            out["lint_findings"] += 1.0
            key = f"lint.{r.get('rule', '?')}"
            out[key] = out.get(key, 0.0) + 1.0
        return out
    return None


def _race_side(raw: str) -> Optional[Dict[str, float]]:
    """Comparable scalars of a ``paddle race --json`` artifact (None
    when the text carries no race records): total + per-detector NEW
    finding counts, zero-filled from the summary's detector list so
    both sides share every key and 0 -> N drift gets a REGRESSION
    verdict instead of landing in only_b — the exact shape of the lint
    diff above, for the dynamic analyzer."""
    recs = list(obs.parse_record_lines(raw))
    summaries = [r for r in recs if r.get("kind") == "race_summary"]
    if summaries:
        s = summaries[-1]  # re-run appended to the same file: last wins
        counts = s.get("counts") or {}
        out = {"race_findings": float(s.get("findings") or 0)}
        for det in (s.get("detectors") or sorted(counts)):
            out[f"race.{det}"] = float(counts.get(det, 0))
        return out
    findings = [r for r in recs if r.get("kind") == "race_finding"]
    if findings:
        out = {"race_findings": 0.0}
        for r in findings:
            if r.get("baselined"):
                continue
            out["race_findings"] += 1.0
            key = f"race.{r.get('detector', '?')}"
            out[key] = out.get(key, 0.0) + 1.0
        return out
    return None


def _probe_lint(path: str) -> bool:
    """O(1) probe for a lint/race artifact — a multi-hundred-MB run
    stream must NOT be read (let alone JSON-parsed) just to learn it is
    not one (read_records streams it later). `paddle lint --json` and
    `paddle race --json` write their record kinds in the very first
    line, so the first 64 KB decide."""
    try:
        with open(path) as f:
            head = f.read(65536)
    except OSError:
        return False
    return any(marker in head for marker in (
        '"lint_summary"', '"lint_finding"',
        '"race_summary"', '"race_finding"',
    ))


def load_side(path: str) -> Dict[str, float]:
    if os.path.isfile(path):
        if path.endswith(".jsonl") and not _probe_lint(path):
            pass  # run stream: fall through to the streaming analyzer
        else:
            # ONE read serves all file-artifact detectors (lint, race,
            # bench)
            with open(path) as f:
                raw = f.read()
            lint = _lint_side(raw)
            if lint is not None:
                return lint
            race = _race_side(raw)
            if race is not None:
                return race
            if not path.endswith(".jsonl"):
                return _bench_side(path, raw)
    if not obs.metrics_files(path):
        raise ValueError(
            f"{path!r} is neither a bench artifact nor a run dir with "
            "metrics*.jsonl"
        )
    return _run_side(path)


# --------------------------------------------------------------- compare


def compare(a: Dict[str, float], b: Dict[str, float],
            threshold: float = 0.05,
            abs_floor: float = 0.05) -> Dict[str, Any]:
    rows = []
    regressions, improvements = [], []
    for name in sorted(set(a) & set(b)):
        va, vb = a[name], b[name]
        delta = (vb - va) / abs(va) if va else (0.0 if vb == va else float("inf"))
        hb = _higher_is_better(name)
        # a zero baseline makes every nonzero delta infinite — the
        # relative threshold can never absorb it, so sub-`abs_floor`
        # absolute movement (metric units) stays noise instead of an
        # automatic verdict (0 -> 0.002 s of ckpt block is not a
        # regression; 0 -> 4 cache hits still registers)
        if abs(delta) <= threshold or (va == 0 and abs(vb) <= abs_floor):
            verdict = "SAME"
        elif (delta > 0) == hb:
            verdict = "IMPROVED"
            improvements.append((name, delta))
        else:
            verdict = "REGRESSION"
            regressions.append((name, delta))
        rows.append({
            "metric": name, "a": va, "b": vb,
            "delta": None if delta == float("inf") else round(delta, 4),
            "higher_is_better": hb, "verdict": verdict,
        })
    if regressions:
        verdict = "REGRESSION"
    elif improvements:
        verdict = "IMPROVED"
    else:
        verdict = "NO CHANGE"
    return {
        "threshold": threshold,
        "metrics": rows,
        "only_a": sorted(set(a) - set(b)),
        "only_b": sorted(set(b) - set(a)),
        "regressions": [n for n, _ in regressions],
        "improvements": [n for n, _ in improvements],
        "verdict": verdict,
    }


def format_comparison(doc: Dict[str, Any], label_a: str, label_b: str) -> str:
    lines = [
        f"# compare: A={label_a}  B={label_b}  "
        f"(noise threshold {doc['threshold'] * 100:.1f}%)",
        f"{'metric':<36} {'A':>12} {'B':>12} {'delta':>8} {'verdict':>11}",
    ]
    for row in doc["metrics"]:
        d = row["delta"]
        lines.append(
            f"{row['metric']:<36} {row['a']:>12.4g} {row['b']:>12.4g} "
            f"{'inf' if d is None else format(d * 100, '+.1f') + '%':>8} "
            f"{row['verdict']:>11}"
        )
    for side, names in (("A", doc["only_a"]), ("B", doc["only_b"])):
        if names:
            lines.append(f"only in {side}: {', '.join(names)}")
    detail = ""
    if doc["regressions"]:
        detail = f" ({', '.join(doc['regressions'])})"
    elif doc["improvements"]:
        detail = f" ({', '.join(doc['improvements'])})"
    lines.append(f"verdict: {doc['verdict']}{detail}")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="paddle compare",
        description="diff two run dirs or bench artifacts with a "
                    "noise-thresholded regression verdict",
    )
    p.add_argument("run_a", help="baseline: run dir, metrics*.jsonl, or "
                                 "BENCH_*.json")
    p.add_argument("run_b", help="candidate: same shapes as run_a")
    p.add_argument("--threshold", type=float, default=0.05,
                   help="relative noise threshold (default 0.05 = 5%%)")
    p.add_argument("--abs-floor", type=float, default=0.05, dest="abs_floor",
                   help="absolute noise floor (metric units) for "
                        "zero-baseline metrics, where every nonzero "
                        "delta is infinite (default 0.05)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit the comparison as JSON")
    args = p.parse_args(argv)

    try:
        a, b = load_side(args.run_a), load_side(args.run_b)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if not set(a) & set(b):
        print("error: the two sides share no comparable metrics "
              f"(A has {sorted(a)}, B has {sorted(b)})", file=sys.stderr)
        return 2
    doc = compare(a, b, threshold=args.threshold, abs_floor=args.abs_floor)
    if args.as_json:
        print(json.dumps(doc, indent=2))
    else:
        print(format_comparison(doc, args.run_a, args.run_b))
    return 1 if doc["verdict"] == "REGRESSION" else 0


if __name__ == "__main__":
    sys.exit(main())
