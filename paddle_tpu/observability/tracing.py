"""``paddle trace`` — cross-process request-timeline reconstruction.

The serving fleet scatters one request's story across N+1 JSONL
telemetry streams: the router's (enqueue → route → reoffer → answer
spans) and each replica's (journal append, engine queue wait, prefill
cohort, decode iteration windows, readback, interference instants).
This module merges those streams — jax-free, read-only, torn tails
tolerated — into per-request timelines joined on the propagated
``trace_id``, and renders the tail-latency attribution table: for the
p99 cohort of each rung, the share of end-to-end latency spent in
router wait / replica queue / prefill / decode / readback /
failover-reoffer (doc/observability.md "Distributed tracing").

Clock alignment: every stream's ``t`` offsets are process-local
monotonic seconds; its ``run_start`` record carries the one wall-clock
anchor (``wall_time``) that maps them to civil time. Wall clocks skew
across processes, so after the anchor join each replica stream gets a
single residual shift ``d`` chosen from hop causality — a replica
cannot journal a request before the router routed it, nor finish it
after the router heard the answer. The feasible interval for ``d`` is
intersected over every hop; the shift nearest zero inside it is
applied and reported as the stream's skew bound (an empty interval is
reported as a violation, never hidden).

Coverage honesty: spans are measured, not invented — the only
synthesized segment is the stdin-pipe wait between the router's send
and the replica's first sight of the request (a real queue: a cold or
busy child buffers routed requests in its pipe), bucketed as replica
queue time. Requests whose spans still fail to cover end-to-end
within ``--tolerance`` (default 5%) are flagged with their gap and
overlap, not silently averaged away.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

from paddle_tpu.observability import metrics as obs

#: attribution buckets, highest precedence first — when spans overlap
#: (a decode window brackets its readback; a reoffer brackets the lost
#: route), each elementary segment counts ONCE, toward the most
#: specific cause
BUCKETS = ("reoffer", "hedge", "readback", "prefill", "decode",
           "queue_wait", "router_wait")

_PRIORITY = {b: i for i, b in enumerate(BUCKETS)}

#: span name → attribution bucket; instants (dur_s=0) ride along in
#: timelines but contribute no covered time
SPAN_BUCKET = {
    "router.wait": "router_wait",
    "router.reoffer": "reoffer",
    "net.hedge": "hedge",               # [route → hedge fired]: the
    # straggler tail a hedge cut; net.rpc/net.connect stay unbucketed
    # (they overlap the replica's own spans — timeline-only)
    "replica.pipe": "queue_wait",       # synthesized (module docstring)
    "replica.journal": "queue_wait",
    "engine.queue_wait": "queue_wait",
    "engine.prefill": "prefill",
    "engine.decode_window": "decode",
    "engine.readback": "readback",
}


# ---------------------------------------------------------- loading

def _read_jsonl(path: str) -> List[Dict[str, Any]]:
    """Every parseable record of one JSONL file, in file order. A torn
    tail (crash mid-append) or stray noise line is skipped, never
    fatal — the analyzer reads streams the writer may not have closed."""
    out: List[Dict[str, Any]] = []
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    out.append(rec)
    except OSError:
        pass
    return out


def load_stream(stream_dir: str) -> Dict[str, Any]:
    """One telemetry stream dir → its trace-relevant records with
    ABSOLUTE (wall-anchored) times. A restarted process appends a new
    ``run_start`` to the same file with a fresh ``t`` base, so
    anchoring is segment-wise: each ``run_start``'s ``wall_time``
    re-anchors everything after it. Records before any anchor are
    unplaceable and dropped (counted)."""
    spans: List[Dict[str, Any]] = []
    requests: List[Dict[str, Any]] = []
    windows: List[Dict[str, Any]] = []
    anchored = False
    dropped = 0
    segments = 0
    router_end = None
    for path in obs.metrics_files(stream_dir):
        anchor: Optional[float] = None
        for rec in _read_jsonl(path):
            kind = rec.get("kind")
            if kind == "run_start":
                wall = rec.get("wall_time")
                if isinstance(wall, (int, float)):
                    anchor = float(wall) - float(rec.get("t") or 0.0)
                    anchored = True
                    segments += 1
                continue
            if anchor is None:
                dropped += 1
                continue
            if kind == "span":
                t0 = rec.get("t0")
                dur = rec.get("dur_s")
                if not isinstance(t0, (int, float)):
                    continue
                spans.append({
                    "name": str(rec.get("name") or ""),
                    "t0": anchor + float(t0),
                    "dur_s": max(float(dur or 0.0), 0.0),
                    "trace": rec.get("trace"),
                    "traces": rec.get("traces"),
                    "rid": rec.get("rid"),
                    "replica": rec.get("replica"),
                    "attempt": rec.get("attempt"),
                })
            elif kind == "request":
                requests.append(rec)
            elif kind == "serve_window":
                windows.append(rec)
            elif kind == "run_end":
                router_end = rec
    return {
        "dir": stream_dir,
        "name": os.path.basename(os.path.normpath(stream_dir)) or stream_dir,
        "spans": spans,
        "requests": requests,
        "windows": windows,
        "anchored": anchored,
        "segments": segments,
        "dropped": dropped,
        "run_end": router_end,
    }


def _expand_dirs(run_dirs: List[str]) -> List[str]:
    """The given dirs plus every discovered fleet replica stream dir,
    deduplicated, order-preserved."""
    seen: Dict[str, None] = {}
    for d in run_dirs:
        for sub in obs.fleet_stream_dirs(d):
            seen.setdefault(os.path.normpath(sub))
    return list(seen)


# -------------------------------------------------------- alignment

def _is_replica_stream(stream: Dict[str, Any]) -> bool:
    return stream["name"].startswith("replica-")


def _trace_events(stream: Dict[str, Any]) -> Dict[str, List[Dict]]:
    """trace id → that stream's spans mentioning it (cohort spans fan
    out to every trace they carry), time-sorted."""
    by: Dict[str, List[Dict]] = {}
    for sp in stream["spans"]:
        traces = []
        if sp.get("trace"):
            traces.append(str(sp["trace"]))
        for t in sp.get("traces") or ():
            traces.append(str(t))
        for t in traces:
            by.setdefault(t, []).append(sp)
    for evs in by.values():
        evs.sort(key=lambda s: s["t0"])
    return by


def align_streams(streams: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Per-replica residual shift from hop causality (module
    docstring). The router stream is the reference (shift 0). Each
    replica stream's shift report: ``{"stream", "shift_s",
    "bound_s", "feasible"}``. Shifts are APPLIED to the stream's span
    times in place."""
    router_spans = [sp for st in streams if not _is_replica_stream(st)
                    for sp in st["spans"]]
    route_end: Dict[Tuple[str, str], float] = {}   # (replica, trace)
    answer_at: Dict[Tuple[str, str], float] = {}
    for sp in router_spans:
        t = str(sp.get("trace") or "")
        rep = str(sp.get("replica") or "")
        if not t or not rep:
            continue
        key = (rep, t)
        if sp["name"] == "router.wait":
            end = sp["t0"] + sp["dur_s"]
            route_end[key] = min(route_end.get(key, end), end)
        elif sp["name"] == "router.answer":
            answer_at[key] = sp["t0"]
    reports = []
    for st in streams:
        if not _is_replica_stream(st) or not st["spans"]:
            continue
        lo, hi = float("-inf"), float("inf")
        by_trace = _trace_events(st)
        for trace, evs in by_trace.items():
            key = (st["name"], trace)
            if key in route_end:
                # the replica cannot see the request before the route
                lo = max(lo, route_end[key] - evs[0]["t0"])
            if key in answer_at:
                # ...nor still be working it after the router heard
                # the answer from THIS replica
                last_end = max(e["t0"] + e["dur_s"] for e in evs)
                hi = min(hi, answer_at[key] - last_end)
        feasible = lo <= hi
        if lo == float("-inf") and hi == float("inf"):
            shift = 0.0
        elif not feasible:
            shift = (lo + hi) / 2.0
        elif lo <= 0.0 <= hi:
            shift = 0.0
        else:
            shift = lo if lo > 0.0 else hi
        for sp in st["spans"]:
            sp["t0"] += shift
        reports.append({
            "stream": st["name"],
            "shift_s": round(shift, 6),
            "bound_s": round(abs(shift), 6),
            "feasible": feasible,
        })
    return reports


# ---------------------------------------------------- reconstruction

def _sweep(intervals: List[Tuple[float, float, str]], start: float,
           end: float) -> Tuple[Dict[str, float], float]:
    """Elementary-segment sweep over ``[start, end]``: each instant of
    the request's life counts toward exactly one bucket (precedence on
    overlap), uncovered instants toward ``uncovered``. Returns
    (bucket seconds, covered union seconds)."""
    clipped = [(max(a, start), min(b, end), bk)
               for a, b, bk in intervals if min(b, end) > max(a, start)]
    pts = sorted({start, end, *(a for a, _b, _k in clipped),
                  *(b for _a, b, _k in clipped)})
    buckets: Dict[str, float] = {}
    union = 0.0
    for a, b in zip(pts, pts[1:]):
        if b <= a:
            continue
        mid = (a + b) / 2.0
        best: Optional[str] = None
        for s, e, bk in clipped:
            if s <= mid < e and (best is None
                                 or _PRIORITY[bk] < _PRIORITY[best]):
                best = bk
        if best is None:
            buckets["uncovered"] = buckets.get("uncovered", 0.0) + (b - a)
        else:
            union += b - a
            buckets[best] = buckets.get(best, 0.0) + (b - a)
    return buckets, union


def analyze_trace(run_dirs: List[str],
                  tolerance: float = 0.05) -> Dict[str, Any]:
    """The full reconstruction document for one fleet (or single-
    stream) run: per-request timelines, coverage verdicts, per-stream
    skew reports, and the per-rung p99 attribution table."""
    dirs = _expand_dirs(list(run_dirs))
    streams = [load_stream(d) for d in dirs]
    streams = [st for st in streams if st["anchored"] or st["spans"]]
    skew = align_streams(streams)
    # rung lookup: request records carry the trace join key, windows
    # carry the offered rate per rung
    rung_of: Dict[str, int] = {}
    for st in streams:
        for rec in st["requests"]:
            tid = rec.get("trace_id")
            if tid:
                rung_of[str(tid)] = int(rec.get("rung") or 0)
    rate_of_rung: Dict[int, float] = {}
    for st in streams:
        for w in st["windows"]:
            r = int(w.get("rung") or 0)
            rate_of_rung.setdefault(r, float(w.get("offered_rps") or 0.0))

    # pool every span per trace across the aligned streams
    pooled: Dict[str, List[Tuple[Dict, str]]] = {}
    for st in streams:
        for trace, evs in _trace_events(st).items():
            pooled.setdefault(trace, []).extend(
                (sp, st["name"]) for sp in evs)
    timelines: Dict[str, Dict[str, Any]] = {}
    for trace, evs in sorted(pooled.items()):
        evs.sort(key=lambda p: p[0]["t0"])
        enq = next((sp for sp, _s in evs
                    if sp["name"] == "router.enqueue"), None)
        ans = next((sp for sp, _s in evs
                    if sp["name"] == "router.answer"), None)
        spans = [{
            "name": sp["name"], "stream": stream_name,
            "t0": round(sp["t0"], 6), "dur_s": round(sp["dur_s"], 6),
            **({"attempt": sp["attempt"]}
               if sp.get("attempt") is not None else {}),
            **({"replica": sp["replica"]} if sp.get("replica") else {}),
        } for sp, stream_name in evs]
        tl: Dict[str, Any] = {
            "trace": trace,
            "rid": str((enq or {}).get("rid") or trace),
            "rung": rung_of.get(trace, 0),
            "answered": ans is not None,
            "spans": spans,
            "streams": sorted({s for _sp, s in evs}),
            "reoffered": any(sp["name"] == "router.reoffer"
                             for sp, _s in evs),
        }
        if enq is not None and ans is not None:
            start, end = enq["t0"], ans["t0"]
            e2e = max(end - start, 1e-9)
            intervals: List[Tuple[float, float, str]] = []
            raw_covered = 0.0
            for sp, _s in evs:
                bucket = SPAN_BUCKET.get(sp["name"])
                if bucket is None or sp["dur_s"] <= 0.0:
                    continue
                a = max(sp["t0"], start)
                b = min(sp["t0"] + sp["dur_s"], end)
                if b > a:
                    intervals.append((a, b, bucket))
                    raw_covered += b - a
            # synthesized stdin-pipe wait: route send → the replica's
            # first sight of the request (module docstring)
            first_by_stream: Dict[str, float] = {}
            for sp, sname in evs:
                if sname.startswith("replica-"):
                    first_by_stream.setdefault(sname, sp["t0"])
            for sp, _s in evs:
                if sp["name"] == "router.wait" and sp.get("replica"):
                    rep = str(sp["replica"])
                    send = sp["t0"] + sp["dur_s"]
                    first = first_by_stream.get(rep)
                    if first is not None and first > send:
                        a, b = max(send, start), min(first, end)
                        if b > a:
                            intervals.append((a, b, "queue_wait"))
                            raw_covered += b - a
            buckets, union = _sweep(intervals, start, end)
            gap = max(e2e - union, 0.0)
            tl.update({
                "t_enqueue": round(start, 6),
                "t_answer": round(end, 6),
                "e2e_s": round(e2e, 6),
                "coverage": round(union / e2e, 4),
                "gap_s": round(gap, 6),
                "overlap_s": round(max(raw_covered - union, 0.0), 6),
                "covered_ok": gap <= tolerance * e2e,
                "buckets": {k: round(v, 6)
                            for k, v in sorted(buckets.items())},
            })
        timelines[trace] = tl

    # per-rung p99 cohort attribution
    by_rung: Dict[int, List[Dict[str, Any]]] = {}
    for tl in timelines.values():
        if "e2e_s" in tl:
            by_rung.setdefault(tl["rung"], []).append(tl)
    rungs = []
    for rung in sorted(by_rung):
        rows = sorted(by_rung[rung], key=lambda t: t["e2e_s"])
        # p99 cohort = every request at or past the 99th-percentile
        # e2e (at small n that is the worst request)
        idx = max(0, -(-99 * len(rows) // 100) - 1)
        cohort = rows[idx:]
        e2e_sum = sum(t["e2e_s"] for t in cohort) or 1e-9
        shares = {}
        for bucket in (*BUCKETS, "uncovered"):
            sec = sum(t["buckets"].get(bucket, 0.0) for t in cohort)
            shares[bucket] = round(sec / e2e_sum, 4)
        rungs.append({
            "rung": rung,
            "offered_rps": rate_of_rung.get(rung, 0.0),
            "requests": len(rows),
            "p99_cohort": [t["trace"] for t in cohort],
            "p99_e2e_s": round(cohort[-1]["e2e_s"], 6),
            "shares": shares,
        })

    answered = [t for t in timelines.values() if t["answered"]]
    flagged = [t for t in timelines.values()
               if "covered_ok" in t and not t["covered_ok"]]
    return {
        "streams": [{
            "name": st["name"], "dir": st["dir"],
            "spans": len(st["spans"]), "segments": st["segments"],
            "dropped_unanchored": st["dropped"],
        } for st in streams],
        "skew": skew,
        "tolerance": tolerance,
        "requests": timelines,
        "n_requests": len(timelines),
        "n_answered": len(answered),
        "n_reconstructed": sum(1 for t in answered if "e2e_s" in t),
        "n_flagged": len(flagged),
        "flagged": [t["trace"] for t in flagged],
        "rungs": rungs,
    }


def p99_shares_by_rate(run_dir: str) -> Dict[float, Dict[str, float]]:
    """``paddle compare``'s join surface: offered rate → p99-cohort
    attribution shares, empty for pre-tracing artifacts (no span
    records anywhere under ``run_dir``)."""
    try:
        doc = analyze_trace([run_dir])
    except Exception:  # noqa: BLE001 — comparison survives odd artifacts
        return {}
    return {float(r["offered_rps"]): dict(r["shares"])
            for r in doc["rungs"]}


# --------------------------------------------------------- rendering

def _render(doc: Dict[str, Any]) -> str:
    lines = [
        f"== paddle trace: {len(doc['streams'])} stream(s), "
        f"{doc['n_requests']} request(s), {doc['n_answered']} answered, "
        f"{doc['n_reconstructed']} reconstructed =="
    ]
    for sk in doc["skew"]:
        note = "" if sk["feasible"] else "  CAUSALITY VIOLATION"
        lines.append(f"  skew {sk['stream']}: shift {sk['shift_s']:+.4f}s "
                     f"(bound {sk['bound_s']:.4f}s){note}")
    if doc["n_flagged"]:
        lines.append(f"  coverage below {1 - doc['tolerance']:.0%} on "
                     f"{doc['n_flagged']} request(s):")
        for trace in doc["flagged"]:
            t = doc["requests"][trace]
            lines.append(f"    {trace}: coverage {t['coverage']:.1%} "
                         f"gap {t['gap_s']:.4f}s "
                         f"overlap {t['overlap_s']:.4f}s")
    if doc["rungs"]:
        cols = (*BUCKETS, "uncovered")
        lines.append("")
        lines.append("p99 tail-latency attribution "
                     "(share of cohort e2e):")
        head = (f"{'rung':>4} {'rps':>7} {'n':>4} {'p99_e2e_s':>10}  "
                + "  ".join(f"{c:>11}" for c in cols))
        lines.append(head)
        for r in doc["rungs"]:
            row = (f"{r['rung']:>4} {r['offered_rps']:>7.2f} "
                   f"{r['requests']:>4} {r['p99_e2e_s']:>10.4f}  "
                   + "  ".join(f"{r['shares'].get(c, 0.0):>11.1%}"
                               for c in cols))
            lines.append(row)
    for trace, t in sorted(doc["requests"].items()):
        if "e2e_s" not in t:
            continue
        lines.append("")
        mark = "" if t["covered_ok"] else "  [COVERAGE FLAG]"
        lines.append(f"{trace} (rung {t['rung']}, e2e {t['e2e_s']:.4f}s, "
                     f"coverage {t['coverage']:.1%}"
                     f"{', reoffered' if t['reoffered'] else ''}){mark}")
        base = t["t_enqueue"]
        for sp in t["spans"]:
            lines.append(f"  {sp['t0'] - base:>9.4f}s "
                         f"+{sp['dur_s']:.4f}s  {sp['name']:<22} "
                         f"{sp['stream']}"
                         + (f" attempt={sp['attempt']}"
                            if "attempt" in sp else ""))
    return "\n".join(lines)


# ---------------------------------------------------------- selftest

def _selftest() -> int:
    """Golden two-stream fixture (router + one replica with a
    deliberate +0.25s wall-clock skew and a torn tail): the analyzer
    must align within the reported bound, reconstruct the request, and
    attribute the decode-dominated tail — jax-free and fast, run by
    bin/check_analysis.sh on every gate."""
    import tempfile

    root = tempfile.mkdtemp(prefix="paddle_trace_selftest_")
    router_d = os.path.join(root, "router")
    replica_d = os.path.join(root, "replica-0")
    os.makedirs(router_d)
    os.makedirs(replica_d)

    def w(d: str, recs: List[Dict[str, Any]], torn: bool = False) -> None:
        with open(os.path.join(d, "metrics.jsonl"), "w",
                  encoding="utf-8") as f:
            for rec in recs:
                f.write(json.dumps(rec) + "\n")
            if torn:
                f.write('{"v": 1, "kind": "span", "name": "eng')

    def span(t: float, name: str, t0: float, dur: float,
             **fields: Any) -> Dict[str, Any]:
        return {"v": 1, "kind": "span", "host": "h", "t": t,
                "name": name, "t0": t0, "dur_s": dur, **fields}

    w(router_d, [
        {"v": 1, "kind": "run_start", "host": "h", "t": 0.0,
         "wall_time": 1000.0},
        span(0.1, "router.enqueue", 0.10, 0.0, trace="r1", rid="r1"),
        span(0.15, "router.wait", 0.10, 0.05, trace="r1", rid="r1",
             replica="replica-0", attempt=1),
        span(1.15, "router.answer", 1.15, 0.0, trace="r1",
             replica="replica-0"),
        {"v": 1, "kind": "run_end", "host": "h", "t": 1.2,
         "status": "completed"},
    ])
    # replica wall clock runs 0.25s BEHIND the router's; its process
    # started at router-time 0.15
    w(replica_d, [
        {"v": 1, "kind": "run_start", "host": "h", "t": 0.0,
         "wall_time": 999.90},
        span(0.02, "replica.journal", 0.01, 0.01, trace="r1"),
        span(0.02, "replica.accept", 0.02, 0.0, trace="r1"),
        span(0.15, "engine.queue_wait", 0.02, 0.13, trace="r1",
             rid="r1"),
        span(0.25, "engine.prefill", 0.15, 0.10, trace="r1", rid="r1"),
        span(0.95, "engine.decode_window", 0.25, 0.70, traces=["r1"]),
        span(0.99, "engine.readback", 0.95, 0.04, traces=["r1"]),
    ], torn=True)

    doc = analyze_trace([router_d, replica_d])
    tl = doc["requests"].get("r1")
    problems = []
    if doc["n_reconstructed"] != 1 or tl is None or "e2e_s" not in tl:
        problems.append("request r1 not reconstructed")
    else:
        if not tl["covered_ok"]:
            problems.append(f"coverage {tl['coverage']} below tolerance")
        sk = next((s for s in doc["skew"]
                   if s["stream"] == "replica-0"), None)
        if sk is None or not sk["feasible"]:
            problems.append("replica-0 skew not aligned")
        elif not (0.1 <= sk["shift_s"] <= 0.3):
            problems.append(f"skew shift {sk['shift_s']} outside the "
                            "planted 0.25s neighbourhood")
        shares = doc["rungs"][0]["shares"] if doc["rungs"] else {}
        if not shares.get("decode", 0.0) > 0.5:
            problems.append(f"decode share {shares.get('decode')} — "
                            "expected the dominant bucket")
        if shares.get("uncovered", 1.0) > 0.05:
            problems.append(f"uncovered share {shares.get('uncovered')}")
    if problems:
        print("paddle trace --selftest FAILED:", file=sys.stderr)
        for p in problems:
            print(f"  - {p}", file=sys.stderr)
        return 1
    print("paddle trace selftest: ok — 2 streams aligned "
          f"(skew bound {doc['skew'][0]['bound_s']:.3f}s), 1 request "
          f"reconstructed, coverage {tl['coverage']:.1%}")
    return 0


# -------------------------------------------------------------- CLI

def main(rest: List[str]) -> int:
    ap = argparse.ArgumentParser(
        prog="paddle trace",
        description="Reconstruct per-request cross-process timelines "
                    "from fleet telemetry streams (jax-free).")
    ap.add_argument("run_dir", nargs="*",
                    help="run or fleet dir(s); replica-*/ and "
                         "fleet_status/replica-*/ streams are "
                         "discovered automatically")
    ap.add_argument("--json", action="store_true",
                    help="emit the full reconstruction document")
    ap.add_argument("--tolerance", type=float, default=0.05,
                    help="e2e coverage slack before a request is "
                         "flagged (default 0.05)")
    ap.add_argument("--selftest", action="store_true",
                    help="golden two-stream fixture, no run dir needed")
    args = ap.parse_args(rest)
    if args.selftest:
        return _selftest()
    if not args.run_dir:
        ap.error("a run dir is required (or --selftest)")
    doc = analyze_trace(args.run_dir, tolerance=args.tolerance)
    if not doc["streams"]:
        print(f"error: no telemetry streams under {args.run_dir}",
              file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(doc, indent=2, sort_keys=True))
    else:
        print(_render(doc))
    return 0
