"""HBM accounting + OOM pre-mortem forensics (`paddle memory`).

The signature workloads — high-dimensional sparse embeddings, variable
length sequence batches — live and die by device memory, yet until this
module the telemetry stack could see time (spans), compute cost
(compile/roofline records), and requests, but not a single byte of HBM:
an OOM was a raw ``XlaRuntimeError`` with no forensics, and "will this
batch size fit" was answered by trying it. Three planes close the gap:

- **static** — every launch-group compilation's
  ``compiled.memory_analysis()`` (argument/output/temp/generated-code
  bytes) is joined onto its ``kind=compile`` record by the
  CompileRegistry (:func:`memory_analysis_of`), so the per-group
  footprint XLA *planned* is on disk before the first step runs;
- **live** — :func:`sample_and_emit` reads ``device.memory_stats()``
  (in-use / cumulative-peak / limit, summed over local devices) plus
  the host RSS at pass boundaries into ``kind=memory`` records and the
  ``mem.hbm_peak_bytes`` / ``mem.hbm_in_use_bytes`` /
  ``mem.host_rss_bytes`` gauges. Backends without allocator stats (the
  CPU backend returns None) degrade to host-RSS-only records with a
  one-time log line — never a crash, never a schema-invalid record;
- **post-mortem** — :func:`trigger_oom_report` writes
  ``oom_report.json`` (static footprint ranked per group, the last
  live snapshot, the telemetry tail + last barrier skew) when a launch
  dies of RESOURCE_EXHAUSTED, mirroring the hang_report flow including
  its write-failure backstop: the report write itself may need memory
  or a wedged fs, so a backstop timer guarantees ``EXIT_OOM`` (20)
  regardless. Supervisors treat 20 as budget-consuming — an OOM loop
  is deterministic poison, not scheduling, and must not restart for
  free.

``paddle memory <run_dir>`` reads it all back jax-free (like `paddle
metrics`): the per-launch-group static table, live peak/headroom vs the
measured allocator limit (or the chip capacity table in
``ops/kernel_flops.py`` when the allocator reported none), and a
rendering of any ``oom_report.json`` found in the run dir.

Usage::

    paddle memory <run_dir | metrics.jsonl> [--json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Callable, Dict, List, Optional

from paddle_tpu.observability import metrics as obs
from paddle_tpu.resilience import EXIT_OOM
from paddle_tpu.utils import concurrency as cc
from paddle_tpu.utils.logging import logger

OOM_REPORT = "oom_report.json"

# same hard deadline as hangwatch.FORENSICS_DEADLINE_S, same reason: an
# OOM'd process may fail ITS OWN forensics (the report write can need
# memory; the run dir can live on the fs that is part of the problem),
# so a backstop timer guarantees the distinct exit code regardless
FORENSICS_DEADLINE_S = 30.0

__all__ = [
    "OOM_REPORT", "EXIT_OOM", "SyntheticOomError", "is_oom_error",
    "memory_analysis_of", "device_memory_stats", "host_rss_bytes",
    "sample_memory", "sample_and_emit", "build_oom_report",
    "trigger_oom_report", "main",
]


# ------------------------------------------------------------ OOM typing


class SyntheticOomError(RuntimeError):
    """The `trainer.oom` fault site's deterministic stand-in for a real
    device OOM: the message carries the canonical RESOURCE_EXHAUSTED
    marker so :func:`is_oom_error` (and any operator tooling grepping
    logs) classifies it exactly like the XlaRuntimeError it simulates."""

    def __init__(self, info: str = ""):
        detail = f" ({info})" if info else ""
        super().__init__(
            "RESOURCE_EXHAUSTED: out of memory "
            f"[synthetic — injected at trainer.oom{detail}]"
        )


def is_oom_error(e: BaseException) -> bool:
    """True only for device-memory exhaustion. The match is message-based
    (XlaRuntimeError carries no typed subclass for it) and deliberately
    narrow: a shape bug must crash loudly, not masquerade as an OOM
    pre-mortem (same contract as bench.py's ladder gate)."""
    msg = f"{type(e).__name__}: {e}".lower()
    return any(
        s in msg
        for s in ("resource_exhausted", "resource exhausted",
                  "out of memory", "failed to allocate")
    )


# --------------------------------------------------------- static plane


def memory_analysis_of(compiled) -> Optional[Dict[str, int]]:
    """Static memory plan of one compiled executable as ``mem_*_bytes``
    fields, or None. Graceful by the cost_analysis_of covenant: backends
    without memory analysis, raising calls, and missing attributes all
    collapse to None/absent keys — accounting must never be able to
    break training."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    out: Dict[str, int] = {}
    for attr, key in (
        ("argument_size_in_bytes", "mem_arg_bytes"),
        ("output_size_in_bytes", "mem_out_bytes"),
        ("temp_size_in_bytes", "mem_temp_bytes"),
        ("alias_size_in_bytes", "mem_alias_bytes"),
        ("generated_code_size_in_bytes", "mem_code_bytes"),
    ):
        v = getattr(ma, attr, None)
        if isinstance(v, (int, float)) and v >= 0:
            out[key] = int(v)
    if not out:
        return None
    # aliased buffers (donated inputs reused as outputs) are counted on
    # both sides of the plan — subtract them once so the total is the
    # planner's actual footprint, clamped at 0 for odd backends
    out["mem_total_bytes"] = max(
        out.get("mem_arg_bytes", 0)
        + out.get("mem_out_bytes", 0)
        + out.get("mem_temp_bytes", 0)
        + out.get("mem_code_bytes", 0)
        - out.get("mem_alias_bytes", 0),
        0,
    )
    return out


# ----------------------------------------------------------- live plane

_warned_no_device_stats = False


def device_memory_stats() -> Optional[Dict[str, int]]:
    """Live allocator stats summed over the local devices:
    ``{bytes_in_use, peak_bytes_in_use, bytes_limit?, devices}``, or
    None when the backend reports none (the CPU backend's
    ``memory_stats()`` is None) or jax is absent entirely. The one-time
    degradation log keeps the silence diagnosable without spamming
    every pass boundary."""
    global _warned_no_device_stats
    try:
        import jax

        devices = jax.local_devices()
    except Exception:
        return None
    in_use = peak = limit = 0
    seen = 0
    for d in devices:
        try:
            s = d.memory_stats()
        except Exception:
            s = None
        if not s:
            continue
        seen += 1
        in_use += int(s.get("bytes_in_use", 0) or 0)
        peak += int(s.get("peak_bytes_in_use", 0) or 0)
        limit += int(s.get("bytes_limit", 0) or 0)
    if not seen:
        if not _warned_no_device_stats:
            _warned_no_device_stats = True
            logger.info(
                "device memory stats unavailable on this backend "
                "(memory_stats() is empty — CPU?) — kind=memory records "
                "carry host RSS only"
            )
        return None
    out = {"bytes_in_use": in_use, "peak_bytes_in_use": peak,
           "devices": seen}
    if limit:
        out["bytes_limit"] = limit
    return out


def host_rss_bytes() -> int:
    """Current resident set size of this process. /proc when available
    (live value); ru_maxrss (the PEAK, linux kB) as the portable
    fallback — a number is always returned, so the host half of a
    memory record can never be absent."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource

        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:
        return 0


def sample_memory() -> Dict[str, Any]:
    """One live snapshot: host RSS always; HBM fields only when the
    backend reports them (validate_record requires only the host
    field, so a CPU run's records stay schema-clean)."""
    snap: Dict[str, Any] = {"host_rss_bytes": host_rss_bytes()}
    dev = device_memory_stats()
    if dev is not None:
        snap["hbm_in_use_bytes"] = dev["bytes_in_use"]
        snap["hbm_peak_bytes"] = dev["peak_bytes_in_use"]
        if "bytes_limit" in dev:
            snap["hbm_limit_bytes"] = dev["bytes_limit"]
        snap["devices"] = dev["devices"]
    return snap


def sample_and_emit(pass_id: Optional[int] = None,
                    step: Optional[int] = None) -> Dict[str, Any]:
    """Sample + publish: the gauges ride the next ``pass_end`` counters
    snapshot, the ``kind=memory`` record is the per-boundary trajectory
    `paddle memory`/`compare` read. Called synchronously at pass
    boundaries (allocator stats are a host-side C call — no device
    sync, no daemon thread to race)."""
    snap = sample_memory()
    r = obs.registry()
    r.gauge("mem.host_rss_bytes").set(snap["host_rss_bytes"])
    if "hbm_peak_bytes" in snap:
        r.gauge("mem.hbm_peak_bytes").set(snap["hbm_peak_bytes"])
        r.gauge("mem.hbm_in_use_bytes").set(snap["hbm_in_use_bytes"])
    obs.emit("memory", pass_id=pass_id, step=step, **snap)
    return snap


# ---------------------------------------------------------- pre-mortem


def build_oom_report(
    report_dir: str,
    error: BaseException,
    groups: Optional[List[Dict[str, Any]]] = None,
    live: Optional[Dict[str, Any]] = None,
    where: Optional[Dict[str, Any]] = None,
    device_kind: str = "",
) -> Dict[str, Any]:
    """The pre-mortem document: which launch groups XLA planned to be
    big (ranked), what the allocator looked like at the last boundary,
    and the telemetry tail — everything "why did this rank die of OOM"
    needs, from the run dir alone."""
    groups = sorted(
        groups or [],
        key=lambda g: -int(g.get("mem_total_bytes", 0) or 0),
    )
    report: Dict[str, Any] = {
        "reason": "oom",
        "error": str(error)[:4000],
        "error_type": type(error).__name__,
        "where": where or {},
        "device_kind": device_kind,
        "groups": groups,
        "static_total_bytes": sum(
            int(g.get("mem_total_bytes", 0) or 0) for g in groups
        ),
        "live": live,
        "pid": os.getpid(),
        "written_at": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
    }
    # same post-mortem evidence shape as hang_report.json / the
    # supervisor's crash report — one shared helper, no drift
    try:
        tails, skew = obs.tail_with_last_skew(report_dir, n=25)
        report["metrics_tail"] = tails
        report["barrier_skew"] = skew
    except Exception as e:  # forensics best-effort, never masks the OOM
        report["metrics_tail_error"] = str(e)
    return report


def write_oom_report(report_dir: str, report: Dict[str, Any]) -> str:
    path = os.path.join(report_dir or ".", OOM_REPORT)
    try:
        os.makedirs(report_dir or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(report, f, indent=2, default=str)
        os.replace(tmp, path)  # readers never see a torn report
    except OSError as e:
        logger.error("oom pre-mortem: could not write %s: %s", path, e)
    return path


def trigger_oom_report(
    report_dir: str,
    error: BaseException,
    groups: Optional[List[Dict[str, Any]]] = None,
    live: Optional[Dict[str, Any]] = None,
    where: Optional[Dict[str, Any]] = None,
    device_kind: str = "",
    exit_fn: Optional[Callable[[int], None]] = None,
) -> str:
    """Write the pre-mortem with the hang-report discipline: announce,
    arm the backstop, write, flush the evidence record, disarm.

    Unlike hangwatch (a daemon thread whose only exit is ``os._exit``),
    the caller here is the step loop itself — on the normal path the
    report lands, the ``kind=oom`` record flushes, and the original
    error is re-raised by the caller (the CLI maps it to
    :data:`EXIT_OOM`). ``exit_fn`` (``os._exit`` in production) backs
    that path up: if the forensics themselves wedge — the report write
    blocking on a dead fs, the tail scan thrashing a memory-starved
    host — the timer still exits 20 within FORENSICS_DEADLINE_S, so the
    supervisor sees a *classified* death either way."""
    path = os.path.join(report_dir or ".", OOM_REPORT)
    logger.error(
        "device OOM (%s) — writing pre-mortem %s, then exiting %d: %s",
        type(error).__name__, path, EXIT_OOM, str(error)[:500],
    )
    backstop = None
    if exit_fn is not None:
        backstop = cc.Timer(FORENSICS_DEADLINE_S, exit_fn, args=(EXIT_OOM,))
        backstop.daemon = True
        backstop.start()
    report = build_oom_report(
        report_dir, error, groups=groups, live=live, where=where,
        device_kind=device_kind,
    )
    path = write_oom_report(report_dir, report)
    obs.registry().counter("ooms.detected").inc()
    obs.emit(
        "oom",
        pass_id=(where or {}).get("pass"),
        step=(where or {}).get("step"),
        error=str(error)[:500],
        report=path,
        static_total_bytes=report["static_total_bytes"],
    )
    obs.flush()  # the caller is about to die — same discipline as faults
    if backstop is not None:
        backstop.cancel()
    return path


# ------------------------------------------------------ jax-free reader


def collect(streams: Dict[int, List[Dict[str, Any]]]) -> Dict[str, Any]:
    """Memory view of merged metrics streams: the static per-group table
    (from ``kind=compile`` records carrying memory analysis, latest-wins
    per (host, group, sig) like the roofline dedupe) and the last live
    ``kind=memory`` snapshot per host."""
    latest_static: Dict[tuple, Dict[str, Any]] = {}
    live_by_host: Dict[int, Dict[str, Any]] = {}
    device_kind = ""
    for host in sorted(streams):
        for rec in streams[host]:
            kind = rec.get("kind")
            if kind == "compile" and "mem_total_bytes" in rec:
                latest_static[(host, rec.get("group"), rec.get("sig"))] = rec
            elif kind == "memory":
                live_by_host[int(rec.get("host", host))] = rec
            elif kind == "roofline" and rec.get("device_kind"):
                device_kind = rec["device_kind"]
    groups: Dict[tuple, Dict[str, Any]] = {}
    for (_h, group, sig), rec in latest_static.items():
        # one host's plan is authoritative (SPMD compiles identically);
        # keep the largest if hosts ever disagree
        key = (group, sig)
        if key not in groups or rec.get("mem_total_bytes", 0) > groups[key].get(
            "mem_total_bytes", 0
        ):
            groups[key] = {
                "group": group,
                "sig": sig,
                **{k: rec[k] for k in rec if k.startswith("mem_")},
            }
    rows = sorted(
        groups.values(), key=lambda r: -int(r.get("mem_total_bytes", 0))
    )
    return {
        "groups": rows,
        "static_total_bytes": sum(
            int(r.get("mem_total_bytes", 0)) for r in rows
        ),
        "live": {h: live_by_host[h] for h in sorted(live_by_host)},
        "device_kind": device_kind,
    }


def _capacity_bytes(doc: Dict[str, Any]) -> Optional[int]:
    """Device HBM capacity for headroom math: the measured allocator
    limit when any host reported one, else the chip capacity table
    (never guessed for unknown device kinds). Both sides of the
    peak-vs-capacity ratio are PER HOST: the records sum peak over
    local devices, so the table fallback must scale by the recorded
    device count or a 4-chip host would read >100% utilization."""
    limits = [
        int(rec["hbm_limit_bytes"])
        for rec in doc["live"].values()
        if isinstance(rec.get("hbm_limit_bytes"), int)
    ]
    if limits:
        return max(limits)
    from paddle_tpu.ops.kernel_flops import peak_hbm_gb

    cap = peak_hbm_gb(doc.get("device_kind", ""))
    if not cap:
        return None
    devices = max(
        (int(rec.get("devices", 1) or 1) for rec in doc["live"].values()),
        default=1,
    )
    return int(cap * 1e9) * devices


def read_oom_report(run_dir: str) -> Optional[Dict[str, Any]]:
    from paddle_tpu.resilience.hangwatch import run_dir_of

    path = os.path.join(run_dir_of(run_dir), OOM_REPORT)
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _mb(v) -> str:
    return f"{v / 1e6:.2f}" if isinstance(v, (int, float)) else "-"


def _gb(v) -> str:
    return f"{v / 1e9:.2f} GB" if isinstance(v, (int, float)) else "-"


def format_report(doc: Dict[str, Any],
                  oom: Optional[Dict[str, Any]] = None) -> str:
    lines: List[str] = []
    if doc["groups"]:
        lines.append(
            "static footprint per launch group (XLA memory analysis at "
            "compile time):"
        )
        lines.append(
            f"{'group':<12} {'sig':<10} {'args MB':>9} {'out MB':>9} "
            f"{'temp MB':>9} {'total MB':>9}"
        )
        for r in doc["groups"]:
            lines.append(
                f"{str(r.get('group', '?')):<12} {str(r.get('sig', '?')):<10} "
                f"{_mb(r.get('mem_arg_bytes')):>9} "
                f"{_mb(r.get('mem_out_bytes')):>9} "
                f"{_mb(r.get('mem_temp_bytes')):>9} "
                f"{_mb(r.get('mem_total_bytes')):>9}"
            )
        lines.append(
            f"static total: {_mb(doc['static_total_bytes'])} MB over "
            f"{len(doc['groups'])} group(s)"
        )
    else:
        lines.append(
            "no static memory analysis in this run's compile records "
            "(pre-memory-telemetry run, or the backend provides none)"
        )
    if doc["live"]:
        lines.append("")
        lines.append("live memory (last sample per host):")
        cap = _capacity_bytes(doc)
        for h, rec in doc["live"].items():
            peak = rec.get("hbm_peak_bytes")
            if isinstance(peak, int):
                line = (
                    f"host {h}: hbm peak {_gb(peak)}, in use "
                    f"{_gb(rec.get('hbm_in_use_bytes'))}"
                )
                if cap:
                    line += (
                        f", capacity {_gb(cap)} (peak {peak / cap * 100:.1f}%"
                        f", headroom {_gb(max(cap - peak, 0))})"
                    )
                line += f"; host RSS {_gb(rec.get('host_rss_bytes'))}"
            else:
                line = (
                    f"host {h}: host RSS {_gb(rec.get('host_rss_bytes'))} "
                    "(device stats unavailable on this backend)"
                )
            lines.append(line)
    if oom is not None:
        lines.append("")
        err = str(oom.get("error", "")).splitlines()
        top = (oom.get("groups") or [{}])[0]
        lines.append(
            f"! OOM pre-mortem ({OOM_REPORT}, written {oom.get('written_at', '?')}): "
            f"{err[0] if err else '?'}"
        )
        if top.get("group"):
            lines.append(
                f"  largest static group: {top['group']} "
                f"({_mb(top.get('mem_total_bytes'))} MB planned)"
            )
        live = oom.get("live") or {}
        if isinstance(live.get("hbm_peak_bytes"), int):
            lines.append(
                f"  last live snapshot: hbm peak {_gb(live['hbm_peak_bytes'])}, "
                f"in use {_gb(live.get('hbm_in_use_bytes'))}"
            )
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="paddle memory",
        description="per-launch-group HBM accounting + live memory "
                    "trajectory + OOM pre-mortem rendering from a run's "
                    "telemetry",
    )
    p.add_argument("run_dir", help="run dir (or one metrics*.jsonl file)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit the analysis as JSON")
    args = p.parse_args(argv)

    from paddle_tpu.observability.analyze import load_run

    files = obs.metrics_files(args.run_dir)
    oom = read_oom_report(args.run_dir)
    if not files and oom is None:
        print(f"no metrics*.jsonl (or {OOM_REPORT}) under {args.run_dir!r} "
              "(was the run started with --metrics_path / --save_dir?)",
              file=sys.stderr)
        return 1
    doc = collect(load_run(args.run_dir)) if files else {
        "groups": [], "static_total_bytes": 0, "live": {}, "device_kind": "",
    }
    if not doc["groups"] and not doc["live"] and oom is None:
        print("no memory telemetry in this run's streams "
              "(pre-memory-telemetry run, or it never finished a pass)",
              file=sys.stderr)
        return 1
    if args.as_json:
        doc["oom_report"] = oom
        print(json.dumps(doc, indent=2, default=str))
    else:
        print(f"# memory: {', '.join(files) if files else args.run_dir}")
        print(format_report(doc, oom))
    return 0


if __name__ == "__main__":
    sys.exit(main())
