"""Request-level serving telemetry + the offered-load serve harness.

Training got end-to-end observability in PR 3 (metrics stream) and PR 7
(compile/roofline attribution); generation had none — ``bench_nmt_gen``
reports one aggregate tokens/s for a static batch, and the embedding
API's ``SequenceGenerator`` emits nothing. This module is the telemetry
contract the continuous-batching server (ROADMAP item 1) must keep,
built and exercised *before* that server exists so it lands on
instrumented rails:

- :class:`RequestLog` — per-request lifecycle records (``kind=request``:
  enqueue/admit/first-token/finish offsets → queue-wait, TTFT, decode
  time; prompt/generated token counts; beam size; batch cohort id and
  size; outcome ok/rejected/timeout/error) plus per-window rollups
  (``kind=serve_window``: offered load, goodput, admitted/completed/
  rejected counts, queue-depth and batch-occupancy histograms).
- :func:`run_rung` / :func:`run_sweep` — a deterministic **open-loop**
  offered-load driver: inter-arrival times are precomputed from a seed
  (:func:`arrival_offsets` — no wall-clock in the schedule), and the
  driver advances a VIRTUAL clock: admission/cohort decisions are pure
  functions of the schedule and the measured (or injected) per-launch
  service times, so the same seed plus the same service times yields
  the same cohort assignment bit-for-bit. Wall-clock is read only to
  *measure* service; at low offered load the virtual clock jumps to the
  next arrival instead of sleeping, so a sweep costs launch time, not
  idle time. Closed-loop benchmarks (fixed batch, back-to-back) can
  never see queueing; this is the p50/p99-vs-offered-load instrument
  VERDICT round 6 asked for.
- :func:`serve_doc` / :func:`main` — ``paddle serve-report <run_dir>``:
  a jax-free per-rung table (p50/p99 latency, TTFT, queue-wait share,
  batch occupancy, goodput) that joins the serving launch group's PR-7
  ``compile``/``roofline`` records, so each rung also says whether
  decode was dispatch-, compute-, memory-, or host-bound — and whether
  pad-to-signature held (recompiles after warmup must be 0).

jax-free by construction: the driver takes an injected ``launch_fn``
(bench.py supplies the jitted generator forward), and the analyzer must
run on a dev box against a run dir copied off a pod.
"""

from __future__ import annotations

import argparse
import collections
import dataclasses
import itertools
import json
import os
import sys
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddle_tpu.observability import metrics as obs
# one data-bound threshold for every analyzer (see analyze.py, already
# a module-level dependency — the analyzer entry points below reuse it)
from paddle_tpu.observability.analyze import (
    DATA_BOUND_SHARE,
    analyze,
    load_run,
)

# the launch-group name the serving front registers with CompileRegistry
# — serve-report joins compile/roofline records on it
SERVE_GROUP = "serve_gen"

# every serving launch group serve-report joins: the PR-8 static
# engine's one-shot generation launch, the continuous engine's
# decode/prefill pair, and the PR-20 speculative verify launch
# (paddle_tpu/serving/jax_backend.py) — all held to the same
# recompiles=0-after-warmup contract
SERVE_GROUPS = (SERVE_GROUP, "serve_decode", "serve_prefill",
                "serve_verify")

# mean exec seconds per launch at or below which a rung is classified
# dispatch-bound: the launch is latency-floor sized (per-launch dispatch
# overhead ~1-3ms through the runtime — doc/performance.md "Fused
# launches"), so wider batching, not a kernel fix, is the lever
DISPATCH_FLOOR_S = 3e-3

# a rung saturates when it completes less than this share of arrivals,
# or its p99 latency exceeds KNEE_P99_FACTOR x the lightest rung's p99
KNEE_COMPLETION = 0.99
KNEE_P99_FACTOR = 5.0

_oneshot_cohorts = itertools.count()


# ------------------------------------------------------------- schedule


def arrival_offsets(n: int, rate_rps: float, seed: int) -> np.ndarray:
    """``n`` Poisson-process arrival offsets (seconds from rung start) at
    ``rate_rps`` offered load — exponential inter-arrivals, precomputed
    from ``seed``. The schedule never reads a clock: determinism tests
    pin that the same seed reproduces it exactly."""
    assert rate_rps > 0, rate_rps
    rng = np.random.RandomState(seed)
    return np.cumsum(rng.exponential(1.0 / rate_rps, size=int(n)))


# -------------------------------------------------------------- request


@dataclasses.dataclass
class Request:
    """One request's lifecycle. Offsets are VIRTUAL seconds from rung
    start for the PR-8 static driver (the envelope ``t`` stays the
    writer's monotonic offset); the continuous engine stamps real
    wall-clock offsets from its window start. ``t_first_token`` differs
    from ``t_finish`` only under per-step decode — single-shot launches
    materialize the whole output at once and leave it unset (-1 →
    first-token == finish in the emitted record). ``max_new`` is the
    client's output-token budget (None = the graph's max_length)."""

    rid: str
    t_enqueue: float
    prompt: Any = None
    prompt_tokens: int = 0
    t_admit: float = -1.0
    t_first_token: float = -1.0
    t_finish: float = -1.0
    gen_tokens: int = 0
    cohort: int = -1
    cohort_size: int = 0
    outcome: str = "pending"
    max_new: Optional[int] = None
    # distributed-tracing join key (doc/observability.md "Distributed
    # tracing"): opaque, echoed verbatim onto every emitted record as
    # `trace_id`. "" = untraced (single-process runs stay unchanged)
    trace: str = ""

    @property
    def queue_wait_s(self) -> Optional[float]:
        return None if self.t_admit < 0 else self.t_admit - self.t_enqueue

    @property
    def e2e_s(self) -> Optional[float]:
        return None if self.t_finish < 0 else self.t_finish - self.t_enqueue


class RequestLog:
    """Emit ``kind=request`` records and accumulate one window's rollup.

    One instance per rung (or per fixed window within a rung, when the
    caller chooses to cut finer). Histograms are the streaming geometric
    kind from metrics.py — p50/p99 without storing samples."""

    def __init__(self, rung: int = 0, offered_rps: float = 0.0,
                 beam_size: Optional[int] = None, engine: str = "static",
                 pipeline: Optional[str] = None, replica: str = "",
                 spec: Optional[str] = None,
                 slot_dtype: Optional[str] = None):
        self.rung = int(rung)
        self.offered_rps = float(offered_rps)
        self.beam_size = beam_size
        # which serving engine produced this window: "static" (the PR-8
        # run-to-completion micro-batch driver / single-shot generate)
        # or "continuous" (paddle_tpu/serving slot-based decode) —
        # stamped on every request and serve_window record so `paddle
        # compare` never joins rungs across engines by accident
        self.engine = str(engine)
        # "on" | "off": whether the continuous engine ran the pipelined
        # dispatch/collect loop — part of the compare join key ((engine,
        # pipeline, offered load)) so a one-dir pipelined-vs-blocking
        # A/B keeps both ladders apart. None (the static driver) leaves
        # the field off the records
        self.pipeline = None if pipeline is None else str(pipeline)
        # fleet identity ("" outside a fleet): which replica's engine
        # produced this window — keeps N replicas' records apart in one
        # stream; the MERGED fleet window instead carries `replicas=N`
        # (serving/fleet.py merge_windows)
        self.replica = str(replica)
        # self-speculative decode config stamps (PR 20): `spec` is the
        # draft-length ladder spelling ("4", "2,4") or "off" when the
        # continuous engine's backend takes drafts but the ladder is
        # empty; `slot_dtype` is the slot-state storage dtype
        # ("f32"/"bf16"). Both None outside the continuous engine —
        # the fields stay off static-driver records entirely. Part of
        # the compare rung join, like `pipeline`.
        self.spec = None if spec is None else str(spec)
        self.slot_dtype = None if slot_dtype is None else str(slot_dtype)
        # draft tokens proposed / accepted across the window's verify
        # launches — accept_rate on the window record, plus the
        # cumulative serve.spec_proposed / serve.spec_accepted counters
        self.spec_proposed = 0
        self.spec_accepted = 0
        # host seconds spent scheduling while a decode launch was in
        # flight (the pipelined loop's dispatch->collect-entry gaps)
        self.overlap_s = 0.0
        self.latency = obs.Histogram("latency_s")
        self.ttft = obs.Histogram("ttft_s")
        self.queue_wait = obs.Histogram("queue_wait_s")
        self.queue_depth = obs.Histogram("queue_depth")
        self.occupancy = obs.Histogram("batch_occupancy")
        self.arrived = 0
        self.admitted = 0
        self.completed = 0
        self.rejected = 0
        self.timeouts = 0
        self.cancels = 0
        self.errors = 0
        self.sheds = 0
        self.breaker_opens = 0
        self.launches = 0
        self.exec_s = 0.0
        self.gen_tokens = 0
        self._wait_ok_s = 0.0
        self._e2e_ok_s = 0.0

    # ------------------------------------------------------- lifecycle

    def _emit(self, req: Request, **extra) -> None:
        rec: Dict[str, Any] = {
            "id": req.rid,
            "rung": self.rung,
            "engine": self.engine,
            "outcome": req.outcome,
            **({"pipeline": self.pipeline} if self.pipeline is not None
               else {}),
            **({"replica": self.replica} if self.replica else {}),
            "t_enqueue": round(req.t_enqueue, 6),
            "prompt_tokens": int(req.prompt_tokens),
        }
        if req.trace:
            rec["trace_id"] = req.trace
        if self.beam_size is not None:
            rec["beam_size"] = int(self.beam_size)
        if req.cohort >= 0:
            rec["cohort"] = req.cohort
            rec["cohort_size"] = req.cohort_size
        if req.t_admit >= 0:
            rec["t_admit"] = round(req.t_admit, 6)
            rec["queue_wait_s"] = round(req.queue_wait_s, 6)
        if req.t_finish >= 0:
            # single-shot decode materializes the whole output with the
            # launch, so first-token == finish there (t_first_token
            # unset); the continuous engine stamps the REAL wall-clock
            # moment its first token left the device mid-sequence
            tft = req.t_first_token if req.t_first_token >= 0 else req.t_finish
            rec["t_first_token"] = round(tft, 6)
            rec["t_finish"] = round(req.t_finish, 6)
            rec["ttft_s"] = round(tft - req.t_enqueue, 6)
            rec["decode_s"] = round(req.t_finish - req.t_admit, 6)
            rec["e2e_s"] = round(req.e2e_s, 6)
            rec["gen_tokens"] = int(req.gen_tokens)
        rec.update(extra)
        obs.emit("request", **rec)

    def reject(self, req: Request, arrived: bool = False) -> None:
        """Admission refused. At submit time the request was never
        enqueued — count its arrival here; a drain-path rejection of an
        ALREADY-enqueued request passes ``arrived=True`` (its arrival
        was counted by :meth:`enqueued` — double-counting would inflate
        the window's completed/arrived ratios)."""
        req.outcome = "rejected"
        if not arrived:
            self.arrived += 1
        self.rejected += 1
        obs.registry().counter("serve.rejected").inc()
        self._emit(req)

    def timeout(self, req: Request, vnow: float) -> None:
        """Past the wall deadline: queued (never admitted) or — under
        the continuous engine — mid-decode, freeing the slot at the next
        iteration boundary."""
        req.outcome = "timeout"
        self.timeouts += 1
        obs.registry().counter("serve.timeouts").inc()
        self._emit(req, queue_wait_s=round(vnow - req.t_enqueue, 6))

    def cancel(self, req: Request, vnow: float) -> None:
        """Client cancellation, applied at an iteration boundary —
        frees the queue entry or the decode slot (continuous engine)."""
        req.outcome = "cancelled"
        self.cancels += 1
        obs.registry().counter("serve.cancelled").inc()
        self._emit(req, t_cancel=round(vnow, 6))

    def error(self, req: Request, service_s: Optional[float] = None,
              **extra) -> None:
        """Failed launch/forward. ``service_s`` (time spent before the
        failure) rides the record — how long the failing call took is
        exactly the evidence an error record exists for."""
        req.outcome = "error"
        self.errors += 1
        obs.registry().counter("serve.errors").inc()
        if service_s is not None:
            extra["service_s"] = round(float(service_s), 6)
        self._emit(req, **extra)

    def shed(self, req: Request, vnow: float, arrived: bool = False,
             retry_after_s: Optional[float] = None) -> None:
        """Overload shedding (doc/resilience.md "Serving resilience"):
        the server refused this request as a POLICY decision — brownout
        pressure, an open launch-failure breaker, or a deadline the
        admission estimate proves unmeetable — distinct from
        ``rejected`` (a hard structural bound: queue cap, draining).
        The answer lands within one collect boundary instead of the
        client waiting out its own timeout; ``retry_after_s`` hints
        when capacity is expected back. ``arrived`` mirrors
        :meth:`reject`'s double-count rule for already-enqueued sheds."""
        req.outcome = "shed"
        if not arrived:
            self.arrived += 1
        self.sheds += 1
        obs.registry().counter("serve.shed").inc()
        extra: Dict[str, Any] = {"t_shed": round(vnow, 6)}
        if retry_after_s is not None:
            extra["retry_after_s"] = round(float(retry_after_s), 3)
        self._emit(req, **extra)

    def note_breaker_open(self) -> None:
        """The launch-failure circuit breaker opened (consecutive
        collect faults hit its threshold) during this window."""
        self.breaker_opens += 1
        obs.registry().counter("serve.breaker_opened").inc()

    def enqueued(self, req: Request) -> None:
        self.arrived += 1
        obs.registry().counter("serve.enqueued").inc()

    def admit(self, req: Request) -> None:
        """The request joined a launch cohort — only now is it admitted
        (a queued request that times out first never was)."""
        self.admitted += 1
        obs.registry().counter("serve.admitted").inc()

    def launch(self, depth_after: int, occupancy: int, service_s: float) -> None:
        """One micro-batch launch: queue depth left behind, cohort size,
        measured service seconds."""
        self.launches += 1
        self.exec_s += float(service_s)
        self.queue_depth.observe(float(depth_after))
        self.occupancy.observe(float(occupancy))
        r = obs.registry()
        r.gauge("serve.queue_depth").set(depth_after)
        r.histogram("serve.batch_occupancy").observe(float(occupancy))

    def note_exec(self, service_s: float) -> None:
        """Device seconds outside :meth:`launch` (the continuous
        engine's prefill writes) — keeps ``host_share`` honest."""
        self.exec_s += float(service_s)

    def note_overlap(self, seconds: float) -> None:
        """Host seconds that ran concurrently with an in-flight launch
        (pipelined loop: dispatch to collect-entry). Rides the window
        record and the cumulative ``serve.overlap_s`` counter — the
        direct measure of what the dispatch/collect split bought."""
        s = max(float(seconds), 0.0)
        self.overlap_s += s
        obs.registry().counter("serve.overlap_s").inc(s)

    def note_spec(self, proposed: int, accepted: int) -> None:
        """One verify launch's draft outcome: ``proposed`` draft tokens
        offered across all live slots, ``accepted`` the sum of common-
        prefix matches the launch committed. Rides the window record
        as ``accept_rate`` and the cumulative ``serve.spec_proposed`` /
        ``serve.spec_accepted`` counters."""
        p = max(int(proposed), 0)
        a = max(int(accepted), 0)
        self.spec_proposed += p
        self.spec_accepted += a
        obs.registry().counter("serve.spec_proposed").inc(p)
        obs.registry().counter("serve.spec_accepted").inc(a)

    def note_dispatch(self, depth: int) -> None:
        """Launches dispatched but not yet collected (``serve.
        dispatch_depth`` gauge): 0 = the serial loop's steady state,
        >=1 = the device has queued work while the host schedules."""
        obs.registry().gauge("serve.dispatch_depth").set(int(depth))

    def complete(self, req: Request, **extra) -> None:
        req.outcome = "ok"
        self.completed += 1
        self.gen_tokens += int(req.gen_tokens)
        self.latency.observe(req.e2e_s)
        tft = req.t_first_token if req.t_first_token >= 0 else req.t_finish
        self.ttft.observe(tft - req.t_enqueue)
        self.queue_wait.observe(req.queue_wait_s)
        self._wait_ok_s += req.queue_wait_s
        self._e2e_ok_s += req.e2e_s
        obs.registry().counter("serve.completed").inc()
        self._emit(req, **extra)

    # ---------------------------------------------------------- window

    def window_record(self, window_s: float,
                      host_share: Optional[float] = None) -> Dict[str, Any]:
        """Emit the ``kind=serve_window`` rollup and return it (sans
        envelope) — the same dict the bench headline and serve-report
        render, so text and telemetry cannot drift."""
        window_s = max(float(window_s), 1e-9)
        rec: Dict[str, Any] = {
            "rung": self.rung,
            "engine": self.engine,
            "offered_rps": round(self.offered_rps, 6),
            "window_s": round(window_s, 6),
            "arrived": self.arrived,
            "admitted": self.admitted,
            "completed": self.completed,
            "rejected": self.rejected,
            "timeouts": self.timeouts,
            "cancelled": self.cancels,
            "errors": self.errors,
            "shed": self.sheds,
            "breaker_open": self.breaker_opens,
            "launches": self.launches,
            "exec_s": round(self.exec_s, 6),
            "gen_tokens": self.gen_tokens,
            "goodput_tok_s": round(self.gen_tokens / window_s, 3),
            "completed_rps": round(self.completed / window_s, 6),
            "latency": self.latency.snapshot(),
            "ttft": self.ttft.snapshot(),
            "queue_wait": self.queue_wait.snapshot(),
            "queue_depth": self.queue_depth.snapshot(),
            "occupancy": self.occupancy.snapshot(),
        }
        if self.beam_size is not None:
            rec["beam_size"] = int(self.beam_size)
        if self.pipeline is not None:
            rec["pipeline"] = self.pipeline
        if self.replica:
            rec["replica"] = self.replica
        if self.spec is not None:
            rec["spec"] = self.spec
        if self.slot_dtype is not None:
            rec["slot_dtype"] = self.slot_dtype
        if self.spec_proposed > 0:
            rec["spec_proposed"] = self.spec_proposed
            rec["spec_accepted"] = self.spec_accepted
            rec["accept_rate"] = round(
                self.spec_accepted / self.spec_proposed, 4)
        if self.overlap_s > 0:
            rec["overlap_s"] = round(self.overlap_s, 6)
        if self._e2e_ok_s > 0:
            rec["queue_wait_share"] = round(self._wait_ok_s / self._e2e_ok_s, 4)
        if host_share is not None:
            rec["host_share"] = round(host_share, 4)
        obs.emit("serve_window", **rec)
        return rec


def log_oneshot(prompt_tokens: Sequence[int], gen_tokens: Sequence[int],
                service_s: float, beam_size: Optional[int] = None,
                outcome: str = "ok", n: Optional[int] = None,
                cold_start: bool = False) -> None:
    """Request records for one single-shot generate() call (the embedding
    API's ``SequenceGenerator``): the whole call is one cohort, every
    sample one request with zero queue wait. ``n`` overrides the sample
    count when ``prompt_tokens`` is incomplete (a dense-only feed on the
    error path — the evidence must still land). ``cold_start=True``
    marks records whose call paid the jit trace+compile — the user DID
    wait that long, but aggregations must be able to split compile cost
    from steady-state decode latency. No-op when telemetry is off —
    call sites never guard."""
    if not obs.enabled():
        return
    cohort = next(_oneshot_cohorts)
    log = RequestLog(rung=-1, beam_size=beam_size)
    n = len(prompt_tokens) if n is None else max(int(n), 1)
    # pid-scoped ids: a relaunched process restarts the cohort counter,
    # and its requests are NEW ones — they must not collide with a
    # previous incarnation's ids in the same stream (the analyzer
    # dedupes request records by (host, id))
    pid = os.getpid()
    for i in range(n):
        req = Request(
            rid=f"gen{pid}-{cohort}-{i}", t_enqueue=0.0,
            prompt_tokens=(int(prompt_tokens[i])
                           if i < len(prompt_tokens) else 0),
            t_admit=0.0, cohort=cohort, cohort_size=n,
        )
        log.enqueued(req)
        log.admit(req)
        extra = {"cold_start": True} if cold_start else {}
        if outcome == "ok":
            req.t_finish = float(service_s)
            req.gen_tokens = int(gen_tokens[i]) if i < len(gen_tokens) else 0
            log.complete(req, **extra)
        else:
            log.error(req, service_s=service_s, **extra)


# --------------------------------------------------------------- driver


def schedule_requests(
    rate_rps: float,
    n_requests: int,
    seed: int,
    rung: int = 0,
    prompt_fn: Optional[Callable[[np.random.RandomState, int], Sequence[int]]] = None,
    budget_fn: Optional[Callable[[np.random.RandomState, int], int]] = None,
) -> List[Request]:
    """The ONE workload builder both serving engines consume: arrival
    offsets, prompts and per-request output budgets are all drawn from
    the rung's seeded rngs in a fixed order, so the static driver and
    the continuous engine (bench.py serve --engine=...) face the SAME
    requests bit-for-bit — the A/B's whole validity. ``budget_fn(rng,
    i)`` caps request ``i``'s generated tokens (``max_new``); None
    leaves the graph's max_length in charge."""
    arrivals = arrival_offsets(n_requests, rate_rps, seed)
    rng = np.random.RandomState(seed + 0x5EED)
    requests: List[Request] = []
    for i in range(n_requests):
        prompt = list(prompt_fn(rng, i)) if prompt_fn is not None else None
        max_new = int(budget_fn(rng, i)) if budget_fn is not None else None
        requests.append(Request(
            rid=f"r{rung}-{i}", t_enqueue=float(arrivals[i]),
            prompt=prompt, prompt_tokens=len(prompt) if prompt else 0,
            max_new=max_new,
        ))
    return requests


def run_rung(
    launch_fn: Callable[[List[Request]], Tuple[Sequence[int], Optional[float]]],
    *,
    rate_rps: float,
    n_requests: int,
    seed: int,
    rung: int = 0,
    max_batch: int = 8,
    timeout_s: float = 60.0,
    queue_cap: int = 0,
    beam_size: Optional[int] = None,
    prompt_fn: Optional[Callable[[np.random.RandomState, int], Sequence[int]]] = None,
    budget_fn: Optional[Callable[[np.random.RandomState, int], int]] = None,
    engine: str = "static",
) -> Tuple[Dict[str, Any], List[Request]]:
    """One offered-load rung: open-loop arrivals at ``rate_rps``, a
    dynamic micro-batch aggregator admitting up to ``max_batch`` queued
    requests per launch (FIFO), virtual-clock accounting.

    ``launch_fn(cohort)`` serves a cohort (padding to its fixed
    signature is the callee's job) and returns ``(gen_token_counts,
    service_s)`` — ``service_s=None`` means "time me" (the real bench
    path); an injected value makes the whole rung deterministic (tests).
    ``prompt_fn(rng, i)`` materializes request ``i``'s prompt ids from
    the rung's seeded rng, so request content is part of the schedule.
    ``queue_cap`` rejects arrivals past the bound (0 = unbounded);
    ``timeout_s`` drops queued requests never admitted in time. Both
    policies are evaluated at launch boundaries in virtual time, so the
    admitted-cohort assignment is a pure function of (seed, service
    times). ``budget_fn`` assigns per-request output budgets
    (mixed-length workloads): run-to-completion launches still PAY the
    graph's full max_length — that honesty is the continuous engine's
    A/B case — so the budget only caps the tokens counted as delivered
    (launch_fn's job, reading ``req.max_new``)."""
    requests = schedule_requests(rate_rps, n_requests, seed, rung=rung,
                                 prompt_fn=prompt_fn, budget_fn=budget_fn)
    arrivals = [r.t_enqueue for r in requests]
    log = RequestLog(rung=rung, offered_rps=rate_rps, beam_size=beam_size,
                     engine=engine)
    # deque: a saturated unbounded queue reaches tens of thousands of
    # entries, and list.pop(0) purges would go quadratic — host time
    # that would then be charged to host_share
    queue: collections.deque = collections.deque()
    i_next = 0
    vnow = 0.0
    cohort_id = 0
    wall_t0 = time.perf_counter()

    while i_next < n_requests or queue:
        if not queue:
            # idle server: jump the virtual clock to the next arrival —
            # no sleeping, low offered loads cost nothing to sweep
            vnow = max(vnow, requests[i_next].t_enqueue)
        while i_next < n_requests and requests[i_next].t_enqueue <= vnow:
            req = requests[i_next]
            i_next += 1
            # entries that expired BEFORE this arrival left the queue
            # first in the modeled server — purge them before judging
            # the cap, or a dead entry could cause a spurious rejection
            while queue and req.t_enqueue - queue[0].t_enqueue > timeout_s:
                log.timeout(queue.popleft(), req.t_enqueue)
            if queue_cap and len(queue) >= queue_cap:
                log.reject(req)
            else:
                queue.append(req)
                log.enqueued(req)
        # drop queued requests past their admission deadline (FIFO, so
        # the oldest are at the front)
        while queue and vnow - queue[0].t_enqueue > timeout_s:
            log.timeout(queue.popleft(), vnow)
        if not queue:
            continue
        cohort = [queue.popleft() for _ in range(min(max_batch, len(queue)))]
        t_admit = vnow
        for req in cohort:
            log.admit(req)
        wall_launch = time.perf_counter()
        try:
            gen_counts, service_s = launch_fn(cohort)
        except Exception:
            # a failed launch must not take its cohort's evidence with
            # it: terminal error records (with the time the failing
            # launch burned) and the partial window land before the
            # re-raise
            failed_s = time.perf_counter() - wall_launch
            for j, req in enumerate(cohort):
                req.t_admit = t_admit
                req.cohort = cohort_id
                req.cohort_size = len(cohort)
                log.error(req, service_s=failed_s)
            wall_s = time.perf_counter() - wall_t0
            log.window_record(
                max(vnow, 1e-9),
                host_share=(max(1.0 - log.exec_s / wall_s, 0.0)
                            if wall_s > 0 else None),
            )
            raise
        if service_s is None:
            service_s = time.perf_counter() - wall_launch
        vnow += float(service_s)
        log.launch(len(queue), len(cohort), service_s)
        for j, req in enumerate(cohort):
            req.t_admit = t_admit
            req.t_finish = vnow
            req.cohort = cohort_id
            req.cohort_size = len(cohort)
            req.gen_tokens = int(gen_counts[j]) if j < len(gen_counts) else 0
            log.complete(req)
        cohort_id += 1

    wall_s = time.perf_counter() - wall_t0
    # host share: wall time the serve loop spent OUTSIDE launches
    # (padding, bookkeeping, record emission) — measured for real, the
    # serve analog of the trainer's data-wait share
    host_share = max(1.0 - log.exec_s / wall_s, 0.0) if wall_s > 0 else None
    window_s = max(vnow, float(arrivals[-1]) if n_requests else 0.0)
    summary = log.window_record(window_s, host_share=host_share)
    return summary, requests


def run_sweep(
    launch_fn, rates: Sequence[float], *, n_requests: int, seed: int, **kw
) -> Dict[str, Any]:
    """Sweep offered-load rungs (one :func:`run_rung` each, seeded
    ``seed + rung`` so schedules differ but reproduce) and locate the
    saturation knee."""
    rungs = []
    for i, rate in enumerate(rates):
        summary, _ = run_rung(
            launch_fn, rate_rps=float(rate), n_requests=n_requests,
            seed=seed + i, rung=i, **kw,
        )
        rungs.append(summary)
    return {"rungs": rungs, "knee_rps": saturation_knee(rungs)}


def saturation_knee(rungs: List[Dict[str, Any]]) -> Optional[float]:
    """Highest offered load the server still *keeps up with*: completes
    ≥ 99% of arrivals AND p99 latency stays within 5x the lightest
    rung's p99 (queueing, not service, is what explodes past the knee).
    CONTIGUOUS from the lightest rung — the scan stops at the first
    saturated rung, so a later rung that happens to pass (sampling
    luck) can never overstate capacity above a demonstrated failure.
    None when even the lightest rung saturates."""
    if not rungs:
        return None
    ordered = sorted(rungs, key=lambda r: r.get("offered_rps", 0.0))
    base_p99 = (ordered[0].get("latency") or {}).get("p99") or 0.0
    knee = None
    for r in ordered:
        arrived = r.get("arrived", 0)
        done_share = r.get("completed", 0) / arrived if arrived else 0.0
        p99 = (r.get("latency") or {}).get("p99") or 0.0
        if done_share < KNEE_COMPLETION or (
            base_p99 > 0 and p99 > KNEE_P99_FACTOR * base_p99
        ):
            break
        knee = r.get("offered_rps")
    return knee


# ------------------------------------------------------- serve-report


def classify_rung(window: Dict[str, Any],
                  roof_row: Optional[Dict[str, Any]]) -> str:
    """What bounded decode this rung: ``host-bound`` (the serve loop
    spent most wall time outside launches), ``dispatch-bound`` (launches
    are latency-floor sized — batch wider), else the roofline bucket
    (compute-/memory-bound from XLA intensity vs the chip's ridge
    point; ``unknown`` is never guessed)."""
    if (window.get("host_share") or 0.0) > DATA_BOUND_SHARE:
        return "host-bound"
    launches = window.get("launches", 0)
    if launches and window.get("exec_s", 0.0) / launches <= DISPATCH_FLOOR_S:
        return "dispatch-bound"
    if roof_row is not None:
        from paddle_tpu.observability.costs import classify

        return classify(roof_row.get("intensity"),
                        roof_row.get("device_kind", ""))
    return "unknown"


def _last_epoch(streams: Dict[int, List[Dict[str, Any]]]) -> Dict[int, List[Dict[str, Any]]]:
    """Each host's records from its LAST ``run_start`` on — the epoch
    the analyzer's serve reset keeps. The compile/roofline joins must
    use the same cut, or a previous sweep's recompile (or stale-sig
    roofline row) would haunt every clean rerun in a reused dir."""
    out: Dict[int, List[Dict[str, Any]]] = {}
    for host, recs in streams.items():
        start = 0
        for i, rec in enumerate(recs):
            if rec.get("kind") == "run_start":
                start = i
        out[host] = recs[start:]
    return out


def serve_doc(streams: Dict[int, List[Dict[str, Any]]]) -> Dict[str, Any]:
    """The serve-report analysis document: deduped serve windows (the
    analyzer's latest-wins policy), the serve launch group's compile and
    roofline joins (last epoch only), and a per-rung bound
    classification."""
    from paddle_tpu.observability.costs import roofline_rows

    doc = analyze(streams)
    windows = doc.get("serve_windows") or []
    epoch = _last_epoch(streams)
    serve_compiles = [
        rec
        for host in sorted(epoch)
        for rec in epoch[host]
        if rec.get("kind") == "compile" and rec.get("group") in SERVE_GROUPS
    ]
    # the decode-side group drives the bound classification: serve_gen
    # for static runs, serve_decode for engine runs (prefill rides as a
    # second compile line but isn't the steady-state launch)
    rows = roofline_rows(epoch)
    roof = next(
        (r for g in (SERVE_GROUP, "serve_decode") for r in rows
         if r.get("group") == g),
        None,
    )
    rungs = []
    for w in sorted(windows, key=lambda w: w.get("rung", 0)):
        rungs.append(dict(w, bound=classify_rung(w, roof)))
    recompiles = max(
        (int(c.get("recompiles", 0)) for c in serve_compiles), default=0
    )
    return {
        "rungs": rungs,
        "knee_rps": saturation_knee(windows),
        "engines": sorted({w.get("engine", "static") for w in windows}),
        "pipelines": sorted({w["pipeline"] for w in windows
                             if isinstance(w.get("pipeline"), str)}),
        "groups": sorted({c.get("group") for c in serve_compiles}),
        "requests": (doc.get("serve") or {}).get("requests", 0),
        "compiles": len(serve_compiles),
        "recompiles": recompiles,
        "roofline": roof,
        "run_ended": doc.get("run_ended", False),
        "invalid_records": doc.get("invalid_records", 0),
    }


def _q(snap: Optional[Dict[str, Any]], key: str) -> Optional[float]:
    v = (snap or {}).get(key)
    return float(v) if isinstance(v, (int, float)) else None


def format_report(doc: Dict[str, Any]) -> str:
    lines = [
        f"{'rung':>4} {'offered r/s':>11} {'reqs':>5} {'ok':>5} {'rej':>4} "
        f"{'shed':>4} {'t/o':>4} {'err':>4} {'p50 ms':>8} {'p99 ms':>8} "
        f"{'ttft p50':>8} {'ttft p99':>8} {'q-wait':>6} {'occ':>5} "
        f"{'accept':>6} {'goodput tok/s':>13} {'bound':>14}"
    ]
    for r in doc["rungs"]:
        p50 = _q(r.get("latency"), "p50")
        p99 = _q(r.get("latency"), "p99")
        t50 = _q(r.get("ttft"), "p50")
        t99 = _q(r.get("ttft"), "p99")
        occ = _q(r.get("occupancy"), "mean")
        acc = r.get("accept_rate")
        acc_s = f"{float(acc) * 100:>5.1f}%" if acc is not None else f"{'-':>6}"
        lines.append(
            f"{r.get('rung', 0):>4} {r.get('offered_rps', 0.0):>11.2f} "
            f"{r.get('arrived', 0):>5} {r.get('completed', 0):>5} "
            f"{r.get('rejected', 0):>4} {r.get('shed', 0):>4} "
            f"{r.get('timeouts', 0):>4} {r.get('errors', 0):>4} "
            f"{(p50 or 0.0) * 1e3:>8.2f} {(p99 or 0.0) * 1e3:>8.2f} "
            f"{(t50 or 0.0) * 1e3:>8.2f} {(t99 or 0.0) * 1e3:>8.2f} "
            f"{(r.get('queue_wait_share') or 0.0) * 100:>5.1f}% "
            f"{occ or 0.0:>5.2f} {acc_s} "
            f"{r.get('goodput_tok_s', 0.0):>13.1f} "
            f"{r.get('bound', 'unknown'):>14}"
        )
    lines.append("")
    knee = doc.get("knee_rps")
    lines.append(
        "saturation knee: "
        + (f"{knee:.2f} req/s (highest offered load completing "
           f"≥{KNEE_COMPLETION:.0%} of arrivals within "
           f"{KNEE_P99_FACTOR:g}x the lightest rung's p99)"
           if knee is not None else
           "none — every rung saturated (offered loads all exceed capacity)")
    )
    opens = sum(int(r.get("breaker_open", 0) or 0) for r in doc["rungs"])
    if opens:
        lines.append(
            f"! launch-failure breaker opened {opens} time(s) — cohorts "
            "were shed fast during the cooldown(s) (doc/resilience.md "
            "\"Serving resilience\")"
        )
    groups = ", ".join(doc.get("groups") or [SERVE_GROUP])
    engines = doc.get("engines") or []
    if engines and engines != ["static"]:
        lines.append(f"engine: {', '.join(engines)}")
    pipelines = doc.get("pipelines") or []
    if pipelines:
        lines.append(f"pipelined decode: {', '.join(pipelines)}")
    proposed = sum(int(r.get("spec_proposed", 0) or 0) for r in doc["rungs"])
    if proposed:
        accepted = sum(int(r.get("spec_accepted", 0) or 0)
                       for r in doc["rungs"])
        specs = sorted({str(r["spec"]) for r in doc["rungs"]
                        if r.get("spec") not in (None, "off")})
        lines.append(
            f"speculative decode: ladder {', '.join(specs) or '?'} — "
            f"{accepted}/{proposed} draft tokens accepted "
            f"({accepted / proposed:.1%})"
        )
    dtypes = sorted({str(r["slot_dtype"]) for r in doc["rungs"]
                     if isinstance(r.get("slot_dtype"), str)})
    if dtypes and dtypes != ["f32"]:
        lines.append(f"slot state dtype: {', '.join(dtypes)}")
    lines.append(
        f"{groups or SERVE_GROUP}: {doc['compiles']} compile(s), "
        f"recompiles after warmup: {doc['recompiles']}"
        + ("" if doc["recompiles"] == 0 else
           "  ! signature instability — pad-to-signature is broken, every "
           "recompile stalls serving")
    )
    roof = doc.get("roofline")
    if roof:
        parts = [f"{roof.get('launches', 0)} launch(es)",
                 f"exec {roof.get('exec_s', 0.0):.3f}s"]
        if roof.get("intensity") is not None:
            parts.append(f"intensity {roof['intensity']:.2f} FLOP/B")
        lines.append(f"{roof.get('group', SERVE_GROUP)} roofline: "
                     + ", ".join(parts))
    if doc.get("invalid_records"):
        lines.append(f"! {doc['invalid_records']} record(s) failed schema "
                     "validation")
    if not doc.get("run_ended"):
        lines.append("! stream ends without run_end — the serve run crashed "
                     "or is still going")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="paddle serve-report",
        description="per-offered-load serving report from a run's "
                    "request/serve_window telemetry (doc/observability.md "
                    "\"Serving telemetry\")",
    )
    p.add_argument("run_dir", help="run dir (or one metrics*.jsonl file)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit the analysis as JSON")
    args = p.parse_args(argv)

    files = obs.metrics_files(args.run_dir)
    if not files:
        print(f"no metrics*.jsonl under {args.run_dir!r} "
              "(was this dir produced by `bench.py serve`?)", file=sys.stderr)
        return 1
    doc = serve_doc(load_run(args.run_dir))
    if not doc["rungs"]:
        print("no serve_window records in this run's telemetry (not a "
              "serve run? `paddle metrics` reads training runs)",
              file=sys.stderr)
        return 1
    if args.as_json:
        print(json.dumps(doc, indent=2, default=str))
    else:
        print(f"# serve-report: {', '.join(files)}")
        print(format_report(doc))
    return 0


if __name__ == "__main__":
    sys.exit(main())
