"""Metrics registry + per-host append-only ``metrics.jsonl`` writer.

Three metric kinds (the prometheus trinity, host-side only):

- :class:`Counter` — monotonically increasing float/int.
- :class:`Gauge` — last-write-wins value.
- :class:`Histogram` — streaming quantiles (p50/p99) WITHOUT storing
  samples: geometric buckets (relative width ``growth - 1``), so memory
  is O(distinct magnitudes) and a quantile is a cumulative walk with
  linear interpolation inside the winning bucket. Quantile error is
  bounded by the bucket width (~5% relative by default) — see
  tests/test_stats.py which pins it against ``np.percentile``.

The writer appends schema-versioned JSON records to a run-dir scoped
``metrics.jsonl`` (one file per host). Records carry ``step``, ``pass``,
``host`` and a ``t`` wall-time OFFSET (monotonic seconds since the
writer was configured — hot paths never read the wall clock; the
``run_start`` record anchors the offset to civil time once). Records are
buffered and flushed on pass boundaries and atexit/SIGTERM-driven
flush calls, so a hard crash loses at most one window.

Everything here is importable without jax (the supervisor and the
``paddle metrics`` analyzer run when the accelerator runtime is down).
"""

from __future__ import annotations

import atexit
import json
import math
import os
import socket
import threading
from paddle_tpu.utils import concurrency as cc
import time
from typing import Any, Dict, Iterator, List, Optional

from paddle_tpu.utils.logging import logger

SCHEMA_VERSION = 1

# metrics.jsonl for host 0 (the single-host name the tooling documents),
# metrics.host<K>.jsonl for the rest; the analyzer merges metrics*.jsonl
FILE_FMT_HOST0 = "metrics.jsonl"
FILE_FMT = "metrics.host%d.jsonl"

# record kinds that force a flush when emitted: each marks a window
# boundary after which losing the buffer would lose a whole window
# (request/serve_window: a serving run killed mid-rung must leave every
# finished request's latency on disk — the whole point of the records.
# The per-record append this buys costs ~tens of µs and is charged,
# honestly, to the serve loop's host_share; telemetry-off pays nothing).
# Historical note: a "crash" kind rode here for five PRs without any
# emitter — the supervisor writes crash_report.json, not a record —
# and was removed when `paddle lint` (PTL007) flagged the drift.
# `memory` rides here (pass boundaries only) and `oom` MUST (the
# process dies right after — same evidence-before-death rule as fault/
# hang); `numerics` deliberately does NOT: at --numerics_log_period=1
# it is a per-batch kind like train_window, and a forced flush per
# record would put file I/O back on the hot step loop. Its crash
# durability is handled at the events that matter — the nonfinite
# handler emits the health table alongside its (soon-flushed) evidence,
# and ordinary aborts reach the atexit flush.
FLUSH_KINDS = frozenset(
    {"run_start", "run_end", "pass_end", "checkpoint",
     "barrier_skew", "restart", "compile", "roofline",
     "request", "serve_window", "memory", "oom", "reload", "sparse",
     "span"}
)

# required keys of every record; kind-specific fields ride alongside
REQUIRED_KEYS = ("v", "kind", "host", "t")

# Kind-specific required fields, one entry per documented record kind
# (doc/observability.md "Record kinds") — `paddle lint` rule PTL007
# keeps this registry, the doc table, and the emit call sites in sync:
# an emitted kind missing here (or documented here but emitted nowhere)
# is a lint finding. An empty tuple means "envelope only"; non-empty
# tuples are the fields without which the record is unanalyzable, and
# validate_record enforces them. `bench` is emitted by bench.py;
# `lint_finding`/`lint_summary` by `paddle lint --json` — both outside
# this package's writer, same schema.
KIND_REQUIRED = {
    "run_start": ("wall_time",),
    "run_end": ("status",),
    "train_window": (),
    "pass_end": (),
    "test": (),
    "checkpoint": ("op",),
    "nonfinite": ("value", "policy"),
    "fault": ("site", "action"),
    "barrier_skew": ("skew_s",),
    "preempt": (),
    "hang": ("age_s",),
    "bench": ("metric", "value"),
    "restart": ("restore_s",),
    "compile": ("group", "sig"),
    "roofline": ("group", "sig"),
    # request/serve_window (observability/serving.py + serving/engine.py):
    # `engine` ("static" | "continuous") keys the compare join — two
    # engines' rungs must never be mistaken for one ladder; request
    # records carry it too (optional pre-PR-12 streams still validate)
    "request": ("id", "outcome"),
    "serve_window": ("rung", "offered_rps", "engine"),
    # hot weight reload (serving/engine.py _apply_reload_locked): one
    # record per boundary swap — `path` names the checkpoint that went
    # live; rare and load-bearing (the train→serve loop's visible
    # seam), so it rides FLUSH_KINDS
    "reload": ("path",),
    # memory plane (observability/memory.py): host_rss_bytes is the one
    # field every backend can supply — hbm_* fields are present exactly
    # when the allocator reports stats (None on the CPU backend)
    "memory": ("host_rss_bytes",),
    # numerics plane (observability/numerics.py): the per-layer health
    # table is the record's whole point
    "numerics": ("layers",),
    # OOM pre-mortem: flushed before the death, like fault/hang
    "oom": ("error", "report"),
    "lint_finding": ("rule", "path", "line"),
    "lint_summary": ("findings", "counts"),
    "race_finding": ("detector", "spec"),
    "race_summary": ("findings", "counts"),
    # sparse-table plane (paddle_tpu/sparse/, doc/sparse.md): one
    # record per pass — touched/unique rows, gather/scatter bytes,
    # reshard events; pass boundaries only, so it rides FLUSH_KINDS
    "sparse": ("rows_touched",),
    # distributed tracing (observability/tracing.py, doc/observability.md
    # "Distributed tracing"): one record per hop — `name` is the hop
    # (router.wait, engine.prefill, ...), `t0` the hop's start as a
    # stream-timebase offset, `dur_s` its duration (0.0 = instant);
    # `trace`/`traces` join hops to requests, `span_id`/`parent_id`
    # order them across processes
    "span": ("name", "t0", "dur_s"),
}


# --------------------------------------------------------------- metrics


class Counter:
    """Monotonic accumulator (thread-safe)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0
        self._lock = cc.Lock()

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """Last-write-wins value (thread-safe by assignment atomicity)."""

    __slots__ = ("name", "_value")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0

    def set(self, v: float) -> None:
        self._value = float(v)

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Streaming histogram with geometric buckets.

    ``observe(v)`` increments the bucket ``ceil(log_g(v / min_value))``;
    ``quantile(q)`` walks the cumulative counts and interpolates
    linearly inside the winning bucket, so p50/p99 come back with
    relative error bounded by ``growth - 1`` without ever storing
    samples. Values below ``min_value`` (including 0 and negatives)
    land in an underflow bucket reported as ``min_value``.
    """

    def __init__(self, name: str, growth: float = 1.05, min_value: float = 1e-6):
        assert growth > 1.0, growth
        self.name = name
        self.growth = growth
        self.min_value = min_value
        self._log_g = math.log(growth)
        self._buckets: Dict[int, int] = {}
        self._count = 0
        self._sum = 0.0
        self._max = -math.inf
        self._min = math.inf
        self._lock = cc.Lock()

    def _index(self, v: float) -> int:
        if v <= self.min_value:
            return 0
        return max(int(math.ceil(math.log(v / self.min_value) / self._log_g)), 0)

    def _upper(self, idx: int) -> float:
        return self.min_value * self.growth ** idx

    def observe(self, v: float) -> None:
        v = float(v)
        idx = self._index(v)
        with self._lock:
            self._buckets[idx] = self._buckets.get(idx, 0) + 1
            self._count += 1
            self._sum += v
            if v > self._max:
                self._max = v
            if v < self._min:
                self._min = v

    @property
    def count(self) -> int:
        return self._count

    @property
    def mean(self) -> float:
        return self._sum / self._count if self._count else 0.0

    def quantile(self, q: float) -> float:
        """Value at quantile ``q`` in [0, 1] (0 with no observations)."""
        assert 0.0 <= q <= 1.0, q
        with self._lock:
            if not self._count:
                return 0.0
            target = q * (self._count - 1) + 1  # rank in [1, count]
            seen = 0
            for idx in sorted(self._buckets):
                n = self._buckets[idx]
                if seen + n >= target:
                    # interpolate within the bucket's geometric span
                    lo = self._upper(idx - 1) if idx > 0 else self.min_value
                    hi = self._upper(idx)
                    frac = (target - seen) / n
                    v = lo + (hi - lo) * frac
                    # never report outside the observed range (the top
                    # bucket's upper bound can overshoot the true max)
                    return min(max(v, self._min), self._max)
                seen += n
            return self._max

    def snapshot(self) -> Dict[str, float]:
        return {
            "count": self._count,
            "mean": self.mean,
            "p50": self.quantile(0.5),
            "p99": self.quantile(0.99),
            "max": self._max if self._count else 0.0,
        }


class MetricsRegistry:
    """Named metrics, one flat namespace per process."""

    def __init__(self):
        self._metrics: Dict[str, Any] = {}
        self._lock = cc.Lock()

    def _get(self, name: str, cls, *args):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, *args)
            assert isinstance(m, cls), (
                f"metric {name!r} already registered as {type(m).__name__}"
            )
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str, growth: float = 1.05,
                  min_value: float = 1e-6) -> Histogram:
        return self._get(name, Histogram, growth, min_value)

    def snapshot(self) -> Dict[str, Any]:
        """Flat {name: value | histogram-summary dict} of everything."""
        with self._lock:
            items = list(self._metrics.items())
        out: Dict[str, Any] = {}
        for name, m in items:
            out[name] = m.snapshot() if isinstance(m, Histogram) else m.value
        return out

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()


# ---------------------------------------------------------------- writer


class MetricsWriter:
    """Buffered append-only JSONL writer, one per host.

    ``path`` may be a directory (the run dir — the conventional shape)
    or an explicit ``*.jsonl`` file. Buffered records flush when the
    buffer fills, when a window-boundary kind (FLUSH_KINDS) is emitted,
    and at interpreter exit — a hard kill loses at most one window.
    """

    def __init__(self, path: str, host: int = 0, buffer_limit: int = 512):
        self.path = _resolve_path(path, host)
        self.dir = os.path.dirname(self.path) or "."
        self.host = int(host)
        self.buffer_limit = int(buffer_limit)
        self._buf: List[str] = []
        self._lock = cc.Lock()
        self._closed = False
        self._t0_mono = time.monotonic()
        os.makedirs(self.dir, exist_ok=True)
        # anchor: the ONLY wall-clock read; every later record carries a
        # monotonic offset from this instant
        self.emit(
            "run_start",
            wall_time=time.time(),  # lint: disable=PTL001 -- run_start anchor: the one read that maps t-offsets to civil time
            wall_time_iso=time.strftime("%Y-%m-%dT%H:%M:%S%z"),
            hostname=socket.gethostname(),
            pid=os.getpid(),
        )

    def emit(self, kind: str, *, pass_id: Optional[int] = None,
             step: Optional[int] = None, **fields) -> None:
        if self._closed:
            return
        rec: Dict[str, Any] = {
            "v": SCHEMA_VERSION,
            "kind": kind,
            "host": self.host,
            "t": round(time.monotonic() - self._t0_mono, 6),
        }
        if pass_id is not None:
            rec["pass"] = int(pass_id)
        if step is not None:
            rec["step"] = int(step)
        rec.update(fields)
        line = json.dumps(_sanitize(rec), default=_json_default)
        with self._lock:
            self._buf.append(line)
            full = len(self._buf) >= self.buffer_limit
        if full or kind in FLUSH_KINDS:
            self.flush()

    def flush(self) -> None:
        with self._lock:
            if not self._buf:
                return
            buf, self._buf = self._buf, []
        try:
            os.makedirs(self.dir, exist_ok=True)  # run dir may have rotated
            with open(self.path, "a") as f:
                f.write("\n".join(buf) + "\n")
        except OSError as e:
            # telemetry must never take down the run it observes
            logger.warning("metrics flush to %s failed: %s", self.path, e)

    def close(self) -> None:
        """Flush and stop accepting records. Does NOT emit ``run_end`` —
        that record means "the run finished on purpose" and is the
        trainer's to write; a reconfigure mid-process must not forge it."""
        if self._closed:
            return
        self.flush()
        self._closed = True


def _resolve_path(path: str, host: int) -> str:
    """The per-host stream file for a run dir (or explicit ``*.jsonl``)."""
    if path.endswith(".jsonl"):
        d, fname = os.path.split(path)
        if host > 0:
            fname = f"{fname[:-len('.jsonl')]}.host{host}.jsonl"
        return os.path.join(d or ".", fname)
    return os.path.join(path, FILE_FMT_HOST0 if host == 0 else FILE_FMT % host)


def _sanitize(o):
    """Keep the stream strict JSON: non-finite floats (a NaN loss is a
    legitimate record value!) become their string names — ``json.dumps``
    would otherwise emit bare ``NaN`` tokens most parsers reject."""
    if isinstance(o, float) and not math.isfinite(o):
        return str(o)  # "nan" / "inf" / "-inf"
    if isinstance(o, dict):
        return {k: _sanitize(v) for k, v in o.items()}
    if isinstance(o, (list, tuple)):
        return [_sanitize(v) for v in o]
    return o


def _json_default(o):
    """Last-resort coercion: numpy scalars/arrays and friends."""
    if hasattr(o, "item"):
        return o.item()
    if hasattr(o, "tolist"):
        return o.tolist()
    return str(o)


# ------------------------------------------------------ process globals

_registry = MetricsRegistry()
_writer: Optional[MetricsWriter] = None
_atexit_installed = False


def registry() -> MetricsRegistry:
    return _registry


def enabled() -> bool:
    return _writer is not None


def configure(path: str, host: int = 0) -> Optional[MetricsWriter]:
    """Install (or with an empty path, clear) the process-global writer.

    Re-configuring with the same resolved file reuses the open writer
    (no duplicate ``run_start``); a different path closes the old one.
    """
    global _writer, _atexit_installed
    if not path:
        if _writer is not None:
            _writer.close()
        _writer = None
        return None
    resolved = _resolve_path(path, host)
    if _writer is not None:
        if os.path.abspath(_writer.path) == os.path.abspath(resolved):
            return _writer
        _writer.close()
    _writer = MetricsWriter(path, host=host)
    if not _atexit_installed:
        atexit.register(_atexit_flush)
        _atexit_installed = True
    return _writer


def configure_from_flags(flags, host: int = 0) -> Optional[MetricsWriter]:
    """Resolve the run's metrics dir: ``--metrics_path`` wins, else the
    save_dir doubles as the run dir (a supervised run always has one, so
    crash reports can read the tail), else telemetry is off."""
    path = getattr(flags, "metrics_path", "") or getattr(flags, "save_dir", "")
    return configure(path, host=host)


def _atexit_flush() -> None:
    if _writer is not None:
        _writer.flush()


def emit(kind: str, **fields) -> None:
    """Emit one record through the global writer; no-op when telemetry
    is off — call sites never need to guard."""
    if _writer is not None:
        _writer.emit(kind, **fields)


def flush() -> None:
    if _writer is not None:
        _writer.flush()


def rel_time(mono: float) -> float:
    """Map an absolute ``time.monotonic()`` reading into the global
    writer's ``t``-offset timebase (seconds since its ``run_start``).
    Span emitters measure hop boundaries with their own monotonic reads
    and convert here, so a span's ``t0`` shares the timebase every other
    record's envelope ``t`` uses — the property the trace reconstructor's
    run_start wall-clock alignment depends on. Returns the reading
    unchanged when telemetry is off (the record it would anchor is a
    no-op anyway)."""
    if _writer is None:
        return float(mono)
    return round(float(mono) - _writer._t0_mono, 6)


# ---------------------------------------------------------------- reading


def metrics_files(run_dir: str) -> List[str]:
    """Every per-host metrics stream under ``run_dir`` (host order).
    A ``*.jsonl`` file path is returned as-is."""
    if os.path.isfile(run_dir):
        return [run_dir]
    if not os.path.isdir(run_dir):
        return []
    out = [
        os.path.join(run_dir, f)
        for f in os.listdir(run_dir)
        if f.startswith("metrics") and f.endswith(".jsonl")
    ]
    return sorted(out)


def fleet_stream_dirs(run_dir: str) -> List[str]:
    """Every telemetry stream dir of a FLEET run rooted at ``run_dir``:
    the dir itself (the router's stream, when it has one) plus each
    replica's per-child metrics dir — ``replica-<i>/`` children of the
    run dir or of a nested ``fleet_status/`` (the layouts
    ``serve-fleet``'s ``_child_argv`` produces for ``--fleet_status_dir``
    inside or beside ``--metrics_path``). A plain single-process run dir
    comes back as ``[run_dir]`` unchanged, so fleet-aware readers can
    call this unconditionally."""
    if not os.path.isdir(run_dir):
        return [run_dir]
    dirs = [run_dir]
    roots = [run_dir, os.path.join(run_dir, "fleet_status")]
    for root in roots:
        if not os.path.isdir(root):
            continue
        for name in sorted(os.listdir(root)):
            sub = os.path.join(root, name)
            if (name.startswith("replica-") and os.path.isdir(sub)
                    and metrics_files(sub)):
                dirs.append(sub)
    return dirs


def parse_record_lines(text: str) -> Iterator[Dict[str, Any]]:
    """The ONE torn-line tolerance policy, shared by every reader
    (file reader, `--follow` live tail, bench-artifact parsing): blank
    lines and unparseable/non-dict lines are skipped — a crash can
    truncate the final line mid-write and that must never fail the
    stream."""
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue  # torn tail line from a crash — expected
        if isinstance(rec, dict):
            yield rec


def read_records(path: str) -> Iterator[Dict[str, Any]]:
    """Tolerant record reader: skips blank and torn lines (a crash can
    truncate the final line mid-write) instead of failing the stream.
    Streams line-by-line — a multi-day run's metrics.jsonl is never
    held in memory whole."""
    try:
        f = open(path)
    except OSError as e:
        logger.warning("cannot read metrics stream %s: %s", path, e)
        return
    with f:
        for line in f:
            yield from parse_record_lines(line)


def read_tail(run_dir: str, n: int = 20) -> Dict[int, List[Dict[str, Any]]]:
    """Last ``n`` records per host — what the supervisor embeds in
    ``crash_report.json`` instead of a grepped log tail."""
    out: Dict[int, List[Dict[str, Any]]] = {}
    for path in metrics_files(run_dir):
        for rec in read_records(path):
            host = int(rec.get("host", 0))
            bucket = out.setdefault(host, [])
            bucket.append(rec)
            if len(bucket) > n:
                del bucket[0]
    return out


def tail_with_last_skew(run_dir: str, n: int = 20):
    """(``{host-str: [last n records]}``, newest ``barrier_skew`` record
    or None) — the shared post-mortem evidence shape embedded by BOTH
    the supervisor's ``crash_report.json`` and hangwatch's
    ``hang_report.json``, extracted here so the skew-selection rule
    cannot drift between them.

    Newest skew: LAST in stream order per host (the ``t`` offset resets
    to ~0 in every restarted child appending to the same stream, so it
    cannot order records across attempts), then the highest pass across
    hosts — all hosts emit the same allgathered table, so any host's
    newest is authoritative."""
    tails = read_tail(run_dir, n=n)
    skew: Optional[Dict[str, Any]] = None
    for recs in tails.values():
        last = next(
            (r for r in reversed(recs) if r.get("kind") == "barrier_skew"),
            None,
        )
        if last is not None and (
            skew is None or last.get("pass", -1) >= skew.get("pass", -1)
        ):
            skew = last
    return {str(h): r for h, r in tails.items()}, skew


def validate_record(rec: Dict[str, Any]) -> List[str]:
    """Problems with one record against the documented schema
    (doc/observability.md); empty list = valid."""
    problems = []
    for k in REQUIRED_KEYS:
        if k not in rec:
            problems.append(f"missing required key {k!r}")
    if rec.get("v") not in (SCHEMA_VERSION,):
        problems.append(f"unknown schema version {rec.get('v')!r}")
    if not isinstance(rec.get("kind"), str):
        problems.append("kind must be a string")
    if "t" in rec and not isinstance(rec["t"], (int, float)):
        problems.append("t must be a number (seconds since run_start)")
    for k in ("pass", "step", "host", "rung"):
        if k in rec and not isinstance(rec[k], int):
            problems.append(f"{k} must be an integer")
    for k in KIND_REQUIRED.get(rec.get("kind"), ()):
        if k not in rec:
            problems.append(f"{rec['kind']} record missing required key {k!r}")
    return problems
