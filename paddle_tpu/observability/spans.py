"""Span layer — ``stat_timer`` scopes as Chrome trace-event JSON.

``utils/stats.py::stat_timer`` already aggregates named scopes into the
global StatSet and annotates the jax profiler trace. This module is the
third consumer: when a collector is configured (``--trace_events_path``),
every scope additionally records a complete ("ph": "X") trace event, and
the collector exports ``{"traceEvents": [...]}`` that chrome://tracing /
Perfetto load directly. Nesting falls out of the format: events on the
same pid/tid nest by time containment, so ``train_step`` spans appear
inside their ``trainer/pass`` span and next to ``data/prefetch_wait``.

This intentionally does NOT replace the jax profiler (``--profile_dir``
captures device-side xplanes; stat_timer's TraceAnnotation names these
same scopes there) — it is the host-side, dependency-free view: a span
file is a few KB of JSON you can open anywhere, not a protobuf needing
tensorboard.

jax-free, thread-safe, and bounded: past ``max_events`` new spans are
dropped (counted), so a long run cannot OOM its own telemetry.
"""

from __future__ import annotations

import atexit
import contextlib
import json
import os
import threading
from paddle_tpu.utils import concurrency as cc
import time
from typing import Iterator, List, Optional

from paddle_tpu.utils.logging import logger


class SpanCollector:
    def __init__(self, path: str, host: int = 0, max_events: int = 200_000):
        self.path = path
        self.host = int(host)
        self.max_events = int(max_events)
        self.dropped = 0
        self._events: List[dict] = []
        self._lock = cc.Lock()
        self._t0 = time.perf_counter()

    def now(self) -> float:
        """Span clock (seconds since collector start)."""
        return time.perf_counter() - self._t0

    def record(self, name: str, start_s: float, dur_s: float) -> None:
        """One complete span; ``start_s`` is a ``now()`` reading."""
        ev = {
            "name": name,
            "ph": "X",
            "ts": round(start_s * 1e6, 3),   # trace-event time unit: us
            "dur": round(dur_s * 1e6, 3),
            "pid": self.host,
            "tid": cc.get_ident() % 2**31,
        }
        with self._lock:
            if len(self._events) >= self.max_events:
                self.dropped += 1
                return
            self._events.append(ev)

    def instant(self, name: str, **args) -> None:
        """Instant event ("ph": "i") — nonfinite hits, fault firings."""
        ev = {
            "name": name,
            "ph": "i",
            "s": "t",
            "ts": round(self.now() * 1e6, 3),
            "pid": self.host,
            "tid": cc.get_ident() % 2**31,
        }
        if args:
            ev["args"] = args
        with self._lock:
            if len(self._events) >= self.max_events:
                self.dropped += 1
                return
            self._events.append(ev)

    def export(self) -> Optional[str]:
        """Write the full trace-event JSON document (idempotent: each
        export rewrites the complete file, so a mid-run export is always
        a loadable trace). Returns the path, or None on failure."""
        with self._lock:
            events = list(self._events)
            dropped = self.dropped
        doc = {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "source": "paddle_tpu stat_timer spans",
                "host": self.host,
                "dropped_events": dropped,
            },
        }
        try:
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            with open(self.path, "w") as f:
                json.dump(doc, f)
        except OSError as e:
            logger.warning("span export to %s failed: %s", self.path, e)
            return None
        return self.path


_collector: Optional[SpanCollector] = None
_atexit_installed = False


def _resolve_path(path: str, host: int) -> str:
    """Multi-host: every process writes its own file next to host 0's."""
    if host > 0:
        root, ext = os.path.splitext(path)
        return f"{root}.host{host}{ext or '.json'}"
    return path


def configure(path: str, host: int = 0) -> Optional[SpanCollector]:
    """Install (or with an empty path, clear) the global collector.
    Re-configuring with the same resolved file keeps the live collector
    (a fresh one would later export over — and erase — its spans)."""
    global _collector, _atexit_installed
    if not path:
        if _collector is not None:
            _collector.export()
        _collector = None
        return None
    path = _resolve_path(path, host)
    if _collector is not None and _collector.path == path:
        return _collector
    if _collector is not None:
        _collector.export()
    _collector = SpanCollector(path, host=host)
    if not _atexit_installed:
        atexit.register(_atexit_export)
        _atexit_installed = True
    return _collector


def configure_from_flags(flags, host: int = 0) -> Optional[SpanCollector]:
    return configure(getattr(flags, "trace_events_path", "") or "", host=host)


def _atexit_export() -> None:
    if _collector is not None:
        _collector.export()


def enabled() -> bool:
    return _collector is not None


def record(name: str, start_s: float, dur_s: float) -> None:
    if _collector is not None:
        _collector.record(name, start_s, dur_s)


def record_perf(name: str, t0_perf: float, dur_s: float) -> None:
    """Record a span whose start was taken with ``time.perf_counter()``
    (stat_timer's clock) — converted onto the collector clock here, so
    the caller needs no collector handle on its hot path."""
    c = _collector
    if c is not None:
        c.record(name, t0_perf - c._t0, dur_s)


def instant(name: str, **args) -> None:
    if _collector is not None:
        _collector.instant(name, **args)


def export() -> Optional[str]:
    return _collector.export() if _collector is not None else None


@contextlib.contextmanager
def span(name: str) -> Iterator[None]:
    """Span-only scope for sites where a StatSet entry would be noise
    (or jax may not be imported); stat_timer uses record() directly."""
    c = _collector
    if c is None:
        yield
        return
    t0 = c.now()
    try:
        yield
    finally:
        c.record(name, t0, c.now() - t0)
