"""Cost attribution: XLA cost analysis → per-launch-group rooflines.

``paddle roofline <run_dir>`` answers the question step timing alone
cannot: *where do the FLOPs and bytes go, and what is each compiled
launch group bound by?* For every launch group the compile telemetry
(``observability/compile_log.py``) recorded, it combines

- FLOPs and bytes accessed per launch (``compiled.cost_analysis()``,
  captured at compile time into the ``kind=compile`` / ``kind=roofline``
  records; the analytic matmul count rides along as
  ``flops_analytic_per_launch`` — XLA counts scan bodies once, so for
  scanned models the analytic number is the honest FLOP basis), with
- measured execution seconds per group (the trainer's step windows,
  attributed launch-by-launch),

into achieved FLOP/s, arithmetic intensity (FLOP/byte), and a roofline
bucket: **compute-bound** (intensity ≥ the chip's ridge point,
peak FLOP/s ÷ peak HBM bytes/s), **memory-bound** (below it), or
**host-bound** (the pass spent most of its time waiting on the data
pipeline — no kernel fix will help). Chip peaks come from
``ops/kernel_flops.py``; unknown device kinds degrade the bucket to
``unknown`` rather than guessing.

jax-free: like ``paddle metrics``, it must run on a dev box against a
run dir copied off a pod.

Usage::

    paddle roofline <run_dir | metrics.jsonl> [--json]
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional

from paddle_tpu.observability import metrics as obs
# data-wait share of pass time above which a group's roofline position
# is moot — the step loop is starved, not the kernel. The SAME constant
# drives the analyzer's data-bound warning (one threshold, two tools,
# no drift); analyze only imports costs lazily, so no cycle.
from paddle_tpu.observability.analyze import DATA_BOUND_SHARE as HOST_BOUND_SHARE


def cost_analysis_of(compiled) -> Optional[Dict[str, float]]:
    """FLOPs / bytes accessed of one compiled executable, or None.

    Graceful by contract: backends without cost analysis (or raising
    from it), list-shaped returns (older jax), and missing keys all
    collapse to None / absent keys — accounting must never be able to
    break training (same covenant as ``_count_model_flops``)."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    out: Dict[str, float] = {}
    f = ca.get("flops")
    if isinstance(f, (int, float)) and f > 0:
        out["flops"] = float(f)
    b = ca.get("bytes accessed")
    if isinstance(b, (int, float)) and b > 0:
        out["bytes_accessed"] = float(b)
    return out or None


def classify(intensity: Optional[float], device_kind: str = "",
             data_wait_share: Optional[float] = None) -> str:
    """Roofline bucket of one launch group."""
    if data_wait_share is not None and data_wait_share > HOST_BOUND_SHARE:
        return "host-bound"
    if intensity is None:
        return "unknown"
    from paddle_tpu.ops.kernel_flops import peak_gbps, peak_tflops

    peak_t = peak_tflops(device_kind or "")
    peak_b = peak_gbps(device_kind or "")
    if not peak_t or not peak_b:
        return "unknown"
    ridge = peak_t * 1e12 / (peak_b * 1e9)  # FLOP/byte at the ridge point
    return "compute-bound" if intensity >= ridge else "memory-bound"


def roofline_rows(streams: Dict[int, List[Dict[str, Any]]],
                  data_wait_share: Optional[float] = None) -> List[Dict[str, Any]]:
    """Per-launch-group roofline rows from merged metrics streams.

    ``roofline`` records are cumulative per (host, group, sig) — kept
    latest-wins in stream order (mirroring the analyzer's pass_end
    dedupe), then hosts are summed per (group, sig)."""
    latest: Dict[tuple, Dict[str, Any]] = {}
    for host in sorted(streams):
        for rec in streams[host]:
            if rec.get("kind") != "roofline":
                continue
            latest[(host, rec.get("group"), rec.get("sig"))] = rec
    merged: Dict[tuple, Dict[str, Any]] = {}
    for (_h, group, sig), rec in latest.items():
        row = merged.setdefault((group, sig), {
            "group": group, "sig": sig, "launches": 0, "batches": 0,
            "exec_s": 0.0,
        })
        row["launches"] += int(rec.get("launches", 0))
        row["batches"] += int(rec.get("batches", 0))
        row["exec_s"] += float(rec.get("exec_s", 0.0))
        for k in ("flops_per_launch", "flops_analytic_per_launch",
                  "bytes_per_launch", "device_kind"):
            if k in rec:
                row[k] = rec[k]
    rows = []
    for (group, _sig), row in sorted(merged.items()):
        # FLOP basis: analytic when present (exact for scans), XLA's
        # cost analysis otherwise; intensity is always XLA/XLA — one
        # consistent basis for the ratio
        basis = row.get("flops_analytic_per_launch") or row.get("flops_per_launch")
        if basis and row["exec_s"] > 0:
            row["achieved_flops_per_s"] = basis * row["launches"] / row["exec_s"]
        xf, xb = row.get("flops_per_launch"), row.get("bytes_per_launch")
        if xf and xb:
            row["intensity"] = xf / xb
        row["bucket"] = classify(
            row.get("intensity"), row.get("device_kind", ""),
            data_wait_share,
        )
        rows.append(row)
    return rows


def totals_of(compiles: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Totals of an already-collected ``kind=compile`` record list —
    the ONE aggregation `paddle metrics`, `roofline`, and `compare` all
    share (callers that walked the streams themselves pass their list
    instead of re-scanning)."""
    return {
        "count": len(compiles),
        "trace_s": round(sum(float(c.get("trace_s", 0.0)) for c in compiles), 6),
        "compile_s": round(sum(float(c.get("compile_s", 0.0)) for c in compiles), 6),
        "cache_hits": sum(1 for c in compiles if c.get("cache_hit") is True),
        "cache_misses": sum(1 for c in compiles if c.get("cache_hit") is False),
    }


def compile_totals(streams: Dict[int, List[Dict[str, Any]]]) -> Dict[str, Any]:
    """Aggregate of every ``kind=compile`` record in the run: total
    trace/compile seconds and the persistent-cache hit split — the
    number a warm-restart claim is checked against."""
    compiles = [
        rec
        for host in sorted(streams)
        for rec in streams[host]
        if rec.get("kind") == "compile"
    ]
    return {"compiles": compiles, "totals": totals_of(compiles)}


def _last_data_wait_share(doc: Dict[str, Any]) -> Optional[float]:
    """Steady-state data-wait share: the analyzer's number for the last
    pass that has one (the host-bound gate)."""
    for row in reversed(doc.get("passes", [])):
        if "data_wait_share" in row:
            return float(row["data_wait_share"])
    return None


def roofline_doc(streams: Dict[int, List[Dict[str, Any]]]) -> Dict[str, Any]:
    # ONE analyzer pass over the streams: data-wait share and compile
    # totals both come out of the same doc (re-walking a multi-day
    # multi-host record set per number is real parse cost)
    from paddle_tpu.observability.analyze import analyze

    doc = analyze(streams)
    share = _last_data_wait_share(doc)
    return {
        "data_wait_share": share,
        "groups": roofline_rows(streams, data_wait_share=share),
        "compile_totals": doc.get("compile_totals") or totals_of([]),
    }


def _fmt(v, scale=1.0, fmt="{:.3g}", dash="-"):
    if v is None:
        return dash
    return fmt.format(v * scale)


def format_report(doc: Dict[str, Any]) -> str:
    lines = [
        f"{'group':<12} {'sig':<10} {'launches':>8} {'exec s':>9} "
        f"{'GFLOP/launch':>12} {'MB/launch':>10} {'GFLOP/s':>9} "
        f"{'FLOP/B':>7} {'bucket':>13}"
    ]
    for row in doc["groups"]:
        lines.append(
            f"{row['group']:<12} {row['sig']:<10} {row['launches']:>8} "
            f"{row['exec_s']:>9.3f} "
            f"{_fmt(row.get('flops_analytic_per_launch') or row.get('flops_per_launch'), 1e-9):>12} "
            f"{_fmt(row.get('bytes_per_launch'), 1e-6):>10} "
            f"{_fmt(row.get('achieved_flops_per_s'), 1e-9):>9} "
            f"{_fmt(row.get('intensity'), 1.0, '{:.2f}'):>7} "
            f"{row['bucket']:>13}"
        )
    t = doc["compile_totals"]
    lines.append("")
    lines.append(
        f"compiles: {t['count']} (trace {t['trace_s']:.3f}s + compile "
        f"{t['compile_s']:.3f}s, cache {t['cache_hits']} hit(s) / "
        f"{t['cache_misses']} miss(es))"
    )
    if doc.get("data_wait_share") is not None:
        lines.append(
            f"data-wait share (last pass): {doc['data_wait_share'] * 100:.1f}%"
        )
    if any(row["bucket"] == "unknown" for row in doc["groups"]):
        lines.append(
            "note: bucket 'unknown' = no cost analysis or no peak "
            "FLOP/bandwidth table for this device kind "
            "(ops/kernel_flops.py) — positions are never guessed"
        )
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="paddle roofline",
        description="per-launch-group roofline report from a run's "
                    "compile/cost telemetry",
    )
    p.add_argument("run_dir", help="run dir (or one metrics*.jsonl file)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit the analysis as JSON")
    args = p.parse_args(argv)

    from paddle_tpu.observability.analyze import load_run

    files = obs.metrics_files(args.run_dir)
    if not files:
        print(f"no metrics*.jsonl under {args.run_dir!r} "
              "(was the run started with --metrics_path / --save_dir?)",
              file=sys.stderr)
        return 1
    doc = roofline_doc(load_run(args.run_dir))
    if not doc["groups"] and not doc["compile_totals"]["count"]:
        print("no compile/roofline records in this run's telemetry "
              "(pre-compile-telemetry run, or it never finished a pass)",
              file=sys.stderr)
        return 1
    if args.as_json:
        print(json.dumps(doc, indent=2, default=str))
    else:
        print(f"# roofline: {', '.join(files)}")
        print(format_report(doc))
    return 0


if __name__ == "__main__":
    sys.exit(main())
