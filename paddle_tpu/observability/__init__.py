"""Unified run telemetry (doc/observability.md).

The reference framework's only observability was ``REGISTER_TIMER`` /
``StatSet`` log dumps and the BarrierStat straggler line — metrics lived
as unstructured log text, scraped back out with regexes. This package is
the structured replacement: every subsystem (trainer step loop, data
pipeline, checkpoint I/O, retry layer, fault injection, barrier skew)
emits into one per-host, schema-versioned ``metrics.jsonl`` stream, and
``spans.py`` upgrades ``stat_timer`` scopes into Chrome trace-event
spans. ``paddle metrics <run_dir>`` (analyze.py) reads it all back.
``compile_log.py`` adds per-launch-group compile telemetry and the
persistent compilation cache; ``costs.py`` turns XLA cost analysis into
``paddle roofline`` reports; ``compare.py`` diffs two runs with a
regression verdict (``paddle compare``); ``serving.py`` gives
generation the same treatment — request-lifecycle records, the
deterministic offered-load serve driver behind ``bench.py serve``, and
``paddle serve-report``.

Deliberately jax-free at import time: the supervisor and the analyzer
must work when the accelerator runtime is exactly what keeps crashing.
"""

from paddle_tpu.observability.metrics import (  # noqa: F401
    SCHEMA_VERSION,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    MetricsWriter,
    configure,
    configure_from_flags,
    emit,
    enabled,
    flush,
    metrics_files,
    read_records,
    read_tail,
    registry,
    validate_record,
)
from paddle_tpu.observability import spans  # noqa: F401
