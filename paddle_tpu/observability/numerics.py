"""Per-layer model-health telemetry + nonfinite blame
(``--numerics_log_period``).

``--nonfinite_policy=rollback`` (PR 2) recovers from divergence without
ever naming the layer that diverged, and nothing in the telemetry stack
answers "is this run *about* to diverge" — grad norms, update ratios,
and nonfinite counts are the standard early-warning signals and they
were simply not collected. Two pieces:

- **in-step health** — :func:`step_health` computes, per layer group,
  squared grad/param/update norms and a nonfinite-element count as one
  extra aux pytree INSIDE the existing jitted step (the grads and both
  parameter trees are already live there — no extra launch, no launch
  signature churn, recompiles stay 0 after warmup). The trainer holds
  the latest device tree and reads it back ONLY at
  ``--numerics_log_period`` boundaries (a tiny [n_layers, 4] transfer),
  emitting ``kind=numerics`` records with per-layer
  grad-norm / param-norm / update-ratio / nonfinite derived host-side
  (:func:`derive`).
- **nonfinite blame** — when ``--nonfinite_policy`` trips,
  :func:`blame_nonfinite` re-runs the poisoned batch in a per-layer
  checking mode (params first — a NaN weight is the commonest poison —
  then the forward layer by layer in topological order, then the
  backward via per-parameter grads) and names the FIRST layer producing
  a nonfinite value. The result rides the ``nonfinite`` record
  (``blame_layer``/``blame_phase``), the abort error message, and —
  through the metrics tail — the supervisor's ``crash_report.json``.

Module import is jax-free (the analyzers read ``kind=numerics`` records
without an accelerator runtime); jax is imported lazily inside the
functions the trainer calls from its jitted step builder.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Tuple

from paddle_tpu.utils.logging import logger

# epsilon guarding the update-ratio division: an all-zero (freshly
# zero-initialized) parameter block must read ratio ~||update||/eps,
# huge but finite, not a schema-breaking inf
EPS = 1e-12

__all__ = ["layer_groups", "step_health", "derive", "blame_nonfinite"]


# ------------------------------------------------------------- grouping


def param_owners(model) -> Dict[str, str]:
    """{parameter name: owning layer name} from the model config: a
    layer owns the parameters its inputs reference plus its bias.
    First-wins on shared parameters (``param_attr`` sharing) — the
    earliest layer in topological order is the blame anchor."""
    owner: Dict[str, str] = {}
    for layer in model.layers:
        for ic in layer.inputs:
            if ic.input_parameter_name:
                owner.setdefault(ic.input_parameter_name, layer.name)
        if layer.bias_parameter_name:
            owner.setdefault(layer.bias_parameter_name, layer.name)
    for sub in getattr(model, "sub_models", []) or []:
        for mem in getattr(sub, "memories", []) or []:
            pn = getattr(mem, "boot_bias_parameter_name", "")
            if pn:
                owner.setdefault(pn, sub.name)
    return owner


def layer_groups(model, param_names) -> Dict[str, List[str]]:
    """{layer name: [its parameter names]} over the given params.
    Parameters no layer claims (state tensors, exotic projections)
    group under their own name — param-level blame beats no blame.
    Deterministic ordering throughout: the group dict is insertion-
    ordered by the sorted param walk, so the health pytree's treedef is
    a pure function of the model (no recompiles from dict order)."""
    owner = param_owners(model)
    groups: Dict[str, List[str]] = {}
    for pn in sorted(param_names):
        groups.setdefault(owner.get(pn, pn), []).append(pn)
    return groups


# ------------------------------------------------------- in-step health

# component order of each layer's health vector (one [4] array per
# layer; the fused scan stacks them to [k, 4])
GRAD_SS, PARAM_SS, UPDATE_SS, NONFINITE = range(4)


def _grad_arrays(g) -> List[Any]:
    """The dense array views of one gradient leaf: the array itself, or
    a RowSparseGrad's occurrence rows (O(batch·seq), the only part that
    exists)."""
    if g is None:
        return []
    if hasattr(g, "dtype") and hasattr(g, "shape"):
        return [g]
    rows = getattr(g, "rows", None)
    if rows is not None:
        return [rows]
    vals = getattr(g, "values", None)
    return [vals] if vals is not None else []


def step_health(params, new_params, grads, groups):
    """Per-layer health vectors, computed with jnp ops so the whole
    thing fuses into the caller's jitted step: ``{layer: [grad_ss,
    param_ss, update_ss, nonfinite_count]}`` (squared sums — the cheap
    associative form; :func:`derive` takes the roots host-side). Shapes
    are static per batch signature, so enabling this adds work to the
    step but never a recompile."""
    import jax.numpy as jnp

    out = {}
    for layer, pnames in groups.items():
        gss = jnp.zeros((), jnp.float32)
        pss = jnp.zeros((), jnp.float32)
        uss = jnp.zeros((), jnp.float32)
        nf = jnp.zeros((), jnp.float32)
        for pn in pnames:
            p = params.get(pn)
            if p is None:
                continue
            pf = p.astype(jnp.float32)
            pss = pss + jnp.sum(pf * pf)
            np_ = new_params.get(pn)
            if np_ is not None:
                d = np_.astype(jnp.float32) - pf
                uss = uss + jnp.sum(d * d)
            for g in _grad_arrays(grads.get(pn)):
                gf = g.astype(jnp.float32)
                gss = gss + jnp.sum(gf * gf)
                nf = nf + jnp.sum((~jnp.isfinite(gf)).astype(jnp.float32))
        out[layer] = jnp.stack([gss, pss, uss, nf])
    return out


def derive(health: Dict[str, Any]) -> Tuple[Dict[str, Dict[str, float]],
                                            List[str], float]:
    """Host-side derivation from one device-fetched health tree:
    (per-layer ``{grad_norm, param_norm, update_ratio, nonfinite}``,
    layers with nonfinite gradients, global grad norm). Fused launches
    hand stacked [k, 4] vectors — the LAST batch of the launch is the
    reported one (the same batch the single-step path would report at
    this boundary)."""
    layers: Dict[str, Dict[str, float]] = {}
    nf_layers: List[str] = []
    total_gss = 0.0
    for name in sorted(health):
        v = health[name]
        row = [float(x) for x in (v[-1] if getattr(v, "ndim", 1) > 1 else v)]
        gss, pss, uss, nf = row[:4]
        # a nonfinite grad poisons its own norm — keep the count honest
        # and report the norm as-is (nan/inf serialize as strings)
        pn = math.sqrt(pss) if pss >= 0 else float("nan")
        layers[name] = {
            "grad_norm": math.sqrt(gss) if gss >= 0 else float(gss),
            "param_norm": pn,
            "update_ratio": (
                (math.sqrt(uss) if uss >= 0 else float(uss)) / (pn + EPS)
                if math.isfinite(pn) else float("nan")
            ),
            "nonfinite": int(nf) if math.isfinite(nf) else -1,
        }
        if nf > 0 or not math.isfinite(nf):
            nf_layers.append(name)
        if math.isfinite(gss):
            total_gss += gss
    return layers, nf_layers, math.sqrt(total_gss)


# ------------------------------------------------------ nonfinite blame


def _nonfinite_count(a) -> int:
    import numpy as np

    arr = np.asarray(a)
    if arr.dtype.kind not in "fc":
        return 0
    return int(arr.size - np.isfinite(arr).sum())


def blame_nonfinite(gm, model, params, in_args, rng=None) -> Optional[Dict[str, Any]]:
    """Re-run one poisoned batch in per-layer checking mode and name
    the first layer producing a nonfinite value.

    Three phases, cheapest-and-most-common first:

    1. **params** — a NaN already resident in a weight (the previous
       update applied a nonfinite grad) blames its owning layer without
       any compute;
    2. **forward** — run the graph eagerly and walk the layer outputs
       in topological (config) order; the first nonfinite activation
       names the layer;
    3. **backward** — forward was clean, so the poison was born in the
       gradient: per-parameter grads map back to layers, and the layer
       LATEST in forward order (first reached by backprop) is blamed.

    This is the cold recovery path (at most ``--max_nonfinite_steps``
    times per run), so it runs eagerly — no jit cache pollution, no
    recompile of the hot step. Never raises: blame that fails returns
    None and the policy proceeds without it."""
    try:
        owner = param_owners(model)
        layer_pos = {l.name: i for i, l in enumerate(model.layers)}
        # phase 1: poisoned parameters
        for pn in sorted(sorted(params),
                         key=lambda n: layer_pos.get(owner.get(n, n), 1 << 30)):
            bad = _nonfinite_count(params[pn])
            if bad:
                return {"layer": owner.get(pn, pn), "phase": "params",
                        "param": pn, "nonfinite": bad}
        # phase 2: forward activations, topological order
        outputs, _ = gm.forward(params, in_args, pass_type="train", rng=rng)
        for layer in model.layers:
            arg = outputs.get(layer.name)
            v = getattr(arg, "value", None)
            if v is None:
                continue
            bad = _nonfinite_count(v)
            if bad:
                return {"layer": layer.name, "phase": "forward",
                        "nonfinite": bad}
        # phase 3: gradients (dense — sparse row sets don't matter for
        # blame, and dense grads exist for every parameter)
        _loss, grads, _outs, _updates = gm.grad_fn(sparse=False)(
            params, in_args, rng
        )
        worst: Optional[Tuple[int, str, str, int]] = None
        for pn, g in grads.items():
            bad = sum(_nonfinite_count(a) for a in _grad_arrays(g))
            if not bad:
                continue
            layer = owner.get(pn, pn)
            pos = layer_pos.get(layer, -1)
            if worst is None or pos > worst[0]:
                worst = (pos, layer, pn, bad)
        if worst is not None:
            return {"layer": worst[1], "phase": "backward",
                    "param": worst[2], "nonfinite": worst[3]}
        return None
    except Exception as e:
        logger.debug("nonfinite blame re-run failed: %s", e, exc_info=True)
        return None
