"""``paddle metrics <run_dir>`` — read the telemetry back.

Merges the per-host ``metrics*.jsonl`` streams of one run dir, prints a
per-pass aggregate table (step-time p50/p99, data-wait share, checkpoint
durations, nonfinite/retry/fault counters), flags stragglers across
hosts (reusing ``utils/barrier.summarize_host_stats`` — the BarrierStat
attribution, now fed from structured records instead of log lines) and
stalls, and emits the whole analysis as JSON with ``--json`` for
tooling. jax-free: it must run on a dev box against a run dir copied
off a pod.

Usage::

    paddle metrics <run_dir | metrics.jsonl> [--json] [--tail N] [--follow]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Dict, Iterator, List, Optional

from paddle_tpu.observability import metrics as obs

# data-wait share of pass time above which the run is data-bound — the
# analyzer's warning AND the roofline host-bound bucket (costs.py)
# classify against this ONE constant so the two tools cannot disagree
DATA_BOUND_SHARE = 0.5

# counters whose per-pass DELTA the table surfaces (snapshot keys from
# MetricsRegistry — cumulative in the records, differenced here)
_COUNTER_COLS = (
    ("data.prefetch_wait_s", "data_wait_s"),
    ("data.bad_samples", "bad_samples"),
    ("retry.attempts", "retries"),
    ("faults.fired", "faults"),
    ("nonfinite.events", "nonfinite"),
    # async checkpointing (doc/performance.md): the background write
    # time plus queued saves dropped by --ckpt_inflight_limit (what the
    # step loop actually waited — ckpt_blocked_s — is attributed from
    # the op="snapshot" checkpoint records instead: pass-end saves run
    # AFTER the pass_end counter snapshot, so a counter delta would
    # land each save's cost one pass late)
    ("ckpt.write_s", "ckpt_write_s"),
    ("ckpt.async_dropped", "ckpt_dropped"),
)


def load_run(run_dir: str) -> Dict[Any, List[Dict[str, Any]]]:
    """{stream key: [records in stream order]} for one run dir.

    A FLEET run dir (router stream + ``replica-*/`` child streams,
    discovered via :func:`metrics.fleet_stream_dirs`) merges every
    stream: keys become ``"<stream>/<host>"`` strings so one replica's
    ``run_start`` cannot supersede another replica's windows, and
    replica-less serve records are stamped with their stream's replica
    name for the merged per-rung tables. Single-stream dirs keep plain
    int host keys (and their exact analysis shape)."""
    dirs = obs.fleet_stream_dirs(run_dir)
    streams: Dict[Any, List[Dict[str, Any]]] = {}
    base = os.path.normpath(run_dir)
    for d in dirs:
        label = ("" if os.path.normpath(d) == base
                 else os.path.basename(os.path.normpath(d)))
        for path in obs.metrics_files(d):
            for rec in obs.read_records(path):
                host = int(rec.get("host", 0))
                if len(dirs) == 1:
                    key: Any = host
                else:
                    key = f"{label or 'router'}/{host}"
                    if (label and not rec.get("replica")
                            and rec.get("kind") in ("serve_window",
                                                    "request", "span")):
                        rec["replica"] = label
                streams.setdefault(key, []).append(rec)
    return streams


def _counter(rec: Dict[str, Any], name: str) -> float:
    v = (rec.get("counters") or {}).get(name, 0.0)
    if isinstance(v, dict):  # histogram snapshot: the count is the tally
        return float(v.get("count", 0.0))
    return float(v or 0.0)


def analyze(streams: Dict[int, List[Dict[str, Any]]]) -> Dict[str, Any]:
    """Aggregate merged streams into the analysis document.

    Re-run passes are first-class input: a supervised restart or a
    rollback re-run appends a SECOND ``pass_end`` for the same (host,
    pass) to the same stream, so records are deduplicated latest-wins
    (stream order) per host before aggregation — otherwise samples
    double-count and the hosts divisor inflates."""
    hosts = sorted(streams)
    checkpoints: List[Dict[str, Any]] = []
    invalid = 0
    # {host: {pass: latest pass_end record}} — latest-wins dedupe
    per_host_pass: Dict[int, Dict[int, Dict[str, Any]]] = {}
    last_skew: Optional[Dict[str, Any]] = None
    # per-host, last-state: a run_start UN-ends its host (a restarted/
    # rerun process appending to the same stream owes a fresh run_end —
    # the same rule `--follow`'s stop condition applies), and the run
    # counts as ended while any host's latest epoch completed
    ended_hosts: set = set()
    hangs: List[Dict[str, Any]] = []
    restarts: List[Dict[str, Any]] = []
    compiles: List[Dict[str, Any]] = []
    ooms: List[Dict[str, Any]] = []
    # memory plane: per-pass worst HBM peak / host RSS (the `hbm pk`
    # column) + the last live snapshot per host; numerics plane: layers
    # that EVER produced a nonfinite gradient, per pass and overall
    # (the `nf lyr` column and the compare surface)
    mem_by_pass: Dict[int, Dict[str, float]] = {}
    mem_last: Dict[int, Dict[str, Any]] = {}
    # sparse-table plane: latest-wins per (host, pass) like pass_end,
    # then hosts are summed per pass (each host touches its own rows)
    sparse_by: Dict[tuple, Dict[str, Any]] = {}
    numerics_count = 0
    nf_layers_by_pass: Dict[int, set] = {}
    nf_layers_all: set = set()
    # request records dedupe by (host, id) — the SAME latest-wins
    # discipline as the windows: a rerun appending to the default serve
    # run dir re-emits the same request ids, and counting every record
    # would report 2x requests next to a rung table summing to half
    serve_request_ids: set = set()
    # hosts whose CURRENT epoch has driver requests (rung >= 0): a serve
    # DRIVER run owes a run_end even when it died before its first
    # serve_window; oneshot records (rung -1, the embedding API) owe
    # nothing, and a superseded epoch's driver doesn't haunt the next
    serve_driver_hosts: set = set()
    # serve_window rollups, latest-wins per (host, engine, rung) like
    # pass_end — a restarted serve driver re-emits its rungs into the
    # same stream, while a stream carrying BOTH engines' sweeps (the
    # A/B in one dir) must keep both ladders, not clobber the first
    serve_windows_by: Dict[tuple, Dict[str, Any]] = {}

    for host in hosts:
        for rec in streams[host]:
            if obs.validate_record(rec):
                invalid += 1
                continue
            kind = rec.get("kind")
            if kind == "run_start":
                # a new sweep appended to a reused serve run dir (or a
                # relaunched driver) supersedes the host's earlier serve
                # telemetry WHOLESALE: rung-keyed latest-wins alone would
                # let a longer previous ladder leave ghost rungs behind
                for k in [k for k in serve_windows_by if k[0] == host]:
                    del serve_windows_by[k]
                serve_request_ids = {
                    k for k in serve_request_ids if k[0] != host
                }
                ended_hosts.discard(host)
                serve_driver_hosts.discard(host)
            elif kind == "run_end":
                ended_hosts.add(host)
            elif kind == "checkpoint":
                checkpoints.append(rec)
            elif kind == "barrier_skew":
                last_skew = rec
            elif kind == "hang":
                hangs.append(rec)
            elif kind == "restart":
                restarts.append(rec)
            elif kind == "compile":
                compiles.append(rec)
            elif kind == "oom":
                ooms.append(rec)
            elif kind == "memory":
                mem_last[host] = rec
                p = rec.get("pass")
                if isinstance(p, int):
                    row = mem_by_pass.setdefault(p, {})
                    for src in ("hbm_peak_bytes", "host_rss_bytes"):
                        if isinstance(rec.get(src), (int, float)):
                            row[src] = max(
                                float(row.get(src, 0.0)), float(rec[src])
                            )
            elif kind == "numerics":
                numerics_count += 1
                p = rec.get("pass")
                nf = set(rec.get("nonfinite_layers") or [])
                nf_layers_all |= nf
                if isinstance(p, int):
                    nf_layers_by_pass.setdefault(p, set()).update(nf)
            elif kind == "request":
                serve_request_ids.add((host, rec.get("id")))
                if rec.get("rung", -1) >= 0:
                    serve_driver_hosts.add(host)
            elif kind == "serve_window":
                # pipeline joins the key: a one-dir pipelined-vs-
                # blocking A/B re-runs the same (engine, rung) ladder
                # and must keep BOTH sweeps, like the both-engines case;
                # replica/replicas join it too — a fleet rung carries N
                # per-replica windows PLUS their merged (replicas=N)
                # rollup, all legitimately at the same (engine, rung)
                serve_windows_by[
                    (host, rec.get("engine", "static"),
                     str(rec.get("pipeline") or ""),
                     str(rec.get("replica") or ""),
                     int(rec.get("replicas") or 0), rec.get("rung"))
                ] = rec
            elif kind == "sparse":
                p = rec.get("pass")
                if isinstance(p, int):
                    sparse_by[(host, p)] = rec
            elif kind == "pass_end":
                p = int(rec.get("pass", -1))
                per_host_pass.setdefault(host, {})[p] = rec
    serve_windows = [
        serve_windows_by[k] for k in sorted(
            serve_windows_by,
            key=lambda k: (k[1] if k[1] is not None else -1, k[2],
                           k[4], k[3],
                           k[5] if isinstance(k[5], int) else -1, k[0]),
        )
    ]

    passes: Dict[int, Dict[str, Any]] = {}
    per_host_prev: Dict[int, Dict[str, float]] = {}
    # per-pass per-host (mean, p99) step times for straggler attribution
    host_steps: Dict[int, Dict[int, tuple]] = {}
    for host in hosts:
        prev_counters: Dict[str, float] = {}
        # (count, count·mean) of the pack_threads_busy histogram at the
        # previous pass_end — the snapshot is run-cumulative, so the
        # per-pass mean must come from the delta like the counter cols
        prev_pack = (0.0, 0.0)
        for p in sorted(per_host_pass.get(host, {})):
            rec = per_host_pass[host][p]
            row = passes.setdefault(p, {"pass": p, "samples": 0, "hosts": 0})
            row["hosts"] += 1
            row["samples"] += int(rec.get("samples", 0))
            if row["hosts"] == 1:
                # representative scalars come from the LOWEST host with
                # this pass (host 0 normally) — samples_per_sec/mfu
                # genuinely differ per host, and last-host-wins would
                # label the pass with an arbitrary host's number
                for src in ("AvgCost", "CurrentCost", "samples_per_sec",
                            "model_tflops_per_sec", "mfu"):
                    if src in rec:
                        row[src] = rec[src]
            # worst-across-hosts per pass: step-time quantiles and the
            # hangwatch's max progress age (a near-miss stall on ANY
            # host is the number an operator tuning --step_hang_timeout
            # needs)
            for k in ("step_time_p50_s", "step_time_p99_s",
                      "progress_age_max_s"):
                if k in rec:
                    row[k] = max(float(row.get(k, 0.0)), float(rec[k]))
            pass_time = float(rec.get("pass_time_s", 0.0))
            row["pass_time_s"] = max(
                float(row.get("pass_time_s", 0.0)), pass_time
            )
            cur = {name: _counter(rec, name) for name, _ in _COUNTER_COLS}
            for name, col in _COUNTER_COLS:
                d = cur[name] - prev_counters.get(name, 0.0)
                row[col] = row.get(col, 0.0) + max(d, 0.0)
            prev_counters = cur
            # packer-pool utilization: mean packers busy at each batch
            # handoff THIS pass (delta of the cumulative histogram) —
            # worst host wins, like the step quantiles
            pack = (rec.get("counters") or {}).get("data.pack_threads_busy")
            if isinstance(pack, dict) and pack.get("count"):
                cnt = float(pack["count"])
                tot = cnt * float(pack.get("mean", 0.0))
                d_cnt, d_tot = cnt - prev_pack[0], tot - prev_pack[1]
                prev_pack = (cnt, tot)
                if d_cnt > 0:
                    row["pack_busy_mean"] = max(
                        float(row.get("pack_busy_mean", 0.0)),
                        round(d_tot / d_cnt, 4),
                    )
            if row.get("pass_time_s", 0.0) > 0:
                share = row.get("data_wait_s", 0.0) / (
                    row["pass_time_s"] * max(row["hosts"], 1)
                )
                row["data_wait_share"] = round(min(share, 1.0), 4)
            if "step_time_mean_s" in rec:
                host_steps.setdefault(p, {})[host] = (
                    float(rec["step_time_mean_s"]),
                    float(rec.get("step_time_p99_s", rec["step_time_mean_s"])),
                )
        per_host_prev[host] = prev_counters

    # fold the memory/numerics planes into the pass rows (worst host,
    # like the step quantiles)
    for p, mrow in mem_by_pass.items():
        if p in passes:
            passes[p].update(mrow)
    for p, layer_set in nf_layers_by_pass.items():
        if p in passes:
            passes[p]["nf_layers"] = len(layer_set)
    # sparse plane: hosts summed per pass (rows_touched and rows/s are
    # per-host quantities; reshard events take the max — every host
    # reports the same restore-time count)
    for (_h, p), srec in sorted(sparse_by.items()):
        if p not in passes:
            continue
        row = passes[p]
        for k in ("rows_touched", "unique_rows", "gather_bytes",
                  "scatter_bytes", "sparse_rows_per_sec"):
            if isinstance(srec.get(k), (int, float)):
                row[k] = row.get(k, 0) + srec[k]
        if isinstance(srec.get("reshard_events"), int):
            row["reshard_events"] = max(
                int(row.get("reshard_events", 0)), srec["reshard_events"]
            )

    # straggler attribution: feed the gathered per-host step stats of the
    # LAST pass with full coverage through the BarrierStat formatter
    straggler = None
    if len(hosts) > 1 and host_steps:
        import numpy as np

        from paddle_tpu.utils.barrier import summarize_host_stats

        for p in sorted(host_steps, reverse=True):
            per_host = host_steps[p]
            if len(per_host) == len(hosts):
                table = np.asarray(
                    [per_host.get(h, (float("nan"),) * 2) for h in hosts]
                )
                straggler = {"pass": p, "line": summarize_host_stats(table)}
                break

    # step-loop checkpoint-stall attribution, from the checkpoint
    # records themselves: op="snapshot" records exist exactly when
    # --async_checkpoint is on and their duration is what the step loop
    # actually waited (ckpt_blocked_s); op="save" blocks the step loop
    # only when async checkpointing is OFF (with it on, saves are the
    # background writer's time)
    async_ckpt = any(c.get("op") == "snapshot" for c in checkpoints)
    # latest-wins per (host, pass, op, step), mirroring the pass_end
    # dedupe: a supervised restart or rollback re-run re-saves the same
    # save point, and summing every attempt would charge one run's
    # pass_time_s with N runs' worth of blocked seconds. Mid-pass
    # periodic saves (--saving_period_by_batches) of one pass carry
    # distinct `step`s and stay individually counted
    latest_dur: Dict[tuple, float] = {}
    for c in checkpoints:
        if isinstance(c.get("pass"), int) and c.get("op") in ("save", "snapshot"):
            latest_dur[(c.get("host"), c["pass"], c["op"], c.get("step"))] = (
                float(c.get("duration_s", 0.0))
            )
    sync_save_s: Dict[int, float] = {}
    snap_s: Dict[int, float] = {}
    for (_h, p_ckpt, op, _s), dur in latest_dur.items():
        tgt = sync_save_s if op == "save" else snap_s
        tgt[p_ckpt] = tgt.get(p_ckpt, 0.0) + dur
    for p, blocked in snap_s.items():
        if p in passes:
            passes[p]["ckpt_blocked_s"] = round(blocked, 6)

    warnings: List[str] = []
    for p in sorted(passes):
        row = passes[p]
        if row.get("data_wait_share", 0.0) > DATA_BOUND_SHARE:
            warnings.append(
                f"pass {p}: data-bound — the step loop spent "
                f"{row['data_wait_share'] * 100:.0f}% of the pass waiting "
                "on the provider (grow pool_size / check input storage)"
            )
        pass_time = row.get("pass_time_s", 0.0)
        if not async_ckpt and pass_time > 0:
            blocked = sync_save_s.get(p, 0.0)
            if blocked / pass_time > 0.1:
                warnings.append(
                    f"pass {p}: checkpoint-bound — synchronous saves "
                    f"blocked the step loop {blocked / pass_time * 100:.0f}% "
                    "of the pass (consider --async_checkpoint)"
                )
        if async_ckpt and pass_time > 0:
            blocked = row.get("ckpt_blocked_s", 0.0)
            if blocked / pass_time > 0.1:
                warnings.append(
                    f"pass {p}: snapshot-heavy — async checkpointing still "
                    f"blocked the step loop {blocked / pass_time * 100:.0f}% "
                    "of the pass on device→host copies (save less often or "
                    "shrink the model state)"
                )
        if row.get("ckpt_dropped", 0) > 0:
            warnings.append(
                f"pass {p}: {int(row['ckpt_dropped'])} queued async "
                "checkpoint save(s) dropped (superseded; raise "
                "--ckpt_inflight_limit or save less often)"
            )
        for col, label in (("nonfinite", "non-finite loss event(s)"),
                           ("faults", "injected fault firing(s)"),
                           ("bad_samples", "malformed sample(s) skipped")):
            if row.get(col, 0) > 0:
                warnings.append(f"pass {p}: {int(row[col])} {label}")
    for h in hangs:
        warnings.append(
            f"hang detected on host {h.get('host', '?')} at pass "
            f"{h.get('pass', '?')} step {h.get('step', '?')}: no progress "
            f"for {h.get('age_s', '?')}s (exit 19; forensics in "
            f"{h.get('report', 'hang_report.json')})"
        )
    for o in ooms:
        warnings.append(
            f"OOM on host {o.get('host', '?')} at pass {o.get('pass', '?')} "
            f"step {o.get('step', '?')} (exit 20; pre-mortem in "
            f"{o.get('report', 'oom_report.json')} — "
            "`paddle memory <run_dir>` renders it)"
        )
    if nf_layers_all:
        warnings.append(
            "nonfinite gradients observed in layer(s): "
            + ", ".join(sorted(nf_layers_all))
        )
    if last_skew is not None and last_skew.get("line"):
        warnings.append(f"barrier skew: {last_skew['line']}")
    # oneshot request records (the embedding API's SequenceGenerator —
    # no driver, so no run_end is ever owed) must not trip the crash
    # heuristic; driver streams (passes, serve windows, or rung>=0
    # request records — a serve run killed before its first window) do
    run_ended = bool(ended_hosts)
    if (passes or serve_windows or serve_driver_hosts) and not run_ended:
        warnings.append(
            "stream ends without a run_end record — the run crashed, was "
            "killed, or is still going"
        )
    if invalid:
        warnings.append(f"{invalid} record(s) failed schema validation")

    # restart latency (ROADMAP item 5 groundwork): the measured numbers
    # heartbeat-grace and crash-loop windows should be tuned from — the
    # WORST observed restore and time-to-first-step across hosts/rounds
    restart_latency = None
    if restarts:
        restart_latency = {
            "rounds": len(restarts),
            "restore_s_max": max(
                float(r.get("restore_s", 0.0)) for r in restarts
            ),
            "time_to_first_step_s_max": max(
                float(r.get("time_to_first_step_s", 0.0)) for r in restarts
            ),
        }

    # compile-cost totals (doc/observability.md "Compile telemetry"):
    # every (re)compile is a record, so the totals are exact — the
    # numbers `paddle compare` diffs and a warm-restart claim is
    # checked against. One aggregation, shared with `paddle roofline`
    # (lazy import: costs imports this module inside a function too).
    compile_totals = None
    if compiles:
        from paddle_tpu.observability.costs import totals_of

        compile_totals = totals_of(compiles)

    # serving telemetry (doc/observability.md "Serving telemetry"): the
    # per-pass table has nothing to say about a serve run — point at the
    # dedicated analyzer instead of printing an empty table silently
    serve = None
    if serve_request_ids or serve_windows:
        serve = {
            "requests": len(serve_request_ids),
            "windows": len(serve_windows),
            "rungs": len({w.get("rung") for w in serve_windows}),
        }
        # fleet runs only — single-stream serve JSON keeps its shape
        replicas = sorted({str(w.get("replica")) for w in serve_windows
                           if w.get("replica")})
        if replicas:
            serve["replicas"] = replicas

    # memory/numerics planes (doc/observability.md "Memory & numerics
    # telemetry") — None when the run predates them, so old-run JSON
    # output keeps its shape
    memory = {"last": mem_last} if mem_last else None
    numerics = (
        {"records": numerics_count,
         "nonfinite_layers": sorted(nf_layers_all)}
        if numerics_count else None
    )

    return {
        "hosts": hosts,
        "passes": [passes[p] for p in sorted(passes)],
        "checkpoints": checkpoints,
        "compiles": compiles,
        "compile_totals": compile_totals,
        "restarts": restarts,
        "restart_latency": restart_latency,
        "memory": memory,
        "numerics": numerics,
        "ooms": ooms,
        "serve": serve,
        "serve_windows": serve_windows,
        "counters": {h: per_host_prev.get(h, {}) for h in hosts},
        "straggler": straggler,
        "barrier_skew": last_skew,
        "hangs": hangs,
        "run_ended": run_ended,
        "invalid_records": invalid,
        "warnings": warnings,
    }


def _fmt_table(doc: Dict[str, Any]) -> str:
    # the age column (hangwatch's max progress age per pass, worst host)
    # only appears when some record carried it — telemetry from runs
    # without --step_hang_timeout keeps the old table shape
    with_age = any("progress_age_max_s" in r for r in doc["passes"])
    # async-checkpoint / packer-pool columns only appear when some record
    # carried them — telemetry from runs without the overlap knobs keeps
    # the old table shape
    with_ckpt = any(r.get("ckpt_blocked_s", 0.0) > 0 for r in doc["passes"])
    with_pack = any("pack_busy_mean" in r for r in doc["passes"])
    # memory/numerics columns: per-pass worst HBM peak (GB — absent on
    # backends without allocator stats, where records carry RSS only)
    # and the count of layers with nonfinite gradients that pass
    with_hbm = any("hbm_peak_bytes" in r for r in doc["passes"])
    with_nf_layers = any("nf_layers" in r for r in doc["passes"])
    # sparse rows/s column: only when some pass carried a kind=sparse
    # record (runs without sparse tables keep the old table shape)
    with_sparse = any("sparse_rows_per_sec" in r for r in doc["passes"])
    header = (
        f"{'pass':>5} {'samples':>9} {'AvgCost':>10} {'p50 ms':>8} "
        f"{'p99 ms':>8} {'data-wait':>9} {'nf':>4} {'retry':>5} {'fault':>5}"
    )
    if with_age:
        header += f" {'age s':>6}"
    if with_ckpt:
        header += f" {'ckpt blk s':>10}"
    if with_pack:
        header += f" {'pack busy':>9}"
    if with_hbm:
        header += f" {'hbm pk':>8}"
    if with_nf_layers:
        header += f" {'nf lyr':>6}"
    if with_sparse:
        header += f" {'rows/s':>9}"
    lines = [header]
    for row in doc["passes"]:
        line = (
            f"{row['pass']:>5} {row.get('samples', 0):>9} "
            f"{row.get('AvgCost', float('nan')):>10.5g} "
            f"{row.get('step_time_p50_s', 0.0) * 1e3:>8.2f} "
            f"{row.get('step_time_p99_s', 0.0) * 1e3:>8.2f} "
            f"{row.get('data_wait_share', 0.0) * 100:>8.1f}% "
            f"{int(row.get('nonfinite', 0)):>4} "
            f"{int(row.get('retries', 0)):>5} "
            f"{int(row.get('faults', 0)):>5}"
        )
        if with_age:
            line += f" {row.get('progress_age_max_s', 0.0):>6.2f}"
        if with_ckpt:
            line += f" {row.get('ckpt_blocked_s', 0.0):>10.4f}"
        if with_pack:
            line += f" {row.get('pack_busy_mean', 0.0):>9.2f}"
        if with_hbm:
            hbm = row.get("hbm_peak_bytes")
            line += f" {hbm / 1e9:>7.2f}G" if hbm is not None else f" {'-':>8}"
        if with_nf_layers:
            line += f" {int(row.get('nf_layers', 0)):>6}"
        if with_sparse:
            rps = row.get("sparse_rows_per_sec")
            line += (f" {rps:>9.3g}" if rps is not None else f" {'-':>9}")
        lines.append(line)
    if doc["checkpoints"]:
        lines.append("")
        lines.append(f"{'checkpoint':<10} {'pass':>5} {'secs':>8} {'MB':>9}")
        for c in doc["checkpoints"]:
            lines.append(
                f"{c.get('op', '?'):<10} {c.get('pass', -1):>5} "
                f"{c.get('duration_s', 0.0):>8.3f} "
                f"{c.get('bytes', 0) / 1e6:>9.2f}"
            )
    if doc.get("compiles"):
        # one row per launch-group (re)compile: where the trace/compile
        # seconds went and whether the persistent cache absorbed the
        # XLA half (`--compile_cache_dir`)
        lines.append("")
        lines.append(
            f"{'compile':<12} {'sig':<10} {'pass':>5} {'trace s':>8} "
            f"{'compile s':>9} {'cache':>6} {'GFLOP':>8}"
        )
        for c in doc["compiles"]:
            hit = c.get("cache_hit")
            flops = c.get("flops_analytic") or c.get("flops")
            lines.append(
                f"{c.get('group', '?'):<12} {c.get('sig', '?'):<10} "
                f"{c.get('pass', -1):>5} {c.get('trace_s', 0.0):>8.3f} "
                f"{c.get('compile_s', 0.0):>9.3f} "
                f"{'hit' if hit is True else 'miss' if hit is False else '-':>6} "
                f"{flops / 1e9 if flops else 0.0:>8.3g}"
            )
        t = doc.get("compile_totals") or {}
        if t:
            lines.append(
                f"compile totals: {t['count']} compilation(s), trace "
                f"{t['trace_s']:.3f}s + compile {t['compile_s']:.3f}s, "
                f"cache {t['cache_hits']} hit(s) / {t['cache_misses']} "
                "miss(es)"
            )
    if doc.get("restarts"):
        # one row per (re)start: restore cost vs full time-to-first-step
        # (restore + trace + compile + step 1) — the gap between them is
        # startup work a checkpoint cannot shrink. `resumed` separates
        # cold starts from checkpoint restores.
        lines.append("")
        lines.append(
            f"{'restart':<8} {'host':>4} {'pass':>5} {'restore s':>9} "
            f"{'ttfs s':>8} {'resumed':>7}"
        )
        for i, r in enumerate(doc["restarts"]):
            lines.append(
                f"{i:<8} {r.get('host', 0):>4} {r.get('pass', -1):>5} "
                f"{r.get('restore_s', 0.0):>9.3f} "
                f"{r.get('time_to_first_step_s', 0.0):>8.3f} "
                f"{'yes' if r.get('resumed') else 'no':>7}"
            )
        lat = doc.get("restart_latency") or {}
        if lat:
            lines.append(
                f"restart latency: worst restore "
                f"{lat['restore_s_max']:.3f}s, worst time-to-first-step "
                f"{lat['time_to_first_step_s_max']:.3f}s over "
                f"{lat['rounds']} round(s) — tune --heartbeat_startup_grace "
                "and crash-loop windows above the ttfs number"
            )
    if doc.get("memory"):
        lines.append("")
        last = doc["memory"]["last"]
        parts = []
        for h in sorted(last):
            rec = last[h]
            peak = rec.get("hbm_peak_bytes")
            parts.append(
                f"host {h}: "
                + (f"hbm peak {peak / 1e9:.2f} GB, " if peak is not None else "")
                + f"rss {rec.get('host_rss_bytes', 0) / 1e9:.2f} GB"
            )
        lines.append(
            "memory telemetry: " + "; ".join(parts)
            + " — `paddle memory <run_dir>` for the per-launch-group table"
        )
    if doc.get("numerics"):
        n = doc["numerics"]
        lines.append("")
        line = f"numerics telemetry: {n['records']} record(s)"
        if n["nonfinite_layers"]:
            line += (
                f", nonfinite gradients in: "
                + ", ".join(n["nonfinite_layers"])
            )
        lines.append(line)
    if doc.get("serve"):
        s = doc["serve"]
        lines.append("")
        line = (
            f"serve telemetry: {s['requests']} request record(s), "
            f"{s['windows']} window(s) over {s['rungs']} offered-load "
            "rung(s)"
        )
        if s["windows"]:
            # serve-report needs windows — don't point at a tool that
            # would exit 1 on an oneshot-only (embedding API) stream
            line += (" — `paddle serve-report <run_dir>` for the "
                     "latency/goodput table")
        lines.append(line)
        wins = doc.get("serve_windows") or []
        if any(w.get("replica") for w in wins):
            # fleet run: merged per-rung view with a replica column
            # (replica-stamped rows from the child streams, plus any
            # replicas=N merged rollups labelled "merged")
            lines.append(
                f"{'rung':>4} {'replica':<12} {'rps':>7} {'completed':>9} "
                f"{'p99 s':>8} {'goodput':>9}"
            )
            for w in wins:
                lat = w.get("latency") or {}
                name = str(w.get("replica") or
                           ("merged" if w.get("replicas") else "-"))
                lines.append(
                    f"{w.get('rung') or 0:>4} {name:<12} "
                    f"{float(w.get('offered_rps') or 0.0):>7.2f} "
                    f"{int(w.get('completed') or 0):>9} "
                    f"{float(lat.get('p99') or 0.0):>8.4f} "
                    f"{float(w.get('goodput_tok_s') or 0.0):>9.1f}"
                )
    if doc["straggler"] and doc["straggler"].get("line"):
        lines.append("")
        lines.append(doc["straggler"]["line"])
    if doc["warnings"]:
        lines.append("")
        for w in doc["warnings"]:
            lines.append(f"! {w}")
    return "\n".join(lines)


def follow(run_dir: str, poll_s: float = 0.5,
           max_polls: Optional[int] = None,
           poll_boundaries: bool = False,
           with_stream: bool = False) -> Iterator[Any]:
    """Live-tail every ``metrics*.jsonl`` stream of a run dir.

    Yields each newly appended record in file order, re-discovering
    per-host stream files as they appear (a late host joining mid-run,
    or a fleet replica's ``replica-*/`` stream dir materializing after
    the router's — :func:`metrics.fleet_stream_dirs` re-runs every
    poll). Torn-tail tolerant like :func:`metrics.read_records`: only
    complete (newline-terminated) lines are consumed — a partially
    flushed tail stays buffered in the file until its newline lands, so
    a record is never yielded twice or half-parsed. ``max_polls``
    bounds the scan loop for tests; the CLI polls until interrupted or
    ``run_end``. ``poll_boundaries=True`` additionally yields ``None``
    after each full scan over every stream — the only safe point to
    decide "all observed hosts are done" (mid-scan, later hosts' files
    are still unread). ``with_stream=True`` yields ``(stream_label,
    record)`` pairs instead — label ``""`` for the run dir's own
    streams, the subdir name for discovered replica streams — so the
    CLI can tell the router's ``run_end`` from a replica's."""
    offsets: Dict[str, int] = {}
    polls = 0
    base = os.path.normpath(run_dir)
    while True:
        for d in obs.fleet_stream_dirs(run_dir):
            label = ("" if os.path.normpath(d) == base
                     else os.path.basename(os.path.normpath(d)))
            for path in obs.metrics_files(d):
                pos = offsets.get(path, 0)
                try:
                    if os.path.getsize(path) < pos:
                        # file shrank: truncated/recreated (run dir
                        # reused) — restart this stream from the top
                        # instead of waiting forever past its EOF
                        pos = offsets[path] = 0
                    with open(path) as f:
                        f.seek(pos)
                        data = f.read()
                except OSError:
                    continue
                end = data.rfind("\n")
                if end < 0:
                    continue  # nothing complete yet (or a torn tail)
                offsets[path] = pos + end + 1
                # same torn-line tolerance policy as every reader
                for rec in obs.parse_record_lines(data[:end]):
                    yield (label, rec) if with_stream else rec
        polls += 1
        if poll_boundaries:
            yield None
        if max_polls is not None and polls >= max_polls:
            return
        time.sleep(poll_s)


def _follow_cli(run_dir: str) -> int:
    """``paddle metrics --follow``: print each new record as a JSON line
    (tail -f for the telemetry stream) until the run ends or ^C. A pod
    run has one stream per host, each with its own ``run_end`` — the
    tail stops only once every OBSERVED host has COMPLETED (hosts are
    tracked from the records themselves — stream-file counts can
    mismatch host ids when a run dir is reused across topologies): a
    ``status="preempted"`` run_end means the supervisor is about to
    relaunch into the same stream, and a later ``run_start`` from a
    host un-ends it. Hosts that crash without a run_end keep the tail
    alive (^C to stop) — silence is not completion.

    Fleet run dirs (any ``replica-*/`` stream discovered) change the
    stop rule: replicas come and go — a killed replica's stream never
    completes and a restarted one re-opens — so only the ROUTER's own
    ``run_end status="completed"`` (the run dir's root stream, which
    the router writes last, after every child is reaped) ends the
    tail."""
    seen: set = set()      # (stream_label, host) pairs
    ended: set = set()
    fleet = False
    try:
        for item in follow(run_dir, poll_boundaries=True,
                           with_stream=True):
            if item is None:
                # full scan over every stream done — the only safe
                # point to conclude: mid-scan, later hosts' files are
                # still unread and would look "never seen"
                if fleet:
                    if any(key[0] == "" for key in ended):
                        print("# router run_end — fleet run complete",
                              file=sys.stderr)
                        return 0
                elif seen and ended >= seen:
                    print("# run_end on every observed host — complete",
                          file=sys.stderr)
                    return 0
                continue
            label, rec = item
            print(json.dumps(rec, default=str), flush=True)
            key = (label, rec.get("host", 0))
            kind = rec.get("kind")
            seen.add(key)
            fleet = fleet or label.startswith("replica-")
            if kind == "run_end" and rec.get("status") == "completed":
                ended.add(key)
            elif kind == "run_start":
                ended.discard(key)
    except KeyboardInterrupt:
        return 0
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="paddle metrics",
        description="summarize a run's metrics.jsonl telemetry",
    )
    p.add_argument("run_dir", help="run dir (or one metrics*.jsonl file)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit the full analysis as JSON")
    p.add_argument("--tail", type=int, default=0, metavar="N",
                   help="also print the last N raw records per host")
    p.add_argument("--follow", action="store_true",
                   help="live-tail the stream: print each new record as "
                        "a JSON line until run_end or ^C (long runs can "
                        "be watched without re-parsing from zero)")
    args = p.parse_args(argv)

    if args.follow:
        # a not-yet-started run dir is fine: streams are discovered as
        # they appear
        if not os.path.isdir(args.run_dir) and not os.path.isfile(args.run_dir):
            print(f"{args.run_dir!r} does not exist (yet?) — waiting for "
                  "streams to appear", file=sys.stderr)
        return _follow_cli(args.run_dir)

    files = obs.metrics_files(args.run_dir)
    if not files:
        print(f"no metrics*.jsonl under {args.run_dir!r} "
              "(was the run started with --metrics_path / --save_dir?)",
              file=sys.stderr)
        return 1
    doc = analyze(load_run(args.run_dir))
    if args.as_json:
        print(json.dumps(doc, indent=2, default=str))
    else:
        print(f"# metrics: {', '.join(files)}")
        print(_fmt_table(doc))
        if args.tail:
            for host, recs in sorted(obs.read_tail(args.run_dir, args.tail).items()):
                print(f"\n-- host {host}: last {len(recs)} records --")
                for rec in recs:
                    print(json.dumps(rec, default=str))
    return 0


if __name__ == "__main__":
    sys.exit(main())
