"""SPMD sharding of the train/test step over a mesh.

This replaces the reference's gradient ring (MultiGradientMachine.h:62-80)
and pserver sync-SGD (ParameterServer2::addGradient/op_SGD,
/root/reference/paddle/pserver/ParameterServer2.cpp:352,1035): instead of
shipping gradients over threads/sockets, the ONE jitted step is compiled
with sharded inputs — XLA partitions the computation and inserts
psum/all-gather over ICI where the math requires it. Sync-SGD semantics
(num_batches_per_send_parameter == 1) fall out exactly: the optimizer
update sees the full-batch mean gradient every step. The async/stale path
is deliberately not reproduced (doc/divergences.md).

Sharding rules:
- batch Arguments: leading axis over the "data" mesh axis
- parameters: replicated, unless ParameterConfig.sharding names mesh axes
  (tensor parallelism), e.g. sharding=["model", null] shards dim 0
- optimizer slots follow their parameter's sharding
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.optimizer.updater import UpdaterState


def batch_sharding(mesh: Mesh) -> NamedSharding:
    if "data" in mesh.axis_names:
        return NamedSharding(mesh, P("data"))
    return NamedSharding(mesh, P())


def param_sharding(mesh: Mesh, param_cfg) -> NamedSharding:
    if param_cfg is not None and param_cfg.sharding:
        axes = [a if (a and a in mesh.axis_names) else None for a in param_cfg.sharding]
        return NamedSharding(mesh, P(*axes))
    return NamedSharding(mesh, P())


def _param_shardings(mesh: Mesh, gm) -> Dict[str, NamedSharding]:
    return {name: param_sharding(mesh, cfg) for name, cfg in gm.param_configs.items()}


def _slot_sharding(mesh: Mesh, param_sh: NamedSharding, ndim: Optional[int]) -> NamedSharding:
    """Optimizer-slot sharding policy (single source of truth, used by the
    train-step in_shardings AND checkpoint restore): row-wise slots (e.g.
    sparse t_last, [V]) take the leading axes of the parameter's spec;
    full-shape slots take it whole."""
    spec = tuple(param_sh.spec)
    if ndim is not None:
        spec = spec[:ndim]
    return NamedSharding(mesh, P(*spec))


def _opt_state_sharding(mesh: Mesh, param_shards: Dict[str, NamedSharding], opt_state: UpdaterState):
    repl = NamedSharding(mesh, P())

    def slot_shard(name, arr):
        ps = param_shards.get(name, repl)
        return _slot_sharding(mesh, ps, arr.ndim if hasattr(arr, "ndim") else None)

    slots = {
        name: {slot: slot_shard(name, arr) for slot, arr in d.items()}
        for name, d in opt_state.slots.items()
    }
    avg = (
        {name: param_shards.get(name, repl) for name in opt_state.avg_sum}
        if opt_state.avg_sum is not None
        else None
    )
    avg_old = (
        {name: param_shards.get(name, repl) for name in opt_state.avg_old_sum}
        if opt_state.avg_old_sum is not None
        else None
    )
    return UpdaterState(
        step=repl, num_samples=repl, slots=slots, avg_sum=avg, avg_count=repl,
        avg_old_sum=avg_old,
        avg_old_count=repl if opt_state.avg_old_count is not None else None,
    )


@functools.lru_cache(maxsize=8)
def _replicate_fn(mesh: Mesh):
    # one cached PjitFunction per mesh so per-batch gathers hit the jit
    # cache instead of retracing every call
    return jax.jit(lambda a: a, out_shardings=NamedSharding(mesh, P()))


def replicate_to_host(x, mesh: Mesh):
    """All-gather a (possibly cross-host sharded) array and return the
    FULL value as host numpy on every process. The jit identity with a
    replicated out_sharding compiles to one all-gather over ICI."""
    import numpy as np

    return np.asarray(_replicate_fn(mesh)(x).addressable_data(0))


def gather_outputs(outputs, mesh: Mesh, names=None):
    """Materialize (selected) layer outputs as full host values on every
    process — the distributeEval analog (reference Evaluator::
    distributeEval merges per-trainer evaluator state over the pserver,
    /root/reference/paddle/gserver/evaluators/Evaluator.h:81-82; here
    each host instead sees the full small output batch and computes
    identical merged metrics). ``names`` limits the gather to the layers
    the evaluator chain actually reads. The whole picked tree goes through
    ONE jitted all-gather (one collective, one host sync per batch)."""
    import numpy as np

    picked = outputs if names is None else {k: outputs[k] for k in names if k in outputs}
    rep = _replicate_fn(mesh)(picked)
    return jax.tree_util.tree_map(lambda x: np.asarray(x.addressable_data(0)), rep)


class NotRowLocal(Exception):
    """An output's process-local rows cannot be assembled on this host
    (non-batch axes sharded across devices, or an exotic layout) — the
    caller falls back to the full per-batch gather."""


def rows_locally_assemblable(outputs, names=None) -> bool:
    """Decide from GLOBAL sharding metadata whether every selected leaf's
    rows can be assembled process-locally. The decision must be identical
    on every process (it gates which collective runs next — a per-process
    disagreement would deadlock), so it only consults sharding specs,
    never this process's addressable shards."""
    picked = outputs if names is None else {k: outputs[k] for k in names if k in outputs}

    def ok(x) -> bool:
        if not isinstance(x, jax.Array) or x.ndim == 0:
            return True
        spec = getattr(x.sharding, "spec", None)
        if spec is None:
            return False  # not a NamedSharding: no portable metadata
        # axes beyond the batch axis must be unsharded (a PartitionSpec
        # shorter than ndim leaves trailing axes unsharded)
        return all(p is None for p in tuple(spec)[1:])

    return all(
        ok(leaf) for leaf in jax.tree_util.tree_leaves(picked)
    )


def local_row_block(outputs, names=None):
    """Each process's contiguous row block of (selected) batch-leading
    outputs as host numpy — the input side of sufficient-statistics
    evaluator merging (reference Evaluator::getState/distributeEval,
    Evaluator.h:81-82): processes accumulate metrics over disjoint row
    blocks locally and SUM small state vectors once per period, instead
    of all-gathering raw [B, V] activations every batch.

    Process p takes rows [B*p/pc, B*(p+1)/pc) of every leaf: replicated
    leaves are sliced on the host; batch-sharded leaves are assembled from
    the replica-0 addressable shards, which must tile exactly that block
    (the standard data-axis layout built by globalize_batch). Check
    rows_locally_assemblable first; an unexpected layout here raises
    NotRowLocal, which the caller must treat as fatal (the decision
    already committed every process to this path).
    """
    import numpy as np

    pid, pc = jax.process_index(), jax.process_count()
    picked = outputs if names is None else {k: outputs[k] for k in names if k in outputs}

    def loc(x):
        if not isinstance(x, jax.Array) or x.ndim == 0:
            return np.asarray(x)
        B = x.shape[0]
        lo, hi = B * pid // pc, B * (pid + 1) // pc
        if x.is_fully_addressable:
            return np.asarray(x)[lo:hi]
        # replicated across processes: some addressable shard holds the
        # full batch axis — slice this process's block from it
        for sh in x.addressable_shards:
            row_sl = sh.index[0] if sh.index else slice(None)
            if (row_sl.start or 0) == 0 and row_sl.stop in (None, B):
                return np.asarray(sh.data)[lo:hi]
        rows = sorted(
            ((s.index[0].start or 0, np.asarray(s.data))
             for s in x.addressable_shards if s.replica_id == 0),
            key=lambda t: t[0],
        )
        expect = lo
        for start, data in rows:
            if start != expect:
                raise NotRowLocal(f"non-contiguous rows at {start} (shape {x.shape})")
            expect += data.shape[0]
        if not rows or rows[0][0] != lo or expect != hi:
            raise NotRowLocal(f"rows {[r[0] for r in rows]} != [{lo}:{hi}] (shape {x.shape})")
        return np.concatenate([d for _, d in rows], axis=0)

    return jax.tree_util.tree_map(loc, picked)


def merge_eval_states(vec):
    """SUM a small per-process evaluator state vector across processes
    (one host allgather per read period — the distributeEval merge)."""
    import numpy as np

    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(np.asarray(vec))).sum(axis=0)


def checkpoint_sharding_fn(mesh: Mesh, gm):
    """(tree_base, flat_key, shape) → NamedSharding for checkpoint restore:
    params and averaging sums take the parameter's sharding; optimizer
    slots take the leading axes of their parameter's spec (row-wise slots
    like sparse t_last are [V]-shaped); everything else replicates."""
    param_shards = _param_shardings(mesh, gm)
    repl = NamedSharding(mesh, P())

    def fn(base: str, key: str, shape) -> NamedSharding:
        if base in ("params", "optimizer_avg", "optimizer_avg_old"):
            return param_shards.get(key, repl)
        if base == "optimizer_slots":
            pname = key.split("/", 1)[0]
            return _slot_sharding(mesh, param_shards.get(pname, repl), len(shape))
        return repl

    return fn


def owned_row_range(arr) -> "tuple[int, int]":
    """The contiguous ``[lo, hi)`` row interval of a dim-0-sharded
    array whose rows THIS process uniquely owns (replica_id == 0) —
    the live-array twin of the ``row_range`` stamped into sparse shard
    records (doc/sparse.md).  A replicated array owns every row on
    process 0 and nothing elsewhere; a process owning non-contiguous
    row blocks is a layout this framework never produces, and raises.
    """
    rows = []
    for sh in arr.addressable_shards:
        if sh.replica_id != 0:
            continue
        sl = sh.index[0] if sh.index else slice(0, int(arr.shape[0]))
        lo = int(sl.start or 0)
        hi = int(sl.stop) if sl.stop is not None else int(arr.shape[0])
        rows.append((lo, hi))
    if not rows:
        return (0, 0)
    rows.sort()
    merged = [list(rows[0])]
    for lo, hi in rows[1:]:
        if lo <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], hi)
        else:
            merged.append([lo, hi])
    if len(merged) != 1:
        raise ValueError(
            f"non-contiguous owned row blocks {merged} — not a "
            "row-sharded table layout"
        )
    return (merged[0][0], merged[0][1])


def _batch_tree_sharding(mesh: Mesh, batch) -> Any:
    bs = batch_sharding(mesh)
    return jax.tree_util.tree_map(lambda _: bs, batch)


def globalize_batch(batch, mesh: Mesh):
    """Assemble per-process batch slices into global sharded arrays.

    Multi-host analog of the reference's per-trainer data partitions
    (each pserver trainer reads its own split): every process builds the
    same host-level batch (providers are seeded identically), takes its
    contiguous row block, and jax.make_array_from_process_local_data
    glues the blocks into one global array sharded over the 'data' axis.
    No-op in single-process mode. Returns None for a remainder batch
    whose size is not divisible by the process count (the end-of-pass
    partial batch) — the caller skips it; sync-SGD needs every host to
    contribute an identical batch structure.
    """
    import numpy as np

    pc = jax.process_count()
    if pc == 1:
        return batch
    bs = batch_sharding(mesh)
    pid = jax.process_index()
    first = next(
        v
        for v in jax.tree_util.tree_leaves(batch)
        if hasattr(v, "shape") and v.shape
    )
    if first.shape[0] % pc != 0:
        return None

    def put(x):
        if x is None:
            return None
        x = np.asarray(x)
        n = x.shape[0] // pc
        local = x[pid * n : (pid + 1) * n]
        return jax.make_array_from_process_local_data(bs, local, x.shape)

    return jax.tree_util.tree_map(put, batch)


def shard_train_step(step, mesh: Mesh, gm, donate: bool = True,
                     extra_outs: int = 0):
    """Wrap a (params, opt_state, batch, rng, batch_size) step with mesh
    shardings. Shardings for the batch depend on its treedef, so the jit is
    built lazily per batch structure and cached. ``donate=False`` keeps the
    input buffers valid after the call (the trainer's skip/rollback
    divergence policies must be able to discard a poisoned update).
    ``extra_outs``: trailing aux outputs beyond the canonical
    (params, opt_state, loss, keep) — the numerics health pytree rides
    this way; shardings for aux are left to jit (tiny replicated
    scalars)."""
    param_shards = _param_shardings(mesh, gm)
    repl = NamedSharding(mesh, P())
    bs = batch_sharding(mesh)
    cache: Dict[Any, Any] = {}

    def call(params, opt_state, batch, rng, batch_size):
        treedef = jax.tree_util.tree_structure((opt_state, batch))
        fn = cache.get(treedef)
        if fn is None:
            p_spec = {k: param_shards.get(k, repl) for k in params}
            o_spec = _opt_state_sharding(mesh, param_shards, opt_state)
            b_spec = jax.tree_util.tree_map(lambda _: bs, batch)
            # pin param/opt-state outputs to the same shardings as the
            # inputs so step N's outputs are valid step N+1 inputs
            fn = jax.jit(
                step,
                in_shardings=(p_spec, o_spec, b_spec, repl, repl),
                out_shardings=(p_spec, o_spec, None, None)
                + (None,) * extra_outs,
                donate_argnums=(0, 1) if donate else (),
            )
            cache[treedef] = fn
        return fn(params, opt_state, batch, rng, batch_size)

    return call


def shard_accum_steps(astep, ustep, mesh: Mesh, gm, donate: bool = True):
    """Mesh-shard the gradient-accumulation pair
    (num_batches_per_send_parameter > 1): ``astep(params, acc, batch,
    rng, n)`` accumulates one batch's gradients; ``ustep(params,
    opt_state, acc, total_n)`` applies one optimizer update. The
    accumulator tree mirrors the parameter tree, so it takes the
    parameter shardings. ``donate=False``: see shard_train_step."""
    param_shards = _param_shardings(mesh, gm)
    repl = NamedSharding(mesh, P())
    bs = batch_sharding(mesh)
    a_cache: Dict[Any, Any] = {}
    u_fn = None

    def p_spec(params):
        return {k: param_shards.get(k, repl) for k in params}

    def a_call(params, acc, batch, rng, n):
        treedef = jax.tree_util.tree_structure(batch)
        fn = a_cache.get(treedef)
        if fn is None:
            ps = p_spec(params)
            b_spec = jax.tree_util.tree_map(lambda _: bs, batch)
            fn = jax.jit(
                astep,
                in_shardings=(ps, ps, b_spec, repl, repl),
                out_shardings=(ps, ps, None, None),
                donate_argnums=(0, 1) if donate else (),
            )
            a_cache[treedef] = fn
        return fn(params, acc, batch, rng, n)

    def u_call(params, opt_state, acc, total_n):
        # the opt-state structure is fixed for a run: one jit, built lazily
        nonlocal u_fn
        if u_fn is None:
            ps = p_spec(params)
            o_spec = _opt_state_sharding(mesh, param_shards, opt_state)
            u_fn = jax.jit(
                ustep,
                in_shardings=(ps, o_spec, ps, repl),
                out_shardings=(ps, o_spec, ps),
                donate_argnums=(0, 1, 2) if donate else (),
            )
        return u_fn(params, opt_state, acc, total_n)

    return a_call, u_call


def shard_test_fwd(fwd, mesh: Mesh, gm):
    param_shards = _param_shardings(mesh, gm)
    repl = NamedSharding(mesh, P())
    bs = batch_sharding(mesh)
    cache: Dict[Any, Any] = {}

    def call(params, batch):
        treedef = jax.tree_util.tree_structure(batch)
        fn = cache.get(treedef)
        if fn is None:
            p_spec = {k: param_shards.get(k, repl) for k in params}
            b_spec = jax.tree_util.tree_map(lambda _: bs, batch)
            fn = jax.jit(fwd, in_shardings=(p_spec, b_spec))
            cache[treedef] = fn
        return fn(params, batch)

    return call
