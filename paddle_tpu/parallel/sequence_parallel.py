"""Sequence/context parallelism: ring attention + all-to-all attention.

Long-context support has no 2016 reference counterpart (SURVEY.md §5 "new
design territory"): the reference's longest-sequence machinery is
host-side batching (SequenceToBatch). Here a sequence is *sharded across
chips* on the mesh's "seq" axis and attention runs over the full context
via ICI collectives:

- ``ring_attention``: blockwise attention with the K/V shards rotating
  around the ring (`lax.ppermute`), combined with a streaming (online
  softmax) accumulator — memory per chip stays O(T/n), comms overlap with
  the next block's compute. The TPU analog of Ring Attention
  (Liu et al. '23) on ICI neighbors.
- ``alltoall_attention``: Ulysses-style — `lax.all_to_all` resharding from
  sequence-sharded to head-sharded, full-context attention locally per
  head group, reshard back. Cheaper comms for moderate contexts; requires
  heads % seq_shards == 0.

Both are differentiable (jax autodiff through the collective), masked for
padded positions, optionally causal, and numerically match the reference
``full_attention`` below — see tests/test_sequence_parallel.py, which runs
them on an 8-device CPU mesh exactly like the reference tests distributed
code on loopback pservers (SURVEY.md §4).

Layout convention: q/k/v are [B, T_local, H, D] under shard_map (T sharded
over "seq"); lengths is the *global* valid-length vector [B], replicated.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

Array = jax.Array

_NEG = -1e30  # big-negative instead of -inf: keeps fully-masked rows NaN-free

# rings up to this size unroll (XLA overlaps each ppermute with the next
# block's matmuls); larger rings roll with lax.scan so program size stays
# O(1) in n. Module-level so tests can force the scan path on small meshes
# (the 64-chip branch must not be dead untested code).
RING_UNROLL_MAX = 8


def full_attention(
    q: Array, k: Array, v: Array,
    lengths: Optional[Array] = None,
    causal: bool = False,
    q_offset: int = 0,
    kv_offset: int = 0,
) -> Array:
    """Single-device attention over [B, T, H, D] tensors.

    On TPU, self-attention shapes the flash kernel supports dispatch to
    paddle_tpu.ops.pallas_attention (O(T) activation memory); everything
    else takes the XLA path below (which materializes [B, H, T, T]).

    Outputs at padded query rows (positions >= lengths) are unspecified
    and differ between the flash and XLA paths — callers must mask them
    (the mha layer does)."""
    if (
        q_offset == 0
        and kv_offset == 0
        and q.shape == k.shape
        and jax.default_backend() == "tpu"
    ):
        from paddle_tpu.ops import pallas_attention

        if pallas_attention.supported(q.shape[1], q.shape[3]):
            return pallas_attention.tpu_flash_attention(
                q, k, v, lengths=lengths, causal=causal
            )
    D = q.shape[-1]
    # scores and softmax in f32 even for bf16 q/k/v: the QK matmul takes
    # bf16 operands with an f32 result; p stays f32 through the PV matmul
    # (matching the ring path's f32 online-softmax state — narrowing p
    # would diverge from it)
    acc_t = jnp.promote_types(q.dtype, jnp.float32)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=acc_t) / math.sqrt(D)
    Tq, Tk = q.shape[1], k.shape[1]
    q_pos = q_offset + jnp.arange(Tq)
    kv_pos = kv_offset + jnp.arange(Tk)
    mask = jnp.ones((Tq, Tk), bool)
    if causal:
        mask &= kv_pos[None, :] <= q_pos[:, None]
    mask = jnp.broadcast_to(mask, (q.shape[0], 1, Tq, Tk))
    if lengths is not None:
        mask &= (kv_pos[None, None, None, :] < lengths[:, None, None, None])
    s = jnp.where(mask, s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v, preferred_element_type=acc_t)
    return out.astype(q.dtype)


def _ring_attention_local(q, k, v, lengths, causal, axis_name):
    """Per-shard body: stream the K/V ring through an online-softmax
    accumulator. q/k/v: [B, T_loc, H, D] (this shard's block)."""
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    B, T_loc, H, D = q.shape
    scale = 1.0 / math.sqrt(D)
    q_pos = idx * T_loc + jnp.arange(T_loc)                      # global positions

    # accumulate in f32 regardless of q.dtype: bf16 online-softmax state
    # drifts across ring steps (matches the f32-accumulating flash kernel)
    acc_t = jnp.float32
    o0 = jnp.zeros((B, H, T_loc, D), acc_t)
    m0 = jnp.full((B, H, T_loc), _NEG, acc_t)
    l0 = jnp.zeros((B, H, T_loc), acc_t)
    # under the new shard_map type system fresh constants are unvarying;
    # the loop carry must already vary over the ring axis like q does
    if hasattr(jax.lax, "pcast"):
        o0, m0, l0 = (
            jax.lax.pcast(x, (axis_name,), to="varying") for x in (o0, m0, l0)
        )
    elif hasattr(jax.lax, "pvary"):
        o0, m0, l0 = (jax.lax.pvary(x, (axis_name,)) for x in (o0, m0, l0))
    perm = [(j, (j + 1) % n) for j in range(n)]

    def block(r, o, m, l, k_blk, v_blk):
        src = (idx - r) % n                                      # block owner
        kv_pos = src * T_loc + jnp.arange(T_loc)
        s = jnp.einsum(
            "bqhd,bkhd->bhqk", q, k_blk, preferred_element_type=acc_t
        ) * scale
        mask = jnp.ones((T_loc, T_loc), bool)
        if causal:
            mask = kv_pos[None, :] <= q_pos[:, None]
        mask = jnp.broadcast_to(mask, (B, 1, T_loc, T_loc))
        if lengths is not None:
            mask = mask & (kv_pos[None, None, None, :] < lengths[:, None, None, None])
        s = jnp.where(mask, s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        p = jnp.where(mask, p, 0.0)                              # kill _NEG rows exactly
        l = l * alpha + jnp.sum(p, axis=-1)
        o = o * alpha[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_blk, preferred_element_type=acc_t
        )
        return o, m_new, l

    if n <= RING_UNROLL_MAX:
        # unrolled ring (n is static under shard_map): no permute after the
        # last block, and XLA can overlap each ppermute with the next matmul
        o, m, l = o0, m0, l0
        k_blk, v_blk = k, v
        for r in range(n):
            o, m, l = block(r, o, m, l, k_blk, v_blk)
            if r != n - 1:
                k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
                v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
    else:
        # large rings (e.g. 64-chip seq axis): roll the ring with lax.scan
        # so compile time and program size stay O(1) in n; the last block
        # runs outside the loop so no wasted trailing ppermute
        def body(carry, r):
            o, m, l, k_blk, v_blk = carry
            o, m, l = block(r, o, m, l, k_blk, v_blk)
            k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
            v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
            return (o, m, l, k_blk, v_blk), None
        (o, m, l, k_blk, v_blk), _ = jax.lax.scan(
            body, (o0, m0, l0, k, v), jnp.arange(n - 1)
        )
        o, m, l = block(n - 1, o, m, l, k_blk, v_blk)
    o = o / jnp.maximum(l[..., None], 1e-20)
    o = o.astype(q.dtype)
    return jnp.transpose(o, (0, 2, 1, 3))                        # [B, T_loc, H, D]


def _alltoall_attention_local(q, k, v, lengths, causal, axis_name):
    """Per-shard body: reshard seq→heads, full local attention, reshard
    back. Requires H % n == 0."""
    n = jax.lax.psum(1, axis_name)
    B, T_loc, H, D = q.shape

    def seq_to_heads(x):  # [B, T_loc, H, D] -> [B, T_glob, H/n, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def heads_to_seq(x):  # inverse
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    out = full_attention(qg, kg, vg, lengths=lengths, causal=causal)
    return heads_to_seq(out)


def _sharded_attention(q, k, v, lengths, mesh: Mesh, *, causal: bool, axis: str, local_fn):
    if axis not in mesh.axis_names or mesh.shape[axis] == 1:
        return full_attention(q, k, v, lengths=lengths, causal=causal)
    n = mesh.shape[axis]
    assert q.shape[1] % n == 0, (
        f"global seq len {q.shape[1]} must divide the {axis}={n} mesh axis "
        "(pad to a multiple; lengths masking keeps numerics exact)"
    )
    # co-shard the batch over any data axes so composing with data
    # parallelism doesn't all-gather q/k/v across the data dimension
    data_axes = tuple(
        n for n in mesh.axis_names if n in ("data", "expert") and mesh.shape[n] > 1
    )
    b_spec = data_axes if data_axes else None
    seq_spec = P(b_spec, axis, None, None)
    len_spec = P(b_spec)
    shard_fn = functools.partial(local_fn, causal=causal, axis_name=axis)

    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    mapped = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(seq_spec, seq_spec, seq_spec, len_spec),
        out_specs=seq_spec,
    )
    return mapped(q, k, v, lengths)


def ring_attention(
    q: Array, k: Array, v: Array,
    mesh: Mesh,
    lengths: Optional[Array] = None,
    causal: bool = False,
    axis: str = "seq",
) -> Array:
    """Attention over sequence-sharded q/k/v [B, T_global, H, D]; T_global
    is sharded over ``axis`` by the caller's in_shardings (or replicated
    inputs get partitioned here). Returns the same layout."""
    if lengths is None:
        lengths = jnp.full((q.shape[0],), q.shape[1], jnp.int32)
    return _sharded_attention(
        q, k, v, lengths, mesh, causal=causal, axis=axis, local_fn=_ring_attention_local
    )


def alltoall_attention(
    q: Array, k: Array, v: Array,
    mesh: Mesh,
    lengths: Optional[Array] = None,
    causal: bool = False,
    axis: str = "seq",
) -> Array:
    if lengths is None:
        lengths = jnp.full((q.shape[0],), q.shape[1], jnp.int32)
    if axis in mesh.axis_names:
        assert q.shape[2] % mesh.shape[axis] == 0, (
            f"heads {q.shape[2]} must divide {axis}={mesh.shape[axis]} "
            "(use ring_attention otherwise)"
        )
    return _sharded_attention(
        q, k, v, lengths, mesh, causal=causal, axis=axis,
        local_fn=_alltoall_attention_local,
    )
