from paddle_tpu.parallel.mesh import make_mesh, MeshSpec
from paddle_tpu.parallel.spmd import shard_train_step, shard_test_fwd, batch_sharding, param_sharding

__all__ = [
    "make_mesh",
    "MeshSpec",
    "shard_train_step",
    "shard_test_fwd",
    "batch_sharding",
    "param_sharding",
]
