"""Local SGD — the TPU-native analog of the reference's async SGD.

Reference semantics (``settings(is_async=True)`` → ``algorithm=
'async_sgd'``, proto default ``TrainerConfig.proto.m4:22``): the pserver
applies each trainer's gradient the moment it arrives instead of waiting
for a synchronized batch (`ParameterServer2.cpp:572` op dispatch without
the sync barriers), and discards gradients that lag more than
``async_lagged_grad_discard_ratio`` behind the current update count
(`TrainerConfig.proto.m4:124-129`, `config_parser.py:2929-2930`).

An SPMD step is lock-step by construction, so apply-on-arrival is
re-designed rather than translated (doc/divergences.md):

- Every data-parallel replica keeps its OWN parameter + optimizer-state
  copy and applies its local gradient immediately each batch — the
  analog of a trainer not waiting for the others. The per-batch step has
  ZERO cross-replica collectives: it is one ``jax.vmap`` over the
  replica axis, which XLA maps 1:1 onto the ``data`` mesh axis.
- Every ``num_batches_per_send_parameter`` batches the replicas merge by
  parameter averaging (one weighted all-reduce of params + slots) — the
  "send parameter" analog.
- The staleness discard maps to a drift gate at the merge: replicas
  whose distance from the element-wise median model exceeds
  ``async_lagged_grad_discard_ratio × R ×`` the median replica drift
  are excluded from the average (their divergent work is discarded,
  exactly what the pserver did to lagged gradients) and snapped to the
  merged values. The R-scaled median statistic is calibrated so
  ordinary stochastic replica spread (≲2-3× the median) never triggers
  while genuine divergence (NaN, exploding replicas) always does —
  mirroring the reference gate, which never fired in healthy runs.
  ``ratio <= 0`` disables the gate.

Determinism note: unlike the reference's wall-clock-dependent async
path, this mode is bit-reproducible — "staleness" is measured in
parameter space, not arrival time, so runs are identical across
repeats. Each replica draws its own rng stream (``jax.random.split`` of
the step key), mirroring per-trainer dropout streams.

Constraints (same reasons as gradient accumulation,
trainer.py::_build_accum_steps): dense gradients only (row-sparse shapes
vary per batch and cannot ride the fixed-shape replica stack), and the
mesh must be data-parallel only — tensor-parallel params have no
per-replica copy to diverge.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def data_axis_size(mesh: Mesh) -> int:
    return dict(zip(mesh.axis_names, mesh.devices.shape)).get("data", 1)


def check_data_only(mesh: Mesh) -> None:
    for ax, size in zip(mesh.axis_names, mesh.devices.shape):
        if ax != "data" and size > 1:
            raise ValueError(
                "async_sgd (local SGD) is data-parallel only; mesh axis "
                f"{ax!r} has size {size} — drop it or use sync SGD"
            )


def _is_float(x) -> bool:
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)


class LocalSgd:
    """Jitted machinery for one local-SGD run: ``stack`` canonical trees
    into per-replica stacks, per-batch ``step``, periodic ``merge``, and
    ``collapse`` back to canonical (replica-0) trees.

    Stacked trees carry a leading replica axis of size R sharded over the
    ``data`` mesh axis, so each device holds exactly its own replica —
    the same per-device memory as the replicated sync path.
    """

    def __init__(self, step_body, mesh: Mesh, ratio: float):
        """``step_body(params, opt_state, batch, rng, batch_size) ->
        (new_params, new_opt, loss, kept_outputs)`` is the SAME one-batch
        closure the sync path jits (Trainer._one_batch_step /
        __graft_entry__._train_step) — taken whole, not rebuilt from
        grad_fn + updater, so the sync and local-SGD per-batch semantics
        cannot diverge."""
        check_data_only(mesh)
        self.mesh = mesh
        self.R = data_axis_size(mesh)
        self.ratio = float(ratio)
        self._step_body = step_body
        self._stacked = NamedSharding(mesh, P("data"))
        self._repl = NamedSharding(mesh, P())
        self._step_cache: Dict[Any, Any] = {}
        self._merge_fn = None
        self._view_fn = None
        self._stack_fn = None
        self._collapse_fn = None

    # ------------------------------------------------------------- stack

    def stack(self, params, opt_state):
        """Broadcast canonical trees to [R, ...] replica stacks (all
        replicas start identical, like trainers pulling the same initial
        model from the pserver)."""
        if self._stack_fn is None:
            R = self.R

            def bcast(tree):
                return jax.tree_util.tree_map(
                    lambda x: jnp.broadcast_to(x, (R,) + jnp.shape(x)), tree
                )

            self._stack_fn = jax.jit(bcast, out_shardings=self._stacked)
        return self._stack_fn(params), self._stack_fn(opt_state)

    def collapse(self, params_r, opt_r):
        """Replica 0 of each stacked tree as canonical replicated values.
        Call only after a merge — replicas must be identical, or work
        from replicas 1..R-1 would be dropped silently."""
        if self._collapse_fn is None:
            self._collapse_fn = jax.jit(
                lambda tree: jax.tree_util.tree_map(lambda x: x[0], tree),
                out_shardings=self._repl,
            )
        return self._collapse_fn(params_r), self._collapse_fn(opt_r)

    # -------------------------------------------------------------- step

    def step(self, params_r, opt_r, batch, rng, n):
        """One local update on every replica: the global batch [B, ...]
        splits into R contiguous sub-batches (a local reshape — the batch
        is already sharded over ``data``), each replica applies its own
        gradient to its own copy. ``n`` (global sample count) advances
        every replica's schedule counter — replicas move in lockstep
        through the global data stream, matching the reference pserver's
        global ``num_samples_processed``."""
        treedef = jax.tree_util.tree_structure(batch)
        fn = self._step_cache.get(treedef)
        if fn is None:
            fn = self._build_step(batch)
            self._step_cache[treedef] = fn
        return fn(params_r, opt_r, batch, rng, n)

    def _build_step(self, batch_example):
        R = self.R
        body = self._step_body

        def lstep(params_r, opt_r, batch, rng, n):
            batch_r = jax.tree_util.tree_map(
                lambda x: x.reshape((R, x.shape[0] // R) + x.shape[1:]), batch
            )
            rngs = jax.random.split(rng, R)
            # n (the GLOBAL sample count) broadcasts unmapped: every
            # replica advances its schedule counter by the global batch
            new_pr, new_or, losses, keeps = jax.vmap(
                body, in_axes=(0, 0, 0, 0, None)
            )(params_r, opt_r, batch_r, rngs, n)
            # kept outputs back to global batch order [B, ...] for the
            # evaluator chain (replica blocks are contiguous row blocks)
            keep_flat = jax.tree_util.tree_map(
                lambda x: x.reshape((-1,) + x.shape[2:]) if x.ndim >= 2 else x,
                keeps,
            )
            return new_pr, new_or, jnp.mean(losses), keep_flat

        b_spec = jax.tree_util.tree_map(lambda _: self._stacked, batch_example)
        return jax.jit(
            lstep,
            in_shardings=(self._stacked, self._stacked, b_spec, self._repl, self._repl),
            out_shardings=(self._stacked, self._stacked, None, None),
            donate_argnums=(0, 1),
        )

    # ------------------------------------------------------------- merge

    def merge(self, params_r, opt_r):
        """Drift-gated parameter averaging across replicas. Returns the
        merged stacks (all replicas identical afterwards) and the number
        of replicas whose work was discarded by the staleness gate."""
        if self._merge_fn is None:
            self._merge_fn = self._build_merge()
        return self._merge_fn(params_r, opt_r)

    def merged_view(self, params_r, opt_r):
        """Read-only merged snapshot as canonical (replicated) trees —
        the same drift-gated weighted average as ``merge`` but WITHOUT
        touching the replica stacks. Mid-pass observability (periodic
        test/stats/checkpoint) reads this, exactly as the reference's
        test path read the pserver's merged parameters without
        collapsing the trainers' local progress — a logging flag must
        not perturb the optimization trajectory or the merge schedule."""
        if self._view_fn is None:
            self._view_fn = self._build_view()
        return self._view_fn(params_r, opt_r)

    def _gate_weights(self, params_r):
        """Drift-gate weights [R] + discard count.

        Per-replica drift ||p_i - median(p)|| is measured from the
        element-wise MEDIAN model: a diverged replica cannot drag the
        anchor toward itself (a mean anchor caps any outlier's relative
        drift at (R-1)x and gets ordinary stochastic variation discarded
        instead). Gate at ratio*R*median(drift): benign replica spread
        stays within ~2-3x of the median, a genuinely broken replica
        (exploding, NaN) is orders of magnitude out, so the margin is
        wide on both sides. Non-finite replicas are handled OUTSIDE the
        drift statistic: a single NaN element would make the plain
        median (and then every replica's drift) NaN, rejecting everyone
        and letting the keep-everyone insurance average the NaN in — so
        the anchor is the nanmedian and a replica with any non-finite
        parameter is discarded by its own finiteness mask."""
        R, ratio = self.R, self.ratio
        leaves = [
            x.astype(jnp.float32)
            for x in jax.tree_util.tree_leaves(params_r)
            if _is_float(x)
        ]
        finite = jnp.ones((R,), bool)
        sq = []
        for xf in leaves:
            finite &= jnp.isfinite(xf).reshape(R, -1).all(axis=1)
            med = jnp.nanmedian(xf, axis=0, keepdims=True)
            d = ((xf - med) ** 2).reshape(R, -1)
            sq.append(jnp.where(jnp.isfinite(d), d, 0.0).sum(axis=1))
        drift = jnp.sqrt(sum(sq)) if sq else jnp.zeros((R,), jnp.float32)
        if ratio > 0:
            med_drift = jnp.nanmedian(jnp.where(finite, drift, jnp.nan))
            # median 0 = at least half the replicas sit exactly on the
            # median model (e.g. just-stacked identical replicas):
            # anything that moved off it is divergent by definition
            keep = finite & (
                drift <= jnp.where(med_drift > 0, ratio * R * med_drift, 0.0)
            )
        else:
            keep = jnp.ones((R,), bool)
        w = keep.astype(jnp.float32)
        wsum = w.sum()
        # a gate that rejects everyone keeps everyone (mirrors the
        # reference never discarding ALL gradients of an update);
        # unreachable with the median gate but cheap insurance
        w = jnp.where(wsum > 0, w / jnp.maximum(wsum, 1.0), jnp.full((R,), 1.0 / R))
        discarded = (R - keep.sum()).astype(jnp.int32)
        return w, discarded

    def _wmean(self, w, x):
        """Gate-weighted mean of one stacked leaf → canonical [..] value."""
        R = self.R
        if not _is_float(x):
            return x[0]  # int counters are replica-identical (lockstep)
        wx = w.reshape((R,) + (1,) * (x.ndim - 1))
        # zero the discarded replicas' values BEFORE the weighted sum —
        # 0 * NaN is NaN, so a NaN replica would otherwise poison the
        # merge through its zero weight
        xf = jnp.where(wx > 0, x.astype(jnp.float32), 0.0)
        return (xf * wx).sum(0).astype(x.dtype)

    def _build_merge(self):
        def merge(params_r, opt_r):
            w, discarded = self._gate_weights(params_r)

            def wmean_bcast(x):
                if not _is_float(x):
                    return x
                return jnp.broadcast_to(self._wmean(w, x), x.shape)

            new_pr = jax.tree_util.tree_map(wmean_bcast, params_r)
            new_or = jax.tree_util.tree_map(wmean_bcast, opt_r)
            return new_pr, new_or, discarded

        return jax.jit(
            merge,
            in_shardings=(self._stacked, self._stacked),
            out_shardings=(self._stacked, self._stacked, None),
            donate_argnums=(0, 1),
        )

    def _build_view(self):
        def view(params_r, opt_r):
            w, _ = self._gate_weights(params_r)
            wm = lambda x: self._wmean(w, x)
            return (
                jax.tree_util.tree_map(wm, params_r),
                jax.tree_util.tree_map(wm, opt_r),
            )

        # NOT donated: the stacks stay live for the next local step
        return jax.jit(
            view,
            in_shardings=(self._stacked, self._stacked),
            out_shardings=(self._repl, self._repl),
        )
