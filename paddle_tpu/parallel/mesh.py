"""Device mesh construction.

The replacement for the reference's two distribution mechanisms — the
intra-process GPU thread ring (MultiGradientMachine,
/root/reference/paddle/gserver/gradientmachines/MultiGradientMachine.h:
62-80) and the socket parameter-server (/root/reference/paddle/pserver/) —
is ONE SPMD story: a `jax.sharding.Mesh` whose axes name the parallelism
kinds, with XLA inserting the collectives over ICI/DCN.

Axis conventions (used by spmd.py and parameter sharding specs):
- "data"  — batch-dim data parallelism (the reference's only mode)
- "model" — tensor parallelism (parameter dim sharding)
- "seq"   — sequence/context parallelism (ring attention)
- "pipe"  — pipeline stages
- "expert"— expert parallelism
Missing axes are simply absent from the mesh; specs referencing only
present axes still work.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_ORDER = ["pipe", "data", "expert", "seq", "model"]


@dataclass(frozen=True)
class MeshSpec:
    axes: Tuple[Tuple[str, int], ...]

    @classmethod
    def parse(cls, spec: str) -> "MeshSpec":
        """Parse "data=8" / "data=4,model=2" / "8" (implicit data)."""
        spec = spec.strip()
        if not spec:
            return cls((("data", len(jax.devices())),))
        axes: List[Tuple[str, int]] = []
        for part in spec.split(","):
            part = part.strip()
            if "=" in part:
                name, _, n = part.partition("=")
                axes.append((name.strip(), int(n)))
            else:
                axes.append(("data", int(part)))
        axes.sort(key=lambda kv: AXIS_ORDER.index(kv[0]) if kv[0] in AXIS_ORDER else 99)
        return cls(tuple(axes))

    @property
    def size(self) -> int:
        n = 1
        for _, k in self.axes:
            n *= k
        return n

    @property
    def names(self) -> Tuple[str, ...]:
        return tuple(n for n, _ in self.axes)

    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(k for _, k in self.axes)


def rescale_mesh_spec(spec: str, orig_hosts: int, cur_hosts: int) -> str:
    """The mesh spec an N-host launch becomes on M surviving hosts —
    reshard-on-relaunch's shape rule (doc/resilience.md "Elastic sharded
    checkpointing"): the "data" axis scales with the host count while
    every other axis keeps its extent, so model/pipe/seq parallelism
    groups stay intact and only the data-parallel width breathes.
    Because the global batch is the config's ``batch_size`` (each
    process takes a 1/num_processes row block — spmd.globalize_batch),
    shrinking the data axis automatically grows the per-host batch and
    the GLOBAL batch (and therefore sync-SGD semantics) is preserved.

    Pure string math — no device queries, so the launcher can call it
    for a pod whose accelerator runtime is the thing that just died. An
    EMPTY spec is identity: the trainer sizes it from jax.devices() at
    startup, which already follows the surviving host set (the
    auto-sized mesh is the most elastic of all). Raises ValueError when
    an explicit spec cannot rescale: no data axis to scale, or a data
    extent not integrally divisible by the host-count ratio."""
    if orig_hosts <= 0 or cur_hosts <= 0:
        raise ValueError(f"host counts must be positive ({orig_hosts}->{cur_hosts})")
    spec = (spec or "").strip()
    if cur_hosts == orig_hosts or not spec:
        return spec
    axes: List[Tuple[str, int]] = []
    for part in spec.split(","):
        part = part.strip()
        if "=" in part:
            name, _, n = part.partition("=")
            axes.append((name.strip(), int(n)))
        else:
            axes.append(("data", int(part)))
    names = [n for n, _ in axes]
    if "data" not in names:
        raise ValueError(
            f"mesh spec {spec!r} has no data axis to rescale for "
            f"{cur_hosts}/{orig_hosts} hosts"
        )
    out = []
    for name, extent in axes:
        if name == "data":
            if (extent * cur_hosts) % orig_hosts:
                raise ValueError(
                    f"data axis {extent} cannot scale by "
                    f"{cur_hosts}/{orig_hosts} integrally"
                )
            extent = extent * cur_hosts // orig_hosts
            if extent < 1:
                raise ValueError(
                    f"data axis vanishes at {cur_hosts}/{orig_hosts} hosts"
                )
        out.append(f"{name}={extent}")
    return ",".join(out)


def make_mesh(spec: str = "", devices: Optional[list] = None) -> Mesh:
    ms = MeshSpec.parse(spec) if isinstance(spec, str) else spec
    devices = devices if devices is not None else jax.devices()
    if ms.size > len(devices):
        raise ValueError(
            f"mesh {ms.axes} needs {ms.size} devices but only {len(devices)} available"
        )
    dev = np.asarray(devices[: ms.size]).reshape(ms.shape)
    return Mesh(dev, ms.names)


def data_only_extent(mesh: Mesh):
    """The data-parallel extent if every OTHER mesh axis is trivial
    (extent 1), else None. Used to gate per-shard shard_map execution of
    the pallas kernels (layers/recurrent.py) — the same purely-data
    question local_sgd.check_data_only asks."""
    d = 1
    for n, e in mesh.shape.items():
        if n == "data":
            d = e
        elif e > 1:
            return None
    return d if d > 1 else None


def shard_map_compat(fn, mesh: Mesh, in_specs, out_specs):
    """shard_map across jax versions, with replication checking off:
    pallas_call out_shapes carry no varying-mesh-axes annotation, which
    the new type system (check_vma) would reject; older jax spells the
    knob check_rep (and lives in jax.experimental.shard_map). The kwarg
    probe happens HERE, eagerly, so a TypeError from tracing user code
    can never be misread as a version mismatch."""
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map
    try:
        return shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)
    except TypeError:
        return shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_rep=False)


def replicated_specs(*arrays):
    """A PartitionSpec per array, fully replicated (weights under a
    data-parallel shard_map)."""
    from jax.sharding import PartitionSpec as P

    return tuple(P(*(None,) * a.ndim) for a in arrays)


def data_axis_names(mesh: Mesh) -> Tuple[str, ...]:
    """Axes that shard the batch dimension (data and expert act as data
    parallel for the dense path)."""
    return tuple(n for n in mesh.axis_names if n in ("data",))
