"""`paddle` CLI — train / supervise / test / checkgrad / dump_config /
merge_model / metrics / memory / roofline / compare / serve-report /
version.

Role of the reference's TrainerMain + `paddle` shell dispatcher
(/root/reference/paddle/trainer/TrainerMain.cpp:35-110,
paddle/scripts/submit_local.sh.in:46-69). The pserver subcommand has no TPU
meaning (SPMD replaces it); multi-host launch is `paddle train
--coordinator_address=... --num_processes=N --process_id=k` per host.
`paddle supervise` wraps `paddle train` in the crash-loop-aware
auto-restart supervisor (doc/resilience.md).
"""

from __future__ import annotations

import os
import sys


def main(argv=None) -> int:
    # die quietly when stdout is a closed pipe (`paddle dump_config | head`)
    import signal

    if hasattr(signal, "SIGPIPE"):
        signal.signal(signal.SIGPIPE, signal.SIG_DFL)
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help", "help"):
        print(__doc__)
        print("usage: paddle <train|supervise|test|gen|serve|serve-fleet|"
              "checkgrad|dump_config|merge_model|check-checkpoint|metrics|"
              "memory|roofline|compare|trace|serve-report|serve-status|lint|race|"
              "faults|version> [--flags]")
        return 0
    cmd, rest = argv[0], argv[1:]
    if cmd == "version":
        from paddle_tpu.version import __version__
        import jax

        print(f"paddle_tpu {__version__} (jax {jax.__version__})")
        print(f"devices: {jax.devices()}")
        return 0
    if cmd in ("train", "test", "checkgrad", "gen"):
        return _run_trainer_job(cmd, rest)
    if cmd == "supervise":
        return _supervise(rest)
    if cmd == "dump_config":
        return _dump_config(rest)
    if cmd == "merge_model":
        return _merge_model(rest)
    if cmd in ("check-checkpoint", "check_checkpoint"):
        return _check_checkpoint(rest)
    if cmd == "metrics":
        # telemetry analyzer (doc/observability.md) — jax-free like
        # `supervise`: it must summarize a run dir copied off a pod
        from paddle_tpu.observability.analyze import main as metrics_main

        return metrics_main(rest)
    if cmd == "memory":
        # HBM accounting: per-launch-group static footprint, live
        # peak/headroom, OOM pre-mortem rendering (doc/observability.md
        # "Memory telemetry") — jax-free like `metrics`
        from paddle_tpu.observability.memory import main as memory_main

        return memory_main(rest)
    if cmd == "roofline":
        # per-launch-group cost attribution (doc/performance.md
        # "Roofline methodology") — jax-free like `metrics`
        from paddle_tpu.observability.costs import main as roofline_main

        return roofline_main(rest)
    if cmd == "compare":
        # run/bench diff with a regression verdict — jax-free
        from paddle_tpu.observability.compare import main as compare_main

        return compare_main(rest)

    if cmd == "trace":
        # cross-process request timelines + tail attribution — jax-free
        from paddle_tpu.observability.tracing import main as trace_main

        return trace_main(rest)
    if cmd == "serve":
        # continuous-batching generation server (doc/serving.md):
        # stdin-JSONL requests through the slot-based decode engine,
        # SIGTERM = graceful drain
        from paddle_tpu.serving.frontend import main as serve_main

        return serve_main(rest)
    if cmd in ("serve-fleet", "serve_fleet"):
        # multi-replica serving: a jax-free router supervises
        # --fleet_replicas `paddle serve` children, balances on their
        # health JSON, fails over via journal replay, restarts on
        # budget (doc/serving.md "Serving fleet")
        from paddle_tpu.serving.fleet import main as fleet_main

        return fleet_main(rest)
    if cmd in ("serve-status", "serve_status"):
        # render a `paddle serve --status_path` health snapshot
        # (queue depth, occupancy, last-collect age, shed/error totals,
        # draining flag) — jax-free: the probe side runs anywhere
        from paddle_tpu.serving.resilience import status_main

        return status_main(rest)
    if cmd in ("serve-report", "serve_report"):
        # per-offered-load serving report (request/serve_window records
        # from `bench.py serve`, doc/observability.md) — jax-free
        from paddle_tpu.observability.serving import main as serve_report_main

        return serve_report_main(rest)
    if cmd == "lint":
        # static analysis over the package's own invariants
        # (doc/static_analysis.md) — jax-free: this is the CI gate and
        # runs before the accelerator runtime exists
        from paddle_tpu.analysis.cli import main as lint_main

        return lint_main(rest)
    if cmd == "race":
        # dynamic analysis: deterministic schedule explorer over the
        # daemon-thread paths (doc/static_analysis.md "Dynamic
        # analysis") — jax-free like lint, and gated the same way
        from paddle_tpu.analysis.dynamic.cli import main as race_main

        return race_main(rest)
    if cmd == "faults":
        return _faults()
    print(f"unknown command {cmd!r}", file=sys.stderr)
    return 2


def _faults() -> int:
    """`paddle faults` — list the fault-injection sites with their
    one-line descriptions, so `--fault_spec` chaos specs are written
    from documentation instead of guessed from source. jax-free."""
    from paddle_tpu.resilience.faultinject import SITE_DOCS

    print("fault-injection sites (--fault_spec='site=action[:arg][@trigger]"
          "[;...]', actions: raise | oserror | exit[:code] | sleep[:secs];"
          " see doc/resilience.md):")
    width = max(len(s) for s in SITE_DOCS)
    for site, desc in SITE_DOCS.items():
        print(f"  {site:<{width}}  {desc}")
    return 0


def _setup(rest):
    from paddle_tpu.utils.flags import FLAGS

    leftover = FLAGS.parse(rest)
    if leftover:
        print(f"warning: unrecognized flags {leftover}", file=sys.stderr)
    if FLAGS.fault_spec:
        # chaos drills: deterministic fault injection at the named sites
        from paddle_tpu.resilience import faultinject

        faultinject.configure(FLAGS.fault_spec, FLAGS.fault_seed)
    if not FLAGS.use_tpu:
        # before ANYTHING imports jax — jax reads JAX_PLATFORMS once at
        # import, so the compile-cache block below must come after
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if FLAGS.compile_cache_dir:
        # before any jax compile (Trainer re-applies the same dir, which
        # is a no-op): warm restarts skip the XLA backend compile and
        # the compile telemetry records the hits
        from paddle_tpu.observability.compile_log import enable_compile_cache

        enable_compile_cache(FLAGS.compile_cache_dir)
    if FLAGS.coordinator_address:
        import jax

        jax.distributed.initialize(
            coordinator_address=FLAGS.coordinator_address,
            num_processes=FLAGS.num_processes,
            process_id=FLAGS.process_id,
        )
    from paddle_tpu.config import parse_config

    if not FLAGS.config:
        print("error: --config is required", file=sys.stderr)
        raise SystemExit(2)
    if not os.path.exists(FLAGS.config):
        print(f"error: config file {FLAGS.config!r} not found", file=sys.stderr)
        raise SystemExit(2)
    config = parse_config(FLAGS.config, FLAGS.config_args)
    return FLAGS, config


def _run_trainer_job(cmd, rest) -> int:
    flags, config = _setup(rest)
    from paddle_tpu.trainer import Trainer

    trainer = Trainer(config, flags)
    if cmd == "train":
        try:
            trainer.train()
        except Exception as e:
            from paddle_tpu.observability.memory import OOM_REPORT, is_oom_error

            if is_oom_error(e):
                # the trainer already wrote oom_report.json and flushed
                # the kind=oom record; the distinct code tells
                # supervisors the death is classified (and budgeted —
                # an OOM loop is poison, not scheduling)
                from paddle_tpu.resilience import EXIT_OOM

                print(f"OOM: {e} (forensics: {OOM_REPORT} in the run "
                      "dir; `paddle memory <run_dir>` renders them)",
                      file=sys.stderr)
                return EXIT_OOM
            raise
        if getattr(trainer, "preempted", False):
            # distinct exit code: supervisors/launchers restart a
            # preempted run without consuming restart budget
            from paddle_tpu.resilience import EXIT_PREEMPTED

            return EXIT_PREEMPTED
        return 0
    if cmd == "test":
        if flags.test_pass >= 0:
            _test_saved_passes(trainer, flags)
        else:
            trainer.test()
        return 0
    if cmd == "gen":
        trainer.generate()
        return 0
    ok = trainer.check_gradient()
    return 0 if ok else 1


def _supervise(rest) -> int:
    """`paddle supervise <train flags>` — run `paddle train` (or, with
    `--supervise_job=serve`, `paddle serve`) as a supervised child:
    restart with backoff on nonzero exit (bounded by --restart_budget;
    train children resume via `--init_model_path=auto`, serve children
    re-offer their `--serve_journal_path` queue themselves), stop with
    a JSON crash report on a crash loop, forward SIGTERM so preemption
    still checkpoints/drains. `--dry_run` prints the child command and
    policy.

    The supervisor itself never initializes jax (a dead child must be
    restartable even when the accelerator runtime is what killed it), so
    this parses flags without `_setup` and forwards `rest` verbatim —
    the child re-parses the same flags and validates --config."""
    from paddle_tpu.utils.flags import FLAGS

    leftover = FLAGS.parse(list(rest))
    if leftover:
        print(f"warning: unrecognized flags {leftover}", file=sys.stderr)
    if FLAGS.supervise_job not in ("train", "serve"):
        print(f"error: --supervise_job={FLAGS.supervise_job!r} (expected "
              "train or serve)", file=sys.stderr)
        return 2
    from paddle_tpu.resilience.supervisor import Supervisor

    return Supervisor(rest, FLAGS).run()


def _test_saved_passes(trainer, flags) -> None:
    """Evaluate saved checkpoints pass by pass (ref: Tester; --test_pass
    with --test_wait polls for passes still being written by a concurrent
    trainer)."""
    import time

    from paddle_tpu.trainer import checkpoint as ckpt

    from paddle_tpu.utils.logging import logger

    save_dir = flags.save_dir or trainer.config.save_dir
    pass_id = flags.test_pass
    while pass_id < flags.num_passes:
        path = os.path.join(save_dir, ckpt.PASS_FMT % pass_id)
        # a checkpoint is complete once meta.json exists (written last by
        # save_checkpoint) — guards against racing a concurrent trainer
        if not os.path.exists(os.path.join(path, "meta.json")):
            newest = ckpt.latest_pass(save_dir)
            if newest is not None and newest > pass_id:
                # rotated away by rolling deletion: skip forward
                logger.warning(
                    "pass %d checkpoint rotated away; skipping to %d",
                    pass_id, newest,
                )
                pass_id = newest
                continue
            if flags.test_wait:
                time.sleep(5)
                continue
            break
        # fallback=False: this is a READ-side job, possibly polling a live
        # trainer's save_dir — it must never quarantine (mutate) that dir
        # or silently report pass-N metrics computed from pass-(N-1) params
        trainer.params, opt_state, _ = ckpt.load_checkpoint(
            path, trainer.opt_state, expected_params=trainer.params,
            sharding_for=trainer.ckpt_sharding_for(), fallback=False,
        )
        if opt_state is not None:
            trainer.opt_state = opt_state
        trainer.test(pass_id=pass_id)
        pass_id += 1


def _dump_config(rest) -> int:
    flags, config = _setup(rest)
    print(config.to_json(indent=2))
    return 0


def _check_checkpoint(rest) -> int:
    """`paddle check-checkpoint <dir>` — offline checkpoint verification.

    <dir> is one pass directory, or a save_dir whose pass-NNNNN children
    are each verified. Each dir gets BOTH checks: the byte-level manifest
    verify (CRC/size of every manifested file) and the sharded-structure
    verify (every shard record in each merged index resolves to its file
    and key, coverage is exact — problems name the owning host). In
    save-dir mode, uncommitted sharded saves (`pass-N.tmp` left by a
    crashed run — the pass never reached its commit agreement) are
    reported as PARTIAL. Exit 0 = everything restorable and no partial
    passes, 1 = problems. Never mutates anything (quarantine is
    load_checkpoint's job)."""
    from paddle_tpu.resilience.manifest import read_manifest
    from paddle_tpu.trainer import checkpoint as ckpt

    targets = [a for a in rest if not a.startswith("-")]
    if len(targets) != 1:
        print("usage: paddle check-checkpoint <pass-dir | save-dir>", file=sys.stderr)
        return 2
    root = targets[0]
    if not os.path.isdir(root):
        print(f"error: {root!r} is not a directory", file=sys.stderr)
        return 2
    if ckpt.has_params_tree(root):
        dirs, partials = [root], []
    else:
        dirs = sorted(
            os.path.join(root, d)
            for d in os.listdir(root)
            if ckpt._is_pass_dir_name(d)
        )
        partials = ckpt.partial_pass_report(root)
        if not dirs and not partials:
            print(f"error: no pass dirs (or params tree) under {root!r}", file=sys.stderr)
            return 2
    bad = 0
    for d in dirs:
        problems = ckpt.verify_checkpoint(d) + ckpt.verify_sharded_shards(d)
        manifest = read_manifest(d)
        # row-coverage holes in a committed dir are PARTIAL, not
        # CORRUPT: the bytes that exist are sound, but a row-sharded
        # table has a gap/overlap (a lost host's rows) — the messages
        # name the missing interval and the responsible host(s)
        row_probs = [p for p in problems if "row coverage:" in p
                     or "rows [" in p]
        if problems and len(row_probs) == len(problems):
            bad += 1
            print(f"PARTIAL  {d} (row-sharded coverage holes — not restorable)")
            for p in problems:
                print(f"         - {p}")
        elif problems:
            bad += 1
            print(f"CORRUPT  {d}")
            for p in problems:
                print(f"         - {p}")
        elif manifest is None:
            print(f"OK?      {d} (no MANIFEST.json — pre-resilience save, contents unverified)")
        else:
            print(f"OK       {d} ({len(manifest.get('files', {}))} files verified)")
    if not ckpt.has_params_tree(root):
        for q in sorted(
            d for d in os.listdir(root) if ckpt.CORRUPT_SUFFIX in d
        ):
            print(f"QUARANTINED  {os.path.join(root, q)} (previously failed restore)")
        for tmp, n_manifests in partials:
            bad += 1
            print(
                f"PARTIAL  {tmp} ({n_manifests} per-host partial manifest(s) "
                "— the save never reached its commit agreement; not "
                "restorable)"
            )
            # name the exact row intervals a torn ROW-SHARDED pass is
            # missing (and which hosts did land their partial index)
            from paddle_tpu.sparse import ckpt as sparse_ckpt

            for hole in sparse_ckpt.partial_row_holes(tmp):
                print(f"         - {hole}")
    return 1 if bad else 0


def _merge_model(rest) -> int:
    flags, config = _setup(rest)
    from paddle_tpu.trainer import checkpoint
    from paddle_tpu.trainer.checkpoint import latest_pass

    save_dir = flags.save_dir or config.save_dir
    pass_id = latest_pass(save_dir)
    assert pass_id is not None, f"no checkpoints under {save_dir}"
    out = os.path.join(save_dir, "merged_model.npz")
    checkpoint.merge_model(save_dir, pass_id, config.to_json(), out)
    print(f"merged model written to {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
