"""Trainer — the pass/batch training driver.

TPU-native replacement for the reference's Trainer/TrainerInternal
(/root/reference/paddle/trainer/Trainer.cpp:266-477,
TrainerInternal.cpp:64-170): the per-batch
startBatch → forwardBackward(updateCallback) → finishBatch pipeline
becomes ONE jit-compiled train_step (forward + grad + optimizer update
fused by XLA, buffers donated); the pass loop, periodic test, stats,
checkpointing and evaluators stay on the host.

When a mesh is configured (opt_config.mesh_shape / FLAGS.mesh_shape) the
step is sharded over devices — see paddle_tpu.parallel.spmd — which is the
replacement for MultiGradientMachine's thread ring and the pserver's dense
sync path.
"""

from __future__ import annotations

import functools
import math
import os
import sys
import time
from typing import Any, Dict, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.data.feeder import DataProvider, create_data_provider
from paddle_tpu.graph.argument import Argument
from paddle_tpu.resilience import NonFiniteLossError, faultinject
from paddle_tpu.graph.machine import GradientMachine
from paddle_tpu.optimizer import Updater
from paddle_tpu.proto import TrainerConfig
from paddle_tpu.trainer import checkpoint as ckpt
from paddle_tpu.trainer.evaluators import EvaluatorChain
from paddle_tpu.observability import compile_log
from paddle_tpu.observability import memory as obs_mem
from paddle_tpu.observability import metrics as obs
from paddle_tpu.observability import numerics as obs_num
from paddle_tpu.observability import spans as obs_spans
from paddle_tpu.sparse import rowshard as sparse_rows
from paddle_tpu.sparse import runtime as sparse_rt
from paddle_tpu.utils import concurrency as cc
from paddle_tpu.utils.flags import FLAGS
from paddle_tpu.utils.logging import logger
from paddle_tpu.utils.stats import global_stats, stat_timer


class TrainerStats:
    """Windowed cost averages (ref: TrainerInternal.h TrainerStats)."""

    def __init__(self):
        self.total_cost = 0.0
        self.total_samples = 0
        self.window_cost = 0.0
        self.window_samples = 0

    def add(self, cost_sum: float, n: int) -> None:
        self.total_cost += cost_sum
        self.total_samples += n
        self.window_cost += cost_sum
        self.window_samples += n

    def reset_window(self) -> None:
        self.window_cost = 0.0
        self.window_samples = 0

    def summary_dict(self) -> Dict[str, Any]:
        """The pass/window stats as one dict — the SINGLE source both the
        human log line (``summary()``) and the metrics.jsonl record are
        rendered from, so log text and telemetry can never drift."""
        return {
            "samples": self.total_samples,
            "AvgCost": self.total_cost / max(self.total_samples, 1),
            "CurrentCost": self.window_cost / max(self.window_samples, 1),
        }

    def summary(self) -> str:
        return " ".join(
            f"{k}={v:d}" if isinstance(v, int) else f"{k}={v:.6g}"
            for k, v in self.summary_dict().items()
        )


class PreemptionExit(Exception):
    """Raised inside the pass loop after a preemption-triggered save."""

    def __init__(self, pass_id: int, saved_path: str):
        super().__init__(f"preempted at pass {pass_id}")
        self.pass_id = pass_id
        self.saved_path = saved_path


class _RollbackRequest(Exception):
    """Internal control flow: train_one_pass asks train() to restore the
    newest verified checkpoint (``--nonfinite_policy=rollback``)."""

    def __init__(self, pass_id: int, batch_id: int):
        super().__init__(f"rollback requested at pass {pass_id} batch {batch_id}")
        self.pass_id = pass_id
        self.batch_id = batch_id


class Trainer:
    def __init__(self, config: TrainerConfig, flags=FLAGS):
        # restart-latency anchor: time_to_first_step_s (the `restart`
        # telemetry record) is measured from here to the first completed
        # launch — the number ROADMAP item 5 tightens heartbeat-grace
        # and crash-loop windows from
        self._t_construct = time.perf_counter()
        self.config = config
        self.flags = flags
        dtype = jnp.float32
        if flags.use_double:
            # the reference's WITH_DOUBLE build; mostly for gradient checks
            jax.config.update("jax_enable_x64", True)
            dtype = jnp.float64
        from paddle_tpu.graph.machine import compute_dtype_of

        # OptimizationConfig.dtype="bfloat16" → bf16 activations/matmuls
        # with f32 master weights + optimizer state (x64 builds stay full)
        compute_dtype = None if flags.use_double else compute_dtype_of(config.opt_config)
        self.gm = GradientMachine(
            config.model_config, dtype=dtype, compute_dtype=compute_dtype,
            scan_unroll=config.opt_config.scan_unroll,
            pallas_rnn=config.opt_config.pallas_rnn,
            pallas_flat=config.opt_config.pallas_flat,
            conv_s2d=config.opt_config.conv_s2d,
            conv_stats_mode=config.opt_config.conv_stats_mode,
            pallas_decoder=config.opt_config.pallas_decoder,
        )
        self.updater = Updater(
            config.opt_config, config.model_config,
            init_model_path=flags.init_model_path or config.init_model_path,
        )
        self.params = self.gm.init_params(seed=flags.seed)
        self.opt_state = self.updater.init_state(self.params)
        self.start_pass = flags.start_pass or config.start_pass
        self.save_dir = flags.save_dir or config.save_dir
        self._train_step_fn = None
        self._test_fwd_fn = None
        self._mesh = None
        mesh_shape = flags.mesh_shape or config.opt_config.mesh_shape
        if not mesh_shape and flags.trainer_count > 1:
            # reference -trainer_count: N-way data parallelism
            mesh_shape = f"data={flags.trainer_count}"
        if mesh_shape:
            from paddle_tpu.parallel.mesh import make_mesh

            self._mesh = make_mesh(mesh_shape)
            self.gm.mesh = self._mesh  # layers with explicit collectives
        # sync-SGD over a data-parallel mesh needs every device to get an
        # identical batch slice: batches whose size is not divisible by
        # the data axis (the end-of-pass remainder) are skipped, matching
        # globalize_batch's multi-host policy (doc/divergences.md)
        self._batch_divisor = 1
        if self._mesh is not None:
            self._batch_divisor = dict(
                zip(self._mesh.axis_names, self._mesh.devices.shape)
            ).get("data", 1)
        self._multiproc = jax.process_count() > 1
        if self._multiproc and self._mesh is None:
            raise ValueError(
                "multi-process training needs a mesh "
                "(--mesh_shape or --trainer_count)"
            )
        # gradient accumulation: N forward/backwards per optimizer update
        # (reference num_batches_per_send_parameter, TrainerInternal.cpp)
        self._accum_n = max(1, int(config.opt_config.num_batches_per_send_parameter))
        # async SGD analog (settings(is_async=True) → algorithm='async_sgd'):
        # per-replica local updates with periodic drift-gated parameter
        # averaging (paddle_tpu/parallel/local_sgd.py). In this mode
        # num_batches_per_send_parameter is the MERGE PERIOD (its
        # reference meaning: batches between parameter sends), not a
        # gradient-accumulation count — reinterpreted HERE, before the
        # fuse/accumulation conflict check below, so an async config with
        # a merge period is never rejected as "accumulation".
        self._async = config.opt_config.algorithm == "async_sgd"
        self._local_sgd = None
        self._lsgd_state = None      # (params_r, opt_r) replica stacks
        self._lsgd_dirty = False     # stacks hold updates self.params lacks
        self._lsgd_batches = 0       # local batches since the last merge
        self._lsgd_discarded = 0     # replicas drift-discarded this pass
        self._sync_n = 1
        if self._async:
            self._sync_n = self._accum_n
            self._accum_n = 1
            if self._mesh is None or self._batch_divisor <= 1:
                logger.warning(
                    "async_sgd with a single data-parallel replica is "
                    "exactly sync SGD — running the ordinary sync step "
                    "(add --mesh_shape=data=N for local-SGD replicas)"
                )
                self._async = False
            else:
                from paddle_tpu.parallel.local_sgd import check_data_only

                check_data_only(self._mesh)
        # fused launches: k consecutive same-shape batches per device
        # dispatch (lax.scan over stacked batches); each batch keeps its
        # own optimizer update, so numerics match the unfused loop
        self._fuse_k = max(1, int(config.opt_config.batches_per_launch))
        if self._fuse_k > 1 and self._accum_n > 1:
            raise ValueError(
                "batches_per_launch > 1 cannot combine with "
                "num_batches_per_send_parameter > 1 — fuse launches of "
                "accumulation micro-batches are not supported; pick one"
            )
        if self._fuse_k > 1 and (self._mesh is not None or self._async):
            logger.warning(
                "batches_per_launch > 1 is a single-chip dispatch-latency "
                "optimization; ignored under a mesh"
            )
            self._fuse_k = 1
        self._fused_step_fn = None
        # per-pass held-out results appended by train(): [(pass_id, {...})]
        # — programmatic convergence-curve access (quality tracking tests,
        # plotcurve's structured counterpart)
        self.test_history: list = []
        # model-FLOP accounting for the pass-end MFU log line: analytic
        # matmul FLOPs per distinct batch-shape signature (one jaxpr
        # trace each — ops/kernel_flops.py; XLA cost analysis undercounts
        # scans so it cannot be the basis)
        self._flops_cache: dict = {}
        self._pass_flops = 0.0
        self._pass_train_s = 0.0
        self._pass_flops_incomplete = False
        # preemption-aware checkpointing: set by the SIGTERM handler that
        # _preemption_guard installs around train(); checked at launch
        # boundaries so the saved checkpoint is always consistent
        self._preempt_requested = False
        self._accum_fns = None
        self._acc = None
        self._acc_batches = 0
        self._acc_samples = 0
        # whole-data batch algorithms (reference Trainer::trainOnePassBatch,
        # Trainer.cpp:492, selected by algorithm=owlqn): one quasi-Newton
        # update per pass, driven host-side between jitted data sweeps
        self._batch_method = None
        self._bm_grad_fn = None
        self._bm_cost_fn = None
        if config.opt_config.algorithm == "owlqn":
            if self._multiproc:
                raise ValueError(
                    "whole-data batch methods (algorithm=owlqn) run "
                    "single-process; drop --mesh_shape/multi-host"
                )
            if self._accum_n > 1:
                raise ValueError(
                    "num_batches_per_send_parameter > 1 (gradient "
                    "accumulation) has no effect under whole-data batch "
                    "methods — each pass already uses the full dataset"
                )
            from paddle_tpu.optimizer.batch_methods import BatchMethod

            # the line search compares full-data objectives, so the
            # objective must be deterministic: dropout and batch-statistics
            # layers are incompatible with whole-data batch methods
            stochastic = [
                f"{l.name} ({l.type})"
                for l in config.model_config.layers
                if getattr(l, "drop_rate", 0) > 0 or "batch_norm" in l.type
            ]
            if stochastic:
                raise ValueError(
                    "whole-data batch methods (algorithm=owlqn) need a "
                    "deterministic objective; remove dropout/batch_norm "
                    "layers: " + ", ".join(stochastic)
                )
            oc = config.opt_config
            self._batch_method = BatchMethod(
                method=oc.learning_method if oc.learning_method in ("lbfgs", "owlqn") else "lbfgs",
                history=oc.owlqn_steps,
                c1=oc.c1,
                backoff=oc.backoff,
                max_backoff=oc.max_backoff,
                l1weight=oc.l1weight,
                l2weight=oc.l2weight,
                learning_rate=oc.learning_rate,
            )
        # divergence policy (--nonfinite_policy, doc/resilience.md): what
        # a NaN/Inf loss does. abort keeps the reference's FP-trap role;
        # skip discards the poisoned update (pre-step buffers stay valid
        # because donation is disabled below); rollback restores the
        # newest verified checkpoint, scales the lr, and fast-forwards
        # past the poison region. Both are bounded by max_nonfinite_steps.
        self._nf_policy = str(getattr(flags, "nonfinite_policy", "abort") or "abort")
        if self._nf_policy not in ("abort", "skip", "rollback"):
            raise ValueError(
                f"--nonfinite_policy={self._nf_policy!r} "
                "(want abort, skip, or rollback)"
            )
        self._nf_budget = max(0, int(getattr(flags, "max_nonfinite_steps", 3)))
        self._nf_count = 0
        self.rollbacks = 0
        # (pass_id, first clean batch): re-run of the rolled-back pass
        # skips batches before this index — the poison region
        self._ff_target: Optional[Tuple[int, int]] = None
        if self._nf_policy != "abort" and (
            self._async or self._batch_method is not None
        ):
            logger.warning(
                "--nonfinite_policy=%s is not supported under %s — a "
                "non-finite loss still aborts (with NonFiniteLossError)",
                self._nf_policy,
                "async_sgd (replica stacks hold no single pre-step state)"
                if self._async else "whole-data batch methods",
            )
            self._nf_policy = "abort"
        if self._nf_policy == "rollback" and not self.save_dir:
            logger.warning(
                "--nonfinite_policy=rollback without --save_dir: there "
                "will be no checkpoint to roll back to — the first "
                "non-finite loss raises NonFiniteLossError"
            )
        # per-layer model-health telemetry (--numerics_log_period,
        # doc/observability.md "Numerics telemetry"): the jitted step
        # grows one aux output — per-layer grad/param/update norms and
        # nonfinite counts, computed on device where the grads already
        # live. The launch signature is fixed at build time by the flag
        # (never per step), so recompiles stay 0 after warmup; the host
        # reads the tiny health tree back only at log-period boundaries.
        self._numerics_period = max(
            0, int(getattr(flags, "numerics_log_period", 0) or 0)
        )
        self._numerics_groups = None
        self._numerics_last = None  # newest launch's device health tree
        if self._numerics_period:
            if (self._accum_n > 1 or self._async
                    or self._batch_method is not None):
                # honest degradation (the hangwatch precedent): these
                # paths apply updates outside _one_batch_step, so the
                # aux would misattribute — better absent than wrong
                logger.warning(
                    "--numerics_log_period is not supported under "
                    "gradient accumulation / async_sgd / whole-data "
                    "batch methods — numerics telemetry disabled for "
                    "this run"
                )
                self._numerics_period = 0
            else:
                self._numerics_groups = obs_num.layer_groups(
                    config.model_config, list(self.params)
                )
        # row-sharded sparse-parameter training (paddle_tpu/sparse/,
        # doc/sparse.md): register each sparse_update table's row count
        # so the durable shard protocol stamps row_range into its shard
        # records; refuse loudly (before any training) when the current
        # host set cannot hold a table within --sparse_row_budget; and
        # account touched rows per pass for the kind=sparse record
        self._sparse_plan = self.gm.sparse_prefetch_plan()
        self._sparse_stats = None
        if self._sparse_plan:
            tables = {
                pn: int(self.params[pn].shape[0])
                for pn, _ in self._sparse_plan
                if pn in self.params
            }
            err = sparse_rows.row_budget_error(
                tables, jax.process_count(),
                int(getattr(flags, "sparse_row_budget", 0) or 0),
            )
            if err:
                raise ValueError(err)
            sparse_rt.register_tables(tables)
            self._sparse_stats = sparse_rt.SparseStats({
                pn: int(np.prod(self.params[pn].shape[1:]) or 1)
                * self.params[pn].dtype.itemsize
                for pn in tables
            })
        # last live memory snapshot (pass-boundary sampling) — the OOM
        # pre-mortem's "what did the allocator look like" fallback when
        # sampling after the OOM itself fails — and the last launch
        # position, so the pre-mortem can say WHERE the run died
        self._mem_last = None
        self._last_launch: Optional[Tuple[int, int]] = None
        # telemetry (doc/observability.md): per-host metrics.jsonl stream
        # (--metrics_path, defaulting to save_dir) + Chrome trace-event
        # spans (--trace_events_path). No-ops when neither is configured.
        obs.configure_from_flags(flags, host=jax.process_index())
        obs_spans.configure_from_flags(flags, host=jax.process_index())
        # compile & cost attribution (doc/observability.md "Compile
        # telemetry"): every launch-group compilation becomes a
        # kind=compile record (trace/compile seconds, cache hit/miss,
        # XLA cost analysis), and --compile_cache_dir persists compiled
        # executables across processes so elastic relaunches stop
        # re-paying the full trace+compile (ROADMAP item 5)
        if getattr(flags, "compile_cache_dir", ""):
            compile_log.enable_compile_cache(flags.compile_cache_dir)
        self._compiles = compile_log.CompileRegistry(
            device_kind=jax.devices()[0].device_kind
        )
        # hang defense (doc/resilience.md "Hang detection"): the step
        # loop pings the watchdog at every launch boundary; a stall
        # beyond --step_hang_timeout dumps forensics (hang_report.json
        # in the run dir — where the supervisor's crash report looks)
        # and exits EXIT_HANG. On a multi-host pod every host runs one:
        # a rank wedged inside a collective because ANOTHER rank died
        # still produces a named, stack-carrying report.
        self._hangwatch = None
        hang_timeout = float(getattr(flags, "step_hang_timeout", 0) or 0)
        if hang_timeout > 0:
            from paddle_tpu.resilience.hangwatch import HangWatch, run_dir_of

            self._hangwatch = HangWatch(
                hang_timeout,
                report_dir=run_dir_of(
                    getattr(flags, "metrics_path", "")
                    or self.save_dir or "."
                ),
            )
        # cluster liveness: renew this host's heartbeat file so
        # cluster_launch can tell a wedged-but-alive rank from a slow one
        self._heartbeat = None
        hb_interval = float(getattr(flags, "heartbeat_interval", 0) or 0)
        if hb_interval > 0:
            from paddle_tpu.resilience import heartbeat as hb

            hb_dir = hb.resolve_dir(
                getattr(flags, "heartbeat_dir", ""), self.save_dir
            )
            if hb_dir:
                self._heartbeat = hb.HeartbeatWriter(
                    hb_dir, jax.process_index(), hb_interval
                )
                # first beat NOW, before the (possibly multi-GB, shared-
                # fs) checkpoint restore below: a monitor must see "this
                # rank is alive and initializing", not silence it could
                # mistake for a wedge
                self._heartbeat.beat(phase="init")
            else:
                logger.warning(
                    "--heartbeat_interval=%g but neither --heartbeat_dir "
                    "nor --save_dir is set — heartbeats disabled",
                    hb_interval,
                )
        # set by the PreemptionExit path: the CLI turns it into the
        # distinct EXIT_PREEMPTED process code so supervisors/launchers
        # can restart preempted runs without consuming restart budget
        self.preempted = False
        # async checkpointing (--async_checkpoint, doc/performance.md +
        # doc/resilience.md "Elastic sharded checkpointing"): save() pays
        # only the device→host snapshot; the durable-protocol write runs
        # on a background thread. Multi-process runs use the SHARDED
        # async checkpointer: each host's writer persists only the
        # shards it owns, and the one remaining collective is drain()'s
        # cheap pass-end commit agreement over the distributed runtime's
        # host KV store (no device collectives on the save path at all).
        self._async_ckpt = None
        if getattr(flags, "async_checkpoint", False) and self.save_dir:
            inflight = int(getattr(flags, "ckpt_inflight_limit", 1) or 1)
            if self._multiproc:
                from paddle_tpu.utils.barrier import distributed_client

                if distributed_client() is None:
                    logger.warning(
                        "--async_checkpoint multi-process needs the jax "
                        "distributed runtime's KV client for the pass-end "
                        "commit agreement — unavailable here; saving "
                        "synchronously"
                    )
                else:
                    from paddle_tpu.trainer.async_ckpt import (
                        ShardedAsyncCheckpointer,
                    )

                    self._async_ckpt = ShardedAsyncCheckpointer(
                        self.save_dir,
                        inflight_limit=inflight,
                        hangwatch=self._hangwatch,
                        agree_timeout=float(
                            getattr(flags, "ckpt_agree_timeout", 600.0) or 600.0
                        ),
                    )
            else:
                from paddle_tpu.trainer.async_ckpt import AsyncCheckpointer

                self._async_ckpt = AsyncCheckpointer(
                    self.save_dir,
                    inflight_limit=inflight,
                    hangwatch=self._hangwatch,
                )
        # restart telemetry: restore cost is captured by _maybe_restore,
        # the `restart` record is emitted at the first completed launch
        self._restore_s = 0.0
        self._restart_pending = True
        self._maybe_restore()
        # StaticPruningHook init semantics: mask values once at startup
        self.params = self.updater.apply_init_hooks(self.params)

    # ------------------------------------------------------------ restore

    def ckpt_sharding_for(self):
        """Multi-process restore must rebuild every value as a global
        array sharded onto the CURRENT mesh (a host-local jnp array could
        not be resharded across processes by jit). None single-process."""
        if self._mesh is None or not self._multiproc:
            return None
        from paddle_tpu.parallel.spmd import checkpoint_sharding_fn

        return checkpoint_sharding_fn(self._mesh, self.gm)

    def _maybe_restore(self) -> None:
        self._restored_pass: Optional[int] = None
        init_path = self.flags.init_model_path or self.config.init_model_path
        sharding_for = self.ckpt_sharding_for()
        pre_verified = False
        if init_path == "auto":
            # newest checkpoint under save_dir that passes manifest
            # verification; a fresh run (nothing restorable) starts clean
            init_path = (
                ckpt.find_restorable_checkpoint(self.save_dir)
                if self.save_dir else None
            )
            if init_path is None:
                logger.info(
                    "--init_model_path=auto: no restorable checkpoint under "
                    "%r — starting fresh", self.save_dir,
                )
                return
            pre_verified = True  # find_restorable just CRC'd this dir
        if init_path:
            # fallback (quarantine + walk to an earlier pass) only within
            # OUR OWN save_dir: an explicit init_model_path pointing at a
            # foreign/pretrained model dir must fail loudly, never rename
            # a shared directory or substitute weights the user did not
            # ask for (same contract as api.py loadParameters)
            own = bool(self.save_dir) and os.path.abspath(
                os.path.dirname(os.path.normpath(init_path))
            ) == os.path.abspath(self.save_dir)
            t_restore = time.perf_counter()
            self.params, opt_state, meta = ckpt.load_checkpoint(
                init_path,
                self.opt_state,
                missing=self.flags.load_missing_parameter_strategy,
                expected_params=self.params,
                sharding_for=sharding_for,
                # don't re-CRC a multi-GB checkpoint the auto scan just
                # verified moments ago (fallback candidates, if the load
                # has to walk to one, are still verified)
                verify=not pre_verified,
                fallback=pre_verified or own,
            )
            self._restore_s = time.perf_counter() - t_restore
            if opt_state is not None:
                self.opt_state = opt_state
            restored = self._note_restored(init_path, meta)
            if pre_verified and restored is not None and self.start_pass == 0:
                # auto-resume: continue pass numbering past the pass the
                # load ACTUALLY restored (meta pass_id — the chain may
                # have fallen back below the scanned candidate), the
                # reference's restart-from-last-pass minus the "hope the
                # files are intact" part
                self.start_pass = restored + 1
                logger.info(
                    "--init_model_path=auto: resumed pass %d from %s "
                    "(start_pass=%d)", restored, init_path, self.start_pass,
                )
            return
        if self.start_pass > 0:
            path = os.path.join(self.save_dir, ckpt.PASS_FMT % (self.start_pass - 1))
            t_restore = time.perf_counter()
            self.params, opt_state, meta = ckpt.load_checkpoint(
                path, self.opt_state, expected_params=self.params,
                sharding_for=sharding_for,
            )
            self._restore_s = time.perf_counter() - t_restore
            if opt_state is not None:
                self.opt_state = opt_state
            self._note_restored(path, meta)

    def _note_restored(self, path: str, meta: Optional[Dict] = None) -> Optional[int]:
        """Record which pass in OUR save_dir this run restored from, so
        rolling deletion never removes the only known-good state (the
        load may also have FALLEN BACK to an earlier pass than the path
        asked for — trust meta['pass_id'] when present)."""
        if (self._sparse_stats is not None and meta is not None
                and isinstance(meta.get("sparse_hosts"), int)
                and meta["sparse_hosts"] != jax.process_count()):
            # the checkpoint was written by a different host set: the
            # sharded restore just re-sliced every table's row ranges
            # onto the current mesh — count it as a reshard event
            self._sparse_stats.note_reshard(
                meta["sparse_hosts"], jax.process_count()
            )
            logger.info(
                "sparse tables resharded across relaunch: %d -> %d host(s)",
                meta["sparse_hosts"], jax.process_count(),
            )
        if meta is not None and isinstance(meta.get("pass_id"), int):
            pass_id = meta["pass_id"]
        else:
            base = os.path.basename(os.path.normpath(path))
            if base.endswith(".old"):
                # torn-commit leftover (see checkpoint._commit): the pass
                # id still applies, so resume numbering stays correct
                base = base[: -len(".old")]
            if not (base.startswith("pass-") and base[5:].isdigit()):
                return None
            pass_id = int(base[5:])
        # abspath both sides: a relative --save_dir must still match an
        # absolute init path to the same directory (and vice versa)
        if self.save_dir and os.path.abspath(
            os.path.dirname(os.path.normpath(path))
        ) == os.path.abspath(self.save_dir):
            self._restored_pass = pass_id
        return pass_id

    # ------------------------------------------------------------- steps

    def _kept_out_layers(self):
        """Layer outputs the train step must return: network outputs plus
        everything the evaluator chain reads."""
        eval_layers = set()
        for e in self.config.model_config.evaluators:
            eval_layers.update(e.input_layers)
        return set(self.gm.network.output_layer_names) | eval_layers

    def _one_batch_step(self, sparse: bool = True):
        """The single-batch grad→update→state→keep body shared by the
        ordinary train step and the fused-launch scan, so the two paths
        cannot diverge."""
        grad_fn = self.gm.grad_fn(
            remat=self.config.opt_config.remat, sparse=sparse
        )
        updater = self.updater
        out_layers = self._kept_out_layers()
        nm_groups = self._numerics_groups

        def step(params, opt_state, in_args, rng, batch_size):
            loss, grads, outputs, state_updates = grad_fn(params, in_args, rng)
            new_params, new_opt = updater(params, grads, opt_state, batch_size)
            for k, v in state_updates.items():
                new_params[k] = v
            keep = {k: v for k, v in outputs.items() if k in out_layers}
            if nm_groups is None:
                return new_params, new_opt, loss, keep
            # numerics aux: fused into THIS launch (grads and both
            # parameter trees are already live on device) — one extra
            # [4]-vector per layer in the outputs, zero extra launches
            health = obs_num.step_health(params, new_params, grads, nm_groups)
            return new_params, new_opt, loss, keep, health

        return step

    @property
    def _donate_steps(self) -> bool:
        """skip/rollback must be able to hand back the pre-step state of
        a poisoned update, so the train steps may not donate their input
        buffers (the documented ~2x parameter-memory cost of those
        policies); abort keeps the donating fast path."""
        return self._nf_policy == "abort"

    def _build_train_step(self):
        step = self._one_batch_step()

        if self._mesh is not None:
            from paddle_tpu.parallel.spmd import shard_train_step

            return shard_train_step(
                step, self._mesh, self.gm, donate=self._donate_steps,
                extra_outs=1 if self._numerics_groups is not None else 0,
            )
        return jax.jit(
            step, donate_argnums=(0, 1) if self._donate_steps else ()
        )

    def _build_accum_steps(self):
        """Gradient accumulation (num_batches_per_send_parameter = N > 1,
        reference TrainerInternal: N forwardBackwards per parameter send):
        ``astep`` folds one batch's sample-weighted gradients into an
        on-device accumulator; ``ustep`` applies ONE optimizer update from
        the accumulated mean. Dense gradients only — RowSparseGrad shapes
        vary per batch and cannot live in a fixed-shape accumulator."""
        grad_fn = self.gm.grad_fn(remat=self.config.opt_config.remat, sparse=False)
        updater = self.updater
        out_layers = self._kept_out_layers()

        def astep(params, acc, in_args, rng, n):
            loss, grads, outputs, state_updates = grad_fn(params, in_args, rng)
            new_acc = jax.tree_util.tree_map(lambda a, g: a + g * n, acc, grads)
            new_params = dict(params)
            for k, v in state_updates.items():  # BN stats advance per batch
                new_params[k] = v
            keep = {k: v for k, v in outputs.items() if k in out_layers}
            return new_params, new_acc, loss, keep

        def ustep(params, opt_state, acc, total_n):
            mean = jax.tree_util.tree_map(lambda a: a / total_n, acc)
            new_params, new_opt = updater(params, mean, opt_state, total_n)
            zero = jax.tree_util.tree_map(jnp.zeros_like, acc)
            return new_params, new_opt, zero

        if self._mesh is not None:
            from paddle_tpu.parallel.spmd import shard_accum_steps

            return shard_accum_steps(
                astep, ustep, self._mesh, self.gm, donate=self._donate_steps
            )
        if not self._donate_steps:
            return jax.jit(astep), jax.jit(ustep)
        return (
            jax.jit(astep, donate_argnums=(0, 1)),
            jax.jit(ustep, donate_argnums=(0, 1, 2)),
        )

    def _build_fused_step(self):
        """k optimizer steps over k stacked batches in ONE device launch
        (``batches_per_launch``): a lax.scan whose carry is (params,
        opt_state) and whose xs are the stacked inputs + per-batch rngs +
        sample counts. Dense gradients only (same constraint and reason as
        gradient accumulation: sparse row sets vary per batch and cannot
        ride a fixed-shape scan input)."""
        one = self._one_batch_step(sparse=False)

        def fstep(params, opt_state, stacked, rngs, ns):
            def body(carry, xs):
                p, o = carry
                in_args, rng, n = xs
                # 4-tuple, or 5 with the numerics health aux — the scan
                # stacks whatever ys the body returns, so both shapes
                # ride the same machinery
                out = one(p, o, in_args, rng, n)
                return (out[0], out[1]), tuple(out[2:])

            (p, o), ys = jax.lax.scan(
                body, (params, opt_state), (stacked, rngs, ns)
            )
            return (p, o) + tuple(ys)

        return jax.jit(
            fstep, donate_argnums=(0, 1) if self._donate_steps else ()
        )

    @property
    def fused_step(self):
        if self._fused_step_fn is None:
            self._fused_step_fn = self._build_fused_step()
        return self._fused_step_fn

    def _launch_groups(self, gen):
        """Group the (n, host, device) batch stream for fused launches.

        Yields ("fused", [k items]) for runs of k consecutive batches with
        identical tree structure/shapes/sample count, and ("single", item)
        otherwise (shape changes, end-of-pass remainders) — partial groups
        run through the ordinary one-batch step rather than compiling a
        scan variant per remainder length."""
        if self._fuse_k <= 1:
            for item in gen:
                yield "single", item
            return

        def sig_of(item):
            # signature from the HOST-side Argument dict: the packed
            # device tree is a deterministic function of the host batch,
            # so identical host field shapes/dtypes imply an identical
            # device tree — and reading ``.shape`` off O(slots) numpy
            # fields costs nothing, where the old jax tree_flatten of
            # the device tree walked O(leaves) registered pytree nodes
            # per batch, every step, on the hot path
            n, host, dev = item
            sig = [n]
            if host is None:
                # no host-side view (direct device trees — tests, future
                # host-less providers): fall back to the device-tree
                # signature the grouping originally used
                leaves, treedef = jax.tree_util.tree_flatten(dev)
                sig.append((
                    treedef,
                    tuple((l.shape, str(l.dtype)) for l in leaves),
                ))
                return tuple(sig)
            for name, arg in host.items():  # dict order is stable per provider
                sig.append((
                    name,
                    tuple(
                        None if f is None else (f.shape, str(f.dtype))
                        for f in (arg.value, arg.ids, arg.seq_lengths,
                                  arg.sub_seq_lengths, arg.weight)
                    ),
                ))
            return tuple(sig)

        buf, sig = [], None
        for item in gen:
            s = sig_of(item)
            if buf and s != sig:
                for it in buf:
                    yield "single", it
                buf = []
            sig = s
            buf.append(item)
            if len(buf) == self._fuse_k:
                yield "fused", buf
                buf, sig = [], None
        for it in buf:
            yield "single", it

    def _build_test_fwd(self):
        gm = self.gm

        def fwd(params, in_args):
            outputs, _ = gm.forward(params, in_args, pass_type="test", rng=None)
            return outputs

        if self._mesh is not None:
            from paddle_tpu.parallel.spmd import shard_test_fwd

            return shard_test_fwd(fwd, self._mesh, self.gm)
        return jax.jit(fwd)

    @property
    def train_step(self):
        if self._train_step_fn is None:
            self._train_step_fn = self._build_train_step()
        return self._train_step_fn

    @property
    def test_fwd(self):
        if self._test_fwd_fn is None:
            self._test_fwd_fn = self._build_test_fwd()
        return self._test_fwd_fn

    # ------------------------------------------------------------- data

    def _provider(self, for_test: bool, ordered: Optional[bool] = None) -> Optional[DataProvider]:
        dc = self.config.test_data_config if for_test else self.config.data_config
        if dc is None:
            return None
        slot_names = self.config.model_config.input_layer_names
        from paddle_tpu.utils.retry import RetryPolicy

        return create_data_provider(
            dc,
            self.config.opt_config.batch_size,
            slot_names,
            seed=self.flags.seed,
            for_test=for_test if ordered is None else ordered,
            # resilience knobs come from THIS trainer's flags object, not
            # the process-global FLAGS (programmatic embeddings pass
            # their own _Flags instance)
            stall_timeout=self.flags.data_stall_timeout,
            max_bad_samples=self.flags.max_bad_samples,
            retry=RetryPolicy.from_flags(self.flags, name="data-provider"),
            packer_threads=getattr(self.flags, "data_packer_threads", None),
            prefetch_depth=getattr(self.flags, "prefetch_depth", None),
        )

    # ------------------------------------------------------------- train

    def _preemption_guard(self):
        """Context manager active for the duration of train(): installs a
        SIGTERM handler that requests a checkpoint-and-exit at the next
        launch boundary (TPU preemption notices arrive as SIGTERM). Only
        installable from the main thread — elsewhere (library embedding,
        test runners) it degrades to a no-op. The previous handler is
        restored on exit, and a SECOND SIGTERM falls through to it, so a
        stuck save can still be killed the ordinary way. Gate:
        flags.save_on_preempt (default on; the handler itself is cheap)."""
        import contextlib
        import signal

        # Gates: flag off; non-main thread (signal API unavailable);
        # multi-process (the flag would be per-host and unsynchronized —
        # hosts at different launch boundaries would issue mismatched
        # collectives and deadlock the save; multi-host preemption relies
        # on the deterministic periodic saves instead, doc/divergences.md)
        if (not getattr(self.flags, "save_on_preempt", True)
                or self._multiproc
                or cc.current_thread() is not cc.main_thread()):
            return contextlib.nullcontext()

        @contextlib.contextmanager
        def guard():
            prev = signal.getsignal(signal.SIGTERM)
            # None = installed by non-Python code; fall through to default
            fallback = prev if prev is not None else signal.SIG_DFL

            def on_sigterm(signum, frame):
                # flag-only: logging (or any IO) from a signal handler can
                # re-enter a buffered stream mid-write and raise; the
                # message is logged at the launch-boundary check instead
                self._preempt_requested = True
                signal.signal(signal.SIGTERM, fallback)  # 2nd signal: old path

            signal.signal(signal.SIGTERM, on_sigterm)
            try:
                yield
            finally:
                self._preempt_requested = False
                if signal.getsignal(signal.SIGTERM) is on_sigterm:
                    signal.signal(signal.SIGTERM, fallback)

        return guard()

    def train(self, num_passes: Optional[int] = None) -> None:
        num_passes = num_passes or self.flags.num_passes
        train_provider = self._provider(for_test=False)
        assert train_provider is not None, "no train data configured"
        if self._batch_method is not None:
            if self._hangwatch is not None:
                # honest degradation, not a silent one: the operator who
                # set the flag must not believe the hangwatch is armed
                logger.warning(
                    "--step_hang_timeout is not supported under "
                    "whole-data batch methods (the pass is one long "
                    "sweep with no launch boundary to ping) — hangwatch "
                    "disabled for this run"
                )
            # the heartbeat is a wall-clock daemon, no launch boundary
            # needed — it MUST run here, or a cluster_launch monitoring
            # the same flags would tear down a healthy batch-mode job
            # as silent
            if self._heartbeat is not None:
                self._heartbeat.start()
            try:
                return self._train_batch_mode(num_passes, train_provider)
            finally:
                if self._heartbeat is not None:
                    self._heartbeat.stop()
        rng = jax.random.PRNGKey(self.flags.seed)
        saved_pass = -1
        # liveness plumbing runs for the whole loop INCLUDING the final
        # save: a save wedged on a dead shared fs is still a hang, and
        # the heartbeat must outlive the last step so cluster_launch
        # never mistakes "finishing up" for "went silent"
        if self._hangwatch is not None:
            self._hangwatch.start()
        if self._heartbeat is not None:
            self._heartbeat.start()
        try:
            with self._preemption_guard():
                try:
                    # while-loop (not range): a rollback rewinds pass_id to
                    # just after the restored checkpoint. Per-pass keys are
                    # folded from the base key, so a re-run pass replays the
                    # same rng stream it saw the first time.
                    pass_id = self.start_pass
                    while pass_id < num_passes:
                        pass_rng = jax.random.fold_in(rng, pass_id)
                        try:
                            self.train_one_pass(pass_id, train_provider, pass_rng)
                        except _RollbackRequest as rb:
                            pass_id = self._apply_rollback(rb)
                            continue
                        with stat_timer("test"):
                            pass_results = self.test(pass_id=pass_id)
                        if pass_results:
                            self.test_history.append((pass_id, pass_results))
                        if self.save_dir and (pass_id + 1) % max(self.flags.saving_period, 1) == 0:
                            self.save(pass_id)
                            saved_pass = pass_id
                        logger.info(global_stats.summary())
                        if self._hangwatch is not None:
                            self._hangwatch.ping(pass_id)
                        pass_id += 1
                except PreemptionExit as e:
                    # the SIGTERM save must be DURABLE before the clean
                    # exit-18 return: a preempted pod may be reclaimed
                    # the instant the process dies
                    self._drain_async_ckpt()
                    if e.saved_path:
                        logger.info(
                            "preemption: checkpoint saved at %s — exiting the "
                            "train loop cleanly (resume with --init_model_path "
                            "on that pass dir and --start_pass=%d)",
                            e.saved_path, e.pass_id,
                        )
                    else:
                        logger.info(
                            "preemption: exiting the train loop cleanly "
                            "(no --save_dir configured, nothing was saved)"
                        )
                    # the CLI maps this to EXIT_PREEMPTED (18): restart
                    # machinery treats the death as the scheduler's call,
                    # not the run's, and charges no restart budget
                    self.preempted = True
                    obs.emit("run_end", status="preempted")
                    obs.flush()
                    return
            if (
                self.save_dir
                and saved_pass != num_passes - 1
                and num_passes > self.start_pass  # at least one pass actually ran
            ):
                self.save(num_passes - 1, final=True)
            # process-exit barrier: everything enqueued must be durable
            # (and any background-write failure must surface) before the
            # run may claim it completed
            self._drain_async_ckpt()
            # the on-purpose end of the run: a stream WITHOUT this record
            # ended in a crash/kill (what `paddle metrics` flags and the
            # supervisor's crash report captures)
            obs.emit("run_end", status="completed")
            obs.flush()
            obs_spans.export()
        except Exception as e:
            # OOM pre-mortem (doc/resilience.md "OOM forensics"): a
            # RESOURCE_EXHAUSTED death leaves oom_report.json — the
            # per-group static footprint ranked, the last live memory
            # snapshot, the telemetry tail — then re-raises; the CLI
            # maps it to the distinct EXIT_OOM so supervisors classify
            # the death (and charge budget — an OOM loop is
            # deterministic poison, not scheduling)
            if obs_mem.is_oom_error(e):
                self._oom_premortem(e)
            raise
        finally:
            if self._hangwatch is not None:
                self._hangwatch.stop()
            if self._heartbeat is not None:
                self._heartbeat.stop()

    # --------------------------------------------- whole-data batch mode

    def _bm_fns(self):
        if self._bm_grad_fn is None:
            gm = self.gm
            # pass_type="test": the line search needs a deterministic
            # objective (the dropout/batch_norm guard in __init__ rejects
            # models where train and test objectives differ)
            loss = functools.partial(gm.loss_fn, pass_type="test")
            self._bm_grad_fn = jax.jit(jax.value_and_grad(loss, has_aux=True))
            self._bm_cost_fn = jax.jit(lambda p, b: loss(p, b, None)[0])
        return self._bm_grad_fn, self._bm_cost_fn

    def _full_data_sweep(self, params, provider, want_grad: bool):
        """Stream the whole dataset once; returns (mean cost, mean grads
        over trainable params as numpy or None, total samples). The
        jitted per-batch step is the 'one forwardBackward over all data'
        of reference trainOnePassBatch, streamed to bound device memory."""
        grad_fn, cost_fn = self._bm_fns()
        trainable = {k for k, t in self.gm.trainable_mask().items() if t}
        total_c, total_n, total_g = 0.0, 0, None
        for batch in provider.batches():
            n = _batch_num_samples(batch)
            w = float(n)
            if want_grad:
                (loss, _aux), grads = grad_fn(params, batch, None)
                gw = {k: grads[k] * w for k in trainable}
                total_g = gw if total_g is None else {
                    k: total_g[k] + gw[k] for k in trainable
                }
            else:
                loss = cost_fn(params, batch)
            total_c += float(loss) * w
            total_n += n
        assert total_n, "empty training data"
        # host-side quasi-Newton math runs in float64 regardless of the
        # device dtype — curvature dot products are precision-sensitive
        mean_g = (
            {k: np.asarray(v, np.float64) / total_n for k, v in total_g.items()}
            if want_grad
            else None
        )
        return total_c / total_n, mean_g, total_n

    def _train_batch_mode(self, num_passes: int, provider: DataProvider) -> None:
        """One quasi-Newton update per pass (Trainer::trainOnePassBatch,
        reference Trainer.cpp:492): full-data gradient → L-BFGS/OWL-QN
        direction → backtracking line search → accept/reject."""
        bm = self._batch_method
        static = {
            k: v for k, v in self.params.items()
            if not self.gm.trainable_mask().get(k, True)
        }
        dtypes = {k: v.dtype for k, v in self.params.items()}

        def merge(xt):
            # host math is float64; devices keep their configured dtype
            full = {k: jnp.asarray(v, dtypes[k]) for k, v in xt.items()}
            full.update(static)
            return full

        def eval_cost(xt):
            c, _, _ = self._full_data_sweep(merge(xt), provider, want_grad=False)
            return c

        cached = None  # (cost, grads, n) from a rejected pass: params did
        # not move and the objective is deterministic, so the sweep would
        # recompute identical values — reuse instead of re-sweeping
        saved_pass = -1
        last_pass = self.start_pass - 1
        for pass_id in range(self.start_pass, num_passes):
            last_pass = pass_id
            with stat_timer("onePass"):
                if cached is not None:
                    cost, grads, n = cached
                    cached = None
                else:
                    cost, grads, n = self._full_data_sweep(
                        self.params, provider, want_grad=True
                    )
                if not np.isfinite(cost):
                    # same typed failure as the per-step trap so
                    # supervisors/tests classify divergence vs. crash
                    # uniformly (subclasses FloatingPointError)
                    raise NonFiniteLossError(
                        f"non-finite whole-data cost ({cost}) at pass {pass_id}",
                        value=float(cost), pass_id=pass_id,
                    )
                bm.record_grad(grads)  # completes the previous pass's (s, y)
                xt = {
                    k: np.asarray(v, np.float64)
                    for k, v in self.params.items()
                    if k not in static
                }
                direction = bm.direction(xt, grads)
                accepted, x_new, f_new = bm.line_search(
                    xt, cost, grads, direction, eval_cost
                )
            if accepted:
                self.params = merge(x_new)
            logger.info(
                "Pass=%d AcceptedPass=%d samples=%d Cost=%g (objective %g%s)",
                pass_id,
                bm.n_accepted - 1 if accepted else -1,
                n,
                cost,
                f_new,
                "" if accepted else ", line search rejected",
            )
            with stat_timer("test"):
                self.test(pass_id=pass_id)
            if (
                self.flags.show_parameter_stats_period
                and (pass_id + 1) % self.flags.show_parameter_stats_period == 0
            ):
                self.show_parameter_stats()
            if (
                accepted
                and self.save_dir
                and (bm.n_accepted - 1) % max(self.flags.saving_period, 1) == 0
            ):
                self.save(pass_id)
                saved_pass = pass_id
            logger.info(global_stats.summary())
            if not accepted:
                cached = (cost, grads, n)
                if not bm.on_reject():
                    # a tempered steepest-descent step already failed; the
                    # deterministic objective would reject identically forever
                    logger.info(
                        "Pass=%d: line search cannot improve the objective — "
                        "converged, stopping batch-mode training", pass_id,
                    )
                    break
        if self.save_dir and saved_pass != last_pass and last_pass >= self.start_pass:
            self.save(last_pass, final=True)
        self._drain_async_ckpt()

    def _count_model_flops(self, key, fn, *args) -> float:
        """Analytic model matmul FLOPs of one ``fn(*args)`` call, cached
        by batch-shape signature (one jaxpr trace per distinct shape —
        the same granularity jit compiles at). Never raises: accounting
        must not be able to break training."""
        if key in self._flops_cache:
            f = self._flops_cache[key]
        else:
            try:
                from paddle_tpu.ops.kernel_flops import train_step_flops

                f = train_step_flops(fn, *args)
            except Exception as e:
                # cached failure: don't re-trace every batch — but leave a
                # trace, once per shape, so broken FLOPs accounting is
                # diagnosable instead of silently zeroing the MFU line
                logger.debug(
                    "FLOPs accounting disabled for batch signature %r: %s",
                    key, e, exc_info=True,
                )
                f = None
            self._flops_cache[key] = f
        if f is None:
            # a partially-counted pass must not log a confident number
            # ("omitted, never guessed")
            self._pass_flops_incomplete = True
            return 0.0
        return f

    @staticmethod
    def _shape_sig(tree):
        return tuple(
            (str(getattr(l, "shape", ())), str(getattr(l, "dtype", "")))
            for l in jax.tree_util.tree_leaves(tree)
        )

    def _mfu_fields(self) -> Dict[str, float]:
        """Model-FLOP throughput of the finished pass as structured
        fields, over TRAINING time only (the summed step windows —
        in-pass test/save/stats time would understate it). Empty on the
        accumulation path and whenever any batch's counting failed; MFU
        only when the chip's peak is known — never guessed. Both the
        human log note (``_mfu_note``) and the pass_end metrics record
        render from THIS dict."""
        if (self._pass_flops <= 0 or self._pass_train_s <= 0
                or self._pass_flops_incomplete):
            return {}
        from paddle_tpu.ops.kernel_flops import peak_tflops

        tfps = self._pass_flops / self._pass_train_s / 1e12
        fields = {"model_tflops_per_sec": tfps}
        peak = peak_tflops(jax.devices()[0].device_kind)
        if peak:
            fields["mfu"] = tfps / (peak * jax.device_count())
        return fields

    def _mfu_note(self, fields: Optional[Dict[str, float]] = None) -> str:
        """', model X TFLOP/s, MFU Y' rendered from ``_mfu_fields``."""
        if fields is None:
            fields = self._mfu_fields()
        if not fields:
            return ""
        note = f", model {fields['model_tflops_per_sec']:.3g} TFLOP/s"
        if "mfu" in fields:
            note += f", MFU {fields['mfu']:.3f}"
        return note

    def train_one_pass(self, pass_id: int, provider: DataProvider, rng) -> None:
        stats = TrainerStats()
        evaluators = EvaluatorChain(self.config.model_config)
        evaluators.start()
        log_period = self.flags.log_period
        profiling = False
        self._pass_flops = 0.0
        self._pass_train_s = 0.0
        self._pass_flops_incomplete = False
        self._lsgd_discarded = 0
        t0 = time.monotonic()  # rate clock: immune to NTP steps mid-pass
        pass_t0 = time.perf_counter()  # span + pass_time_s clock
        batch_id = 0
        step_times: list = []
        launch_counts = {"single": 0, "fused": 0}
        profiled = False
        # rollback fast-forward: when re-running the pass that diverged,
        # consume (without training) the batches up to and past the
        # poison region, so the same poisoned update is not re-applied
        ff_until = 0
        if self._ff_target is not None:
            tgt_pass, tgt_batch = self._ff_target
            if pass_id == tgt_pass:
                ff_until = tgt_batch
                logger.info(
                    "Pass %d: fast-forwarding past the poison region "
                    "(skipping batches < %d)", pass_id, tgt_batch,
                )
            if pass_id >= tgt_pass:
                self._ff_target = None
        for kind, group in self._launch_groups(
            self._device_prefetch(self._global_batches(provider))
        ):
            # launch boundary: the hangwatch ping that proves the step
            # loop is alive — everything below (stall site included)
            # counts against --step_hang_timeout. BEFORE the
            # fast-forward skip: replaying the data pipeline past a
            # rollback's poison region IS progress (same rationale as
            # the feeder watchdog's fast-forward heartbeat), and a long
            # replay must not be misdiagnosed as a hang mid-recovery.
            if self._hangwatch is not None:
                self._hangwatch.ping(pass_id, batch_id)
            self._last_launch = (pass_id, batch_id)
            if ff_until and batch_id < ff_until:
                batch_id += len(group) if kind == "fused" else 1
                continue
            # chaos sites (one hit per trained launch):
            # `trainer.crash=exit@N` is a deterministic mid-run process
            # death — what `paddle supervise` drills recover from;
            # `trainer.stall=sleep:S@N` wedges the step loop — what the
            # hangwatch (--step_hang_timeout) drills detect
            faultinject.fault_point(
                "trainer.crash", info=f"pass={pass_id} batch={batch_id}"
            )
            faultinject.fault_point(
                "trainer.stall", info=f"pass={pass_id} batch={batch_id}"
            )
            # `trainer.oom=raise@N` is a deterministic device OOM at the
            # launch boundary — what the oom_report.json pre-mortem +
            # exit-20 drills recover from (the synthetic error carries
            # the canonical RESOURCE_EXHAUSTED marker, so the catch in
            # train() classifies it exactly like the real thing)
            try:
                faultinject.fault_point(
                    "trainer.oom", info=f"pass={pass_id} batch={batch_id}"
                )
            except faultinject.FaultInjected as e:
                raise obs_mem.SyntheticOomError(
                    f"pass={pass_id} batch={batch_id}"
                ) from e
            # `trainer.nonfinite_layer=raise:LAYER@N` poisons the named
            # layer's parameters with NaN — the effect a nonfinite
            # gradient applied by the optimizer has — so the next loss
            # goes NaN and the per-layer blame re-run must name LAYER
            try:
                faultinject.fault_point(
                    "trainer.nonfinite_layer",
                    info=f"pass={pass_id} batch={batch_id}",
                )
            except faultinject.FaultInjected as e:
                self._poison_layer(e.arg, pass_id, batch_id)
            # sparse tables: `sparse.gather_fault=raise@N` aborts the
            # launch whose touched-row prefetch is about to run (loud
            # failure, never training on stale rows), and the host
            # batch ids feed the kind=sparse per-pass accounting —
            # BEFORE the fused path drops its per-batch host args
            if self._sparse_stats is not None:
                faultinject.fault_point(
                    "sparse.gather_fault",
                    info=f"pass={pass_id} batch={batch_id}",
                )
                for hb in ([it[1] for it in group] if kind == "fused"
                           else [group[1]]):
                    self._sparse_stats.note_batch(self._sparse_plan, hb)
            launch_counts[kind] += 1
            if (
                self.flags.profile_dir
                and pass_id == self.start_pass
                and not profiling
                and not profiled
                and batch_id >= self.flags.profile_start_batch
            ):
                # fused launches advance batch_id by k: trigger at launch
                # granularity (the window covers whole launches)
                jax.profiler.start_trace(self.flags.profile_dir)
                profiling = True
                logger.info("profiler trace started → %s", self.flags.profile_dir)
            if kind == "fused":
                t_prep = time.perf_counter()
                items = group
                kf = len(items)
                ns = [it[0] for it in items]
                stacked = jax.tree_util.tree_map(
                    lambda *xs: jnp.stack(xs), *[it[2] for it in items]
                )
                # the stacked copy is what the launch consumes — drop the
                # per-batch device arrays now instead of holding ~2x the
                # launch's input data in HBM across the step. Cleared IN
                # PLACE: _launch_groups' suspended frame still aliases
                # this list (its buf rebind only runs on the next resume)
                group.clear()
                items = group = None
                # consume one split of the pass chain PER BATCH, exactly
                # as the unfused loop does, so batches_per_launch=k
                # reproduces k=1 numerics for rng-using models (dropout)
                step_keys = []
                for _ in range(kf):
                    rng, sr = jax.random.split(rng)
                    step_keys.append(sr)
                rngs = jnp.stack(step_keys)
                ns_arr = jnp.asarray([float(x) for x in ns])
                prep_s = time.perf_counter() - t_prep
                # launch FLOPs counted exactly: the walker multiplies the
                # fused scan body by its length k. Counted OUTSIDE the
                # step window (a cache-miss jaxpr trace must not inflate
                # step timing), while host-side stacking/rng prep stays
                # INSIDE it, preserving the window's original semantics
                launch_key = ("fused", kf, self._shape_sig(stacked))
                self._pass_flops += self._count_model_flops(
                    launch_key,
                    self.fused_step, self.params, self.opt_state, stacked,
                    rngs, ns_arr,
                )
                t_step = time.perf_counter() - prep_s
                snap = self._nf_snapshot()
                with stat_timer("train_step"):
                    fused_out = self._compiles.call(
                        "fused_step", launch_key, self.fused_step,
                        self.params, self.opt_state, stacked, rngs, ns_arr,
                        analytic_flops=self._flops_cache.get(launch_key),
                        pass_id=pass_id, step=batch_id,
                    )
                self.params, self.opt_state, losses, keeps = fused_out[:4]
                if self._numerics_groups is not None:
                    # stays on device: read back only at the log period
                    self._numerics_last = fused_out[4]
                # ONE device→host transfer per launch (losses + kept
                # outputs together); numpy slicing below adds no further
                # device dispatches
                losses_host, keeps_host = jax.device_get((losses, keeps))  # lint: disable=PTL002 -- the one designed sync: amortized over the k-batch launch, feeds the nonfinite gate
                losses_host = np.asarray(losses_host)
                if faultinject.is_active():
                    losses_host = np.asarray([
                        self._poisoned_loss(float(l), pass_id, batch_id + i)
                        for i, l in enumerate(losses_host)
                    ])
                if not np.isfinite(losses_host).all():
                    # gate BEFORE any per-batch housekeeping: params already
                    # contain all k updates, so a periodic save fired for an
                    # earlier batch of this launch would checkpoint
                    # NaN-poisoned weights as if they were pre-NaN
                    bad = int(np.flatnonzero(~np.isfinite(losses_host))[0])
                    if self._handle_nonfinite(
                        pass_id, batch_id + bad, float(losses_host[bad]),
                        snap, f"(launch of {kf}) ",
                        # the poisoned batch, sliced out of the stacked
                        # launch for the per-layer blame re-run (cold
                        # path: this only ever runs on a NaN loss)
                        batch=jax.tree_util.tree_map(
                            lambda x, i=bad: x[i], stacked
                        ),
                        rng=rngs[bad],
                    ):
                        # poisoned launch discarded whole (skip policy):
                        # pre-launch params/opt_state are back in place.
                        # If this was the group's FIRST launch, nobody
                        # consumed its compile-cost deduction — drop it,
                        # or the next clean launch's exec time would be
                        # zeroed by a compile it never paid
                        self._compiles.drop_pending("fused_step", launch_key)
                        batch_id += kf
                        continue
                launch_s = time.perf_counter() - t_step
                self._pass_train_s += launch_s
                self._compiles.note_exec(
                    "fused_step", launch_key, launch_s, batches=kf
                )
                step_dt = launch_s / kf
                results = [
                    (
                        float(losses_host[i]),
                        jax.tree_util.tree_map(lambda x, i=i: x[i], keeps_host),
                        ns[i],
                    )
                    for i in range(kf)
                ]
            else:
                rng, step_rng = jax.random.split(rng)
                n, _host_batch, batch = group
                launch_key = None
                if self._accum_n <= 1 and not self._async:
                    launch_key = ("single", self._shape_sig(batch))
                    self._pass_flops += self._count_model_flops(
                        launch_key,
                        self.train_step, self.params, self.opt_state, batch,
                        step_rng, jnp.asarray(float(n)),
                    )
                t_step = time.perf_counter()
                snap = self._nf_snapshot()
                with stat_timer("train_step"):
                    if self._accum_n > 1:
                        loss, outputs = self._accum_step(batch, step_rng, n)
                    elif self._async:
                        loss, outputs = self._async_step(batch, step_rng, n)
                    else:
                        step_out = self._compiles.call(
                            "train_step", launch_key, self.train_step,
                            self.params, self.opt_state, batch, step_rng,
                            jnp.asarray(float(n)),
                            analytic_flops=self._flops_cache.get(launch_key),
                            pass_id=pass_id, step=batch_id,
                        )
                        self.params, self.opt_state, loss, outputs = step_out[:4]
                        if self._numerics_groups is not None:
                            self._numerics_last = step_out[4]
                loss_f = self._poisoned_loss(float(loss), pass_id, batch_id)  # lint: disable=PTL002 -- single-step path: the per-launch loss read IS the nonfinite gate
                step_dt = time.perf_counter() - t_step
                self._pass_train_s += step_dt
                if launch_key is not None:
                    self._compiles.note_exec("train_step", launch_key, step_dt)
                results = [(loss_f, outputs, n)]
            if self._restart_pending:
                # the run's first completed launch: restart latency is
                # now fully paid (restore + trace + compile + step 1) —
                # the structured number heartbeat-grace and crash-loop
                # windows are tuned from (`paddle metrics` "restore s" /
                # "ttfs s" columns)
                self._restart_pending = False
                obs.emit(
                    "restart", pass_id=pass_id, step=batch_id,
                    restore_s=round(self._restore_s, 6),
                    time_to_first_step_s=round(
                        time.perf_counter() - self._t_construct, 6
                    ),
                    resumed=self._restored_pass is not None,
                )
            batch_id_start = batch_id
            for loss_f, outputs, n in results:
                step_times.append(step_dt)
                if not np.isfinite(loss_f):
                    # FP trap role (ref: feenableexcept(FE_INVALID|FE_DIVBYZERO|
                    # FE_OVERFLOW), TrainerMain.cpp:96), now policy-driven:
                    # abort raises, skip discards the update, rollback
                    # restores a checkpoint. Fused launches were gated
                    # above; reaching here is the single-batch path. loss
                    # is already read back each batch, so the check is free.
                    # (`batch` is only bound on the non-fused path —
                    # fused launches were gated above and never get here)
                    if self._handle_nonfinite(
                        pass_id, batch_id, loss_f, snap,
                        batch=batch if kind == "single" else None,
                        rng=step_rng if kind == "single" else None,
                    ):
                        batch_id += 1
                        continue
                stats.add(loss_f * n, n)
                self._eval_outputs(evaluators, outputs)
                batch_id += 1
                if self.flags.dot_period and batch_id % self.flags.dot_period == 0:
                    print(".", end="", flush=True, file=sys.stderr)
                    self._dots_pending = True

            # periodic housekeeping fires at LAUNCH boundaries: params hold
            # every update of the launch, so a save labeled with a
            # mid-launch batch_id would contain later batches' updates and
            # a resume from it would double-apply them. ``crossed`` is the
            # plain modulo check when a launch is one batch.
            def crossed(period):
                return period and batch_id // period > batch_id_start // period

            if crossed(self.flags.test_period):
                self._end_dot_line()
                with stat_timer("test"):
                    self.test(pass_id=pass_id)
            if crossed(self.flags.show_parameter_stats_period):
                self._end_dot_line()
                self.show_parameter_stats()
            if crossed(log_period):
                self._end_dot_line()
                logger.info(
                    "Pass %d batch %d  %s  %s",
                    pass_id,
                    batch_id,
                    stats.summary(),
                    evaluators.summary(),
                )
                # the window record carries the SAME key=value pairs the
                # log line just printed (one shared dict, satellite of
                # doc/observability.md)
                obs.emit("train_window", pass_id=pass_id, step=batch_id,
                         **stats.summary_dict())
                stats.reset_window()
            if crossed(self._numerics_period) and self._numerics_last is not None:
                # the ONLY host readback of the health aux: a tiny
                # [n_layers, 4] transfer at the numerics log period,
                # inside a helper so the per-step loop stays sync-free
                self._emit_numerics(pass_id, batch_id)
            # preemption (SIGTERM flag) saves through the SAME block as the
            # periodic save — one flush, one save, even when both fire on
            # this boundary (TPU pods preempt with a SIGTERM notice; the
            # reference is restart-from-last-pass only — SURVEY §5 names
            # this the recovery gap). Snapshot the flag ONCE: a signal
            # landing between two reads must not make the raise claim a
            # save that never ran.
            preempted = self._preempt_requested
            want_save = crossed(self.flags.saving_period_by_batches) or preempted
            if want_save and self.save_dir:
                if self._accum_n > 1:
                    # apply pending gradients first or the checkpoint
                    # would silently drop up to N-1 batches' worth
                    self._accum_flush()
                self.save(pass_id, batch_id=batch_id)
            if preempted:
                self._end_dot_line()
                logger.info("SIGTERM received — checkpointed at the launch "
                            "boundary" if self.save_dir else
                            "SIGTERM received — no save_dir, nothing saved")
                if profiling:
                    # the open trace would otherwise be abandoned mid-write
                    jax.block_until_ready(self.params)  # lint: disable=PTL002 -- preemption exit: runs AT MOST ONCE per process (SIGTERM teardown), and the profiler trace must see the last launch land before stop_trace abandons it
                    jax.profiler.stop_trace()
                    logger.info("profiler trace written to %s",
                                self.flags.profile_dir)
                saved_path = (
                    os.path.join(self.save_dir, ckpt.PASS_FMT % pass_id)
                    if self.save_dir else ""
                )
                # SIGTERM-driven flush: the preemption window must not
                # cost the buffered telemetry of this partial pass
                obs.emit("preempt", pass_id=pass_id, step=batch_id,
                         saved_path=saved_path)
                obs.flush()
                obs_spans.export()
                raise PreemptionExit(pass_id, saved_path)
            if profiling and batch_id >= (
                self.flags.profile_start_batch + self.flags.profile_num_batches
            ):
                jax.block_until_ready(self.params)  # lint: disable=PTL002 -- profiler window close: runs ONCE per run (profiling flips false right below), and the trace must include the final profiled launch before stop_trace
                jax.profiler.stop_trace()
                profiling = False
                profiled = True
                logger.info("profiler trace written to %s", self.flags.profile_dir)
        if self._accum_n > 1:
            # end-of-pass remainder: apply whatever is accumulated so no
            # sample's gradient is dropped (reference flushes on finishPass)
            self._accum_flush()
        self._async_flush(final=True)  # pass end: real merge + collapse
        if self._lsgd_discarded:
            logger.info(
                "Pass %d: drift gate discarded %d replica update block(s) "
                "(async_lagged_grad_discard_ratio=%g)",
                pass_id, self._lsgd_discarded,
                self.config.opt_config.async_lagged_grad_discard_ratio,
            )
        if profiling:
            jax.block_until_ready(self.params)
            jax.profiler.stop_trace()
            logger.info("profiler trace written to %s", self.flags.profile_dir)
        self._end_dot_line()
        # pass-boundary telemetry for the two new planes: the last
        # launch's numerics health (so every pass has at least one
        # numerics record even when the period exceeds the pass), and a
        # live memory snapshot (kind=memory record + mem.* gauges — the
        # gauges land in the counters snapshot of the pass_end below)
        if self._numerics_last is not None:
            self._emit_numerics(pass_id, batch_id)
        if obs.enabled():
            self._mem_last = obs_mem.sample_and_emit(
                pass_id=pass_id, step=batch_id
            )
        dt = time.monotonic() - t0
        rate = stats.total_samples / max(dt, 1e-9)
        mfu_fields = self._mfu_fields()
        logger.info(
            "Pass %d done: %s  %s  (%.1f samples/s%s)",
            pass_id,
            stats.summary(),
            evaluators.summary(),
            rate,
            self._mfu_note(mfu_fields),
        )
        # the structured twin of the "Pass N done" line: same shared
        # dict (summary_dict / mfu_fields) plus step-time quantiles,
        # launch-group counts, and the cumulative counters snapshot —
        # flushed here, so a crash loses at most one pass window
        record: Dict[str, Any] = dict(stats.summary_dict())
        record.update(evaluators.results())
        record.update(mfu_fields)
        record["samples_per_sec"] = rate
        record["pass_time_s"] = time.perf_counter() - pass_t0
        if step_times:
            record["step_time_mean_s"] = float(np.mean(step_times))
            record["step_time_p50_s"] = float(np.percentile(step_times, 50))
            record["step_time_p99_s"] = float(np.percentile(step_times, 99))
        record["launches_single"] = launch_counts["single"]
        record["launches_fused"] = launch_counts["fused"]
        if self._hangwatch is not None:
            # worst step-progress age this pass (the hangwatch gauge's
            # max-since-last-read) — `paddle metrics` surfaces it, so a
            # near-miss stall is visible before the one that kills a run
            record["progress_age_max_s"] = round(
                self._hangwatch.take_max_age(), 3
            )
        if obs.enabled():
            record["counters"] = obs.registry().snapshot()
        obs.emit("pass_end", pass_id=pass_id, step=batch_id, **record)
        # sparse-table plane (doc/sparse.md): touched/unique rows,
        # gather/scatter bytes, reshard events — one kind=sparse
        # record per pass, the raw material of `paddle metrics`' rows/s
        # column and `paddle compare`'s sparse verdicts
        if self._sparse_stats is not None:
            obs.emit(
                "sparse", pass_id=pass_id, step=batch_id,
                **self._sparse_stats.pass_record(duration_s=dt),
            )
        # per-launch-group cost attribution (cumulative totals —
        # `paddle roofline` keeps latest-wins per group, so re-run
        # passes never double-count)
        self._compiles.emit_roofline(pass_id=pass_id)
        obs_spans.record_perf(
            "trainer/pass", pass_t0, time.perf_counter() - pass_t0
        )
        from paddle_tpu.utils.barrier import step_time_skew_summary

        step_time_skew_summary(step_times, pass_id=pass_id)

    # --------------------------------------------- divergence recovery

    def _nf_snapshot(self):
        """Pre-step state the skip policy can hand back: plain references
        — valid after the step because _donate_steps disabled buffer
        donation for every non-abort policy. None under abort (the
        handler will raise, nothing to restore)."""
        if self._nf_policy == "abort":
            return None
        return (
            self.params, self.opt_state,
            self._acc, self._acc_batches, self._acc_samples,
        )

    def _poisoned_loss(self, loss_f: float, pass_id: int, batch_id: int) -> float:
        """`trainer.nonfinite` injection site — one hit per batch; a
        firing `raise` rule turns this batch's loss into NaN, the
        deterministic divergence the chaos tests drive policies with."""
        if faultinject.is_active():
            try:
                faultinject.fault_point(
                    "trainer.nonfinite", info=f"pass={pass_id} batch={batch_id}"
                )
            except faultinject.FaultInjected:
                logger.warning(
                    "injected non-finite loss at pass %d batch %d",
                    pass_id, batch_id,
                )
                return float("nan")
        return loss_f

    def _handle_nonfinite(self, pass_id, batch_id, value, snap,
                          launch_note="", batch=None, rng=None):
        """Apply --nonfinite_policy to one non-finite loss. Returns True
        when the poisoned update was discarded (skip) and the caller
        should move on; raises NonFiniteLossError (abort / exhausted
        budget) or _RollbackRequest (rollback) otherwise.

        When the poisoned ``batch`` is available it is re-run in the
        per-layer checking mode (observability/numerics.py) and the
        first layer producing a nonfinite value rides the ``nonfinite``
        record (``blame_layer``/``blame_phase``) and the abort message —
        recovery that names its culprit instead of just surviving it."""
        base = (
            f"non-finite loss ({value}) at pass {pass_id} "
            f"batch {batch_id} {launch_note}"
        )
        blame = None
        if batch is not None:
            # skip/rollback kept the pre-step state (donation disabled):
            # blame re-runs the exact poisoned step. Abort donated the
            # pre-step buffers, so the post-update params stand in —
            # approximate, but a NaN born in the forward/backward still
            # reproduces there.
            params_src = snap[0] if snap is not None else self.params
            blame = obs_num.blame_nonfinite(
                self.gm, self.config.model_config, params_src, batch, rng
            )
        blame_fields = {}
        blame_note = ""
        if blame is not None:
            blame_fields = {"blame_layer": blame["layer"],
                            "blame_phase": blame["phase"]}
            blame_note = (
                f" [first nonfinite at layer {blame['layer']!r}, "
                f"{blame['phase']} phase, {blame['nonfinite']} value(s)]"
            )
            logger.warning(
                "nonfinite blame: first nonfinite value at layer %r "
                "(%s phase, %d nonfinite value(s)%s)",
                blame["layer"], blame["phase"], blame["nonfinite"],
                f", param {blame['param']}" if blame.get("param") else "",
            )
        if self._numerics_last is not None:
            # flush the poisoned launch's health table alongside the
            # event: an abort must not die with the per-layer evidence
            # still sitting on device awaiting the next log period
            self._emit_numerics(pass_id, batch_id)
        obs.registry().counter("nonfinite.events").inc()
        obs.emit("nonfinite", pass_id=pass_id, step=batch_id,
                 value=value, policy=self._nf_policy, **blame_fields)
        if self._nf_policy == "abort" or snap is None:
            raise NonFiniteLossError(
                base + blame_note
                + "— aborting. Try --job=checkgrad, a lower learning "
                "rate, or gradient clipping to locate the cause "
                "(or --nonfinite_policy=skip/rollback to recover).",
                value=value, pass_id=pass_id, batch_id=batch_id,
            )
        self._nf_count += 1
        if self._nf_count > self._nf_budget:
            raise NonFiniteLossError(
                base + blame_note + f"— non-finite budget exhausted "
                f"(--max_nonfinite_steps={self._nf_budget}, "
                f"{self._nf_count - 1} poisoned event(s) already recovered)",
                value=value, pass_id=pass_id, batch_id=batch_id,
            )
        (self.params, self.opt_state, self._acc,
         self._acc_batches, self._acc_samples) = snap
        if self._nf_policy == "skip":
            logger.warning(
                "%s— update discarded (%d/%d non-finite budget used)",
                base, self._nf_count, self._nf_budget,
            )
            return True
        raise _RollbackRequest(pass_id, batch_id)

    def _emit_numerics(self, pass_id: int, batch_id: int) -> None:
        """Read the newest launch's health aux back (the tiny
        [n_layers, 4] tree — the ONLY readback the numerics plane ever
        does, at --numerics_log_period boundaries and pass ends) and
        emit the ``kind=numerics`` record."""
        health = jax.device_get(self._numerics_last)
        layers, nf_layers, grad_norm = obs_num.derive(health)
        obs.emit(
            "numerics", pass_id=pass_id, step=batch_id,
            layers=layers, nonfinite_layers=nf_layers,
            global_grad_norm=grad_norm,
        )
        r = obs.registry()
        r.gauge("numerics.global_grad_norm").set(
            grad_norm if math.isfinite(grad_norm) else -1.0
        )
        if nf_layers:
            r.counter("numerics.nonfinite_layer_events").inc(len(nf_layers))

    def _poison_layer(self, layer: Optional[str], pass_id: int,
                      batch_id: int) -> None:
        """`trainer.nonfinite_layer` injection: write one NaN into each
        of the named layer's parameters — exactly what applying a
        nonfinite gradient through the optimizer would leave behind —
        so the next launch's loss goes NaN and the blame re-run has a
        real poisoned layer to find (no shortcut: blame never consults
        the injector)."""
        groups = self._numerics_groups or obs_num.layer_groups(
            self.config.model_config, list(self.params)
        )
        pnames = groups.get(layer or "")
        if not pnames:
            logger.warning(
                "trainer.nonfinite_layer: no parameters belong to layer "
                "%r (known: %s) — nothing poisoned",
                layer, ", ".join(sorted(groups)),
            )
            return
        for pn in pnames:
            v = np.array(jax.device_get(self.params[pn]))
            v.reshape(-1)[0] = float("nan")
            self.params[pn] = jnp.asarray(v)
        logger.warning(
            "injected NaN into layer %r parameter(s) %s at pass %d "
            "batch %d (trainer.nonfinite_layer)",
            layer, pnames, pass_id, batch_id,
        )

    def _oom_premortem(self, err: BaseException) -> None:
        """Write oom_report.json into the run dir before the OOM death
        propagates: per-group static footprint (XLA's memory plans,
        ranked), the freshest live snapshot the allocator will still
        give us, and the telemetry tail. The backstop timer inside
        trigger_oom_report guarantees exit EXIT_OOM even when the
        forensics themselves wedge — same discipline as hangwatch."""
        from paddle_tpu.resilience.hangwatch import run_dir_of

        report_dir = run_dir_of(
            getattr(self.flags, "metrics_path", "") or self.save_dir or "."
        )
        try:
            # post-OOM sampling usually still works (the allocator is
            # full, not gone) and is the most truthful evidence; the
            # last pass-boundary snapshot is the fallback
            live = obs_mem.sample_memory()
        except Exception:
            live = self._mem_last
        obs_mem.trigger_oom_report(
            report_dir, err,
            groups=self._compiles.static_memory_rows(),
            live=live or self._mem_last,
            where=(
                {"pass": self._last_launch[0], "step": self._last_launch[1]}
                if self._last_launch is not None else None
            ),
            device_kind=self._compiles.device_kind or "",
            exit_fn=os._exit,
        )

    def _apply_rollback(self, rb: _RollbackRequest) -> int:
        """--nonfinite_policy=rollback: restore the newest verified
        checkpoint, temper the learning rate, and arrange to fast-forward
        past the poison region. Returns the pass id to resume from."""
        # settle the background writer first: the newest enqueued save
        # must be on disk before the restore scan, and a FAILED async
        # write must not abort the rollback (older checkpoints remain) —
        # log it and restore from what is actually durable
        if self._async_ckpt is not None:
            try:
                self._async_ckpt.drain()
            except Exception as e:
                logger.warning(
                    "rollback: async checkpoint writer reported %s — "
                    "restoring from the newest durable checkpoint", e,
                )
        # warm-resume: a checkpoint THIS process committed earlier in
        # the run needs no re-CRC before the rollback restore —
        # verification cost belongs to cold restores (fresh processes
        # have written nothing, so they still verify in full)
        path = (
            ckpt.find_restorable_checkpoint(self.save_dir, trust_own_writes=True)
            if self.save_dir else None
        )
        if path is None:
            raise NonFiniteLossError(
                f"non-finite loss at pass {rb.pass_id} batch {rb.batch_id} "
                "— --nonfinite_policy=rollback found no restorable "
                "checkpoint under --save_dir to roll back to",
                pass_id=rb.pass_id, batch_id=rb.batch_id,
            )
        # the restore below (multi-GB on a slow shared fs, then a full
        # re-jit at the next launch) is recovery progress, not a hang —
        # ping around it so an armed hangwatch does not kill a healthy
        # rollback mid-flight (the fast-forward replay after it pings
        # per launch for the same reason)
        if self._hangwatch is not None:
            self._hangwatch.ping(rb.pass_id, rb.batch_id)
        # find_restorable either CRC'd the candidate or trusted this
        # process's own write — verify=False skips the redundant re-CRC
        # in both cases, and trust_own_writes tells load_checkpoint
        # which case it is (a corrupt TRUSTED checkpoint must fall back
        # to an earlier pass, not re-raise as a config error)
        self.params, opt_state, meta = ckpt.load_checkpoint(
            path, self.opt_state, expected_params=self.params,
            sharding_for=self.ckpt_sharding_for(),
            verify=False, fallback=True, trust_own_writes=True,
        )
        if self._hangwatch is not None:
            self._hangwatch.ping(rb.pass_id, rb.batch_id)
        if opt_state is not None:
            self.opt_state = opt_state
        restored = self._note_restored(path, meta)
        scale = float(getattr(self.flags, "rollback_lr_scale", 0.5) or 1.0)
        oc = self.config.opt_config
        old_lr = oc.learning_rate
        oc.learning_rate = old_lr * scale
        # the jitted steps baked the old schedule constants at trace
        # time — drop them so the tempered lr actually takes effect
        # (including the compile registry's AOT executables; the re-jit
        # shows up in the compile telemetry as recompiles>0)
        self._train_step_fn = None
        self._fused_step_fn = None
        self._accum_fns = None
        self._compiles.invalidate("train_step", "fused_step")
        self._acc = None
        self._acc_batches = 0
        self._acc_samples = 0
        self.rollbacks += 1
        self._ff_target = (rb.pass_id, rb.batch_id + 1)
        resume = (restored + 1) if restored is not None else rb.pass_id
        logger.warning(
            "rollback: non-finite loss at pass %d batch %d — restored %s, "
            "learning_rate %g -> %g (x%g), resuming at pass %d "
            "(will fast-forward past batch %d of pass %d)",
            rb.pass_id, rb.batch_id, path, old_lr, oc.learning_rate, scale,
            resume, rb.batch_id, rb.pass_id,
        )
        return resume

    def _accum_step(self, batch, step_rng, n: int):
        """One gradient-accumulation batch; applies the optimizer update
        every N-th call."""
        if self._accum_fns is None:
            self._accum_fns = self._build_accum_steps()
        astep, ustep = self._accum_fns
        if self._acc is None:
            self._acc = jax.tree_util.tree_map(jnp.zeros_like, dict(self.params))
        self.params, self._acc, loss, outputs = astep(
            self.params, self._acc, batch, step_rng, jnp.asarray(float(n))
        )
        self._acc_batches += 1
        self._acc_samples += n
        if self._acc_batches >= self._accum_n:
            self._accum_flush()
        return loss, outputs

    def _accum_flush(self) -> None:
        if self._acc_batches == 0 or self._acc is None:
            return
        astep, ustep = self._accum_fns
        self.params, self.opt_state, self._acc = ustep(
            self.params, self.opt_state, self._acc,
            jnp.asarray(float(self._acc_samples)),
        )
        self._acc_batches = 0
        self._acc_samples = 0

    # ----------------------------------------------- async SGD (local SGD)

    def _async_step(self, batch, step_rng, n: int):
        """One local-SGD batch: every replica applies its own gradient to
        its own parameter copy (no cross-replica collective); merges every
        ``num_batches_per_send_parameter``-th call."""
        if self._local_sgd is None:
            from paddle_tpu.parallel.local_sgd import LocalSgd

            # the SAME one-batch body the sync path jits (dense grads:
            # sparse row sets vary per batch and cannot ride the stack)
            self._local_sgd = LocalSgd(
                self._one_batch_step(sparse=False),
                self._mesh,
                self.config.opt_config.async_lagged_grad_discard_ratio,
            )
        if self._lsgd_state is None:
            self._lsgd_state = self._local_sgd.stack(self.params, self.opt_state)
        pr, po = self._lsgd_state
        pr, po, loss, outputs = self._local_sgd.step(
            pr, po, batch, step_rng, jnp.asarray(float(n))
        )
        self._lsgd_state = (pr, po)
        self._lsgd_dirty = True
        self._lsgd_batches += 1
        if self._lsgd_batches >= self._sync_n:
            self._lsgd_merge()
        return loss, outputs

    def _lsgd_merge(self) -> None:
        pr, po = self._lsgd_state
        pr, po, discarded = self._local_sgd.merge(pr, po)
        self._lsgd_state = (pr, po)
        self._lsgd_batches = 0
        self._lsgd_discarded += int(discarded)

    def _async_flush(self, final: bool = False) -> None:
        """Materialize canonical params/opt_state from the replica stacks
        — called before any consumer of self.params (test/save/stats).

        Mid-pass (``final=False``) this reads a PASSIVE merged snapshot
        (`LocalSgd.merged_view`): the replica stacks and the merge
        schedule are untouched, so observability flags (test_period,
        show_parameter_stats_period, periodic saves) never perturb the
        optimization trajectory — the reference's test path likewise
        read the pserver's merged parameters without collapsing the
        trainers' local progress. At pass end (``final=True``) a real
        merge runs and the stacks collapse, so the pass boundary is a
        true synchronization point (reference waitPassFinish)."""
        if not self._async or not self._lsgd_dirty:
            return
        if not final:
            self.params, self.opt_state = self._local_sgd.merged_view(
                *self._lsgd_state
            )
            return  # stacks still ahead of params: stays dirty
        if self._lsgd_batches:
            self._lsgd_merge()
        self.params, self.opt_state = self._local_sgd.collapse(*self._lsgd_state)
        self._lsgd_dirty = False

    @property
    def _is_writer(self) -> bool:
        """Exactly one process writes result/prediction files."""
        return not self._multiproc or jax.process_index() == 0

    def _global_batches(self, provider: DataProvider, pad: bool = False):
        """Yield (n_samples, host batch, mesh-ready batch).

        Batches that cannot be evenly sharded (data-axis divisor ×
        multi-host process count): training SKIPS them with a one-time
        warning (sync-SGD needs identical per-device slices;
        doc/divergences.md), inference jobs (``pad=True``) PAD them by
        repeating the last sample and the caller trims outputs back to n
        — every sample is processed exactly once."""
        div = self._batch_divisor
        if self._multiproc:
            div = div * jax.process_count() // math.gcd(div, jax.process_count())
        for batch in provider.batches():
            n = _batch_num_samples(batch)
            if div > 1 and n % div:
                if not pad:
                    self._warn_remainder(n)
                    continue
                batch = _pad_batch(batch, n + (div - n % div))
            if self._multiproc:
                from paddle_tpu.parallel.spmd import globalize_batch

                g = globalize_batch(batch, self._mesh)
                assert g is not None  # padded/skipped to divisibility above
                yield n, batch, g
            else:
                yield n, batch, batch

    def _gather_host(self, outputs, names):
        """All-gather selected (small) outputs to full host values on
        every process — see spmd.gather_outputs (distributeEval role)."""
        from paddle_tpu.parallel.spmd import gather_outputs

        return gather_outputs(outputs, self._mesh, names)

    def _device_prefetch(self, gen):
        """One-step-lookahead device transfer: the NEXT batch's host→device
        copy is dispatched (async) while the current step computes — the
        device-side half of the reference's DoubleBuffer
        (DataProvider.h:245; the host half is the feeder's prefetch
        thread). Multi-process batches are already device-resident global
        arrays (globalize_batch), so they pass through."""
        if self._multiproc:
            yield from gen
            return
        if self._mesh is not None:
            from paddle_tpu.parallel.spmd import batch_sharding

            sharding = batch_sharding(self._mesh)
            put = lambda b: jax.device_put(b, sharding)
        else:
            put = jax.device_put
        it = iter(gen)
        try:
            n, host, dev = next(it)
        except StopIteration:
            return
        cur = (n, host, put(dev))
        for n2, host2, dev2 in it:
            nxt = (n2, host2, put(dev2))  # dispatches the copy immediately
            yield cur
            cur = nxt
        yield cur

    def _eval_outputs(self, evaluators: EvaluatorChain, outputs, gathered=False) -> None:
        """Feed one batch's outputs to the evaluator chain.

        Multi-process: evaluators with summable state accumulate over this
        process's LOCAL row block and merge their small state vectors once
        per read period (the reference's getState/distributeEval split,
        Evaluator.h:81-82) — no per-batch [B, V] activation gather.
        Evaluators without mergeable state (raw-record, printers) still
        get their layers gathered per batch. The local/gather split is
        decided ONCE per chain from global sharding metadata so every
        process runs the same collectives. ``gathered``: outputs are
        already full host values."""
        if not evaluators:
            return
        if self._multiproc and not gathered:
            from paddle_tpu.parallel import spmd

            plan = getattr(evaluators, "_dist_plan", None)
            if plan is None:
                merge_evs, gather_evs = evaluators.partition()
                local_layers = evaluators.layers_for(merge_evs)
                if merge_evs and spmd.rows_locally_assemblable(outputs, local_layers):
                    evaluators.merge_fn = spmd.merge_eval_states
                else:
                    # e.g. a vocab-sharded output: local rows are partial —
                    # fall back to gathering for everything
                    gather_evs = evaluators.evaluators
                    merge_evs, local_layers = [], []
                plan = evaluators._dist_plan = (
                    merge_evs, local_layers, gather_evs,
                    evaluators.layers_for(gather_evs),
                )
            merge_evs, local_layers, gather_evs, gather_layers = plan
            if merge_evs:
                evaluators.eval_batch(
                    spmd.local_row_block(outputs, local_layers), only=merge_evs
                )
            if gather_evs:
                evaluators.eval_batch(
                    self._gather_host(outputs, gather_layers), only=gather_evs
                )
            return
        evaluators.eval_batch(outputs)

    def _warn_remainder(self, n: int) -> None:
        if not getattr(self, "_remainder_warned", False):
            self._remainder_warned = True
            logger.warning(
                "skipping remainder batch of %d samples (not divisible by "
                "the %d-way data axis); pad the dataset or pick a batch "
                "size multiple of the mesh to use every sample", n,
                self._batch_divisor,
            )

    def _end_dot_line(self) -> None:
        """Terminate a run of progress dots before a log line (the
        reference printed the newline in TrainerInternal too)."""
        if getattr(self, "_dots_pending", False):
            print("", flush=True, file=sys.stderr)
            self._dots_pending = False

    def show_parameter_stats(self) -> None:
        """Per-parameter value stats (ref: TrainerInternal::showParameterStats,
        TrainerInternal.cpp:184-213)."""
        self._async_flush()
        for name in sorted(self.params):
            v = np.asarray(self.params[name])
            logger.info(
                "Param %-40s mean=%.5g absmax=%.5g std=%.5g shape=%s",
                name, float(v.mean()), float(np.abs(v).max()), float(v.std()),
                tuple(v.shape),
            )

    # -------------------------------------------------------------- test

    def test(self, pass_id: int = -1) -> Dict[str, float]:
        # pass-end eval doubles as the async-checkpoint barrier: the
        # previous pass's background write had a whole pass of training
        # to overlap with, and a writer failure surfaces here at most
        # one pass late instead of at process exit
        self._drain_async_ckpt()
        provider = self._provider(for_test=True)
        if provider is None:
            return {}
        self._async_flush()
        params = self.updater.averaged_params(self.params, self.opt_state)
        if not self.gm.has_cost():
            return self.predict(provider, params)
        stats = TrainerStats()
        evaluators = EvaluatorChain(self.config.model_config)
        evaluators.start()
        for n, _host_batch, batch in self._global_batches(provider, pad=True):
            launch_key = ("test", self._shape_sig(batch))
            t_launch = time.perf_counter()
            outputs = jax.block_until_ready(self._compiles.call(
                "test_fwd", launch_key, self.test_fwd,
                params, batch, pass_id=pass_id,
            ))
            # the block makes exec_s measure execution, not dispatch —
            # the registry's roofline contract (the train paths sync via
            # their loss transfer instead)
            self._compiles.note_exec(
                "test_fwd", launch_key, time.perf_counter() - t_launch
            )
            if self._multiproc:
                # gather only what cost + evaluators read, then slice the
                # padding off host-side
                keep = list(
                    dict.fromkeys(
                        self.gm.cost_layer_names() + evaluators.needed_layers
                    )
                )
                outputs = self._gather_host(outputs, keep)
            outputs = self._trim_outputs(outputs, n)
            cost = float(self.gm.total_cost(outputs))
            stats.add(cost * n, n)
            self._eval_outputs(evaluators, outputs, gathered=True)
        results = {"cost": stats.total_cost / max(stats.total_samples, 1)}
        results.update(evaluators.results())
        logger.info("Test (pass %d): %s  %s", pass_id, stats.summary(),
                    evaluators.summary())
        obs.emit("test", pass_id=pass_id, **results)
        # standalone `paddle test` never reaches a train pass_end —
        # emit the roofline totals here (cumulative + latest-wins, so
        # the in-train duplicate emission is harmless)
        self._compiles.emit_roofline(pass_id=pass_id)
        return results

    def predict(self, provider: DataProvider, params=None) -> Dict[str, float]:
        """Cost-less test job: forward the net and dump output-layer values.

        The role of the reference Tester's prediction path
        (/root/reference/paddle/trainer/Tester.cpp, --predict_output_dir):
        when the config has no cost layer (is_predict configs ending in
        maxid/softmax outputs), write one text file per output layer —
        ids for id outputs, rows of values otherwise.
        """
        if params is None:
            params = self.updater.averaged_params(self.params, self.opt_state)
        out_dir = self.flags.predict_output_dir
        write = self._is_writer
        if out_dir and write:
            os.makedirs(out_dir, exist_ok=True)
        files = {}
        n_total = 0
        try:
            for n, _host_batch, batch in self._global_batches(provider, pad=True):
                launch_key = ("test", self._shape_sig(batch))
                t_launch = time.perf_counter()
                outputs = jax.block_until_ready(self._compiles.call(
                    "test_fwd", launch_key, self.test_fwd, params, batch,
                ))
                self._compiles.note_exec(
                    "test_fwd", launch_key, time.perf_counter() - t_launch
                )
                if self._multiproc:
                    # collective: every host gathers, only process 0 writes
                    outputs = self._gather_host(
                        outputs, self.gm.network.output_layer_names
                    )
                outputs = self._trim_outputs(outputs, n)
                n_total += n
                for name in self.gm.network.output_layer_names:
                    arg = outputs[name]
                    if out_dir and write:
                        f = files.get(name)
                        if f is None:
                            f = files[name] = open(
                                os.path.join(out_dir, f"predict_{name}.txt"), "w"
                            )
                    else:
                        f = None
                    lengths = (
                        np.asarray(arg.seq_lengths) if arg.seq_lengths is not None else None
                    )
                    if arg.ids is not None:
                        data = np.asarray(arg.ids)
                        if data.ndim == 1:
                            data = data[:, None]
                    else:
                        data = np.asarray(arg.value)
                    # one line per sample; sequence outputs print only the
                    # valid (unpadded) timesteps, space-joined
                    if not write:
                        continue
                    for b in range(data.shape[0]):
                        row = data[b]
                        if lengths is not None and row.ndim >= 1 and row.shape[0] >= lengths[b]:
                            row = row[: lengths[b]]
                        line = " ".join(f"{v:.6g}" for v in np.ravel(row))
                        if f is not None:
                            f.write(line + "\n")
                        else:
                            logger.info("predict %s: %s", name, line)
        finally:
            for f in files.values():
                f.close()
        logger.info(
            "Predict done: %d samples%s",
            n_total,
            f" → {out_dir}" if out_dir else "",
        )
        # predict jobs have no pass_end either — flush roofline totals
        self._compiles.emit_roofline()
        return {"samples": float(n_total)}

    # --------------------------------------------------------------- gen

    def generate(self, result_file: Optional[str] = None):
        """Sequence-generation job (ref: RecurrentGradientMachine
        generateSequence + demo/seqToseq gen.conf; the reference drives it
        as `paddle train --job=test` over a generating config).

        Runs the generator sub-model over the test (or train) data and
        writes, per sample, an index line followed by
        ``score\\ttok tok ...`` per kept beam. Returns the list of
        (best_ids, beam_ids, beam_scores, beam_lens) batches."""
        gen_sub = next(
            (s for s in self.config.model_config.sub_models if s.generator is not None),
            None,
        )
        assert gen_sub is not None, "config has no generator (use beam_search in the config)"
        gen = gen_sub.generator
        group = gen_sub.name
        result_file = result_file or self.flags.gen_result or gen.result_file
        words = None
        if gen.dict_file and os.path.exists(gen.dict_file):
            with open(gen.dict_file) as f:
                words = [line.rstrip("\n") for line in f]

        gm = self.gm

        def gen_fwd_fn(params, in_args):
            outputs, _ = gm.forward(params, in_args, pass_type="gen", rng=None)
            return outputs

        if self._mesh is not None:
            from paddle_tpu.parallel.spmd import shard_test_fwd

            gen_fwd = shard_test_fwd(gen_fwd_fn, self._mesh, self.gm)
        else:
            gen_fwd = jax.jit(gen_fwd_fn)

        # generation must consume samples in order (result indices map to
        # data order), even when falling back to the train data source
        provider = self._provider(for_test=True) or self._provider(
            for_test=False, ordered=True
        )
        assert provider is not None, "no data configured for generation"
        params = self.updater.averaged_params(self.params, self.opt_state)
        n_keep = max(int(gen.num_results_per_sample), 1)
        results = []
        sample_idx = 0
        out_f = open(result_file, "w") if result_file and self._is_writer else None
        try:
            for n, host_batch, batch in self._global_batches(provider, pad=True):
                # sample ids come from the HOST batch (pre-globalize), so
                # every process sees the full index column
                id_arg = (
                    host_batch.get(gen.id_input_layer) if gen.id_input_layer else None
                )
                sample_ids = (
                    np.asarray(id_arg.ids).reshape(-1) if id_arg is not None else None
                )
                launch_key = ("gen", self._shape_sig(batch))
                t_launch = time.perf_counter()
                outputs = jax.block_until_ready(self._compiles.call(
                    "generator", launch_key, gen_fwd, params, batch,
                ))
                self._compiles.note_exec(
                    "generator", launch_key, time.perf_counter() - t_launch
                )
                if self._multiproc:
                    outputs = self._gather_host(outputs, [group, f"{group}@beams"])
                outputs = self._trim_outputs(outputs, n)
                best = outputs[group]
                beams = outputs.get(f"{group}@beams")
                ids = np.asarray(best.ids)
                beam_ids = np.asarray(beams.ids) if beams is not None else ids[:, None]
                scores = (
                    np.asarray(beams.value)
                    if beams is not None
                    else np.zeros(beam_ids.shape[:2], np.float32)
                )
                lens = (
                    np.asarray(beams.sub_seq_lengths)
                    if beams is not None
                    else np.asarray(best.seq_lengths)[:, None]
                )
                results.append((ids, beam_ids, scores, lens))
                if out_f is not None:
                    for b in range(ids.shape[0]):
                        tag = sample_ids[b] if sample_ids is not None else sample_idx
                        out_f.write(f"{tag}\n")
                        for k in range(min(n_keep, beam_ids.shape[1])):
                            toks = beam_ids[b, k, : lens[b, k]].tolist()
                            text = " ".join(
                                words[t] if words and t < len(words) else str(t)
                                for t in toks
                            )
                            out_f.write(f"{scores[b, k]:.6f}\t{text}\n")
                        sample_idx += 1
        finally:
            if out_f is not None:
                out_f.close()
                logger.info("generation results written to %s", result_file)
        # `paddle gen` has no pass_end — the ROADMAP-2 ask ("give
        # generation the same roofline discipline training got") needs
        # the totals flushed here
        self._compiles.emit_roofline()
        return results

    # -------------------------------------------------------------- save

    def save(self, pass_id: int, batch_id: Optional[int] = None, final: bool = False) -> None:
        # collective in multi-process runs: each host writes the shards it
        # owns (ckpt.save_checkpoint handles the barrier + index merge) —
        # a cross-host model-sharded parameter is never materialized on
        # one process
        self._async_flush()
        extra = {"config_json": self.config.to_json()}
        if batch_id is not None:
            extra["batch_id"] = batch_id
        if self._sparse_stats is not None:
            # which params are row-sharded tables + how many hosts
            # wrote this pass: a relaunch on a different host set reads
            # these to detect (and count) the reshard it just performed
            extra["sparse_tables"] = sparse_rt.registered_tables()
            extra["sparse_hosts"] = jax.process_count()
        keep = 0 if final else 3
        if self._async_ckpt is not None:
            # step-loop cost: device→host snapshot only; the durable
            # write (and the protect-clearing below) happens when the
            # background writer reports the checkpoint landed
            self._async_ckpt.save(
                pass_id,
                self.params,
                self.opt_state,
                extra_meta=extra,
                keep=keep,
                protect_pass=self._restored_pass,
                on_durable=self._on_ckpt_durable,
            )
            return
        ckpt.save_checkpoint(
            self.save_dir,
            pass_id,
            self.params,
            self.opt_state,
            extra_meta=extra,
            keep=keep,
            # rolling deletion must never remove the checkpoint this run
            # restored from — until a newer save proves restorable it is
            # the only known-good state
            protect_pass=self._restored_pass,
        )
        self._on_ckpt_durable(pass_id, "")

    def _on_ckpt_durable(self, pass_id: int, _path: str) -> None:
        """A checkpoint for ``pass_id`` is durable on disk (manifested +
        renamed). Sync saves call this inline; async saves from the
        writer thread once the background protocol finished — only THEN
        may the restored-from pass rejoin the normal rotation budget."""
        if self._restored_pass is not None and pass_id != self._restored_pass:
            self._restored_pass = None

    def _drain_async_ckpt(self) -> None:
        """Barrier on the background checkpoint writer (no-op when sync).
        Raises CheckpointError if a background write failed — an async
        save failure must never be silent (doc/performance.md)."""
        if self._async_ckpt is not None:
            self._async_ckpt.drain()

    # ---------------------------------------------------------- checkgrad

    def check_gradient(self, epsilon: float = 1e-4, max_entries: int = 10) -> bool:
        """--job=checkgrad (ref: Trainer.cpp:313-387)."""
        provider = self._provider(for_test=False) or self._provider(for_test=True)
        assert provider is not None, "checkgrad needs data"
        batch = next(iter(provider.batches()))
        report = self.gm.check_gradient(self.params, batch, epsilon, max_entries)
        ok = True
        for name, diff in sorted(report.items()):
            status = "OK" if diff < 5e-2 else "FAIL"
            if diff >= 5e-2:
                ok = False
            logger.info("checkgrad %-40s max_rel_diff=%.3e %s", name, diff, status)
        return ok


    def _trim_outputs(self, outputs, n: int):
        """Slice every output's batch dim back to the true sample count
        (inverse of _global_batches' inference padding). Multi-process
        callers must gather to host first (host values slice freely)."""
        first = next(
            (
                v
                for v in jax.tree_util.tree_leaves(outputs)
                if hasattr(v, "shape") and v.shape
            ),
            None,
        )
        if first is None or first.shape[0] == n:
            return outputs
        return jax.tree_util.tree_map(lambda x: x[:n], outputs)


def _pad_batch(batch: Dict[str, Argument], m: int) -> Dict[str, Argument]:
    """Pad every leaf's batch dim to m rows by repeating the last sample
    (host-side; all processes see the same padded batch)."""

    def pad(x):
        x = np.asarray(x)
        if x.shape[0] >= m:
            return x
        reps = np.repeat(x[-1:], m - x.shape[0], axis=0)
        return np.concatenate([x, reps], axis=0)

    return jax.tree_util.tree_map(pad, batch)


def _batch_num_samples(batch: Dict[str, Argument]) -> int:
    for arg in batch.values():
        return arg.batch_size
    return 0
