"""Async checkpointing — the step loop never waits on checkpoint I/O.

The synchronous ``Trainer.save()`` serializes + fsyncs the whole model
inline at pass end: on a big model over a shared filesystem that is the
single largest stall left in the hot path (PR 3's ``paddle metrics``
measures it as the ``checkpoint`` row durations). The reference hid
host work behind device compute everywhere it could (DoubleBuffer
prefetch threads, async pserver pushes) but its ParamUtil save was just
as synchronous — this module closes the gap for the TPU port.

Behind ``--async_checkpoint`` a save becomes two halves:

1. **Snapshot** (the step loop's only cost): every device array's
   host copy is *dispatched* asynchronously (``copy_to_host_async``),
   then collected — the one unavoidable device→host wait. The wall
   time of this half is the ``ckpt.blocked_s`` counter and the
   ``op="snapshot"`` checkpoint record.
2. **Write** (background): a daemon writer thread runs the *unchanged*
   PR-1 durability protocol over the host trees —
   ``pass-N.tmp`` → fsync → ``MANIFEST.json`` → rename, rotation with
   ``protect_pass`` — via ``checkpoint.save_checkpoint``. Its wall time
   is the ``ckpt.write_s`` counter (and the usual ``op="save"`` record,
   now emitted from the writer thread).

Contracts that make this safe, not just fast:

- **Bounded in-flight saves** (``--ckpt_inflight_limit``, default 1):
  at most one save is actively writing and at most ``limit`` more may
  queue behind it; enqueueing past the bound drops the OLDEST pending
  (never the active, never the newest — the newest state is the one
  worth making durable), counted by ``ckpt.async_dropped`` and logged.
- **drain()** blocks until everything enqueued is durable. The trainer
  drains at every pass-end test/eval (so a writer failure surfaces at
  most one pass late), on preemption (the SIGTERM save must be durable
  before exit ``EXIT_PREEMPTED``), before a rollback-restore (the
  newest save must be on disk before ``find_restorable_checkpoint``
  scans), and at the end of ``train()``.
- **Writer failures are never silent**: an exception in the background
  write is stored and re-raised as :class:`CheckpointError` from the
  NEXT ``save()`` or ``drain()``. A crash before either loses only the
  in-flight write — the PR-1 protocol guarantees the previous
  checkpoint is still durable and restorable.
- **Hangwatch**: the writer pings the step-progress watchdog at the
  start and end of every background write, and ``drain()`` pings it
  while an active write is still making the queue shrink — a long
  (but live) write at a drain barrier is not misdiagnosed as a trainer
  hang. A writer wedged forever on a dead shared fs still trips the
  watchdog once pings stop, exactly like a wedged synchronous save.

Multi-process runs keep the synchronous path: the sharded save is a
collective (barriers + shard writes on every host) and must run where
every process participates at the same launch boundary.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

import jax
import numpy as np

from paddle_tpu.observability import metrics as obs
from paddle_tpu.resilience import CheckpointError
from paddle_tpu.trainer import checkpoint as ckpt
from paddle_tpu.utils.logging import logger

__all__ = ["AsyncCheckpointer", "snapshot_to_host"]


def snapshot_to_host(tree):
    """Device→host copy of a pytree: dispatch EVERY leaf's async copy
    first, then collect — the collection blocks only until the last DMA
    lands, not once per leaf. Host leaves (numpy scalars in a restored
    opt_state) pass through."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    for leaf in leaves:
        copy_async = getattr(leaf, "copy_to_host_async", None)
        if copy_async is not None:
            try:
                copy_async()
            except Exception:
                pass  # backends without async copies fall back to the
                # blocking np.asarray below — correct, just slower
    return jax.tree_util.tree_unflatten(
        treedef, [np.asarray(leaf) for leaf in leaves]
    )


class _Job:
    __slots__ = ("pass_id", "params", "opt_state", "extra_meta", "keep",
                 "protect_pass", "on_durable")

    def __init__(self, pass_id, params, opt_state, extra_meta, keep,
                 protect_pass, on_durable):
        self.pass_id = pass_id
        self.params = params
        self.opt_state = opt_state
        self.extra_meta = extra_meta
        self.keep = keep
        self.protect_pass = protect_pass
        self.on_durable = on_durable


class AsyncCheckpointer:
    """Background checkpoint writer (see module docstring).

    ``write_fn`` is an injectable seam (fake-clock/gated unit tests);
    production uses :func:`checkpoint.save_checkpoint` — the unchanged
    durable protocol."""

    def __init__(
        self,
        save_dir: str,
        inflight_limit: int = 1,
        hangwatch=None,
        *,
        write_fn: Optional[Callable[..., str]] = None,
    ):
        self.save_dir = save_dir
        self.inflight_limit = max(1, int(inflight_limit))
        self.hangwatch = hangwatch
        self._write_fn = write_fn or ckpt.save_checkpoint
        self._cv = threading.Condition()
        self._pending: List[_Job] = []     # queued, oldest first
        self._active: Optional[_Job] = None
        self._error: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None
        self.dropped = 0
        self.completed = 0

    # -------------------------------------------------------- trainer side

    def save(
        self,
        pass_id: int,
        params: Dict[str, jax.Array],
        opt_state=None,
        extra_meta: Optional[Dict[str, Any]] = None,
        keep: int = 3,
        protect_pass: Optional[int] = None,
        on_durable: Optional[Callable[[int, str], None]] = None,
    ) -> float:
        """Snapshot device→host and enqueue the background write.
        Returns the seconds the caller was blocked (the snapshot — what
        ``ckpt.blocked_s`` accounts). Raises :class:`CheckpointError`
        first if a PREVIOUS background write failed."""
        self._raise_pending_error()
        t0 = time.perf_counter()
        # ONE pytree so every leaf's async copy (params AND opt_state)
        # is dispatched before the first collection blocks — collecting
        # params first would serialize the two DMA trees
        host_params, host_opt = snapshot_to_host((params, opt_state))
        blocked = time.perf_counter() - t0
        job = _Job(pass_id, host_params, host_opt, dict(extra_meta or {}),
                   keep, protect_pass, on_durable)
        with self._cv:
            self._pending.append(job)
            # drop-oldest-pending: the active write cannot be revoked
            # mid-protocol and the newest state is the one worth keeping
            while len(self._pending) > self.inflight_limit:
                old = self._pending.pop(0)
                self.dropped += 1
                obs.registry().counter("ckpt.async_dropped").inc()
                logger.warning(
                    "async checkpoint: dropping queued save of pass %d "
                    "(superseded by pass %d; --ckpt_inflight_limit=%d)",
                    old.pass_id, pass_id, self.inflight_limit,
                )
            self._set_inflight_gauge_locked()
            self._cv.notify_all()
        self._ensure_thread()
        obs.registry().counter("ckpt.blocked_s").inc(blocked)
        obs.emit(
            "checkpoint", op="snapshot", pass_id=pass_id,
            step=job.extra_meta.get("batch_id"),
            path=ckpt.PASS_FMT % pass_id if self.save_dir else "",
            duration_s=round(blocked, 6),
        )
        return blocked

    def drain(self, timeout: Optional[float] = None) -> None:
        """Block until every enqueued save is durable (or ``timeout``
        seconds passed — then :class:`CheckpointError`). Re-raises a
        stored writer failure. Pings the hangwatch while the writer is
        demonstrably live so a long write at a drain barrier is not
        misdiagnosed as a trainer hang."""
        deadline = None if timeout is None else time.monotonic() + timeout
        # a dead/never-started writer would leave the queue stuck: make
        # sure one is running before waiting on it
        self._ensure_thread()
        with self._cv:
            last_state = None
            while self._pending or self._active is not None:
                # ping only when the writer DEMONSTRABLY progressed
                # (a write completed / a new job was claimed) since the
                # last poll: an unconditional ping would keep a writer
                # wedged forever on a dead fs from ever tripping the
                # watchdog — the exact failure hangwatch exists for
                state = (self.completed, len(self._pending),
                         id(self._active))
                if (self.hangwatch is not None
                        and self._active is not None
                        and state != last_state):
                    self.hangwatch.ping(self._active.pass_id)
                last_state = state
                self._cv.wait(timeout=0.2)
                if deadline is not None and time.monotonic() > deadline:
                    raise CheckpointError(
                        f"async checkpoint drain timed out after {timeout}s "
                        f"({len(self._pending)} pending, active="
                        f"{self._active.pass_id if self._active else None})"
                    )
        self._raise_pending_error()

    def inflight(self) -> int:
        with self._cv:
            return len(self._pending) + (1 if self._active is not None else 0)

    # --------------------------------------------------------- writer side

    def _ensure_thread(self) -> None:
        with self._cv:
            if self._thread is not None and self._thread.is_alive():
                return
            self._thread = threading.Thread(
                target=self._run, name="pt-ckpt-writer", daemon=True
            )
            self._thread.start()

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._pending:
                    self._cv.wait()
                self._active = self._pending.pop(0)
                self._set_inflight_gauge_locked()
                job = self._active
            try:
                self._write(job)
            finally:
                # drop BOTH references to the host snapshot before the
                # idle wait — holding it would keep a full extra host
                # copy of model+optimizer state resident between saves
                job = None
                with self._cv:
                    self._active = None
                    self._set_inflight_gauge_locked()
                    self._cv.notify_all()

    def _write(self, job: _Job) -> None:
        if self.hangwatch is not None:
            self.hangwatch.ping(job.pass_id)
        t0 = time.perf_counter()
        try:
            path = self._write_fn(
                self.save_dir,
                job.pass_id,
                job.params,
                job.opt_state,
                extra_meta=job.extra_meta,
                keep=job.keep,
                protect_pass=job.protect_pass,
            )
        except BaseException as e:
            with self._cv:
                self._error = e
            logger.error(
                "async checkpoint: background write of pass %d failed: %s "
                "(will re-raise as CheckpointError on the next save/drain)",
                job.pass_id, e,
            )
            return
        finally:
            if self.hangwatch is not None:
                self.hangwatch.ping(job.pass_id)
        dt = time.perf_counter() - t0
        self.completed += 1
        obs.registry().counter("ckpt.write_s").inc(dt)
        if job.on_durable is not None:
            try:
                job.on_durable(job.pass_id, path)
            except Exception:
                logger.warning(
                    "async checkpoint: on_durable callback failed for "
                    "pass %d", job.pass_id, exc_info=True,
                )

    # ------------------------------------------------------------- plumbing

    def _set_inflight_gauge_locked(self) -> None:
        obs.registry().gauge("ckpt.async_inflight").set(
            len(self._pending) + (1 if self._active is not None else 0)
        )

    def _raise_pending_error(self) -> None:
        with self._cv:
            err, self._error = self._error, None
        if err is not None:
            raise CheckpointError(
                f"async checkpoint write failed: {err}"
            ) from err
