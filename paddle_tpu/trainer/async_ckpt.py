"""Async checkpointing — the step loop never waits on checkpoint I/O.

The synchronous ``Trainer.save()`` serializes + fsyncs the whole model
inline at pass end: on a big model over a shared filesystem that is the
single largest stall left in the hot path (PR 3's ``paddle metrics``
measures it as the ``checkpoint`` row durations). The reference hid
host work behind device compute everywhere it could (DoubleBuffer
prefetch threads, async pserver pushes) but its ParamUtil save was just
as synchronous — this module closes the gap for the TPU port.

Behind ``--async_checkpoint`` a save becomes two halves:

1. **Snapshot** (the step loop's only cost): every device array's
   host copy is *dispatched* asynchronously (``copy_to_host_async``),
   then collected — the one unavoidable device→host wait. The wall
   time of this half is the ``ckpt.blocked_s`` counter and the
   ``op="snapshot"`` checkpoint record.
2. **Write** (background): a daemon writer thread runs the *unchanged*
   PR-1 durability protocol over the host trees —
   ``pass-N.tmp`` → fsync → ``MANIFEST.json`` → rename, rotation with
   ``protect_pass`` — via ``checkpoint.save_checkpoint``. Its wall time
   is the ``ckpt.write_s`` counter (and the usual ``op="save"`` record,
   now emitted from the writer thread).

Contracts that make this safe, not just fast:

- **Bounded in-flight saves** (``--ckpt_inflight_limit``, default 1):
  at most one save is actively writing and at most ``limit`` more may
  queue behind it; enqueueing past the bound drops the OLDEST pending
  (never the active, never the newest — the newest state is the one
  worth making durable), counted by ``ckpt.async_dropped`` and logged.
- **drain()** blocks until everything enqueued is durable. The trainer
  drains at every pass-end test/eval (so a writer failure surfaces at
  most one pass late), on preemption (the SIGTERM save must be durable
  before exit ``EXIT_PREEMPTED``), before a rollback-restore (the
  newest save must be on disk before ``find_restorable_checkpoint``
  scans), and at the end of ``train()``.
- **Writer failures are never silent**: an exception in the background
  write is stored and re-raised as :class:`CheckpointError` from the
  NEXT ``save()`` or ``drain()``. A crash before either loses only the
  in-flight write — the PR-1 protocol guarantees the previous
  checkpoint is still durable and restorable.
- **Hangwatch**: the writer pings the step-progress watchdog at the
  start and end of every background write, and ``drain()`` pings it
  while an active write is still making the queue shrink — a long
  (but live) write at a drain barrier is not misdiagnosed as a trainer
  hang. A writer wedged forever on a dead shared fs still trips the
  watchdog once pings stop, exactly like a wedged synchronous save.

Multi-process runs use :class:`ShardedAsyncCheckpointer` — the elastic
sharded twin (doc/resilience.md "Elastic sharded checkpointing"):

- ``save()`` snapshots only the shards THIS process uniquely owns
  (``checkpoint.snapshot_owned_trees`` — every owned shard's
  device→host copy dispatched before the first collect blocks) and
  enqueues them on the same bounded queue.
- The per-host background writer runs the PR-1 durable discipline over
  its own files only: shard npz + partial index + partial manifest into
  ``pass-N.tmp`` (``checkpoint.write_sharded_host_trees``). No
  cross-process coordination happens on the write path at all.
- The ONLY collective is ``drain()``'s cheap pass-end agreement, and it
  is a HOST protocol (the jax distributed runtime's KV store + barrier
  — no device collectives): every process publishes which passes its
  writer made locally durable (or its writer error), all rendezvous,
  and the commit set is the INTERSECTION (writer speeds differ, so the
  drop-oldest policy can drop different passes per host — a pass is
  durable only where EVERY host's shards landed). Process 0 then merges
  partial indexes + manifests and renames each agreed pass into place;
  a second agreement round carries process 0's commit verdict to every
  host (and keeps the round counters aligned even when the commit
  itself fails).
- **Writer failures propagate to every host**: a failed write surfaces
  as :class:`CheckpointError` from drain() on ALL processes (the
  agreement carries the error), so the job tears down together instead
  of one rank dying while the rest block in a barrier. This is the
  sharded analog of the single-process "next save/drain" contract —
  made symmetric, which is why sharded ``save()`` does NOT re-raise a
  pending local error early.
"""

from __future__ import annotations

import importlib
import json
import os
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from paddle_tpu.observability import metrics as obs
from paddle_tpu.resilience import CheckpointError
from paddle_tpu.sparse import runtime as sparse_rt
from paddle_tpu.utils import concurrency as cc
from paddle_tpu.utils.logging import logger

__all__ = [
    "AsyncCheckpointer", "ShardedAsyncCheckpointer", "snapshot_to_host",
]


class _LazyModule:
    """Import-on-first-attribute proxy. The concurrency machinery here
    (queues, writer threads, the drain protocol) is jax-free by design
    — `paddle race` drives it with injected write/snapshot/finalize
    seams and must never pay (or depend on) the jax import — while the
    production paths still reach the real checkpoint module the moment
    they touch it. Attribute assignment works normally (tests
    monkeypatch ``ac_mod.ckpt.finalize_sharded_pass``)."""

    def __init__(self, name: str):
        self._name = name

    def __getattr__(self, attr):
        if attr.startswith("__"):  # dunder probes (copy/pickle) stay cheap
            raise AttributeError(attr)
        return getattr(importlib.import_module(self._name), attr)


#: the durable-protocol module (PR 1), resolved lazily — see _LazyModule
ckpt: Any = _LazyModule("paddle_tpu.trainer.checkpoint")


def snapshot_to_host(tree):
    """Device→host copy of a pytree: dispatch EVERY leaf's async copy
    first, then collect — the collection blocks only until the last DMA
    lands, not once per leaf. Host leaves (numpy scalars in a restored
    opt_state) pass through."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    for leaf in leaves:
        copy_async = getattr(leaf, "copy_to_host_async", None)
        if copy_async is not None:
            try:
                copy_async()
            except Exception:
                pass  # backends without async copies fall back to the
                # blocking np.asarray below — correct, just slower
    return jax.tree_util.tree_unflatten(
        treedef, [np.asarray(leaf) for leaf in leaves]
    )


class _Job:
    __slots__ = ("pass_id", "params", "opt_state", "extra_meta", "keep",
                 "protect_pass", "on_durable", "snapshot", "meta", "seq")

    def __init__(self, pass_id, params, opt_state, extra_meta, keep,
                 protect_pass, on_durable, snapshot=None, meta=None):
        # seq: per-checkpointer monotonically increasing id, assigned at
        # enqueue under the cv. drain()'s writer-progress signal keys on
        # it — NOT on id(job), which the allocator can recycle
        self.seq = -1
        self.pass_id = pass_id
        self.params = params
        self.opt_state = opt_state
        self.extra_meta = extra_meta
        self.keep = keep
        self.protect_pass = protect_pass
        self.on_durable = on_durable
        # sharded-mode payload: {base: (pieces, partial_index)} host
        # snapshot + the pass meta dict (built at save time — the live
        # state keeps training while the write is in flight)
        self.snapshot = snapshot
        self.meta = meta


class AsyncCheckpointer:
    """Background checkpoint writer (see module docstring).

    ``write_fn`` is an injectable seam (fake-clock/gated unit tests);
    production uses :func:`checkpoint.save_checkpoint` — the unchanged
    durable protocol."""

    def __init__(
        self,
        save_dir: str,
        inflight_limit: int = 1,
        hangwatch=None,
        *,
        write_fn: Optional[Callable[..., str]] = None,
        snapshot_fn: Optional[Callable[[Any], Any]] = None,
    ):
        self.save_dir = save_dir
        self.inflight_limit = max(1, int(inflight_limit))
        self.hangwatch = hangwatch
        # injectable seams: production uses the PR-1 durable protocol
        # and the async device→host snapshot; unit tests and the race
        # explorer substitute gated/jax-free fakes
        self._write_fn = write_fn  # None -> ckpt.save_checkpoint, lazily
        self._snapshot_fn = snapshot_fn or snapshot_to_host
        self._cv = cc.Condition()
        self._pending: List[_Job] = []     # queued, oldest first
        self._active: Optional[_Job] = None
        self._error: Optional[BaseException] = None
        self._thread = None
        self._job_seq = 0                  # next _Job.seq, under the cv
        self.dropped = 0
        self.completed = 0

    # -------------------------------------------------------- trainer side

    def save(
        self,
        pass_id: int,
        params: Dict[str, jax.Array],
        opt_state=None,
        extra_meta: Optional[Dict[str, Any]] = None,
        keep: int = 3,
        protect_pass: Optional[int] = None,
        on_durable: Optional[Callable[[int, str], None]] = None,
    ) -> float:
        """Snapshot device→host and enqueue the background write.
        Returns the seconds the caller was blocked (the snapshot — what
        ``ckpt.blocked_s`` accounts). Raises :class:`CheckpointError`
        first if a PREVIOUS background write failed."""
        self._raise_pending_error()
        t0 = cc.perf_counter()
        # ONE pytree so every leaf's async copy (params AND opt_state)
        # is dispatched before the first collection blocks — collecting
        # params first would serialize the two DMA trees
        host_params, host_opt = self._snapshot_fn((params, opt_state))
        blocked = cc.perf_counter() - t0
        job = _Job(pass_id, host_params, host_opt, dict(extra_meta or {}),
                   keep, protect_pass, on_durable)
        self._enqueue(job, blocked)
        return blocked

    def _enqueue(self, job: _Job, blocked: float) -> None:
        """Queue one snapshotted job on the bounded writer queue (the
        shared half of sync-tree and sharded saves): drop-oldest-pending
        beyond the limit, wake the writer, account the snapshot cost."""
        with self._cv:
            job.seq = self._job_seq
            self._job_seq += 1
            self._pending.append(job)
            # drop-oldest-pending: the active write cannot be revoked
            # mid-protocol and the newest state is the one worth keeping
            while len(self._pending) > self.inflight_limit:
                old = self._pending.pop(0)
                self.dropped += 1
                obs.registry().counter("ckpt.async_dropped").inc()
                logger.warning(
                    "async checkpoint: dropping queued save of pass %d "
                    "(superseded by pass %d; --ckpt_inflight_limit=%d)",
                    old.pass_id, job.pass_id, self.inflight_limit,
                )
            self._set_inflight_gauge_locked()
            self._cv.notify_all()
        self._ensure_thread()
        obs.registry().counter("ckpt.blocked_s").inc(blocked)
        obs.emit(
            "checkpoint", op="snapshot", pass_id=job.pass_id,
            step=job.extra_meta.get("batch_id"),
            path=ckpt.PASS_FMT % job.pass_id if self.save_dir else "",
            duration_s=round(blocked, 6),
        )

    def _wait_idle(self, timeout: Optional[float] = None) -> None:
        """Block until the local writer queue is empty (or ``timeout``
        seconds passed — then :class:`CheckpointError`). Pings the
        hangwatch while the writer is demonstrably live so a long write
        at a drain barrier is not misdiagnosed as a trainer hang."""
        deadline = None if timeout is None else cc.monotonic() + timeout
        # a dead/never-started writer would leave the queue stuck: make
        # sure one is running before waiting on it
        self._ensure_thread()
        with self._cv:
            last_state = None
            while self._pending or self._active is not None:
                # ping only when the WRITER demonstrably progressed (a
                # write completed / a new job was claimed) since the
                # last poll: an unconditional ping would keep a writer
                # wedged forever on a dead fs from ever tripping the
                # watchdog — the exact failure hangwatch exists for.
                # Keyed on the claimed job's enqueue seq, NOT on queue
                # shape or id(): a concurrent save()'s drop-oldest
                # rearranging `_pending` is trainer-side motion (the
                # wedged writer would look live and never trip the
                # watchdog), and a recycled id() after a completed job
                # would hide a real claim (a live writer tripping it) —
                # both surfaced by the `paddle race` drain spec
                state = (self.completed,
                         self._active.seq if self._active is not None
                         else None)
                if (self.hangwatch is not None
                        and self._active is not None
                        and state != last_state):
                    self.hangwatch.ping(self._active.pass_id)
                last_state = state
                self._cv.wait(timeout=0.2)
                if deadline is not None and cc.monotonic() > deadline:
                    raise CheckpointError(
                        f"async checkpoint drain timed out after {timeout}s "
                        f"({len(self._pending)} pending, active="
                        f"{self._active.pass_id if self._active else None})"
                    )

    def drain(self, timeout: Optional[float] = None) -> None:
        """Block until every enqueued save is durable (or ``timeout``
        seconds passed — then :class:`CheckpointError`). Re-raises a
        stored writer failure."""
        self._wait_idle(timeout)
        self._raise_pending_error()

    def inflight(self) -> int:
        with self._cv:
            return len(self._pending) + (1 if self._active is not None else 0)

    # --------------------------------------------------------- writer side

    def _ensure_thread(self) -> None:
        with self._cv:
            if self._thread is not None and self._thread.is_alive():
                return
            self._thread = cc.Thread(
                target=self._run, name="pt-ckpt-writer", daemon=True
            )
            self._thread.start()

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._pending:
                    # BOUNDED idle wait (lint rule PTL008): a daemon
                    # thread parked forever on an uninstrumented
                    # primitive cannot be reported forensically by the
                    # hang-defense stack; waking to re-check the
                    # predicate once a minute is free
                    self._cv.wait(timeout=60.0)
                self._active = self._pending.pop(0)
                self._set_inflight_gauge_locked()
                job = self._active
            try:
                self._write(job)
            finally:
                # drop BOTH references to the host snapshot before the
                # idle wait — holding it would keep a full extra host
                # copy of model+optimizer state resident between saves
                job = None
                with self._cv:
                    self._active = None
                    self._set_inflight_gauge_locked()
                    self._cv.notify_all()

    def _default_write_fn(self):
        return ckpt.save_checkpoint

    def _write(self, job: _Job) -> None:
        if self.hangwatch is not None:
            self.hangwatch.ping(job.pass_id)
        t0 = cc.perf_counter()
        try:
            path = (self._write_fn or self._default_write_fn())(
                self.save_dir,
                job.pass_id,
                job.params,
                job.opt_state,
                extra_meta=job.extra_meta,
                keep=job.keep,
                protect_pass=job.protect_pass,
            )
        except BaseException as e:
            with self._cv:
                self._error = e
            logger.error(
                "async checkpoint: background write of pass %d failed: %s "
                "(will re-raise as CheckpointError on the next save/drain)",
                job.pass_id, e,
            )
            return
        finally:
            if self.hangwatch is not None:
                self.hangwatch.ping(job.pass_id)
        dt = cc.perf_counter() - t0
        # under the cv: drain() reads `completed` (from the step-loop
        # thread) as its writer-progress signal — a torn increment would
        # read as "no progress" and misdiagnose a live drain as a hang
        with self._cv:
            self.completed += 1
        obs.registry().counter("ckpt.write_s").inc(dt)
        if job.on_durable is not None:
            try:
                job.on_durable(job.pass_id, path)
            except Exception:
                logger.warning(
                    "async checkpoint: on_durable callback failed for "
                    "pass %d", job.pass_id, exc_info=True,
                )

    # ------------------------------------------------------------- plumbing

    def _set_inflight_gauge_locked(self) -> None:
        obs.registry().gauge("ckpt.async_inflight").set(
            len(self._pending) + (1 if self._active is not None else 0)
        )

    def _take_error(self) -> Optional[BaseException]:
        with self._cv:
            err, self._error = self._error, None
        return err

    def _raise_pending_error(self) -> None:
        err = self._take_error()
        if err is not None:
            raise CheckpointError(
                f"async checkpoint write failed: {err}"
            ) from err


class _KvAgreement:
    """The pass-end agreement channel: publish a small payload, wait for
    every process, read everyone's payloads back — over the jax
    distributed runtime's KV store + host barrier. No device collectives
    (the agreement must work even when the backend cannot run
    cross-process computations, and must not occupy the accelerator).
    Single-process (or no distributed client): degenerates to returning
    only the local payload. Rounds are numbered locally; the agreement
    is only ever called from collective call sites (drain), so every
    process's round counter stays aligned."""

    def __init__(self, timeout_s: float = 600.0):
        import jax

        from paddle_tpu.utils.barrier import distributed_client

        self.timeout_s = float(timeout_s)
        self.client = distributed_client()
        self.pid = jax.process_index()
        self.count = jax.process_count()
        self._round = 0
        self._prev_key: Optional[str] = None

    def agree(self, payload: str) -> List[str]:
        """Everyone's payloads, pid-ordered. Raises on rendezvous
        failure (a peer died mid-protocol)."""
        r = self._round
        self._round += 1
        if self.client is None or self.count == 1:
            return [payload]
        timeout_ms = int(self.timeout_s * 1000)
        key = f"ckpt_agree/{r}/{self.pid:05d}"
        if self._prev_key is not None:
            # bound KV-store growth by one round, deleting only NOW:
            # deleting right after our own dir read would race a slower
            # peer still reading that round's directory (the barrier
            # orders the sets before any read, but nothing orders one
            # process's delete after ANOTHER's read — except the next
            # round's barrier, which is where we are)
            try:
                self.client.key_value_delete(self._prev_key)
            except Exception:
                pass
        self._prev_key = key
        self.client.key_value_set(key, payload)
        self.client.wait_at_barrier(f"ckpt_agree_{r}", timeout_ms)
        items = self.client.key_value_dir_get(f"ckpt_agree/{r}/")
        return [v for _k, v in sorted(items)]



class ShardedAsyncCheckpointer(AsyncCheckpointer):
    """Per-host async shard writer + pass-end commit agreement — the
    multi-process elastic twin of :class:`AsyncCheckpointer` (see the
    module docstring for the protocol and its failure contract)."""

    def __init__(
        self,
        save_dir: str,
        inflight_limit: int = 1,
        hangwatch=None,
        *,
        process_index: Optional[int] = None,
        process_count: Optional[int] = None,
        agreement=None,
        agree_timeout: float = 600.0,
        write_fn: Optional[Callable[..., None]] = None,
        snapshot_fn: Optional[Callable[..., Any]] = None,
        finalize_fn: Optional[Callable[..., str]] = None,
    ):
        super().__init__(save_dir, inflight_limit, hangwatch,
                         write_fn=write_fn)
        # sharded snapshot contract differs from the base's one-tree
        # copy: (pass_id, params, opt_state, extra_meta) -> (snapshot,
        # meta); finalize_fn(pass_id, job, rotate) -> final path runs
        # process 0's commit merge. Both injectable (race specs drive
        # the REAL queue/commit protocol jax-free)
        self._snapshot_fn = snapshot_fn or self._default_shard_snapshot
        self._finalize_fn = finalize_fn or self._default_finalize
        if process_index is None or process_count is None:
            import jax
        self.pid = (jax.process_index() if process_index is None
                    else int(process_index))
        self.count = (jax.process_count() if process_count is None
                      else int(process_count))
        self.agreement = agreement or _KvAgreement(agree_timeout)
        # locally durable jobs awaiting the commit agreement
        self._durable: List[_Job] = []
        # save() calls since the last drain: when zero on every process
        # (deterministic — saves are collective call sites), drain skips
        # the agreement round entirely, so saving_period > 1 does not
        # pay per-pass KV chatter
        self._saves_since_drain = 0

    # -------------------------------------------------------- trainer side

    def save(
        self,
        pass_id: int,
        params: Dict[str, jax.Array],
        opt_state=None,
        extra_meta: Optional[Dict[str, Any]] = None,
        keep: int = 3,
        protect_pass: Optional[int] = None,
        on_durable: Optional[Callable[[int, str], None]] = None,
    ) -> float:
        """Snapshot this process's owned shards device→host and enqueue
        the background shard write. Unlike the single-process save, a
        pending LOCAL writer error is NOT raised here — it travels
        through the next drain's agreement so every host fails together
        instead of this one desyncing the collective call sites."""
        t0 = cc.perf_counter()
        snapshot, meta = self._snapshot_fn(
            pass_id, params, opt_state, extra_meta
        )
        blocked = cc.perf_counter() - t0
        job = _Job(pass_id, None, None, dict(extra_meta or {}), keep,
                   protect_pass, on_durable, snapshot=snapshot, meta=meta)
        self._saves_since_drain += 1
        self._enqueue(job, blocked)
        return blocked

    def drain(self, timeout: Optional[float] = None) -> None:
        """Local writer barrier + the pass-end commit agreement.

        1. Wait for THIS host's writer queue to empty.
        2. Publish ``{ok, passes}`` (locally durable pass ids, or the
           writer error) and rendezvous with every process.
        3. Any host not ok → :class:`CheckpointError` on EVERY host.
        4. Process 0 finalizes the agreed (intersection) passes: merge
           indexes + manifests, meta, rename, one rotation at the end.
        5. A second agreement round carries process 0's commit verdict
           (a barrier alone could not say WHY it was released): a failed
           finalize raises :class:`CheckpointError` on every host with
           the rounds still aligned, instead of process 0 dying raw
           while the peers stall out a bare barrier.
        6. Per-process ``on_durable`` callbacks for the committed set.
        """
        self._wait_idle(timeout)
        err = self._take_error()
        with self._cv:
            durable, self._durable = self._durable, []
        saves, self._saves_since_drain = self._saves_since_drain, 0
        if not saves and err is None and not durable:
            return  # nothing enqueued anywhere since the last agreement
        local: Dict[int, _Job] = {}
        for job in durable:  # latest-wins per pass (periodic + pass-end)
            local[job.pass_id] = job
        payload = json.dumps({
            "pid": self.pid,
            "ok": err is None,
            "passes": sorted(local),
            "error": "" if err is None else f"{type(err).__name__}: {err}",
        })
        if self.hangwatch is not None and local:
            # entering a blocking rendezvous that lasts as long as the
            # slowest peer's write: one ping so the wait is measured
            # from here, exactly like the sync sharded save's barrier
            self.hangwatch.ping(max(local))
        try:
            replies = [json.loads(r) for r in self.agreement.agree(payload)]
        except Exception as e:
            raise CheckpointError(
                f"sharded checkpoint agreement failed (peer died "
                f"mid-protocol?): {e}"
            ) from e
        bad = [d for d in replies if not d.get("ok")]
        if bad or err is not None:
            detail = "; ".join(
                f"host {d.get('pid')}: {d.get('error') or 'failed'}" for d in bad
            ) or f"host {self.pid}: {err}"
            raise CheckpointError(
                f"sharded async checkpoint write failed — {detail} "
                "(no pass from this round was committed)"
            ) from err
        commit = set(local)
        for d in replies:
            commit &= set(d.get("passes", []))
        ordered = sorted(commit)
        finals: Dict[int, str] = {}
        commit_err: Optional[BaseException] = None
        if self.pid == 0:
            try:
                for i, p in enumerate(ordered):
                    # ONE rotation after the last commit: rotating
                    # mid-batch would sweep the .tmp of the next pass
                    # awaiting its own commit
                    finals[p] = self._finalize_fn(
                        p, local[p], i == len(ordered) - 1
                    )
            except BaseException as e:
                # captured, not raised: the commit round below must still
                # run so the peers learn the verdict and every process's
                # agreement round counter stays aligned
                commit_err = e
        try:
            verdicts = self.agreement.agree(json.dumps({
                "pid": self.pid, "committed": commit_err is None,
            }))
        except Exception as e:
            raise CheckpointError(
                f"sharded checkpoint commit rendezvous failed: {e}"
            ) from e
        # pid-ordered replies: the head is process 0's commit verdict
        head = json.loads(verdicts[0])
        if not head.get("committed", False):
            raise CheckpointError(
                "sharded checkpoint commit failed on host 0: "
                f"{commit_err if commit_err is not None else 'see host 0 log'}"
            ) from commit_err
        for p in ordered:
            job = local[p]
            if job.on_durable is not None:
                final = finals.get(p)
                if final is None:
                    # non-zero pids never ran finalize; reconstruct the
                    # path (this is the one place a peer host touches
                    # the checkpoint module, and only lazily)
                    final = os.path.join(self.save_dir, ckpt.PASS_FMT % p)
                try:
                    job.on_durable(p, final)
                except Exception:
                    logger.warning(
                        "async checkpoint: on_durable callback failed for "
                        "pass %d", p, exc_info=True,
                    )

    # --------------------------------------------------------- writer side

    def _default_shard_snapshot(self, pass_id, params, opt_state, extra_meta):
        trees, meta = ckpt.build_save_trees(
            pass_id, params, opt_state, extra_meta, multihost=True
        )
        # sparse-table meta: which params are row-sharded tables and
        # how many hosts wrote this pass — restore compares the host
        # count against its own to detect (and count) a reshard
        tables = sparse_rt.registered_tables()
        if tables:
            meta.setdefault("sparse_tables", tables)
            meta.setdefault("sparse_hosts", self.count)
        return ckpt.snapshot_owned_trees(trees, self.pid), meta

    def _default_finalize(self, pass_id: int, job: _Job, rotate: bool) -> str:
        t0 = cc.perf_counter()
        final = ckpt.finalize_sharded_pass(
            self.save_dir, pass_id, job.snapshot.keys(), job.meta,
            keep=job.keep, protect_pass=job.protect_pass,
            expected_pids=range(self.count), rotate=rotate,
        )
        logger.info("saved checkpoint %s", final)
        ckpt._ckpt_record(
            "save", final, t0, pass_id=pass_id, measure_bytes=True,
            step=job.extra_meta.get("batch_id"),
        )
        return final

    def _default_write_fn(self):
        return ckpt.write_sharded_host_trees

    def _write(self, job: _Job) -> None:
        if self.hangwatch is not None:
            self.hangwatch.ping(job.pass_id)
        t0 = cc.perf_counter()
        try:
            (self._write_fn or self._default_write_fn())(
                self.save_dir, job.pass_id, job.snapshot, self.pid
            )
        except BaseException as e:
            with self._cv:
                self._error = e
            logger.error(
                "async checkpoint: background shard write of pass %d failed "
                "on host %d: %s (will surface as CheckpointError on every "
                "host at the next drain agreement)",
                job.pass_id, self.pid, e,
            )
            return
        finally:
            if self.hangwatch is not None:
                self.hangwatch.ping(job.pass_id)
        dt = cc.perf_counter() - t0
        obs.registry().counter("ckpt.write_s").inc(dt)
        # the written pieces are on disk now — keep only the tree bases
        # (what the commit merge needs), so a pass awaiting its
        # agreement does not pin a full host copy of this host's shards
        job.snapshot = dict.fromkeys(job.snapshot)
        # `completed` under the cv with the durable list: drain() reads
        # both from the step-loop thread as its writer-progress signal
        with self._cv:
            self.completed += 1
            self._durable.append(job)
