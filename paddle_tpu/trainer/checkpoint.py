"""Checkpointing — pass-%05d directories with params + optimizer state.

Reference: ParameterUtil (/root/reference/paddle/trainer/ParamUtil.cpp:
53-103) wrote one binary file per parameter with a versioned header and
rolled old pass dirs; the reference did NOT checkpoint optimizer state — we
do (SURVEY.md §5 flags this as a required upgrade). Format: one .npz for
params, one for optimizer slots, meta.json for step counters + config
snapshot. Multi-host sharded checkpointing rides orbax (parallel stage).
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.optimizer.updater import UpdaterState
from paddle_tpu.utils.logging import logger

PASS_FMT = "pass-%05d"


def _flatten(tree: Dict, prefix: str = "") -> Dict[str, np.ndarray]:
    out = {}
    for k, v in tree.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, key + "/"))
        elif v is not None:
            out[key] = np.asarray(v)
    return out


def _unflatten(flat: Dict[str, np.ndarray]) -> Dict:
    out: Dict = {}
    for k, v in flat.items():
        parts = k.split("/")
        d = out
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = jnp.asarray(v)
    return out


def save_checkpoint(
    save_dir: str,
    pass_id: int,
    params: Dict[str, jax.Array],
    opt_state: Optional[UpdaterState] = None,
    extra_meta: Optional[Dict[str, Any]] = None,
    keep: int = 3,
) -> str:
    path = os.path.join(save_dir, PASS_FMT % pass_id)
    os.makedirs(path, exist_ok=True)
    np.savez(os.path.join(path, "params.npz"), **_flatten(params))
    meta: Dict[str, Any] = {"pass_id": pass_id, "format_version": 1}
    if opt_state is not None:
        np.savez(os.path.join(path, "optimizer_slots.npz"), **_flatten(opt_state.slots))
        if opt_state.avg_sum is not None:
            np.savez(os.path.join(path, "optimizer_avg.npz"), **_flatten(opt_state.avg_sum))
        if opt_state.avg_old_sum is not None:
            np.savez(
                os.path.join(path, "optimizer_avg_old.npz"),
                **_flatten(opt_state.avg_old_sum),
            )
        meta["optimizer"] = {
            "step": int(opt_state.step),
            "num_samples": float(opt_state.num_samples),
            "avg_count": float(opt_state.avg_count),
            "avg_old_count": (
                float(opt_state.avg_old_count)
                if opt_state.avg_old_count is not None
                else 0.0
            ),
        }
    if extra_meta:
        meta.update(extra_meta)
    with open(os.path.join(path, "meta.json"), "w") as f:
        json.dump(meta, f, indent=2)
    _rotate(save_dir, keep)
    logger.info("saved checkpoint %s", path)
    return path


def _rotate(save_dir: str, keep: int) -> None:
    """Rolling deletion of old pass dirs (ParamUtil::deleteOldestPass)."""
    if keep <= 0:
        return
    passes = sorted(
        d for d in os.listdir(save_dir) if d.startswith("pass-") and d[5:].isdigit()
    )
    for d in passes[:-keep]:
        shutil.rmtree(os.path.join(save_dir, d), ignore_errors=True)


def latest_pass(save_dir: str) -> Optional[int]:
    if not os.path.isdir(save_dir):
        return None
    passes = [
        int(d[5:]) for d in os.listdir(save_dir) if d.startswith("pass-") and d[5:].isdigit()
    ]
    return max(passes) if passes else None


def load_checkpoint(
    path: str,
    opt_template: Optional[UpdaterState] = None,
    missing: str = "fail",
    expected_params: Optional[Dict[str, jax.Array]] = None,
) -> Tuple[Dict[str, jax.Array], Optional[UpdaterState], Dict[str, Any]]:
    """Load params (+ optimizer state rebuilt onto ``opt_template``).

    ``missing``: fail | rand | zero — the reference's
    --load_missing_parameter_strategy; ``expected_params`` supplies shapes
    (and values, for 'rand') for parameters absent from the file.
    """
    with np.load(os.path.join(path, "params.npz")) as z:
        params = {k: jnp.asarray(z[k]) for k in z.files}
    if expected_params is not None:
        for name, val in expected_params.items():
            if name not in params:
                if missing == "fail":
                    raise KeyError(f"parameter {name!r} missing from checkpoint {path}")
                params[name] = jnp.zeros_like(val) if missing == "zero" else val
    meta = {}
    meta_path = os.path.join(path, "meta.json")
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
    opt_state = None
    slots_path = os.path.join(path, "optimizer_slots.npz")
    if opt_template is not None and os.path.exists(slots_path):
        with np.load(slots_path) as z:
            slots = _unflatten({k: z[k] for k in z.files})
        om = meta.get("optimizer", {})
        avg_sum = opt_template.avg_sum
        avg_path = os.path.join(path, "optimizer_avg.npz")
        if avg_sum is not None and os.path.exists(avg_path):
            with np.load(avg_path) as z:
                avg_sum = {k: jnp.asarray(z[k]) for k in z.files}
        avg_old_sum = opt_template.avg_old_sum
        avg_old_path = os.path.join(path, "optimizer_avg_old.npz")
        if avg_old_sum is not None and os.path.exists(avg_old_path):
            with np.load(avg_old_path) as z:
                avg_old_sum = {k: jnp.asarray(z[k]) for k in z.files}
        opt_state = UpdaterState(
            step=jnp.asarray(om.get("step", 0), jnp.int32),
            num_samples=jnp.asarray(om.get("num_samples", 0.0), jnp.float32),
            slots={k: {s: jnp.asarray(v) for s, v in d.items()} for k, d in slots.items()},
            avg_sum=avg_sum,
            avg_count=jnp.asarray(om.get("avg_count", 0.0), jnp.float32),
            avg_old_sum=avg_old_sum,
            avg_old_count=jnp.asarray(om.get("avg_old_count", 0.0), jnp.float32),
        )
    logger.info("loaded checkpoint %s", path)
    return params, opt_state, meta


def merge_model(save_dir: str, pass_id: int, config_json: str, out_path: str) -> None:
    """MergeModel analog (/root/reference/paddle/trainer/MergeModel.cpp):
    bundle config + parameters into one deployable .npz."""
    path = os.path.join(save_dir, PASS_FMT % pass_id)
    with np.load(os.path.join(path, "params.npz")) as z:
        arrays = {k: z[k] for k in z.files}
    arrays["__config_json__"] = np.frombuffer(config_json.encode(), dtype=np.uint8)
    np.savez(out_path, **arrays)
