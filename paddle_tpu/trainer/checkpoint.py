"""Checkpointing — pass-%05d directories with params + optimizer state.

Reference: ParameterUtil (/root/reference/paddle/trainer/ParamUtil.cpp:
53-103) wrote one binary file per parameter with a versioned header and
rolled old pass dirs; the reference did NOT checkpoint optimizer state — we
do (SURVEY.md §5 flags this as a required upgrade).

Single-host format: one .npz for params, one per optimizer tree,
meta.json for step counters + config snapshot.

Multi-host SHARDED format (the pserver-side save/load analog,
ParameterServer2::loadValueVector/saveValueVector,
/root/reference/paddle/pserver/ParameterServer2.cpp:1150-1213): every
process writes the addressable shards it uniquely owns (replica_id == 0)
to ``<tree>.shard<pid>.npz`` plus a partial index; after a cross-process
barrier, process 0 merges the partials into ``<tree>.index.json``. The
save_dir must be a shared filesystem (the standard TPU-pod setup; same
assumption orbax/GCS makes). Restore assembles each parameter from its
shard records and re-shards onto the CURRENT mesh via
``jax.make_array_from_callback`` — a checkpoint written on one mesh
layout loads onto any other, including single-host ↔ multi-host moves.

DURABILITY (doc/resilience.md): a save writes into ``pass-%05d.tmp``,
fsyncs every file, records a per-file CRC32/size manifest
(``MANIFEST.json``), and only then renames the directory into place —
the previous checkpoint (including an earlier save of the SAME pass) is
never removed until the new one is durable, so a crash at any point
leaves at least one restorable checkpoint. ``load_checkpoint`` verifies
the manifest first and, on corruption or incompleteness, quarantines the
bad directory (``*.corrupt``) and falls back to the newest earlier pass.
File I/O retries transient OSErrors through the shared RetryPolicy
(``--io_retry_*``). The reference's ParamUtil rewrote pass dirs in
place, destroying the previous checkpoint on a mid-save crash — the
exact gap SURVEY §5 flags.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import time
import zipfile
import zlib
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.observability import metrics as obs
from paddle_tpu.observability import spans as obs_spans
from paddle_tpu.optimizer.updater import UpdaterState
from paddle_tpu.resilience import CheckpointCorruptError
from paddle_tpu.resilience import manifest as ckpt_manifest
from paddle_tpu.resilience.faultinject import FaultInjected, fault_point
from paddle_tpu.sparse import runtime as sparse_rt
from paddle_tpu.utils.flags import FLAGS
from paddle_tpu.utils.logging import logger
from paddle_tpu.utils.retry import RetryPolicy

PASS_FMT = "pass-%05d"
TMP_SUFFIX = ".tmp"
CORRUPT_SUFFIX = ".corrupt"

# pass dirs COMMITTED (written, fsynced, manifested, renamed into place)
# by THIS process. An in-run restore of one of them — the rollback path,
# where the trainer reloads a checkpoint it saved minutes earlier — may
# skip re-CRCing the bytes (callers opt in via ``trust_own_writes``);
# verification cost belongs to cold restores, and a fresh process
# starts with an empty set, so those always verify in full.
_written_this_process: set = set()


def written_this_process(path: str) -> bool:
    """True when this process committed ``path`` (and it has not been
    quarantined since)."""
    return os.path.abspath(os.path.normpath(path)) in _written_this_process


def _is_pass_dir_name(d: str) -> bool:
    return d.startswith("pass-") and d[5:].isdigit()


def _dir_bytes(path: str) -> int:
    """On-disk size of one checkpoint dir (telemetry only: best-effort)."""
    total = 0
    try:
        for root, _dirs, files in os.walk(path):
            for f in files:
                try:
                    total += os.path.getsize(os.path.join(root, f))
                except OSError:
                    pass
    except OSError:
        pass
    return total


def _ckpt_record(op: str, path: str, t0: float, pass_id: Optional[int] = None,
                 measure_bytes: bool = False, **fields) -> None:
    """One structured ``checkpoint`` record + matching span (save/load/
    verify durations and bytes — doc/observability.md). The dir walk for
    ``measure_bytes`` only runs when telemetry is actually on — a
    telemetry-less tool (merge_model, tests) must not pay thousands of
    stat() calls for a field a no-op emit would discard. Multi-host
    saves/loads are collective: only process 0 records (and walks), so a
    pod save costs ONE shared-FS directory walk, not N, and `paddle
    metrics` shows one checkpoint row per operation. Spans stay per-host
    (host-side timing is cheap and genuinely per process)."""
    dur = time.perf_counter() - t0
    obs_spans.record_perf(f"checkpoint/{op}", t0, dur)
    if not obs.enabled():
        return
    if jax.process_count() > 1 and jax.process_index() != 0:
        return
    if measure_bytes:
        fields["bytes"] = _dir_bytes(path)
    obs.emit("checkpoint", op=op, path=path, pass_id=pass_id,
             duration_s=round(dur, 6), **fields)


def _io_policy() -> RetryPolicy:
    """Shared-FS writes/reads see transient errors at pod scale; all
    checkpoint file I/O funnels through this one policy.

    Deliberately built from the process-global FLAGS (not a trainer's
    _Flags instance): this module also serves flag-less tools
    (check-checkpoint, merge_model, torch2paddle) and deep helpers that
    have no trainer in scope. Per-trainer ``--io_retry_*`` overrides DO
    reach the data-provider retry (trainer._provider); a trainer wanting
    different checkpoint-I/O retries sets the global FLAGS."""
    return RetryPolicy.from_flags(FLAGS, name="checkpoint-io")


def _fsync_dir(path: str) -> None:
    """Make a directory entry durable (rename atomicity needs the parent
    synced). Best-effort: not every filesystem supports dir fsync."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _write_file(path: str, writer: Callable, mode: str = "wb") -> None:
    """One durable checkpoint file: fault site → write → flush → fsync,
    the whole unit retried on transient OSError (a retry reopens the
    file, so a partial first attempt is truncated away)."""

    def once():
        fault_point("checkpoint.write", info=os.path.basename(path))
        with open(path, mode) as f:
            writer(f)
            f.flush()
            os.fsync(f.fileno())

    _io_policy().call(once, name=f"write {os.path.basename(path)}")


def _durable_manifest(fn, *args, label: str):
    """Manifest writes get the same treatment as every other checkpoint
    file: the checkpoint.write fault site + the shared retry policy
    (the fsync discipline lives inside manifest.py itself)."""

    def once():
        fault_point("checkpoint.write", info=label)
        return fn(*args)

    return _io_policy().call(once, name=f"write {label}")


def _flatten(tree: Dict, prefix: str = "") -> Dict[str, Any]:
    """Flatten nested dicts to 'a/b' keys. Values are NOT materialized —
    np.savez coerces at write time (single-host), and the sharded writer
    must see live jax.Arrays to read their addressable shards."""
    out = {}
    for k, v in tree.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, key + "/"))
        elif v is not None:
            out[key] = v
    return out


def _unflatten(flat: Dict[str, np.ndarray]) -> Dict:
    out: Dict = {}
    for k, v in flat.items():
        parts = k.split("/")
        d = out
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = jnp.asarray(v)
    return out


def snapshot_owned_trees(
    trees: Dict[str, Dict[str, Any]], pid: Optional[int] = None
) -> Dict[str, Tuple[Dict[str, np.ndarray], Dict[str, Any]]]:
    """Device→host snapshot of the shards THIS process uniquely owns
    (replica_id == 0), across ALL trees at once: every owned shard's
    async copy is dispatched before the first collection blocks (the
    sharded twin of async_ckpt.snapshot_to_host), so the caller pays one
    DMA wait, not one per shard. Returns ``{base: (pieces, partial)}``
    where ``pieces`` are the npz members to write and ``partial`` is the
    per-process index fragment (shard filenames already stamped)."""
    pid = jax.process_index() if pid is None else int(pid)
    staged: Dict[str, List[Tuple[str, Any, str, Any]]] = {}
    for base, flat in trees.items():
        owned: List[Tuple[str, Any, str, Any]] = []
        for name, arr in flat.items():
            arr = jnp.asarray(arr) if not isinstance(arr, jax.Array) else arr
            for i, sh in enumerate(arr.addressable_shards):
                if sh.replica_id != 0:
                    continue  # exactly one process owns each distinct slice
                copy_async = getattr(sh.data, "copy_to_host_async", None)
                if copy_async is not None:
                    try:
                        copy_async()
                    except Exception:
                        pass  # backends without async copies: the
                        # np.asarray below blocks — correct, just slower
                owned.append((name, arr, f"{name}::{i}", sh))
        staged[base] = owned
    out: Dict[str, Tuple[Dict[str, np.ndarray], Dict[str, Any]]] = {}
    for base, owned in staged.items():
        shard_file = f"{base}.shard{pid:05d}.npz"
        pieces: Dict[str, np.ndarray] = {}
        partial: Dict[str, Any] = {}
        for name, arr, key, sh in owned:
            data = np.asarray(sh.data)
            pieces[key] = data
            entry = partial.get(name)
            if entry is None:
                # the GLOBAL parameter shape/dtype, not the shard's
                entry = partial[name] = {
                    "shape": list(arr.shape),
                    "dtype": str(arr.dtype),
                    "shards": [],
                }
            rec = {
                "file": shard_file,
                "key": key,
                "start": [int(sl.start or 0) for sl in sh.index],
                # record extent up front so restore can skip
                # non-overlapping records without reading them
                "shape": list(data.shape),
            }
            # row-sharded sparse tables (and their per-row optimizer
            # slots) carry an EXPLICIT row interval: check-checkpoint
            # proves exact row coverage from these, and a relaunch
            # reshard reads only overlapping records (doc/sparse.md)
            nrows = sparse_rt.registered_tables().get(name.split("/", 1)[0])
            if (nrows is not None and data.ndim >= 1
                    and int(arr.shape[0]) == int(nrows)):
                lo = rec["start"][0] if rec["start"] else 0
                rec["row_range"] = [lo, lo + int(data.shape[0])]
            entry["shards"].append(rec)
        out[base] = (pieces, partial)
    return out


def write_owned_shards(
    path: str, base: str, pid: int,
    pieces: Dict[str, np.ndarray], partial: Dict[str, Any],
) -> str:
    """Durably write one process's shard file + partial index for one
    tree (the write half of ``_save_tree_sharded``). Returns the shard
    filename (the caller manifests the files it wrote)."""
    shard_file = f"{base}.shard{pid:05d}.npz"
    _write_file(os.path.join(path, shard_file), lambda f: np.savez(f, **pieces))
    # the partial index is transient (merged then deleted): durable write,
    # but never manifested
    _write_file(
        os.path.join(path, f"{base}.index.{pid:05d}.json"),
        lambda f: json.dump(partial, f),
        mode="w",
    )
    return shard_file


def _save_tree_sharded(path: str, base: str, flat: Dict[str, jax.Array]) -> str:
    """Write this process's uniquely-owned shards of one tree + a partial
    index. Called by EVERY process (snapshot + write in one step — the
    synchronous path; the async path stages the two halves)."""
    pid = jax.process_index()
    pieces, partial = snapshot_owned_trees({base: flat}, pid)[base]
    return write_owned_shards(path, base, pid, pieces, partial)


def write_sharded_host_trees(
    save_dir: str, pass_id: int,
    snapshot: Dict[str, Tuple[Dict[str, np.ndarray], Dict[str, Any]]],
    pid: int,
) -> None:
    """Background-writer half of a sharded ASYNC save: write this
    process's shard files + partial indexes + partial manifest into the
    pass's tmp dir. Every process's writer calls this independently
    (``exist_ok``: no cross-process ordering before the pass-end
    agreement); the commit half is :func:`finalize_sharded_pass`."""
    tmp = os.path.join(save_dir, PASS_FMT % pass_id) + TMP_SUFFIX
    os.makedirs(tmp, exist_ok=True)
    # chaos site: this host's row shards never land — the pass cannot
    # commit, and check-checkpoint must name the missing row interval
    fault_point("sparse.shard_lost", info=f"pass={pass_id} pid={pid}")
    own_files = [
        write_owned_shards(tmp, base, pid, pieces, partial)
        for base, (pieces, partial) in snapshot.items()
    ]
    _durable_manifest(
        ckpt_manifest.write_partial_manifest, tmp, pid, own_files,
        label=f"MANIFEST.partial.{pid:05d}.json",
    )
    # chaos site: poison a row AFTER the manifest digested the healthy
    # bytes — the CRC verify must catch it and quarantine/fall back
    try:
        fault_point("sparse.row_corrupt", info=f"pass={pass_id} pid={pid}")
    except FaultInjected:
        for fn in own_files:
            full = os.path.join(tmp, fn)
            try:
                size = os.path.getsize(full)
                with open(full, "r+b") as f:
                    f.seek(size // 2)
                    b = f.read(1) or b"\x00"
                    f.seek(size // 2)
                    f.write(bytes([b[0] ^ 0xFF]))
                    f.flush()
                    os.fsync(f.fileno())
            except OSError:
                pass
            break


_SHARD_FILE_RE = re.compile(r"^(?P<base>.+)\.shard(?P<pid>\d{5})\.npz$")
_PARTIAL_IDX_RE = re.compile(r"^(?P<base>.+)\.index\.(?P<pid>\d{5})\.json$")
_MERGED_IDX_RE = re.compile(r"^(?P<base>.+)\.index\.json$")
_PARTIAL_MANIFEST_RE = re.compile(r"^MANIFEST\.partial\.(?P<pid>\d{5})\.json$")


def _sweep_stale_sharded_files(
    tmp: str, tree_bases: Iterable[str], expected_pids: Iterable[int]
) -> None:
    """Drop litter from a CRASHED earlier attempt at this pass out of the
    tmp dir before merging: shard/index/partial-manifest files from a pid
    outside the current process set, or from a tree the current save does
    not write (e.g. an optimizer tree that existed before). Without this,
    the manifest merge would digest a dead process's stale shard into the
    checkpoint and the index merge would resurrect its slices. Only
    recognized checkpoint file patterns are touched."""
    bases = set(tree_bases)
    pids = {int(p) for p in expected_pids}
    for fn in os.listdir(tmp):
        m = _SHARD_FILE_RE.match(fn)
        if m:
            if m.group("base") in bases and int(m.group("pid")) in pids:
                continue
        else:
            m = _PARTIAL_IDX_RE.match(fn)
            if m:
                if m.group("base") in bases and int(m.group("pid")) in pids:
                    continue
            else:
                m = _PARTIAL_MANIFEST_RE.match(fn)
                if m:
                    if int(m.group("pid")) in pids:
                        continue
                else:
                    m = _MERGED_IDX_RE.match(fn)
                    if not m or m.group("base") in bases:
                        continue  # unknown files and live merged indexes stay
        logger.warning("sharded save: sweeping stale file %s from %s", fn, tmp)
        try:
            os.remove(os.path.join(tmp, fn))
        except OSError:
            pass


def finalize_sharded_pass(
    save_dir: str,
    pass_id: int,
    tree_bases: Iterable[str],
    meta: Dict[str, Any],
    keep: int = 3,
    protect_pass: Optional[int] = None,
    expected_pids: Optional[Iterable[int]] = None,
    rotate: bool = True,
) -> str:
    """Process-0 commit half of a sharded save: merge the partial indexes
    and partial manifests every process left in ``pass-N.tmp``, write
    meta.json, and atomically publish the dir (``_commit``). Must only
    run once every process's shards + partial manifest are known durable
    (the sync path's barrier / the async path's pass-end agreement).
    ``expected_pids`` turns on the stale-file sweep (async saves reuse a
    tmp dir a crashed run may have littered); ``rotate=False`` lets a
    caller committing SEVERAL passes in one drain defer rotation until
    the last one (rotation sweeps ``*.tmp`` dirs — including, otherwise,
    the tmp of the next pass awaiting its own commit)."""
    final = os.path.join(save_dir, PASS_FMT % pass_id)
    tmp = final + TMP_SUFFIX
    tree_bases = list(tree_bases)
    if expected_pids is not None:
        _sweep_stale_sharded_files(tmp, tree_bases, expected_pids)
    for base in tree_bases:
        _merge_tree_indexes(tmp, base)
    _write_file(
        os.path.join(tmp, "meta.json"),
        lambda f: json.dump(meta, f, indent=2),
        mode="w",
    )
    _durable_manifest(
        ckpt_manifest.merge_partial_manifests, tmp, label="MANIFEST.json"
    )
    # peers' shards arrived over the shared fs — process 0 cannot vouch
    # for their bytes, so the merged pass never rides the verify skip
    _commit(tmp, final, self_written=False)
    if rotate:
        _rotate(save_dir, keep, protect=protect_pass)
    return final


def _merge_tree_indexes(path: str, base: str) -> None:
    """Process 0, after the barrier: merge partial indexes into
    ``<base>.index.json`` and drop the partials."""
    merged: Dict[str, Any] = {}
    for fn in sorted(os.listdir(path)):
        if not (fn.startswith(f"{base}.index.") and fn.endswith(".json")):
            continue
        if fn == f"{base}.index.json":
            continue
        with open(os.path.join(path, fn)) as f:
            partial = json.load(f)
        for name, entry in partial.items():
            if name in merged:
                assert merged[name]["shape"] == entry["shape"], name
                merged[name]["shards"].extend(entry["shards"])
            else:
                merged[name] = entry
        os.remove(os.path.join(path, fn))
    _write_file(
        os.path.join(path, f"{base}.index.json"),
        lambda f: json.dump(merged, f),
        mode="w",
    )


def _optimizer_trees(opt_state: UpdaterState) -> Dict[str, Dict]:
    trees = {"optimizer_slots": _flatten(opt_state.slots)}
    if opt_state.avg_sum is not None:
        trees["optimizer_avg"] = _flatten(opt_state.avg_sum)
    if opt_state.avg_old_sum is not None:
        trees["optimizer_avg_old"] = _flatten(opt_state.avg_old_sum)
    return trees


def build_save_trees(
    pass_id: int,
    params: Dict[str, jax.Array],
    opt_state: Optional[UpdaterState],
    extra_meta: Optional[Dict[str, Any]],
    multihost: bool,
) -> Tuple[Dict[str, Dict], Dict[str, Any]]:
    """(trees, meta) of one save — the single source both the sync
    ``save_checkpoint`` and the async sharded snapshot build from, so
    the two paths cannot diverge on format."""
    trees: Dict[str, Dict] = {"params": _flatten(params)}
    meta: Dict[str, Any] = {"pass_id": pass_id, "format_version": 2 if multihost else 1}
    if opt_state is not None:
        trees.update(_optimizer_trees(opt_state))
        meta["optimizer"] = {
            "step": int(opt_state.step),
            "num_samples": float(opt_state.num_samples),
            "avg_count": float(opt_state.avg_count),
            "avg_old_count": (
                float(opt_state.avg_old_count)
                if opt_state.avg_old_count is not None
                else 0.0
            ),
        }
    if extra_meta:
        meta.update(extra_meta)
    return trees, meta


def save_checkpoint(
    save_dir: str,
    pass_id: int,
    params: Dict[str, jax.Array],
    opt_state: Optional[UpdaterState] = None,
    extra_meta: Optional[Dict[str, Any]] = None,
    keep: int = 3,
    protect_pass: Optional[int] = None,
) -> str:
    """Save one pass directory, atomically. In multi-process runs every
    process must call this (collective); shards are written where they
    live instead of materializing cross-host arrays on process 0.

    Protocol: everything is written into ``pass-%05d.tmp`` (fsynced),
    a CRC32/size ``MANIFEST.json`` is recorded, then the tmp dir is
    renamed into place. A pre-existing final dir for the same pass (a
    periodic save followed by the pass-end save) is moved aside and
    removed only AFTER the rename — at every instant at least one
    complete checkpoint of this pass exists on disk. ``protect_pass``
    exempts one pass (the one this run restored from) from rolling
    deletion."""
    final = os.path.join(save_dir, PASS_FMT % pass_id)
    tmp = final + TMP_SUFFIX
    t0 = time.perf_counter()
    multihost = jax.process_count() > 1
    if jax.process_index() == 0:
        os.makedirs(save_dir, exist_ok=True)
        # a stale .tmp here is a crashed previous attempt at this pass —
        # garbage by definition (it never renamed); the FINAL dir stays
        # untouched until the fresh write is durable
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
    trees, meta = build_save_trees(pass_id, params, opt_state, extra_meta, multihost)
    if multihost:
        from paddle_tpu.utils.barrier import host_barrier

        # everyone waits for mkdir, writes its shards + its slice of the
        # manifest, then process 0 merges partial indexes and manifests,
        # finalizes meta, and commits the rename. The barriers are HOST
        # barriers (distributed-runtime rendezvous): this is a pure
        # filesystem protocol and must not depend on the backend being
        # able to run cross-process device computations.
        host_barrier("ckpt_dir:" + os.path.basename(tmp))
        own_files = [_save_tree_sharded(tmp, base, flat) for base, flat in trees.items()]
        pid = jax.process_index()
        _durable_manifest(
            ckpt_manifest.write_partial_manifest, tmp, pid, own_files,
            label=f"MANIFEST.partial.{pid:05d}.json",
        )
        host_barrier("ckpt_shards:" + os.path.basename(tmp))
        if jax.process_index() == 0:
            finalize_sharded_pass(
                save_dir, pass_id, trees, meta, keep=keep,
                protect_pass=protect_pass,
            )
        host_barrier("ckpt_done:" + os.path.basename(final))
    else:
        for base, flat in trees.items():
            _write_file(
                os.path.join(tmp, f"{base}.npz"),
                lambda f, _flat=flat: np.savez(f, **_flat),
            )
        _write_file(
            os.path.join(tmp, "meta.json"),
            lambda f: json.dump(meta, f, indent=2),
            mode="w",
        )
        _durable_manifest(ckpt_manifest.write_manifest, tmp, label="MANIFEST.json")
        _commit(tmp, final)
        _rotate(save_dir, keep, protect=protect_pass)
    logger.info("saved checkpoint %s", final)
    _ckpt_record("save", final, t0, pass_id=pass_id, measure_bytes=True,
                 # mid-pass periodic saves (--saving_period_by_batches)
                 # of one pass are distinct stalls: the batch id keys
                 # them apart in `paddle metrics` dedupe
                 step=(extra_meta or {}).get("batch_id"))
    return final


def _commit(tmp: str, final: str, self_written: bool = True) -> None:
    """Atomically publish a complete tmp dir as the final pass dir. A
    crash before the rename leaves the old checkpoint untouched (plus a
    stale .tmp that the next save's rotation sweeps); a crash after it
    leaves the new checkpoint complete — there is no window in which
    neither is restorable.

    ``self_written=False`` (the sharded-pass merge commit): the dir
    holds shards PEER processes wrote over the shared fs, so it must
    not enter the trust-own-writes verify skip — this process can only
    vouch for bytes it wrote and fsynced itself."""
    _fsync_dir(tmp)
    fault_point("checkpoint.rename", info=os.path.basename(final))
    old = None
    if os.path.lexists(final):
        # re-save of the same pass id: POSIX cannot rename onto a
        # non-empty dir, so move the old one aside and drop it only
        # after the new dir is in place
        old = final + ".old"
        shutil.rmtree(old, ignore_errors=True)
        os.rename(final, old)
    os.rename(tmp, final)
    _fsync_dir(os.path.dirname(final) or ".")
    if self_written:
        _written_this_process.add(os.path.abspath(final))
    if old is not None:
        shutil.rmtree(old, ignore_errors=True)


def _rotate(save_dir: str, keep: int, protect: Optional[int] = None) -> None:
    """Rolling deletion of old pass dirs (ParamUtil::deleteOldestPass).

    Only completed ``pass-NNNNN`` dirs count toward the keep budget:
    ``*.tmp`` and ``*.corrupt`` dirs are not restorable state, and
    counting them would silently shrink the number of real checkpoints
    retained. Stale ``*.tmp`` dirs (crashed writes — ours already
    renamed) are swept outright; quarantined ``*.corrupt`` dirs are kept
    for post-mortem. ``protect`` (the pass this run restored from) is
    never rolled away: until a newer checkpoint proves itself loadable,
    it is the only state known-good."""
    names = os.listdir(save_dir)
    for d in names:
        # .tmp = crashed write; .old = crash inside _commit's two-rename
        # window — both are litter once a newer save completed. The one
        # exception: the .old of the protected pass, which may be the
        # very dir this run restored from (torn-commit recovery).
        if d.startswith("pass-") and (d.endswith(TMP_SUFFIX) or d.endswith(".old")):
            if protect is not None and d == (PASS_FMT % protect) + ".old":
                continue
            shutil.rmtree(os.path.join(save_dir, d), ignore_errors=True)
    if keep <= 0:
        return
    passes = sorted(int(d[5:]) for d in names if _is_pass_dir_name(d))
    for p in passes[:-keep]:
        if protect is not None and p == protect:
            continue
        shutil.rmtree(os.path.join(save_dir, PASS_FMT % p), ignore_errors=True)


def has_params_tree(path: str) -> bool:
    """True if a pass dir contains a params tree in either format."""
    return os.path.exists(os.path.join(path, "params.npz")) or os.path.exists(
        os.path.join(path, "params.index.json")
    )


def latest_pass(save_dir: str) -> Optional[int]:
    if not os.path.isdir(save_dir):
        return None
    passes = [
        int(d[5:]) for d in os.listdir(save_dir) if _is_pass_dir_name(d)
    ]
    return max(passes) if passes else None


def verify_checkpoint(path: str) -> List[str]:
    """Problems with one pass directory; empty list = restorable.

    Checks completeness (a params tree is present — meta.json stays
    optional, as in the loader) and, when a ``MANIFEST.json`` exists,
    every manifested file's size and CRC32. Pre-manifest checkpoints
    verify on completeness alone — old checkpoints must keep loading."""
    if not os.path.isdir(path):
        return [f"{path}: not a directory"]
    t0 = time.perf_counter()
    problems: List[str] = []
    if not has_params_tree(path):
        problems.append("no params tree (params.npz / params.index.json)")
    # the CRC pass reads every manifested byte — transient shared-FS read
    # errors retry through the shared policy rather than condemning a
    # good checkpoint
    problems.extend(
        _io_policy().call(ckpt_manifest.verify_dir, path, name=f"verify {path}")
    )
    _ckpt_record("verify", path, t0, ok=not problems)
    return problems


def _shard_host(fname: str) -> Optional[int]:
    m = _SHARD_FILE_RE.match(fname)
    return int(m.group("pid")) if m else None


def verify_sharded_shards(path: str) -> List[str]:
    """Structural verification of the SHARDED trees in one pass dir —
    what the byte-level manifest check cannot see: every shard record in
    each merged index must resolve (its file present, its key in the npz
    archive), and the records of each parameter must cover its full
    extent exactly once (a bad merge that silently lost one host's
    partial index leaves a hole the manifest never notices, because the
    manifest only covers files that EXIST). Problems name the owning
    host parsed from the shard filename. Cheap: only zip directories are
    read, never shard data (CRC content checks are the manifest's job).
    Empty list = clean; non-sharded (format-1) dirs verify trivially."""
    problems: List[str] = []
    if not os.path.isdir(path):
        return [f"{path}: not a directory"]
    members: Dict[str, Optional[set]] = {}  # shard file -> npz keys (None=unreadable)

    def keys_of(fname: str) -> Optional[set]:
        if fname not in members:
            full = os.path.join(path, fname)
            if not os.path.exists(full):
                members[fname] = None
            else:
                try:
                    with zipfile.ZipFile(full) as z:
                        members[fname] = {
                            n[:-4] if n.endswith(".npy") else n
                            for n in z.namelist()
                        }
                except (OSError, zipfile.BadZipFile):
                    members[fname] = None
        return members[fname]

    for fn in sorted(os.listdir(path)):
        m = _MERGED_IDX_RE.match(fn)
        if not m or _PARTIAL_IDX_RE.match(fn):
            continue
        base = m.group("base")
        try:
            with open(os.path.join(path, fn)) as f:
                index = json.load(f)
        except (OSError, ValueError) as e:
            problems.append(f"{fn}: unreadable index ({e})")
            continue
        for name, entry in sorted(index.items()):
            total = 1
            for d in entry.get("shape", []):
                total *= int(d)
            covered = 0
            coverage_known = True
            for rec in entry.get("shards", []):
                fname = rec.get("file", "")
                host = _shard_host(fname)
                who = f"host {host}" if host is not None else fname
                keys = keys_of(fname)
                if keys is None:
                    word = ("missing" if not os.path.exists(os.path.join(path, fname))
                            else "unreadable")
                    problems.append(
                        f"{base}/{name}: shard file {fname} {word} ({who})"
                    )
                    coverage_known = False
                    continue
                if rec.get("key") not in keys:
                    problems.append(
                        f"{base}/{name}: record {rec.get('key')!r} absent "
                        f"from {fname} ({who})"
                    )
                    coverage_known = False
                    continue
                rshape = rec.get("shape")
                if rshape is None:
                    coverage_known = False  # pre-'shape' checkpoints
                    continue
                vol = 1
                for d in rshape:
                    vol *= int(d)
                covered += vol
            if coverage_known and covered != total:
                problems.append(
                    f"{base}/{name}: shard records cover {covered} of "
                    f"{total} elements (lost or duplicated host shards?)"
                )
            # row-sharded entries additionally prove EXACT row
            # coverage: a missing or overlapping row_range is a named
            # hole (check-checkpoint classifies these as PARTIAL),
            # never a silent zero-init on restore
            row_recs = [
                (rec["row_range"][0], rec["row_range"][1],
                 _shard_host(rec.get("file", "")))
                for rec in entry.get("shards", [])
                if rec.get("row_range")
            ]
            if row_recs and entry.get("shape"):
                from paddle_tpu.sparse import rowshard

                for msg in rowshard.coverage_problems(
                        int(entry["shape"][0]), row_recs):
                    problems.append(f"{base}/{name}: row coverage: {msg}")
    return problems


def partial_pass_report(save_dir: str) -> List[Tuple[str, int]]:
    """Uncommitted sharded saves under ``save_dir``: ``pass-N.tmp`` dirs
    a crashed run left behind, with how many per-process partial
    manifests each holds. These are NOT restorable (the pass never
    reached its commit agreement) — `paddle check-checkpoint` surfaces
    them so an operator can tell 'that save never landed' from 'all
    good'."""
    out: List[Tuple[str, int]] = []
    if not os.path.isdir(save_dir):
        return out
    for d in sorted(os.listdir(save_dir)):
        if not (d.endswith(TMP_SUFFIX)
                and _is_pass_dir_name(d[: -len(TMP_SUFFIX)])):
            continue
        full = os.path.join(save_dir, d)
        try:
            partials = sum(
                1 for fn in os.listdir(full) if _PARTIAL_MANIFEST_RE.match(fn)
            )
        except OSError:
            continue
        out.append((full, partials))
    return out


def find_restorable_checkpoint(
    save_dir: str, trust_own_writes: bool = False
) -> Optional[str]:
    """Newest pass dir under ``save_dir`` that verifies clean, or None.

    Read-only (corrupt candidates are logged and skipped, never
    quarantined here — that is load_checkpoint's job); backs
    ``--init_model_path=auto``.

    ``trust_own_writes``: skip the CRC walk for pass dirs this process
    committed itself (the trainer's in-run rollback path — re-reading a
    multi-GB checkpoint just to re-hash bytes this process wrote and
    fsynced minutes earlier is restart latency for nothing). Fresh
    processes have committed nothing, so cold restores always verify."""
    if not os.path.isdir(save_dir):
        return None
    passes = sorted(
        (int(d[5:]) for d in os.listdir(save_dir) if _is_pass_dir_name(d)),
        reverse=True,
    )
    for p in passes:
        path = os.path.join(save_dir, PASS_FMT % p)
        if trust_own_writes and written_this_process(path):
            logger.info(
                "find_restorable_checkpoint: %s was committed by this "
                "process — skipping re-verification", path,
            )
            return path
        problems = verify_checkpoint(path)
        if not problems:
            return path
        logger.warning(
            "find_restorable_checkpoint: skipping %s: %s", path, "; ".join(problems)
        )
    # last resort: a crash exactly between _commit's two renames leaves
    # the previous (fully durable, once-published) checkpoint as
    # pass-NNNNN.old — restorable even though unpublished. Never .tmp:
    # a tmp dir was never known complete+published as a whole.
    olds = sorted(
        (
            d for d in os.listdir(save_dir)
            if d.endswith(".old") and _is_pass_dir_name(d[: -len(".old")])
        ),
        reverse=True,
    )
    for d in olds:
        path = os.path.join(save_dir, d)
        if not verify_checkpoint(path):
            logger.warning(
                "find_restorable_checkpoint: recovering from torn commit "
                "leftover %s", path,
            )
            return path
    return None


def _quarantine(path: str) -> Optional[str]:
    """Rename a corrupt pass dir to ``*.corrupt`` (kept for post-mortem,
    excluded from rotation budgets and restore scans). Returns the new
    path, or None when quarantine was skipped (not a pass dir, already
    gone, or a non-0 process in a multi-host run — one renamer only)."""
    if not _is_pass_dir_name(os.path.basename(path)):
        return None
    if jax.process_count() > 1 and jax.process_index() != 0:
        return None
    dest = path + CORRUPT_SUFFIX
    n = 1
    while os.path.lexists(dest):
        dest = f"{path}{CORRUPT_SUFFIX}{n}"
        n += 1
    try:
        os.rename(path, dest)
    except OSError as e:
        logger.warning("could not quarantine %s: %s", path, e)
        return None
    # proven bad: it must never ride the trust-own-writes verify skip
    _written_this_process.discard(os.path.abspath(os.path.normpath(path)))
    logger.warning("quarantined corrupt checkpoint %s -> %s", path, dest)
    return dest


def _fallback_candidate(path: str) -> Optional[str]:
    """The newest pass dir older than ``path`` in the same save_dir, or
    None when ``path`` is not a pass dir / nothing older exists."""
    base = os.path.basename(path)
    if not _is_pass_dir_name(base):
        return None
    save_dir = os.path.dirname(path) or "."
    bad_id = int(base[5:])
    if not os.path.isdir(save_dir):
        return None
    older = [
        int(d[5:])
        for d in os.listdir(save_dir)
        if _is_pass_dir_name(d) and int(d[5:]) < bad_id
    ]
    if not older:
        return None
    return os.path.join(save_dir, PASS_FMT % max(older))


class _ShardedTreeReader:
    """Lazy reader over one sharded-format tree: `read_slice` loads ONLY
    the shard records overlapping the requested slice, so restoring onto a
    sharded layout costs O(local shard bytes) host memory per parameter —
    never O(full parameter) on every host (the reference streams blocks
    the same way, ParameterServer2.cpp:1150-1213). `bytes_read` counts the
    record bytes actually pulled off disk (tests pin the streaming claim
    on it)."""

    def __init__(self, path: str, index: Dict[str, Any]):
        self.path = path
        self.index = index
        self._files: Dict[str, Any] = {}
        self.bytes_read = 0

    def names(self):
        return self.index.keys()

    def spec(self, name: str) -> Tuple[Tuple[int, ...], np.dtype]:
        e = self.index[name]
        return tuple(e["shape"]), np.dtype(e["dtype"])

    def _record(self, rec) -> np.ndarray:
        z = self._files.get(rec["file"])
        if z is None:
            z = self._files[rec["file"]] = np.load(os.path.join(self.path, rec["file"]))
        data = z[rec["key"]]  # decompresses this member only
        self.bytes_read += data.nbytes
        return data

    def read_slice(self, name: str, idx, shape, dtype) -> np.ndarray:
        """Assemble the sub-array covering `idx` (a tuple of slices as
        handed out by jax.make_array_from_callback; None bounds mean the
        full axis)."""
        want = tuple(
            slice(s.start or 0, dim if s.stop is None else s.stop)
            for s, dim in zip(idx, shape)
        )
        out = np.zeros([w.stop - w.start for w in want], dtype)
        for rec in self.index[name]["shards"]:
            starts = rec["start"]
            data = None
            rec_shape = rec.get("shape")
            if rec_shape is None:  # pre-'shape' checkpoints: the probe
                data = self._record(rec)  # read doubles as the data read
                rec_shape = data.shape
            lo = [max(w.start, st) for w, st in zip(want, starts)]
            hi = [min(w.stop, st + d) for w, st, d in zip(want, starts, rec_shape)]
            if any(l >= h for l, h in zip(lo, hi)):
                continue  # no overlap: record never read (when indexed)
            if data is None:
                data = self._record(rec)
            src = tuple(slice(l - st, h - st) for l, h, st in zip(lo, hi, starts))
            dst = tuple(slice(l - w.start, h - w.start) for l, h, w in zip(lo, hi, want))
            out[dst] = data[src]
        return out

    def close(self):
        for z in self._files.values():
            z.close()


def _tree_index(path: str, base: str) -> Optional[Dict[str, Any]]:
    idx_path = os.path.join(path, f"{base}.index.json")
    if os.path.exists(idx_path):
        with open(idx_path) as f:
            return json.load(f)
    return None


def _load_tree_numpy(path: str, base: str) -> Optional[Dict[str, np.ndarray]]:
    """Read one tree as full host numpy arrays from either format, or
    None if the tree is absent (merge_model and single-process restores —
    the streaming path is load_checkpoint's sharding_for branch)."""
    index = _tree_index(path, base)
    if index is not None:
        reader = _ShardedTreeReader(path, index)
        try:
            return {
                name: reader.read_slice(
                    name, (slice(None),) * len(shape), shape, dtype
                )
                for name, (shape, dtype) in ((n, reader.spec(n)) for n in reader.names())
            }
        finally:
            reader.close()
    npz_path = os.path.join(path, f"{base}.npz")
    if os.path.exists(npz_path):
        with np.load(npz_path) as z:
            return {k: z[k] for k in z.files}
    return None


def load_checkpoint(
    path: str,
    opt_template: Optional[UpdaterState] = None,
    missing: str = "fail",
    expected_params: Optional[Dict[str, jax.Array]] = None,
    sharding_for: Optional[Callable[[str, str, Any], Any]] = None,
    io_stats: Optional[Dict[str, int]] = None,
    verify: bool = True,
    fallback: bool = True,
    trust_own_writes: bool = False,
) -> Tuple[Dict[str, jax.Array], Optional[UpdaterState], Dict[str, Any]]:
    """Load params (+ optimizer state rebuilt onto ``opt_template``),
    with verification and a fallback restore chain.

    ``verify``: check completeness + the CRC32/size manifest before
    deserializing anything. ``trust_own_writes``: also skip that check
    when ``path`` is a checkpoint THIS process committed earlier in the
    run (rollback/in-run restart) — verification cost belongs to cold
    restores, and a fresh process has committed nothing, so those keep
    the full verify. Only the first candidate is ever trusted; anything
    the fallback chain reaches is verified regardless. ``fallback``:
    when ``path`` is a ``pass-NNNNN`` dir that fails verification,
    quarantine it (``*.corrupt``) and retry with the newest earlier pass
    dir in the same save_dir, logging exactly what was skipped and why;
    raises CheckpointCorruptError only when no candidate survives. A
    mismatched model (``missing='fail'`` KeyError) is a config error,
    not corruption — it never triggers fallback.

    A path that does not exist at all is a caller error (wrong
    ``--start_pass``, a typo'd ``--init_model_path``) and raises
    FileNotFoundError up front — fallback is for checkpoints that went
    bad, never a license to silently substitute state the caller did
    not ask for.

    Multi-host: every process verifies the FULL manifest (an
    N_hosts × checkpoint-size read amplification on restore — the known
    cost of keeping verification collective-free; the optimization path
    is verify-on-process-0 + broadcast) and walks the fallback chain
    independently; only process 0 quarantines. Verification outcomes
    depend on per-process I/O, so under concurrent corruption hosts CAN
    diverge on the candidate — corrupt-restore on a pod is best-effort;
    when a pod-wide restore reports corruption, run
    ``paddle check-checkpoint`` and restart cleanly rather than relying
    on per-host fallback. See the remaining parameters on
    ``_load_checkpoint_once``."""
    tried: List[str] = []
    cur = os.path.normpath(path)
    if not os.path.isdir(cur):
        raise FileNotFoundError(f"checkpoint {cur} does not exist")
    t0 = time.perf_counter()
    first = True
    while True:
        # verify=False / trust_own_writes cover only the FIRST candidate
        # (the caller just CRC'd it, e.g. find_restorable_checkpoint, or
        # this process wrote it); anything the fallback chain reaches is
        # unvetted and must be verified here
        trusted = trust_own_writes and written_this_process(cur)
        if first and trusted and verify:
            logger.info(
                "load_checkpoint: %s was committed by this process — "
                "skipping re-verification", cur,
            )
        skip_crc = first and (not verify or trusted)
        problems = [] if skip_crc else verify_checkpoint(cur)
        # the corruption-vs-config disambiguation below may assume
        # clean bytes only when a CRC actually ran — here, or by the
        # caller (the verify=False contract). A trusted self-written
        # skip verified NOTHING: its deserialization failures must
        # enter the fallback chain, not re-raise as config errors.
        bytes_vetted = not (skip_crc and trusted)
        first = False
        if not problems:
            try:
                result = _load_checkpoint_once(
                    cur, opt_template, missing, expected_params, sharding_for,
                    io_stats,
                )
                _ckpt_record(
                    "load", cur, t0,
                    pass_id=result[2].get("pass_id")
                    if isinstance(result[2].get("pass_id"), int) else None,
                    measure_bytes=True,
                    fallbacks=len(tried),
                )
                return result
            except (
                FileNotFoundError,
                EOFError,
                ValueError,
                zipfile.BadZipFile,
                zlib.error,
            ) as e:
                # corruption-shaped deserialization failures: no params
                # tree, a file vanished between verify and read, or a
                # torn/truncated archive in a PRE-MANIFEST checkpoint
                # (np.load raises BadZipFile on truncation, zlib.error on
                # corrupt members, ValueError/EOFError on garbage). But a
                # checkpoint whose manifest just CRC-verified clean cannot
                # be torn on disk — a ValueError there is a model/config
                # mismatch (wrong shapes for this net), and quarantining
                # good checkpoints over it would walk the whole chain into
                # *.corrupt. Config errors propagate; only manifest-less
                # dirs (and vanished files) enter the fallback chain here.
                if (
                    bytes_vetted
                    and not isinstance(e, FileNotFoundError)
                    and ckpt_manifest.read_manifest(cur) is not None
                ):
                    raise
                problems = [f"load failed: {e}"]
        detail = f"{cur}: {'; '.join(problems)}"
        tried.append(detail)
        logger.error("checkpoint failed verification: %s", detail)
        nxt = _fallback_candidate(cur) if fallback else None
        if fallback:
            _quarantine(cur)
        if nxt is None:
            raise CheckpointCorruptError(
                "no restorable checkpoint: " + " | ".join(tried), problems=tried
            )
        logger.warning("falling back to earlier checkpoint %s", nxt)
        cur = nxt


def _load_checkpoint_once(
    path: str,
    opt_template: Optional[UpdaterState] = None,
    missing: str = "fail",
    expected_params: Optional[Dict[str, jax.Array]] = None,
    sharding_for: Optional[Callable[[str, str, Any], Any]] = None,
    io_stats: Optional[Dict[str, int]] = None,
) -> Tuple[Dict[str, jax.Array], Optional[UpdaterState], Dict[str, Any]]:
    """Deserialize one (pre-verified) pass directory.

    ``missing``: fail | rand | zero — the reference's
    --load_missing_parameter_strategy; ``expected_params`` supplies shapes
    (and values, for 'rand') for parameters absent from the file.

    ``sharding_for(tree_base, flat_key, shape)`` (multi-process restore):
    returns the NamedSharding each value must live on; values are built with
    ``jax.make_array_from_callback`` so the restore re-shards onto the
    CURRENT mesh regardless of the layout the checkpoint was written
    with. Without it values load as host-local arrays (single process).

    Sharded-format trees restore STREAMING: each device slice is assembled
    from only the shard records overlapping it, so peak host memory is
    O(local shard bytes) per parameter, not O(parameter bytes) — the
    ParameterServer2 block-wise semantics. ``io_stats`` (optional dict)
    receives per-tree bytes actually read from shard files.
    """

    def put(base: str, key: str, full):
        if sharding_for is None:
            return jnp.asarray(full)
        full = np.asarray(full)
        sh = sharding_for(base, key, full.shape)
        return jax.make_array_from_callback(full.shape, sh, lambda idx, _f=full: _f[idx])

    def load_tree(base: str) -> Optional[Dict[str, jax.Array]]:
        index = _tree_index(path, base)
        if index is not None:
            reader = _ShardedTreeReader(path, index)
            try:
                out = {}
                for name in reader.names():
                    shape, dtype = reader.spec(name)
                    if sharding_for is None:
                        out[name] = jnp.asarray(
                            reader.read_slice(name, (slice(None),) * len(shape), shape, dtype)
                        )
                    else:
                        sh = sharding_for(base, name, shape)
                        # several local devices may ask for the same slice
                        # (replication): memoize per parameter so each
                        # record is decompressed at most once, holding at
                        # most this parameter's process-local bytes
                        memo: Dict[Any, np.ndarray] = {}

                        def cb(idx, n=name, s=shape, d=dtype, m=memo):
                            key = tuple((x.start, x.stop) for x in idx)
                            if key not in m:
                                m[key] = reader.read_slice(n, idx, s, d)
                            return m[key]

                        out[name] = jax.make_array_from_callback(shape, sh, cb)
                return out
            finally:
                if io_stats is not None:
                    io_stats[base] = reader.bytes_read
                reader.close()
        npz_path = os.path.join(path, f"{base}.npz")
        if not os.path.exists(npz_path):
            return None
        with np.load(npz_path) as z:
            return {k: put(base, k, z[k]) for k in z.files}

    params = load_tree("params")
    if params is None:
        raise FileNotFoundError(f"no params tree in checkpoint {path}")
    if expected_params is not None:
        for name, val in expected_params.items():
            if name not in params:
                if missing == "fail":
                    raise KeyError(f"parameter {name!r} missing from checkpoint {path}")
                params[name] = jnp.zeros_like(val) if missing == "zero" else val
    meta = {}
    meta_path = os.path.join(path, "meta.json")
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
    opt_state = None
    slot_vals = load_tree("optimizer_slots") if opt_template is not None else None
    if opt_template is not None and slot_vals is not None:
        slots = _unflatten(slot_vals)
        om = meta.get("optimizer", {})
        avg_sum = opt_template.avg_sum
        if avg_sum is not None:
            avg_sum = load_tree("optimizer_avg") or avg_sum
        avg_old_sum = opt_template.avg_old_sum
        if avg_old_sum is not None:
            avg_old_sum = load_tree("optimizer_avg_old") or avg_old_sum

        def scalar(v, dtype):
            # multi-process: keep host numpy — jit treats it as replicated
            # input; a committed single-device jnp array would fail to
            # reshard across processes
            return np.asarray(v, dtype) if sharding_for is not None else jnp.asarray(v, dtype)

        opt_state = UpdaterState(
            step=scalar(om.get("step", 0), jnp.int32),
            num_samples=scalar(om.get("num_samples", 0.0), jnp.float32),
            slots=slots,
            avg_sum=avg_sum,
            avg_count=scalar(om.get("avg_count", 0.0), jnp.float32),
            avg_old_sum=avg_old_sum,
            avg_old_count=scalar(om.get("avg_old_count", 0.0), jnp.float32),
        )
    logger.info("loaded checkpoint %s", path)
    return params, opt_state, meta


def merge_model(save_dir: str, pass_id: int, config_json: str, out_path: str) -> None:
    """MergeModel analog (/root/reference/paddle/trainer/MergeModel.cpp):
    bundle config + parameters into one deployable .npz."""
    path = os.path.join(save_dir, PASS_FMT % pass_id)
    arrays = _load_tree_numpy(path, "params")
    if arrays is None:
        raise FileNotFoundError(f"no params tree in checkpoint {path}")
    arrays["__config_json__"] = np.frombuffer(config_json.encode(), dtype=np.uint8)
    np.savez(out_path, **arrays)
