"""Checkpointing — pass-%05d directories with params + optimizer state.

Reference: ParameterUtil (/root/reference/paddle/trainer/ParamUtil.cpp:
53-103) wrote one binary file per parameter with a versioned header and
rolled old pass dirs; the reference did NOT checkpoint optimizer state — we
do (SURVEY.md §5 flags this as a required upgrade).

Single-host format: one .npz for params, one per optimizer tree,
meta.json for step counters + config snapshot.

Multi-host SHARDED format (the pserver-side save/load analog,
ParameterServer2::loadValueVector/saveValueVector,
/root/reference/paddle/pserver/ParameterServer2.cpp:1150-1213): every
process writes the addressable shards it uniquely owns (replica_id == 0)
to ``<tree>.shard<pid>.npz`` plus a partial index; after a cross-process
barrier, process 0 merges the partials into ``<tree>.index.json``. The
save_dir must be a shared filesystem (the standard TPU-pod setup; same
assumption orbax/GCS makes). Restore assembles each parameter from its
shard records and re-shards onto the CURRENT mesh via
``jax.make_array_from_callback`` — a checkpoint written on one mesh
layout loads onto any other, including single-host ↔ multi-host moves.
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.optimizer.updater import UpdaterState
from paddle_tpu.utils.logging import logger

PASS_FMT = "pass-%05d"


def _flatten(tree: Dict, prefix: str = "") -> Dict[str, Any]:
    """Flatten nested dicts to 'a/b' keys. Values are NOT materialized —
    np.savez coerces at write time (single-host), and the sharded writer
    must see live jax.Arrays to read their addressable shards."""
    out = {}
    for k, v in tree.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, key + "/"))
        elif v is not None:
            out[key] = v
    return out


def _unflatten(flat: Dict[str, np.ndarray]) -> Dict:
    out: Dict = {}
    for k, v in flat.items():
        parts = k.split("/")
        d = out
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = jnp.asarray(v)
    return out


def _save_tree_sharded(path: str, base: str, flat: Dict[str, jax.Array]) -> None:
    """Write this process's uniquely-owned shards of one tree + a partial
    index. Called by EVERY process."""
    pid = jax.process_index()
    shard_file = f"{base}.shard{pid:05d}.npz"
    pieces: Dict[str, np.ndarray] = {}
    partial: Dict[str, Any] = {}
    for name, arr in flat.items():
        arr = jnp.asarray(arr) if not isinstance(arr, jax.Array) else arr
        entry = {"shape": list(arr.shape), "dtype": str(arr.dtype), "shards": []}
        for i, sh in enumerate(arr.addressable_shards):
            if sh.replica_id != 0:
                continue  # exactly one process owns each distinct slice
            key = f"{name}::{i}"
            data = np.asarray(sh.data)
            pieces[key] = data
            entry["shards"].append(
                {
                    "file": shard_file,
                    "key": key,
                    "start": [int(sl.start or 0) for sl in sh.index],
                    # record extent up front so restore can skip
                    # non-overlapping records without reading them
                    "shape": list(data.shape),
                }
            )
        if entry["shards"]:
            partial[name] = entry
    np.savez(os.path.join(path, shard_file), **pieces)
    with open(os.path.join(path, f"{base}.index.{pid:05d}.json"), "w") as f:
        json.dump(partial, f)


def _merge_tree_indexes(path: str, base: str) -> None:
    """Process 0, after the barrier: merge partial indexes into
    ``<base>.index.json`` and drop the partials."""
    merged: Dict[str, Any] = {}
    for fn in sorted(os.listdir(path)):
        if not (fn.startswith(f"{base}.index.") and fn.endswith(".json")):
            continue
        if fn == f"{base}.index.json":
            continue
        with open(os.path.join(path, fn)) as f:
            partial = json.load(f)
        for name, entry in partial.items():
            if name in merged:
                assert merged[name]["shape"] == entry["shape"], name
                merged[name]["shards"].extend(entry["shards"])
            else:
                merged[name] = entry
        os.remove(os.path.join(path, fn))
    with open(os.path.join(path, f"{base}.index.json"), "w") as f:
        json.dump(merged, f)


def _optimizer_trees(opt_state: UpdaterState) -> Dict[str, Dict]:
    trees = {"optimizer_slots": _flatten(opt_state.slots)}
    if opt_state.avg_sum is not None:
        trees["optimizer_avg"] = _flatten(opt_state.avg_sum)
    if opt_state.avg_old_sum is not None:
        trees["optimizer_avg_old"] = _flatten(opt_state.avg_old_sum)
    return trees


def save_checkpoint(
    save_dir: str,
    pass_id: int,
    params: Dict[str, jax.Array],
    opt_state: Optional[UpdaterState] = None,
    extra_meta: Optional[Dict[str, Any]] = None,
    keep: int = 3,
) -> str:
    """Save one pass directory. In multi-process runs every process must
    call this (collective); shards are written where they live instead of
    materializing cross-host arrays on process 0."""
    path = os.path.join(save_dir, PASS_FMT % pass_id)
    multihost = jax.process_count() > 1
    if jax.process_index() == 0:
        # clear any previous contents: a re-save in the OTHER format would
        # otherwise leave a stale <tree>.index.json that the loader prefers
        # over the fresh .npz
        shutil.rmtree(path, ignore_errors=True)
        os.makedirs(path, exist_ok=True)
    trees: Dict[str, Dict] = {"params": _flatten(params)}
    meta: Dict[str, Any] = {"pass_id": pass_id, "format_version": 2 if multihost else 1}
    if opt_state is not None:
        trees.update(_optimizer_trees(opt_state))
        meta["optimizer"] = {
            "step": int(opt_state.step),
            "num_samples": float(opt_state.num_samples),
            "avg_count": float(opt_state.avg_count),
            "avg_old_count": (
                float(opt_state.avg_old_count)
                if opt_state.avg_old_count is not None
                else 0.0
            ),
        }
    if extra_meta:
        meta.update(extra_meta)
    if multihost:
        from jax.experimental import multihost_utils

        # everyone waits for mkdir, writes its shards, then process 0
        # merges the partial indexes and finalizes meta
        multihost_utils.sync_global_devices("ckpt_dir:" + path)
        for base, flat in trees.items():
            _save_tree_sharded(path, base, flat)
        multihost_utils.sync_global_devices("ckpt_shards:" + path)
        if jax.process_index() == 0:
            for base in trees:
                _merge_tree_indexes(path, base)
            with open(os.path.join(path, "meta.json"), "w") as f:
                json.dump(meta, f, indent=2)
            _rotate(save_dir, keep)
        multihost_utils.sync_global_devices("ckpt_done:" + path)
    else:
        for base, flat in trees.items():
            np.savez(os.path.join(path, f"{base}.npz"), **flat)
        with open(os.path.join(path, "meta.json"), "w") as f:
            json.dump(meta, f, indent=2)
        _rotate(save_dir, keep)
    logger.info("saved checkpoint %s", path)
    return path


def _rotate(save_dir: str, keep: int) -> None:
    """Rolling deletion of old pass dirs (ParamUtil::deleteOldestPass)."""
    if keep <= 0:
        return
    passes = sorted(
        d for d in os.listdir(save_dir) if d.startswith("pass-") and d[5:].isdigit()
    )
    for d in passes[:-keep]:
        shutil.rmtree(os.path.join(save_dir, d), ignore_errors=True)


def has_params_tree(path: str) -> bool:
    """True if a pass dir contains a params tree in either format."""
    return os.path.exists(os.path.join(path, "params.npz")) or os.path.exists(
        os.path.join(path, "params.index.json")
    )


def latest_pass(save_dir: str) -> Optional[int]:
    if not os.path.isdir(save_dir):
        return None
    passes = [
        int(d[5:]) for d in os.listdir(save_dir) if d.startswith("pass-") and d[5:].isdigit()
    ]
    return max(passes) if passes else None


class _ShardedTreeReader:
    """Lazy reader over one sharded-format tree: `read_slice` loads ONLY
    the shard records overlapping the requested slice, so restoring onto a
    sharded layout costs O(local shard bytes) host memory per parameter —
    never O(full parameter) on every host (the reference streams blocks
    the same way, ParameterServer2.cpp:1150-1213). `bytes_read` counts the
    record bytes actually pulled off disk (tests pin the streaming claim
    on it)."""

    def __init__(self, path: str, index: Dict[str, Any]):
        self.path = path
        self.index = index
        self._files: Dict[str, Any] = {}
        self.bytes_read = 0

    def names(self):
        return self.index.keys()

    def spec(self, name: str) -> Tuple[Tuple[int, ...], np.dtype]:
        e = self.index[name]
        return tuple(e["shape"]), np.dtype(e["dtype"])

    def _record(self, rec) -> np.ndarray:
        z = self._files.get(rec["file"])
        if z is None:
            z = self._files[rec["file"]] = np.load(os.path.join(self.path, rec["file"]))
        data = z[rec["key"]]  # decompresses this member only
        self.bytes_read += data.nbytes
        return data

    def read_slice(self, name: str, idx, shape, dtype) -> np.ndarray:
        """Assemble the sub-array covering `idx` (a tuple of slices as
        handed out by jax.make_array_from_callback; None bounds mean the
        full axis)."""
        want = tuple(
            slice(s.start or 0, dim if s.stop is None else s.stop)
            for s, dim in zip(idx, shape)
        )
        out = np.zeros([w.stop - w.start for w in want], dtype)
        for rec in self.index[name]["shards"]:
            starts = rec["start"]
            data = None
            rec_shape = rec.get("shape")
            if rec_shape is None:  # pre-'shape' checkpoints: the probe
                data = self._record(rec)  # read doubles as the data read
                rec_shape = data.shape
            lo = [max(w.start, st) for w, st in zip(want, starts)]
            hi = [min(w.stop, st + d) for w, st, d in zip(want, starts, rec_shape)]
            if any(l >= h for l, h in zip(lo, hi)):
                continue  # no overlap: record never read (when indexed)
            if data is None:
                data = self._record(rec)
            src = tuple(slice(l - st, h - st) for l, h, st in zip(lo, hi, starts))
            dst = tuple(slice(l - w.start, h - w.start) for l, h, w in zip(lo, hi, want))
            out[dst] = data[src]
        return out

    def close(self):
        for z in self._files.values():
            z.close()


def _tree_index(path: str, base: str) -> Optional[Dict[str, Any]]:
    idx_path = os.path.join(path, f"{base}.index.json")
    if os.path.exists(idx_path):
        with open(idx_path) as f:
            return json.load(f)
    return None


def _load_tree_numpy(path: str, base: str) -> Optional[Dict[str, np.ndarray]]:
    """Read one tree as full host numpy arrays from either format, or
    None if the tree is absent (merge_model and single-process restores —
    the streaming path is load_checkpoint's sharding_for branch)."""
    index = _tree_index(path, base)
    if index is not None:
        reader = _ShardedTreeReader(path, index)
        try:
            return {
                name: reader.read_slice(
                    name, (slice(None),) * len(shape), shape, dtype
                )
                for name, (shape, dtype) in ((n, reader.spec(n)) for n in reader.names())
            }
        finally:
            reader.close()
    npz_path = os.path.join(path, f"{base}.npz")
    if os.path.exists(npz_path):
        with np.load(npz_path) as z:
            return {k: z[k] for k in z.files}
    return None


def load_checkpoint(
    path: str,
    opt_template: Optional[UpdaterState] = None,
    missing: str = "fail",
    expected_params: Optional[Dict[str, jax.Array]] = None,
    sharding_for: Optional[Callable[[str, str, Any], Any]] = None,
    io_stats: Optional[Dict[str, int]] = None,
) -> Tuple[Dict[str, jax.Array], Optional[UpdaterState], Dict[str, Any]]:
    """Load params (+ optimizer state rebuilt onto ``opt_template``).

    ``missing``: fail | rand | zero — the reference's
    --load_missing_parameter_strategy; ``expected_params`` supplies shapes
    (and values, for 'rand') for parameters absent from the file.

    ``sharding_for(tree_base, flat_key, shape)`` (multi-process restore):
    returns the NamedSharding each value must live on; values are built with
    ``jax.make_array_from_callback`` so the restore re-shards onto the
    CURRENT mesh regardless of the layout the checkpoint was written
    with. Without it values load as host-local arrays (single process).

    Sharded-format trees restore STREAMING: each device slice is assembled
    from only the shard records overlapping it, so peak host memory is
    O(local shard bytes) per parameter, not O(parameter bytes) — the
    ParameterServer2 block-wise semantics. ``io_stats`` (optional dict)
    receives per-tree bytes actually read from shard files.
    """

    def put(base: str, key: str, full):
        if sharding_for is None:
            return jnp.asarray(full)
        full = np.asarray(full)
        sh = sharding_for(base, key, full.shape)
        return jax.make_array_from_callback(full.shape, sh, lambda idx, _f=full: _f[idx])

    def load_tree(base: str) -> Optional[Dict[str, jax.Array]]:
        index = _tree_index(path, base)
        if index is not None:
            reader = _ShardedTreeReader(path, index)
            try:
                out = {}
                for name in reader.names():
                    shape, dtype = reader.spec(name)
                    if sharding_for is None:
                        out[name] = jnp.asarray(
                            reader.read_slice(name, (slice(None),) * len(shape), shape, dtype)
                        )
                    else:
                        sh = sharding_for(base, name, shape)
                        # several local devices may ask for the same slice
                        # (replication): memoize per parameter so each
                        # record is decompressed at most once, holding at
                        # most this parameter's process-local bytes
                        memo: Dict[Any, np.ndarray] = {}

                        def cb(idx, n=name, s=shape, d=dtype, m=memo):
                            key = tuple((x.start, x.stop) for x in idx)
                            if key not in m:
                                m[key] = reader.read_slice(n, idx, s, d)
                            return m[key]

                        out[name] = jax.make_array_from_callback(shape, sh, cb)
                return out
            finally:
                if io_stats is not None:
                    io_stats[base] = reader.bytes_read
                reader.close()
        npz_path = os.path.join(path, f"{base}.npz")
        if not os.path.exists(npz_path):
            return None
        with np.load(npz_path) as z:
            return {k: put(base, k, z[k]) for k in z.files}

    params = load_tree("params")
    if params is None:
        raise FileNotFoundError(f"no params tree in checkpoint {path}")
    if expected_params is not None:
        for name, val in expected_params.items():
            if name not in params:
                if missing == "fail":
                    raise KeyError(f"parameter {name!r} missing from checkpoint {path}")
                params[name] = jnp.zeros_like(val) if missing == "zero" else val
    meta = {}
    meta_path = os.path.join(path, "meta.json")
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
    opt_state = None
    slot_vals = load_tree("optimizer_slots") if opt_template is not None else None
    if opt_template is not None and slot_vals is not None:
        slots = _unflatten(slot_vals)
        om = meta.get("optimizer", {})
        avg_sum = opt_template.avg_sum
        if avg_sum is not None:
            avg_sum = load_tree("optimizer_avg") or avg_sum
        avg_old_sum = opt_template.avg_old_sum
        if avg_old_sum is not None:
            avg_old_sum = load_tree("optimizer_avg_old") or avg_old_sum

        def scalar(v, dtype):
            # multi-process: keep host numpy — jit treats it as replicated
            # input; a committed single-device jnp array would fail to
            # reshard across processes
            return np.asarray(v, dtype) if sharding_for is not None else jnp.asarray(v, dtype)

        opt_state = UpdaterState(
            step=scalar(om.get("step", 0), jnp.int32),
            num_samples=scalar(om.get("num_samples", 0.0), jnp.float32),
            slots=slots,
            avg_sum=avg_sum,
            avg_count=scalar(om.get("avg_count", 0.0), jnp.float32),
            avg_old_sum=avg_old_sum,
            avg_old_count=scalar(om.get("avg_old_count", 0.0), jnp.float32),
        )
    logger.info("loaded checkpoint %s", path)
    return params, opt_state, meta


def merge_model(save_dir: str, pass_id: int, config_json: str, out_path: str) -> None:
    """MergeModel analog (/root/reference/paddle/trainer/MergeModel.cpp):
    bundle config + parameters into one deployable .npz."""
    path = os.path.join(save_dir, PASS_FMT % pass_id)
    arrays = _load_tree_numpy(path, "params")
    if arrays is None:
        raise FileNotFoundError(f"no params tree in checkpoint {path}")
    arrays["__config_json__"] = np.frombuffer(config_json.encode(), dtype=np.uint8)
    np.savez(out_path, **arrays)
