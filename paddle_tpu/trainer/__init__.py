from paddle_tpu.trainer.trainer import Trainer, TrainerStats
from paddle_tpu.trainer.evaluators import EvaluatorChain, evaluator_registry
from paddle_tpu.trainer import checkpoint

__all__ = ["Trainer", "TrainerStats", "EvaluatorChain", "evaluator_registry", "checkpoint"]
