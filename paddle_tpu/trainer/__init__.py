"""Training driver package.

Lazily resolved (PEP 562): ``paddle_tpu.trainer.async_ckpt``'s
concurrency machinery is jax-free by design and `paddle race` (the
deterministic schedule explorer) imports it on machines — and in CI
lanes — where the accelerator runtime must not be paid for or even
present. Importing the package therefore must not drag in
``trainer.trainer`` (jax) as a side effect; ``from paddle_tpu.trainer
import Trainer`` still works, resolving on first touch.
"""

import importlib
from typing import Any

__all__ = ["Trainer", "TrainerStats", "EvaluatorChain",
           "evaluator_registry", "checkpoint"]

# attribute -> the submodule that defines it. importlib.import_module
# (NOT `from ... import ...`) — the from-import form re-probes this
# package's __getattr__ for the submodule name mid-import and recurses.
_HOMES = {
    "Trainer": "paddle_tpu.trainer.trainer",
    "TrainerStats": "paddle_tpu.trainer.trainer",
    "EvaluatorChain": "paddle_tpu.trainer.evaluators",
    "evaluator_registry": "paddle_tpu.trainer.evaluators",
    "checkpoint": "paddle_tpu.trainer.checkpoint",
}


def __getattr__(name: str) -> Any:
    home = _HOMES.get(name)
    if home is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    mod = importlib.import_module(home)
    return mod if name == "checkpoint" else getattr(mod, name)
